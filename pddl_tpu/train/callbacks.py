"""Callback engine with parity for every callback the reference uses.

Reference surface (SURVEY.md §2a C10, §5):

- ``ReduceLROnPlateau(monitor='val_loss', factor=0.1, patience=5,
  min_lr=1e-5)`` — ``/root/reference/imagenet-resnet50.py:64``
- ``EarlyStopping(monitor='val_loss', min_delta=0.001, patience=10)`` —
  ``imagenet-resnet50.py:65``
- ``hvd.callbacks.BroadcastGlobalVariablesCallback(0)`` —
  ``imagenet-resnet50-hvd.py:111`` (replicated-init no-op under SPMD; kept
  in :mod:`pddl_tpu.compat.hvd`)
- ``hvd.callbacks.MetricAverageCallback`` — ``imagenet-resnet50-hvd.py:112``
  (metrics are already global means under jit-with-shardings)
- ``hvd.callbacks.LearningRateWarmupCallback(warmup_epochs=3, verbose=1)``
  — ``imagenet-resnet50-hvd.py:114`` → :class:`LearningRateWarmup`
- rank-0-gated verbosity/saving — ``imagenet-resnet50-hvd.py:117,125`` →
  coordinator gating lives in the Trainer/logging layer.

Callbacks mutate training functionally: they may return a new ``TrainState``
from hooks (LR changes are state edits, not attribute pokes) and set
``trainer.stop_training`` exactly like Keras EarlyStopping.
"""

from __future__ import annotations

import math
import os
import sys
import time
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp

from pddl_tpu.train.state import TrainState, get_learning_rate, set_learning_rate


class Callback:
    """Base class; hooks mirror ``keras.callbacks.Callback``.

    Hooks that can change training state return a ``TrainState`` (or None to
    leave it untouched). ``self.trainer`` is bound by the Trainer before use.
    """

    trainer = None  # set by Trainer

    def set_trainer(self, trainer) -> None:
        self.trainer = trainer

    # epoch/train hooks: return Optional[TrainState]
    def on_train_begin(self, state: TrainState):
        return None

    def on_train_end(self, state: TrainState, logs: Dict[str, float]):
        return None

    def on_epoch_begin(self, epoch: int, state: TrainState):
        return None

    def on_epoch_end(self, epoch: int, state: TrainState, logs: Dict[str, float]):
        return None

    def on_train_batch_end(self, step: int, state: TrainState, logs: Dict[str, float]):
        return None


class ReduceLROnPlateau(Callback):
    """LR decay on metric plateau — defaults exactly the reference's
    (``imagenet-resnet50.py:64``)."""

    def __init__(self, monitor: str = "val_loss", factor: float = 0.1,
                 patience: int = 5, min_lr: float = 1e-5,
                 min_delta: float = 1e-4, mode: str = "min", verbose: int = 0):
        if factor >= 1.0:
            raise ValueError("ReduceLROnPlateau factor must be < 1")
        self.monitor, self.factor, self.patience = monitor, factor, patience
        self.min_lr, self.min_delta, self.mode, self.verbose = min_lr, min_delta, mode, verbose
        self.best = math.inf if mode == "min" else -math.inf
        self.wait = 0

    def _improved(self, current: float) -> bool:
        if self.mode == "min":
            return current < self.best - self.min_delta
        return current > self.best + self.min_delta

    def on_epoch_end(self, epoch, state, logs):
        current = logs.get(self.monitor)
        if current is None:
            return None
        if self._improved(current):
            self.best, self.wait = current, 0
            return None
        self.wait += 1
        if self.wait >= self.patience:
            old = get_learning_rate(state)
            new = max(old * self.factor, self.min_lr)
            self.wait = 0
            if new < old:
                if self.verbose:
                    print(f"ReduceLROnPlateau: lr {old:.2e} -> {new:.2e}", file=sys.stderr)
                return set_learning_rate(state, new)
        return None


class EarlyStopping(Callback):
    """Stop when the monitored metric stops improving — defaults exactly the
    reference's (``imagenet-resnet50.py:65``)."""

    def __init__(self, monitor: str = "val_loss", min_delta: float = 0.001,
                 patience: int = 10, mode: str = "min",
                 restore_best_weights: bool = False):
        self.monitor, self.min_delta, self.patience = monitor, min_delta, patience
        self.mode = mode
        self.restore_best_weights = restore_best_weights
        self.best = math.inf if mode == "min" else -math.inf
        self.wait = 0
        self.best_params = None
        self.best_ema = None
        self.best_ema_bs = None
        self.stopped_epoch: Optional[int] = None

    def _improved(self, current: float) -> bool:
        if self.mode == "min":
            return current < self.best - self.min_delta
        return current > self.best + self.min_delta

    def on_epoch_end(self, epoch, state, logs):
        current = logs.get(self.monitor)
        if current is None:
            return None
        if self._improved(current):
            self.best, self.wait = current, 0
            if self.restore_best_weights:
                # Deep-copy: the live params buffers are donated by the next
                # jitted train step and would be deleted under our feet.
                # The EMA shadows are what eval ran on (when enabled), so
                # they — params AND batch_stats shadows, which move on the
                # same cadence — are part of "the best weights" and roll
                # back together.
                self.best_params = jax.tree.map(jnp.copy, state.params)
                self.best_ema = jax.tree.map(jnp.copy, state.ema_params)
                self.best_ema_bs = jax.tree.map(jnp.copy,
                                                state.ema_batch_stats)
            return None
        self.wait += 1
        if self.wait >= self.patience:
            self.stopped_epoch = epoch
            self.trainer.stop_training = True
            if self.restore_best_weights and self.best_params is not None:
                return state.replace(params=self.best_params,
                                     ema_params=self.best_ema,
                                     ema_batch_stats=self.best_ema_bs)
        return None


class LearningRateWarmup(Callback):
    """Linear LR warmup over the first epochs, Horovod-style.

    Equivalent of ``hvd.callbacks.LearningRateWarmupCallback(warmup_epochs=3)``
    (``imagenet-resnet50-hvd.py:114-115``): ramps from ``initial_lr/world``
    (or a given start) to the target LR over ``warmup_epochs`` epochs,
    stepping each batch.
    """

    def __init__(self, warmup_epochs: int = 3, steps_per_epoch: Optional[int] = None,
                 start_lr: Optional[float] = None, verbose: int = 0):
        self.warmup_epochs = warmup_epochs
        self.steps_per_epoch = steps_per_epoch
        self.start_lr = start_lr
        self.verbose = verbose
        self.target_lr: Optional[float] = None
        self._warmup_steps: Optional[int] = None

    def on_train_begin(self, state):
        self.target_lr = get_learning_rate(state)
        spe = self.steps_per_epoch or self.trainer.steps_per_epoch
        if spe is None:
            raise ValueError("LearningRateWarmup needs steps_per_epoch")
        self._warmup_steps = max(1, self.warmup_epochs * spe)
        start = self.start_lr if self.start_lr is not None else self.target_lr / self._warmup_steps
        return set_learning_rate(state, start)

    def on_train_batch_end(self, step, state, logs):
        if step >= self._warmup_steps:
            return None
        start = self.start_lr if self.start_lr is not None else 0.0
        frac = (step + 1) / self._warmup_steps
        lr = start + (self.target_lr - start) * frac
        new_state = set_learning_rate(state, lr)
        if self.verbose and step + 1 == self._warmup_steps:
            print(f"LearningRateWarmup: reached target lr {self.target_lr:.2e}", file=sys.stderr)
        return new_state


class ModelSummary(Callback):
    """Print the parameter table once at train start, coordinator-only —
    the reference's rank-0 ``print(model.summary())``
    (``imagenet-resnet50-hvd.py:95-96``)."""

    def on_train_begin(self, state):
        from pddl_tpu.core import dist
        from pddl_tpu.utils.summary import param_summary

        if dist.is_coordinator():
            print(param_summary(state.params, state.batch_stats),
                  file=sys.stderr)
        return None


class LambdaCallback(Callback):
    def __init__(self, on_epoch_end=None, on_train_batch_end=None,
                 on_train_begin=None, on_train_end=None):
        self._on_epoch_end = on_epoch_end
        self._on_train_batch_end = on_train_batch_end
        self._on_train_begin = on_train_begin
        self._on_train_end = on_train_end

    def on_train_begin(self, state):
        return self._on_train_begin(state) if self._on_train_begin else None

    def on_train_end(self, state, logs):
        return self._on_train_end(state, logs) if self._on_train_end else None

    def on_epoch_end(self, epoch, state, logs):
        return self._on_epoch_end(epoch, state, logs) if self._on_epoch_end else None

    def on_train_batch_end(self, step, state, logs):
        return self._on_train_batch_end(step, state, logs) if self._on_train_batch_end else None


class CSVLogger(Callback):
    """Epoch metrics to CSV on the coordinator — the History-file analogue."""

    def __init__(self, path: str, append: bool = False):
        self.path = path
        self.append = append
        self._file = None
        self._keys: Optional[List[str]] = None

    def on_train_begin(self, state):
        from pddl_tpu.core import dist

        if dist.is_coordinator():
            self._file = open(self.path, "a" if self.append else "w")
        return None

    def on_epoch_end(self, epoch, state, logs):
        if self._file is None:
            return None
        if self._keys is None:
            self._keys = sorted(logs)
            self._file.write(",".join(["epoch"] + self._keys) + "\n")
        row = [str(epoch)] + [f"{logs.get(k, float('nan')):.6g}" for k in self._keys]
        self._file.write(",".join(row) + "\n")
        self._file.flush()
        return None

    def on_train_end(self, state, logs):
        if self._file is not None:
            self._file.close()
            self._file = None
        return None


class TensorBoard(Callback):
    """Epoch metrics (and LR) as TensorBoard event files, coordinator-only.

    The reference's only observability is Keras ``verbose`` console lines
    (``imagenet-resnet50.py:67``); this writes the standard event-file
    format instead. ``train``/``validation`` subdirectories mirror Keras's
    TensorBoard callback: ``val_``-prefixed metrics land in ``validation``
    under their bare name, so both curves overlay on one chart.

    Uses TensorFlow's (CPU) summary writer; raises at train start if TF is
    unavailable rather than silently logging nothing.
    """

    def __init__(self, log_dir: str, write_lr: bool = True):
        self.log_dir = log_dir
        self.write_lr = write_lr
        self._writers = None

    def on_train_begin(self, state):
        from pddl_tpu.core import dist

        if not dist.is_coordinator():
            return None
        import tensorflow as tf  # CPU-only build; summary writer lives here

        self._writers = {
            split: tf.summary.create_file_writer(
                os.path.join(self.log_dir, split)
            )
            for split in ("train", "validation")
        }
        return None

    def on_epoch_end(self, epoch, state, logs):
        if self._writers is None:
            return None
        import tensorflow as tf

        by_split = {"train": {}, "validation": {}}
        for key, value in logs.items():
            if key.startswith("val_"):
                by_split["validation"][key[4:]] = value
            else:
                by_split["train"][key] = value
        if self.write_lr:
            try:
                by_split["train"]["learning_rate"] = get_learning_rate(state)
            except ValueError:  # optimizer without injected LR
                pass
        for split, metrics in by_split.items():
            if not metrics:
                continue
            with self._writers[split].as_default(step=epoch):
                for key, value in metrics.items():
                    tf.summary.scalar(key, float(value))
            self._writers[split].flush()
        return None

    def on_train_end(self, state, logs):
        if self._writers is not None:
            for w in self._writers.values():
                w.close()
            self._writers = None
        return None


class Timing(Callback):
    """Wall-clock timing like the Horovod script's rank-0 ``Total time``
    print (``imagenet-resnet50-hvd.py:119-126``)."""

    def __init__(self, verbose: int = 1):
        self.verbose = verbose
        self.start: Optional[float] = None
        self.total: Optional[float] = None

    def on_train_begin(self, state):
        self.start = time.perf_counter()
        return None

    def on_train_end(self, state, logs):
        self.total = time.perf_counter() - self.start
        from pddl_tpu.core import dist

        if self.verbose and dist.is_coordinator():
            print(f"Total time: {self.total:.1f}s", file=sys.stderr)
        return None
