"""Deterministic fault injection for the training loop.

The paper's whole subject is distributed *training*, yet its only
failure handling is a ``GRPC_FAIL_FAST`` toggle and a Horovod
re-broadcast comment (SURVEY.md §5). The serving engine grew the full
recovery story first (`pddl_tpu/serve/faults.py` + one guarded
device-call boundary + token-exact replay); this module ports that
design to the Trainer, following CheckFreq (Mohan et al., FAST '21)
for low-overhead step-granular checkpointing and Gemini (Wang et al.,
SOSP '23) for checkpoint-validity / fast in-memory recovery
discipline.

The machinery is :mod:`pddl_tpu.utils.faults`, unchanged; this module
pins the TRAINING site vocabulary — the Trainer's compiled program
names (== ``Trainer.compile_counts()`` keys):

- ``train_step``: the jitted donated SPMD update. The fault contract
  (``Trainer._device_call``): TRANSIENT retries with bounded
  exponential backoff; exhausted retries — or any OOM, or a REAL error
  from the donated program (whose input buffers may already be
  consumed) — restore the last VERIFIED checkpoint **in-process** and
  replay forward to the failed step from the Trainer's bounded batch
  replay buffer (`ckpt/checkpoint.py` ``CheckpointEveryN`` supplies
  both the saves and the buffer depth). Replay is bit-exact: the step
  is a pure function of (state, batch) and the per-step PRNG folds in
  ``state.step``.
- ``eval_step``: pure read-only evaluation — TRANSIENT retries in
  place; exhausted retries re-raise (no state was mutated, nothing to
  restore).

KILL unwinds through ``fit()`` like a real SIGKILL; the recovery story
for it is process restart + ``Trainer.fit(resume=...)`` (exact resume
from the newest verified step-granular checkpoint, loader position
included), exercised by the ``chaos``-marked matrix in
``tests/test_train_faults.py`` and documented in docs/OPERATIONS.md
§ "Failure modes & recovery (training)".
"""

from __future__ import annotations

from pddl_tpu.utils.faults import (  # noqa: F401 - the train-layer surface
    FaultKind,
    FaultSpec,
    InjectedResourceExhausted,
    InjectedTransientError,
    KillPoint,
    classify,
)
from pddl_tpu.utils.faults import FaultPlan as _BaseFaultPlan


class TrainFaultPlan(_BaseFaultPlan):
    """Seeded fault schedule over the Trainer's device-call sites
    (== ``Trainer.compile_counts()`` keys). The step coordinate is the
    GLOBAL optimizer step (``int(state.step)`` at dispatch time), so a
    scheduled fault stays pinned to the same update across resumes."""

    SITES = ("train_step", "eval_step")


class TrainStateLost(RuntimeError):
    """Internal escalation from the Trainer's guarded boundary: the
    device call could not complete within the retry budget (or the
    donated state may have been consumed by a real error) — the live
    TrainState is no longer trustworthy and must be restored from the
    last verified checkpoint. Carries the failing site and the
    original error as ``__cause__``."""

    def __init__(self, site: str, err: BaseException):
        self.site = site
        self.err = err
        super().__init__(f"training state lost at site {site!r}: {err}")
