"""Keras-``History``-equivalent training record (SURVEY.md §5 observability)."""

from __future__ import annotations

import json
from typing import Dict, List


class History:
    """Per-epoch metric history, dict-of-lists like ``keras.callbacks.History``."""

    def __init__(self) -> None:
        self.history: Dict[str, List[float]] = {}
        self.epoch: List[int] = []

    def append(self, epoch: int, logs: Dict[str, float]) -> None:
        self.epoch.append(epoch)
        for k, v in logs.items():
            self.history.setdefault(k, []).append(float(v))

    def to_jsonl(self) -> str:
        lines = []
        for i, e in enumerate(self.epoch):
            row = {"epoch": e}
            for k, vals in self.history.items():
                if i < len(vals):
                    row[k] = vals[i]
            lines.append(json.dumps(row))
        return "\n".join(lines)

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            f.write(self.to_jsonl() + "\n")

    def __repr__(self) -> str:
        return f"History(epochs={len(self.epoch)}, keys={sorted(self.history)})"
