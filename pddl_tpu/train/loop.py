"""The Trainer: Keras-``compile``/``fit`` surface over one jitted SPMD core.

Replaces the reference's orchestration layer (``keras.Model.compile`` +
``model.fit`` + callbacks, ``/root/reference/imagenet-resnet50.py:62-67``)
with a custom loop:

- ``train_step``/``eval_step`` are pure functions jitted **once** with
  ``NamedSharding``-annotated inputs/outputs over the strategy's mesh. All
  cross-device traffic (gradient all-reduce, sharded-state gather/scatter,
  cross-replica BN) is inserted by XLA's SPMD partitioner at compile time —
  the collectives ride ICI/DCN with zero framework code in the hot loop.
- State buffers are donated: params/optimizer state update in place in HBM.
- The epoch driver is host-side Python: data feeding, callbacks, History —
  deliberately outside jit (dynamic control flow stays off the device).

TPU-first details: metrics are computed from the same forward pass as the
loss (no second pass), device->host sync happens once per epoch (metric
fetch), and augmentation runs on-device inside the step (the reference puts
augmentation in the model graph for the same reason,
``imagenet-resnet50.py:53-55``).
"""

from __future__ import annotations

import logging
import sys
import time
from collections import deque
from typing import Any, Callable, Dict, Iterable, Iterator, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from pddl_tpu.core.mesh import mesh_context
from pddl_tpu.obs.trace import NULL_TRACER
from pddl_tpu.parallel.base import Strategy
from pddl_tpu.parallel.single import SingleDeviceStrategy
from pddl_tpu.train import metrics as metrics_lib
from pddl_tpu.train.callbacks import Callback
from pddl_tpu.train.faults import (
    InjectedResourceExhausted,
    InjectedTransientError,
    TrainStateLost,
    classify,
)
from pddl_tpu.train.history import History
from pddl_tpu.train.state import TrainState, make_optimizer

PyTree = Any
log = logging.getLogger(__name__)


class Trainer:
    """Strategy-agnostic training orchestrator.

    Args mirror ``model.compile`` (``imagenet-resnet50.py:62``):

    >>> trainer = Trainer(model, optimizer="adam", loss="sparse_categorical_crossentropy",
    ...                   metrics=["accuracy"], strategy=MirroredStrategy())
    >>> history = trainer.fit(train_ds, epochs=50, validation_data=val_ds,
    ...                       callbacks=[ReduceLROnPlateau(), EarlyStopping()])
    """

    def __init__(
        self,
        model,
        optimizer: str | Any = "adam",
        learning_rate: float = 1e-3,
        loss: str | Callable = "sparse_categorical_crossentropy",
        metrics: Sequence[str | Callable] = ("accuracy",),
        strategy: Optional[Strategy] = None,
        seed: int = 0,
        augment: Optional[Callable] = None,  # fn(rng, images) -> images, on-device
        eval_transform: Optional[Callable] = None,  # fn(images) -> images, deterministic
        donate_state: bool = True,
        input_key: str = "image",   # batch keys; the GPT family uses
        target_key: str = "label",  # tokens/targets (models/gpt.py)
        lr_schedule: Optional[str | Callable] = None,
        lr_schedule_options: Optional[Dict[str, Any]] = None,
        ema_decay: Optional[float] = None,
        # Evaluate on the EMA weights when ema_decay is set. BN models
        # evaluate against the EMA-shadowed batch_stats (TrainState.
        # ema_batch_stats), averaged on the same cadence as the params.
        eval_with_ema: bool = True,
        gradient_accumulation_steps: Optional[int] = None,
        # Add the global gradient L2 norm to the train logs — cheap (one
        # fused reduction in the compiled step) and the observable the
        # multichip equivalence gate compares: unlike per-leaf gradients
        # (ill-conditioned through BN backward), the norm separates fp
        # reduction noise (~1e-3 relative) from semantic errors like a
        # psum-where-pmean-belongs (device_count x).
        log_grad_norm: bool = False,
        # Low-precision parameter-update rule for bf16 param storage:
        # "plain" | "stochastic_round" | "f32_master"
        # (train/mixed_precision.py). No-op for f32 params.
        param_update: str = "plain",
        # -- crash resilience (train/faults.py, docs/OPERATIONS.md
        # § "Failure modes & recovery (training)") --------------------
        # Seeded deterministic fault injection over the compiled-program
        # sites ("train_step"/"eval_step") — the chaos handle.
        fault_plan=None,
        # Transient-device-error retry budget per dispatch; past it the
        # state is declared lost and the in-process restore+replay path
        # runs (needs a CheckpointEveryN callback attached).
        max_retries: int = 3,
        retry_backoff_s: float = 0.02,
        # Restore+replay attempts per failed step before giving up (a
        # persistently failing site must surface, not crash-loop).
        max_recoveries: int = 8,
        # Training fault/recovery/checkpoint events flow through the
        # same tracer surface the serving engine uses (obs/trace.py).
        tracer=None,
        # How retry backoff waits (tests pass a no-op).
        retry_sleep=time.sleep,
    ):
        self.model = model
        self.input_key = input_key
        self.target_key = target_key
        self.strategy = strategy or SingleDeviceStrategy()
        self.tx = make_optimizer(
            optimizer, learning_rate,
            schedule=lr_schedule, schedule_options=lr_schedule_options,
            accumulate_steps=gradient_accumulation_steps,
            param_update=param_update, update_seed=seed,
        )
        self.ema_decay = ema_decay
        self.eval_with_ema = eval_with_ema
        self.eval_transform = eval_transform
        self.loss_fn = metrics_lib.resolve_loss(loss)
        self.metric_fns = dict(metrics_lib.resolve_metric(m) for m in metrics)
        self.seed = seed
        self.augment = augment
        self.donate_state = donate_state
        self.log_grad_norm = log_grad_norm

        self.state: Optional[TrainState] = None
        self.stop_training = False
        self.steps_per_epoch: Optional[int] = None
        self._train_step = None
        self._eval_step = None
        self._state_shardings = None

        # -- crash-resilience state ------------------------------------
        self._faults = fault_plan
        self.max_retries = int(max_retries)
        self.retry_backoff_s = float(retry_backoff_s)
        self.max_recoveries = int(max_recoveries)
        self._tracer = tracer if tracer is not None else NULL_TRACER
        self._retry_sleep = retry_sleep
        if self._faults is not None and self._faults.on_inject is None:
            # Every injection — LATENCY included, which raises nothing —
            # lands in the trace at its exact (step, site) coordinate.
            self._faults.on_inject = self._tracer.on_fault_injected
        # Host-side dispatch wall time per site (obs exposition).
        self._site_wall: Dict[str, float] = {}
        # Lifetime fault/recovery counters (obs/export.train_exposition
        # renders every key — keep in sync with TRAIN_COUNTER_KEYS).
        self.fault_stats: Dict[str, float] = {
            "retries": 0, "recoveries": 0, "replayed_steps": 0,
            "checkpoints_saved": 0, "checkpoint_wall_s": 0.0,
        }
        # In-process recovery plumbing: the CheckpointEveryN callback
        # registers itself here (attach_recovery) and the bounded batch
        # replay buffer covers the gap back to its last verified save.
        self._recovery_cb = None
        self._replay_buffer: Optional[deque] = None
        # Python mirror of state.step (no per-step device sync) — the
        # (step, site) fault coordinate and the replay-buffer key.
        self._opt_step = 0
        # Data-pipeline position, refreshed after every step; saved into
        # checkpoint metadata so a restart resumes MID-epoch, bit-exact.
        self._loader_state: Optional[Dict[str, int]] = None
        self._batches_consumed = 0

    # ------------------------------------------------------------------ init
    def init_state(self, sample_batch: Dict[str, np.ndarray]) -> TrainState:
        """Create the (sharded) TrainState from a sample batch.

        Initialization is itself jitted with the strategy's output shardings,
        so parameters materialize directly in their final layout — no host
        round-trip, no replicated staging (matters for PS-sharded state).
        """
        mesh = self.strategy.setup()
        sample = np.asarray(sample_batch[self.input_key])
        image = jnp.zeros((1,) + tuple(sample.shape[1:]), sample.dtype)
        rng = jax.random.key(self.seed)

        def _init(rng):
            variables = self.model.init(rng, image, train=False)
            params = variables["params"]
            batch_stats = variables.get("batch_stats", {})
            return TrainState(
                step=jnp.zeros((), jnp.int32),
                params=params,
                batch_stats=batch_stats,
                opt_state=self.tx.init(params),
                ema_params=params if self.ema_decay else None,
                ema_batch_stats=batch_stats if self.ema_decay else None,
            )

        abstract = jax.eval_shape(_init, rng)
        self._state_shardings = self.strategy.state_sharding(abstract)
        with mesh_context(mesh):
            self.state = jax.jit(_init, out_shardings=self._state_shardings)(rng)
        self._build_steps()
        return self.state

    # ----------------------------------------------------------------- steps
    def _apply(self, params, batch_stats, images, train: bool, rngs=None, mutable=False):
        variables = {"params": params}
        if batch_stats:
            variables["batch_stats"] = batch_stats
        kwargs = dict(train=train)
        if rngs:
            kwargs["rngs"] = rngs
        if mutable:
            # "losses" collects model-internal auxiliary losses sown via
            # self.sow("losses", ...) — e.g. the MoE load-balancing loss
            # (pddl_tpu/ops/moe.py); train AND eval steps add them to the
            # task loss (Keras add_loss semantics: evaluate() includes
            # add_loss terms, so train loss and val_loss stay comparable).
            # "metrics" collects model-internal observables (e.g. the MoE
            # capacity drop rate) — logged, never added to the loss.
            collections = (["batch_stats", "losses", "metrics"] if train
                           else ["losses", "metrics"])
            return self.model.apply(
                variables, images, mutable=collections, **kwargs
            )
        return self.model.apply(variables, images, **kwargs), {}

    @staticmethod
    def _sown_metrics(updates) -> Dict[str, jnp.ndarray]:
        """Aggregate model-internal observables sown into "metrics".

        Leaves sharing a name (one per MoE block, say) are averaged into
        one log entry — e.g. ``moe_drop_rate`` = mean fraction of routed
        token-slots dropped at capacity, across routed blocks.
        """
        groups: Dict[str, list] = {}
        flat = jax.tree_util.tree_flatten_with_path(
            updates.get("metrics", {}))[0]
        for path, leaf in flat:
            names = [p.key for p in path
                     if isinstance(p, jax.tree_util.DictKey)]
            if names:
                groups.setdefault(str(names[-1]), []).append(leaf)
        return {name: sum(vals) / len(vals)
                for name, vals in groups.items()}

    def _build_steps(self) -> None:
        batch_sh = self.strategy.batch_sharding()
        state_sh = self._state_shardings
        base_rng = jax.random.key(self.seed + 1)

        def train_step(state: TrainState, batch):
            images, labels = batch[self.input_key], batch[self.target_key]
            rng = jax.random.fold_in(base_rng, state.step)
            if self.augment is not None:
                aug_rng, rng = jax.random.split(rng)
                images = self.augment(aug_rng, images)

            def loss_of(params):
                (logits, updates) = self._apply(
                    params, state.batch_stats, images, train=True,
                    rngs={"dropout": rng}, mutable=True,
                )
                loss = self.loss_fn(logits, labels)
                # Model-internal auxiliary losses (sown into "losses").
                for aux in jax.tree.leaves(updates.get("losses", {})):
                    loss = loss + jnp.sum(aux)
                return loss, (logits, updates)

            (loss, (logits, updates)), grads = jax.value_and_grad(
                loss_of, has_aux=True
            )(state.params)
            new_state = state.apply_gradients(
                self.tx, grads, updates.get("batch_stats", state.batch_stats),
                ema_decay=self.ema_decay,
            )
            logs = {"loss": loss}
            if self.log_grad_norm:
                import optax

                logs["grad_norm"] = optax.global_norm(grads)
            for name, fn in self.metric_fns.items():
                logs[name] = fn(logits, labels)
            logs.update(self._sown_metrics(updates))
            return new_state, logs

        def eval_step(state: TrainState, batch):
            images, labels = batch[self.input_key], batch[self.target_key]
            if self.eval_transform is not None:
                images = self.eval_transform(images)
            # Structural (trace-time) choice: EMA weights when enabled —
            # and the EMA-shadowed batch_stats with them, so BN models
            # see statistics averaged on the same cadence as the params.
            use_ema = self.eval_with_ema and state.ema_params is not None
            eval_params = state.ema_params if use_ema else state.params
            eval_stats = (
                state.ema_batch_stats
                if use_ema and state.ema_batch_stats is not None
                else state.batch_stats
            )
            (logits, updates) = self._apply(
                eval_params, eval_stats, images, train=False,
                mutable=True,
            )
            loss = self.loss_fn(logits, labels)
            for aux in jax.tree.leaves(updates.get("losses", {})):
                loss = loss + jnp.sum(aux)
            logs = {"loss": loss}
            for name, fn in self.metric_fns.items():
                logs[name] = fn(logits, labels)
            logs.update(self._sown_metrics(updates))
            return logs

        batch_shardings = {self.input_key: batch_sh, self.target_key: batch_sh}
        self._train_step = jax.jit(
            train_step,
            in_shardings=(state_sh, batch_shardings),
            out_shardings=(state_sh, None),
            donate_argnums=(0,) if self.donate_state else (),
        )
        self._eval_step = jax.jit(
            eval_step,
            in_shardings=(state_sh, batch_shardings),
            out_shardings=None,
        )

    # -------------------------------------------------- fault handling
    def compile_counts(self) -> Dict[str, int]:
        """Compiled-executable count per resident program — the
        training analogue of ``ServeEngine.compile_counts()`` (and the
        vocabulary of :class:`~pddl_tpu.train.faults.TrainFaultPlan`
        sites). Any value above 1 is a recompile; the chaos suite pins
        exactly 1 across every recovery transition."""
        counts: Dict[str, int] = {}
        for name, fn in (("train_step", self._train_step),
                         ("eval_step", self._eval_step)):
            if fn is not None:
                n = fn._cache_size()
                if n:
                    counts[name] = n
        return counts

    def attach_recovery(self, checkpoint_cb) -> None:
        """Wire a ``CheckpointEveryN`` callback as the in-process
        restore source (called automatically by its ``set_trainer``).
        The batch replay buffer is sized to TWO save intervals: the
        newest save can be torn/corrupt, and recovery must still reach
        back to the previous verified one."""
        self._recovery_cb = checkpoint_cb
        self._replay_buffer = deque(
            maxlen=2 * int(checkpoint_cb.every_n_steps))

    def on_checkpoint_saved(self, step: int, wall_s: float) -> None:
        """``CheckpointEveryN`` save hook: telemetry only."""
        self.fault_stats["checkpoints_saved"] += 1
        self.fault_stats["checkpoint_wall_s"] += wall_s
        self._tracer.on_checkpoint_saved(step, wall_s)

    def loader_state(self) -> Optional[Dict[str, int]]:
        """Data-pipeline position after the latest completed step:
        ``{"epoch", "step_in_epoch", "batches_consumed"}`` — what a
        step-granular save embeds so ``fit(resume=...)`` repositions
        the stream exactly. ``None`` before the first step."""
        return dict(self._loader_state) if self._loader_state else None

    def fault_snapshot(self) -> Dict[str, object]:
        """Flat export dict (``ServeMetrics.snapshot()`` discipline:
        every key always present) for the Prometheus exposition —
        rendered whole by ``obs.export.train_exposition``."""
        injected = ({k.value: v for k, v in self._faults.injected.items()}
                    if self._faults is not None else {})
        return {
            **{k: self.fault_stats[k] for k in sorted(self.fault_stats)},
            "faults_injected": injected,
            "site_wall_s": {k: round(v, 6)
                            for k, v in sorted(self._site_wall.items())},
            "compile_counts": self.compile_counts(),
            "opt_step": self._opt_step,
        }

    def _device_call(self, site: str, fn, *args):
        """The ONE guarded device-dispatch boundary (the serving
        engine's ``_device_call`` ported to training): consult the
        fault plan, classify failures, retry transients with bounded
        exponential backoff, and escalate to
        :class:`~pddl_tpu.train.faults.TrainStateLost` when the budget
        runs out. ``KillPoint`` is a BaseException — it passes through
        everything here, like the SIGKILL it stands for. Injected
        faults fire BEFORE ``fn`` runs, so retrying never touches a
        half-consumed donated buffer; a REAL error from the donated
        train step is never re-dispatched (its donated state may
        already be deleted) — it escalates immediately, as does any
        OOM (an allocation that just failed won't pass until the
        restore path rebuilds the state)."""
        attempt = 0
        while True:
            try:
                if self._faults is not None:
                    self._faults.check(site)
                t0 = time.perf_counter()
                out = fn(*args)
                self._site_wall[site] = (self._site_wall.get(site, 0.0)
                                         + time.perf_counter() - t0)
                return out
            except Exception as e:
                kind = classify(e)
                if kind is None:
                    raise  # not a device fault: bugs stay loud
                injected = isinstance(e, (InjectedTransientError,
                                          InjectedResourceExhausted))
                consumed = (not injected and site == "train_step"
                            and self.donate_state)
                if kind == "oom" or consumed:
                    raise TrainStateLost(site, e) from e
                attempt += 1
                if attempt > self.max_retries:
                    raise TrainStateLost(site, e) from e
                self.fault_stats["retries"] += 1
                self._tracer.on_retry(self._opt_step, site, attempt)
                self._retry_sleep(self.retry_backoff_s * (2 ** (attempt - 1)))

    def _guarded_train_step(self, batch) -> Dict[str, jnp.ndarray]:
        """One optimizer step through the guarded boundary. On
        escalation, restore the last verified checkpoint IN-PROCESS,
        replay forward from the batch buffer to the failed step, then
        retry the failed step itself — CheckFreq-style recovery without
        a process restart. Bit-exact: the step is a pure function of
        (state, batch) and the per-step PRNG folds in ``state.step``."""
        while True:
            try:
                if self._faults is not None:
                    self._faults.on_step(self._opt_step)
                out = self._device_call("train_step", self._train_step,
                                        self.state, batch)
                break
            except TrainStateLost as lost:
                self._restore_and_replay(lost)
        self.state, logs = out
        if self._replay_buffer is not None:
            self._replay_buffer.append((self._opt_step, batch))
        self._opt_step += 1
        return logs

    def _restore_and_replay(self, lost: TrainStateLost) -> None:
        """Roll the live state back to the newest VERIFIED checkpoint
        and replay buffered batches forward to the step that failed.
        Leaves ``self.state`` at exactly ``self._opt_step`` (the failed
        step re-dispatches in the caller's loop)."""
        cb = self._recovery_cb
        if cb is None or cb.ckpt is None:
            raise lost
        target = self._opt_step
        for _ in range(self.max_recoveries):
            self.fault_stats["recoveries"] += 1
            cb.ckpt.wait()  # an in-flight async save may be the newest good
            restored = cb.ckpt.restore(self.state)
            restored_step = int(jax.device_get(restored.step))
            if restored_step > target:
                raise RuntimeError(
                    f"newest checkpoint (step {restored_step}) is AHEAD "
                    f"of the failed step {target}; cannot replay "
                    "backwards — is another run writing this directory?"
                ) from lost
            buffered = dict(self._replay_buffer or ())
            missing = [s for s in range(restored_step, target)
                       if s not in buffered]
            if missing:
                raise RuntimeError(
                    f"replay buffer does not cover steps {missing} "
                    f"between the restored checkpoint ({restored_step}) "
                    f"and the failed step ({target}) — checkpoint "
                    "cadence outran the buffer") from lost
            self._tracer.on_restore(target, restored_step, lost.site)
            self.state = restored
            try:
                for s in range(restored_step, target):
                    if self._faults is not None:
                        self._faults.on_step(s)
                    self.state, _ = self._device_call(
                        "train_step", self._train_step, self.state,
                        buffered[s])
                    self.fault_stats["replayed_steps"] += 1
            except TrainStateLost as again:
                lost = again
                continue
            self._tracer.on_recovery(target, restored_step,
                                     target - restored_step)
            log.warning(
                "recovered in-process from %s at step %d: restored "
                "step %d and replayed %d step(s)", lost.site, target,
                restored_step, target - restored_step)
            return
        raise RuntimeError(
            f"recovery budget exhausted ({self.max_recoveries} "
            f"restore+replay attempts) at step {target}") from lost

    # --------------------------------------------------------------- prefetch
    def _prefetch_distributed(self, it: Iterator, depth: int) -> Iterator:
        """Yield already-distributed global batches, ``depth`` ahead.

        ``device_put``/``make_array_from_process_local_data`` dispatch
        asynchronously, so queuing the next batches while the device chews
        on the current step overlaps host-side data work with compute —
        the ``.prefetch(AUTOTUNE)`` moment (``imagenet-resnet50.py:47``)
        at the host→HBM boundary.
        """
        from collections import deque

        q: deque = deque()

        def fill():
            while len(q) < depth:
                try:
                    q.append(self.strategy.distribute_batch(next(it)))
                except StopIteration:
                    return

        fill()
        while q:
            batch = q.popleft()
            yield batch
            fill()

    # ------------------------------------------------------------------- fit
    def fit(
        self,
        train_data: Iterable[Dict[str, np.ndarray]],
        epochs: int = 1,
        steps_per_epoch: Optional[int] = None,
        validation_data: Optional[Iterable[Dict[str, np.ndarray]]] = None,
        validation_steps: Optional[int] = None,
        callbacks: Sequence[Callback] = (),
        verbose: int = 2,  # reference uses verbose=2 (imagenet-resnet50.py:67)
        initial_epoch: int = 0,
        prefetch: int = 2,  # device-feed lookahead; 0/1 disables
        # Crash-resume: a checkpoint directory (or Checkpointer). The
        # newest VERIFIED save restores (a torn/corrupt latest falls
        # back to the previous good step), the data stream repositions
        # from the saved loader state, and training continues MID-epoch
        # — bit-exact with an uninterrupted run. Overrides
        # ``initial_epoch``. An empty directory starts fresh (so the
        # same command line works for the first launch and every
        # restart). See docs/OPERATIONS.md § "Failure modes & recovery
        # (training)".
        resume=None,
    ) -> History:
        if validation_data is not None and isinstance(validation_data, Iterator):
            raise ValueError(
                "validation_data is a one-shot iterator; fit() evaluates it "
                "once per epoch, so pass a re-iterable dataset"
            )
        self.steps_per_epoch = steps_per_epoch
        history = History()
        self.stop_training = False
        self.global_step = 0
        self._batches_consumed = 0
        self._loader_state = None
        if self._replay_buffer is not None:
            # Stale batches from a previous fit would alias step indices.
            self._replay_buffer.clear()

        resume_offset = 0  # steps already done inside the resumed epoch
        host_skip = 0      # batches to drop from the fresh iterator
        if resume is not None:
            prepared = self._prepare_resume(resume, train_data,
                                            steps_per_epoch)
            if prepared is not None:
                train_data, initial_epoch, resume_offset, host_skip = prepared

        train_iter = self._ensure_iterator(train_data)
        if self.state is None:
            first = next(train_iter)
            self.init_state(first)
            train_iter = _chain_first(first, train_iter)
        self._opt_step = int(jax.device_get(self.state.step))
        if host_skip:
            train_iter = self._skip_consumed(train_iter, host_skip,
                                             train_data, steps_per_epoch)

        for cb in callbacks:
            cb.set_trainer(self)

        final_logs: Dict[str, float] = {}
        stopped_mid_epoch = False
        continuous_feed = None
        # on_train_begin is INSIDE the try: if a later callback's
        # on_train_begin raises (corrupt restore, ...), earlier callbacks
        # that already acquired resources (signal handlers, checkpoint
        # managers — utils/preemption.py) still get their on_train_end
        # cleanup from the finally.
        try:
            self._run_hooks(callbacks, "on_train_begin")
            for epoch in range(initial_epoch, epochs):
                if self.stop_training:
                    break
                self._run_hooks(callbacks, "on_epoch_begin", epoch)
                t0 = time.perf_counter()
                step_logs = []
                steps = 0
                samples = 0
                # Mid-epoch resume: the restored epoch already ran this
                # many steps before the crash — run only the remainder.
                offset = resume_offset if epoch == initial_epoch else 0
                def make_feed(it):
                    if prefetch and prefetch > 1:
                        return self._prefetch_distributed(it, prefetch)
                    return (self.strategy.distribute_batch(b) for b in it)

                if steps_per_epoch is not None:
                    # Continuous stream: ONE persistent feed across epochs
                    # (recreating it each epoch would drop the batches the
                    # prefetcher already pulled from the shared iterator).
                    # A finite RE-ITERABLE dataset repeats when it drains —
                    # the reference's own `.repeat()` + fixed steps_per_epoch
                    # pattern (imagenet-resnet50-ps.py:118-119,143) without
                    # the caller spelling it; each re-pass is a fresh
                    # __iter__ (so per-epoch reshuffles apply). One-shot
                    # iterators still just end.
                    if continuous_feed is None:
                        def _repeating(first_iter, data=train_data):
                            it = first_iter
                            batches = 0
                            repassed = False
                            while True:
                                yielded = False
                                for b in it:
                                    yielded = True
                                    batches += 1
                                    yield b
                                if isinstance(data, Iterator) or not yielded:
                                    return
                                if not repassed:
                                    # Loud once: a mis-sized pipeline (e.g. a
                                    # glob matching too few files) would
                                    # otherwise repeat data silently.
                                    repassed = True
                                    log.warning(
                                        "steps_per_epoch outlives the "
                                        "dataset (%d batches/pass); "
                                        "re-iterating (reference .repeat() "
                                        "semantics)", batches,
                                    )
                                it = iter(data)

                        continuous_feed = make_feed(_repeating(train_iter))
                    feed = continuous_feed
                elif epoch == initial_epoch:
                    # First epoch must include the batch consumed by
                    # init_state via _chain_first; finite data drains the
                    # feed fully so nothing is lost between epochs.
                    feed = make_feed(train_iter)
                else:
                    if isinstance(train_data, Iterator):
                        raise ValueError(
                            "train_data is a one-shot iterator but steps_per_epoch "
                            "is None; pass a re-iterable dataset or set steps_per_epoch"
                        )
                    feed = make_feed(iter(train_data))
                while steps_per_epoch is None or offset + steps < steps_per_epoch:
                    try:
                        global_batch = next(feed)
                    except StopIteration:
                        break
                    # Global batch size (leading dim of the global array).
                    samples += int(global_batch[self.target_key].shape[0])
                    logs = self._guarded_train_step(global_batch)
                    step_logs.append(logs)
                    steps += 1
                    # Loader position settles BEFORE batch-end hooks run,
                    # so a step-granular save records exactly this step's
                    # stream position (normalized to the next epoch's
                    # start at the boundary).
                    self._batches_consumed += 1
                    in_ep = offset + steps
                    if steps_per_epoch is not None and in_ep >= steps_per_epoch:
                        self._loader_state = {
                            "epoch": epoch + 1, "step_in_epoch": 0,
                            "batches_consumed": self._batches_consumed}
                    else:
                        self._loader_state = {
                            "epoch": epoch, "step_in_epoch": in_ep,
                            "batches_consumed": self._batches_consumed}
                    self._run_hooks(
                        callbacks, "on_train_batch_end", self.global_step, logs=logs
                    )
                    self.global_step += 1
                    if self.stop_training:
                        # Honored mid-epoch (Keras semantics) — e.g. preemption
                        # checkpointing stops at the next batch boundary.
                        stopped_mid_epoch = True
                        break
                if steps == 0:
                    if offset:
                        # The resumed epoch was already fully trained
                        # before the crash (the save landed on its last
                        # batch): nothing to re-run HERE, but the later
                        # epochs still must run — fall through to them.
                        # (Only the first resumed epoch can carry an
                        # offset, so a genuinely empty dataset still
                        # raises on the next iteration.)
                        continue
                    raise ValueError("empty training dataset/epoch")
                if stopped_mid_epoch:
                    # A mid-epoch stop means "exit NOW" (preemption grace
                    # window): no validation pass, no epoch-end hooks (whose
                    # checkpoint saves could also collide with the preemption
                    # save), no partial-epoch History entry that would mislead
                    # plateau/early-stop logic on resume.
                    break
                # Epoch boundary reached (finite stream drained): saves
                # from here resume at the NEXT epoch's start.
                self._loader_state = {
                    "epoch": epoch + 1, "step_in_epoch": 0,
                    "batches_consumed": self._batches_consumed}

                # Training throughput: window closes before validation runs.
                dt = time.perf_counter() - t0
                epoch_logs = _mean_logs(step_logs)
                if validation_data is not None:
                    val_logs = self.evaluate(validation_data, steps=validation_steps,
                                             verbose=0, _prefix="val_")
                    epoch_logs.update(val_logs)

                epoch_logs["images_per_sec"] = samples / dt if dt > 0 else 0.0
                history.append(epoch, epoch_logs)
                if verbose and self.strategy.is_coordinator:
                    line = " - ".join(
                        [f"Epoch {epoch + 1}/{epochs}", f"{dt:.1f}s"]
                        + [f"{k}: {v:.4f}" for k, v in epoch_logs.items()
                           if k != "images_per_sec"]
                        + [f"{epoch_logs['images_per_sec']:.0f} img/s"]
                    )
                    print(line, file=sys.stderr)
                self._run_hooks(callbacks, "on_epoch_end", epoch, logs=epoch_logs)
                final_logs = epoch_logs

        finally:
            self._run_hooks(callbacks, "on_train_end", logs=final_logs)
        self.history = history
        return history

    # --------------------------------------------------------------- resume
    @staticmethod
    def _skip_consumed(it, n: int, data, steps_per_epoch) -> Iterator:
        """Drain ``n`` already-consumed batches from the stream. With
        ``steps_per_epoch`` set, a finite re-iterable that drains is
        RE-ITERATED — exactly the ``_repeating`` wrap-around the
        original run's continuous feed applied — so the skip follows
        the same batch sequence the crashed run consumed. Without it,
        the skip stays within the resumed epoch's single pass."""
        skipped = 0
        while skipped < n:
            advanced = False
            for _ in it:
                advanced = True
                skipped += 1
                if skipped == n:
                    return it
            if (steps_per_epoch is None or isinstance(data, Iterator)
                    or not advanced):
                raise ValueError(
                    f"resume: dataset ended after {skipped} of {n} "
                    "already-consumed batches — the stream is shorter "
                    "than it was before the crash")
            it = iter(data)
        return it

    def _prepare_resume(self, resume, train_data, steps_per_epoch):
        """Restore the newest verified checkpoint and work out where the
        data stream must restart. Returns ``(train_data, initial_epoch,
        step_offset, host_skip)`` or ``None`` when the directory holds
        no checkpoint yet (fresh start — same CLI for launch and
        restart).

        Stream repositioning, in preference order: a dataset exposing
        ``with_offset(n)`` (the synthetic families) is shifted by the
        saved ``batches_consumed`` — free; otherwise ``host_skip``
        batches are drained from the fresh iterator before training
        (exact for any deterministic re-iterable). Without
        ``steps_per_epoch`` the feed is rebuilt per epoch, so only the
        resumed epoch's ``step_in_epoch`` batches are skipped. Legacy
        saves (no loader metadata) keep the old semantics: restart at
        the epoch after the recorded one, stream from the top.
        """
        if isinstance(train_data, Iterator):
            raise ValueError(
                "fit(resume=...) needs a re-iterable dataset — a one-shot "
                "iterator cannot be repositioned to the saved offset"
            )
        from pddl_tpu.ckpt.checkpoint import Checkpointer

        own = isinstance(resume, str)
        ckpt = Checkpointer(resume, async_save=False) if own else resume
        try:
            if ckpt.latest_step() is None:
                log.info("resume: no checkpoint under %s yet — fresh run",
                         getattr(ckpt, "directory", resume))
                return None
            if self.state is None:
                self.init_state(next(iter(train_data)))
            self.state = ckpt.restore(self.state)
            step = int(jax.device_get(self.state.step))
            try:
                meta = ckpt.metadata(step)
            except Exception:  # noqa: BLE001 - meta is advisory here
                meta = {}
        finally:
            if own:
                ckpt.close()
        loader = meta.get("loader") or None
        if loader:
            initial_epoch = int(loader.get("epoch", 0))
            offset = int(loader.get("step_in_epoch", 0))
            consumed = int(loader.get("batches_consumed", 0))
        else:
            saved = meta.get("epoch")
            initial_epoch = int(saved) + 1 if saved is not None else 0
            offset = consumed = 0
        self._batches_consumed = consumed
        skip = consumed if steps_per_epoch is not None else offset
        host_skip = 0
        if skip:
            if (steps_per_epoch is not None
                    and hasattr(train_data, "with_offset")):
                train_data = train_data.with_offset(skip)
            else:
                host_skip = skip
        log.info(
            "resume: restored verified step %d (epoch %d, step_in_epoch "
            "%d, %d batches consumed)", step, initial_epoch, offset,
            consumed)
        return train_data, initial_epoch, offset, host_skip

    # -------------------------------------------------------------- evaluate
    def evaluate(
        self,
        data: Iterable[Dict[str, np.ndarray]],
        steps: Optional[int] = None,
        verbose: int = 0,
        _prefix: str = "",
    ) -> Dict[str, float]:
        if self.state is None:
            raise RuntimeError("call fit() or init_state() before evaluate()")
        it = self._ensure_iterator(data, fresh=True)
        logs_list = []
        n = 0
        while steps is None or n < steps:
            try:
                batch = next(it)
            except StopIteration:
                break
            global_batch = self.strategy.distribute_batch(batch)
            try:
                if self._faults is not None:
                    self._faults.on_step(self._opt_step)
                logs_list.append(self._device_call(
                    "eval_step", self._eval_step, self.state, global_batch))
            except TrainStateLost as lost:
                # Eval mutates nothing — there is no state to restore;
                # an exhausted retry budget surfaces the device error.
                raise lost.err
            n += 1
        if not logs_list:
            raise ValueError("empty evaluation dataset")
        out = {_prefix + k: v for k, v in _mean_logs(logs_list).items()}
        if verbose and self.strategy.is_coordinator:
            print(" - ".join(f"{k}: {v:.4f}" for k, v in out.items()), file=sys.stderr)
        return out

    # --------------------------------------------------------------- predict
    def predict(self, images: np.ndarray) -> np.ndarray:
        """Forward pass (inference mode) on a batch of images."""
        if self.state is None:
            raise RuntimeError("call fit() or init_state() before predict()")
        x = self.strategy.distribute_batch(
            {self.input_key: np.asarray(images)})[self.input_key]
        if self.eval_transform is not None:
            x = self.eval_transform(x)
        logits, _ = self._apply(self.state.params, self.state.batch_stats, x, train=False)
        return np.asarray(jax.device_get(logits))

    # --------------------------------------------------------------- helpers
    def _ensure_iterator(self, data, fresh: bool = False) -> Iterator:
        # A bare iterator cannot be restarted; fit() rejects one-shot
        # iterators for train (multi-epoch) and validation data up front.
        if isinstance(data, Iterator):
            return data
        return iter(data)

    def _run_hooks(self, callbacks, hook: str, *args, logs=None) -> None:
        # on_train_end is CLEANUP: every callback must get its turn
        # (checkpoint flush, signal-handler restore) even when an
        # earlier one raises — e.g. HeartbeatCallback re-raising
        # WorkerLost for the supervisor. The first error re-raises
        # after the sweep, so it still reaches the caller.
        deferred: Optional[Exception] = None
        for cb in callbacks:
            fn = getattr(cb, hook)
            if hook in ("on_train_begin",):
                result = fn(self.state)
            elif hook in ("on_train_end",):
                try:
                    result = fn(self.state, logs or {})
                except Exception as e:  # noqa: BLE001 - swept, re-raised
                    if deferred is None:
                        deferred = e
                    else:
                        # Only the first propagates; later failures must
                        # not vanish without a trace.
                        log.error(
                            "on_train_end of %s also failed (suppressed "
                            "in favor of the first error): %s",
                            type(cb).__name__, e)
                    continue
            elif hook == "on_epoch_begin":
                result = fn(args[0], self.state)
            elif hook == "on_epoch_end":
                result = fn(args[0], self.state, logs or {})
            elif hook == "on_train_batch_end":
                result = fn(args[0], self.state, logs or {})
            else:  # pragma: no cover
                raise ValueError(hook)
            if result is not None:
                self.state = result
        if deferred is not None:
            raise deferred


def _mean_logs(logs_list) -> Dict[str, float]:
    """Fetch once, average on host (one device sync per epoch).

    Perplexity keys are logged per batch in log space (mean CE — see
    ``metrics.log_perplexity``); exponentiating AFTER the average yields
    exactly exp(mean CE) over all tokens (the standard corpus number),
    where a mean of per-batch exponentials would be Jensen-biased high
    and could overflow.
    """
    fetched = jax.device_get(logs_list)
    keys = fetched[0].keys()
    out = {}
    for k in keys:
        vals = np.asarray([d[k] for d in fetched], np.float64)
        # Exact key only (evaluate() adds its val_ prefix after this
        # aggregation): user metrics with "perplexity" in their name are
        # not assumed to be log-space.
        if k == "perplexity":
            out[k] = float(np.exp(np.mean(vals)))
        else:
            out[k] = float(np.mean(vals))
    return out


def _chain_first(first, rest: Iterator) -> Iterator:
    yield first
    yield from rest
