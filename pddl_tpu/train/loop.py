"""The Trainer: Keras-``compile``/``fit`` surface over one jitted SPMD core.

Replaces the reference's orchestration layer (``keras.Model.compile`` +
``model.fit`` + callbacks, ``/root/reference/imagenet-resnet50.py:62-67``)
with a custom loop:

- ``train_step``/``eval_step`` are pure functions jitted **once** with
  ``NamedSharding``-annotated inputs/outputs over the strategy's mesh. All
  cross-device traffic (gradient all-reduce, sharded-state gather/scatter,
  cross-replica BN) is inserted by XLA's SPMD partitioner at compile time —
  the collectives ride ICI/DCN with zero framework code in the hot loop.
- State buffers are donated: params/optimizer state update in place in HBM.
- The epoch driver is host-side Python: data feeding, callbacks, History —
  deliberately outside jit (dynamic control flow stays off the device).

TPU-first details: metrics are computed from the same forward pass as the
loss (no second pass), device->host sync happens once per epoch (metric
fetch), and augmentation runs on-device inside the step (the reference puts
augmentation in the model graph for the same reason,
``imagenet-resnet50.py:53-55``).
"""

from __future__ import annotations

import logging
import sys
import time
from typing import Any, Callable, Dict, Iterable, Iterator, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from pddl_tpu.parallel.base import Strategy
from pddl_tpu.parallel.single import SingleDeviceStrategy
from pddl_tpu.train import metrics as metrics_lib
from pddl_tpu.train.callbacks import Callback
from pddl_tpu.train.history import History
from pddl_tpu.train.state import TrainState, make_optimizer

PyTree = Any
log = logging.getLogger(__name__)


class Trainer:
    """Strategy-agnostic training orchestrator.

    Args mirror ``model.compile`` (``imagenet-resnet50.py:62``):

    >>> trainer = Trainer(model, optimizer="adam", loss="sparse_categorical_crossentropy",
    ...                   metrics=["accuracy"], strategy=MirroredStrategy())
    >>> history = trainer.fit(train_ds, epochs=50, validation_data=val_ds,
    ...                       callbacks=[ReduceLROnPlateau(), EarlyStopping()])
    """

    def __init__(
        self,
        model,
        optimizer: str | Any = "adam",
        learning_rate: float = 1e-3,
        loss: str | Callable = "sparse_categorical_crossentropy",
        metrics: Sequence[str | Callable] = ("accuracy",),
        strategy: Optional[Strategy] = None,
        seed: int = 0,
        augment: Optional[Callable] = None,  # fn(rng, images) -> images, on-device
        eval_transform: Optional[Callable] = None,  # fn(images) -> images, deterministic
        donate_state: bool = True,
        input_key: str = "image",   # batch keys; the GPT family uses
        target_key: str = "label",  # tokens/targets (models/gpt.py)
        lr_schedule: Optional[str | Callable] = None,
        lr_schedule_options: Optional[Dict[str, Any]] = None,
        ema_decay: Optional[float] = None,
        # Evaluate on the EMA weights when ema_decay is set. BN models
        # evaluate against the EMA-shadowed batch_stats (TrainState.
        # ema_batch_stats), averaged on the same cadence as the params.
        eval_with_ema: bool = True,
        gradient_accumulation_steps: Optional[int] = None,
        # Add the global gradient L2 norm to the train logs — cheap (one
        # fused reduction in the compiled step) and the observable the
        # multichip equivalence gate compares: unlike per-leaf gradients
        # (ill-conditioned through BN backward), the norm separates fp
        # reduction noise (~1e-3 relative) from semantic errors like a
        # psum-where-pmean-belongs (device_count x).
        log_grad_norm: bool = False,
        # Low-precision parameter-update rule for bf16 param storage:
        # "plain" | "stochastic_round" | "f32_master"
        # (train/mixed_precision.py). No-op for f32 params.
        param_update: str = "plain",
    ):
        self.model = model
        self.input_key = input_key
        self.target_key = target_key
        self.strategy = strategy or SingleDeviceStrategy()
        self.tx = make_optimizer(
            optimizer, learning_rate,
            schedule=lr_schedule, schedule_options=lr_schedule_options,
            accumulate_steps=gradient_accumulation_steps,
            param_update=param_update, update_seed=seed,
        )
        self.ema_decay = ema_decay
        self.eval_with_ema = eval_with_ema
        self.eval_transform = eval_transform
        self.loss_fn = metrics_lib.resolve_loss(loss)
        self.metric_fns = dict(metrics_lib.resolve_metric(m) for m in metrics)
        self.seed = seed
        self.augment = augment
        self.donate_state = donate_state
        self.log_grad_norm = log_grad_norm

        self.state: Optional[TrainState] = None
        self.stop_training = False
        self.steps_per_epoch: Optional[int] = None
        self._train_step = None
        self._eval_step = None
        self._state_shardings = None

    # ------------------------------------------------------------------ init
    def init_state(self, sample_batch: Dict[str, np.ndarray]) -> TrainState:
        """Create the (sharded) TrainState from a sample batch.

        Initialization is itself jitted with the strategy's output shardings,
        so parameters materialize directly in their final layout — no host
        round-trip, no replicated staging (matters for PS-sharded state).
        """
        mesh = self.strategy.setup()
        sample = np.asarray(sample_batch[self.input_key])
        image = jnp.zeros((1,) + tuple(sample.shape[1:]), sample.dtype)
        rng = jax.random.key(self.seed)

        def _init(rng):
            variables = self.model.init(rng, image, train=False)
            params = variables["params"]
            batch_stats = variables.get("batch_stats", {})
            return TrainState(
                step=jnp.zeros((), jnp.int32),
                params=params,
                batch_stats=batch_stats,
                opt_state=self.tx.init(params),
                ema_params=params if self.ema_decay else None,
                ema_batch_stats=batch_stats if self.ema_decay else None,
            )

        abstract = jax.eval_shape(_init, rng)
        self._state_shardings = self.strategy.state_sharding(abstract)
        with jax.set_mesh(mesh):
            self.state = jax.jit(_init, out_shardings=self._state_shardings)(rng)
        self._build_steps()
        return self.state

    # ----------------------------------------------------------------- steps
    def _apply(self, params, batch_stats, images, train: bool, rngs=None, mutable=False):
        variables = {"params": params}
        if batch_stats:
            variables["batch_stats"] = batch_stats
        kwargs = dict(train=train)
        if rngs:
            kwargs["rngs"] = rngs
        if mutable:
            # "losses" collects model-internal auxiliary losses sown via
            # self.sow("losses", ...) — e.g. the MoE load-balancing loss
            # (pddl_tpu/ops/moe.py); train AND eval steps add them to the
            # task loss (Keras add_loss semantics: evaluate() includes
            # add_loss terms, so train loss and val_loss stay comparable).
            # "metrics" collects model-internal observables (e.g. the MoE
            # capacity drop rate) — logged, never added to the loss.
            collections = (["batch_stats", "losses", "metrics"] if train
                           else ["losses", "metrics"])
            return self.model.apply(
                variables, images, mutable=collections, **kwargs
            )
        return self.model.apply(variables, images, **kwargs), {}

    @staticmethod
    def _sown_metrics(updates) -> Dict[str, jnp.ndarray]:
        """Aggregate model-internal observables sown into "metrics".

        Leaves sharing a name (one per MoE block, say) are averaged into
        one log entry — e.g. ``moe_drop_rate`` = mean fraction of routed
        token-slots dropped at capacity, across routed blocks.
        """
        groups: Dict[str, list] = {}
        flat = jax.tree_util.tree_flatten_with_path(
            updates.get("metrics", {}))[0]
        for path, leaf in flat:
            names = [p.key for p in path
                     if isinstance(p, jax.tree_util.DictKey)]
            if names:
                groups.setdefault(str(names[-1]), []).append(leaf)
        return {name: sum(vals) / len(vals)
                for name, vals in groups.items()}

    def _build_steps(self) -> None:
        batch_sh = self.strategy.batch_sharding()
        state_sh = self._state_shardings
        base_rng = jax.random.key(self.seed + 1)

        def train_step(state: TrainState, batch):
            images, labels = batch[self.input_key], batch[self.target_key]
            rng = jax.random.fold_in(base_rng, state.step)
            if self.augment is not None:
                aug_rng, rng = jax.random.split(rng)
                images = self.augment(aug_rng, images)

            def loss_of(params):
                (logits, updates) = self._apply(
                    params, state.batch_stats, images, train=True,
                    rngs={"dropout": rng}, mutable=True,
                )
                loss = self.loss_fn(logits, labels)
                # Model-internal auxiliary losses (sown into "losses").
                for aux in jax.tree.leaves(updates.get("losses", {})):
                    loss = loss + jnp.sum(aux)
                return loss, (logits, updates)

            (loss, (logits, updates)), grads = jax.value_and_grad(
                loss_of, has_aux=True
            )(state.params)
            new_state = state.apply_gradients(
                self.tx, grads, updates.get("batch_stats", state.batch_stats),
                ema_decay=self.ema_decay,
            )
            logs = {"loss": loss}
            if self.log_grad_norm:
                import optax

                logs["grad_norm"] = optax.global_norm(grads)
            for name, fn in self.metric_fns.items():
                logs[name] = fn(logits, labels)
            logs.update(self._sown_metrics(updates))
            return new_state, logs

        def eval_step(state: TrainState, batch):
            images, labels = batch[self.input_key], batch[self.target_key]
            if self.eval_transform is not None:
                images = self.eval_transform(images)
            # Structural (trace-time) choice: EMA weights when enabled —
            # and the EMA-shadowed batch_stats with them, so BN models
            # see statistics averaged on the same cadence as the params.
            use_ema = self.eval_with_ema and state.ema_params is not None
            eval_params = state.ema_params if use_ema else state.params
            eval_stats = (
                state.ema_batch_stats
                if use_ema and state.ema_batch_stats is not None
                else state.batch_stats
            )
            (logits, updates) = self._apply(
                eval_params, eval_stats, images, train=False,
                mutable=True,
            )
            loss = self.loss_fn(logits, labels)
            for aux in jax.tree.leaves(updates.get("losses", {})):
                loss = loss + jnp.sum(aux)
            logs = {"loss": loss}
            for name, fn in self.metric_fns.items():
                logs[name] = fn(logits, labels)
            logs.update(self._sown_metrics(updates))
            return logs

        batch_shardings = {self.input_key: batch_sh, self.target_key: batch_sh}
        self._train_step = jax.jit(
            train_step,
            in_shardings=(state_sh, batch_shardings),
            out_shardings=(state_sh, None),
            donate_argnums=(0,) if self.donate_state else (),
        )
        self._eval_step = jax.jit(
            eval_step,
            in_shardings=(state_sh, batch_shardings),
            out_shardings=None,
        )

    # --------------------------------------------------------------- prefetch
    def _prefetch_distributed(self, it: Iterator, depth: int) -> Iterator:
        """Yield already-distributed global batches, ``depth`` ahead.

        ``device_put``/``make_array_from_process_local_data`` dispatch
        asynchronously, so queuing the next batches while the device chews
        on the current step overlaps host-side data work with compute —
        the ``.prefetch(AUTOTUNE)`` moment (``imagenet-resnet50.py:47``)
        at the host→HBM boundary.
        """
        from collections import deque

        q: deque = deque()

        def fill():
            while len(q) < depth:
                try:
                    q.append(self.strategy.distribute_batch(next(it)))
                except StopIteration:
                    return

        fill()
        while q:
            batch = q.popleft()
            yield batch
            fill()

    # ------------------------------------------------------------------- fit
    def fit(
        self,
        train_data: Iterable[Dict[str, np.ndarray]],
        epochs: int = 1,
        steps_per_epoch: Optional[int] = None,
        validation_data: Optional[Iterable[Dict[str, np.ndarray]]] = None,
        validation_steps: Optional[int] = None,
        callbacks: Sequence[Callback] = (),
        verbose: int = 2,  # reference uses verbose=2 (imagenet-resnet50.py:67)
        initial_epoch: int = 0,
        prefetch: int = 2,  # device-feed lookahead; 0/1 disables
    ) -> History:
        if validation_data is not None and isinstance(validation_data, Iterator):
            raise ValueError(
                "validation_data is a one-shot iterator; fit() evaluates it "
                "once per epoch, so pass a re-iterable dataset"
            )
        self.steps_per_epoch = steps_per_epoch
        history = History()
        self.stop_training = False
        self.global_step = 0

        train_iter = self._ensure_iterator(train_data)
        if self.state is None:
            first = next(train_iter)
            self.init_state(first)
            train_iter = _chain_first(first, train_iter)

        for cb in callbacks:
            cb.set_trainer(self)

        final_logs: Dict[str, float] = {}
        stopped_mid_epoch = False
        continuous_feed = None
        # on_train_begin is INSIDE the try: if a later callback's
        # on_train_begin raises (corrupt restore, ...), earlier callbacks
        # that already acquired resources (signal handlers, checkpoint
        # managers — utils/preemption.py) still get their on_train_end
        # cleanup from the finally.
        try:
            self._run_hooks(callbacks, "on_train_begin")
            for epoch in range(initial_epoch, epochs):
                if self.stop_training:
                    break
                self._run_hooks(callbacks, "on_epoch_begin", epoch)
                t0 = time.perf_counter()
                step_logs = []
                steps = 0
                samples = 0
                def make_feed(it):
                    if prefetch and prefetch > 1:
                        return self._prefetch_distributed(it, prefetch)
                    return (self.strategy.distribute_batch(b) for b in it)

                if steps_per_epoch is not None:
                    # Continuous stream: ONE persistent feed across epochs
                    # (recreating it each epoch would drop the batches the
                    # prefetcher already pulled from the shared iterator).
                    # A finite RE-ITERABLE dataset repeats when it drains —
                    # the reference's own `.repeat()` + fixed steps_per_epoch
                    # pattern (imagenet-resnet50-ps.py:118-119,143) without
                    # the caller spelling it; each re-pass is a fresh
                    # __iter__ (so per-epoch reshuffles apply). One-shot
                    # iterators still just end.
                    if continuous_feed is None:
                        def _repeating(first_iter, data=train_data):
                            it = first_iter
                            batches = 0
                            repassed = False
                            while True:
                                yielded = False
                                for b in it:
                                    yielded = True
                                    batches += 1
                                    yield b
                                if isinstance(data, Iterator) or not yielded:
                                    return
                                if not repassed:
                                    # Loud once: a mis-sized pipeline (e.g. a
                                    # glob matching too few files) would
                                    # otherwise repeat data silently.
                                    repassed = True
                                    log.warning(
                                        "steps_per_epoch outlives the "
                                        "dataset (%d batches/pass); "
                                        "re-iterating (reference .repeat() "
                                        "semantics)", batches,
                                    )
                                it = iter(data)

                        continuous_feed = make_feed(_repeating(train_iter))
                    feed = continuous_feed
                elif epoch == initial_epoch:
                    # First epoch must include the batch consumed by
                    # init_state via _chain_first; finite data drains the
                    # feed fully so nothing is lost between epochs.
                    feed = make_feed(train_iter)
                else:
                    if isinstance(train_data, Iterator):
                        raise ValueError(
                            "train_data is a one-shot iterator but steps_per_epoch "
                            "is None; pass a re-iterable dataset or set steps_per_epoch"
                        )
                    feed = make_feed(iter(train_data))
                while steps_per_epoch is None or steps < steps_per_epoch:
                    try:
                        global_batch = next(feed)
                    except StopIteration:
                        break
                    # Global batch size (leading dim of the global array).
                    samples += int(global_batch[self.target_key].shape[0])
                    self.state, logs = self._train_step(self.state, global_batch)
                    step_logs.append(logs)
                    self._run_hooks(
                        callbacks, "on_train_batch_end", self.global_step, logs=logs
                    )
                    steps += 1
                    self.global_step += 1
                    if self.stop_training:
                        # Honored mid-epoch (Keras semantics) — e.g. preemption
                        # checkpointing stops at the next batch boundary.
                        stopped_mid_epoch = True
                        break
                if steps == 0:
                    raise ValueError("empty training dataset/epoch")
                if stopped_mid_epoch:
                    # A mid-epoch stop means "exit NOW" (preemption grace
                    # window): no validation pass, no epoch-end hooks (whose
                    # checkpoint saves could also collide with the preemption
                    # save), no partial-epoch History entry that would mislead
                    # plateau/early-stop logic on resume.
                    break

                # Training throughput: window closes before validation runs.
                dt = time.perf_counter() - t0
                epoch_logs = _mean_logs(step_logs)
                if validation_data is not None:
                    val_logs = self.evaluate(validation_data, steps=validation_steps,
                                             verbose=0, _prefix="val_")
                    epoch_logs.update(val_logs)

                epoch_logs["images_per_sec"] = samples / dt if dt > 0 else 0.0
                history.append(epoch, epoch_logs)
                if verbose and self.strategy.is_coordinator:
                    line = " - ".join(
                        [f"Epoch {epoch + 1}/{epochs}", f"{dt:.1f}s"]
                        + [f"{k}: {v:.4f}" for k, v in epoch_logs.items()
                           if k != "images_per_sec"]
                        + [f"{epoch_logs['images_per_sec']:.0f} img/s"]
                    )
                    print(line, file=sys.stderr)
                self._run_hooks(callbacks, "on_epoch_end", epoch, logs=epoch_logs)
                final_logs = epoch_logs

        finally:
            self._run_hooks(callbacks, "on_train_end", logs=final_logs)
        self.history = history
        return history

    # -------------------------------------------------------------- evaluate
    def evaluate(
        self,
        data: Iterable[Dict[str, np.ndarray]],
        steps: Optional[int] = None,
        verbose: int = 0,
        _prefix: str = "",
    ) -> Dict[str, float]:
        if self.state is None:
            raise RuntimeError("call fit() or init_state() before evaluate()")
        it = self._ensure_iterator(data, fresh=True)
        logs_list = []
        n = 0
        while steps is None or n < steps:
            try:
                batch = next(it)
            except StopIteration:
                break
            global_batch = self.strategy.distribute_batch(batch)
            logs_list.append(self._eval_step(self.state, global_batch))
            n += 1
        if not logs_list:
            raise ValueError("empty evaluation dataset")
        out = {_prefix + k: v for k, v in _mean_logs(logs_list).items()}
        if verbose and self.strategy.is_coordinator:
            print(" - ".join(f"{k}: {v:.4f}" for k, v in out.items()), file=sys.stderr)
        return out

    # --------------------------------------------------------------- predict
    def predict(self, images: np.ndarray) -> np.ndarray:
        """Forward pass (inference mode) on a batch of images."""
        if self.state is None:
            raise RuntimeError("call fit() or init_state() before predict()")
        x = self.strategy.distribute_batch(
            {self.input_key: np.asarray(images)})[self.input_key]
        if self.eval_transform is not None:
            x = self.eval_transform(x)
        logits, _ = self._apply(self.state.params, self.state.batch_stats, x, train=False)
        return np.asarray(jax.device_get(logits))

    # --------------------------------------------------------------- helpers
    def _ensure_iterator(self, data, fresh: bool = False) -> Iterator:
        # A bare iterator cannot be restarted; fit() rejects one-shot
        # iterators for train (multi-epoch) and validation data up front.
        if isinstance(data, Iterator):
            return data
        return iter(data)

    def _run_hooks(self, callbacks, hook: str, *args, logs=None) -> None:
        for cb in callbacks:
            fn = getattr(cb, hook)
            if hook in ("on_train_begin",):
                result = fn(self.state)
            elif hook in ("on_train_end",):
                result = fn(self.state, logs or {})
            elif hook == "on_epoch_begin":
                result = fn(args[0], self.state)
            elif hook == "on_epoch_end":
                result = fn(args[0], self.state, logs or {})
            elif hook == "on_train_batch_end":
                result = fn(args[0], self.state, logs or {})
            else:  # pragma: no cover
                raise ValueError(hook)
            if result is not None:
                self.state = result


def _mean_logs(logs_list) -> Dict[str, float]:
    """Fetch once, average on host (one device sync per epoch).

    Perplexity keys are logged per batch in log space (mean CE — see
    ``metrics.log_perplexity``); exponentiating AFTER the average yields
    exactly exp(mean CE) over all tokens (the standard corpus number),
    where a mean of per-batch exponentials would be Jensen-biased high
    and could overflow.
    """
    fetched = jax.device_get(logs_list)
    keys = fetched[0].keys()
    out = {}
    for k in keys:
        vals = np.asarray([d[k] for d in fetched], np.float64)
        # Exact key only (evaluate() adds its val_ prefix after this
        # aggregation): user metrics with "perplexity" in their name are
        # not assumed to be log-space.
        if k == "perplexity":
            out[k] = float(np.exp(np.mean(vals)))
        else:
            out[k] = float(np.mean(vals))
    return out


def _chain_first(first, rest: Iterator) -> Iterator:
    yield first
    yield from rest
