"""Losses and metrics.

Parity surface: the reference compiles every model with
``loss='sparse_categorical_crossentropy'`` and ``metrics=['accuracy']``
(``/root/reference/imagenet-resnet50.py:62``). We compute from *logits* (the
reference's softmax head + CE is folded into one numerically-stable
log-softmax CE — same gradients, fewer HBM round-trips).

Under the trainer's jit-with-shardings regime a ``jnp.mean`` over the
globally-sharded batch axis compiles to a cross-replica reduction, so these
per-batch metrics are already the cross-worker averages the reference gets
from ``MetricAverageCallback`` (``imagenet-resnet50-hvd.py:112-113``).
"""

from __future__ import annotations

from typing import Callable, Dict

import jax.numpy as jnp
import optax

MetricFn = Callable[[jnp.ndarray, jnp.ndarray], jnp.ndarray]  # (logits, labels) -> scalar


def sparse_categorical_crossentropy(logits: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    """Mean CE over the (possibly globally sharded) batch; labels are ints."""
    return optax.softmax_cross_entropy_with_integer_labels(logits, labels).mean()


def categorical_crossentropy(logits: jnp.ndarray, onehot: jnp.ndarray) -> jnp.ndarray:
    return optax.softmax_cross_entropy(logits, onehot).mean()


def mean_squared_error(pred: jnp.ndarray, target: jnp.ndarray) -> jnp.ndarray:
    return jnp.mean((pred - target) ** 2)


LOSSES: Dict[str, MetricFn] = {
    "sparse_categorical_crossentropy": sparse_categorical_crossentropy,
    "categorical_crossentropy": categorical_crossentropy,
    "mse": mean_squared_error,
}


def accuracy(logits: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    """Top-1 accuracy (the reference's ``metrics=['accuracy']``)."""
    return jnp.mean(jnp.argmax(logits, axis=-1) == labels)


def top_k_accuracy(k: int) -> MetricFn:
    def _top_k(logits: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
        top = jnp.argsort(logits, axis=-1)[..., -k:]
        return jnp.mean(jnp.any(top == labels[..., None], axis=-1))

    _top_k.__name__ = f"top_{k}_accuracy"
    return _top_k


def log_perplexity(logits: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    """Mean token cross-entropy (what the ``perplexity`` metric logs).

    The registry maps ``"perplexity"`` to THIS log-space value: it is
    overflow-free on device (exp(CE) hits float32 inf at CE ≈ 88.7) and
    averaging it across batches then exponentiating once — which the
    Trainer's ``_mean_logs`` does for the exact key ``"perplexity"``
    only — is exactly exp(mean CE) over all tokens, the standard corpus
    number, rather than a Jensen-biased mean of exponentials. Per-BATCH
    callback logs therefore carry the log-space value. Logged under any
    OTHER key (e.g. ``metrics=[log_perplexity]`` → ``"log_perplexity"``)
    the epoch value stays an averaged log-space number.
    """
    ce = optax.softmax_cross_entropy_with_integer_labels(logits, labels)
    return jnp.mean(ce)


def perplexity(logits: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    """exp(mean token cross-entropy) — for direct one-shot use.

    Works on ``[B, V]`` or ``[B, S, V]`` logits (mean over all tokens).
    The Trainer metric named ``"perplexity"`` logs :func:`log_perplexity`
    per batch and exponentiates after epoch averaging instead.
    """
    return jnp.exp(log_perplexity(logits, labels))


METRICS: Dict[str, MetricFn] = {
    "accuracy": accuracy,
    "top_5_accuracy": top_k_accuracy(5),
    "perplexity": log_perplexity,
}


def resolve_loss(loss: str | MetricFn) -> MetricFn:
    if callable(loss):
        return loss
    try:
        return LOSSES[loss]
    except KeyError:
        raise ValueError(f"unknown loss {loss!r}; known: {sorted(LOSSES)}") from None


def resolve_metric(metric: str | MetricFn) -> tuple[str, MetricFn]:
    if metric is perplexity:
        # The public exp-space helper is for one-shot use; as a Trainer
        # metric it must log in log space (the exact "perplexity" key is
        # exponentiated once after epoch averaging — loop._mean_logs).
        return "perplexity", log_perplexity
    if callable(metric):
        return getattr(metric, "__name__", "metric"), metric
    try:
        return metric, METRICS[metric]
    except KeyError:
        raise ValueError(f"unknown metric {metric!r}; known: {sorted(METRICS)}") from None
