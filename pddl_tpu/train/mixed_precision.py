"""Safe parameter-update rules for low-precision parameter storage.

The bf16 storage recipe (``config.py param_dtype`` — how the 1B llama
fits one v5e chip) carries a measured quality cost: +0.0244 nats (+2.4%
val loss) at the 304M/3k-step pycorpus budget (docs/CONVERGENCE.md,
round 4). The physical cause is round-to-nearest on the parameter
update: with LR ~3e-4 the per-step update is ~1e-4 of the parameter
scale while a bf16 ulp is ~0.4% relative (8 mantissa bits), so most
updates round to ZERO and their information is lost — a systematic
bias, not noise.

(It is the *update* that is at fault, not the moments: Adam's moments
under bf16 params silently settle in f32 anyway — the f32 hyperparams
pinned in ``make_optimizer`` promote ``b1*mu + (1-b1)*g`` to f32 on the
first step. ``make_optimizer`` now pins them f32 from ``init`` so the
state dtype is stable (no hidden step-2 retrace) and the memory
arithmetic below is honest.)

Two optax wrappers erase the bias, trading memory differently
(bytes per parameter, Adam):

==========================  =======  ==================================
recipe                      bytes/p  quality mechanism
==========================  =======  ==================================
f32 everything                   12  baseline
bf16 plain                       10  none — loses sub-ulp updates
bf16 + stochastic_round          10  unbiased rounding: E[round(x)]=x,
                                     a sub-ulp update lands with
                                     probability update/ulp, so updates
                                     accumulate correctly in expectation
bf16 + f32_master                14  exact: the f32 master accumulates
                                     every update; bf16 params are a
                                     cast of it
==========================  =======  ==================================

``stochastic_round`` is the headline fix: SAME memory as the plain bf16
recipe (the RNG key is 8 bytes total), strictly better convergence.
``f32_master`` is the exactness gold standard — more total HBM than
pure f32; its bf16 params buy *bandwidth* (matmul reads) and activation
dtype, not capacity. Under the PS/ZeRO strategy both wrappers' extra
state (master copy) shards over the data axis like any other optimizer
leaf, so per-chip cost divides by the axis size.

Both wrap the INJECTED optimizer chain (inside grad-clip and
``optax.MultiSteps``) and keep their state as NamedTuples so
``get_learning_rate``/``set_learning_rate``'s tuple recursion reaches
the inner ``inject_hyperparams`` state unchanged.

Reference stake: the reference's deliverable is a *trained* model
(``/root/reference/imagenet-resnet50.py:67``) — a memory recipe that
trains worse is not parity. Measured end-to-end by
``examples/real_data_convergence.py --track bf16-recipe-safe``.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import optax
from jax import lax

PyTree = Any


def _f32(tree: PyTree) -> PyTree:
    return jax.tree.map(lambda x: x.astype(jnp.float32), tree)


def _sr_to_bf16(x32: jnp.ndarray, key: jnp.ndarray) -> jnp.ndarray:
    """Stochastically round an f32 array to bf16.

    bf16 is f32 with the low 16 mantissa bits dropped, so adding a
    uniform random 16-bit integer to the f32 bit pattern and truncating
    implements exact stochastic rounding: the probability of rounding up
    equals the truncated fraction, and the truncated-bits-zero f32 is
    value-identical to its bf16 cast.
    """
    bits = lax.bitcast_convert_type(x32, jnp.uint32)
    noise = jax.random.bits(key, x32.shape, jnp.uint32) & jnp.uint32(0xFFFF)
    rounded = (bits + noise) & jnp.uint32(0xFFFF0000)
    return lax.bitcast_convert_type(rounded, jnp.float32).astype(jnp.bfloat16)


class StochasticRoundState(NamedTuple):
    key: jnp.ndarray  # raw uint32 PRNG key (orbax-serializable)
    inner: optax.OptState


def stochastic_round_update(
    inner: optax.GradientTransformation, *, seed: int = 0,
) -> optax.GradientTransformation:
    """Apply ``inner``'s updates to bf16 params with stochastic rounding.

    The inner optimizer runs in f32 (f32 grads in, f32-initialized
    moments). Emitted updates ``u`` are built so ``optax.apply_updates``
    reproduces the stochastically-rounded new parameters bit-for-bit:
    the rounded value and the old parameter are both exactly
    representable in f32, so ``f32(new) - f32(old)``, added back in f32
    and cast, is lossless. Non-bf16 leaves pass the inner update through
    untouched.
    """

    def init(params: PyTree) -> StochasticRoundState:
        return StochasticRoundState(
            key=jax.random.PRNGKey(seed), inner=inner.init(_f32(params)))

    def update(grads, state, params=None):
        if params is None:
            raise ValueError("stochastic_round_update requires params")
        u, inner_state = inner.update(_f32(grads), state.inner, _f32(params))
        key, sub = jax.random.split(state.key)
        leaves, treedef = jax.tree.flatten(params)
        u_leaves = treedef.flatten_up_to(u)
        out = []
        for i, (p, du) in enumerate(zip(leaves, u_leaves)):
            if p.dtype != jnp.bfloat16:
                out.append(du)
                continue
            new32 = p.astype(jnp.float32) + du.astype(jnp.float32)
            new16 = _sr_to_bf16(new32, jax.random.fold_in(sub, i))
            out.append(new16.astype(jnp.float32) - p.astype(jnp.float32))
        return (jax.tree.unflatten(treedef, out),
                StochasticRoundState(key=key, inner=inner_state))

    return optax.GradientTransformation(init, update)


class F32MasterState(NamedTuple):
    master: PyTree
    inner: optax.OptState


def f32_master_update(
    inner: optax.GradientTransformation,
) -> optax.GradientTransformation:
    """Keep an f32 master copy; bf16 stored params are a cast of it.

    The inner optimizer runs entirely against the f32 master (so its
    moments are f32 too), every update accumulates exactly, and the
    emitted update rebases the stored params onto ``cast(master)`` —
    ``f32(cast(new_master)) - f32(params)`` is exact in f32, so
    ``optax.apply_updates`` reproduces the cast bit-for-bit. Leaves
    already in f32 (or any non-bf16 dtype) receive the inner update
    directly and their master stays equal to them by construction.
    """

    def init(params: PyTree) -> F32MasterState:
        if not any(leaf.dtype == jnp.bfloat16
                   for leaf in jax.tree.leaves(params)):
            # No bf16 leaves: a master copy would duplicate every
            # parameter (+4 bytes/param of optimizer state) for zero
            # behavioral change — make the documented "no-op for f32
            # params" literal. master=None marks the pass-through.
            return F32MasterState(master=None, inner=inner.init(params))
        master = _f32(params)
        return F32MasterState(master=master, inner=inner.init(master))

    def update(grads, state, params=None):
        if params is None:
            raise ValueError("f32_master_update requires params")
        if state.master is None:
            u, inner_state = inner.update(grads, state.inner, params)
            return u, F32MasterState(master=None, inner=inner_state)
        u, inner_state = inner.update(_f32(grads), state.inner, state.master)
        new_master = optax.apply_updates(state.master, u)

        def emit(m_new, p, du):
            if p.dtype == jnp.bfloat16:
                return (m_new.astype(jnp.bfloat16).astype(jnp.float32)
                        - p.astype(jnp.float32))
            return du

        out = jax.tree.map(emit, new_master, params, u)
        return out, F32MasterState(master=new_master, inner=inner_state)

    return optax.GradientTransformation(init, update)


def stabilize_moment_dtype(
    tx: optax.GradientTransformation,
) -> optax.GradientTransformation:
    """Pin bf16 optimizer-state leaves (Adam moments, SGD traces) to f32
    at ``init``.

    They settle there after one update regardless — the f32 hyperparams
    pinned in ``make_optimizer`` promote ``b1*mu + (1-b1)*g`` to f32 —
    so initializing them bf16 only buys a hidden retrace of the jitted
    train step at step 2 when the state signature changes. A no-op for
    f32 params.
    """

    def init(params: PyTree) -> optax.OptState:
        return jax.tree.map(
            lambda l: l.astype(jnp.float32)
            if getattr(l, "dtype", None) == jnp.bfloat16 else l,
            tx.init(params))

    return optax.GradientTransformation(init, tx.update)


#: config-string → wrapper registry (``config.param_update``).
PARAM_UPDATE_MODES = ("plain", "stochastic_round", "f32_master")


def wrap_param_update(
    tx: optax.GradientTransformation, mode: str, *, seed: int = 0,
) -> optax.GradientTransformation:
    """Apply a :data:`PARAM_UPDATE_MODES` wrapper to a built chain."""
    if mode == "plain":
        return tx
    if mode == "stochastic_round":
        return stochastic_round_update(tx, seed=seed)
    if mode == "f32_master":
        return f32_master_update(tx)
    raise ValueError(
        f"unknown param_update {mode!r}; known: {PARAM_UPDATE_MODES}")
