"""Train state + optimizer factory.

The reference compiles with ``optimizer='adam'`` everywhere
(``/root/reference/imagenet-resnet50.py:62``), with the Horovod variant
scaling LR by world size (``imagenet-resnet50-hvd.py:99``). Optimizers here
are optax transforms wrapped in ``inject_hyperparams`` so the learning rate
is *state*, not a trace-time constant — that is what lets
``ReduceLROnPlateau`` / warmup callbacks (``imagenet-resnet50.py:64``,
``imagenet-resnet50-hvd.py:114``) adjust LR between steps without
recompiling the jitted train step.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import optax
from flax import struct

PyTree = Any


class TrainState(struct.PyTreeNode):
    """Pure-data training state (params + BN stats + optimizer state).

    Unlike Keras's stateful ``Model``, everything mutable lives here and the
    train step is a pure function ``(state, batch, rng) -> (state, metrics)``
    — the property that lets XLA compile the whole update, shard it over a
    mesh, and donate buffers.
    """

    step: jnp.ndarray
    params: PyTree
    batch_stats: PyTree
    opt_state: optax.OptState
    # Shadow parameters for exponential moving averaging (None = disabled).
    # Evaluating/serving with the EMA weights is standard large-batch
    # practice; the reference has no analogue (Keras Adam only).
    ema_params: PyTree = None
    # Shadow of batch_stats under EMA, so BatchNorm models evaluate EMA
    # weights against statistics averaged on the SAME cadence — evaluating
    # EMA params against the live stats skews BN eval metrics.
    ema_batch_stats: PyTree = None

    def apply_gradients(self, tx: optax.GradientTransformation, grads: PyTree,
                        new_batch_stats: PyTree | None = None,
                        ema_decay: float | None = None) -> "TrainState":
        updates, new_opt_state = tx.update(grads, self.opt_state, self.params)
        new_params = optax.apply_updates(self.params, updates)
        new_ema = self.ema_params
        new_ema_bs = self.ema_batch_stats
        if ema_decay is not None:
            # optax.MultiSteps: mid-accumulation steps emit zero updates;
            # decaying the EMA there would compound to decay^k per real
            # update. mini_step wraps to 0 exactly when the averaged
            # update was applied. batch_stats shadow on the same cadence.
            emit = (new_opt_state.mini_step == 0
                    if hasattr(new_opt_state, "mini_step") else None)

            def shadowed(shadow: PyTree, live: PyTree) -> PyTree:
                decayed = jax.tree.map(
                    lambda e, p: e * ema_decay + (1.0 - ema_decay) * p,
                    shadow, live,
                )
                if emit is None:
                    return decayed
                return jax.tree.map(
                    lambda d, e: jnp.where(emit, d, e), decayed, shadow
                )

            if new_ema is not None:
                new_ema = shadowed(new_ema, new_params)
            if new_ema_bs is not None:
                new_ema_bs = shadowed(
                    new_ema_bs,
                    new_batch_stats if new_batch_stats is not None
                    else self.batch_stats,
                )
        return self.replace(
            step=self.step + 1,
            params=new_params,
            batch_stats=new_batch_stats if new_batch_stats is not None else self.batch_stats,
            opt_state=new_opt_state,
            ema_params=new_ema,
            ema_batch_stats=new_ema_bs,
        )


_OPTIMIZERS: dict[str, Callable[..., optax.GradientTransformation]] = {
    "adam": optax.adam,
    "adamw": optax.adamw,
    "sgd": optax.sgd,
    "momentum": lambda learning_rate, **kw: optax.sgd(learning_rate, momentum=kw.pop("momentum", 0.9), **kw),
    "rmsprop": optax.rmsprop,
    "lamb": optax.lamb,
    "lars": optax.lars,
    "adagrad": optax.adagrad,
}


def make_schedule(
    name: str | Callable[[jnp.ndarray], jnp.ndarray],
    learning_rate: float,
    *,
    decay_steps: Optional[int] = None,
    warmup_steps: int = 0,
    alpha: float = 0.0,
    decay_rate: float = 0.96,
    boundaries_and_scales: Optional[dict] = None,
    end_value: float = 0.0,
) -> Callable[[jnp.ndarray], jnp.ndarray]:
    """Build a compiled LR schedule (an ``optax`` step→LR function).

    Schedules run *inside* the jitted step — no host round-trip per epoch,
    unlike the reference's callback-driven LR control
    (``imagenet-resnet50.py:64``, ``imagenet-resnet50-hvd.py:114``), which
    remains available for plateau-style adaptive control.

    Names: ``cosine`` (optionally warmed up), ``exponential``, ``linear``,
    ``piecewise`` (step decay via ``boundaries_and_scales``), ``constant``.

    One convention for ``warmup_steps`` across every schedule (the one
    ``optax.warmup_cosine_decay_schedule`` uses): ``decay_steps`` is the
    TOTAL schedule horizon INCLUDING warmup, so horizon-style schedules
    (``cosine``, ``linear``) finish decaying exactly at step
    ``decay_steps``, with the decay portion running over
    ``decay_steps - warmup_steps``. ``exponential``'s ``decay_steps`` is a
    rate constant (multiply by ``decay_rate`` per ``decay_steps`` updates
    after warmup), not a horizon. ``piecewise`` boundaries are absolute
    step indices whether or not warmup is present (each must be >=
    ``warmup_steps``).
    """
    if callable(name):
        return name
    kind = name.lower()
    if kind in ("cosine", "warmup_cosine"):
        if decay_steps is None:
            raise ValueError(f"{kind!r} schedule requires decay_steps")
        if kind == "warmup_cosine" and not warmup_steps:
            raise ValueError(
                "'warmup_cosine' requires warmup_steps > 0; use 'cosine' "
                "for no warmup"
            )
        if warmup_steps and decay_steps <= warmup_steps:
            raise ValueError(
                f"cosine schedule needs decay_steps > warmup_steps (total "
                f"horizon includes warmup); got {decay_steps} <= "
                f"{warmup_steps}"
            )
        if warmup_steps:
            return optax.warmup_cosine_decay_schedule(
                init_value=0.0, peak_value=learning_rate,
                warmup_steps=warmup_steps, decay_steps=decay_steps,
                end_value=alpha * learning_rate,
            )
        return optax.cosine_decay_schedule(learning_rate, decay_steps, alpha)
    if kind == "exponential":
        if decay_steps is None:
            raise ValueError("'exponential' schedule requires decay_steps")
        # decay_steps is a RATE constant here (transition steps per
        # decay_rate application), not a horizon — warmup subtraction
        # would silently change the decay rate.
        sched = optax.exponential_decay(learning_rate, decay_steps, decay_rate)
    elif kind == "linear":
        if decay_steps is None:
            raise ValueError("'linear' schedule requires decay_steps")
        if warmup_steps and decay_steps <= warmup_steps:
            raise ValueError(
                f"'linear' schedule needs decay_steps > warmup_steps "
                f"(total horizon includes warmup); got {decay_steps} <= "
                f"{warmup_steps}"
            )
        # Total-horizon convention: the decay leg covers what remains of
        # decay_steps after warmup, so LR hits end_value at decay_steps.
        sched = optax.linear_schedule(
            learning_rate, end_value, decay_steps - warmup_steps
        )
    elif kind == "piecewise":
        if not boundaries_and_scales:
            raise ValueError(
                "'piecewise' schedule requires boundaries_and_scales "
                "({step: scale, ...}); without them it would silently be "
                "a constant LR"
            )
        if warmup_steps:
            if any(b < warmup_steps for b in boundaries_and_scales):
                raise ValueError(
                    "'piecewise' boundaries are absolute step indices and "
                    f"must be >= warmup_steps={warmup_steps}; got "
                    f"{sorted(boundaries_and_scales)}"
                )
            # join_schedules rebases the tail to (step - warmup_steps);
            # shift the boundaries so they stay absolute for the caller.
            boundaries_and_scales = {
                b - warmup_steps: s for b, s in boundaries_and_scales.items()
            }
        sched = optax.piecewise_constant_schedule(
            learning_rate, boundaries_and_scales
        )
    elif kind == "constant":
        sched = optax.constant_schedule(learning_rate)
    else:
        raise ValueError(
            f"unknown schedule {name!r}; known: cosine, warmup_cosine, "
            "exponential, linear, piecewise, constant"
        )
    if warmup_steps:
        warmup = optax.linear_schedule(0.0, learning_rate, warmup_steps)
        sched = optax.join_schedules([warmup, sched], [warmup_steps])
    return sched


def make_optimizer(
    name: str | optax.GradientTransformation = "adam",
    learning_rate: float = 1e-3,  # Keras Adam default, as compiled at :62
    *,
    schedule: Optional[str | Callable] = None,
    schedule_options: Optional[dict] = None,
    weight_decay: Optional[float] = None,
    grad_clip_norm: Optional[float] = None,
    accumulate_steps: Optional[int] = None,
    param_update: str = "plain",
    update_seed: int = 0,
    **kwargs,
) -> optax.GradientTransformation:
    """Build an optimizer with a state-injected (callback-adjustable) LR.

    With ``schedule`` set, the LR is a compiled step→value function
    (:func:`make_schedule`); ``inject_hyperparams`` still exposes the
    current value in the optimizer state, so ``get_learning_rate`` keeps
    working (callback writes would be overwritten each step — pick
    schedule OR plateau-callback control, not both).

    ``accumulate_steps=k`` wraps the whole chain in ``optax.MultiSteps``:
    gradients average over k consecutive micro-batches and the parameters
    move once per k steps — how a reference global batch that exceeds HBM
    at 32/replica (``imagenet-resnet50-mirror.py:54``) still trains with
    identical optimizer math. Schedules then count *optimizer* updates,
    not micro-steps.

    ``param_update`` selects the low-precision update rule for bf16
    parameter storage (:mod:`pddl_tpu.train.mixed_precision`):
    ``"plain"`` (round-to-nearest — loses sub-ulp updates, the measured
    +2.4% recipe), ``"stochastic_round"`` (unbiased rounding, same
    memory), or ``"f32_master"`` (exact f32 master copy). A no-op for
    f32 params.
    """
    if isinstance(name, optax.GradientTransformation):
        # A prebuilt transformation: chain-level options still compose;
        # factory-level ones cannot be injected after the fact.
        if (schedule is not None or weight_decay is not None
                or "decay_mask" in kwargs):
            raise ValueError(
                "schedule/weight_decay/decay_mask cannot be applied to a "
                "prebuilt optax.GradientTransformation — build it with "
                "them, or pass the optimizer by name"
            )
        tx = name
        from pddl_tpu.train.mixed_precision import (
            stabilize_moment_dtype,
            wrap_param_update,
        )

        # param_update composes with a prebuilt chain the same way the
        # factory path does — silently ignoring it would train with the
        # biased plain rule while config/logs claim otherwise.
        if param_update != "plain":
            tx = wrap_param_update(tx, param_update, seed=update_seed)
        if grad_clip_norm is not None:
            tx = optax.chain(optax.clip_by_global_norm(grad_clip_norm), tx)
        if accumulate_steps is not None and accumulate_steps > 1:
            tx = optax.MultiSteps(tx, every_k_schedule=accumulate_steps)
        # NO moment-dtype pin here: a prebuilt chain carries Python-float
        # (weak-typed) hyperparams, so bf16 moments genuinely stay bf16 —
        # the user's deliberate choice; promoting them would double
        # moment memory and break restore against old checkpoints. The
        # promotion premise only holds for the injected factory path
        # below (f32 hyperparam arrays).
        return tx
    try:
        factory = _OPTIMIZERS[name.lower()]
    except KeyError:
        raise ValueError(f"unknown optimizer {name!r}; known: {sorted(_OPTIMIZERS)}") from None
    has_decay_mask = "decay_mask" in kwargs
    decay_mask = kwargs.pop("decay_mask", None)
    if name.lower() in ("adamw", "lamb"):
        if weight_decay is not None:
            kwargs["weight_decay"] = weight_decay
        # Standard practice: decay matrices only — biases, LayerNorm/BN
        # scales and other 1D leaves are excluded (decaying them hurts and
        # no major recipe does it). This applies to the optimizer's OWN
        # default decay too (optax.adamw defaults to 1e-4), not just an
        # explicit weight_decay. decay_mask overrides (an optax mask
        # pytree/callable; None = decay everything).
        if has_decay_mask:
            if decay_mask is not None:
                kwargs["mask"] = decay_mask
        else:
            kwargs["mask"] = lambda params: jax.tree.map(
                lambda p: p.ndim > 1, params)
    elif weight_decay is not None or has_decay_mask:
        raise ValueError(
            f"weight_decay/decay_mask are not supported for {name!r} (they "
            "would be silently ignored); use 'adamw'/'lamb', or pass a "
            "prebuilt optax.GradientTransformation with "
            "optax.add_decayed_weights"
        )
    lr: Any = learning_rate
    if schedule is not None:
        lr = make_schedule(schedule, learning_rate, **(schedule_options or {}))
    # `mask` must be declared static: inject_hyperparams otherwise treats
    # any callable kwarg as a step->value schedule. hyperparam_dtype MUST
    # be pinned to f32: inject otherwise casts hyperparams to the params'
    # dtype, and under bf16 parameter storage b2=0.999 rounds to exactly
    # 1.0 — bias correction 1-b2^t becomes 0 and the first Adam update
    # divides by zero (params go NaN in one step).
    inject = (optax.inject_hyperparams(factory, static_args=("mask",),
                                       hyperparam_dtype=jnp.float32)
              if "mask" in kwargs
              else optax.inject_hyperparams(factory,
                                            hyperparam_dtype=jnp.float32))
    tx = inject(learning_rate=lr, **kwargs)
    from pddl_tpu.train.mixed_precision import (
        stabilize_moment_dtype,
        wrap_param_update,
    )

    if param_update != "plain":
        tx = wrap_param_update(tx, param_update, seed=update_seed)
    if grad_clip_norm is not None:
        tx = optax.chain(optax.clip_by_global_norm(grad_clip_norm), tx)
    if accumulate_steps is not None and accumulate_steps > 1:
        tx = optax.MultiSteps(tx, every_k_schedule=accumulate_steps)
    # Under bf16 params, f32 hyperparams promote every floating state
    # leaf (Adam moments, MultiSteps' grad accumulator) to f32 on the
    # FIRST update anyway; pinning them f32 from init keeps the jitted
    # step's state signature stable (no hidden step-2 retrace) and makes
    # the recipe's memory honest: bf16 params, f32 optimizer state.
    return stabilize_moment_dtype(tx)


def _find_hyperparams(opt_state) -> Optional[dict]:
    """Locate the inject_hyperparams dict inside a possibly-chained state."""
    if hasattr(opt_state, "hyperparams") and "learning_rate" in opt_state.hyperparams:
        return opt_state.hyperparams
    # optax.MultiSteps needs no special case: MultiStepsState is a
    # NamedTuple, so the tuple recursion reaches inner_opt_state.
    if isinstance(opt_state, tuple):
        for sub in opt_state:
            found = _find_hyperparams(sub)
            if found is not None:
                return found
    return None


def get_learning_rate(state: TrainState) -> float:
    """Current LR (the ``model.optimizer.lr`` read in Keras callbacks)."""
    hp = _find_hyperparams(state.opt_state)
    if hp is None:
        raise ValueError("optimizer has no injected learning_rate hyperparam")
    return float(jax.device_get(hp["learning_rate"]))


def set_learning_rate(state: TrainState, value: float) -> TrainState:
    """Return state with a new LR — functional ``optimizer.lr.assign``.

    Powers ReduceLROnPlateau (``imagenet-resnet50.py:64``) and Horovod-style
    warmup (``imagenet-resnet50-hvd.py:114``) without retracing: the LR is an
    optimizer-state leaf, so the jitted step just sees a new value.
    """

    def _set(opt_state):
        if hasattr(opt_state, "hyperparams") and "learning_rate" in opt_state.hyperparams:
            old = opt_state.hyperparams["learning_rate"]
            new_hp = dict(opt_state.hyperparams)
            # Stamp a DEVICE scalar, placed like the leaf it replaces.
            # A host-numpy scalar here rides the next donated train
            # step as a buffer the runtime does not own — the
            # r10-documented container-jaxlib corruption class, and the
            # roaming tier-1 flake (ROADMAP "Known flake": the final LR
            # read back as float32-bits-of-int). Re-using the OLD
            # leaf's sharding keeps the multi-host property the numpy
            # choice was protecting: every process stamps the same
            # value under the same (committed) sharding, so checkpoint
            # saves still see a consistently-addressable array.
            dtype = jnp.asarray(old).dtype
            if isinstance(old, jax.Array) and hasattr(old, "sharding"):
                new_hp["learning_rate"] = jax.device_put(
                    jnp.asarray(value, dtype=dtype), old.sharding)
            else:
                # The leaf is ALREADY host numpy (a tree from an old
                # pre-r15 setter — fresh init and verified-ckpt
                # restore both produce jax.Arrays). Replacing host
                # with host keeps the multi-host save property; a bare
                # jnp scalar here would be host-local
                # (SingleDeviceSharding), which a multi-host save
                # rejects — and no NEW donation hazard is introduced,
                # since the tree carried a host leaf before this call.
                import numpy as _np

                new_hp["learning_rate"] = _np.asarray(  # graftlint: disable=donation
                    value, dtype=dtype)
            return opt_state._replace(hyperparams=new_hp)
        if isinstance(opt_state, tuple):
            subs = [_set(s) for s in opt_state]
            return type(opt_state)(*subs) if hasattr(opt_state, "_fields") else tuple(subs)
        return opt_state

    if _find_hyperparams(state.opt_state) is None:
        raise ValueError("optimizer has no injected learning_rate hyperparam")
    return state.replace(opt_state=_set(state.opt_state))
