"""Utilities: profiling/tracing, throughput accounting, determinism helpers."""
