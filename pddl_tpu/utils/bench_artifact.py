"""Bench-artifact provenance and repeat-timing discipline.

Round 5's verdict found the committed serving docs and artifacts
disagreeing (2.02x in prose vs 1.505x in the final-tree JSON; two r5
artifacts 26% apart on an identical config) because numbers were
measured on MIXED TREES with single-shot timings. This module is the
fix, shared by every serving bench (`serve_bench.py`,
`decode_bench.py`, `specdecode_bench.py`):

- :func:`provenance` stamps ``{git_commit, dirty, n_repeats}`` into the
  record, so any artifact can be traced to the exact tree it measured
  (and a dirty tree is visible, not hidden).
- :func:`timed_stats` runs ``n_repeats >= 3`` timed repetitions and
  returns ``{median, spread_pct, samples}`` — the median is the
  headline, the spread is the drift detector (a >5% spread means the
  number is weather, not signal, and the docs must say so).

Keep the repo's sync discipline: the ``sync`` callable must FETCH A
VALUE from the result (``int(out[0, -1])``-style), because
``block_until_ready`` is not a reliable barrier on tunneled transports
(ARCHITECTURE.md §7e, round-5 re-measurement note).
"""

from __future__ import annotations

import json
import os
import re
import statistics
import subprocess
import time
from typing import Callable, Dict, List, Optional, Tuple


def git_commit() -> Dict[str, object]:
    """``{commit, dirty}`` of the working tree, or ``unknown`` outside
    a repo — never raises (benches must run anywhere)."""
    try:
        commit = subprocess.run(
            ["git", "rev-parse", "HEAD"], capture_output=True, text=True,
            check=True, timeout=10).stdout.strip()
        dirty = bool(subprocess.run(
            ["git", "status", "--porcelain"], capture_output=True,
            text=True, check=True, timeout=10).stdout.strip())
        return {"commit": commit, "dirty": dirty}
    except Exception:  # noqa: BLE001 - no git, not a repo, timeout: all fine
        return {"commit": "unknown", "dirty": None}


def provenance(n_repeats: int) -> Dict[str, object]:
    """The artifact-level provenance block every serving bench embeds
    as ``record["provenance"]``."""
    g = git_commit()
    return {
        "git_commit": g["commit"],
        "git_dirty": g["dirty"],
        "n_repeats": int(n_repeats),
        "timing": "median over n_repeats; spread_pct = "
                  "100*(max-min)/median",
    }


def median_spread(samples: List[float]) -> tuple:
    """``(median, spread_pct)`` of a sample list — ONE definition of
    both statistics (``statistics.median``, even-length averaging), so
    no bench can drift to a different convention. Requires >= 3
    samples: a single sample cannot expose drift."""
    if len(samples) < 3:
        raise ValueError(
            f"need >= 3 samples for a meaningful spread, got "
            f"{len(samples)}")
    med = statistics.median(samples)
    return med, 100.0 * (max(samples) - min(samples)) / med


def timed_stats(fn: Callable, sync: Callable, *,
                n_repeats: int = 3) -> Dict[str, object]:
    """Median/spread wall-clock of ``sync(fn())`` over ``n_repeats``
    repetitions (>= 3 enforced via :func:`median_spread`). The caller
    warms compilation before the first call."""
    samples: List[float] = []
    for _ in range(max(n_repeats, 0)):
        t0 = time.perf_counter()
        sync(fn())
        samples.append(time.perf_counter() - t0)
    med, spread = median_spread(samples)
    return {
        "median_s": med,
        "spread_pct": spread,
        "samples_s": [round(s, 6) for s in samples],
    }


# ---------------------------------------------------------------------
# Artifact comparison: the perf-trajectory gate (ROADMAP item 5).
#
# The artifact series is now long enough that SILENT regressions are the
# main risk to the "fast as the hardware allows" claim: a slow change
# lands, the next round re-measures on the slower tree, and the docs
# faithfully quote the regressed number. The gate makes that loud:
# compare() diffs two records measured at the SAME (metric, config) and
# fails on any headline median moving the WRONG direction by more than
# the threshold — higher-is-better keys (tok/s, speedup, hit rate,
# retention) falling, lower-is-better keys (TTFT, latency, wall time)
# rising. Spread/sample/count keys are noise, not headlines, and are
# never compared.

# Direction heuristics over the repo's artifact key vocabulary. Checked
# in order: the FIRST match wins, so e.g. "ttft_reduction_x" (a ratio,
# higher = better) beats the "ttft" latency rule.
_HIGHER_BETTER = ("tokens_per_s", "tokens_per_sec", "speedup", "retained",
                  "reduction", "hit_rate", "accepted", "_per_tick",
                  "throughput", "goodput", "shed_absorbed",
                  "eliminated", "tokens_per_byte",
                  # Any *_tok_s leaf is a decode rate (r14's mixed/
                  # plain/constrained legs included); adapter_hit_rate
                  # rides "hit_rate", mask_overhead_x "overhead". The
                  # graftlint snapshot-hygiene rule audits every
                  # committed headline key against this vocabulary.
                  "tok_s",
                  # Throughput ratios against a clean baseline
                  # (r09 tracing_off_vs_r08_clean_x, r11 vs_r08_clean_x)
                  # and the tracing-on/off retention ratio: up = less
                  # overhead lost.
                  "clean_x", "tracing_on_over_off",
                  # Elastic-autoscaling headlines (r16): goodput rides
                  # the "goodput" rule; scale_events is the per-wave
                  # floor of executed capacity transitions (an r-record
                  # whose autoscaler stops scaling must fail loudly);
                  # *_zero_lost counts requests live-migrated with
                  # nothing lost — fewer proven-safe migrations is a
                  # coverage regression.
                  "scale_events", "zero_lost",
                  # Speculative-serving headlines (r17): acceptance_rate
                  # is the draft-quality series behind the throughput
                  # win (spec_tok_s rides "tok_s", spec_speedup_x rides
                  # "speedup", tokens_per_tick rides "_per_tick").
                  "acceptance_rate",
                  # Tiered-KV-cache headlines (r18): demotion/promotion
                  # traffic that stopped happening is a coverage
                  # regression (spilled blocks are chains saved from
                  # recompute, promoted blocks are prefills avoided);
                  # "promot" covers both host_tier_promotions and
                  # host_tier_promote_tokens_charged; hit-rate leaves
                  # ride "hit_rate", the TTFT ratio rides "ttft"
                  # below, chain pulls ride "chain_pull".
                  "spill", "promot", "chain_pull",
                  # Control-plane robustness headlines (r19): hedge
                  # wins are interactive requests a gray replica would
                  # have stalled (throughput_retained rides
                  # "retained", the hedged-TTFT ratio rides
                  # "reduction"; raw wire-reject COUNTS are draw-level
                  # telemetry, deliberately not gated).
                  "hedge_win",
                  # Storage-fault availability (r21): the fraction of
                  # clean throughput the fleet holds while its WAL is
                  # degraded NON_DURABLE under a persistent-EIO storm
                  # — a dying disk must cost serving nothing (re-arm
                  # latency rides "latency", campaign recovery rides
                  # "recovery_s").
                  "availability")
_LOWER_BETTER = ("ttft", "latency", "_ms", "_wall_s", "overhead",
                 "_seconds", "tick_s", "step_s", "copy_us",
                 # Time the brownout ladder spent engaged (r16): a
                 # same-config record whose fleet browns out longer
                 # regressed its overload posture.
                 "rung_time",
                 # Router WAL crash recovery wall time (r19): MTTR for
                 # the control plane — a same-config record whose
                 # recovery got slower regressed the durability story.
                 "recovery_s",
                 # Prefill tokens the fleet spent on prefixes a sibling
                 # replica already held (r18): the number the chain
                 # pull exists to eliminate.
                 "duplicate_prefill",
                 # Decode-side p99 token latency under long-prompt
                 # bursts (r20): the interference disaggregation
                 # exists to remove — lower means prefill stopped
                 # stealing decode ticks.
                 "interference",
                 # Hot-standby detection+promotion wall time (r23):
                 # the HA headline riding next to recovery_s — a
                 # same-config record whose failover got slower
                 # regressed the whole point of keeping a standby.
                 "failover_s")
_NEVER = ("spread", "samples", "per_pair", "per_repeat", "n_requests",
          "count", "injected", "provenance", "seed", "offered",
          # The r18 tier curve's sweep axis (working_set_x is a
          # multiple of the pool size, not a measurement) — its _x
          # suffix only LOOKS like a ratio headline.
          "working_set")


def metric_direction(key: str) -> int:
    """+1 higher-is-better, -1 lower-is-better, 0 not comparable."""
    k = key.lower()
    if any(m in k for m in _NEVER):
        return 0
    for m in _HIGHER_BETTER:
        if m in k:
            return 1
    for m in _LOWER_BETTER:
        if m in k:
            return -1
    return 0


def load_artifact(path: str) -> List[Dict[str, object]]:
    """Records from an artifact file: whole-file JSON (single record,
    possibly pretty-printed) or JSONL (one record per line)."""
    with open(path) as f:
        text = f.read()
    try:
        doc = json.loads(text)
        return doc if isinstance(doc, list) else [doc]
    except json.JSONDecodeError:
        return [json.loads(line) for line in text.splitlines()
                if line.strip()]


def artifact_key(record: Dict[str, object]) -> Optional[Tuple[str, str]]:
    """The comparability key: ``(metric, canonical-config-json)``.
    Records only compare when BOTH match — a different model or slot
    count is a different experiment, not a regression. Records without
    a ``metric`` field predate the discipline and are skipped."""
    metric = record.get("metric")
    if not isinstance(metric, str):
        return None
    return metric, json.dumps(record.get("config", {}), sort_keys=True)


def _numeric_leaves(node, path: str = "") -> Dict[str, float]:
    out: Dict[str, float] = {}
    if isinstance(node, dict):
        for k, v in node.items():
            out.update(_numeric_leaves(v, f"{path}.{k}" if path else str(k)))
    elif isinstance(node, list):
        # Lists of sub-records (the fleet artifact's per-N scaling and
        # killed legs) are headline-bearing; key items by a semantic
        # field when one exists so a series that grows an N still pairs
        # the shared entries, else by index.
        for i, v in enumerate(node):
            tag = (f"[replicas={v['replicas']}]"
                   if isinstance(v, dict) and "replicas" in v else f"[{i}]")
            out.update(_numeric_leaves(v, path + tag))
    elif isinstance(node, bool):
        pass
    elif isinstance(node, (int, float)):
        out[path] = float(node)
    return out


def compare(old: Dict[str, object], new: Dict[str, object], *,
            threshold_pct: float = 5.0) -> List[Dict[str, object]]:
    """Regressions of ``new`` vs ``old`` (same artifact_key required):
    every shared numeric leaf under ``results`` (plus top-level
    scalars) whose directional move exceeds ``threshold_pct`` of the
    old value. Returns ``[]`` when nothing regressed; raises if the
    records are not comparable at all."""
    ko, kn = artifact_key(old), artifact_key(new)
    if ko is None or kn is None or ko != kn:
        raise ValueError(
            f"records are not comparable: {ko} vs {kn} — the gate "
            "compares identical (metric, config) only")
    leaves_old = _numeric_leaves(old.get("results", {}), "results")
    leaves_new = _numeric_leaves(new.get("results", {}), "results")
    for rec, leaves in ((old, leaves_old), (new, leaves_new)):
        for k, v in rec.items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                leaves[k] = float(v)
    regressions: List[Dict[str, object]] = []
    for path in sorted(set(leaves_old) & set(leaves_new)):
        direction = metric_direction(path)
        if direction == 0:
            continue
        a, b = leaves_old[path], leaves_new[path]
        if a == 0.0:
            continue
        change_pct = 100.0 * (b - a) / abs(a)
        if -direction * change_pct > threshold_pct:
            regressions.append({
                "path": path, "old": a, "new": b,
                "change_pct": round(change_pct, 2),
                "direction": "higher-better" if direction > 0
                             else "lower-better",
            })
    # A directional leaf that DISAPPEARS is the quietest regression of
    # all — rename results.tokens_per_s and the intersection above never
    # sees it again. Growing new legs is fine (old side lacks them);
    # dropping a headline the old record measured is not.
    for path in sorted(set(leaves_old) - set(leaves_new)):
        if metric_direction(path) == 0:
            continue
        regressions.append({
            "path": path, "old": leaves_old[path], "new": None,
            "change_pct": None, "direction": "missing-in-new",
        })
    return regressions


_R_PREFIX = re.compile(r"^r(\d+)")


def check_series(paths: List[str], *, threshold_pct: float = 5.0):
    """The series gate: group every record in ``paths`` by
    :func:`artifact_key`, order each group by its ``rNN`` filename
    round (then filename), and :func:`compare` each consecutive pair.
    Returns ``(pairs_checked, failures)`` where each failure is
    ``{key, old_path, new_path, regressions}`` — the caller (the
    ``bench_gate`` pytest marker, or the CLI) fails loudly on any."""
    def round_of(path: str) -> int:
        m = _R_PREFIX.match(os.path.basename(path))
        return int(m.group(1)) if m else -1

    groups: Dict[Tuple[str, str], List[Tuple[int, str, Dict]]] = {}
    for path in paths:
        try:
            records = load_artifact(path)
        except (json.JSONDecodeError, OSError):
            continue  # not an artifact record file (txt probes etc.)
        for record in records:
            key = artifact_key(record)
            if key is None:
                continue
            groups.setdefault(key, []).append((round_of(path), path,
                                               record))
    pairs_checked, failures = 0, []
    for key, members in sorted(groups.items()):
        members.sort(key=lambda m: (m[0], m[1]))
        for (_, old_path, old), (_, new_path, new) in zip(members,
                                                          members[1:]):
            pairs_checked += 1
            regressions = compare(old, new, threshold_pct=threshold_pct)
            if regressions:
                failures.append({"key": key, "old_path": old_path,
                                 "new_path": new_path,
                                 "regressions": regressions})
    return pairs_checked, failures


def _main(argv: Optional[List[str]] = None) -> int:
    """CLI: ``python -m pddl_tpu.utils.bench_artifact compare OLD NEW``
    or ``... gate DIR`` (every r*.json under DIR). Exit 1 = regression."""
    import argparse
    import glob
    import sys

    p = argparse.ArgumentParser(prog="bench_artifact")
    sub = p.add_subparsers(dest="cmd", required=True)
    pc = sub.add_parser("compare", help="diff two artifacts at one config")
    pc.add_argument("old")
    pc.add_argument("new")
    pg = sub.add_parser("gate", help="gate the committed r*.json series")
    pg.add_argument("directory")
    for sp in (pc, pg):
        sp.add_argument("--threshold-pct", type=float, default=5.0)
    args = p.parse_args(argv)
    if args.cmd == "compare":
        old = load_artifact(args.old)[0]
        regressions = compare(old, load_artifact(args.new)[0],
                              threshold_pct=args.threshold_pct)
        pairs, failures = 1, ([{"key": artifact_key(old),
                                "old_path": args.old,
                                "new_path": args.new,
                                "regressions": regressions}]
                              if regressions else [])
    else:
        paths = sorted(glob.glob(os.path.join(args.directory, "r*.json")))
        pairs, failures = check_series(paths,
                                       threshold_pct=args.threshold_pct)
    print(f"bench gate: {pairs} comparable pair(s) checked, "
          f"{len(failures)} with regressions > {args.threshold_pct}%",
          file=sys.stderr)
    for failure in failures:
        print(f"REGRESSION {failure['old_path']} -> "
              f"{failure['new_path']} ({failure['key'][0]}):",
              file=sys.stderr)
        for r in failure["regressions"]:
            change = ("leaf vanished" if r["change_pct"] is None
                      else f"{r['change_pct']:+.1f}%")
            print(f"  {r['path']}: {r['old']} -> {r['new']} "
                  f"({change}, {r['direction']})",
                  file=sys.stderr)
    return 1 if failures else 0


if __name__ == "__main__":
    raise SystemExit(_main())
