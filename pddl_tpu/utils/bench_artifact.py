"""Bench-artifact provenance and repeat-timing discipline.

Round 5's verdict found the committed serving docs and artifacts
disagreeing (2.02x in prose vs 1.505x in the final-tree JSON; two r5
artifacts 26% apart on an identical config) because numbers were
measured on MIXED TREES with single-shot timings. This module is the
fix, shared by every serving bench (`serve_bench.py`,
`decode_bench.py`, `specdecode_bench.py`):

- :func:`provenance` stamps ``{git_commit, dirty, n_repeats}`` into the
  record, so any artifact can be traced to the exact tree it measured
  (and a dirty tree is visible, not hidden).
- :func:`timed_stats` runs ``n_repeats >= 3`` timed repetitions and
  returns ``{median, spread_pct, samples}`` — the median is the
  headline, the spread is the drift detector (a >5% spread means the
  number is weather, not signal, and the docs must say so).

Keep the repo's sync discipline: the ``sync`` callable must FETCH A
VALUE from the result (``int(out[0, -1])``-style), because
``block_until_ready`` is not a reliable barrier on tunneled transports
(ARCHITECTURE.md §7e, round-5 re-measurement note).
"""

from __future__ import annotations

import statistics
import subprocess
import time
from typing import Callable, Dict, List


def git_commit() -> Dict[str, object]:
    """``{commit, dirty}`` of the working tree, or ``unknown`` outside
    a repo — never raises (benches must run anywhere)."""
    try:
        commit = subprocess.run(
            ["git", "rev-parse", "HEAD"], capture_output=True, text=True,
            check=True, timeout=10).stdout.strip()
        dirty = bool(subprocess.run(
            ["git", "status", "--porcelain"], capture_output=True,
            text=True, check=True, timeout=10).stdout.strip())
        return {"commit": commit, "dirty": dirty}
    except Exception:  # noqa: BLE001 - no git, not a repo, timeout: all fine
        return {"commit": "unknown", "dirty": None}


def provenance(n_repeats: int) -> Dict[str, object]:
    """The artifact-level provenance block every serving bench embeds
    as ``record["provenance"]``."""
    g = git_commit()
    return {
        "git_commit": g["commit"],
        "git_dirty": g["dirty"],
        "n_repeats": int(n_repeats),
        "timing": "median over n_repeats; spread_pct = "
                  "100*(max-min)/median",
    }


def median_spread(samples: List[float]) -> tuple:
    """``(median, spread_pct)`` of a sample list — ONE definition of
    both statistics (``statistics.median``, even-length averaging), so
    no bench can drift to a different convention. Requires >= 3
    samples: a single sample cannot expose drift."""
    if len(samples) < 3:
        raise ValueError(
            f"need >= 3 samples for a meaningful spread, got "
            f"{len(samples)}")
    med = statistics.median(samples)
    return med, 100.0 * (max(samples) - min(samples)) / med


def timed_stats(fn: Callable, sync: Callable, *,
                n_repeats: int = 3) -> Dict[str, object]:
    """Median/spread wall-clock of ``sync(fn())`` over ``n_repeats``
    repetitions (>= 3 enforced via :func:`median_spread`). The caller
    warms compilation before the first call."""
    samples: List[float] = []
    for _ in range(max(n_repeats, 0)):
        t0 = time.perf_counter()
        sync(fn())
        samples.append(time.perf_counter() - t0)
    med, spread = median_spread(samples)
    return {
        "median_s": med,
        "spread_pct": spread,
        "samples_s": [round(s, 6) for s in samples],
    }
