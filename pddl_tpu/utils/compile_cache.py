"""Shared persistent XLA compile cache configuration.

The CPU fake-mesh world (SURVEY.md §4's testing recipe) spends most of its
wall-clock in XLA:CPU compiles of sharded train steps. Both the test suite
(``tests/conftest.py``) and the driver's multichip gate
(``__graft_entry__.dryrun_multichip``) persist those compiles to one shared
on-disk cache so either one warms the other.
"""

from __future__ import annotations

import os

DEFAULT_CACHE_DIR = os.path.join("/tmp", "pddl_tpu_xla_cache")
CACHE_DIR_ENV = "PDDL_TEST_COMPILE_CACHE"


def enable_persistent_compile_cache() -> str:
    """Point jax at the shared on-disk compile cache; return the cache dir.

    Honors the ``PDDL_TEST_COMPILE_CACHE`` env override. Safe to call before
    or after backend initialization (the config only affects future compiles).
    """
    import jax

    cache_dir = os.environ.get(CACHE_DIR_ENV, DEFAULT_CACHE_DIR)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    return cache_dir
