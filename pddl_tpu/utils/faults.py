"""Seeded deterministic fault injection — the shared core.

The ROADMAP north star is a system that "handles as many scenarios as
you can imagine"; at production scale device faults are ROUTINE, not
exceptional — a transient ``XlaRuntimeError`` from a flaky
interconnect, a ``RESOURCE_EXHAUSTED`` under HBM pressure, a latency
spike from a neighbor, a SIGKILL from the scheduler. You cannot trust
a recovery path you cannot exercise, so faults here are INJECTABLE and
SEEDED: a :class:`FaultPlan` hooks every guarded device-call boundary
of a host loop (the serving engine's ``_device_call``, the Trainer's
``_device_call``) and fires transient errors, allocation failures,
latency spikes, or hard kill-points at chosen or randomly drawn
``(step, site)`` coordinates. Reproducible by construction: the same
seed against the same workload injects the same faults, so every
recovery path is testable in tier-1 on CPU.

This module is the machinery only — the SITE VOCABULARY is owned by
each subsystem: :class:`pddl_tpu.serve.faults.FaultPlan` pins the
serving engine's ``compile_counts()`` keys,
:class:`pddl_tpu.train.faults.TrainFaultPlan` the Trainer's compiled
program names. Both are thin subclasses overriding :attr:`FaultPlan.
SITES`; everything else (scheduling, rate draws, classification, the
injection-before-dispatch discipline) is identical, which is the point:
one fault taxonomy, one recovery contract, serving AND training.

Fault taxonomy and the caller's contract for each:

- **TRANSIENT** (raises :class:`InjectedTransientError`, the stand-in
  for an ``INTERNAL``/``UNAVAILABLE`` ``XlaRuntimeError``): the call is
  retried with bounded exponential backoff; past ``max_retries`` the
  affected device state is declared lost and the subsystem's replay
  path runs (serving: token-exact request replay; training: restore
  the last verified checkpoint and replay forward).
- **OOM** (raises :class:`InjectedResourceExhausted`, the stand-in for
  ``RESOURCE_EXHAUSTED``): never blind-retried — memory must be shed
  (serving: degraded mode) or the state rebuilt (training: restore)
  before the allocation can pass.
- **LATENCY**: the call is delayed (``sleep_fn``), nothing raises — the
  tail-latency fault; deadlines, drains, and checkpoints must keep
  working under it.
- **KILL** (raises :class:`KillPoint`, a ``BaseException``): simulates
  abrupt termination mid-step. Nothing catches it — it unwinds like a
  real SIGKILL, and the test then exercises restart/restore on what
  the process left on disk.

Injection happens BEFORE the wrapped program dispatches, so device
buffers (including donated ones) are never left half-consumed by an
injected fault — which is what makes retry sound. Real device errors
from a donated program must escalate straight to the rebuild path
instead (see ``serve/engine._device_call``, ``train/loop.Trainer``).
"""

from __future__ import annotations

import dataclasses
import enum
import errno
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np


class FaultKind(enum.Enum):
    TRANSIENT = "transient"  # retryable device error
    OOM = "oom"              # RESOURCE_EXHAUSTED: shed/rebuild, don't retry
    LATENCY = "latency"      # slow call, nothing raised
    KILL = "kill"            # hard termination mid-step (BaseException)


class InjectedTransientError(RuntimeError):
    """Stand-in for a retryable ``XlaRuntimeError`` (INTERNAL /
    UNAVAILABLE / ABORTED): the device call failed but nothing about
    the caller's resident state is invalidated."""


class InjectedResourceExhausted(RuntimeError):
    """Stand-in for ``RESOURCE_EXHAUSTED``: an allocation failed —
    retrying the same call without shedding memory is pointless."""


class KillPoint(BaseException):
    """Simulated hard kill at a (step, site) coordinate. A
    ``BaseException`` so no retry/except-Exception path can swallow it:
    it unwinds through the host loop exactly like a real SIGKILL would
    end the process mid-dispatch."""

    def __init__(self, site: str, step: int):
        self.site = site
        self.step = step
        super().__init__(f"injected kill-point at step {step}, site {site!r}")


# What a fault-aware caller may see from jax itself. Classification is
# by status-code marker in the message (jaxlib's XlaRuntimeError carries
# the absl status string); anything unrecognized is NOT swallowed.
_TRANSIENT_MARKERS = ("INTERNAL", "UNAVAILABLE", "ABORTED", "DATA_LOSS",
                      "DEADLINE_EXCEEDED")


def classify(err: BaseException) -> Optional[str]:
    """``"transient"`` / ``"oom"`` / ``None`` (not a device fault — let
    it propagate: a shape error or a bug must stay loud)."""
    if isinstance(err, InjectedResourceExhausted):
        return "oom"
    if isinstance(err, InjectedTransientError):
        return "transient"
    if type(err).__name__ == "XlaRuntimeError":
        msg = str(err)
        if "RESOURCE_EXHAUSTED" in msg:
            return "oom"
        if any(m in msg for m in _TRANSIENT_MARKERS):
            return "transient"
    return None


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    """One scheduled fault: fire ``kind`` on the next ``count``
    invocations of ``site`` during host-loop step ``step``. ``count``
    matters for TRANSIENT — ``count <= max_retries`` recovers inside
    the retry loop, ``count > max_retries`` forces the replay path."""

    step: int
    site: str
    kind: FaultKind
    count: int = 1


class FaultPlan:
    """Seeded fault schedule over a host loop's device-call sites.

    Two layers, both deterministic:

    - ``scheduled``: explicit :class:`FaultSpec` coordinates — the
      surgical tool (kill exactly at step 3's tick; fail the donate of
      step 1 twice).
    - rates: per-check Bernoulli draws from one ``np.random.default_rng
      (seed)`` stream — the chaos tool. Given the same workload the
      call sequence is identical, so the same seed injects the same
      faults at the same coordinates.

    Subclasses pin :attr:`SITES` to their subsystem's site vocabulary
    (serving: the engine's ``compile_counts()`` keys; training: the
    Trainer's compiled program names); construction validates every
    site against it so a typo'd coordinate cannot silently never fire.

    Args:
      seed: the PRNG seed (reproducibility handle).
      transient_rate / oom_rate / latency_rate: per-call probabilities
        (must sum to <= 1).
      latency_s: injected delay per LATENCY fault.
      sites: optional allowlist — random faults only fire at these
        sites (scheduled specs are never filtered).
      scheduled: :class:`FaultSpec` sequence.
      max_random_injections: cap on rate-drawn faults (keeps a chaos
        run terminating even at silly rates); ``None`` = unbounded.
      sleep_fn: how LATENCY waits (tests pass a fake-clock advancer).
    """

    SITES: Tuple[str, ...] = ()

    def __init__(self, seed: int = 0, *, transient_rate: float = 0.0,
                 oom_rate: float = 0.0, latency_rate: float = 0.0,
                 latency_s: float = 0.005,
                 sites: Optional[Sequence[str]] = None,
                 scheduled: Sequence[FaultSpec] = (),
                 max_random_injections: Optional[int] = None,
                 sleep_fn=time.sleep):
        for name, rate in (("transient_rate", transient_rate),
                           ("oom_rate", oom_rate),
                           ("latency_rate", latency_rate)):
            if not 0.0 <= rate <= 1.0:
                raise ValueError(f"{name} must be in [0, 1], got {rate}")
        if transient_rate + oom_rate + latency_rate > 1.0:
            raise ValueError("fault rates must sum to <= 1")
        if sites is not None:
            unknown = set(sites) - set(self.SITES)
            if unknown:
                raise ValueError(
                    f"unknown fault site(s) {sorted(unknown)}; valid "
                    f"sites are {self.SITES}")
        for spec in scheduled:
            if spec.site not in self.SITES:
                raise ValueError(
                    f"unknown scheduled site {spec.site!r}; valid sites "
                    f"are {self.SITES}")
            if spec.count < 1:
                raise ValueError(f"FaultSpec.count must be >= 1: {spec}")
        self.seed = int(seed)
        self._rng = np.random.default_rng(seed)
        self._rates = (float(transient_rate), float(oom_rate),
                       float(latency_rate))
        self.latency_s = float(latency_s)
        self._sites = frozenset(sites) if sites is not None else None
        self._sched: Dict[Tuple[int, str], List[FaultKind]] = {}
        for spec in scheduled:
            self._sched.setdefault((spec.step, spec.site), []).extend(
                [spec.kind] * spec.count)
        self._max_random = max_random_injections
        self._random_fired = 0
        self._sleep = sleep_fn
        self.step_idx = -1  # the host loop stamps this at the top of a step
        # Telemetry for tests/benches: injections per kind.
        self.injected: Dict[FaultKind, int] = {k: 0 for k in FaultKind}
        # Injection observer (``fn(step, site, kind_value)``), wired by
        # the host loop's tracer plumbing so every injection — LATENCY
        # included, which raises nothing — lands in the trace with the
        # exact (step, site) coordinate it fired at.
        self.on_inject = None

    @property
    def total_injected(self) -> int:
        return sum(self.injected.values())

    def on_step(self, step_idx: int) -> None:
        """Host-loop hook: the current step coordinate for scheduled
        specs (retries within a step re-check the same coordinate,
        which is how ``FaultSpec.count`` consumes consecutive
        invocations)."""
        self.step_idx = int(step_idx)

    def check(self, site: str) -> None:
        """Called by the host loop immediately before dispatching
        ``site``. Raises / sleeps per the schedule; returns normally
        otherwise."""
        key = (self.step_idx, site)
        pending = self._sched.get(key)
        if pending:
            kind = pending.pop(0)
            if not pending:
                del self._sched[key]
            self._fire(kind, site)
            return
        t, o, lat = self._rates
        if t + o + lat <= 0.0:
            return
        if self._sites is not None and site not in self._sites:
            return
        if (self._max_random is not None
                and self._random_fired >= self._max_random):
            return
        u = self._rng.random()
        if u < t:
            kind = FaultKind.TRANSIENT
        elif u < t + o:
            kind = FaultKind.OOM
        elif u < t + o + lat:
            kind = FaultKind.LATENCY
        else:
            return
        self._random_fired += 1
        self._fire(kind, site)

    def _fire(self, kind: FaultKind, site: str) -> None:
        self.injected[kind] += 1
        if self.on_inject is not None:
            self.on_inject(self.step_idx, site, kind.value)
        where = f"at step {self.step_idx}, site {site!r}"
        if kind is FaultKind.TRANSIENT:
            raise InjectedTransientError(
                f"INTERNAL: injected transient device error {where}")
        if kind is FaultKind.OOM:
            raise InjectedResourceExhausted(
                f"RESOURCE_EXHAUSTED: injected allocation failure {where}")
        if kind is FaultKind.KILL:
            raise KillPoint(site, self.step_idx)
        self._sleep(self.latency_s)  # LATENCY: slow, not broken


class StorageFaultKind(enum.Enum):
    EIO = "eio"          # transient-or-persistent I/O error (``errno.EIO``)
    ENOSPC = "enospc"    # disk full (``errno.ENOSPC``): reclaim, don't retry
    TORN = "torn"        # write persists a prefix, then fails (power-cut model)
    SLOW = "slow"        # slow fsync/write — the gray disk; nothing raised


@dataclasses.dataclass(frozen=True)
class StorageFaultSpec:
    """One scheduled storage fault: fire ``kind`` on the next ``count``
    invocations of file operation ``op``, starting at the ``seq``-th
    call of that op (a per-op invocation counter, 0-based — the storage
    analog of :class:`FaultSpec`'s ``(step, site)`` coordinate, because
    a journal has no step clock of its own)."""

    op: str
    seq: int
    kind: StorageFaultKind
    count: int = 1


class StorageFaultPlan:
    """Seeded fault schedule over a journal's file-operation sites.

    The storage sibling of :class:`FaultPlan`: same two deterministic
    layers (explicit :class:`StorageFaultSpec` coordinates + per-call
    Bernoulli rate draws from one seeded stream), but coordinates are
    ``(op, seq)`` — the op name and its per-op invocation index —
    because file ops have no host-loop step to hang a schedule on.

    The consumer is a VFS shim (``journal._JournalVFS``) that calls
    :meth:`check` immediately BEFORE each real ``os`` call:

    - **EIO** raises ``OSError(errno.EIO)`` before the op runs — the
      retryable class; persistent storms drive the journal into its
      NON_DURABLE degraded mode.
    - **ENOSPC** raises ``OSError(errno.ENOSPC)`` — not retried; the
      journal's contract is to reclaim space (emergency checkpoint +
      rotate) before writing again.
    - **TORN** is *returned* to the shim rather than raised: only the
      write path can model it (persist a prefix of the buffer, then
      raise EIO), which is exactly the torn-tail shape
      ``_readable_prefix_len`` truncates at recovery.
    - **SLOW** sleeps ``slow_s`` and returns — the gray disk; fsync
      deadlines and tick cadence must survive it.

    :meth:`quiesce` clears rates and pending schedule in place — how a
    test "repairs the disk" so re-arm probes can restore durability.
    """

    SITES: Tuple[str, ...] = ("open", "write", "fsync", "replace", "fstat")

    def __init__(self, seed: int = 0, *, eio_rate: float = 0.0,
                 enospc_rate: float = 0.0, torn_rate: float = 0.0,
                 slow_rate: float = 0.0, slow_s: float = 0.005,
                 ops: Optional[Sequence[str]] = None,
                 scheduled: Sequence[StorageFaultSpec] = (),
                 max_random_injections: Optional[int] = None,
                 sleep_fn=time.sleep):
        for name, rate in (("eio_rate", eio_rate),
                           ("enospc_rate", enospc_rate),
                           ("torn_rate", torn_rate),
                           ("slow_rate", slow_rate)):
            if not 0.0 <= rate <= 1.0:
                raise ValueError(f"{name} must be in [0, 1], got {rate}")
        if eio_rate + enospc_rate + torn_rate + slow_rate > 1.0:
            raise ValueError("storage fault rates must sum to <= 1")
        if ops is not None:
            unknown = set(ops) - set(self.SITES)
            if unknown:
                raise ValueError(
                    f"unknown storage op(s) {sorted(unknown)}; valid ops "
                    f"are {self.SITES}")
        for spec in scheduled:
            if spec.op not in self.SITES:
                raise ValueError(
                    f"unknown scheduled op {spec.op!r}; valid ops are "
                    f"{self.SITES}")
            if spec.seq < 0:
                raise ValueError(f"StorageFaultSpec.seq must be >= 0: {spec}")
            if spec.count < 1:
                raise ValueError(
                    f"StorageFaultSpec.count must be >= 1: {spec}")
        self.seed = int(seed)
        self._rng = np.random.default_rng(seed)
        self._rates = (float(eio_rate), float(enospc_rate),
                       float(torn_rate), float(slow_rate))
        self.slow_s = float(slow_s)
        self._ops = frozenset(ops) if ops is not None else None
        self._sched: Dict[Tuple[str, int], List[StorageFaultKind]] = {}
        for spec in scheduled:
            for i in range(spec.count):
                self._sched.setdefault((spec.op, spec.seq + i), []).append(
                    spec.kind)
        self._max_random = max_random_injections
        self._random_fired = 0
        self._sleep = sleep_fn
        # Per-op invocation counters: the ``seq`` axis of the schedule.
        self.calls: Dict[str, int] = {op: 0 for op in self.SITES}
        self.injected: Dict[StorageFaultKind, int] = {
            k: 0 for k in StorageFaultKind}
        # Observer ``fn(seq, op, kind_value)``, mirroring FaultPlan's
        # ``on_inject`` so injections land in traces with coordinates.
        self.on_inject = None

    @property
    def total_injected(self) -> int:
        return sum(self.injected.values())

    def quiesce(self) -> None:
        """Repair the disk: clear rates and any pending schedule so
        every later :meth:`check` passes (re-arm probes succeed)."""
        self._rates = (0.0, 0.0, 0.0, 0.0)
        self._sched.clear()

    def check(self, op: str) -> Optional[StorageFaultKind]:
        """Called by the VFS shim immediately before the real ``os``
        op. Raises ``OSError`` (EIO/ENOSPC), sleeps (SLOW), or returns
        :data:`StorageFaultKind.TORN` for the shim to half-write;
        returns ``None`` when the op should proceed untouched."""
        if op not in self.calls:
            raise ValueError(
                f"unknown storage op {op!r}; valid ops are {self.SITES}")
        seq = self.calls[op]
        self.calls[op] = seq + 1
        pending = self._sched.get((op, seq))
        if pending:
            kind = pending.pop(0)
            if not pending:
                del self._sched[(op, seq)]
            return self._fire(kind, op, seq)
        e, n, t, s = self._rates
        if e + n + t + s <= 0.0:
            return None
        if self._ops is not None and op not in self._ops:
            return None
        if (self._max_random is not None
                and self._random_fired >= self._max_random):
            return None
        u = self._rng.random()
        if u < e:
            kind = StorageFaultKind.EIO
        elif u < e + n:
            kind = StorageFaultKind.ENOSPC
        elif u < e + n + t:
            kind = StorageFaultKind.TORN
        elif u < e + n + t + s:
            kind = StorageFaultKind.SLOW
        else:
            return None
        self._random_fired += 1
        return self._fire(kind, op, seq)

    def _fire(self, kind: StorageFaultKind, op: str,
              seq: int) -> Optional[StorageFaultKind]:
        self.injected[kind] += 1
        if self.on_inject is not None:
            self.on_inject(seq, op, kind.value)
        where = f"at op {op!r} seq {seq}"
        if kind is StorageFaultKind.EIO:
            raise OSError(errno.EIO, f"injected I/O error {where}")
        if kind is StorageFaultKind.ENOSPC:
            raise OSError(errno.ENOSPC,
                          f"injected no-space-on-device {where}")
        if kind is StorageFaultKind.TORN:
            return kind  # the write path half-writes, then raises EIO
        self._sleep(self.slow_s)  # SLOW: gray disk, not a broken one
        return None
