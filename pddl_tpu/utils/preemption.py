"""Preemption handling: checkpoint-on-SIGTERM, the failure-detection layer.

The reference has almost nothing here (SURVEY.md §5 "Failure detection":
its only crumbs are ``GRPC_FAIL_FAST`` — ``/root/reference/
imagenet-resnet50-ps.py:67-69`` — and the Horovod re-broadcast comment).
On Cloud TPU the real-world failure mode is *preemption*: the VM gets a
SIGTERM with a grace window. This callback turns that signal into a clean
save + stop, pairing with :class:`pddl_tpu.ckpt.BackupAndRestore` /
``--resume`` for end-to-end crash-resume:

    trainer.fit(..., callbacks=[PreemptionCheckpoint("/ckpt/run1")])

The handler only sets a flag (async-signal-safe); the actual save happens
at the next batch boundary on the training thread, so the checkpoint is a
consistent TrainState, not a torn mid-step capture.
"""

from __future__ import annotations

import logging
import signal

from pddl_tpu.train.callbacks import Callback

log = logging.getLogger(__name__)


class PreemptionCheckpoint(Callback):
    """Save a checkpoint and stop training cleanly when preempted.

    Args:
      directory: checkpoint directory (shared with ``BackupAndRestore`` /
        ``--resume`` so the restarted job continues from the save).
      signals: which signals mean "about to be killed" (default SIGTERM —
        what Cloud TPU / GCE / Slurm send before eviction).
      restore_previous_handlers: put the old handlers back at train end.
    """

    def __init__(self, directory: str, signals=(signal.SIGTERM,),
                 restore_previous_handlers: bool = True):
        self.directory = directory
        self.signals = tuple(signals)
        self.restore_previous_handlers = restore_previous_handlers
        self.preempted = False
        self._previous: dict = {}
        self._ckpt = None
        self._epoch = 0

    # -- signal plumbing ----------------------------------------------------
    def _on_signal(self, signum, frame):  # async-signal-safe: flag only
        self.preempted = True

    def on_train_begin(self, state):
        from pddl_tpu.ckpt.checkpoint import Checkpointer

        # Fresh run: a reused callback instance (in-process resume/retry)
        # must not inherit the previous run's preempted flag.
        self.preempted = False
        # Sync saves: during a grace window there may be no "later" to
        # finish an async save in.
        self._ckpt = Checkpointer(self.directory, max_to_keep=2,
                                  async_save=False)
        for sig in self.signals:
            self._previous[sig] = signal.signal(sig, self._on_signal)
        return None

    def on_epoch_begin(self, epoch, state):
        self._epoch = epoch
        return None

    # -- checkpoint at the next safe point ---------------------------------
    def on_train_batch_end(self, step, state, logs):
        if not self.preempted or self.trainer.stop_training:
            return None
        log.warning("preemption signal received: checkpointing to %s and "
                    "stopping", self.directory)
        # epoch-1: the interrupted epoch is incomplete, so --resume's
        # initial_epoch = saved+1 restarts exactly it.
        self._ckpt.save(state, epoch=self._epoch - 1, metrics=None,
                        force=True)
        self._ckpt.wait()
        self.trainer.stop_training = True
        return None

    def on_train_end(self, state, logs):
        if self.restore_previous_handlers:
            for sig, old in self._previous.items():
                signal.signal(sig, old)
        if self._ckpt is not None:
            self._ckpt.close()
            self._ckpt = None
        return None
