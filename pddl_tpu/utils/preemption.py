"""Preemption handling: checkpoint-on-SIGTERM, the failure-detection layer.

The reference has almost nothing here (SURVEY.md §5 "Failure detection":
its only crumbs are ``GRPC_FAIL_FAST`` — ``/root/reference/
imagenet-resnet50-ps.py:67-69`` — and the Horovod re-broadcast comment).
On Cloud TPU the real-world failure mode is *preemption*: the VM gets a
SIGTERM with a grace window. This callback turns that signal into a clean
save + stop, pairing with :class:`pddl_tpu.ckpt.BackupAndRestore` /
``--resume`` for end-to-end crash-resume:

    trainer.fit(..., callbacks=[PreemptionCheckpoint("/ckpt/run1")])

The handler only sets a flag (async-signal-safe); the actual save happens
at the next batch boundary on the training thread, so the checkpoint is a
consistent TrainState, not a torn mid-step capture.

The grace-window save is STEP-granular: it records the Trainer's loader
position (epoch, step offset within it, batches consumed) and per-leaf
checksums alongside the state, so the restarted job resumes exactly the
interrupted step via ``Trainer.fit(resume=...)`` / ``--resume`` — not a
replay of the whole epoch — and a save torn by the eviction itself is
detected and skipped on restore (`pddl_tpu/ckpt/checkpoint.py`).
"""

from __future__ import annotations

import logging
import signal
from typing import Optional

from pddl_tpu.train.callbacks import Callback

log = logging.getLogger(__name__)


class PreemptionCheckpoint(Callback):
    """Save a checkpoint and stop training cleanly when preempted.

    Args:
      directory: checkpoint directory (shared with ``--resume`` /
        ``Trainer.fit(resume=...)`` so the restarted job continues from
        the save). Ignored when ``delegate`` is given.
      signals: which signals mean "about to be killed" (default SIGTERM —
        what Cloud TPU / GCE / Slurm send before eviction).
      restore_previous_handlers: put the old handlers back at train end.
      delegate: an already-installed checkpoint callback exposing
        ``save_now(state)`` + ``.ckpt`` (``CheckpointEveryN`` or
        ``ModelCheckpoint``) to save through instead of opening a
        second manager. Two WRITING
        ``CheckpointManager``s on one directory race each other's
        retention GC and can collide on the same step number (a SIGTERM
        landing on a save-cadence batch would double-save) — delegating
        keeps ONE writer per directory.
    """

    def __init__(self, directory: Optional[str] = None,
                 signals=(signal.SIGTERM,),
                 restore_previous_handlers: bool = True,
                 delegate=None):
        if (directory is None) == (delegate is None):
            raise ValueError(
                "pass exactly one of directory (own manager) or "
                "delegate (a CheckpointEveryN to save through)")
        self.directory = directory
        self.delegate = delegate
        self.signals = tuple(signals)
        self.restore_previous_handlers = restore_previous_handlers
        self.preempted = False
        self._previous: dict = {}
        self._ckpt = None
        self._epoch = 0

    # -- signal plumbing ----------------------------------------------------
    def _on_signal(self, signum, frame):  # async-signal-safe: flag only
        self.preempted = True

    def on_train_begin(self, state):
        # Fresh run: a reused callback instance (in-process resume/retry)
        # must not inherit the previous run's preempted flag.
        self.preempted = False
        if self.delegate is None:
            from pddl_tpu.ckpt.checkpoint import Checkpointer

            # Sync saves: during a grace window there may be no "later"
            # to finish an async save in.
            self._ckpt = Checkpointer(self.directory, max_to_keep=2,
                                      async_save=False)
        for sig in self.signals:
            self._previous[sig] = signal.signal(sig, self._on_signal)
        return None

    def on_epoch_begin(self, epoch, state):
        self._epoch = epoch
        return None

    # -- checkpoint at the next safe point ---------------------------------
    def on_train_batch_end(self, step, state, logs):
        if not self.preempted or self.trainer.stop_training:
            return None
        log.warning("preemption signal received: checkpointing to %s and "
                    "stopping",
                    self.directory if self.delegate is None
                    else self.delegate.ckpt.directory)
        if self.delegate is not None:
            # One writer per directory: save through the step-granular
            # callback's manager (loader metadata included by save_now)
            # and make sure the write lands inside the grace window.
            self.delegate.save_now(state)
            self.delegate.ckpt.wait()
        else:
            # Step-granular grace save: loader position (epoch, step
            # offset, batches consumed) rides in the metadata so
            # fit(resume=...) continues MID-epoch instead of replaying
            # the whole epoch. epoch-1 stays in the legacy field: the
            # interrupted epoch is incomplete, so a legacy resume's
            # initial_epoch = saved+1 restarts exactly it.
            loader = self.trainer.loader_state()
            epoch = loader["epoch"] - 1 if loader else self._epoch - 1
            self._ckpt.save(state, epoch=epoch, metrics=None, force=True,
                            loader=loader)
            self._ckpt.wait()
        self.trainer.stop_training = True
        return None

    def on_train_end(self, state, logs):
        if self.restore_previous_handlers:
            for sig, old in self._previous.items():
                signal.signal(sig, old)
        if self._ckpt is not None:
            self._ckpt.close()
            self._ckpt = None
        return None
