"""Tracing / profiling: the observability layer the reference lacks.

The reference's entire measurement surface is one wall-clock print around
``model.fit`` on Horovod rank 0 (``/root/reference/imagenet-resnet50-hvd.py:
119-126``). SURVEY.md §5 "Tracing / profiling" calls for the TPU-native
story: ``jax.profiler`` traces (viewable in TensorBoard/XProf, with XLA HLO
and ICI collective timelines), per-step timing, and first-class
images/sec/chip reporting (the BASELINE.json headline metric).
"""

from __future__ import annotations

import contextlib
import statistics
import sys
import time
from typing import Dict, List, Optional

import jax

from pddl_tpu.train.callbacks import Callback


@contextlib.contextmanager
def trace(name: str, step: Optional[int] = None):
    """Annotate a host-side region so it shows up on the trace timeline.

    ``step`` uses :class:`jax.profiler.StepTraceAnnotation`, which lets
    XProf group device activity by training step.
    """
    if step is not None:
        ctx = jax.profiler.StepTraceAnnotation(name, step_num=step)
    else:
        ctx = jax.profiler.TraceAnnotation(name)
    with ctx:
        yield


@contextlib.contextmanager
def capture(logdir: str):
    """Capture a profiler trace for the enclosed region into ``logdir``."""
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


class Profiler(Callback):
    """Capture a ``jax.profiler`` trace for selected steps of an epoch.

    Skips the first ``warmup_steps`` (compilation) and records
    ``num_steps`` steps of epoch ``epoch`` — the standard "profile a steady
    -state window" recipe. Coordinator-only, like all reference logging.
    """

    def __init__(self, logdir: str, epoch: int = 0, start_step: int = 2,
                 num_steps: int = 5):
        self.logdir = logdir
        self.epoch = epoch
        self.start_step = start_step
        self.num_steps = num_steps
        self._active = False
        self._epoch_step = 0
        self._in_epoch = False

    def on_epoch_begin(self, epoch, state):
        self._in_epoch = epoch == self.epoch
        self._epoch_step = 0
        return None

    def on_train_batch_end(self, step, state, logs):
        from pddl_tpu.core import dist

        if not (self._in_epoch and dist.is_coordinator()):
            return None
        if self._epoch_step == self.start_step and not self._active:
            jax.profiler.start_trace(self.logdir)
            self._active = True
        elif self._active and self._epoch_step >= self.start_step + self.num_steps:
            self._stop(state)
        self._epoch_step += 1
        return None

    def _stop(self, state):
        # Block on the last result so device work lands inside the trace.
        jax.tree.leaves(state.params)[0].block_until_ready()
        jax.profiler.stop_trace()
        self._active = False

    def on_epoch_end(self, epoch, state, logs):
        if self._active:
            self._stop(state)
        return None

    def on_train_end(self, state, logs):
        if self._active:
            self._stop(state)
        return None


class StepTimer(Callback):
    """Per-step wall-time stats (mean/p50/p90/p99, compile step
    excluded) and steady-state images/sec/chip — the per-chip number
    the strategies multiply out (BASELINE.json metric).

    :meth:`snapshot` emits the stats in the same flat-dict shape as
    ``ServeMetrics.snapshot()`` (stable keys, ``None`` before data), so
    the training step loop and the serving engine share one Prometheus
    export path (`pddl_tpu/obs/export.py`)."""

    def __init__(self, global_batch_size: Optional[int] = None,
                 skip_steps: int = 1, verbose: int = 1):
        self.global_batch_size = global_batch_size
        self.skip_steps = skip_steps  # first step(s) include compilation
        self.verbose = verbose
        self.step_times: List[float] = []
        self._last: Optional[float] = None
        self._step_in_run = 0

    def on_train_begin(self, state):
        self._last = time.perf_counter()
        return None

    def on_train_batch_end(self, step, state, logs):
        now = time.perf_counter()
        if self._step_in_run >= self.skip_steps:
            self.step_times.append(now - self._last)
        self._last = now
        self._step_in_run += 1
        return None

    @property
    def stats(self) -> Dict[str, float]:
        if not self.step_times:
            return {}
        ts = sorted(self.step_times)
        n = len(ts)
        out = {
            "step_time_mean_s": statistics.fmean(ts),
            "step_time_p50_s": ts[n // 2],
            "step_time_p90_s": ts[min(n - 1, int(0.9 * n))],
            "step_time_p99_s": ts[min(n - 1, int(0.99 * n))],
            "steps_timed": float(n),
        }
        if self.global_batch_size:
            per_sec = self.global_batch_size / out["step_time_mean_s"]
            out["images_per_sec"] = per_sec
            out["images_per_sec_per_chip"] = per_sec / jax.device_count()
        return out

    def snapshot(self) -> Dict[str, Optional[float]]:
        """The export dict (`ServeMetrics.snapshot()` discipline):
        every key always present, ``None`` where nothing was measured
        yet — render with
        ``obs.export.render_prometheus(timer.snapshot(),
        prefix="pddl_train_step")`` or through
        ``obs.export.serve_exposition(..., step_timer=timer)``."""
        stats = self.stats
        return {
            "step_time_mean_s": stats.get("step_time_mean_s"),
            "step_time_p50_s": stats.get("step_time_p50_s"),
            "step_time_p90_s": stats.get("step_time_p90_s"),
            "step_time_p99_s": stats.get("step_time_p99_s"),
            "steps_timed": stats.get("steps_timed", 0.0),
            "images_per_sec": stats.get("images_per_sec"),
            "images_per_sec_per_chip": stats.get("images_per_sec_per_chip"),
        }

    def on_train_end(self, state, logs):
        from pddl_tpu.core import dist

        if self.verbose and dist.is_coordinator() and self.step_times:
            parts = [f"{k}: {v:.4g}" for k, v in self.stats.items()]
            print("StepTimer: " + " - ".join(parts), file=sys.stderr)
        return None


def device_memory_stats() -> Dict[str, Dict[str, int]]:
    """Per-device HBM stats (bytes) where the backend exposes them."""
    out = {}
    for d in jax.local_devices():
        stats = {}
        try:
            stats = d.memory_stats() or {}
        except Exception:
            pass
        out[str(d)] = {
            "bytes_in_use": int(stats.get("bytes_in_use", -1)),
            "peak_bytes_in_use": int(stats.get("peak_bytes_in_use", -1)),
            "bytes_limit": int(stats.get("bytes_limit", -1)),
        }
    return out
