"""Parameter summary: the ``model.summary()`` moment.

The reference prints Keras's layer table on rank 0
(``/root/reference/imagenet-resnet50-hvd.py:95-96``). The functional
analogue summarizes the initialized parameter tree — per-top-level-module
parameter counts, dtypes, and totals — which works uniformly across the
model families (ResNet/ViT/GPT) without re-tracing the model.
"""

from __future__ import annotations

from typing import Any

import jax
import numpy as np

PyTree = Any


def format_table(title: str, rows: dict[str, Any]) -> str:
    """Aligned two-column text table in :func:`param_summary`'s house
    style, for summary surfaces whose rows are plain key → value (the
    serving engine's :meth:`~pddl_tpu.serve.metrics.ServeMetrics.summary`).
    ``param_summary`` itself keeps its hand-rolled layout — its TOTAL
    and batch-stats rows carry trailing annotations this two-column
    form doesn't express. Numbers get thousands separators; floats
    keep 3 decimals."""
    def _fmt(v: Any) -> str:
        if isinstance(v, bool):
            return str(v)
        if isinstance(v, int):
            return f"{v:,}"
        if isinstance(v, float):
            return f"{v:,.3f}"
        return str(v)

    lines = [title]
    width = max((len(k) for k in rows), default=10)
    for key, value in rows.items():
        lines.append(f"  {key:<{width}}  {_fmt(value):>14}")
    return "\n".join(lines)


def param_summary(params: PyTree, batch_stats: PyTree | None = None) -> str:
    """Human-readable per-module parameter table + totals."""
    by_module: dict[str, int] = {}
    total = 0
    total_bytes = 0
    for path, leaf in jax.tree_util.tree_leaves_with_path(params):
        top = str(getattr(path[0], "key", path[0])) if path else "<root>"
        n = int(np.prod(leaf.shape)) if leaf.shape else 1
        by_module[top] = by_module.get(top, 0) + n
        total += n
        total_bytes += n * np.dtype(leaf.dtype).itemsize
    lines = ["Model parameters:"]
    width = max((len(k) for k in by_module), default=10)
    for name in sorted(by_module):
        lines.append(f"  {name:<{width}}  {by_module[name]:>14,}")
    lines.append(f"  {'TOTAL':<{width}}  {total:>14,}  "
                 f"({total_bytes / 1e6:.1f} MB)")
    if batch_stats is not None:
        n_stats = sum(
            int(np.prod(leaf.shape)) if leaf.shape else 1
            for leaf in jax.tree.leaves(batch_stats)
        )
        if n_stats:
            lines.append(f"  {'(batch stats)':<{width}}  {n_stats:>14,}")
    return "\n".join(lines)
