"""Parameter summary: the ``model.summary()`` moment.

The reference prints Keras's layer table on rank 0
(``/root/reference/imagenet-resnet50-hvd.py:95-96``). The functional
analogue summarizes the initialized parameter tree — per-top-level-module
parameter counts, dtypes, and totals — which works uniformly across the
model families (ResNet/ViT/GPT) without re-tracing the model.
"""

from __future__ import annotations

from typing import Any

import jax
import numpy as np

PyTree = Any


def param_summary(params: PyTree, batch_stats: PyTree | None = None) -> str:
    """Human-readable per-module parameter table + totals."""
    by_module: dict[str, int] = {}
    total = 0
    total_bytes = 0
    for path, leaf in jax.tree_util.tree_leaves_with_path(params):
        top = str(getattr(path[0], "key", path[0])) if path else "<root>"
        n = int(np.prod(leaf.shape)) if leaf.shape else 1
        by_module[top] = by_module.get(top, 0) + n
        total += n
        total_bytes += n * np.dtype(leaf.dtype).itemsize
    lines = ["Model parameters:"]
    width = max((len(k) for k in by_module), default=10)
    for name in sorted(by_module):
        lines.append(f"  {name:<{width}}  {by_module[name]:>14,}")
    lines.append(f"  {'TOTAL':<{width}}  {total:>14,}  "
                 f"({total_bytes / 1e6:.1f} MB)")
    if batch_stats is not None:
        n_stats = sum(
            int(np.prod(leaf.shape)) if leaf.shape else 1
            for leaf in jax.tree.leaves(batch_stats)
        )
        if n_stats:
            lines.append(f"  {'(batch stats)':<{width}}  {n_stats:>14,}")
    return "\n".join(lines)
