"""Child process for the LM pipeline-parallel multi-process test (not a
pytest file).

Trains a tiny GQA GPipeLlama for two steps under PipelineStrategy over a
``data=1 x stage=2`` mesh and prints the final loss. Run two ways by
tests/test_multiprocess.py:

- TWO real OS processes x 1 fake CPU device each (PDDL_* bootstrap set):
  one pipeline stage per process, so EVERY ``ppermute`` activation hop of
  the GPipe schedule (forward and the AD-derived backward) crosses the
  process boundary on gloo — the one collective family no other
  process-boundary test exercises.
- ONE process x 2 fake devices (no coordinator): the single-process
  fake-mesh oracle the multi-process loss must match.

The batch is replicated over the ``stage`` axis (data axis has size 1),
so both workers generate and feed the IDENTICAL full batch
(process_count=1 for the dataset regardless of world size).

Exits non-zero on any assertion failure.
"""

import os
import sys

_LOCAL = int(os.environ.get("PDDL_TEST_LOCAL_DEVICES", "1"))

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + f" --xla_force_host_platform_device_count={_LOCAL}"
)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402


def main() -> None:
    from pddl_tpu.core import dist

    multiprocess = "PDDL_COORDINATOR" in os.environ
    if multiprocess:
        spec = dist.initialize()
        assert spec.is_multiprocess, spec

    from pddl_tpu.parallel.pipeline import PipelineStrategy

    strategy = PipelineStrategy(n_stages=2)
    mesh = strategy.setup()
    assert mesh.devices.size == 2, mesh
    if multiprocess:
        # The point of this test: the stage axis must SPAN the processes.
        stage_procs = {d.process_index for d in mesh.devices.flat}
        assert stage_procs == {0, 1}, stage_procs

    from pddl_tpu.data.synthetic import SyntheticLanguageModeling
    from pddl_tpu.models.llama import GPipeLlama
    from pddl_tpu.train.loop import Trainer

    model = GPipeLlama(vocab_size=16, n_stages=2, blocks_per_stage=1,
                       n_microbatches=2, mesh=mesh, embed_dim=32,
                       num_heads=4, num_kv_heads=2, attention="reference")
    # data axis is size 1 -> the batch replicates over `stage`; every
    # process must feed the identical FULL batch (not a shard of it).
    data = SyntheticLanguageModeling(
        batch_size=4, seq_len=32, vocab_size=16, seed=3,
        process_index=0, process_count=1,
    )
    trainer = Trainer(model, optimizer="sgd", learning_rate=0.01,
                      strategy=strategy, seed=0, input_key="tokens",
                      target_key="targets")
    hist = trainer.fit(data, epochs=1, steps_per_epoch=2, verbose=0)
    loss = float(hist.history["loss"][-1])
    assert np.isfinite(loss), loss

    # The stage layout must actually be installed: stacked block weights
    # shard their leading (stage) dim; embed/head replicate.
    from jax.sharding import PartitionSpec as P
    from pddl_tpu.core.mesh import STAGE_AXIS

    wq = trainer.state.params["stages"]["block0"]["attn"]["query"]["kernel"]
    assert wq.sharding.spec[0] == STAGE_AXIS, wq.sharding.spec
    emb = trainer.state.params["embed"]["embed"]["embedding"]
    assert emb.sharding.spec == P(), emb.sharding.spec

    print(f"child {jax.process_index()} LMPP OK loss={loss:.10f}",
          flush=True)


if __name__ == "__main__":
    main()
    sys.exit(0)
