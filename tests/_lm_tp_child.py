"""Child process for the LM DP x TP multi-process test (not a pytest file).

Trains a tiny GQA Llama for two steps under TensorParallelStrategy
(LLAMA_TP_RULES) over a 4-device ``data=2 x model=2`` mesh and prints the
final loss. Run two ways by tests/test_multiprocess.py:

- TWO real OS processes x 2 fake CPU devices each (PDDL_* bootstrap set):
  DP crosses the process boundary, the Megatron all-reduces compile into
  the step, gradients ride gloo — the transformer-family analogue of the
  ResNet path in _multiworker_child.py.
- ONE process x 4 fake devices (no coordinator): the single-process
  oracle the multi-process loss must match.

Exits non-zero on any assertion failure.
"""

import os
import sys

_LOCAL = int(os.environ.get("PDDL_TEST_LOCAL_DEVICES", "2"))

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + f" --xla_force_host_platform_device_count={_LOCAL}"
)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402


def main() -> None:
    from pddl_tpu.core import dist

    multiprocess = "PDDL_COORDINATOR" in os.environ
    if multiprocess:
        spec = dist.initialize()
        assert spec.is_multiprocess, spec

    from pddl_tpu.parallel.tensor_parallel import (
        LLAMA_TP_RULES,
        TensorParallelStrategy,
    )

    strategy = TensorParallelStrategy(model_parallel=2,
                                      rules=LLAMA_TP_RULES)
    mesh = strategy.setup()
    assert mesh.devices.size == 4, mesh

    from pddl_tpu.data.synthetic import SyntheticLanguageModeling
    from pddl_tpu.models.llama import Llama
    from pddl_tpu.train.loop import Trainer

    model = Llama(vocab_size=16, max_len=32, embed_dim=32, depth=2,
                  num_heads=4, num_kv_heads=2, attention="reference")
    data = SyntheticLanguageModeling(
        batch_size=strategy.scale_batch_size(4), seq_len=32, vocab_size=16,
        seed=3, process_index=strategy.process_index,
        process_count=strategy.data_process_count,
    )
    trainer = Trainer(model, optimizer="sgd", learning_rate=0.01,
                      strategy=strategy, seed=0, input_key="tokens",
                      target_key="targets")
    hist = trainer.fit(data, epochs=1, steps_per_epoch=2, verbose=0)
    loss = float(hist.history["loss"][-1])
    assert np.isfinite(loss), loss

    # The Megatron sharding must actually be installed: q/k/v
    # column-parallel on `model`, embed vocab-parallel.
    from jax.sharding import PartitionSpec as P
    from pddl_tpu.core.mesh import MODEL_AXIS

    attn = trainer.state.params["block0"]["attn"]
    assert attn["query"]["kernel"].sharding.spec == P(None, MODEL_AXIS), \
        attn["query"]["kernel"].sharding.spec
    emb = trainer.state.params["embed"]["embedding"]
    assert emb.sharding.spec == P(MODEL_AXIS), emb.sharding.spec

    print(f"child {jax.process_index()} LMTP OK loss={loss:.10f}",
          flush=True)


if __name__ == "__main__":
    main()
    sys.exit(0)
