"""Child process for the multi-process bootstrap tests (not a pytest file).

Each process owns ``PDDL_TEST_LOCAL_DEVICES`` (default 2) fake CPU devices;
``PDDL_NUM_PROCESSES`` of them form the global mesh. This is the JAX
analogue of the reference's in-process gRPC cluster trick
(``/root/reference/imagenet-resnet50-ps.py:31-65``) — a genuine
multi-process topology on one machine, no hardware needed (SURVEY.md §4
mechanism 1).

Run by tests/test_multiprocess.py with PDDL_COORDINATOR / PDDL_NUM_PROCESSES
/ PDDL_PROCESS_ID set; exits non-zero on any assertion failure.
"""

import os
import sys

_LOCAL = int(os.environ.get("PDDL_TEST_LOCAL_DEVICES", "2"))

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + f" --xla_force_host_platform_device_count={_LOCAL}"
)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402


def main() -> None:
    from pddl_tpu.core import dist

    n_procs = int(os.environ["PDDL_NUM_PROCESSES"])
    world = n_procs * _LOCAL

    # Bootstrap purely from PDDL_* env (discovery order step 2 in core/dist).
    spec = dist.initialize()
    assert spec.is_multiprocess, spec
    assert spec.num_processes == n_procs, spec
    assert jax.process_count() == n_procs
    assert len(jax.local_devices()) == _LOCAL
    assert len(jax.devices()) == world
    assert dist.is_coordinator() == (jax.process_index() == 0)

    # The multiworker strategy over the global mesh (idempotent re-init).
    from pddl_tpu.parallel.multiworker import MultiWorkerMirroredStrategy

    strategy = MultiWorkerMirroredStrategy()
    mesh = strategy.setup()
    assert mesh.devices.size == world
    assert strategy.num_workers == n_procs
    assert strategy.num_replicas_in_sync == world
    # Reference batch arithmetic at multi-host scale: 32 * replicas
    # (imagenet-resnet50-multiworkers.py:70).
    assert strategy.scale_batch_size(32) == 32 * world

    # DATA-sharded feeding: each process contributes its local rows; the
    # assembled array is the world-sized global batch.
    local = np.full((_LOCAL, 3), float(jax.process_index()), np.float32)
    batch = strategy.distribute_batch({"image": local})
    assert batch["image"].shape == (world, 3)

    # A real cross-process collective (the NCCL-allreduce moment): global
    # mean over the whole array = mean of process ids.
    from jax.sharding import NamedSharding, PartitionSpec as P

    mean = jax.jit(
        jnp.mean, out_shardings=NamedSharding(mesh, P())
    )(batch["image"])
    np.testing.assert_allclose(
        np.asarray(mean), (n_procs - 1) / 2.0, atol=1e-6)

    # hvd-shim host collectives across the real processes.
    from pddl_tpu.compat import hvd

    hvd._mesh = mesh  # the cluster is already up via dist.initialize
    summed = hvd.allreduce(np.float32(jax.process_index()), average=False)
    np.testing.assert_allclose(
        np.asarray(summed), n_procs * (n_procs - 1) / 2.0)
    gathered = hvd.allgather(np.full((2,), float(jax.process_index()),
                                     np.float32))
    expect = np.repeat(np.arange(n_procs, dtype=np.float32), 2)
    np.testing.assert_array_equal(np.asarray(gathered), expect)
    # broadcast from a NON-zero root: every process must receive the last
    # rank's value (full hvd surface — root_rank is not pinned to 0).
    root = n_procs - 1
    got = hvd.broadcast(np.float32(jax.process_index()), root_rank=root)
    np.testing.assert_allclose(np.asarray(got), float(root))

    # One real training step through the Trainer (grad all-reduce across
    # all processes compiled into the step).
    from pddl_tpu.data.synthetic import SyntheticImageClassification
    from pddl_tpu.models.resnet import tiny_resnet
    from pddl_tpu.train.loop import Trainer

    data = SyntheticImageClassification(
        batch_size=strategy.scale_batch_size(2), image_size=16, num_classes=4,
        seed=0, process_index=strategy.process_index,
        process_count=strategy.data_process_count,
    )
    trainer = Trainer(tiny_resnet(num_classes=4), learning_rate=1e-2,
                      strategy=strategy)
    hist = trainer.fit(data, epochs=1, steps_per_epoch=2, verbose=0)
    loss = hist.history["loss"][-1]
    assert np.isfinite(loss), loss

    # Heartbeat failure detection over a REAL multi-process topology
    # (shared-dir beats + coordinated restart marker), when the driver
    # provides the shared directory.
    hb_dir = os.environ.get("PDDL_HEARTBEAT_DIR")
    if hb_dir:
        import time

        from pddl_tpu.parallel.multiworker import (
            HeartbeatMonitor,
            WorkerLost,
        )

        mon = HeartbeatMonitor(hb_dir, timeout_s=30.0)
        mon.start()
        # Every process beats; after a barrier-ish settle, nobody reads
        # as failed (the live fleet is quiet).
        deadline = time.time() + 20.0
        while time.time() < deadline:
            if all(s is not None for s in mon.last_seen().values()):
                break
            mon.beat()
            time.sleep(0.05)
        assert all(s is not None for s in mon.last_seen().values()), \
            mon.last_seen()
        assert mon.failed() == [], mon.failed()

        # A worker that NEVER beat reads as lost once the timeout
        # passes: watch one phantom extra worker on a fast fake clock.
        # Advancing the fake clock also ages the REAL peers' wall-clock
        # beats, so assert containment, not equality — the phantom must
        # be among the lost, whatever the live workers read as.
        fake_now = [time.time()]
        ghost = HeartbeatMonitor(hb_dir, process_id=mon.process_id,
                                 num_processes=n_procs + 1,
                                 timeout_s=5.0, clock=lambda: fake_now[0])
        ghost.start()
        fake_now[0] += 6.0
        try:
            ghost.check()
            raise AssertionError("phantom worker not detected")
        except WorkerLost as e:
            assert n_procs in e.lost, e.lost

        # Coordinated restart: the LAST rank requests it; every process
        # observes the shared marker.
        if jax.process_index() == n_procs - 1:
            mon.request_restart("elastic scale-down drill")
        deadline = time.time() + 20.0
        while time.time() < deadline and not mon.restart_requested():
            time.sleep(0.05)
        assert mon.restart_requested()

    print(f"child {jax.process_index()} OK loss={loss:.4f}", flush=True)


if __name__ == "__main__":
    main()
    sys.exit(0)
