"""Child for the cross-process drain→restore round-trip test.

Builds the deterministic fleet-worker engine
(`pddl_tpu.serve.fleet.worker.build_engine`, seeded params), submits a
fixed workload, runs a few steps so some streams are mid-flight, then
drains to ``<out_dir>/snapshot.json`` via the engine's own SIGTERM-path
``drain()`` and writes a sidecar ``state.json`` with each request's
partial stream at drain time — everything the PARENT test (a different
interpreter) needs to pin the restore token-exact.

Usage: ``python tests/_serve_drain_child.py <out_dir> <config-json>``
"""

import json
import os
import sys


def main() -> int:
    out_dir, config_json = sys.argv[1], sys.argv[2]
    config = json.loads(config_json)
    os.makedirs(out_dir, exist_ok=True)

    from pddl_tpu.serve.fleet.worker import build_engine

    engine = build_engine(config)
    engine.warmup()
    handles = [engine.submit(req["prompt"], req["max_new_tokens"])
               for req in config["workload"]]
    for _ in range(int(config.get("steps_before_drain", 3))):
        engine.step()
    partial = [list(h.tokens) for h in handles]
    engine.drain(os.path.join(out_dir, "snapshot.json"))
    with open(os.path.join(out_dir, "state.json"), "w") as f:
        json.dump({
            "partial_tokens": partial,
            "states": [h.state.value for h in handles],
            "pid": os.getpid(),
        }, f)
    return 0


if __name__ == "__main__":
    sys.exit(main())
