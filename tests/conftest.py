"""Test harness: fake 8-device CPU mesh.

The reference "tests" multi-node topologies with an in-process gRPC cluster
(`/root/reference/imagenet-resnet50-ps.py:31-65`) and CUDA-hiding env vars
(`:29`). The JAX equivalent (SURVEY.md §4): force the host platform and split
it into 8 virtual devices so every sharding/collective path compiles and runs
on one CPU.

Must run before any JAX backend initialization — the axon TPU plugin
registers itself via sitecustomize and pins ``jax_platforms=axon,cpu``, so we
both set the env *and* override the config after import.
"""

import os

os.environ.setdefault("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in os.environ["XLA_FLAGS"]:
    os.environ["XLA_FLAGS"] += " --xla_force_host_platform_device_count=8"
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def eight_devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 fake CPU devices, got {len(devs)}"
    return devs


@pytest.fixture()
def mesh8():
    from pddl_tpu.core.mesh import build_mesh, MeshConfig

    return build_mesh(MeshConfig(data=8))


@pytest.fixture()
def mesh4x2():
    from pddl_tpu.core.mesh import build_mesh, MeshConfig

    return build_mesh(MeshConfig(data=4, model=2))
