"""Test harness: fake 8-device CPU mesh.

The reference "tests" multi-node topologies with an in-process gRPC cluster
(`/root/reference/imagenet-resnet50-ps.py:31-65`) and CUDA-hiding env vars
(`:29`). The JAX equivalent (SURVEY.md §4): force the host platform and split
it into 8 virtual devices so every sharding/collective path compiles and runs
on one CPU.

Must run before any JAX backend initialization — the axon TPU plugin
registers itself via sitecustomize and pins ``jax_platforms=axon,cpu``, so we
both set the env *and* override the config after import.
"""

import os

os.environ.setdefault("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in os.environ["XLA_FLAGS"]:
    os.environ["XLA_FLAGS"] += " --xla_force_host_platform_device_count=8"
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
# Newer jax defaults this True (random bits independent of how the key
# computation is partitioned); older releases default False, which makes
# sharded-vs-single-device runs draw DIFFERENT dropout masks and fail the
# SPMD-identity pins. Align old jax with the modern semantics.
try:
    jax.config.update("jax_threefry_partitionable", True)
except Exception:  # noqa: BLE001 - flag removed once it's the only behavior
    pass

# The suite's wall-clock is dominated by XLA:CPU compiles of the sharded
# train steps. Persist them (shared with the driver's multichip gate):
# a warm cache cuts a full run by minutes.
from pddl_tpu.utils.compile_cache import enable_persistent_compile_cache  # noqa: E402

enable_persistent_compile_cache()

import pytest  # noqa: E402


def native_build_error(tfrecord: bool = False) -> str:
    """Build the native library if missing; '' on success, else the error.

    Shared by the native-loader and TFRecord test modules so a missing
    toolchain produces one self-explanatory skip reason. Only TFRecord
    tests (``tfrecord=True``) additionally require the ``pddl_tfr_*``
    symbols, so a prebuilt pre-TFRecord library still runs the
    packed-loader tests.
    """
    try:
        from pddl_tpu.data.native_loader import build_native

        build_native()  # no-op when the .so is already fresh
        if tfrecord:
            from pddl_tpu.data.tfrecord import _tfr_lib

            _tfr_lib()  # raises if a stale pre-TFRecord .so got loaded
        return ""
    except Exception as e:  # noqa: BLE001 - any failure means "skip"
        return str(e)


def ref_greedy(model, variables, prompt, n_new):
    """The serving test suite's oracle: one-shot batch-1 ``generate()``
    over the same params. Every engine/fleet path (cold admit, prefix
    hit, replay, migration) is pinned token-exact against THIS — one
    copy, so every serving test file pins the same reference."""
    import jax.numpy as jnp
    import numpy as np

    from pddl_tpu.models.gpt import generate

    out = generate(model, variables,
                   jnp.asarray(prompt, jnp.int32)[None], n_new)
    return np.asarray(out)[0, len(prompt):].tolist()


class FakeClock:
    """Deterministic ``clock=`` stand-in: time advances only when a
    test sets ``.now`` (deadlines, backoff, breaker windows)."""

    def __init__(self, now=0.0):
        self.now = now

    def __call__(self):
        return self.now


@pytest.fixture()
def pin_zero_recompiles():
    """THE fixed-shape contract as a reusable fixture: every resident
    compiled program of a registered object has exactly ONE executable
    at registration AND still exactly one when the test ends — whatever
    mixed workload (or fault-recovery path) ran in between compiled
    nothing new.

    Works for anything exposing ``compile_counts()``: a ``ServeEngine``
    (warmed first — it exposes ``warmup()``), a ``Trainer`` (register
    it after its first fit, when both programs exist), or a
    ``FleetRouter``, whose aggregated counts are keyed
    ``r<replica>/<site>`` — registering a fleet pins zero recompiles
    PER REPLICA, which is how the fleet chaos matrix asserts that no
    surviving replica recompiled anything across a migration::

        eng = pin_zero_recompiles(ServeEngine(model, variables, ...))
        trainer.fit(...); pin_zero_recompiles(trainer)
        fleet = pin_zero_recompiles(FleetRouter([...]))

    Every serve-layer test that builds an engine through it gets the
    zero-recompile pin for free (`test_serve_engine.py`,
    `test_prefix_cache.py`); the training chaos matrix pins recovery
    transitions the same way (`test_train_faults.py`), the fleet
    matrix per surviving replica (`test_serve_fleet.py`).
    """
    engines = []

    def register(engine):
        if hasattr(engine, "warmup"):
            engine.warmup()
        counts = engine.compile_counts()
        assert counts and all(v == 1 for v in counts.values()), \
            f"program(s) compiled more than once at registration: {counts}"
        engines.append(engine)
        return engine

    yield register
    for engine in engines:
        counts = engine.compile_counts()
        assert all(v == 1 for v in counts.values()), \
            f"workload recompiled resident program(s): {counts}"


@pytest.fixture(scope="session")
def eight_devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 fake CPU devices, got {len(devs)}"
    return devs


@pytest.fixture()
def mesh8():
    from pddl_tpu.core.mesh import build_mesh, MeshConfig

    return build_mesh(MeshConfig(data=8))


@pytest.fixture()
def mesh4x2():
    from pddl_tpu.core.mesh import build_mesh, MeshConfig

    return build_mesh(MeshConfig(data=4, model=2))
