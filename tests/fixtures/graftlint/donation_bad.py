"""Seeded-bad fixture for the ``donation`` rule: a read of a donated
buffer after the call, and a host-numpy leaf stored into a donated
tree (the set_learning_rate tier-1 flake, distilled)."""

import jax
import numpy as np


class Engine:
    def build(self, tick):
        self._tick_p = jax.jit(tick, donate_argnums=(1,))

    def step(self, tokens):
        new_cache, out = self._tick_p(self._params, self._cache, tokens)
        # BUG: self._cache was donated to the tick — its buffer is
        # consumed; this read sees freed (or silently reused) memory.
        stale = self._cache["k"]
        self._cache = new_cache
        return out, stale


def set_learning_rate(state, value):
    def _set(opt_state):
        new_hp = dict(opt_state.hyperparams)
        # BUG (the ROADMAP "Known flake"): a HOST numpy scalar stored
        # into the opt_state tree rides the donated train step — the
        # runtime donates a buffer it does not own.
        new_hp["learning_rate"] = np.asarray(value, dtype=np.float32)
        return opt_state._replace(hyperparams=new_hp)

    return state.replace(opt_state=_set(state.opt_state))
