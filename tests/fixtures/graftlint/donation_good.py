"""Good twin for the ``donation`` fixtures: the donated tree is
adopted from the call's result before any further use, and the stored
leaf is a device (jnp) stamp. Must lint clean."""

import jax
import jax.numpy as jnp


class Engine:
    def build(self, tick):
        self._tick_p = jax.jit(tick, donate_argnums=(1,))

    def step(self, tokens):
        # Adoption over the donated name: the engine always re-binds
        # the returned tree, so no stale reference can survive.
        self._cache, out = self._tick_p(self._params, self._cache, tokens)
        fresh = self._cache["k"]
        return out, fresh


def set_learning_rate(state, value):
    def _set(opt_state):
        new_hp = dict(opt_state.hyperparams)
        # Device stamp: the donated train step owns this buffer.
        new_hp["learning_rate"] = jnp.asarray(value, dtype=jnp.float32)
        return opt_state._replace(hyperparams=new_hp)

    return state.replace(opt_state=_set(state.opt_state))
