"""Seeded-bad fixture for the ``epoch-vocab`` rule (ISSUE 20): the
fencing-epoch manifest drifts in every direction the rule covers.
Self-paired — EPOCH_CMDS (driver manifest) and FENCED_CMDS (worker
fence-gate mirror) both live here, the fixture analogue of
replica.py + worker.py in one module.

Seeded findings (4):
- ``drain_replica`` emits ``{"cmd": "drain"}`` with an inline epoch
  stamp, but EPOCH_CMDS never declared it — the fence gate will not
  intercept it, so a deposed primary can still drain the fleet;
- EPOCH_CMDS lists ``"retire"``, which no function epoch-stamps — a
  stale manifest entry claiming a fence the driver never arms;
- FENCED_CMDS disagrees with EPOCH_CMDS: it gates ``"pause"`` (never
  stamped) and is missing ``"restore"`` and ``"retire"``;
- FENCED_CMDS entry ``"pause"`` has no ``== "pause"`` dispatch branch
  in the handler — the gate guards a command no branch serves.
"""

EPOCH_CMDS = ("submit", "cancel", "restore", "fence", "retire")

FENCED_CMDS = ("submit", "cancel", "fence", "pause")


def submit(rid, prompt, epoch=None):
    cmd = {"cmd": "submit", "rid": int(rid), "prompt": list(prompt)}
    if epoch is not None:
        cmd["epoch"] = int(epoch)
    return cmd


def cancel(rid, epoch=None):
    return {"cmd": "cancel", "rid": int(rid), "epoch": epoch}


def restore(rid, tokens, epoch=None):
    cmd = {"cmd": "restore", "rid": int(rid), "tokens": list(tokens)}
    if epoch is not None:
        cmd["epoch"] = int(epoch)
    return cmd


def fence(epoch):
    return {"cmd": "fence", "epoch": int(epoch)}


def drain_replica(epoch):
    # BUG: epoch-stamped mutator that never entered EPOCH_CMDS.
    return {"cmd": "drain", "epoch": int(epoch)}


def handle(cmd):
    kind = cmd.get("cmd")
    if kind == "fence":
        return {"ev": "fence_ok"}
    if kind == "submit":
        return {"ev": "admitted", "rid": cmd["rid"]}
    if kind == "cancel":
        return {"ev": "cancelled", "rid": cmd["rid"]}
    if kind == "restore":
        return {"ev": "restored", "rid": cmd["rid"]}
    return {"ev": "unknown"}
