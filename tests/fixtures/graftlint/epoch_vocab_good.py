"""Good twin for the epoch-vocab fixture: the fence-gate mirror
equals the driver manifest, every manifested command has an
epoch-stamped emit site, every gated command has a dispatch branch,
and the epoch-free read path (``"ping"``) is legitimately outside
the manifest. Must lint clean."""

EPOCH_CMDS = ("submit", "cancel", "restore", "fence")

FENCED_CMDS = ("submit", "cancel", "restore", "fence")


def submit(rid, prompt, epoch=None):
    cmd = {"cmd": "submit", "rid": int(rid), "prompt": list(prompt)}
    if epoch is not None:
        cmd["epoch"] = int(epoch)
    return cmd


def cancel(rid, epoch=None):
    cmd = {"cmd": "cancel", "rid": int(rid)}
    if epoch is not None:
        cmd["epoch"] = int(epoch)
    return cmd


def restore(rid, tokens, epoch=None):
    cmd = {"cmd": "restore", "rid": int(rid), "tokens": list(tokens)}
    if epoch is not None:
        cmd["epoch"] = int(epoch)
    return cmd


def fence(epoch):
    return {"cmd": "fence", "epoch": int(epoch)}


def ping():
    # Read-only probe: carries no epoch and is not a fleet mutator,
    # so it stays out of the manifest by design.
    return {"cmd": "ping"}


def handle(cmd):
    kind = cmd.get("cmd")
    if kind == "fence":
        return {"ev": "fence_ok"}
    if kind == "submit":
        return {"ev": "admitted", "rid": cmd["rid"]}
    if kind == "cancel":
        return {"ev": "cancelled", "rid": cmd["rid"]}
    if kind == "restore":
        return {"ev": "restored", "rid": cmd["rid"]}
    if kind == "ping":
        return {"ev": "pong"}
    return {"ev": "unknown"}
