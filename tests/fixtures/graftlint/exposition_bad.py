"""Seeded-bad fixture for the ``exposition-parity`` rule: a recorded
counter that never surfaces in snapshot() (the real retry_sites gap
this rule found in ServeMetrics), and a counter-key declaration typing
a metric nobody emits."""

# BUG: "ghost_total" is declared a counter but no snapshot emits it —
# stale typing for a metric that does not exist.
SERVE_COUNTER_KEYS = frozenset({"requests_finished", "ghost_total"})


class Metrics:
    def __init__(self):
        self.requests_finished = 0
        # BUG: recorded on every retry, never exported — invisible to
        # the exposition AND to the runtime drift guard.
        self.retry_sites = {}

    def record_retry(self, site):
        self.retry_sites[site] = self.retry_sites.get(site, 0) + 1

    def snapshot(self):
        return {
            "requests_finished": self.requests_finished,
        }
