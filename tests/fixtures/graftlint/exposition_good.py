"""Good twin for the ``exposition-parity`` fixture: every recorded
field surfaces in snapshot(), every declared counter key is emitted.
Must lint clean."""

SERVE_COUNTER_KEYS = frozenset({"requests_finished"})


class Metrics:
    def __init__(self, reservoir_cap: int = 8192):
        # Configuration (from a constructor parameter) — not a metric.
        self.reservoir_cap = int(reservoir_cap)
        self.requests_finished = 0
        self.retry_sites = {}
        self.ttft_s = []

    def record_retry(self, site):
        self.retry_sites[site] = self.retry_sites.get(site, 0) + 1

    def snapshot(self):
        return {
            "requests_finished": self.requests_finished,
            "retry_sites": dict(self.retry_sites),
            # Derived keys cover their source field (ttft_s).
            "ttft_p50_s": None,
            "ttft_p99_s": None,
        }
