"""Seeded-bad fixture for the ``snapshot-hygiene`` rule's JOURNAL
family (ISSUE 14): a record encoder emits a key the versioned
``RECORD_KEYS_V*`` manifest does not declare — the control-plane WAL
format changed without a ``JOURNAL_VERSION`` bump, so a recovering
router would mis-decode its own log."""

JOURNAL_VERSION = 1

RECORD_KEYS_V1 = ("rec", "rid", "toks")


def encode_tokens(rid, toks):
    return {
        "rec": "tokens",
        "rid": int(rid),
        "toks": [int(t) for t in toks],
        # BUG: a new record key with no version bump — recovery built
        # against the old manifest silently drops the binding.
        "replica": 0,
    }
