"""Good twin for the journal-manifest fixture: ``RECORD_KEYS_V*``
names the current ``JOURNAL_VERSION`` and matches the record encoders
exactly. Must lint clean."""

JOURNAL_VERSION = 2

RECORD_KEYS_V2 = ("rec", "rid", "toks", "replica")


def encode_tokens(rid, toks):
    return {
        "rec": "tokens",
        "rid": int(rid),
        "toks": [int(t) for t in toks],
        "replica": 0,
    }
