"""Seeded-bad fixture: a host-tier promotion that LEAKS its pin on the
fault-unwind path (the ISSUE 13 demote/promote pin-pair class).

``pin_chain`` is the host tier's match-and-pin acquire
(`pddl_tpu/serve/kvcache/hosttier.py`): the returned tip must be
``unpin``-ed exactly once on every path out of the promotion. Here the
unwind releases the device-side block ids but forgets the host pin, so
the byte budget can never evict the chain again — a permanent host-
memory leak per faulted promotion. The graftlint ``pin-release`` rule
must flag the raise path.
"""


class Engine:
    def promote_host_chain(self, prompt, m, cap):
        tip = self._host.pin_chain(prompt, m, cap - m)
        ids = self._prefix.allocate(cap - m)
        try:
            self.dispatch_scatter(ids)
        except RuntimeError:
            # BUG: the unwind hands back the device ids but LEAKS the
            # host-tier pin — the chain is unevictable forever.
            self._prefix.release(ids)
            raise
        self._prefix.extend(tip, prompt, ids)
        self._host.unpin(tip)
        return len(ids)
