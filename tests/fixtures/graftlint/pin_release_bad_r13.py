"""Seeded-bad fixture: the r13 parked-slice drop (CHANGES.md PR 8
review pass), distilled.

A parked mid-prefill slice was dropped on the paged-world reset still
holding freshly allocated block ids and a pinned index node — an early
exit path that never released what admission had acquired. The
graftlint ``pin-release`` rule must flag both escapes.
"""


class Engine:
    def start_slice(self, prompt, n_blocks):
        node = self.match(prompt)
        self._prefix.pin(node)
        private = self._prefix.allocate(n_blocks)
        if self._draining:
            # BUG (r13 class): the slice is dropped pre-reset WITHOUT
            # releasing the private blocks or unpinning the node — the
            # pool leaks the ids and the refcount wedges the chain.
            return None
        slice_state = {"node": node, "private": private, "off": 0}
        self._slices.append(slice_state)
        return slice_state

    def start_slice_faulty_unwind(self, prompt, n_blocks):
        node = self.match(prompt)
        self._prefix.pin(node)
        ids = self._prefix.allocate(n_blocks)
        try:
            self.scatter(ids)
        except RuntimeError:
            # BUG (r13 class): the exception unwind releases the ids
            # but forgets the pin — the chain can never be evicted.
            self._prefix.release(ids)
            raise
        self._prefix.extend(node, prompt, ids)
        self._prefix.unpin(node)
