"""Seeded-bad fixture: the r14 adapter-pin double-release (CHANGES.md
PR 9 review pass), distilled.

A sliced admission that completed within its first step and whose
install then faulted released the adapter pin TWICE on the unwind path
(slice-done bookkeeping could not distinguish never-created from
created-finished-then-faulted) — a refcount underflow. The graftlint
``pin-release`` rule must flag the second release.
"""


class Engine:
    def finish_slice_install(self, sl):
        row = sl["arow"]
        try:
            self.install_slot(sl)
        except RuntimeError:
            # Slice teardown releases the adapter pin...
            self._apool.unpin(row)
            self.scrub(sl)
            # BUG (r14 class): ...and the admission unwind releases the
            # SAME pin again — refcount underflow on the fault path.
            self._apool.unpin(row)
            raise
