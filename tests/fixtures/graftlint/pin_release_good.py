"""Good twin for the ``pin-release`` fixtures: the same shapes with
the discipline intact — release on every unwind path, exactly once,
or an explicit hand-off to longer-lived state. Must lint clean.
"""


class Engine:
    def start_slice(self, prompt, n_blocks):
        node = self.match(prompt)
        self._prefix.pin(node)
        private = self._prefix.allocate(n_blocks)
        if self._draining:
            # Fixed r13 shape: the early exit releases everything the
            # admission acquired before dropping the slice.
            self._prefix.release(private)
            self._prefix.unpin(node)
            return None
        slice_state = {"node": node, "private": private, "off": 0}
        self._slices.append(slice_state)   # hand-off: slice owns them
        return slice_state

    def start_slice_clean_unwind(self, prompt, n_blocks):
        node = self.match(prompt)
        self._prefix.pin(node)
        ids = self._prefix.allocate(n_blocks)
        try:
            self.scatter(ids)
        except RuntimeError:
            # Full unwind: ids AND pin, restoring the pre-admission
            # refcount baseline exactly.
            self._prefix.release(ids)
            self._prefix.unpin(node)
            raise
        self._prefix.extend(node, prompt, ids)
        self._prefix.unpin(node)

    def finish_slice_install(self, sl):
        row = sl["arow"]
        try:
            self.install_slot(sl)
        except RuntimeError:
            # Fixed r14 shape: exactly one release on the fault path.
            self._apool.unpin(row)
            self.scrub(sl)
            raise

    def acquire_adapter(self, name):
        # Pin-then-return: ownership transfers to the caller — not a
        # leak (the real engine's _acquire_adapter shape).
        row = self._apool.assign(name)
        try:
            self.load(row)
        except RuntimeError:
            self._apool.unassign(row)
            raise
        self._apool.pin(row)
        return row

    def park_slot(self, slot_id):
        # Releases of state owned elsewhere (pinned at admission,
        # stored on self) — not double releases.
        self._prefix.release(self._private[slot_id])
        self._prefix.unpin(self._slot_nodes[slot_id])
        self._slot_nodes[slot_id] = None
