"""Good twin of ``pin_release_bad_hosttier.py``: the same promotion
shape with the host-tier pin pair intact — the fault-unwind releases
the device ids AND the host pin, restoring the pre-promotion refcount
baseline exactly (the discipline `ServeEngine._promote_host_chain`
holds). Must lint clean.
"""


class Engine:
    def promote_host_chain(self, prompt, m, cap):
        tip = self._host.pin_chain(prompt, m, cap - m)
        ids = self._prefix.allocate(cap - m)
        try:
            self.dispatch_scatter(ids)
        except RuntimeError:
            # Full unwind: device ids and the host-tier pin, exactly
            # once each.
            self._prefix.release(ids)
            self._host.unpin(tip)
            raise
        self._prefix.extend(tip, prompt, ids)
        self._host.unpin(tip)
        return len(ids)
