"""Seeded-bad fixture for the ``recompile-hazard`` rule: a traced
body closing over request attributes as Python scalars — every
distinct value is a silent recompile of the serving tick."""

import jax
import jax.numpy as jnp


def build_tick(req):
    def _tick(params, cache, tokens):
        # BUG: req.temperature is a per-request Python scalar baked
        # into the trace — a new executable per distinct temperature.
        scaled = cache["logits"] / req.temperature
        return scaled, jnp.argmax(scaled, axis=-1)

    return jax.jit(_tick)
