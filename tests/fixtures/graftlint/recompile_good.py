"""Good twin for the ``recompile-hazard`` fixture: per-request
variation enters the traced body as a runtime array argument — one
executable serves every value. Must lint clean."""

import jax
import jax.numpy as jnp


def build_tick():
    def _tick(params, cache, tokens, temps):
        # temps is a [S] runtime array stamped by the host loop —
        # attribute access on traced ARGUMENTS is array access.
        scaled = cache["logits"] / temps[:, None]
        return scaled, jnp.argmax(scaled, axis=-1)

    return jax.jit(_tick)
