"""Seeded-bad fixture for the ``role-vocab`` rule (ISSUE 17): the
disaggregation vocabularies drift in every direction the rule covers.
Self-paired — RECORD_KINDS, VIA_LABELS, and ROUTE_LABELS all live
here, the fixture analogue of journal.py + router.py in one module.

Seeded findings (5):
- ``encode_handoff`` emits ``"handoff"``, which RECORD_KINDS never
  declared — recovery has no reader-side decision for the kind;
- RECORD_KINDS lists ``"finish"`` and ``"retired_kind"``, which no
  encoder emits — two stale entries;
- ROUTE_LABELS mints ``"mystery"``, absent from VIA_LABELS;
- an ``encode_route`` call site passes the literal ``via="hedgerow"``,
  absent from VIA_LABELS.
"""

RECORD_KINDS = ("admit", "route", "finish", "retired_kind")

VIA_LABELS = ("sticky", "load", "migration", "hedge")

ROUTE_LABELS = ("sticky", "load", "mystery")


def encode_admit(rid):
    return {"rec": "admit", "rid": int(rid)}


def encode_route(rid, replica_id, via):
    return {"rec": "route", "rid": int(rid), "replica": int(replica_id),
            "via": str(via)}


def encode_handoff(rid, from_replica, to_replica):
    # BUG: a new record kind that never entered RECORD_KINDS.
    return {"rec": "handoff", "rid": int(rid),
            "replica": int(to_replica),
            "from_replica": int(from_replica)}


def journal_rebind(journal, rid, replica_id):
    # BUG: a via label minted at the call site only.
    journal.append(encode_route(rid, replica_id, "hedgerow"))
