"""Good twin for the role-vocab fixture: every emitted record kind is
declared (and none is stale), ROUTE_LABELS is a subset of VIA_LABELS,
and every literal ``via`` at an ``encode_route`` call site is
classified. Must lint clean."""

RECORD_KINDS = ("admit", "route", "handoff")

VIA_LABELS = ("sticky", "load", "migration", "hedge")

ROUTE_LABELS = ("sticky", "load")


def encode_admit(rid):
    return {"rec": "admit", "rid": int(rid)}


def encode_route(rid, replica_id, via):
    return {"rec": "route", "rid": int(rid), "replica": int(replica_id),
            "via": str(via)}


def encode_handoff(rid, from_replica, to_replica):
    return {"rec": "handoff", "rid": int(rid),
            "replica": int(to_replica),
            "from_replica": int(from_replica)}


def journal_rebind(journal, rid, replica_id):
    journal.append(encode_route(rid, replica_id, "hedge"))
