"""Seeded-bad fixture for the ``site-vocab`` rule: a dispatched site
missing from compile_counts, a counted site missing from SITES (the
real adapter_load gap this rule found in serve/faults.py), and a
stale SITES entry naming no program."""


class FaultPlan:
    # BUG: "gather" is stale (no such program here), and "adapter_load"
    # (counted below) is missing — chaos can never target it.
    SITES = ("tick", "prefill", "gather")


class Engine:
    def compile_counts(self):
        return {
            "tick": self._tick_p._cache_size(),
            "prefill": self._prefill_p._cache_size(),
            "adapter_load": self._adapter_load_p._cache_size(),
        }

    def step(self):
        out = self._device_call("tick", self._tick_p, self._cache)
        # BUG: "sample" is dispatched but is not a compile_counts key —
        # invisible to the zero-recompile pin.
        tok = self._device_call("sample", self._sample_p, out)
        return tok
