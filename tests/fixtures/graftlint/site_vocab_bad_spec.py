"""Seeded-bad fixture for the ``site-vocab`` rule, SPECULATIVE sites
(ISSUE 12): a ``spec_k > 0`` engine grows ``draft``/``verify``/
``draft_prefill`` device-call boundaries — exactly the gap class this
rule exists for. Here the faults vocabulary predates the speculative
programs: ``verify``/``draft_prefill`` are counted-and-dispatched but
absent from SITES (no chaos profile could ever target the verify
window or the draft model's admission chunk), and the retired
``tick`` lingers as a stale entry naming no program."""


class FaultPlan:
    # BUG: "verify" and "draft_prefill" (counted below) are missing —
    # the speculative recovery paths are untargetable by chaos — and
    # "tick" is stale (the spec engine replaced it with "verify").
    SITES = ("prefill", "draft", "tick")


class Engine:
    def compile_counts(self):
        return {
            "prefill": self._prefill_p._cache_size(),
            "draft": self._draft_p._cache_size(),
            "verify": self._verify_p._cache_size(),
            "draft_prefill": self._dchunk_p._cache_size(),
        }

    def step(self):
        drafts = self._device_call("draft", self._draft_p, self._hist)
        out = self._device_call("verify", self._verify_p, self._cache,
                                drafts)
        return out

    def admit(self):
        self._dcache = self._device_call("draft_prefill", self._dchunk_p,
                                         self._dcache)
        return self._device_call("prefill", self._prefill_p, self._row)
