"""Good twin for the ``site-vocab`` fixture: one vocabulary across
dispatch sites, compile_counts keys, and SITES. Must lint clean."""


class FaultPlan:
    SITES = ("tick", "prefill", "sample", "adapter_load")


class Engine:
    def compile_counts(self):
        return {
            "tick": self._tick_p._cache_size(),
            "prefill": self._prefill_p._cache_size(),
            "sample": self._sample_p._cache_size(),
            "adapter_load": self._adapter_load_p._cache_size(),
        }

    def step(self):
        out = self._device_call("tick", self._tick_p, self._cache)
        tok = self._device_call("sample", self._sample_p, out)
        row = self._device_call("adapter_load", self._adapter_load_p, tok)
        return row
