"""Good twin for the speculative ``site-vocab`` fixture: the
draft/verify/draft_prefill program names appear in compile_counts(),
FaultPlan.SITES, and the ``_device_call`` literals in lockstep. Must
lint clean."""


class FaultPlan:
    SITES = ("prefill", "draft", "verify", "draft_prefill")


class Engine:
    def compile_counts(self):
        return {
            "prefill": self._prefill_p._cache_size(),
            "draft": self._draft_p._cache_size(),
            "verify": self._verify_p._cache_size(),
            "draft_prefill": self._dchunk_p._cache_size(),
        }

    def step(self):
        drafts = self._device_call("draft", self._draft_p, self._hist)
        out = self._device_call("verify", self._verify_p, self._cache,
                                drafts)
        return out

    def admit(self):
        self._dcache = self._device_call("draft_prefill", self._dchunk_p,
                                         self._dcache)
        return self._device_call("prefill", self._prefill_p, self._row)
