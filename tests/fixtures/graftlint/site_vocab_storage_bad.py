"""Seeded-bad fixture for the ``site-vocab`` storage leg (ISSUE 18):
a ``_storage_op`` gate missing from the STORAGE_OPS manifest, a stale
manifest entry gating nothing, and a manifest/SITES split — the plan
would reject coordinates the journal actually gates, and carries a
site the journal never dispatches."""

# BUG: "fdatasync" is stale (no gate below dispatches it), and the
# "unlink" gate in close() is missing — untargetable by chaos.
STORAGE_OPS = ("open", "write", "fsync", "fdatasync")


class StorageFaultPlan:
    # BUG: "replace" matches no STORAGE_OPS entry (stale vocabulary),
    # and "fdatasync" (in the manifest) is missing — scheduling a
    # fault at a manifest op would raise at plan construction.
    SITES = ("open", "write", "fsync", "replace")


class JournalVFS:
    def open(self, path, flags, mode=0o644):
        self._storage_op("open")
        return _os_open(path, flags, mode)

    def write(self, fd, data):
        self._storage_op("write")
        return _os_write(fd, data)

    def fsync(self, fd):
        self._storage_op("fsync")
        _os_fsync(fd)

    def close(self, path):
        # BUG: "unlink" is dispatched but not a STORAGE_OPS entry.
        self._storage_op("unlink")
        _os_unlink(path)
