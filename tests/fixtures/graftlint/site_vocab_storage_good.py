"""Good twin for the ``site-vocab`` storage-leg fixture: one
vocabulary across ``_storage_op`` gates, the STORAGE_OPS manifest,
and ``StorageFaultPlan.SITES``. Must lint clean."""

STORAGE_OPS = ("open", "write", "fsync")


class StorageFaultPlan:
    SITES = ("open", "write", "fsync")


class JournalVFS:
    def open(self, path, flags, mode=0o644):
        self._storage_op("open")
        return _os_open(path, flags, mode)

    def write(self, fd, data):
        self._storage_op("write")
        return _os_write(fd, data)

    def fsync(self, fd):
        self._storage_op("fsync")
        _os_fsync(fd)
