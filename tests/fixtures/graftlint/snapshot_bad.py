"""Seeded-bad fixture for the ``snapshot-hygiene`` rule: the encoder
emits a key the versioned manifest does not declare — the wire format
changed without a SNAPSHOT_VERSION bump."""

SNAPSHOT_VERSION = 4

ENTRY_KEYS_V4 = ("prompt", "tokens", "elapsed_s")


def encode_handle(handle, now_s):
    return {
        "prompt": list(handle.request.prompt),
        "tokens": list(handle.tokens),
        "elapsed_s": float(now_s - handle.arrival_s),
        # BUG: a new wire key with no version bump — every restoring
        # engine reads the versioned header, then mis-decodes entries.
        "adapter": handle.request.adapter,
    }
