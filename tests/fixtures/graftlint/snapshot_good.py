"""Good twin for the ``snapshot-hygiene`` fixture: the manifest names
the current version and matches the encoder exactly. Must lint
clean."""

SNAPSHOT_VERSION = 5

ENTRY_KEYS_V5 = ("prompt", "tokens", "elapsed_s", "adapter")


def encode_handle(handle, now_s):
    return {
        "prompt": list(handle.request.prompt),
        "tokens": list(handle.tokens),
        "elapsed_s": float(now_s - handle.arrival_s),
        "adapter": handle.request.adapter,
    }
