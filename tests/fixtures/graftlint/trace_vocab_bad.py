"""Seeded-bad fixture for the ``trace-vocab`` rule (ISSUE 19): the
tracer and the assembler's event vocabulary drift in both directions
the rule covers. Self-paired — TRACE_EVENTS and the emitting call
sites live here, the fixture analogue of assemble.py + trace.py +
propagate.py in one module.

Seeded findings (3):
- a ``span.event`` call site mints ``"first_tok"`` (a typo of
  ``first_token``), which TRACE_EVENTS never declared — the
  assembler's TTFT attribution would silently never anchor;
- a ``self._event`` call site mints ``"rerouted"``, also undeclared
  — invisible to the gap checker;
- TRACE_EVENTS lists ``"thaw"``, which no call site emits — a stale
  entry promising coverage no emitter mints.
"""

TRACE_EVENTS = ("queued", "first_token", "preempted", "finish", "thaw")


def _named(events, name):
    return [e for e in events if e.get("name") == name]


def anchor(events):
    # The assembler-side consumer: keeps ``first_token`` non-stale so
    # the typo'd EMITTER below is the finding, not the declaration.
    return _named(events, "first_token")


class _Span:
    def __init__(self):
        self.events = []

    def event(self, t_s, name, **attrs):
        self.events.append({"t_s": t_s, "name": name, **attrs})


class _Tracer:
    def __init__(self):
        self.span = _Span()

    def _event(self, rid, name, **attrs):
        return {"rid": rid, "name": name, **attrs}

    def on_queue(self, now):
        self.span.event(now, "queued", depth=0)

    def on_first_token(self, now):
        # BUG: a typo'd event name the assembler will never anchor on.
        self.span.event(now, "first_tok", ttft_s=0.0)

    def on_preempt(self, now):
        self.span.event(now, "preempted")

    def on_reroute(self, rid):
        # BUG: an event name minted here only — undeclared.
        self._event(rid, "rerouted", replica=1)

    def on_finish(self, rid):
        self._event(rid, "finish", state="finished")
