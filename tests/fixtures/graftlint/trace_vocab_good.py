"""Good twin of ``trace_vocab_bad.py``: the same self-paired shape
with the vocabulary and every call site in agreement — every emitted
event name is declared, every declared name is emitted (or consumed
at a ``_named`` site). Zero findings expected.
"""

TRACE_EVENTS = ("queued", "first_token", "preempted", "finish")


def _named(events, name):
    return [e for e in events if e.get("name") == name]


def anchor(events):
    return _named(events, "first_token")


class _Span:
    def __init__(self):
        self.events = []

    def event(self, t_s, name, **attrs):
        self.events.append({"t_s": t_s, "name": name, **attrs})


class _Tracer:
    def __init__(self):
        self.span = _Span()

    def _event(self, rid, name, **attrs):
        return {"rid": rid, "name": name, **attrs}

    def on_queue(self, now):
        self.span.event(now, "queued", depth=0)

    def on_first_token(self, now):
        self.span.event(now, "first_token", ttft_s=0.0)

    def on_preempt(self, now):
        self.span.event(now, "preempted")

    def on_finish(self, rid):
        self._event(rid, "finish", state="finished")
