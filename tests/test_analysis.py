"""graftlint: the static invariant-analysis suite (ISSUE 10).

Three layers, marker ``analysis``, all tier-1:

1. **Golden fixtures** — every rule flags its seeded-bad fixture
   (including re-creations of the r13 parked-slice drop and the r14
   adapter double-release, the two review-pass bugs the pin-release
   rule exists for) and passes its minimal good twin clean.
2. **Framework semantics** — line/file suppressions, the baseline
   (justified exceptions; stale entries fail), parse-error reporting.
3. **The tree gate** — ``python -m pddl_tpu.analysis --check
   pddl_tpu/`` exits clean from the repo root, stays pure-AST (no jax
   in sys.modules), and runs fast enough for every test run.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import textwrap

import pytest

from pddl_tpu.analysis import (
    DEFAULT_BASELINE,
    apply_baseline,
    load_baseline,
    run_analysis,
)
from pddl_tpu.analysis.checkers import RULES

pytestmark = pytest.mark.analysis

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO_ROOT, "tests", "fixtures", "graftlint")


def findings_for(path, rule=None):
    findings, errors, _ = run_analysis([path])
    assert not errors, errors
    if rule is not None:
        findings = [f for f in findings if f.rule == rule]
    return findings


# ------------------------------------------------------ golden fixtures

# (rule, bad fixture, minimum findings expected from that rule)
BAD_FIXTURES = [
    ("pin-release", "pin_release_bad_r13.py", 3),
    ("pin-release", "pin_release_bad_r14.py", 1),
    # The host-tier promotion twin (ISSUE 13): a fault-unwind that
    # releases the device ids but leaks the pin_chain host pin.
    ("pin-release", "pin_release_bad_hosttier.py", 1),
    ("donation", "donation_bad.py", 2),
    ("recompile-hazard", "recompile_bad.py", 1),
    ("site-vocab", "site_vocab_bad.py", 3),
    # The speculative-site twin (ISSUE 12): verify/draft_prefill
    # counted-but-unlisted (2 findings) + the stale retired "tick" (1).
    ("site-vocab", "site_vocab_bad_spec.py", 3),
    # The storage leg (ISSUE 18): an unmanifested _storage_op gate, a
    # stale manifest entry, and a manifest/StorageFaultPlan.SITES
    # split (one missing + one stale) — 4 findings.
    ("site-vocab", "site_vocab_storage_bad.py", 4),
    ("exposition-parity", "exposition_bad.py", 2),
    ("snapshot-hygiene", "snapshot_bad.py", 1),
    # The journal-manifest twin (ISSUE 14): a WAL record key added
    # without a JOURNAL_VERSION bump — same rule, second wire format.
    ("snapshot-hygiene", "journal_bad.py", 1),
    # The disaggregation vocabularies (ISSUE 17): undeclared record
    # kind + two stale kinds + an unclassified route label + an
    # unclassified literal via at an encode_route call site.
    ("role-vocab", "role_vocab_bad.py", 3),
    # The tracer/assembler event vocabulary (ISSUE 19): a typo'd
    # span.event name + an undeclared _event name + a stale
    # TRACE_EVENTS entry no emitter mints.
    ("trace-vocab", "trace_vocab_bad.py", 3),
    # The fencing-epoch manifest (ISSUE 20): an epoch-stamped command
    # outside EPOCH_CMDS + a stale manifest entry + a FENCED_CMDS
    # mirror drift (extra "pause", missing "restore"/"retire") + a
    # gated command with no dispatch branch.
    ("epoch-vocab", "epoch_vocab_bad.py", 4),
]

GOOD_FIXTURES = [
    "pin_release_good.py", "pin_release_good_hosttier.py",
    "donation_good.py", "recompile_good.py",
    "site_vocab_good.py", "site_vocab_good_spec.py",
    "site_vocab_storage_good.py",
    "exposition_good.py", "snapshot_good.py", "journal_good.py",
    "role_vocab_good.py",
    "trace_vocab_good.py",
    "epoch_vocab_good.py",
]


@pytest.mark.parametrize("rule,fixture,min_findings", BAD_FIXTURES,
                         ids=[f[1] for f in BAD_FIXTURES])
def test_bad_fixture_is_flagged(rule, fixture, min_findings):
    found = findings_for(os.path.join(FIXTURES, fixture), rule)
    assert len(found) >= min_findings, (
        f"{fixture}: expected >= {min_findings} {rule!r} findings, "
        f"got {[f.format() for f in found]}")


@pytest.mark.parametrize("fixture", GOOD_FIXTURES)
def test_good_twin_is_clean(fixture):
    found = findings_for(os.path.join(FIXTURES, fixture))
    assert found == [], [f.format() for f in found]


def test_r13_parked_slice_findings_name_both_leaks():
    """The r13 re-creation leaks a pinned node AND allocated block ids
    on the early-return path; the rule must name both resources."""
    found = findings_for(
        os.path.join(FIXTURES, "pin_release_bad_r13.py"), "pin-release")
    messages = " | ".join(f.message for f in found)
    assert "node" in messages and "private" in messages
    assert any(f.symbol.endswith("start_slice") for f in found)


def test_r14_double_release_is_the_underflow_class():
    found = findings_for(
        os.path.join(FIXTURES, "pin_release_bad_r14.py"), "pin-release")
    assert len(found) == 1
    assert "underflow" in found[0].message
    assert "unpin" in found[0].message


def test_hosttier_promotion_leak_names_the_pinned_tip():
    """The ISSUE 13 class: the fault-unwind released the device ids
    but the ``pin_chain`` host pin escapes the raise — the finding
    must name the leaked tip, and only it (the ids were released)."""
    found = findings_for(
        os.path.join(FIXTURES, "pin_release_bad_hosttier.py"),
        "pin-release")
    messages = " | ".join(f.message for f in found)
    assert "tip" in messages and "pin_chain" in messages
    assert "ids" not in messages.replace("block ids", "")


# ----------------------------------------------- framework semantics


def _write(tmp_path, name, body):
    path = tmp_path / name
    path.write_text(textwrap.dedent(body))
    return str(path)


LEAKY = """
    class E:
        def f(self, prompt):
            node = self.match(prompt)
            self._prefix.pin(node)
            if self._draining:
                return None{suffix}
            self._store[prompt] = node
"""


def test_line_suppression_silences_exactly_that_rule(tmp_path):
    bad = _write(tmp_path, "bad.py", LEAKY.format(suffix=""))
    assert len(findings_for(bad, "pin-release")) == 1
    suppressed = _write(
        tmp_path, "suppressed.py",
        LEAKY.format(suffix="  # graftlint: disable=pin-release"))
    assert findings_for(suppressed) == []
    wrong_rule = _write(
        tmp_path, "wrong.py",
        LEAKY.format(suffix="  # graftlint: disable=donation"))
    assert len(findings_for(wrong_rule, "pin-release")) == 1


def test_file_suppression(tmp_path):
    body = "# graftlint: disable-file=pin-release\n" \
        + textwrap.dedent(LEAKY.format(suffix=""))
    path = tmp_path / "filewide.py"
    path.write_text(body)
    assert findings_for(str(path)) == []


def test_baseline_absorbs_and_stale_entries_surface(tmp_path):
    bad = _write(tmp_path, "bad.py", LEAKY.format(suffix=""))
    findings, _, _ = run_analysis([bad])
    assert len(findings) == 1
    entry = {"rule": findings[0].rule, "path": findings[0].path,
             "symbol": findings[0].symbol,
             "reason": "fixture: justified for the test"}
    kept, used, stale = apply_baseline(findings, [entry])
    assert kept == [] and len(used) == 1 and stale == []
    # A stale entry (nothing matches) must surface so the baseline can
    # only shrink honestly.
    ghost = dict(entry, symbol="E.nonexistent")
    kept, used, stale = apply_baseline(findings, [entry, ghost])
    assert kept == [] and stale == [ghost]


def test_baseline_rejects_unjustified_entries(tmp_path):
    path = tmp_path / "baseline.json"
    path.write_text(json.dumps(
        [{"rule": "pin-release", "path": "x.py", "symbol": "f",
          "reason": "   "}]))
    with pytest.raises(ValueError, match="reason"):
        load_baseline(str(path))


def test_parse_errors_are_reported_not_swallowed(tmp_path):
    path = tmp_path / "broken.py"
    path.write_text("def f(:\n")
    findings, errors, _ = run_analysis([str(path)])
    assert findings == []
    assert len(errors) == 1 and "broken.py" in errors[0]


def test_every_registered_rule_has_a_fixture_pair():
    """Adding a checker without golden fixtures fails here, not in
    review."""
    covered = {rule for rule, _, _ in BAD_FIXTURES}
    assert covered == {cls.name for cls in RULES}


# ---------------------------------------------------------- tree gate


def test_repo_baseline_is_valid_and_justified():
    for entry in load_baseline(DEFAULT_BASELINE):
        assert entry["reason"].strip()


def test_tree_is_clean_via_cli_and_imports_no_jax():
    """THE gate: `python -m pddl_tpu.analysis --check pddl_tpu/` exits
    clean from the repo root, and the whole run never imports jax —
    the pure-AST contract that keeps it safe and fast inside tier-1."""
    code = (
        "import sys, pddl_tpu.analysis.__main__ as m; "
        "rc = m.main(['--check', 'pddl_tpu/']); "
        "assert 'jax' not in sys.modules, 'analysis imported jax'; "
        "sys.exit(rc)"
    )
    proc = subprocess.run(
        [sys.executable, "-c", code], cwd=REPO_ROOT,
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, (
        f"graftlint found unsuppressed/unbaselined findings:\n"
        f"{proc.stdout}\n{proc.stderr}")


def test_cli_fails_loudly_on_a_seeded_bug(tmp_path):
    bad = _write(tmp_path, "bad.py", LEAKY.format(suffix=""))
    proc = subprocess.run(
        [sys.executable, "-m", "pddl_tpu.analysis", "--check", bad],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 1
    assert "pin-release" in proc.stdout


def test_artifact_vocab_gap_is_flagged(tmp_path):
    """The (b) half of snapshot-hygiene: a committed artifact headline
    key (``*_x`` / ``*tok_s``) that gets no direction from the
    bench_artifact vocabulary is a metric the perf gate silently
    skips."""
    from pddl_tpu.analysis.checkers.snapshot_vocab import (
        SnapshotHygieneRule,
    )

    art = tmp_path / "r99_bench.json"
    art.write_text(json.dumps({
        "metric": "x", "results": {
            "frobnication_x": 1.7,          # no vocabulary rule -> flag
            "decode_tok_s": 912.0,          # covered by "tok_s"
            "warmup_s_spread_pct": 2.0,     # _NEVER'd -> deliberate
        }}))
    vocab = os.path.join(REPO_ROOT, "pddl_tpu", "utils",
                         "bench_artifact.py")
    rule = SnapshotHygieneRule(artifacts_root=str(tmp_path))
    findings, errors, _ = run_analysis([vocab], rules=[rule])
    assert not errors
    flagged = [f for f in findings if "frobnication_x" in f.message]
    assert len(flagged) == 1, [f.format() for f in findings]
    assert not any("decode_tok_s" in f.message for f in findings)
    assert not any("spread" in f.message for f in findings)


def test_cli_rules_filter():
    proc = subprocess.run(
        [sys.executable, "-m", "pddl_tpu.analysis", "--list-rules"],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0
    for cls in RULES:
        assert cls.name in proc.stdout


def test_cli_exit_codes_distinguish_broken_run_from_findings(tmp_path):
    """0 = clean, 1 = findings, 2 = the gate never really ran (bad
    path / unparseable file) — a CI wrapper must be able to tell a
    vacuous green from a real one."""
    proc = subprocess.run(
        [sys.executable, "-m", "pddl_tpu.analysis", "--check",
         "no_such_dir_xyz/"],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=60)
    assert proc.returncode == 2
    assert "no such file" in proc.stderr
    broken = tmp_path / "broken.py"
    broken.write_text("def f(:\n")
    proc = subprocess.run(
        [sys.executable, "-m", "pddl_tpu.analysis", "--check",
         str(broken)],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=60)
    assert proc.returncode == 2
    empty = tmp_path / "empty_dir"
    empty.mkdir()
    proc = subprocess.run(
        [sys.executable, "-m", "pddl_tpu.analysis", "--check",
         str(empty)],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=60)
    assert proc.returncode == 2
    assert "no Python files" in proc.stderr


def test_suppression_honored_on_lazily_loaded_companion(tmp_path):
    """A suppression in a companion module resolved through
    module_by_suffix (e.g. the faults file paired with an engine) must
    work even when only the engine file is on the command line —
    targeted and full-tree runs must agree."""
    engine = _write(tmp_path, "engine.py", """
        class Engine:
            def compile_counts(self):
                return {"tick": 1}

            def step(self):
                return self._device_call("tick", self._tick_p)
    """)
    _write(tmp_path, "faults.py", """
        class FaultPlan:
            SITES = ("tick", "stale_site")  # graftlint: disable=site-vocab
    """)
    import pddl_tpu.analysis.checkers.site_vocab as sv

    old_pairs = sv.ENGINE_FAULTS_PAIRS
    sv.ENGINE_FAULTS_PAIRS = (("engine.py", "faults.py"),)
    try:
        findings, errors, _ = run_analysis([engine], root=str(tmp_path))
        assert not errors
        assert findings == [], [f.format() for f in findings]
    finally:
        sv.ENGINE_FAULTS_PAIRS = old_pairs


def test_recompile_rule_covers_jit_of_partial(tmp_path):
    src = """
        import jax
        from functools import partial

        def build(req):
            def _tick(params, cache):
                return cache * req.temperature
            return jax.jit(partial(_tick, 1))
    """
    path = _write(tmp_path, "m.py", src)
    found = findings_for(path, "recompile-hazard")
    assert len(found) == 1 and "req.temperature" in found[0].message


def test_try_finally_release_is_not_a_leak(tmp_path):
    """Python runs ``finally`` before a return/raise completes, so the
    canonical cleanup idiom must lint clean — and a finally that
    releases only half the obligations must still flag the rest."""
    clean = _write(tmp_path, "clean.py", """
        class E:
            def f(self, n):
                ids = self._pool.allocate(n)
                try:
                    if self.bad:
                        raise RuntimeError("nope")
                    return 1
                finally:
                    self._pool.release(ids)
    """)
    assert findings_for(clean) == [], \
        [f.format() for f in findings_for(clean)]
    partial = _write(tmp_path, "partial.py", """
        class E:
            def f(self, prompt, n):
                node = self.match(prompt)
                self._prefix.pin(node)
                ids = self._prefix.allocate(n)
                try:
                    if self.bad:
                        raise RuntimeError("nope")
                    return 1
                finally:
                    self._prefix.release(ids)
    """)
    found = findings_for(partial, "pin-release")
    assert found and all("node" in f.message for f in found), \
        [f.format() for f in found]


def test_scoped_run_does_not_report_out_of_scope_baseline_stale(tmp_path):
    """A --rules/single-file run must not demand removal of a baseline
    entry whose path/rule it never re-observed."""
    bad = _write(tmp_path, "bad.py", LEAKY.format(suffix=""))
    findings, _, analyzed = run_analysis([bad])
    out_of_scope = {"rule": "pin-release", "path": "other/engine.py",
                    "symbol": "E.g", "reason": "justified elsewhere"}
    kept, used, stale = apply_baseline(
        findings, [out_of_scope], analyzed_paths=analyzed,
        active_rules={"pin-release"})
    assert stale == [] and used == []
    wrong_rule = {"rule": "donation", "path": findings[0].path,
                  "symbol": findings[0].symbol, "reason": "x"}
    kept, used, stale = apply_baseline(
        findings, [wrong_rule], analyzed_paths=analyzed,
        active_rules={"pin-release"})
    assert stale == []
    # In scope and unmatched -> still stale (the honesty property).
    ghost = {"rule": "pin-release", "path": findings[0].path,
             "symbol": "E.nonexistent", "reason": "x"}
    kept, used, stale = apply_baseline(
        findings, [ghost], analyzed_paths=analyzed,
        active_rules={"pin-release"})
    assert stale == [ghost]


def test_donation_rule_ignores_sibling_branch_reads(tmp_path):
    """A donate in one arm of an if must not flag a read in the
    mutually-exclusive other arm — the structural continuation walk
    replaces the old flat source-order scan."""
    src = """
        import jax

        class E:
            def build(self, step):
                self._step_p = jax.jit(step, donate_argnums=(0,))

            def run(self, batch, log):
                if log:
                    out = self._step_p(self._state, batch)
                    return out
                return self._render(self._state)
    """
    path = _write(tmp_path, "m.py", src)
    assert findings_for(path, "donation") == [], \
        [f.format() for f in findings_for(path, "donation")]


def test_non_python_path_argument_is_an_error(tmp_path):
    notes = tmp_path / "notes.txt"
    notes.write_text("hello")
    proc = subprocess.run(
        [sys.executable, "-m", "pddl_tpu.analysis", "--check",
         "pddl_tpu/analysis/core.py", str(notes)],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=60)
    assert proc.returncode == 2
    assert "not a Python source file" in proc.stderr


def test_duplicate_baseline_entries_rejected(tmp_path):
    entry = {"rule": "pin-release", "path": "x.py", "symbol": "f",
             "reason": "justified"}
    path = tmp_path / "baseline.json"
    path.write_text(json.dumps([entry, dict(entry)]))
    with pytest.raises(ValueError, match="duplicate"):
        load_baseline(str(path))
