"""Attention numerics: flash kernel and ring attention vs the reference
oracle, plus ViT end-to-end training (the long-context stack, SURVEY.md §5)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pddl_tpu.core.mesh import has_vma_checking, shard_map
from pddl_tpu.ops.attention import attention_reference, flash_attention
from pddl_tpu.ops.ring_attention import (
    ring_attention,
    sequence_parallel_attention,
)


def _qkv(b=2, h=2, s=256, d=64, dtype=jnp.float32, seed=0):
    kq, kk, kv = jax.random.split(jax.random.key(seed), 3)
    return (jax.random.normal(kq, (b, h, s, d), dtype),
            jax.random.normal(kk, (b, h, s, d), dtype),
            jax.random.normal(kv, (b, h, s, d), dtype))


@pytest.mark.parametrize("causal", [False, True])
def test_flash_matches_reference(causal):
    q, k, v = _qkv(s=256, d=64)
    ref = attention_reference(q, k, v, causal=causal)
    out = flash_attention(q, k, v, causal=causal, block_q=128, block_k=128)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_flash_small_blocks():
    q, k, v = _qkv(s=64, d=32)
    ref = attention_reference(q, k, v, causal=True)
    out = flash_attention(q, k, v, causal=True, block_q=16, block_k=16)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_gradients_match_reference(causal):
    """The fused Pallas backward (dq/dk/dv kernels) vs AD of the oracle."""
    q, k, v = _qkv(s=64, d=32)

    def loss_flash(q, k, v):
        # Non-uniform cotangent so dq/dk/dv all get exercised non-trivially.
        out = flash_attention(q, k, v, causal=causal, block_q=32, block_k=32)
        return (out * jnp.cos(jnp.arange(out.size).reshape(out.shape))).sum()

    def loss_ref(q, k, v):
        out = attention_reference(q, k, v, causal=causal)
        return (out * jnp.cos(jnp.arange(out.size).reshape(out.shape))).sum()

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-4, rtol=1e-4)


def test_flash_gradients_rectangular_and_multiblock():
    """sq != sk and several blocks per sweep (accumulator reuse paths)."""
    kq, kk, kv = jax.random.split(jax.random.key(7), 3)
    q = jax.random.normal(kq, (1, 2, 96, 32))
    k = jax.random.normal(kk, (1, 2, 160, 32))
    v = jax.random.normal(kv, (1, 2, 160, 32))

    gf = jax.grad(lambda *a: flash_attention(
        *a, block_q=32, block_k=32).sum(), argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(lambda *a: attention_reference(*a).sum(),
                  argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-4, rtol=1e-4)


def test_flash_second_order_via_reference_fallback():
    """Hessian-vector products: the fused Pallas backward is first-order
    only; fused_backward=False routes through the any-order reference path."""
    q, k, v = _qkv(b=1, h=1, s=32, d=16)

    def inner(q):
        return flash_attention(q, k, v, fused_backward=False).sum()

    hvp = jax.grad(lambda q_: jax.grad(inner)(q_).sum())(q)
    ref_hvp = jax.grad(
        lambda q_: jax.grad(
            lambda q2: attention_reference(q2, k, v).sum())(q_).sum())(q)
    np.testing.assert_allclose(np.asarray(hvp), np.asarray(ref_hvp),
                               atol=1e-5, rtol=1e-5)


def test_flash_gradients_bf16():
    q, k, v = _qkv(s=128, d=64, dtype=jnp.bfloat16)

    gf = jax.grad(lambda *a: flash_attention(
        *a, causal=True, block_q=64, block_k=64).astype(jnp.float32).sum(),
        argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(lambda *a: attention_reference(
        *a, causal=True).astype(jnp.float32).sum(), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        assert a.dtype == jnp.bfloat16
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32),
                                   atol=5e-2, rtol=5e-2)


def test_flash_bf16_close_to_f32():
    q, k, v = _qkv(s=128, d=64, dtype=jnp.bfloat16)
    ref = attention_reference(
        q.astype(jnp.float32), k.astype(jnp.float32), v.astype(jnp.float32))
    out = flash_attention(q, k, v, block_q=64, block_k=64)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(out, np.float32), np.asarray(ref),
                               atol=3e-2, rtol=3e-2)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_full(mesh8, causal):
    """8-way sequence-sharded ring attention == full attention, exactly the
    long-context guarantee: no device ever holds the whole sequence."""
    q, k, v = _qkv(b=1, h=2, s=128, d=16)
    ref = attention_reference(q, k, v, causal=causal)

    # Rebuild the mesh with all 8 devices on the seq axis.
    from pddl_tpu.core.mesh import MeshConfig, build_mesh

    mesh = build_mesh(MeshConfig(data=1, seq=8))
    out = sequence_parallel_attention(q, k, v, mesh, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_gradients_match_full(causal):
    """AD through the ring (ppermute transpose + fori_loop) must equal the
    full-attention gradients — the backward pass of sequence parallelism."""
    from pddl_tpu.core.mesh import MeshConfig, build_mesh

    mesh = build_mesh(MeshConfig(data=1, seq=8))
    q, k, v = _qkv(b=1, h=2, s=128, d=16)

    def loss_ring(q, k, v):
        out = sequence_parallel_attention(q, k, v, mesh, causal=causal)
        return (out * jnp.sin(jnp.arange(out.size).reshape(out.shape))).sum()

    def loss_full(q, k, v):
        out = attention_reference(q, k, v, causal=causal)
        return (out * jnp.sin(jnp.arange(out.size).reshape(out.shape))).sum()

    gr = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    gf = jax.grad(loss_full, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gr, gf):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-5, rtol=2e-5)


def test_ring_attention_single_shard_degenerates_to_full():
    from jax.sharding import PartitionSpec as P
    from pddl_tpu.core.mesh import MeshConfig, build_mesh

    mesh = build_mesh(MeshConfig(data=8, seq=1))
    q, k, v = _qkv(b=1, h=1, s=32, d=8)
    spec = P(None, None, "seq", None)
    out = shard_map(
        lambda q, k, v: ring_attention(q, k, v, axis_name="seq"),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
    )(q, k, v)
    ref = attention_reference(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_vit_trains_on_synthetic():
    from pddl_tpu.data.synthetic import SyntheticImageClassification
    from pddl_tpu.models.vit import tiny_vit
    from pddl_tpu.parallel.mirrored import MirroredStrategy
    from pddl_tpu.train.loop import Trainer

    tr = Trainer(tiny_vit(num_classes=8), optimizer="adamw",
                 learning_rate=1e-3, strategy=MirroredStrategy())
    ds = SyntheticImageClassification(batch_size=16, image_size=32,
                                      num_classes=8, seed=5)
    hist = tr.fit(ds, epochs=2, steps_per_epoch=4, verbose=0)
    assert hist.history["loss"][-1] < hist.history["loss"][0]


def test_vit_registry_and_config_path():
    from pddl_tpu.config import ExperimentConfig
    from pddl_tpu.run import run_experiment

    cfg = ExperimentConfig(
        model="tiny_vit", num_classes=8, image_size=32, crop=32,
        per_replica_batch=2, epochs=1, strategy="mirrored",
        compute_dtype="float32", verbose=0,
        reduce_lr_on_plateau=False, early_stopping=False,
    )
    hist = run_experiment(cfg, steps_per_epoch=2, validation_steps=1)
    assert np.isfinite(hist.history["loss"][-1])


def test_remat_policies_numerics_and_grads():
    """Remat must change memory, never numbers: forward and gradients
    identical across none/dots/full for ViT and GPT."""
    import jax
    import jax.numpy as jnp

    from pddl_tpu.models.gpt import tiny_gpt
    from pddl_tpu.models.vit import ViT

    x_img = jnp.linspace(0, 1, 2 * 16 * 16 * 3).reshape(2, 16, 16, 3)
    tokens = jnp.arange(2 * 16, dtype=jnp.int32).reshape(2, 16) % 32

    def check(make, inp):
        base = make("none")
        variables = base.init(jax.random.key(0), inp, train=False)

        def loss(m):
            def f(params):
                out = m.apply({"params": params}, inp, train=True)
                return jnp.sum(out.astype(jnp.float32) ** 2)
            return f

        ref_val, ref_grad = jax.value_and_grad(loss(base))(variables["params"])
        for policy in ("dots", "full"):
            m = make(policy)
            val, grad = jax.value_and_grad(loss(m))(variables["params"])
            np.testing.assert_allclose(float(val), float(ref_val),
                                       rtol=1e-5)
            for a, b in zip(jax.tree.leaves(grad),
                            jax.tree.leaves(ref_grad)):
                # atol covers XLA-version rematerialization reassociation
                # (older CPU backends land ~1e-5 off on isolated elements).
                np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                           rtol=1e-4, atol=5e-5)

    check(lambda r: ViT(patch_size=4, embed_dim=32, depth=2, num_heads=4,
                        num_classes=8, attention="reference", remat=r),
          x_img)
    check(lambda r: tiny_gpt(vocab_size=32, max_len=32, remat=r), tokens)

    import pytest

    with pytest.raises(ValueError, match="remat"):
        from pddl_tpu.models.vit import remat_block, TransformerBlock
        remat_block(TransformerBlock, "bogus")


def test_flash_attention_lse_matches_reference():
    from pddl_tpu.ops.attention import (
        _attention_reference_lse,
        flash_attention_lse,
    )

    B, H, S, D = 2, 2, 64, 16
    q, k, v = (jax.random.normal(jax.random.key(i), (B, H, S, D))
               for i in range(3))
    for causal in (False, True):
        o1, l1 = flash_attention_lse(q, k, v, causal=causal)
        o2, l2 = _attention_reference_lse(q, k, v, causal, D ** -0.5)
        np.testing.assert_allclose(np.asarray(o1), np.asarray(o2),
                                   atol=2e-5, rtol=2e-5)
        np.testing.assert_allclose(np.asarray(l1), np.asarray(l2),
                                   atol=2e-5, rtol=2e-5)

        # Gradients INCLUDING through the lse output (dlse folds into the
        # fused backward's row term).
        def loss(fn, qq):
            o, l = fn(qq)
            return (o.sum() + 0.3 * l.sum()).astype(jnp.float32)

        g1 = jax.grad(lambda qq: loss(
            lambda x: flash_attention_lse(x, k, v, causal=causal), qq))(q)
        g2 = jax.grad(lambda qq: loss(
            lambda x: _attention_reference_lse(x, k, v, causal, D ** -0.5),
            qq))(q)
        np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                                   atol=3e-5, rtol=3e-5)


@pytest.mark.slow  # multi-hop pallas-interpret loop: tier-2 wall-clock
def test_flash_ring_matches_reference_and_xla_ring(mesh8):
    """Flash-per-rotation ring == XLA-einsum ring == full attention,
    forward AND gradients, causal and not."""
    from pddl_tpu.core.mesh import MeshConfig, build_mesh
    from pddl_tpu.ops.attention import attention_reference
    from pddl_tpu.ops.ring_attention import sequence_parallel_attention

    mesh = build_mesh(MeshConfig(data=1, seq=8))
    B, H, S, D = 1, 2, 64, 16
    q, k, v = (jax.random.normal(jax.random.key(10 + i), (B, H, S, D))
               for i in range(3))
    for causal in (False, True):
        ref = attention_reference(q, k, v, causal=causal)
        flash_ring = jax.jit(lambda a, b, c: sequence_parallel_attention(
            a, b, c, mesh, causal=causal, use_flash=True))(q, k, v)
        np.testing.assert_allclose(np.asarray(flash_ring), np.asarray(ref),
                                   atol=2e-4, rtol=2e-4)

        xla_ring = jax.jit(lambda a, b, c: sequence_parallel_attention(
            a, b, c, mesh, causal=causal, use_flash=False))(q, k, v)
        np.testing.assert_allclose(np.asarray(flash_ring),
                                   np.asarray(xla_ring),
                                   atol=2e-4, rtol=2e-4)

        # Gradients w.r.t. ALL inputs (dk/dv cross the ppermute transpose
        # and carry the dlse fold through the dkv kernel too).
        g_ref = jax.grad(lambda a, b, c: attention_reference(
            a, b, c, causal=causal).astype(jnp.float32).sum(),
            argnums=(0, 1, 2))(q, k, v)
        g_ring = jax.grad(lambda a, b, c: sequence_parallel_attention(
            a, b, c, mesh, causal=causal, use_flash=True)
            .astype(jnp.float32).sum(), argnums=(0, 1, 2))(q, k, v)
        for gr, gf in zip(g_ref, g_ring):
            np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                                       atol=3e-4, rtol=3e-4)


@pytest.mark.skipif(not has_vma_checking(),
                    reason="pre-vma jax: the legacy check_rep "
                           "checker is disabled by the compat "
                           "shard_map, so there is no checker "
                           "behaviour to pin")
def test_flash_ring_check_vma_limitation():
    """Pin WHY the flash ring runs with check_vma=False (VERDICT r1 weak #5).

    The ring itself is branch-free (the pallas call sits in straight-line
    shard_map code), but jax's varying-axes checker cannot propagate
    through the pallas kernel: its internal dynamic_slices combine varying
    ref data with invariant grid indices, and the checker raises the
    upstream 'varying manual axes to match' ValueError whose own message
    prescribes check_vma=False. When a jax upgrade makes this test FAIL
    (the checked call succeeds), flip use_flash to run checked in
    sequence_parallel_attention and delete this test."""
    import functools

    from jax.sharding import PartitionSpec as P

    from pddl_tpu.core.mesh import MeshConfig, build_mesh
    from pddl_tpu.ops.ring_attention import ring_attention_flash

    mesh = build_mesh(MeshConfig(data=1, seq=8))
    B, H, S, D = 1, 2, 64, 16
    q, k, v = (jax.random.normal(jax.random.key(20 + i), (B, H, S, D))
               for i in range(3))
    spec = P(None, None, "seq", None)
    checked = shard_map(
        functools.partial(ring_attention_flash, axis_name="seq", causal=True),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=True,
    )
    with pytest.raises(ValueError, match="varying manual axes"):
        jax.jit(checked)(q, k, v)


def test_tuned_blocks_resolution():
    """Defaults resolve per device generation; explicit args still win."""
    from pddl_tpu.ops.attention import TUNED_BLOCKS, tuned_blocks

    bq, bk = tuned_blocks()
    assert bq >= 8 and bk >= 8
    # Unknown generations (this CPU test backend included) fall back to
    # the measured v5e pair rather than failing.
    assert (bq, bk) == TUNED_BLOCKS.get(
        jax.devices()[0].device_kind, (512, 1024))

    # None-defaulted call == explicit tuned call, bitwise.
    q, k, v = (jax.random.normal(jax.random.key(i), (1, 2, 256, 16))
               for i in range(3))
    auto = flash_attention(q, k, v, causal=True)
    explicit = flash_attention(q, k, v, causal=True, block_q=bq, block_k=bk)
    np.testing.assert_array_equal(np.asarray(auto), np.asarray(explicit))


# ------------------------------------------------------- sliding window
def test_flash_sliding_window_matches_reference():
    """Flash SWA vs the windowed reference oracle, with blocks small
    enough that whole k-blocks are skipped below the band (the O(S*W)
    path), windows aligned and unaligned to the block size."""
    q, k, v = (jax.random.normal(jax.random.key(i), (2, 2, 256, 32))
               for i in range(3))
    for w in (1, 37, 64, 200):
        ref = attention_reference(q, k, v, causal=True, window=w)
        got = flash_attention(q, k, v, causal=True, window=w,
                              block_q=64, block_k=64)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5, err_msg=f"w={w}")


def test_flash_sliding_window_grads_match_reference():
    q, k, v = (jax.random.normal(jax.random.key(i), (1, 2, 256, 32))
               for i in range(3))

    for w in (37, 128):
        def loss_flash(q, k, v, w=w):
            return flash_attention(q, k, v, causal=True, window=w,
                                   block_q=64, block_k=64).sum()

        def loss_ref(q, k, v, w=w):
            return attention_reference(q, k, v, causal=True, window=w).sum()

        gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gf, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=2e-4, rtol=2e-4,
                                       err_msg=f"w={w}")


def test_window_geq_seq_degrades_to_plain_causal():
    q, k, v = (jax.random.normal(jax.random.key(i), (1, 2, 64, 32))
               for i in range(3))
    plain = flash_attention(q, k, v, causal=True)
    wide = flash_attention(q, k, v, causal=True, window=64)
    np.testing.assert_array_equal(np.asarray(plain), np.asarray(wide))


# -------------------------------------------------- grouped-query (GQA)
def _tiled(t, rep):
    """Oracle-side expansion: repeat each kv head rep times (what the
    kernels must now match WITHOUT materializing)."""
    return jnp.repeat(t, rep, axis=1)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("rep", [2, 4])
def test_flash_gqa_matches_expanded_reference(causal, rep):
    """Flash with unexpanded [B, H_kv, S, D] K/V == MHA flash on the
    jnp.repeat-expanded K/V — the no-copy GQA path's core guarantee."""
    kq, kk, kv = jax.random.split(jax.random.key(3), 3)
    b, h, s, d = 2, 4, 256, 64
    q = jax.random.normal(kq, (b, h, s, d))
    k = jax.random.normal(kk, (b, h // rep, s, d))
    v = jax.random.normal(kv, (b, h // rep, s, d))
    ref = attention_reference(q, _tiled(k, rep), _tiled(v, rep),
                              causal=causal)
    grouped_ref = attention_reference(q, k, v, causal=causal)
    out = flash_attention(q, k, v, causal=causal, block_q=64, block_k=64)
    np.testing.assert_allclose(np.asarray(grouped_ref), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_gqa_gradients_match_expanded_reference(causal):
    """dq at query-head shape; dk/dv at KV-head shape must equal the
    group-sum of the expanded oracle's per-head gradients (the kernel
    accumulates the query group in its dkv sweep)."""
    rep = 2
    kq, kk, kv = jax.random.split(jax.random.key(5), 3)
    b, h, s, d = 1, 4, 128, 32
    q = jax.random.normal(kq, (b, h, s, d))
    k = jax.random.normal(kk, (b, h // rep, s, d))
    v = jax.random.normal(kv, (b, h // rep, s, d))
    cot = jnp.cos(jnp.arange(b * h * s * d, dtype=jnp.float32)
                  ).reshape(b, h, s, d)

    def loss_flash(q, k, v):
        o = flash_attention(q, k, v, causal=causal, block_q=32, block_k=32)
        return (o * cot).sum()

    def loss_ref(q, k, v):
        o = attention_reference(q, _tiled(k, rep), _tiled(v, rep),
                                causal=causal)
        return (o * cot).sum()

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    # Differentiating through jnp.repeat group-sums dk/dv automatically
    # (repeat's transpose), so oracle grads land at kv-head shape too.
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for got, want, name in zip(gf, gr, "qkv"):
        assert got.shape == want.shape
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-4, rtol=1e-4,
                                   err_msg=f"d{name}")


def test_flash_gqa_sliding_window():
    """GQA × SWA through the flash kernel (band skip composes with the
    kv-head index maps)."""
    kq, kk, kv = jax.random.split(jax.random.key(8), 3)
    q = jax.random.normal(kq, (1, 6, 256, 32))
    k = jax.random.normal(kk, (1, 2, 256, 32))
    v = jax.random.normal(kv, (1, 2, 256, 32))
    for w in (37, 128):
        ref = attention_reference(q, _tiled(k, 3), _tiled(v, 3),
                                  causal=True, window=w)
        got = flash_attention(q, k, v, causal=True, window=w,
                              block_q=64, block_k=64)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5, err_msg=f"w={w}")


def test_flash_lse_gqa_matches_reference():
    from pddl_tpu.ops.attention import (
        _attention_reference_lse,
        flash_attention_lse,
    )

    kq, kk, kv = jax.random.split(jax.random.key(9), 3)
    q = jax.random.normal(kq, (1, 4, 64, 16))
    k = jax.random.normal(kk, (1, 2, 64, 16))
    v = jax.random.normal(kv, (1, 2, 64, 16))
    for causal in (False, True):
        o1, l1 = flash_attention_lse(q, k, v, causal=causal,
                                     block_q=32, block_k=32)
        o2, l2 = _attention_reference_lse(q, _tiled(k, 2), _tiled(v, 2),
                                          causal, 16 ** -0.5)
        np.testing.assert_allclose(np.asarray(o1), np.asarray(o2),
                                   atol=2e-5, rtol=2e-5)
        np.testing.assert_allclose(np.asarray(l1), np.asarray(l2),
                                   atol=2e-5, rtol=2e-5)


@pytest.mark.slow  # multi-hop pallas-interpret loop: tier-2 wall-clock
@pytest.mark.parametrize("use_flash", [False, True])
def test_ring_gqa_rotates_unexpanded_kv(mesh8, use_flash):
    """Ring attention with kv-head-sized shards (the ppermute payload is
    H/H_kv-times smaller) == full expanded attention, fwd and grads."""
    from pddl_tpu.core.mesh import MeshConfig, build_mesh

    mesh = build_mesh(MeshConfig(data=1, seq=8))
    kq, kk, kv = jax.random.split(jax.random.key(12), 3)
    q = jax.random.normal(kq, (1, 4, 128, 16))
    k = jax.random.normal(kk, (1, 2, 128, 16))
    v = jax.random.normal(kv, (1, 2, 128, 16))
    for causal in (False, True):
        ref = attention_reference(q, _tiled(k, 2), _tiled(v, 2),
                                  causal=causal)
        out = jax.jit(lambda a, b, c: sequence_parallel_attention(
            a, b, c, mesh, causal=causal, use_flash=use_flash))(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-4, rtol=2e-4)

    # Oracle grads: differentiating THROUGH jnp.repeat already reduces
    # dk/dv over each query group (repeat's transpose is a group-sum), so
    # shapes match the ring's kv-head-sized grads directly.
    g_ref = jax.grad(lambda a, b, c: attention_reference(
        a, _tiled(b, 2), _tiled(c, 2), causal=True).sum(),
        argnums=(0, 1, 2))(q, k, v)
    g_ring = jax.grad(lambda a, b, c: sequence_parallel_attention(
        a, b, c, mesh, causal=True, use_flash=use_flash).sum(),
        argnums=(0, 1, 2))(q, k, v)
    for got, want in zip(g_ring, g_ref):
        assert got.shape == want.shape
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=3e-4, rtol=3e-4)


@pytest.mark.parametrize("gqa", [False, True])
def test_fused_and_twosweep_backwards_agree(monkeypatch, gqa):
    """The single-sweep fused backward (default) and the two-sweep
    fallback (forced via a zero dq-scratch budget) must produce the same
    gradients — the fallback exists only for sequences whose dq
    accumulator exceeds VMEM."""
    import pddl_tpu.ops.attention as A

    kq, kk, kv = jax.random.split(jax.random.key(31), 3)
    hkv = 2 if gqa else 4
    q = jax.random.normal(kq, (1, 4, 128, 32))
    k = jax.random.normal(kk, (1, hkv, 128, 32))
    v = jax.random.normal(kv, (1, hkv, 128, 32))

    def grads():
        return jax.grad(lambda *a: flash_attention(
            *a, causal=True, window=50, block_q=32, block_k=32).sum(),
            argnums=(0, 1, 2))(q, k, v)

    fused = grads()
    monkeypatch.setattr(A, "_FUSED_BWD_DQ_BYTES", 0)
    twosweep = grads()
    for a, b, name in zip(fused, twosweep, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-5, rtol=1e-5,
                                   err_msg=f"d{name}")


def test_decode_attention_linear_and_rolling_match_oracle():
    """The serving sweep (bf16-style storage reads, grouped heads,
    prefix-bounded fori_loop, ring-buffer slot mapping) vs plain windowed
    attention over the true key history."""
    from pddl_tpu.ops.attention import decode_attention

    B, Hkv, rep, D = 1, 2, 3, 16
    H = Hkv * rep
    ring, window, T = 128, 100, 300  # cache wrapped twice
    kk, kv, kq = jax.random.split(jax.random.key(21), 3)
    keys = jax.random.normal(kk, (B, Hkv, T, D))
    vals = jax.random.normal(kv, (B, Hkv, T, D))
    q = jax.random.normal(kq, (B, H, 1, D))

    # Oracle: the current token (position T-1) attends over the real
    # history under the window.
    ref = attention_reference(q, keys, vals, causal=True, window=window,
                              k_offset=-(T - 1))

    # Linear cache: history at slots 0..T-1, padded tail beyond.
    k_lin = jnp.zeros((B, Hkv, 512, D)).at[:, :, :T].set(keys)
    v_lin = jnp.zeros((B, Hkv, 512, D)).at[:, :, :T].set(vals)
    out_lin = decode_attention(q, k_lin, v_lin, jnp.int32(T - 1),
                               window=window, chunk=128)
    np.testing.assert_allclose(np.asarray(out_lin), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)

    # Ring cache: slot j holds the newest position ≡ j (mod ring).
    slots = jnp.arange(T) % ring
    k_ring = jnp.zeros((B, Hkv, ring, D)).at[:, :, slots].set(keys)
    v_ring = jnp.zeros((B, Hkv, ring, D)).at[:, :, slots].set(vals)
    out_ring = decode_attention(q, k_ring, v_ring, jnp.int32(T - 1),
                                window=window, rolling=True, chunk=64)
    np.testing.assert_allclose(np.asarray(out_ring), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_decode_attention_chunk_not_dividing_cache():
    """A cache length the chunk doesn't divide (prime-ish max_decode_len)
    must stay exact: the tail chunk clamps its slice start and masks the
    re-read overlap — never degrading to a chunk=1 sweep."""
    from pddl_tpu.ops.attention import decode_attention

    B, H, D, L, T = 1, 2, 16, 331, 331  # prime cache length, fully live
    kk, kv, kq = jax.random.split(jax.random.key(6), 3)
    keys = jax.random.normal(kk, (B, H, T, D))
    vals = jax.random.normal(kv, (B, H, T, D))
    q = jax.random.normal(kq, (B, H, 1, D))
    ref = attention_reference(q, keys, vals, causal=True,
                              k_offset=-(T - 1))
    out = decode_attention(q, keys, vals, jnp.int32(T - 1), chunk=128)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_decode_attention_empty_history_returns_zero_weight():
    """history_only at index 0 (nothing attended yet) must yield zero
    output and ~-inf lse on BOTH the single-shot and chunked paths — a
    fully-masked fused pass would otherwise average the raw cache (the
    masked-softmax exp(0) pitfall)."""
    from pddl_tpu.ops.attention import decode_attention

    kq = jax.random.key(2)
    q = jax.random.normal(kq, (1, 2, 1, 8))
    cache = jnp.full((1, 2, 64, 8), 7.0)  # garbage that must not leak
    for chunk in (64, 16):  # single-shot and chunked
        out, lse = decode_attention(q, cache, cache, jnp.int32(0),
                                    history_only=True, return_lse=True,
                                    chunk=chunk)
        np.testing.assert_array_equal(np.asarray(out), 0.0)
        assert float(lse.max()) < -1e29


def test_decode_attention_prefix_bound_ignores_cache_garbage():
    """Slots beyond the valid prefix must never influence the output —
    the fori_loop stops at the last live chunk and masking covers the
    partial one (huge garbage planted past the prefix stays inert)."""
    from pddl_tpu.ops.attention import decode_attention

    B, H, D, L, T = 1, 2, 8, 256, 70
    kk, kv, kq = jax.random.split(jax.random.key(4), 3)
    keys = jax.random.normal(kk, (B, H, T, D))
    vals = jax.random.normal(kv, (B, H, T, D))
    q = jax.random.normal(kq, (B, H, 1, D))
    k_cache = jnp.full((B, H, L, D), 1e30).at[:, :, :T].set(keys)
    v_cache = jnp.full((B, H, L, D), 1e30).at[:, :, :T].set(vals)
    out = decode_attention(q, k_cache, v_cache, jnp.int32(T - 1), chunk=64)
    ref = attention_reference(q, keys, vals, causal=True,
                              k_offset=-(T - 1))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.slow  # multi-hop pallas-interpret loop: tier-2 wall-clock
@pytest.mark.parametrize("use_flash", [False, True])
def test_ring_swa_gqa_matches_windowed_reference(mesh8, use_flash):
    """Ring × SWA × GQA (VERDICT r3 task 4): the full composition —
    sequence-sharded ring rotating unexpanded kv-head shards with a
    sliding window that skips out-of-band rotations — fwd and grads vs
    the windowed grouped oracle. Windows aligned and unaligned to the
    16-position shard size, including one so narrow (w=5) that most
    rotations are skipped outright."""
    from pddl_tpu.core.mesh import MeshConfig, build_mesh

    mesh = build_mesh(MeshConfig(data=1, seq=8))
    kq, kk, kv = jax.random.split(jax.random.key(14), 3)
    q = jax.random.normal(kq, (1, 4, 128, 16))   # s_local = 16
    k = jax.random.normal(kk, (1, 2, 128, 16))
    v = jax.random.normal(kv, (1, 2, 128, 16))
    for w in (5, 16, 37, 100):
        ref = attention_reference(q, k, v, causal=True, window=w)
        got = jax.jit(lambda a, b, c, w=w: sequence_parallel_attention(
            a, b, c, mesh, causal=True, window=w,
            use_flash=use_flash))(q, k, v)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   atol=2e-4, rtol=2e-4, err_msg=f"w={w}")

    w = 37
    g_ref = jax.grad(lambda a, b, c: attention_reference(
        a, b, c, causal=True, window=w).sum(), argnums=(0, 1, 2))(q, k, v)
    g_ring = jax.grad(lambda a, b, c: sequence_parallel_attention(
        a, b, c, mesh, causal=True, window=w, use_flash=use_flash).sum(),
        argnums=(0, 1, 2))(q, k, v)
    for got, want, name in zip(g_ring, g_ref, "qkv"):
        assert got.shape == want.shape
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=3e-4, rtol=3e-4,
                                   err_msg=f"d{name} (w={w})")


def test_flash_k_offset_matches_reference():
    """The static k_offset (ring rotations' shifted key positions) in
    the Pallas kernel vs the reference's k_offset masking, fwd + grads."""
    kq, kk, kv = jax.random.split(jax.random.key(15), 3)
    q = jax.random.normal(kq, (1, 2, 64, 16))
    k = jax.random.normal(kk, (1, 2, 64, 16))
    v = jax.random.normal(kv, (1, 2, 64, 16))
    from pddl_tpu.ops.attention import flash_attention_lse

    for off, w in ((-64, 100), (-32, 40), (-64, None)):
        ref = attention_reference(q, k, v, causal=True, window=w,
                                  k_offset=off)
        got, _ = flash_attention_lse(q, k, v, causal=True, window=w,
                                     k_offset=off, block_q=16, block_k=16)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5,
                                   err_msg=f"off={off} w={w}")


def test_gqa_head_divisibility_validated():
    q = jnp.zeros((1, 4, 16, 8))
    k = jnp.zeros((1, 3, 16, 8))
    with pytest.raises(ValueError, match="divisible"):
        flash_attention(q, k, k)
    with pytest.raises(ValueError, match="divisible"):
        attention_reference(q, k, k)


def test_window_requires_causal():
    q = jnp.zeros((1, 1, 16, 8))
    with pytest.raises(ValueError, match="causal"):
        flash_attention(q, q, q, window=4)
    with pytest.raises(ValueError, match="causal"):
        attention_reference(q, q, q, window=4)
    with pytest.raises(ValueError, match=">= 1"):
        flash_attention(q, q, q, causal=True, window=0)
