"""Augmentation parity: rescale / crop-or-pad / random crop / random flip
(`/root/reference/imagenet-resnet50.py:36-41,53-55`)."""

import jax
import jax.numpy as jnp
import numpy as np

from pddl_tpu.ops import augment


def test_rescale():
    x = jnp.full((1, 2, 2, 3), 255.0)
    np.testing.assert_allclose(augment.rescale(x), jnp.ones((1, 2, 2, 3)))


def test_center_crop():
    x = jnp.arange(6 * 6, dtype=jnp.float32).reshape(1, 6, 6, 1)
    out = augment.center_crop_or_pad(x, 4, 4)
    assert out.shape == (1, 4, 4, 1)
    np.testing.assert_allclose(out[0, 0, 0, 0], x[0, 1, 1, 0])


def test_center_pad():
    x = jnp.ones((1, 2, 2, 1))
    out = augment.center_crop_or_pad(x, 4, 4)
    assert out.shape == (1, 4, 4, 1)
    assert float(out.sum()) == 4.0  # original mass preserved
    assert float(out[0, 0, 0, 0]) == 0.0  # padded corner


def test_random_crop_shape_and_content():
    rng = jax.random.key(0)
    x = jax.random.normal(jax.random.key(1), (4, 8, 8, 3))
    out = augment.random_crop(rng, x, 5, 5)
    assert out.shape == (4, 5, 5, 3)
    # every crop window is a contiguous sub-block of the source image
    x0 = np.asarray(x[0, :, :, 0])
    o0 = np.asarray(out[0, :, :, 0])
    found = any(
        np.allclose(x0[i : i + 5, j : j + 5], o0)
        for i in range(4)
        for j in range(4)
    )
    assert found


def test_random_crop_pads_when_target_larger():
    """The reference's RandomCrop(244) on 224 input quirk: we pad instead of
    upscale (SURVEY.md §0 faithfulness fix)."""
    rng = jax.random.key(0)
    x = jnp.ones((2, 4, 4, 1))
    out = augment.random_crop(rng, x, 6, 6)
    assert out.shape == (2, 6, 6, 1)


def test_random_flip_is_flip_or_identity():
    rng = jax.random.key(2)
    x = jax.random.normal(jax.random.key(3), (8, 4, 4, 1))
    out = augment.random_flip_horizontal(rng, x)
    for i in range(8):
        same = np.allclose(out[i], x[i])
        flipped = np.allclose(out[i], jnp.flip(x[i], axis=-2))
        assert same or flipped
    # with 8 images, overwhelmingly likely both outcomes occur
    outcomes = {bool(np.allclose(out[i], x[i])) for i in range(8)}
    assert len(outcomes) == 2


def test_standard_augment_jits():
    fn = jax.jit(augment.standard_augment(crop=3, flip=True))
    out = fn(jax.random.key(0), jnp.ones((2, 5, 5, 3)) * 255.0)
    assert out.shape == (2, 3, 3, 3)
    assert float(out.max()) <= 1.0
