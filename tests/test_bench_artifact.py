"""The perf-trajectory gate (`utils/bench_artifact.py` compare/gate —
ROADMAP item 5): >5% median regressions between committed artifacts at
the same (metric, config) must fail LOUDLY, and the committed
`artifacts/gpt_bench/r*.json` series must currently be regression-free.
"""

import copy
import glob
import os

import pytest

from pddl_tpu.utils.bench_artifact import (
    artifact_key,
    check_series,
    compare,
    load_artifact,
    metric_direction,
    _main,
)

pytestmark = pytest.mark.bench_gate

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_BENCH_DIR = os.path.join(_ROOT, "artifacts", "gpt_bench")


def _record(**results):
    return {"metric": "online_serving_tokens_per_sec",
            "config": {"model": "gpt 4x256", "slots": 8},
            "results": results}


def test_metric_direction_vocabulary():
    assert metric_direction("concurrent_engine_tokens_per_s") == 1
    assert metric_direction("throughput_retained_x") == 1
    assert metric_direction("ttft_p99_s") == -1
    assert metric_direction("mean_ttft_prefix_on_s") == -1
    # Ratio keys beat the latency substring: a bigger TTFT *reduction*
    # is an improvement, not a regression.
    assert metric_direction("ttft_reduction_x") == 1
    # The r12 SLO headlines are covered: goodput up is better, the
    # best_effort shed-absorption fraction up is better, and the
    # interactive TTFT inflation ratio down is better.
    assert metric_direction("interactive_goodput_tokens_per_s") == 1
    assert metric_direction("best_effort_shed_absorbed_frac") == 1
    assert metric_direction(
        "interactive_ttft_p99_overload_over_uncontended_x") == -1
    # The r13 paged-attention headlines: duplicate-KV elimination and
    # cache density up is better, admission TTFT/copy time down is
    # better, and the paged-vs-gather admission ratio is a speedup.
    assert metric_direction("duplicate_kv_eliminated_x") == 1
    assert metric_direction("effective_cached_tokens_per_byte_paged") == 1
    assert metric_direction("hit_admission_ttft_paged_s") == -1
    assert metric_direction("hit_admission_speedup_x") == 1
    assert metric_direction("admission_copy_us_row") == -1
    # The r14 multi-tenant headlines: merged-copy elimination, the
    # mixed-tenant throughput-retained ratio and absolute rate, and the
    # adapter hit rate up are better; the constrained-decode mask
    # overhead down is better.
    assert metric_direction("merged_copy_eliminated_x") == 1
    assert metric_direction("tenant_throughput_retained_x") == 1
    assert metric_direction("mixed_tenant_tok_s") == 1
    assert metric_direction("adapter_hit_rate") == 1
    assert metric_direction("mask_overhead_x") == -1
    # The r16 elastic-autoscaling headlines: goodput per replica-hour
    # (and its vs-best-static ratio) up is better, executed scale
    # events and zero-loss migration coverage up are better, time the
    # brownout ladder spent engaged down is better.
    assert metric_direction("goodput_per_replica_hour") == 1
    assert metric_direction(
        "goodput_per_replica_hour_vs_best_static_x") == 1
    assert metric_direction("scale_events") == 1
    assert metric_direction("migrated_zero_lost") == 1
    assert metric_direction("brownout_rung_time_autoscaled_s") == -1
    # The r17 speculative-serving headlines (ISSUE 12): the spec
    # engine's absolute rate and its paired speedup up are better, and
    # the acceptance rate (draft quality behind the throughput win) up
    # is better; tokens-per-tick rides the "_per_tick" rule.
    assert metric_direction("spec_tok_s") == 1
    assert metric_direction("spec_speedup_x") == 1
    assert metric_direction("acceptance_rate") == 1
    assert metric_direction("spec_acceptance_rate") == 1
    assert metric_direction("tokens_per_tick") == 1
    # The r18 tiered-KV-cache headlines (ISSUE 13): tier hit rates and
    # demotion/promotion traffic up are better (chains saved from
    # recompute), the paired tiered-over-evict TTFT ratio down is
    # better, duplicate prefill tokens down is better, and chain pulls
    # (the fleet-wide eliminator) up are better.
    assert metric_direction("hit_rate_tiered") == 1
    assert metric_direction("host_tier_spills") == 1
    assert metric_direction("host_tier_promotions") == 1
    assert metric_direction("host_tier_promote_tokens_charged") == 1
    assert metric_direction("mean_ttft_ratio_at_8x") == -1
    assert metric_direction("ttft_tiered_over_evict_x") == -1
    assert metric_direction("duplicate_prefill_tokens_blind") == -1
    assert metric_direction("chain_pulls") == 1
    # Raw byte tallies are scale context, not headlines.
    assert metric_direction("kv_bytes_used_row") == 0
    assert metric_direction("host_tier_bytes_resident") == 0
    # Noise keys are never compared.
    assert metric_direction("spread_pct") == 0
    assert metric_direction("ttft_inflation_per_pair") == 0
    assert metric_direction("n_requests") == 0


def test_r13_paged_artifact_is_gated():
    """The paged-attention artifact participates in the series: it
    loads, keys into a (metric, config) group, and its capacity and
    admission headlines are DIRECTIONAL — a future r-record at the
    same config that regresses them fails `check_series` loudly."""
    path = os.path.join(_BENCH_DIR, "r13_serve_paged.json")
    records = [r for r in load_artifact(path)
               if artifact_key(r) is not None]
    assert records, "r13_serve_paged.json has no keyed record"
    paged = records[0]["results"]["paged"]
    assert paged["duplicate_kv_eliminated_x"] >= 1.8
    # "No slower than the gather path" (ISSUE 8 acceptance): the
    # committed median must clear parity minus the observed noise
    # floor.
    assert paged["hit_admission_speedup_x"] >= 0.95
    for key in ("duplicate_kv_eliminated_x",
                "effective_cached_tokens_per_byte_paged",
                "hit_admission_ttft_paged_s"):
        assert metric_direction(key) != 0, key


def test_r14_tenant_artifact_is_gated():
    """The multi-tenant artifact participates in the series: it loads,
    keys into a (metric, config) group, its committed headlines clear
    the ISSUE 9 bounds, they are DIRECTIONAL — and a same-config
    r-record that regresses them fails `check_series` LOUDLY (the
    regressing-record leg below is the gate-participation pin)."""
    path = os.path.join(_BENCH_DIR, "r14_serve_tenant.json")
    records = [r for r in load_artifact(path)
               if artifact_key(r) is not None]
    assert records, "r14_serve_tenant.json has no keyed record"
    tenant = records[0]["results"]["tenant"]
    # ISSUE 9 acceptance bounds on the committed medians.
    assert tenant["merged_copy_eliminated_x"] >= 3.0
    assert tenant["tenant_throughput_retained_x"] >= 0.85
    assert tenant["mask_overhead_x"] <= 1.10
    for key in ("merged_copy_eliminated_x",
                "tenant_throughput_retained_x", "mixed_tenant_tok_s",
                "mask_overhead_x"):
        assert metric_direction(key) != 0, key
    # A hypothetical r15 record at the SAME config whose tenant
    # headlines regressed must fail the series gate loudly.
    worse = copy.deepcopy(records[0])
    worse["results"]["tenant"]["tenant_throughput_retained_x"] *= 0.8
    worse["results"]["tenant"]["mask_overhead_x"] *= 1.5
    import json as _json
    import tempfile
    with tempfile.TemporaryDirectory() as d:
        old_p = os.path.join(d, "r14_t.json")
        new_p = os.path.join(d, "r15_t.json")
        with open(old_p, "w") as f:
            _json.dump(records[0], f)
        with open(new_p, "w") as f:
            _json.dump(worse, f)
        pairs, failures = check_series([old_p, new_p])
        assert pairs == 1 and len(failures) == 1
        paths = {r["path"] for r in failures[0]["regressions"]}
        assert "results.tenant.tenant_throughput_retained_x" in paths
        assert "results.tenant.mask_overhead_x" in paths


def test_r16_autoscale_artifact_is_gated():
    """The elastic-autoscaling artifact participates in the series: it
    loads, keys into a (metric, config) group, its committed headlines
    clear the ISSUE 11 bounds, they are DIRECTIONAL — and a same-config
    r-record that regresses them fails `check_series` LOUDLY."""
    path = os.path.join(_BENCH_DIR, "r16_serve_autoscale.json")
    records = [r for r in load_artifact(path)
               if artifact_key(r) is not None]
    assert records, "r16_serve_autoscale.json has no keyed record"
    auto = records[0]["results"]["autoscale"]
    # ISSUE 11 acceptance bounds on the committed medians.
    assert auto["goodput_per_replica_hour_vs_best_static_x"] >= 1.15
    assert auto["scale_events"] >= 2
    # BOTH directions per wave, from the raw per-wave lists — the
    # scalar alone could hide a fleet that only ever grows.
    assert all(u >= 1 for u in auto["scale_up_events_per_wave"])
    assert all(d >= 1 for d in auto["scale_down_events_per_wave"])
    assert auto["migrated_zero_lost"] >= 1
    assert auto["requests_lost_total"] == 0
    assert auto["brownout_rung_time_autoscaled_s"] \
        < auto["brownout_rung_time_static_under_s"]
    for key in ("goodput_per_replica_hour",
                "goodput_per_replica_hour_vs_best_static_x",
                "scale_events", "migrated_zero_lost",
                "brownout_rung_time_autoscaled_s"):
        assert metric_direction(key) != 0, key
    # A hypothetical r17 record at the SAME config whose autoscale
    # headlines regressed must fail the series gate loudly.
    worse = copy.deepcopy(records[0])
    worse["results"]["autoscale"][
        "goodput_per_replica_hour_vs_best_static_x"] *= 0.8
    worse["results"]["autoscale"]["scale_events"] = 0
    worse["results"]["autoscale"]["brownout_rung_time_autoscaled_s"] = \
        10.0 + 2.0 * auto["brownout_rung_time_autoscaled_s"]
    import json as _json
    import tempfile
    with tempfile.TemporaryDirectory() as d:
        old_p = os.path.join(d, "r16_a.json")
        new_p = os.path.join(d, "r17_a.json")
        with open(old_p, "w") as f:
            _json.dump(records[0], f)
        with open(new_p, "w") as f:
            _json.dump(worse, f)
        pairs, failures = check_series([old_p, new_p])
        assert pairs == 1 and len(failures) == 1
        paths = {r["path"] for r in failures[0]["regressions"]}
        assert ("results.autoscale."
                "goodput_per_replica_hour_vs_best_static_x") in paths
        assert "results.autoscale.scale_events" in paths
        if auto["brownout_rung_time_autoscaled_s"] > 0:
            # compare() cannot flag growth off a zero baseline (no
            # percentage exists); the bound assertion above still pins
            # the committed value itself.
            assert ("results.autoscale.brownout_rung_time_autoscaled_s"
                    in paths)


def test_r17_spec_artifact_is_gated():
    """The speculative-serving artifact participates in the series: it
    loads, keys into a (metric, config) group, its committed headlines
    clear the ISSUE 12 bounds (median speedup >= 1.3x at the default
    k, EVERY pair >= 1.2x, the acceptance curve recorded, the chaos
    leg token-exact with zero divergence), they are DIRECTIONAL — and
    a same-config r-record that regresses them fails `check_series`
    LOUDLY."""
    path = os.path.join(_BENCH_DIR, "r17_serve_spec.json")
    records = [r for r in load_artifact(path)
               if artifact_key(r) is not None]
    assert records, "r17_serve_spec.json has no keyed record"
    spec = records[0]["results"]["spec"]
    # ISSUE 12 acceptance bounds on the committed medians.
    assert spec["spec_speedup_x"] >= 1.3
    assert all(r >= 1.2 for r in spec["spec_speedup_per_pair"])
    assert spec["all_streams_token_exact"] is True
    curve = spec["acceptance_curve"]
    assert len(curve) >= 3 and all("acceptance_rate" in c for c in curve)
    # Draft quality falls as k outruns the workload's self-similarity
    # (the runbook's k-tuning story, pinned on the committed curve).
    ks = [c["k"] for c in curve]
    assert ks == sorted(ks)
    assert curve[0]["acceptance_rate"] > curve[-1]["acceptance_rate"]
    chaos = spec["chaos"]
    assert chaos["requests_token_exact"] >= 12
    assert chaos["requests_migrated"] >= 1
    for key in ("spec_tok_s", "spec_speedup_x", "acceptance_rate",
                "tokens_per_tick"):
        assert metric_direction(key) != 0, key
    # A hypothetical r18 record at the SAME config whose speculative
    # headlines regressed must fail the series gate loudly.
    worse = copy.deepcopy(records[0])
    worse["results"]["spec"]["spec_speedup_x"] *= 0.7
    worse["results"]["spec"]["acceptance_rate"] *= 0.5
    import json as _json
    import tempfile
    with tempfile.TemporaryDirectory() as d:
        old_p = os.path.join(d, "r17_s.json")
        new_p = os.path.join(d, "r18_s.json")
        with open(old_p, "w") as f:
            _json.dump(records[0], f)
        with open(new_p, "w") as f:
            _json.dump(worse, f)
        pairs, failures = check_series([old_p, new_p])
        assert pairs == 1 and len(failures) == 1
        paths = {r["path"] for r in failures[0]["regressions"]}
        assert "results.spec.spec_speedup_x" in paths
        assert "results.spec.acceptance_rate" in paths


def test_r18_tier_artifact_is_gated():
    """The tiered-KV-cache artifact participates in the series: it
    loads, keys into a (metric, config) group, its committed headlines
    clear the ISSUE 13 bounds (mean-TTFT ratio <= 0.8x at the 8x
    working set, EVERY pair at EVERY sweep point directional, the
    2-replica chain pull eliminating duplicate prefill outright, the
    tiered compile set = the evict set + exactly ``host_promote``),
    they are DIRECTIONAL — and a same-config r-record that regresses
    them fails `check_series` LOUDLY."""
    path = os.path.join(_BENCH_DIR, "r18_serve_tier.json")
    records = [r for r in load_artifact(path)
               if artifact_key(r) is not None]
    assert records, "r18_serve_tier.json has no keyed record"
    tier = records[0]["results"]["tier"]
    fleet = records[0]["results"]["fleet"]
    # ISSUE 13 acceptance bounds on the committed medians.
    assert tier["mean_ttft_ratio_at_8x"] <= 0.8
    assert tier["all_pairs_directional"] is True
    assert all(r < 1.0 for c in tier["curve"]
               for r in c["ttft_ratio_per_pair"])
    for c in tier["curve"]:
        # The tier must actually be the lever at every sweep point:
        # better hit rate, real demotion/promotion traffic.
        assert c["hit_rate_tiered"] > c["hit_rate_evict"]
        assert c["host_tier_spills"] > 0
        assert c["host_tier_promotions"] > 0
    ct = dict(tier["engine_compile_counts_tiered"])
    ce = dict(tier["engine_compile_counts_evict"])
    assert ct.pop("host_promote") == 1
    assert ct == ce and all(n == 1 for n in ce.values())
    # The fleet leg: duplicate prefill eliminated, not just reduced,
    # with the streams bit-identical either way.
    assert fleet["duplicate_prefill_tokens_blind"] > 0
    assert fleet["duplicate_prefill_tokens_pulled"] == 0.0
    assert fleet["all_pairs_directional"] is True
    assert fleet["chain_pulls"] >= 1
    assert fleet["streams_identical_blind_vs_pulled"] is True
    for key in ("mean_ttft_ratio_at_8x", "hit_rate_tiered",
                "host_tier_spills", "host_tier_promotions",
                "duplicate_prefill_tokens_blind", "chain_pulls"):
        assert metric_direction(key) != 0, key
    # A hypothetical r19 record at the SAME config whose tier
    # headlines regressed must fail the series gate loudly. (The
    # committed duplicate_prefill_tokens_pulled is exactly 0 — growth
    # off a zero baseline has no percentage, so the ratio and
    # hit-rate legs carry the loudness.)
    worse = copy.deepcopy(records[0])
    worse["results"]["tier"]["mean_ttft_ratio_at_8x"] *= 1.4
    for c in worse["results"]["tier"]["curve"]:
        c["hit_rate_tiered"] *= 0.5
    import json as _json
    import tempfile
    with tempfile.TemporaryDirectory() as d:
        old_p = os.path.join(d, "r18_t.json")
        new_p = os.path.join(d, "r19_t.json")
        with open(old_p, "w") as f:
            _json.dump(records[0], f)
        with open(new_p, "w") as f:
            _json.dump(worse, f)
        pairs, failures = check_series([old_p, new_p])
        assert pairs == 1 and len(failures) == 1
        paths = {r["path"] for r in failures[0]["regressions"]}
        assert "results.tier.mean_ttft_ratio_at_8x" in paths
        assert any("hit_rate_tiered" in p for p in paths)


def test_r19_ctrlplane_artifact_is_gated():
    """The control-plane durability artifact participates in the
    series: it loads, keys into a (metric, config) group, its
    committed headlines clear the ISSUE 14 bounds (>= 0.95x clean
    throughput retained at the 1% injected wire-fault rate with zero
    corrupt frames accepted and every CRC reject counted; WAL
    recovery wall time recorded with every stream token-exact and
    zero recompiles; hedging cutting interactive p99 TTFT with EVERY
    pair directional), they are DIRECTIONAL — and a same-config
    r-record that regresses them fails `check_series` LOUDLY."""
    path = os.path.join(_BENCH_DIR, "r19_serve_ctrlplane.json")
    records = [r for r in load_artifact(path)
               if artifact_key(r) is not None]
    assert records, "r19_serve_ctrlplane.json has no keyed record"
    ctrl = records[0]["results"]["ctrlplane"]
    wire, rec, hedge = ctrl["wire"], ctrl["recovery"], ctrl["hedge"]
    # ISSUE 14 acceptance bounds on the committed medians.
    assert wire["injected_fault_rate_per_frame"] == 0.01
    assert wire["throughput_retained_x"] >= 0.95
    assert wire["corrupt_frames_accepted"] == 0
    assert wire["wire_crc_rejects_total"] > 0  # every reject counted
    assert wire["wire_retries_total"] > 0      # ...and healed
    assert wire["streams_token_exact"] is True
    assert rec["recovery_s"] > 0               # measured, recorded
    assert rec["streams_token_exact"] is True
    assert rec["zero_recompiles_recovered"] is True
    assert all(n > 0 for n in rec["streams_revived_per_repeat"])
    assert hedge["hedged_ttft_p99_reduction_x"] > 1.0
    assert hedge["all_pairs_directional"] is True
    assert hedge["hedge_wins_total"] > 0
    assert hedge["zero_recompiles"] is True
    for key in ("throughput_retained_x", "recovery_s",
                "hedged_ttft_p99_reduction_x", "hedge_wins_total",
                "ttft_p99_hedge_on_s"):
        assert metric_direction(key) != 0, key
    # A hypothetical r20 record at the SAME config whose control-plane
    # headlines regressed must fail the series gate loudly.
    worse = copy.deepcopy(records[0])
    w = worse["results"]["ctrlplane"]
    w["wire"]["throughput_retained_x"] *= 0.8
    w["recovery"]["recovery_s"] *= 2.0
    w["hedge"]["hedged_ttft_p99_reduction_x"] *= 0.5
    import json as _json
    import tempfile
    with tempfile.TemporaryDirectory() as d:
        old_p = os.path.join(d, "r19_c.json")
        new_p = os.path.join(d, "r20_c.json")
        with open(old_p, "w") as f:
            _json.dump(records[0], f)
        with open(new_p, "w") as f:
            _json.dump(worse, f)
        pairs, failures = check_series([old_p, new_p])
        assert pairs == 1 and len(failures) == 1
        paths = {r["path"] for r in failures[0]["regressions"]}
        assert ("results.ctrlplane.wire.throughput_retained_x"
                in paths)
        assert "results.ctrlplane.recovery.recovery_s" in paths
        assert ("results.ctrlplane.hedge.hedged_ttft_p99_reduction_x"
                in paths)


def test_r20_disagg_artifact_is_gated():
    """The disaggregated-serving artifact participates in the series:
    it loads, keys into a (metric, config) group, its committed
    headlines clear the ISSUE 17 bounds (split-fleet decode-side p99
    token latency <= 0.8x the same-N unified fleet with aggregate
    tok/s >= 0.95x retained, EVERY pair directional; hand-off latency
    measured per shipped chain; every stream token-exact across the
    two fleet shapes; zero recompiles on the decode replicas), they
    are DIRECTIONAL — and a same-config r-record that regresses them
    fails `check_series` LOUDLY."""
    path = os.path.join(_BENCH_DIR, "r20_serve_disagg.json")
    records = [r for r in load_artifact(path)
               if artifact_key(r) is not None]
    assert records, "r20_serve_disagg.json has no keyed record"
    d = records[0]["results"]["disagg"]
    # ISSUE 17 acceptance bounds on the committed medians.
    assert d["decode_p99_interference"] <= 0.8
    assert d["decode_p99_interference_bound"] == 0.8
    assert d["tokens_per_s_retained_x"] >= 0.95
    assert d["tokens_per_s_retained_floor"] == 0.95
    assert d["all_pairs_directional"] is True
    assert len(d["decode_p99_interference_per_pair"]) >= 5
    assert all(r <= 0.8 for r in d["decode_p99_interference_per_pair"])
    assert all(r >= 0.95 for r in d["tokens_per_s_retained_per_pair"])
    assert d["handoffs_completed_total"] > 0
    assert d["handoff_ms"] > 0          # measured, recorded
    assert d["streams_token_exact_split_vs_unified"] is True
    assert d["zero_recompiles_decode_replicas"] is True
    m = d["split_fleet_metrics_last_repeat"]
    assert m["handoffs_completed"] > 0
    for key in ("decode_p99_interference", "handoff_ms",
                "tokens_per_s_retained_x", "split_decode_lat_p99_ms",
                "unified_capacity_tokens_per_s"):
        assert metric_direction(key) != 0, key
    # A hypothetical r21 record at the SAME config whose disagg
    # headlines regressed must fail the series gate loudly.
    worse = copy.deepcopy(records[0])
    w = worse["results"]["disagg"]
    w["decode_p99_interference"] *= 2.0
    w["tokens_per_s_retained_x"] *= 0.8
    w["handoff_ms"] *= 3.0
    import json as _json
    import tempfile
    with tempfile.TemporaryDirectory() as d_:
        old_p = os.path.join(d_, "r20_d.json")
        new_p = os.path.join(d_, "r21_d.json")
        with open(old_p, "w") as f:
            _json.dump(records[0], f)
        with open(new_p, "w") as f:
            _json.dump(worse, f)
        pairs, failures = check_series([old_p, new_p])
        assert pairs == 1 and len(failures) == 1
        paths = {r["path"] for r in failures[0]["regressions"]}
        assert "results.disagg.decode_p99_interference" in paths
        assert "results.disagg.tokens_per_s_retained_x" in paths
        assert "results.disagg.handoff_ms" in paths


def test_r21_chaosd_artifact_is_gated():
    """The storage-chaos artifact participates in the series: it
    loads, keys into a (metric, config) group, its committed headlines
    clear the ISSUE 18 bounds (>= 0.95x clean throughput held while
    the WAL is degraded NON_DURABLE under a persistent-EIO storm with
    every stream token-exact; durability re-armed within one probe
    interval; all 3 composed-plane conductor campaigns green across
    every referee invariant), they are DIRECTIONAL — and a same-config
    r-record that regresses them fails `check_series` LOUDLY."""
    path = os.path.join(_BENCH_DIR, "r21_serve_chaosd.json")
    records = [r for r in load_artifact(path)
               if artifact_key(r) is not None]
    assert records, "r21_serve_chaosd.json has no keyed record"
    avail = records[0]["results"]["storm"]
    camp = records[0]["results"]["campaign"]
    # ISSUE 18 acceptance bounds on the committed medians.
    assert avail["non_durable_availability_x"] >= 0.95
    assert avail["storage_faults_injected_total"] > 0  # storm landed
    assert avail["journal_degraded_events_total"] > 0  # ...degraded
    assert avail["journal_rearms_total"] == \
        avail["journal_degraded_events_total"]  # every incident healed
    assert avail["rearm_within_one_probe_interval"] is True
    assert avail["rearm_latency_s"] > 0            # measured, recorded
    assert avail["streams_token_exact"] is True
    assert camp["campaigns_all_ok"] is True
    assert camp["invariants_failed"] == []
    assert len(camp["seeds"]) == 3                 # the 3-seed matrix
    assert set(camp["planes_composed"]) == {
        "wire", "storage", "gray", "kill", "router"}
    assert "token_exact" in camp["invariants_checked"]
    assert "zero_recompiles" in camp["invariants_checked"]
    assert "recover_idempotent" in camp["invariants_checked"]
    assert camp["kills_fired_total"] > 0
    assert camp["router_crashes_total"] == 3
    assert camp["wire_faults_injected_total"] > 0
    assert camp["storage_faults_injected_total"] > 0
    assert camp["recovery_s"] > 0                  # measured, recorded
    for key in ("non_durable_availability_x", "rearm_latency_s",
                "recovery_s", "tokens_per_s_storm"):
        assert metric_direction(key) != 0, key
    # A hypothetical r22 record at the SAME config whose storage-chaos
    # headlines regressed must fail the series gate loudly.
    worse = copy.deepcopy(records[0])
    w = worse["results"]
    w["storm"]["non_durable_availability_x"] *= 0.8
    w["storm"]["rearm_latency_s"] *= 10.0
    w["campaign"]["recovery_s"] *= 2.0
    import json as _json
    import tempfile
    with tempfile.TemporaryDirectory() as d_:
        old_p = os.path.join(d_, "r21_s.json")
        new_p = os.path.join(d_, "r22_s.json")
        with open(old_p, "w") as f:
            _json.dump(records[0], f)
        with open(new_p, "w") as f:
            _json.dump(worse, f)
        pairs, failures = check_series([old_p, new_p])
        assert pairs == 1 and len(failures) == 1
        paths = {r["path"] for r in failures[0]["regressions"]}
        assert "results.storm.non_durable_availability_x" in paths
        assert "results.storm.rearm_latency_s" in paths
        assert "results.campaign.recovery_s" in paths


def test_r22_dtrace_artifact_is_gated():
    """The distributed-tracing artifact participates in the series: it
    loads, keys into a (metric, config) group, its committed headlines
    clear the ISSUE 19 bounds (tracing-on retains >= 0.95x tracing-off
    throughput with EVERY pair above the floor; every stitched trace
    gap-free; zero remote span drops; streams token-exact), they are
    DIRECTIONAL — and a same-config r-record that regresses them fails
    `check_series` LOUDLY."""
    path = os.path.join(_BENCH_DIR, "r22_serve_dtrace.json")
    records = [r for r in load_artifact(path)
               if artifact_key(r) is not None]
    assert records, "r22_serve_dtrace.json has no keyed record"
    dt = records[0]["results"]["dtrace"]
    # ISSUE 19 acceptance bounds on the committed medians.
    floor = dt["tracing_retained_floor"]
    assert floor == 0.95
    assert dt["tracing_on_over_off_x"] >= floor
    assert dt["all_pairs_above_floor"] is True
    pairs = dt["tracing_on_over_off_per_pair"]
    assert len(pairs) == 5                      # the 5 paired runs
    assert all(r >= floor for r in pairs)       # every pair directional
    assert dt["traces_stitched_total"] > 0
    assert dt["traces_gap_free_total"] == dt["traces_stitched_total"]
    assert dt["traces_all_gap_free"] is True
    assert dt["replica_spans_collected_total"] > 0  # spans crossed the
    assert dt["spans_dropped_remote_total"] == 0    # pipe, none lost
    assert dt["streams_token_exact"] is True
    for key in ("tracing_on_over_off_x", "tokens_per_s_tracing_on",
                "tokens_per_s_tracing_off"):
        assert metric_direction(key) != 0, key
    # Per-pair lists and spreads are telemetry, never gated.
    assert metric_direction("tracing_on_over_off_per_pair") == 0
    assert metric_direction("tracing_on_over_off_spread_pct") == 0
    # A hypothetical r23 record at the SAME config whose tracing
    # overhead regressed must fail the series gate loudly.
    worse = copy.deepcopy(records[0])
    w = worse["results"]["dtrace"]
    w["tracing_on_over_off_x"] *= 0.8
    w["tokens_per_s_tracing_on"] *= 0.5
    import json as _json
    import tempfile
    with tempfile.TemporaryDirectory() as d_:
        old_p = os.path.join(d_, "r22_t.json")
        new_p = os.path.join(d_, "r23_t.json")
        with open(old_p, "w") as f:
            _json.dump(records[0], f)
        with open(new_p, "w") as f:
            _json.dump(worse, f)
        pairs_checked, failures = check_series([old_p, new_p])
        assert pairs_checked == 1 and len(failures) == 1
        paths = {r["path"] for r in failures[0]["regressions"]}
        assert "results.dtrace.tracing_on_over_off_x" in paths
        assert "results.dtrace.tokens_per_s_tracing_on" in paths


def test_r23_ha_artifact_is_gated():
    """The router-HA artifact participates in the series: it loads,
    keys into a (metric, config) group, its committed headlines clear
    the ISSUE 20 bounds (automatic lease-lapse failover under 2 s
    median vs the multi-second cold recover path, every pair
    directional; zero acked-stream loss; token-exact vs the unkilled
    oracle; zero recompiles on the promoted router; the deposed
    primary refused by fencing on 100% of its probes), they are
    DIRECTIONAL — and a same-config r-record that regresses them fails
    `check_series` LOUDLY."""
    path = os.path.join(_BENCH_DIR, "r23_serve_ha.json")
    records = [r for r in load_artifact(path)
               if artifact_key(r) is not None]
    assert records, "r23_serve_ha.json has no keyed record"
    ha = records[0]["results"]["ha"]
    # ISSUE 20 acceptance bounds on the committed medians.
    assert ha["failover_s"] <= 2.0            # sub-2s detect+promote
    assert ha["failover_s"] > 0               # measured, recorded
    assert ha["cold_recover_s"] > ha["failover_s"]
    assert ha["failover_speedup_vs_cold_x"] > 1.0
    assert ha["all_pairs_directional"] is True
    pairs = list(zip(ha["failover_s_per_repeat"],
                     ha["cold_recover_s_per_repeat"]))
    assert len(pairs) == 5                    # the 5 paired runs
    assert all(hot < cold for hot, cold in pairs)
    assert ha["acked_streams_lost_total"] == 0
    assert ha["streams_token_exact"] is True
    assert ha["zero_recompiles_promoted"] is True
    assert ha["deposed_probes_attempted"] > 0
    assert ha["deposed_probes_refused"] == \
        ha["deposed_probes_attempted"]        # fencing: 100% refusal
    assert ha["detection_lease_ttl_s"] > 0    # detection is in the clock
    for key in ("failover_s", "failover_speedup_vs_cold_x"):
        assert metric_direction(key) != 0, key
    # Per-pair lists, spreads, and the baseline's own wall are
    # telemetry, never gated (the cold path is r19's series to watch).
    assert metric_direction("failover_s_per_repeat") == 0
    assert metric_direction("failover_s_spread_pct") == 0
    assert metric_direction("detection_lease_ttl_s") == 0
    # A hypothetical r24 record at the SAME config whose failover
    # headlines regressed must fail the series gate loudly.
    worse = copy.deepcopy(records[0])
    w = worse["results"]["ha"]
    w["failover_s"] *= 10.0
    w["failover_speedup_vs_cold_x"] *= 0.1
    import json as _json
    import tempfile
    with tempfile.TemporaryDirectory() as d_:
        old_p = os.path.join(d_, "r23_h.json")
        new_p = os.path.join(d_, "r24_h.json")
        with open(old_p, "w") as f:
            _json.dump(records[0], f)
        with open(new_p, "w") as f:
            _json.dump(worse, f)
        pairs_checked, failures = check_series([old_p, new_p])
        assert pairs_checked == 1 and len(failures) == 1
        paths = {r["path"] for r in failures[0]["regressions"]}
        assert "results.ha.failover_s" in paths
        assert "results.ha.failover_speedup_vs_cold_x" in paths


def test_compare_flags_directional_regressions_only():
    old = _record(tokens_per_s=1000.0, ttft_p99_s=0.10, spread_pct=2.0,
                  prefix_hit_rate=0.97)
    # 4% throughput dip: inside the gate.
    ok = compare(old, _record(tokens_per_s=960.0, ttft_p99_s=0.10,
                              spread_pct=9.0, prefix_hit_rate=0.97))
    assert ok == []
    # 10% throughput drop: flagged, with the right direction label.
    bad = compare(old, _record(tokens_per_s=900.0, ttft_p99_s=0.10,
                               spread_pct=2.0, prefix_hit_rate=0.97))
    assert [r["path"] for r in bad] == ["results.tokens_per_s"]
    assert bad[0]["direction"] == "higher-better"
    # TTFT rising 50%: flagged as a lower-better regression; TTFT
    # FALLING 50% is an improvement and passes.
    worse = compare(old, _record(tokens_per_s=1000.0, ttft_p99_s=0.15,
                                 spread_pct=2.0, prefix_hit_rate=0.97))
    assert [r["path"] for r in worse] == ["results.ttft_p99_s"]
    assert worse[0]["direction"] == "lower-better"
    assert compare(old, _record(tokens_per_s=1050.0, ttft_p99_s=0.05,
                                spread_pct=2.0,
                                prefix_hit_rate=0.99)) == []


def test_compare_flags_vanished_directional_leaves():
    """A renamed/dropped headline must not silently exit the gate: a
    directional leaf present in old but absent in new is a loud
    failure; noise leaves and NEW legs (absent in old) are not."""
    old = _record(tokens_per_s=1000.0, ttft_p99_s=0.10, spread_pct=2.0)
    gone = compare(old, _record(toks_per_s=1000.0, ttft_p99_s=0.10,
                                spread_pct=2.0))
    assert [r["path"] for r in gone] == ["results.tokens_per_s"]
    assert gone[0]["direction"] == "missing-in-new"
    assert gone[0]["new"] is None and gone[0]["change_pct"] is None
    # Dropping a noise key, or growing a brand-new leg, stays green.
    assert compare(old, _record(tokens_per_s=1000.0, ttft_p99_s=0.10,
                                killed_tokens_per_s=900.0)) == []


def test_compare_refuses_mismatched_configs():
    old = _record(tokens_per_s=1000.0)
    other = copy.deepcopy(old)
    other["config"]["slots"] = 16  # a different experiment
    with pytest.raises(ValueError, match="not comparable"):
        compare(old, other)


def test_committed_artifact_series_has_no_silent_regressions():
    """THE gate: every consecutive same-(metric, config) pair in the
    committed r*.json series is within 5% on every directional
    headline. A failure here means a perf regression was committed —
    fix the regression or consciously re-baseline the artifact, never
    ignore this test."""
    paths = sorted(glob.glob(os.path.join(_BENCH_DIR, "r*.json")))
    assert paths, "committed bench artifacts are missing"
    pairs, failures = check_series(paths, threshold_pct=5.0)
    lines = []
    for failure in failures:
        for r in failure["regressions"]:
            change = ("leaf vanished" if r["change_pct"] is None
                      else f"{r['change_pct']:+.1f}%")
            lines.append(
                f"{failure['old_path']} -> {failure['new_path']}: "
                f"{r['path']} {r['old']} -> {r['new']} "
                f"({change}, {r['direction']})")
    assert not failures, "committed perf regressions:\n" + "\n".join(lines)
    # The loader really parsed the series (metric'd records exist, and
    # the r11 fleet artifact participates in at least its own group).
    keyed = [r for p in paths for r in load_artifact(p)
             if artifact_key(r) is not None]
    assert len(keyed) >= 8


def test_cli_gate_exit_codes(tmp_path):
    import json

    old = _record(tokens_per_s=1000.0)
    new = _record(tokens_per_s=850.0)
    (tmp_path / "r01_x.json").write_text(json.dumps(old))
    (tmp_path / "r02_x.json").write_text(json.dumps(new))
    assert _main(["gate", str(tmp_path)]) == 1  # loud on regression
    assert _main(["compare", str(tmp_path / "r01_x.json"),
                  str(tmp_path / "r01_x.json")]) == 0
    assert _main(["gate", _BENCH_DIR]) == 0  # the committed series
