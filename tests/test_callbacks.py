"""Callback parity: ReduceLROnPlateau + EarlyStopping defaults match the
reference (`/root/reference/imagenet-resnet50.py:64-65`); warmup matches the
Horovod schedule (`imagenet-resnet50-hvd.py:114-115`)."""

import numpy as np

from pddl_tpu.data.synthetic import SyntheticImageClassification
from pddl_tpu.models.resnet import tiny_resnet
from pddl_tpu.parallel import SingleDeviceStrategy
from pddl_tpu.train.callbacks import (
    CSVLogger,
    EarlyStopping,
    LambdaCallback,
    LearningRateWarmup,
    ReduceLROnPlateau,
    Timing,
)
from pddl_tpu.train.loop import Trainer
from pddl_tpu.train.state import get_learning_rate


def _trainer(**kw):
    kw.setdefault("strategy", SingleDeviceStrategy())
    kw.setdefault("learning_rate", 1e-2)
    return Trainer(tiny_resnet(num_classes=10), **kw)


def _ds():
    return SyntheticImageClassification(
        batch_size=16, image_size=32, num_classes=10, signal_strength=3.0
    )


def test_reduce_lr_on_plateau_fires():
    # signal_strength=0: pure noise, val_loss plateaus immediately.
    noise = SyntheticImageClassification(
        batch_size=16, image_size=32, num_classes=10, signal_strength=0.0
    )
    tr = _trainer()
    cb = ReduceLROnPlateau(monitor="val_loss", factor=0.1, patience=2, min_lr=1e-5)
    tr.fit(noise, epochs=6, steps_per_epoch=2, validation_data=noise,
           validation_steps=1, callbacks=[cb], verbose=0)
    lr = get_learning_rate(tr.state)
    assert lr < 1e-2  # decayed at least once
    assert lr >= 1e-5  # never below min_lr (reference's floor)


def test_reduce_lr_respects_min_lr_floor():
    noise = SyntheticImageClassification(
        batch_size=16, image_size=32, num_classes=10, signal_strength=0.0
    )
    tr = _trainer()
    # min_delta so large nothing ever counts as improvement -> decays every
    # epoch, must clamp at the floor.
    cb = ReduceLROnPlateau(patience=1, factor=0.001, min_lr=1e-3, min_delta=10.0)
    tr.fit(noise, epochs=4, steps_per_epoch=1, validation_data=noise,
           validation_steps=1, callbacks=[cb], verbose=0)
    assert np.isclose(get_learning_rate(tr.state), 1e-3)


def test_early_stopping_stops():
    noise = SyntheticImageClassification(
        batch_size=16, image_size=32, num_classes=10, signal_strength=0.0
    )
    tr = _trainer()
    cb = EarlyStopping(monitor="val_loss", min_delta=0.001, patience=2)
    h = tr.fit(noise, epochs=50, steps_per_epoch=1, validation_data=noise,
               validation_steps=1, callbacks=[cb], verbose=0)
    assert len(h.epoch) < 50
    assert cb.stopped_epoch is not None


def test_warmup_ramps_to_target():
    tr = _trainer(learning_rate=0.8)
    cb = LearningRateWarmup(warmup_epochs=2, verbose=0)
    lrs = []
    spy = LambdaCallback(
        on_train_batch_end=lambda step, state, logs: lrs.append(get_learning_rate(state))
    )
    tr.fit(_ds(), epochs=3, steps_per_epoch=4, callbacks=[cb, spy], verbose=0)
    # Ramp over 2 epochs * 4 steps, then hold at target.
    assert lrs[0] < 0.2
    assert np.isclose(lrs[7], 0.8, rtol=1e-5)
    assert np.isclose(lrs[-1], 0.8, rtol=1e-5)
    assert all(b >= a - 1e-9 for a, b in zip(lrs, lrs[1:]))


def test_csv_logger(tmp_path):
    path = tmp_path / "history.csv"
    tr = _trainer()
    tr.fit(_ds(), epochs=2, steps_per_epoch=2, callbacks=[CSVLogger(str(path))],
           verbose=0)
    lines = path.read_text().strip().splitlines()
    assert lines[0].startswith("epoch,")
    assert len(lines) == 3  # header + 2 epochs


def test_timing_callback():
    tr = _trainer()
    cb = Timing(verbose=0)
    tr.fit(_ds(), epochs=1, steps_per_epoch=2, callbacks=[cb], verbose=0)
    assert cb.total is not None and cb.total > 0
