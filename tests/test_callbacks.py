"""Callback parity: ReduceLROnPlateau + EarlyStopping defaults match the
reference (`/root/reference/imagenet-resnet50.py:64-65`); warmup matches the
Horovod schedule (`imagenet-resnet50-hvd.py:114-115`)."""

import numpy as np

from pddl_tpu.data.synthetic import SyntheticImageClassification
from pddl_tpu.models.resnet import tiny_resnet
from pddl_tpu.parallel import SingleDeviceStrategy
from pddl_tpu.train.callbacks import (
    CSVLogger,
    EarlyStopping,
    LambdaCallback,
    LearningRateWarmup,
    ReduceLROnPlateau,
    Timing,
)
from pddl_tpu.train.loop import Trainer
from pddl_tpu.train.state import get_learning_rate


def _trainer(**kw):
    kw.setdefault("strategy", SingleDeviceStrategy())
    kw.setdefault("learning_rate", 1e-2)
    return Trainer(tiny_resnet(num_classes=10), **kw)


def _ds():
    return SyntheticImageClassification(
        batch_size=16, image_size=32, num_classes=10, signal_strength=3.0
    )


def test_reduce_lr_on_plateau_fires():
    # signal_strength=0: pure noise, val_loss plateaus immediately.
    noise = SyntheticImageClassification(
        batch_size=16, image_size=32, num_classes=10, signal_strength=0.0
    )
    tr = _trainer()
    cb = ReduceLROnPlateau(monitor="val_loss", factor=0.1, patience=2, min_lr=1e-5)
    tr.fit(noise, epochs=6, steps_per_epoch=2, validation_data=noise,
           validation_steps=1, callbacks=[cb], verbose=0)
    lr = get_learning_rate(tr.state)
    assert lr < 1e-2  # decayed at least once
    assert lr >= 1e-5  # never below min_lr (reference's floor)


def test_reduce_lr_respects_min_lr_floor():
    noise = SyntheticImageClassification(
        batch_size=16, image_size=32, num_classes=10, signal_strength=0.0
    )
    tr = _trainer()
    # min_delta so large nothing ever counts as improvement -> decays every
    # epoch, must clamp at the floor.
    cb = ReduceLROnPlateau(patience=1, factor=0.001, min_lr=1e-3, min_delta=10.0)
    tr.fit(noise, epochs=4, steps_per_epoch=1, validation_data=noise,
           validation_steps=1, callbacks=[cb], verbose=0)
    assert np.isclose(get_learning_rate(tr.state), 1e-3)


def test_early_stopping_stops():
    noise = SyntheticImageClassification(
        batch_size=16, image_size=32, num_classes=10, signal_strength=0.0
    )
    tr = _trainer()
    cb = EarlyStopping(monitor="val_loss", min_delta=0.001, patience=2)
    h = tr.fit(noise, epochs=50, steps_per_epoch=1, validation_data=noise,
               validation_steps=1, callbacks=[cb], verbose=0)
    assert len(h.epoch) < 50
    assert cb.stopped_epoch is not None


def test_warmup_ramps_to_target():
    tr = _trainer(learning_rate=0.8)
    cb = LearningRateWarmup(warmup_epochs=2, verbose=0)
    lrs = []
    spy = LambdaCallback(
        on_train_batch_end=lambda step, state, logs: lrs.append(get_learning_rate(state))
    )
    tr.fit(_ds(), epochs=3, steps_per_epoch=4, callbacks=[cb, spy], verbose=0)
    # Ramp over 2 epochs * 4 steps, then hold at target.
    assert lrs[0] < 0.2
    assert np.isclose(lrs[7], 0.8, rtol=1e-5)
    assert np.isclose(lrs[-1], 0.8, rtol=1e-5)
    assert all(b >= a - 1e-9 for a, b in zip(lrs, lrs[1:]))


def test_warmup_and_plateau_compose():
    """Warmup owns epochs 0-2; plateau reductions stick only after release.

    The reference hvd script runs ReduceLROnPlateau in the same callback list
    as the warmup callback (`/root/reference/imagenet-resnet50-hvd.py:106,114`).
    The runtime behavior to preserve: while warmup is ramping it re-sets the
    LR every batch, so a plateau reduction fired mid-warmup is transient and
    the ramp still reaches the full target; once warmup releases (after
    warmup_epochs), plateau's multiplicative reductions persist.
    """
    noise = SyntheticImageClassification(
        batch_size=16, image_size=32, num_classes=10, signal_strength=0.0
    )
    tr = _trainer(learning_rate=0.8)
    # min_delta so large nothing ever improves: plateau fires at the end of
    # EVERY epoch from epoch 1 on — including inside the warmup window.
    # (1e30, not 10: at lr=0.8 the noise-fit loss explodes, and a small
    # threshold lets a >min_delta swing register as improvement on some
    # XLA:CPU runs, skipping one reduction. Finite, unlike inf, so the
    # first epoch still sets the baseline: inf - inf is NaN.)
    plateau = ReduceLROnPlateau(patience=1, factor=0.1, min_delta=1e30,
                                min_lr=1e-6)
    warmup = LearningRateWarmup(warmup_epochs=3, verbose=0)
    lrs = []
    spy = LambdaCallback(
        on_train_batch_end=lambda step, state, logs: lrs.append(
            get_learning_rate(state)
        )
    )
    # Reference order: plateau first, warmup after (:106 vs :114).
    tr.fit(noise, epochs=5, steps_per_epoch=2, validation_data=noise,
           validation_steps=1, callbacks=[plateau, warmup, spy], verbose=0)
    # Epochs 0-2 (6 batches): the pure linear ramp to 0.8, unperturbed by the
    # plateau reductions fired at the ends of epochs 1 and 2.
    ramp = [0.8 * (k + 1) / 6 for k in range(6)]
    assert np.allclose(lrs[:6], ramp, rtol=1e-5), lrs[:6]
    # Warmup released at 0.8; epoch-2-end plateau cut it to 0.08, and nothing
    # restores it during epoch 3.
    assert np.allclose(lrs[6:8], 0.08, rtol=1e-5), lrs[6:8]
    # Epoch-3-end and epoch-4-end reductions compound: 0.8 -> 0.08 -> 0.008
    # -> 0.0008 persists in the final state.
    assert np.isclose(get_learning_rate(tr.state), 8e-4, rtol=1e-5)


def test_hvd_and_ps_presets_keep_reference_callbacks():
    """The hvd/ps presets must not drop the reference's val_loss callbacks
    (`imagenet-resnet50-hvd.py:106-107`, `imagenet-resnet50-ps.py:139-140`)."""
    from pddl_tpu.config import get_preset

    for preset in ("hvd", "ps"):
        cfg = get_preset(preset)
        assert cfg.reduce_lr_on_plateau, preset
        assert cfg.early_stopping, preset


def test_csv_logger(tmp_path):
    path = tmp_path / "history.csv"
    tr = _trainer()
    tr.fit(_ds(), epochs=2, steps_per_epoch=2, callbacks=[CSVLogger(str(path))],
           verbose=0)
    lines = path.read_text().strip().splitlines()
    assert lines[0].startswith("epoch,")
    assert len(lines) == 3  # header + 2 epochs


def test_timing_callback():
    tr = _trainer()
    cb = Timing(verbose=0)
    tr.fit(_ds(), epochs=1, steps_per_epoch=2, callbacks=[cb], verbose=0)
    assert cb.total is not None and cb.total > 0


def test_model_summary_prints_param_table(capsys):
    """The rank-0 model.summary() analogue (imagenet-resnet50-hvd.py:95-96)."""
    from pddl_tpu.train.callbacks import ModelSummary

    tr = _trainer()
    tr.fit(_ds(), epochs=1, steps_per_epoch=1, callbacks=[ModelSummary()],
           verbose=0)
    err = capsys.readouterr().err
    assert "Model parameters:" in err
    assert "TOTAL" in err
    # Totals are real: match the state's actual parameter count.
    import jax

    n = sum(x.size for x in jax.tree.leaves(tr.state.params))
    assert f"{n:,}" in err


def test_set_learning_rate_stamps_a_device_leaf():
    """Regression for the ROADMAP "Known flake": set_learning_rate
    stored a HOST-numpy LR scalar into opt_state, which then rode the
    DONATED train step — container jaxlib intermittently corrupted the
    buffer (the final LR read back as float32-bits-of-int, roaming
    between test_hvd_compat and the warmup test). The fix stamps a
    device (jax.Array) leaf placed like the one it replaces; this pins
    the leaf's type so the host-numpy shape cannot quietly return.
    This is exactly the bug class graftlint's `donation` rule checks
    statically (pddl_tpu/analysis/checkers/donation.py)."""
    import jax

    from pddl_tpu.train.state import set_learning_rate

    tr = _trainer()
    tr.fit(_ds(), epochs=1, steps_per_epoch=1, verbose=0)
    state = set_learning_rate(tr.state, 5e-4)

    def _find(opt_state):
        if hasattr(opt_state, "hyperparams") \
                and "learning_rate" in opt_state.hyperparams:
            return opt_state.hyperparams["learning_rate"]
        if isinstance(opt_state, tuple):
            for sub in opt_state:
                found = _find(sub)
                if found is not None:
                    return found
        return None

    leaf = _find(state.opt_state)
    assert leaf is not None
    assert isinstance(leaf, jax.Array), (
        f"LR leaf must be device-resident, got {type(leaf)} — a host "
        "buffer here rides the donated train step (the r10/flake class)")
    assert np.isclose(float(jax.device_get(leaf)), 5e-4)
    # The placement survives a real donated step: train one more step
    # on the updated state and read the LR back uncorrupted.
    tr.state = state
    tr.fit(_ds(), epochs=1, steps_per_epoch=1, verbose=0)
    assert 0 < get_learning_rate(tr.state) <= 5e-4 + 1e-9
