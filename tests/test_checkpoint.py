"""Checkpoint/resume + Keras .h5 import tests.

The resume story is a capability the reference lacks (SURVEY.md §5: final
``model.save`` only, `/root/reference/imagenet-resnet50.py:69-72`); the
pretrained import is its ``weights='imagenet'`` mode
(`imagenet-pretrained-resnet50.py:56`).
"""

import os

import jax
import numpy as np
import pytest

from pddl_tpu.ckpt import (
    BackupAndRestore,
    Checkpointer,
    ModelCheckpoint,
    latest_epoch,
    load_keras_resnet50_h5,
)
from pddl_tpu.ckpt.keras_import import export_keras_style_h5, keras_layer_map
from pddl_tpu.data.synthetic import SyntheticImageClassification
from pddl_tpu.models.resnet import ResNet, tiny_resnet
from pddl_tpu.parallel.ps import ParameterServerStrategy
from pddl_tpu.parallel.single import SingleDeviceStrategy
from pddl_tpu.train.loop import Trainer


def _dataset(batch=8, classes=10):
    return SyntheticImageClassification(
        batch_size=batch, image_size=32, num_classes=classes, seed=3
    )


def _trainer(strategy=None, **kw):
    return Trainer(
        tiny_resnet(num_classes=10), optimizer="adam", learning_rate=1e-2,
        strategy=strategy or SingleDeviceStrategy(), **kw,
    )


def _assert_tree_equal(a, b):
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_checkpointer_roundtrip(tmp_path):
    tr = _trainer()
    tr.fit(_dataset(), epochs=1, steps_per_epoch=3, verbose=0)
    ckpt = Checkpointer(str(tmp_path / "ck"), async_save=False)
    step = ckpt.save(tr.state, epoch=0, metrics={"loss": 1.0})
    assert step == 3
    assert ckpt.latest_step() == 3
    assert ckpt.metadata()["epoch"] == 0

    # Train further, then restore: state must be bitwise the saved one.
    tr.fit(_dataset(), epochs=1, steps_per_epoch=2, verbose=0)
    before = jax.device_get(tr.state.params)
    restored = ckpt.restore(tr.state)
    assert int(restored.step) == 3
    with pytest.raises(AssertionError):
        _assert_tree_equal(before, jax.device_get(restored.params))
    ckpt.close()


def test_restore_preserves_sharded_layout(tmp_path, mesh8):
    """PS/ZeRO-sharded state round-trips with its NamedShardings intact."""
    strategy = ParameterServerStrategy(min_shard_bytes=1 << 8)
    strategy._mesh = mesh8
    tr = _trainer(strategy=strategy)
    tr.fit(_dataset(batch=16), epochs=1, steps_per_epoch=2, verbose=0)

    sharded = [
        (p, x) for p, x in
        jax.tree_util.tree_flatten_with_path(tr.state.opt_state)[0]
        if isinstance(x, jax.Array) and not x.sharding.is_fully_replicated
    ]
    assert sharded, "expected some PS-sharded optimizer leaves"

    ckpt = Checkpointer(str(tmp_path / "ck"), async_save=False)
    ckpt.save(tr.state)
    restored = ckpt.restore(tr.state)
    flat_r = dict(jax.tree_util.tree_flatten_with_path(restored.opt_state)[0])
    for path, orig in sharded:
        assert flat_r[path].sharding == orig.sharding
    _assert_tree_equal(jax.device_get(tr.state.params),
                       jax.device_get(restored.params))
    ckpt.close()


def test_resume_training_continues_deterministically(tmp_path):
    """fit(5) == fit(3) + save + restore + fit(initial_epoch=3..5):
    the determinism-under-resume guarantee. Model/optimizer/PRNG state all
    live in the checkpoint (the step counter keys the per-step PRNG fold-in);
    the data stream must resume at its saved position — here via the
    synthetic dataset's deterministic batch indexing."""
    ds = _dataset()
    ckdir = str(tmp_path / "bk")

    straight = _trainer(seed=7)
    straight.fit(ds, epochs=5, steps_per_epoch=2, verbose=0)

    part1 = _trainer(seed=7)
    part1.fit(ds, epochs=3, steps_per_epoch=2, verbose=0,
              callbacks=[BackupAndRestore(ckdir, async_save=False)])
    assert latest_epoch(ckdir) == 2

    # Resume: same task, data stream positioned at batch 6 (= 3 epochs x 2
    # steps already consumed), like a resumable input pipeline would be.
    ds_resumed = SyntheticImageClassification(
        batch_size=8, image_size=32, num_classes=10, seed=3, index_offset=6
    )
    part2 = _trainer(seed=7)
    part2.fit(ds_resumed, epochs=5, steps_per_epoch=2, verbose=0,
              initial_epoch=3,
              callbacks=[BackupAndRestore(ckdir, async_save=False)])

    _assert_tree_equal(jax.device_get(straight.state.params),
                       jax.device_get(part2.state.params))


def test_model_checkpoint_best_only(tmp_path):
    tr = _trainer()
    cb = ModelCheckpoint(str(tmp_path / "best"), monitor="loss",
                         save_best_only=True, async_save=False)
    tr.fit(_dataset(), epochs=3, steps_per_epoch=2, verbose=0, callbacks=[cb])
    # Loss decreases every epoch on this task → last save is at final step.
    assert cb.ckpt.latest_step() == 6
    cb.ckpt.close()


def test_keras_h5_import_roundtrip(tmp_path):
    """export → import maps every tensor back bitwise (name mapping is
    involutive), on a narrow ResNet-50 topology."""
    model = ResNet(stage_sizes=(3, 4, 6, 3), num_classes=10,
                   width_multiplier=0.0625)
    rng = jax.random.key(0)
    x = np.zeros((1, 64, 64, 3), np.float32)
    v1 = model.init(rng, x, train=False)
    v2 = model.init(jax.random.key(1), x, train=False)

    path = str(tmp_path / "w.h5")
    export_keras_style_h5(path, v1)
    v2_loaded = load_keras_resnet50_h5(path, v2)

    _assert_tree_equal(v1["params"], v2_loaded["params"])
    _assert_tree_equal(v1["batch_stats"], v2_loaded["batch_stats"])
    # and the import really changed v2
    with pytest.raises(AssertionError):
        _assert_tree_equal(v2["params"], v2_loaded["params"])


def test_keras_h5_import_shape_mismatch_raises(tmp_path):
    wide = ResNet(stage_sizes=(3, 4, 6, 3), num_classes=10,
                  width_multiplier=0.0625)
    narrow = ResNet(stage_sizes=(3, 4, 6, 3), num_classes=10,
                    width_multiplier=0.125)
    x = np.zeros((1, 64, 64, 3), np.float32)
    v_wide = wide.init(jax.random.key(0), x, train=False)
    v_narrow = narrow.init(jax.random.key(0), x, train=False)
    path = str(tmp_path / "w.h5")
    export_keras_style_h5(path, v_wide)
    with pytest.raises(ValueError, match="shape"):
        load_keras_resnet50_h5(path, v_narrow)


def test_keras_h5_import_wrong_depth_raises(tmp_path):
    r18_like = ResNet(stage_sizes=(1, 1), num_classes=10,
                      width_multiplier=0.125, small_input_stem=True)
    x = np.zeros((1, 32, 32, 3), np.float32)
    v = r18_like.init(jax.random.key(0), x, train=False)
    path = str(tmp_path / "w.h5")
    export_keras_style_h5(path, v, stage_sizes=(1, 1))
    with pytest.raises(ValueError, match="layers matched"):
        load_keras_resnet50_h5(path, v)  # expects (3,4,6,3) layer names


def test_layer_map_covers_resnet50():
    m = keras_layer_map((3, 4, 6, 3))
    convs = [k for k, (kind, _) in m.items() if kind == "conv"]
    bns = [k for k, (kind, _) in m.items() if kind == "bn"]
    # 1 stem + 48 block convs + 4 shortcuts = 53 convs, same count of BNs.
    assert len(convs) == 53
    assert len(bns) == 53


def test_stablehlo_export_roundtrip(tmp_path):
    """Serialize the compiled forward as StableHLO; reload and match."""
    import jax.numpy as jnp

    from pddl_tpu.ckpt.export import (
        load_inference_artifact,
        save_inference_artifact,
    )
    from pddl_tpu.models.resnet import ResNet

    model = ResNet(stage_sizes=(1,), num_classes=8, width_multiplier=0.25,
                   small_input_stem=True)
    x = jnp.linspace(0, 1, 1 * 16 * 16 * 3).reshape(1, 16, 16, 3)
    variables = model.init(jax.random.key(0), x, train=False)

    path = str(tmp_path / "resnet.shlo")
    save_inference_artifact(
        path, model, variables["params"], (1, 16, 16, 3),
        batch_stats=variables.get("batch_stats"),
    )
    assert os.path.getsize(path) > 0

    call, exported = load_inference_artifact(path)
    got = call(x)
    want = model.apply(variables, x, train=False)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-6, atol=1e-6)
    # The artifact records its input contract.
    assert exported.in_avals[0].shape == (1, 16, 16, 3)


def test_stablehlo_export_multi_platform():
    """platforms=... records several targets in one artifact."""
    import jax.numpy as jnp

    from pddl_tpu.ckpt.export import (
        export_inference_fn,
        load_inference_artifact,
    )
    from pddl_tpu.models.resnet import ResNet

    model = ResNet(stage_sizes=(1,), num_classes=4, width_multiplier=0.25,
                   small_input_stem=True)
    x = jnp.zeros((1, 8, 8, 3))
    variables = model.init(jax.random.key(0), x, train=False)
    data = export_inference_fn(
        model, variables["params"], (1, 8, 8, 3),
        batch_stats=variables.get("batch_stats"),
        platforms=("cpu", "tpu"),
    )
    call, exported = load_inference_artifact(data)
    assert set(p.lower() for p in exported.platforms) == {"cpu", "tpu"}
    assert np.asarray(call(np.asarray(x))).shape == (1, 4)


def test_keras_h5_import_into_s2d_stem(tmp_path):
    """A Keras-stem .h5 loads into the space-to-depth variant through the
    exact 7x7 -> 4x4x12 kernel transform; both models then compute the
    same logits on the same input."""
    kw = dict(stage_sizes=(2, 2), num_classes=10, width_multiplier=0.125)
    src = ResNet(**kw)
    dst = ResNet(**kw, stem="space_to_depth")
    rng = jax.random.key(0)
    x = jax.random.normal(jax.random.key(2), (1, 64, 64, 3))
    v_src = src.init(rng, x, train=False)
    v_dst = dst.init(jax.random.key(1), x, train=False)

    path = str(tmp_path / "w.h5")
    export_keras_style_h5(path, v_src, stage_sizes=(2, 2))
    v_loaded = load_keras_resnet50_h5(path, v_dst, stage_sizes=(2, 2))

    y_src = src.apply(v_src, x, train=False)
    y_dst = dst.apply(v_loaded, x, train=False)
    np.testing.assert_allclose(np.asarray(y_dst), np.asarray(y_src),
                               atol=1e-4, rtol=2e-3)

    # And the reverse: an .h5 exported FROM the s2d model loads back into
    # the keras-shaped stem (full round trip through both transforms).
    back_path = str(tmp_path / "w_s2d.h5")
    export_keras_style_h5(back_path, v_loaded, stage_sizes=(2, 2))
    v_back = load_keras_resnet50_h5(
        back_path, src.init(jax.random.key(3), x, train=False),
        stage_sizes=(2, 2))
    y_back = src.apply(v_back, x, train=False)
    np.testing.assert_allclose(np.asarray(y_back), np.asarray(y_src),
                               atol=1e-4, rtol=2e-3)


def test_restore_pre_ema_batch_stats_checkpoint(tmp_path):
    """Migration: a checkpoint written before TrainState grew
    ema_batch_stats (r2 layout) restores into a BN+EMA trainer — the
    stats shadow is seeded from the restored live batch_stats instead of
    failing the orbax structure match."""
    old = _trainer(ema_decay=0.9)
    old.fit(_dataset(), epochs=1, steps_per_epoch=2, verbose=0)
    legacy_state = old.state.replace(ema_batch_stats=None)  # r2 tree shape
    ckpt = Checkpointer(str(tmp_path / "ck"), async_save=False)
    ckpt.save(legacy_state, epoch=0)
    ckpt.wait()

    new = _trainer(ema_decay=0.9)
    new.init_state(next(iter(_dataset())))
    assert jax.tree.leaves(new.state.ema_batch_stats)  # BN model, shadow on
    restored = ckpt.restore(new.state)
    ckpt.close()

    _assert_tree_equal(restored.params, old.state.params)
    _assert_tree_equal(restored.batch_stats, old.state.batch_stats)
    # Shadow seeded from the restored stats (its init-time value).
    _assert_tree_equal(restored.ema_batch_stats, old.state.batch_stats)


def test_decode_program_export_roundtrip(tmp_path):
    """The SERVING artifact for the LM families: prefill + full decode
    scan (sampling included) export as StableHLO, reload, and reproduce
    gpt.generate()'s tokens exactly — greedy and temperature/top-k."""
    import jax.numpy as jnp

    from pddl_tpu.ckpt.export import (
        load_decode_artifact,
        save_decode_artifact,
    )
    from pddl_tpu.models.gpt import generate, tiny_gpt

    model = tiny_gpt(vocab_size=32, max_len=64)
    prompt = jnp.array([[1, 2, 3, 4, 5, 6, 7, 8],
                        [9, 10, 11, 12, 13, 14, 15, 16]], jnp.int32)
    variables = model.init(jax.random.key(0), prompt)
    params = variables["params"]

    # greedy
    path = str(tmp_path / "decode.zip")
    save_decode_artifact(path, model, params, batch=2, prompt_len=8,
                         max_new_tokens=12)
    prefill, decode, manifest = load_decode_artifact(path)
    assert manifest["max_new_tokens"] == 12
    cache, logits = prefill(params, prompt)
    toks = decode(params, cache, logits,
                  jax.random.key_data(jax.random.key(0)))
    want = generate(model, variables, prompt, 12)
    np.testing.assert_array_equal(np.asarray(toks),
                                  np.asarray(want[:, 8:]))

    # temperature + top-k sampling: same key data => same tokens
    path2 = str(tmp_path / "decode_t.zip")
    save_decode_artifact(path2, model, params, batch=2, prompt_len=8,
                         max_new_tokens=12, temperature=0.8, top_k=8)
    prefill2, decode2, _ = load_decode_artifact(path2)
    key = jax.random.key(42)
    cache2, logits2 = prefill2(params, prompt)
    toks2 = decode2(params, cache2, logits2, jax.random.key_data(key))
    want2 = generate(model, variables, prompt, 12, temperature=0.8,
                     top_k=8, rng=key)
    np.testing.assert_array_equal(np.asarray(toks2),
                                  np.asarray(want2[:, 8:]))


def test_decode_program_export_llama(tmp_path):
    """The modern-decoder family (GQA + rolling SWA cache) exports the
    same way — the cache tree crosses the boundary opaquely."""
    import jax.numpy as jnp

    from pddl_tpu.ckpt.export import (
        load_decode_artifact,
        save_decode_artifact,
    )
    from pddl_tpu.models.gpt import generate
    from pddl_tpu.models.llama import tiny_llama

    model = tiny_llama(vocab_size=32, max_len=64)
    prompt = jnp.arange(8, dtype=jnp.int32).reshape(1, 8) % 32
    variables = model.init(jax.random.key(1), prompt)
    params = variables["params"]

    path = str(tmp_path / "llama_decode.zip")
    save_decode_artifact(path, model, params, batch=1, prompt_len=8,
                         max_new_tokens=10)
    prefill, decode, _ = load_decode_artifact(path)
    cache, logits = prefill(params, prompt)
    toks = decode(params, cache, logits,
                  jax.random.key_data(jax.random.key(0)))
    want = generate(model, variables, prompt, 10)
    np.testing.assert_array_equal(np.asarray(toks),
                                  np.asarray(want[:, 8:]))


def test_decode_program_export_int8(tmp_path):
    """Int8 serving exports: the artifact's parameter ARGUMENTS are the
    int8+scale leaves (half the serving bytes) and the dequant compiles
    into the programs — reload must reproduce generate() on the same
    quantized weights exactly."""
    import jax.numpy as jnp

    from pddl_tpu.ckpt.export import (
        load_decode_artifact,
        save_decode_artifact,
    )
    from pddl_tpu.models.gpt import generate, tiny_gpt
    from pddl_tpu.ops.quant import dequantize, quantize_int8

    model = tiny_gpt(vocab_size=32, max_len=64)
    prompt = jnp.arange(8, dtype=jnp.int32).reshape(2, 4) % 32
    params = model.init(jax.random.key(0), prompt)["params"]
    qparams = quantize_int8(params, min_elems=128)

    path = str(tmp_path / "decode_int8.zip")
    save_decode_artifact(path, model, qparams, batch=2, prompt_len=4,
                         max_new_tokens=9, param_transform=dequantize)
    prefill, decode, manifest = load_decode_artifact(path)
    assert manifest["quantized_params"] is True
    cache, logits = prefill(qparams, prompt)
    toks = decode(qparams, cache, logits,
                  jax.random.key_data(jax.random.key(0)))
    want = generate(model, {"params": dequantize(qparams)}, prompt, 9)
    np.testing.assert_array_equal(np.asarray(toks),
                                  np.asarray(want[:, 4:]))
