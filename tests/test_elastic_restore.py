"""Elastic resume: a checkpoint written on one mesh restores onto a
different device count/layout.

The reference cannot do this at all (its only persistence is a final
Keras .h5, ``/root/reference/imagenet-resnet50.py:69-72``). Here restore
targets the NEW state's ``NamedSharding``s, so Orbax reshards on load —
an 8-chip run resumes on 4 chips (scale-down after hardware loss) or on
a single device, with bitwise-identical parameters."""

import jax
import numpy as np
import pytest

from pddl_tpu.ckpt.checkpoint import Checkpointer
from pddl_tpu.core.mesh import MeshConfig, build_mesh
from pddl_tpu.data.synthetic import SyntheticImageClassification
from pddl_tpu.models.resnet import ResNet
from pddl_tpu.parallel.ps import ParameterServerStrategy
from pddl_tpu.train.loop import Trainer


def _model():
    return ResNet(stage_sizes=(1,), num_classes=8, width_multiplier=0.25,
                  small_input_stem=True)


def _fit_trainer(n_devices, steps=2, eight=None):
    strategy = ParameterServerStrategy(min_shard_bytes=1 << 8)
    strategy._mesh = build_mesh(MeshConfig(data=n_devices),
                                devices=eight[:n_devices])
    trainer = Trainer(_model(), optimizer="adam", learning_rate=1e-3,
                      strategy=strategy, seed=0)
    data = SyntheticImageClassification(
        batch_size=strategy.scale_batch_size(2), image_size=16,
        num_classes=8, seed=0,
    )
    if steps:
        trainer.fit(data, epochs=1, steps_per_epoch=steps, verbose=0)
    else:
        trainer.init_state(next(iter(data)))
    return trainer


def _leaves_equal(a, b):
    for la, lb in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_array_equal(np.asarray(jax.device_get(la)),
                                      np.asarray(jax.device_get(lb)))


@pytest.mark.parametrize("restore_devices", [4, 1])
def test_restore_onto_smaller_mesh(tmp_path, eight_devices, restore_devices):
    big = _fit_trainer(8, steps=2, eight=eight_devices)
    ckpt = Checkpointer(str(tmp_path), async_save=False)
    ckpt.save(big.state, epoch=0)
    ckpt.wait()

    small = _fit_trainer(restore_devices, steps=0, eight=eight_devices)
    restored = ckpt.restore(small.state)
    ckpt.close()

    # Identical parameter values...
    _leaves_equal(restored.params, big.state.params)
    assert int(jax.device_get(restored.step)) == 2
    # ...but laid out for the SMALL mesh (restore reshards, not replays).
    for leaf in jax.tree.leaves(restored.params):
        assert leaf.sharding.mesh.devices.size == restore_devices

    # And training continues from the restored state on the small mesh.
    small.state = restored
    data = SyntheticImageClassification(
        batch_size=small.strategy.scale_batch_size(2), image_size=16,
        num_classes=8, seed=1,
    )
    small.fit(data, epochs=1, steps_per_epoch=1, verbose=0)
    assert int(jax.device_get(small.state.step)) == 3
    assert np.isfinite(small.history.history["loss"][-1])


def test_restore_onto_larger_mesh(tmp_path, eight_devices):
    """Scale-UP resume: 2-device checkpoint onto the full 8-device mesh."""
    small = _fit_trainer(2, steps=1, eight=eight_devices)
    ckpt = Checkpointer(str(tmp_path), async_save=False)
    ckpt.save(small.state, epoch=0)
    ckpt.wait()

    big = _fit_trainer(8, steps=0, eight=eight_devices)
    restored = ckpt.restore(big.state)
    ckpt.close()
    _leaves_equal(restored.params, small.state.params)
    for leaf in jax.tree.leaves(restored.params):
        assert leaf.sharding.mesh.devices.size == 8
