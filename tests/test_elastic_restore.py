"""Elastic resume: a checkpoint written on one mesh restores onto a
different device count/layout.

The reference cannot do this at all (its only persistence is a final
Keras .h5, ``/root/reference/imagenet-resnet50.py:69-72``). Here restore
targets the NEW state's ``NamedSharding``s, so Orbax reshards on load —
an 8-chip run resumes on 4 chips (scale-down after hardware loss) or on
a single device, with bitwise-identical parameters."""

import jax
import numpy as np
import pytest

from pddl_tpu.ckpt.checkpoint import Checkpointer
from pddl_tpu.core.mesh import MeshConfig, build_mesh
from pddl_tpu.data.synthetic import SyntheticImageClassification
from pddl_tpu.models.resnet import ResNet
from pddl_tpu.parallel.ps import ParameterServerStrategy
from pddl_tpu.train.loop import Trainer


def _model():
    return ResNet(stage_sizes=(1,), num_classes=8, width_multiplier=0.25,
                  small_input_stem=True)


def _fit_trainer(n_devices, steps=2, eight=None):
    strategy = ParameterServerStrategy(min_shard_bytes=1 << 8)
    strategy._mesh = build_mesh(MeshConfig(data=n_devices),
                                devices=eight[:n_devices])
    trainer = Trainer(_model(), optimizer="adam", learning_rate=1e-3,
                      strategy=strategy, seed=0)
    data = SyntheticImageClassification(
        batch_size=strategy.scale_batch_size(2), image_size=16,
        num_classes=8, seed=0,
    )
    if steps:
        trainer.fit(data, epochs=1, steps_per_epoch=steps, verbose=0)
    else:
        trainer.init_state(next(iter(data)))
    return trainer


def _leaves_equal(a, b):
    for la, lb in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_array_equal(np.asarray(jax.device_get(la)),
                                      np.asarray(jax.device_get(lb)))


@pytest.mark.parametrize("restore_devices", [4, 1])
def test_restore_onto_smaller_mesh(tmp_path, eight_devices, restore_devices):
    big = _fit_trainer(8, steps=2, eight=eight_devices)
    ckpt = Checkpointer(str(tmp_path), async_save=False)
    ckpt.save(big.state, epoch=0)
    ckpt.wait()

    small = _fit_trainer(restore_devices, steps=0, eight=eight_devices)
    restored = ckpt.restore(small.state)
    ckpt.close()

    # Identical parameter values...
    _leaves_equal(restored.params, big.state.params)
    assert int(jax.device_get(restored.step)) == 2
    # ...but laid out for the SMALL mesh (restore reshards, not replays).
    for leaf in jax.tree.leaves(restored.params):
        assert leaf.sharding.mesh.devices.size == restore_devices

    # And training continues from the restored state on the small mesh.
    small.state = restored
    data = SyntheticImageClassification(
        batch_size=small.strategy.scale_batch_size(2), image_size=16,
        num_classes=8, seed=1,
    )
    small.fit(data, epochs=1, steps_per_epoch=1, verbose=0)
    assert int(jax.device_get(small.state.step)) == 3
    assert np.isfinite(small.history.history["loss"][-1])


def test_restore_onto_larger_mesh(tmp_path, eight_devices):
    """Scale-UP resume: 2-device checkpoint onto the full 8-device mesh."""
    small = _fit_trainer(2, steps=1, eight=eight_devices)
    ckpt = Checkpointer(str(tmp_path), async_save=False)
    ckpt.save(small.state, epoch=0)
    ckpt.wait()

    big = _fit_trainer(8, steps=0, eight=eight_devices)
    restored = ckpt.restore(big.state)
    ckpt.close()
    _leaves_equal(restored.params, small.state.params)
    for leaf in jax.tree.leaves(restored.params):
        assert leaf.sharding.mesh.devices.size == 8


def _fit_ps_trainer(model, *, num_ps, eight, steps=2, min_bytes=1 << 8,
                    image=16, classes=8):
    strategy = ParameterServerStrategy(num_ps=num_ps,
                                       min_shard_bytes=min_bytes)
    strategy._mesh = build_mesh(MeshConfig(data=8), devices=eight)
    trainer = Trainer(model, optimizer="adam", learning_rate=1e-3,
                      strategy=strategy, seed=0)
    data = SyntheticImageClassification(
        batch_size=strategy.scale_batch_size(2), image_size=image,
        num_classes=classes, seed=0,
    )
    if steps:
        trainer.fit(data, epochs=1, steps_per_epoch=steps, verbose=0)
    else:
        trainer.init_state(next(iter(data)))
    return trainer


def test_restore_across_axis_factorizations(tmp_path, eight_devices):
    """A checkpoint saved under FACTORED sub-axis layouts (num_ps=3: 3-way
    shard x replicate over the 8-device axis, core/sharding.py) restores
    onto a different factorization (num_ps=2) — the reference PS
    variables' whole point is surviving topology changes
    (/root/reference/imagenet-resnet50-ps.py:75-84)."""
    saved = _fit_ps_trainer(_model(), num_ps=3, eight=eight_devices)
    # The factored layout must actually be in play, or this test is
    # restore_onto_same_mesh in disguise.
    sub_axis = [
        leaf for leaf in jax.tree.leaves(saved.state.params)
        if any("_shard" in str(n) for n in leaf.sharding.mesh.axis_names)
    ]
    assert sub_axis, "num_ps=3 produced no factored sub-axis shardings"

    ckpt = Checkpointer(str(tmp_path), async_save=False)
    ckpt.save(saved.state, epoch=0)
    ckpt.wait()

    target = _fit_ps_trainer(_model(), num_ps=2, eight=eight_devices,
                             steps=0)
    restored = ckpt.restore(target.state)
    ckpt.close()

    _leaves_equal(restored.params, saved.state.params)
    _leaves_equal(restored.opt_state, saved.state.opt_state)
    # ...laid out per the NEW factorization, not the saved one.
    for a, b in zip(jax.tree.leaves(restored.params),
                    jax.tree.leaves(target.state.params)):
        assert a.sharding == b.sharding

    # Training continues under the new layout.
    target.state = restored
    data = SyntheticImageClassification(
        batch_size=target.strategy.scale_batch_size(2), image_size=16,
        num_classes=8, seed=1,
    )
    target.fit(data, epochs=1, steps_per_epoch=1, verbose=0)
    assert np.isfinite(target.history.history["loss"][-1])


def test_restore_ps_checkpoint_onto_tp_mesh(tmp_path, eight_devices):
    """Cross-STRATEGY portability: a ViT trained under PS/ZeRO sharded
    state restores onto a Megatron TP mesh (data=4 x model=2), with the
    weights re-laid out per the TP rules and training continuing."""
    from pddl_tpu.models.vit import tiny_vit
    from pddl_tpu.parallel.tensor_parallel import TensorParallelStrategy

    saved = _fit_ps_trainer(tiny_vit(num_classes=8), num_ps=3,
                            eight=eight_devices, image=32)

    ckpt = Checkpointer(str(tmp_path), async_save=False)
    ckpt.save(saved.state, epoch=0)
    ckpt.wait()

    tp = TensorParallelStrategy(model_parallel=2)
    tp._mesh = build_mesh(MeshConfig(data=4, model=2),
                          devices=eight_devices)
    target = Trainer(tiny_vit(num_classes=8), optimizer="adam",
                     learning_rate=1e-3, strategy=tp, seed=0)
    data = SyntheticImageClassification(
        batch_size=tp.scale_batch_size(2), image_size=32, num_classes=8,
        seed=1,
    )
    target.init_state(next(iter(data)))
    restored = ckpt.restore(target.state)
    ckpt.close()

    _leaves_equal(restored.params, saved.state.params)
    # The restored weights follow the TP layout: at least one leaf is
    # genuinely sharded over the `model` axis.
    def on_model_axis(leaf):
        spec = getattr(leaf.sharding, "spec", ())
        return any("model" in str(s) for s in jax.tree.leaves(list(spec)))

    assert any(on_model_axis(l) for l in jax.tree.leaves(restored.params))
    for a, b in zip(jax.tree.leaves(restored.params),
                    jax.tree.leaves(target.state.params)):
        assert a.sharding == b.sharding

    target.state = restored
    target.fit(data, epochs=1, steps_per_epoch=1, verbose=0)
    assert np.isfinite(target.history.history["loss"][-1])
