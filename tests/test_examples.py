"""The examples/ scripts must actually run (on the fake CPU mesh)."""

import os
import subprocess
import sys

import pytest

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_EXAMPLES = sorted(
    f for f in os.listdir(os.path.join(_ROOT, "examples"))
    if f.endswith(".py")
)


@pytest.mark.parametrize("script", _EXAMPLES)
def test_example_runs(script, tmp_path):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    # A site plugin inherited via PYTHONPATH (e.g. a TPU tunnel's
    # sitecustomize) can pin the platform and defeat JAX_PLATFORMS; an
    # empty sitecustomize FIRST on the path shadows it so the child
    # really runs the 8-device CPU mesh.
    (tmp_path / "sitecustomize.py").write_text("")
    env["PYTHONPATH"] = (str(tmp_path) + os.pathsep + _ROOT + os.pathsep
                         + env.get("PYTHONPATH", ""))
    out = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "examples", script)],
        env=env, cwd=_ROOT, capture_output=True, text=True, timeout=560,
    )
    assert out.returncode == 0, f"{script} failed:\n{out.stderr[-3000:]}"
