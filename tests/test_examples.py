"""The examples/ scripts must actually run (on the fake CPU mesh)."""

import os
import subprocess
import sys
import time

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_EXAMPLES = sorted(
    f for f in os.listdir(os.path.join(_ROOT, "examples"))
    # workflow_rehearsal runs TWO sequential training legs (preempt ->
    # resume) — too long for this test's shared concurrent deadline; it
    # gets its own sequential test below.
    if f.endswith(".py") and f != "workflow_rehearsal.py"
)


def test_examples_run(tmp_path):
    """All examples, launched CONCURRENTLY (each is import+compile bound;
    running them in parallel takes the wall-clock of the slowest one)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    # Scripts with a full-scale default (real_data_convergence) run tiny.
    env["PDDL_EXAMPLE_SMOKE"] = "1"
    # A site plugin inherited via PYTHONPATH (e.g. a TPU tunnel's
    # sitecustomize) can pin the platform and defeat JAX_PLATFORMS; an
    # empty sitecustomize FIRST on the path shadows it so the children
    # really run the 8-device CPU mesh.
    (tmp_path / "sitecustomize.py").write_text("")
    env["PYTHONPATH"] = (str(tmp_path) + os.pathsep + _ROOT + os.pathsep
                         + env.get("PYTHONPATH", ""))
    # Children write to FILES, not pipes: a pipe drained sequentially
    # would stall any child emitting more than the OS buffer while an
    # earlier sibling is being waited on.
    procs = {}
    logs = {}
    for script in _EXAMPLES:
        logs[script] = open(tmp_path / f"{script}.log", "w+")
        # Isolate mutable state per test run: these examples default to a
        # fixed /tmp work dir shared across sessions.
        extra = (["--work-dir", str(tmp_path / f"work_{script}")]
                 if script in ("real_data_convergence.py",
                               "generate_python.py") else [])
        procs[script] = subprocess.Popen(
            [sys.executable, os.path.join(_ROOT, "examples", script), *extra],
            env=env, cwd=_ROOT, stdout=logs[script],
            stderr=subprocess.STDOUT, text=True,
        )
    failures = []
    deadline = time.monotonic() + 540  # shared: children run concurrently
    try:
        for script, p in procs.items():
            timed_out = False
            try:
                p.wait(timeout=max(1.0, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                timed_out = True
                p.kill()
                p.wait()
            logs[script].seek(0)
            out = logs[script].read()
            if timed_out:
                failures.append(f"{script} timed out:\n{out[-3000:]}")
            elif p.returncode != 0:
                failures.append(f"{script} (rc={p.returncode}):\n{out[-3000:]}")
    finally:
        for p in procs.values():
            if p.poll() is None:
                p.kill()
                p.wait()
        for f in logs.values():
            f.close()
    assert not failures, "\n\n".join(failures)


def test_workflow_rehearsal_smoke(tmp_path):
    """The four-leg reference-workflow rehearsal (preempt -> resume ->
    export -> re-import check) in smoke mode, run ALONE: two sequential
    training legs don't fit the concurrent test's shared deadline."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    env["PDDL_EXAMPLE_SMOKE"] = "1"
    (tmp_path / "sitecustomize.py").write_text("")
    env["PYTHONPATH"] = (str(tmp_path) + os.pathsep + _ROOT + os.pathsep
                         + env.get("PYTHONPATH", ""))
    proc = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "examples",
                                      "workflow_rehearsal.py"),
         "--work-dir", str(tmp_path / "work"),
         "--artifacts-dir", str(tmp_path / "art")],
        env=env, cwd=_ROOT, capture_output=True, text=True, timeout=540,
    )
    assert proc.returncode == 0, proc.stdout[-3000:] + proc.stderr[-2000:]
    assert "REHEARSAL PASS" in proc.stdout
