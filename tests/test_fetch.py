"""Pretrained-weight acquisition (`ckpt/fetch.py`) — the explicit-opt-in
analogue of the reference's implicit ``weights='imagenet'`` download
(`/root/reference/imagenet-pretrained-resnet50.py:56`)."""

import hashlib

import pytest

from pddl_tpu.ckpt.fetch import (
    KERAS_RESNET_WEIGHTS,
    fetch_keras_resnet50_weights,
)


def test_missing_file_error_is_the_offline_procedure(tmp_path):
    with pytest.raises(FileNotFoundError) as ei:
        fetch_keras_resnet50_weights(cache_dir=str(tmp_path))
    msg = str(ei.value)
    # The error must hand the user the exact acquisition command.
    assert "curl" in msg
    assert "resnet50_weights_tf_dim_ordering_tf_kernels_notop.h5" in msg
    assert "storage.googleapis.com" in msg
    assert KERAS_RESNET_WEIGHTS["resnet50"]["notop"][1] in msg  # the MD5


def test_cached_file_verified_and_returned(tmp_path, monkeypatch):
    payload = b"pretend-weights"
    name = "resnet50_weights_tf_dim_ordering_tf_kernels_notop.h5"
    (tmp_path / name).write_bytes(payload)
    # A wrong file must not be silently accepted.
    with pytest.raises(ValueError, match="MD5 mismatch"):
        fetch_keras_resnet50_weights(cache_dir=str(tmp_path))
    # With the published hash patched to the payload's, the cache hit wins
    # (no network involved).
    monkeypatch.setitem(
        KERAS_RESNET_WEIGHTS["resnet50"],
        "notop", (name, hashlib.md5(payload).hexdigest()),
    )
    path = fetch_keras_resnet50_weights(cache_dir=str(tmp_path))
    assert path == str(tmp_path / name)
    # verify=False skips hashing entirely (restore the real constant).
    monkeypatch.setitem(
        KERAS_RESNET_WEIGHTS["resnet50"],
        "notop", (name, "0" * 32),
    )
    assert fetch_keras_resnet50_weights(
        cache_dir=str(tmp_path), verify=False
    ) == str(tmp_path / name)


def test_download_opt_in(tmp_path, monkeypatch):
    payload = b"downloaded-weights"
    name = "resnet50_weights_tf_dim_ordering_tf_kernels_notop.h5"
    monkeypatch.setitem(
        KERAS_RESNET_WEIGHTS["resnet50"],
        "notop", (name, hashlib.md5(payload).hexdigest()),
    )
    fetched_urls = []

    def fake_urlretrieve(url, dst):
        fetched_urls.append(url)
        with open(dst, "wb") as f:
            f.write(payload)

    monkeypatch.setattr("urllib.request.urlretrieve", fake_urlretrieve)
    path = fetch_keras_resnet50_weights(
        cache_dir=str(tmp_path), download=True
    )
    assert (tmp_path / name).read_bytes() == payload
    assert fetched_urls == [
        "https://storage.googleapis.com/tensorflow/keras-applications/"
        "resnet/" + name
    ]
    # Second call: cache hit, no new fetch.
    fetch_keras_resnet50_weights(cache_dir=str(tmp_path), download=True)
    assert len(fetched_urls) == 1
    assert path == str(tmp_path / name)


def test_unknown_variant_raises():
    with pytest.raises(ValueError, match="unknown weights"):
        fetch_keras_resnet50_weights(variant="bottom")
    with pytest.raises(ValueError, match="unknown weights"):
        fetch_keras_resnet50_weights(model="resnet34")


def test_pretrained_preset_resolves_through_fetch(tmp_path, monkeypatch):
    """run_experiment on a pretrained preset reaches the fetch helper and
    surfaces its offline procedure when the cache is cold (wiring check for
    `--preset single-pretrained` from a clean machine)."""
    from pddl_tpu.config import get_preset
    from pddl_tpu.run import run_experiment

    monkeypatch.setenv("PDDL_TPU_CACHE", str(tmp_path))
    cfg = get_preset("single-pretrained", steps_per_epoch=1, epochs=1,
                     verbose=0)
    assert cfg.weights == "imagenet"
    # Resolution is hoisted above model/mesh/data construction, so the
    # cold-cache failure is immediate (no ResNet-50 init happens first).
    with pytest.raises(FileNotFoundError, match="curl"):
        run_experiment(cfg)
    # Families without published keras weights fail with a clear error
    # instead of silently fetching the ResNet-50 file.
    with pytest.raises(ValueError, match="unknown weights"):
        run_experiment(cfg.replace(model="tiny_resnet", num_classes=10))
