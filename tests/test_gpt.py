"""GPT family: causal attention on the training path, all strategies.

The reference is vision-only; the GPT line is the long-context workload
(SURVEY.md §5) — it exercises causal flash attention and causal ring
attention end to end. Checks: causality (future tokens cannot influence
past logits), flash == reference numerics through the full model, ring
attention on a seq-sharded mesh matches, the LM learns a deterministic
next-token task, and Megatron TP applies unchanged (shared block names).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from pddl_tpu.core.mesh import MODEL_AXIS, MeshConfig, build_mesh
from pddl_tpu.data.synthetic import SyntheticLanguageModeling
from pddl_tpu.models.gpt import GPT, tiny_gpt
from pddl_tpu.parallel import MirroredStrategy, TensorParallelStrategy
from pddl_tpu.train.loop import Trainer


def _tokens(b=2, s=32, vocab=64, seed=0):
    return jax.random.randint(jax.random.key(seed), (b, s), 0, vocab)


def test_causality_future_tokens_do_not_leak():
    model = tiny_gpt()
    x = _tokens()
    variables = model.init(jax.random.key(1), x, train=False)
    base = model.apply(variables, x, train=False)
    # Perturb the last 8 tokens; logits for earlier positions must not move.
    x2 = x.at[:, -8:].set((x[:, -8:] + 7) % 64)
    out = model.apply(variables, x2, train=False)
    np.testing.assert_allclose(np.asarray(out[:, :-8]),
                               np.asarray(base[:, :-8]), atol=1e-5, rtol=1e-5)
    assert np.abs(np.asarray(out[:, -8:]) - np.asarray(base[:, -8:])).max() > 1e-3


def test_flash_matches_reference_through_model():
    ref_model = tiny_gpt(attention="reference")
    x = _tokens(s=64)
    variables = ref_model.init(jax.random.key(1), x, train=False)
    ref = ref_model.apply(variables, x, train=False)
    flash_model = tiny_gpt(attention="flash")
    out = flash_model.apply(variables, x, train=False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-4, rtol=2e-4)


def test_ring_attention_gpt_matches_reference(mesh8):
    mesh = build_mesh(MeshConfig(data=1, seq=8))
    ref_model = tiny_gpt(attention="reference")
    x = _tokens(b=1, s=64)
    variables = ref_model.init(jax.random.key(1), x, train=False)
    ref = ref_model.apply(variables, x, train=False)
    ring_model = tiny_gpt(attention="ring", mesh=mesh)
    out = jax.jit(lambda v, xx: ring_model.apply(v, xx, train=False))(variables, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-4, rtol=2e-4)


def test_gpt_learns_next_token_task():
    ds = SyntheticLanguageModeling(batch_size=32, seq_len=32, vocab_size=16,
                                   seed=0)
    tr = Trainer(tiny_gpt(vocab_size=16), optimizer="adamw",
                 learning_rate=3e-3, strategy=MirroredStrategy(), seed=0,
                 input_key="tokens", target_key="targets")
    hist = tr.fit(ds, epochs=3, steps_per_epoch=8, verbose=0)
    assert hist.history["loss"][-1] < hist.history["loss"][0] * 0.7
    assert hist.history["accuracy"][-1] > hist.history["accuracy"][0]


def test_gpipe_gpt_matches_sequential_and_trains():
    """PP x long-context: the pipelined causal LM is exactly the sequential
    model, and it trains under PipelineStrategy (DP x PP)."""
    from pddl_tpu.models.gpt import GPipeGPT
    from pddl_tpu.parallel import PipelineStrategy

    strategy = PipelineStrategy(n_stages=4)  # data=2 x stage=4
    mesh = strategy.setup()
    model = GPipeGPT(vocab_size=16, n_stages=4, blocks_per_stage=1,
                     n_microbatches=2, mesh=mesh, max_len=64, embed_dim=32,
                     num_heads=4)
    x = _tokens(b=4, s=32, vocab=16)
    variables = model.init(jax.random.key(1), x)
    piped = np.asarray(jax.jit(lambda v, xx: model.apply(v, xx))(variables, x))
    seq = np.asarray(model.apply_sequential(variables, x))
    np.testing.assert_allclose(piped, seq, atol=1e-4, rtol=1e-4)

    # Causality survives the pipeline.
    x2 = x.at[:, -8:].set((x[:, -8:] + 5) % 16)
    out2 = np.asarray(model.apply(variables, x2, train=False))
    np.testing.assert_allclose(out2[:, :-8], piped[:, :-8],
                               atol=1e-4, rtol=1e-4)

    ds = SyntheticLanguageModeling(batch_size=8, seq_len=32, vocab_size=16,
                                  seed=0)
    tr = Trainer(model, optimizer="adamw", learning_rate=3e-3,
                 strategy=strategy, input_key="tokens", target_key="targets",
                 seed=0)
    hist = tr.fit(ds, epochs=2, steps_per_epoch=4, verbose=0)
    assert hist.history["loss"][-1] < hist.history["loss"][0]
    # Stage weights sharded one per position.
    leaf = jax.tree.leaves(tr.state.params["stages"])[0]
    assert leaf.sharding.spec[0] == "stage"


def test_decode_cache_matches_full_forward():
    """Step-by-step KV-cache decoding must reproduce the full causal
    forward's logits at every position."""
    from pddl_tpu.models.gpt import generate  # noqa: F401 (import check)

    model = tiny_gpt(vocab_size=16, max_len=32)
    x = _tokens(b=2, s=16, vocab=16)
    variables = model.init(jax.random.key(1), x, train=False)
    full = model.apply(variables, x, train=False)        # (B, S, V)

    dec = model.clone(decode=True)
    cache = dec.init(jax.random.key(0), x[:, :1], train=False)["cache"]
    step_logits = []
    for i in range(x.shape[1]):
        out, mutated = dec.apply(
            {"params": variables["params"], "cache": cache},
            x[:, i:i + 1], train=False, mutable=["cache"])
        cache = mutated["cache"]
        step_logits.append(out[:, 0])
    decoded = jnp.stack(step_logits, axis=1)
    np.testing.assert_allclose(np.asarray(decoded), np.asarray(full),
                               atol=1e-4, rtol=1e-4)


def test_generate_continues_learned_sequence():
    """Train on the deterministic next-token task, then greedy generation
    must reproduce the true recurrence — the end-to-end LM story."""
    from pddl_tpu.models.gpt import generate

    ds = SyntheticLanguageModeling(batch_size=32, seq_len=32, vocab_size=16,
                                   seed=0)
    model = tiny_gpt(vocab_size=16, max_len=48)
    tr = Trainer(model, optimizer="adamw", learning_rate=3e-3,
                 strategy=MirroredStrategy(), seed=0,
                 input_key="tokens", target_key="targets")
    hist = tr.fit(ds, epochs=6, steps_per_epoch=8, verbose=0)
    assert hist.history["accuracy"][-1] > 0.95, hist.history["accuracy"]

    variables = {"params": jax.device_get(tr.state.params)}
    batch = ds.batch(0)
    prompt = jnp.asarray(batch["tokens"][:4, :8])
    out = generate(model, variables, prompt, max_new_tokens=8)
    assert out.shape == (4, 16)
    # True continuation under the affine recurrence the data follows.
    seq = np.asarray(prompt)
    cur = seq[:, -1]
    expected = []
    for _ in range(8):
        cur = (ds.a * cur + ds.b) % ds.vocab_size
        expected.append(cur)
    expected = np.stack(expected, axis=1)
    match = (np.asarray(out[:, 8:]) == expected).mean()
    assert match > 0.9, f"generated continuation only {match:.0%} correct"


def test_gpt_under_tensor_parallel():
    strategy = TensorParallelStrategy(model_parallel=4)
    ds = SyntheticLanguageModeling(batch_size=16, seq_len=32, vocab_size=16,
                                   seed=0)
    tr = Trainer(tiny_gpt(vocab_size=16), optimizer="adamw",
                 learning_rate=3e-3, strategy=strategy, seed=0,
                 input_key="tokens", target_key="targets")
    hist = tr.fit(ds, epochs=1, steps_per_epoch=4, verbose=0)
    assert np.isfinite(hist.history["loss"][-1])
    # The Megatron rules hit the shared TransformerBlock param names.
    qk = tr.state.params["block0"]["attn"]["query"]["kernel"]
    assert qk.sharding.spec == P(None, MODEL_AXIS)


def test_sample_logits_filters():
    """top-k / top-p truncation semantics of the sampling step."""
    from pddl_tpu.models.gpt import sample_logits

    logits = jnp.log(jnp.asarray([[0.4, 0.3, 0.2, 0.05, 0.05]]))
    rng = jax.random.key(0)

    # top_k=2: only the two largest ids ever sampled.
    draws = {
        int(sample_logits(jax.random.fold_in(rng, i), logits, top_k=2)[0])
        for i in range(64)
    }
    assert draws <= {0, 1} and len(draws) == 2

    # top_p=0.65: the smallest prefix reaching 0.65 is {0.4, 0.3}.
    draws = {
        int(sample_logits(jax.random.fold_in(rng, i), logits, top_p=0.65)[0])
        for i in range(64)
    }
    assert draws <= {0, 1} and len(draws) == 2

    # top_p=0.95 keeps {0.4,0.3,0.2,0.05}: id 4 can appear, but after
    # top_k=3 composes first it cannot.
    draws = {
        int(sample_logits(jax.random.fold_in(rng, i), logits,
                          top_k=3, top_p=0.95)[0])
        for i in range(200)
    }
    assert draws <= {0, 1, 2}

    # Boundary ties: probs [0.4, 0.3, 0.3, ...]; at top_p=0.5 the smallest
    # set reaching 0.5 is {0.4, one 0.3} — a value-threshold formulation
    # would keep BOTH tied 0.3s. The stable descending argsort breaks the
    # tie toward the lower vocab id, so id 2 must never be drawn.
    tie_logits = jnp.log(
        jnp.asarray([[0.4, 0.3, 0.3, 1e-9]], dtype=jnp.float32)
    )
    draws = {
        int(sample_logits(jax.random.fold_in(rng, i), tie_logits, top_p=0.5)[0])
        for i in range(128)
    }
    assert draws <= {0, 1} and len(draws) == 2, draws

    # Degenerate top_p keeps only the argmax; jittable end to end.
    jitted = jax.jit(lambda r, l: sample_logits(r, l, top_p=0.01))
    assert int(jitted(rng, logits)[0]) == 0

    # temperature=0 is the greedy limit, not a division by zero.
    assert int(sample_logits(rng, logits, temperature=0.0)[0]) == 0


def test_generate_with_sampling_filters():
    from pddl_tpu.models.gpt import generate

    model = tiny_gpt(vocab_size=16, max_len=48)
    variables = model.init(jax.random.key(0),
                           jnp.zeros((1, 4), jnp.int32), train=False)
    prompt = jnp.asarray([[1, 2, 3, 4]], jnp.int32)
    out = generate(model, {"params": variables["params"]}, prompt,
                   max_new_tokens=6, temperature=0.8, top_k=4, top_p=0.9,
                   rng=jax.random.key(1))
    assert out.shape == (1, 10)
    assert (np.asarray(out) >= 0).all() and (np.asarray(out) < 16).all()


def test_perplexity_metric():
    from pddl_tpu.train.metrics import perplexity

    # Uniform logits over V -> perplexity V, for both 2D and 3D shapes.
    v = 8
    logits2 = jnp.zeros((5, v))
    labels2 = jnp.arange(5) % v
    np.testing.assert_allclose(float(perplexity(logits2, labels2)), v,
                               rtol=1e-5)
    logits3 = jnp.zeros((2, 3, v))
    labels3 = jnp.zeros((2, 3), jnp.int32)
    np.testing.assert_allclose(float(perplexity(logits3, labels3)), v,
                               rtol=1e-5)

    trainer = Trainer(tiny_gpt(vocab_size=16, max_len=48),
                      optimizer="adamw", learning_rate=3e-3,
                      metrics=["accuracy", "perplexity"],
                      input_key="tokens", target_key="targets")
    ds = SyntheticLanguageModeling(batch_size=8, seq_len=16, vocab_size=16,
                                   seed=0)
    trainer.fit(ds, epochs=2, steps_per_epoch=6, verbose=0)
    ppl = trainer.history.history["perplexity"]
    assert ppl[-1] < ppl[0] <= 16.5  # starts near uniform (16), improves


def test_perplexity_aggregates_geometrically():
    """Epoch perplexity must equal exp(mean CE), not mean(exp(CE))."""
    from pddl_tpu.train.loop import _mean_logs

    # Per-batch perplexity logs in LOG space (mean CE); aggregation
    # exponentiates once -> exp(mean CE), overflow-free at any CE.
    logs = [{"perplexity": 1.0, "loss": 1.0},
            {"perplexity": 3.0, "loss": 3.0}]
    out = _mean_logs(logs)
    np.testing.assert_allclose(out["perplexity"], np.exp(2.0), rtol=1e-6)
    np.testing.assert_allclose(out["loss"], 2.0, rtol=1e-6)
    huge = _mean_logs([{"perplexity": 100.0}, {"perplexity": 200.0}])
    assert np.isfinite(huge["perplexity"]) and huge["perplexity"] > 1e60


def test_tensor_parallel_generate_matches_single_device(mesh4x2):
    """Sharded (TP) decoding must reproduce single-device generation."""
    from pddl_tpu.models.gpt import generate
    from pddl_tpu.parallel.tensor_parallel import TensorParallelStrategy

    model = tiny_gpt(vocab_size=16, max_len=48)
    variables = model.init(jax.random.key(0),
                           jnp.zeros((1, 4), jnp.int32), train=False)
    prompt = jnp.asarray([[3, 1, 4, 1, 5]], jnp.int32)

    ref = generate(model, {"params": variables["params"]}, prompt,
                   max_new_tokens=8)

    strategy = TensorParallelStrategy(model_parallel=2)
    strategy._mesh = mesh4x2
    # Cache shards by head over `model`; params by the Megatron rules.
    sharded = generate(model, {"params": variables["params"]}, prompt,
                       max_new_tokens=8, strategy=strategy)
    np.testing.assert_array_equal(np.asarray(sharded), np.asarray(ref))

    # Filtered sampling composes with sharded decode too.
    out = generate(model, {"params": variables["params"]}, prompt,
                   max_new_tokens=4, temperature=0.8, top_k=4,
                   rng=jax.random.key(2), strategy=strategy)
    assert out.shape == (1, 9)


def test_perplexity_callable_metric_resolves_to_log_space():
    from pddl_tpu.train import metrics as M

    name, fn = M.resolve_metric(M.perplexity)
    assert name == "perplexity" and fn is M.log_perplexity


def test_sampling_misuse_raises():
    from pddl_tpu.models.gpt import generate, sample_logits

    logits = jnp.zeros((1, 8))
    with pytest.raises(ValueError, match="top_p"):
        sample_logits(jax.random.key(0), logits, top_p=0.0)
    with pytest.raises(ValueError, match="top_k"):
        sample_logits(jax.random.key(0), logits, top_k=0)
    # NumPy/device scalars are concrete too — still validated.
    with pytest.raises(ValueError, match="top_p"):
        sample_logits(jax.random.key(0), logits, top_p=np.float32(1.5))
    with pytest.raises(ValueError, match="top_k"):
        sample_logits(jax.random.key(0), logits, top_k=np.int64(0))

    model = tiny_gpt(vocab_size=16, max_len=48)
    v = model.init(jax.random.key(0), jnp.zeros((1, 2), jnp.int32),
                   train=False)
    with pytest.raises(ValueError, match="temperature"):
        generate(model, {"params": v["params"]},
                 jnp.asarray([[1, 2]], jnp.int32), max_new_tokens=2,
                 top_k=4)  # greedy default would silently drop the filter


def test_sample_logits_traced_filters_stay_jittable():
    from pddl_tpu.models.gpt import sample_logits

    logits = jnp.log(jnp.asarray([[0.7, 0.2, 0.1]]))
    f = jax.jit(lambda r, l, p: sample_logits(r, l, top_p=p))
    tok = int(f(jax.random.key(0), logits, jnp.float32(0.9))[0])
    assert 0 <= tok < 3


def test_ring_flash_gpt_matches_reference(mesh8):
    """attention="ring_flash": flash-kernel rotations over the seq axis
    reproduce the reference transformer exactly."""
    mesh = build_mesh(MeshConfig(data=1, seq=8))
    ref_model = tiny_gpt(attention="reference")
    x = _tokens(b=1, s=64)
    variables = ref_model.init(jax.random.key(1), x, train=False)
    ref = ref_model.apply(variables, x, train=False)
    ring_model = tiny_gpt(attention="ring_flash", mesh=mesh)
    out = jax.jit(lambda v, xx: ring_model.apply(v, xx, train=False))(
        variables, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-4, rtol=2e-4)


def test_fused_lm_loss_matches_materialized():
    """fused_lm_loss (features -> chunked CE, logits never materialized)
    computes the same loss AND parameter gradients as the standard
    logits + sparse-CE path, including on a padded-vocab head."""
    import optax

    from pddl_tpu.models.gpt import fused_lm_loss

    for vm in (1, 32):  # plain and vocab_multiple-padded heads
        model = GPT(vocab_size=97, max_len=32, embed_dim=32, depth=2,
                    num_heads=4, attention="reference", vocab_multiple=vm)
        tokens = jnp.asarray(
            np.random.default_rng(0).integers(0, 97, (2, 24)), jnp.int32)
        targets = jnp.asarray(
            np.random.default_rng(1).integers(0, 97, (2, 24)), jnp.int32)
        v = model.init(jax.random.key(0), tokens, train=False)

        def materialized(v):
            logits = model.apply(v, tokens, train=False)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, targets).mean()

        def fused(v):
            return fused_lm_loss(model, v, tokens, targets, train=False)

        lm, gm = jax.value_and_grad(materialized)(v)
        lf, gf = jax.value_and_grad(fused)(v)
        np.testing.assert_allclose(float(lf), float(lm), rtol=1e-6)
        for a, b in zip(jax.tree.leaves(gf), jax.tree.leaves(gm)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-5, rtol=1e-5)

        # Chunked (multi-slab) variant agrees too — the memory valve.
        lc = fused_lm_loss(model, v, tokens, targets, train=False,
                           chunk_size=32)
        np.testing.assert_allclose(float(lc), float(lm), rtol=1e-6)

    # init() with features_only=True must STILL create lm_head (the
    # early return is apply-only), or the params tree silently loses the
    # head and checkpoints go shape-incompatible.
    v_feat = GPT(vocab_size=97, max_len=32, embed_dim=32, depth=1,
                 num_heads=4, attention="reference").init(
        jax.random.key(0), tokens, train=False, features_only=True)
    assert "lm_head" in v_feat["params"]

    # bf16 (the bench/TPU configuration): both paths do the head matmul
    # from bf16 operands with f32 accumulation — bf16-level agreement.
    model = GPT(vocab_size=97, max_len=32, embed_dim=32, depth=2,
                num_heads=4, attention="reference", dtype=jnp.bfloat16)
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, 97, (2, 24)), jnp.int32)
    targets = jnp.asarray(
        np.random.default_rng(1).integers(0, 97, (2, 24)), jnp.int32)
    v = model.init(jax.random.key(0), tokens, train=False)

    def materialized16(v):
        logits = model.apply(v, tokens, train=False)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, targets).mean()

    lm, gm = jax.value_and_grad(materialized16)(v)
    lf, gf = jax.value_and_grad(
        lambda v: fused_lm_loss(model, v, tokens, targets, train=False))(v)
    np.testing.assert_allclose(float(lf), float(lm), rtol=2e-3)
    for a, b in zip(jax.tree.leaves(gf), jax.tree.leaves(gm)):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32),
                                   atol=2e-2, rtol=2e-2)
