"""HF GPT-2 weight import: cross-framework logits parity.

The LM analogue of test_keras_parity.py: a genuine ``transformers``
GPT-2 (random-init — no network access) converts into our GPT tree, and
both frameworks produce the same logits on the same tokens.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

from pddl_tpu.ckpt.hf_import import load_hf_gpt2  # noqa: E402
from pddl_tpu.models.gpt import GPT  # noqa: E402

V, P, E, L, H = 97, 64, 32, 2, 2


def _hf_model(vocab=V):
    cfg = transformers.GPT2Config(
        vocab_size=vocab, n_positions=P, n_embd=E, n_layer=L, n_head=H,
        resid_pdrop=0.0, embd_pdrop=0.0, attn_pdrop=0.0,
    )
    torch.manual_seed(0)
    return transformers.GPT2LMHeadModel(cfg).eval()


def _tokens(batch=2, seq=17, vocab=V):
    return np.asarray(
        jax.random.randint(jax.random.key(3), (batch, seq), 0, vocab),
        np.int32,
    )


def test_hf_gpt2_logits_match():
    hf = _hf_model()
    ours = GPT(vocab_size=V, max_len=P, embed_dim=E, depth=L, num_heads=H,
               attention="reference", ln_eps=1e-5)  # HF GPT-2 epsilon
    tokens = _tokens()
    v = ours.init(jax.random.key(0), tokens, train=False)
    v = load_hf_gpt2(hf, v)

    with torch.no_grad():
        ref = hf(torch.from_numpy(tokens.astype(np.int64))).logits.numpy()
    got = np.asarray(ours.apply(v, jnp.asarray(tokens), train=False))
    np.testing.assert_allclose(got, ref, atol=2e-4, rtol=2e-4)


def test_hf_gpt2_import_into_padded_vocab():
    """vocab_multiple padding: the HF vocab fills the real slice; padded
    classes stay sliced away by the head, so logits still match."""
    hf = _hf_model()
    ours = GPT(vocab_size=V, max_len=P, embed_dim=E, depth=L, num_heads=H,
               attention="reference", vocab_multiple=32, ln_eps=1e-5)  # 97 -> 128
    tokens = _tokens()
    v = ours.init(jax.random.key(0), tokens, train=False)
    v = load_hf_gpt2(hf, v)

    with torch.no_grad():
        ref = hf(torch.from_numpy(tokens.astype(np.int64))).logits.numpy()
    got = np.asarray(ours.apply(v, jnp.asarray(tokens), train=False))
    assert got.shape[-1] == V  # padding sliced away
    np.testing.assert_allclose(got, ref, atol=2e-4, rtol=2e-4)


def test_hf_gpt2_wrong_shape_raises():
    hf = _hf_model()
    wrong_depth = GPT(vocab_size=V, max_len=P, embed_dim=E, depth=L + 1,
                      num_heads=H, attention="reference")
    v = wrong_depth.init(jax.random.key(0), _tokens(), train=False)
    with pytest.raises(ValueError, match="depths must match"):
        load_hf_gpt2(hf, v)
    wrong_pos = GPT(vocab_size=V, max_len=P * 2, embed_dim=E, depth=L,
                    num_heads=H, attention="reference")
    v = wrong_pos.init(jax.random.key(0), _tokens(), train=False)
    with pytest.raises(ValueError, match="positions"):
        load_hf_gpt2(hf, v)


def test_hf_gpt2_ln_eps_mismatch_raises():
    """ln_eps is a module attribute, invisible in the variables tree: a
    model left at the default 1e-6 must not import HF weights (1e-5)
    silently — logits would drift with no error."""
    hf = _hf_model()
    default_eps = GPT(vocab_size=V, max_len=P, embed_dim=E, depth=L,
                      num_heads=H, attention="reference")  # ln_eps=1e-6
    v = default_eps.init(jax.random.key(0), _tokens(), train=False)
    with pytest.raises(ValueError, match="ln_eps"):
        load_hf_gpt2(hf, v, model=default_eps)
    with pytest.raises(ValueError, match="ln_eps"):
        load_hf_gpt2(hf, v, expected_ln_eps=1e-6)
    # Matching epsilon passes the gate (model= form).
    ok = GPT(vocab_size=V, max_len=P, embed_dim=E, depth=L, num_heads=H,
             attention="reference", ln_eps=1e-5)
    v_ok = ok.init(jax.random.key(0), _tokens(), train=False)
    load_hf_gpt2(hf, v_ok, model=ok)


def test_hf_gpt2_deeper_checkpoint_raises():
    """A checkpoint with MORE layers than the model must not import
    silently (the dropped-layers case)."""
    hf = _hf_model()  # 2 layers
    shallow = GPT(vocab_size=V, max_len=P, embed_dim=E, depth=1,
                  num_heads=H, attention="reference")
    v = shallow.init(jax.random.key(0), _tokens(), train=False)
    with pytest.raises(ValueError, match="depths must match"):
        load_hf_gpt2(hf, v)
