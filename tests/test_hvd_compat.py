"""Horovod-shim tests: API parity with the reference's hvd usage
(`/root/reference/imagenet-resnet50-hvd.py`) on the fake 8-device mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import pddl_tpu.compat.hvd as hvd
from pddl_tpu.core.mesh import shard_map
from pddl_tpu.data.synthetic import SyntheticImageClassification
from pddl_tpu.models.resnet import tiny_resnet
from pddl_tpu.parallel.mirrored import MirroredStrategy
from pddl_tpu.train.loop import Trainer
from pddl_tpu.train.state import get_learning_rate


@pytest.fixture(autouse=True)
def _init():
    hvd.init()


def test_world_shape(eight_devices):
    assert hvd.size() == 8           # replicas = devices (LR/batch parity)
    assert hvd.rank() == 0
    assert hvd.local_rank() == 0
    assert hvd.local_size() == 8
    assert hvd.num_data_shards() == 1   # single process feeds all replicas
    assert hvd.data_shard_index() == 0


def test_lr_scaling_matches_reference_rule():
    """`0.1 * hvd.size()` (imagenet-resnet50-hvd.py:99) on 8 replicas."""
    assert 0.1 * hvd.size() == pytest.approx(0.8)


def test_allreduce_and_broadcast_single_process_identity():
    x = {"a": np.arange(4.0), "b": 3.0}
    out = hvd.allreduce(x)
    np.testing.assert_array_equal(out["a"], x["a"])
    out = hvd.broadcast(x)
    np.testing.assert_array_equal(out["a"], x["a"])
    # allgather: single process concatenates to itself; scalars become
    # a [size]-vector (hvd semantics).
    out = hvd.allgather(x)
    np.testing.assert_array_equal(out["a"], x["a"])
    np.testing.assert_array_equal(out["b"], np.asarray([3.0]))
    # Any in-range root is accepted (real cross-process check lives in
    # tests/_multiworker_child.py); out-of-range raises.
    import pytest

    with pytest.raises(ValueError, match="root_rank"):
        hvd.broadcast(x, root_rank=1)  # only 1 process here


def test_distributed_optimizer_pmeans_gradients_in_shard_map(mesh8):
    """Explicit regime: per-replica different grads → identical (averaged)
    updates, the literal hvd ring-allreduce semantics."""
    tx = hvd.DistributedOptimizer("sgd", learning_rate=1.0, axis_name="data")
    params = {"w": jnp.zeros((8, 4))}  # leading dim sharded over data

    from jax.sharding import PartitionSpec as P

    @jax.jit
    def step(params, grads):
        def _inner(p, g):
            opt_state = tx.init(p)
            updates, _ = tx.update(g, opt_state, p)
            return optax.apply_updates(p, updates)

        return shard_map(
            _inner, mesh=mesh8,
            in_specs=(P("data"), P("data")),
            out_specs=P("data"),
        )(params, grads)

    # grads: replica i sees constant value i → pmean = 3.5 everywhere
    grads = {"w": jnp.repeat(jnp.arange(8.0)[:, None], 4, axis=1)}
    new = step(params, grads)
    np.testing.assert_allclose(np.asarray(new["w"]), -3.5, rtol=1e-6)


def test_distributed_optimizer_default_regime_is_plain_optimizer():
    tx = hvd.DistributedOptimizer("adam", learning_rate=2e-3)
    params = {"w": jnp.ones(3)}
    state = tx.init(params)
    updates, _ = tx.update({"w": jnp.ones(3)}, state, params)
    assert jax.tree.leaves(updates)[0].shape == (3,)


def test_reference_hvd_script_workflow_end_to_end():
    """The hvd script's shape, recomposed: scaled LR, DistributedOptimizer,
    warmup + broadcast + metric-average callbacks, rank-0 gating."""
    base_lr = 0.01
    scaled = base_lr * hvd.size() / 8  # keep it small for the tiny task
    trainer = Trainer(
        tiny_resnet(num_classes=10),
        optimizer=hvd.DistributedOptimizer("adam", learning_rate=scaled),
        strategy=MirroredStrategy(),
        seed=11,
    )
    cbs = [
        hvd.callbacks.BroadcastGlobalVariablesCallback(0),
        hvd.callbacks.MetricAverageCallback(),
        hvd.callbacks.LearningRateWarmupCallback(warmup_epochs=2),
    ]
    ds = SyntheticImageClassification(batch_size=16, image_size=32,
                                      num_classes=10, seed=4)
    hist = trainer.fit(ds, epochs=3, steps_per_epoch=4, callbacks=cbs,
                       verbose=0)
    assert hist.history["loss"][-1] < hist.history["loss"][0]
    # warmup has reached the target LR
    assert get_learning_rate(trainer.state) == pytest.approx(scaled, rel=1e-5)
    # rank-0 gating helper used for save/logging (:117-129)
    assert hvd.rank() == 0
