"""Multi-slice (DCN-aware) mesh construction.

The reference splits collectives into NCCL intra-node rings + cross-host
rings (``/root/reference/imagenet-resnet50-multiworkers.py:19-25``). The
TPU analogue: non-DCN mesh axes must stay inside one slice (ICI); the DCN
axis is laid out slice-major so its all-reduce is hierarchical. Slices are
faked here by splitting the 8 fake CPU devices into groups."""

import numpy as np
import pytest

from pddl_tpu.core.mesh import (
    CANONICAL_AXES,
    MeshConfig,
    build_hybrid_mesh,
    slice_groups,
)


def _slice_of(dev, groups):
    for i, g in enumerate(groups):
        if dev in g:
            return i
    raise AssertionError(f"{dev} in no slice")


def test_slice_groups_fake_split(eight_devices):
    groups = slice_groups(eight_devices, num_slices=2)
    assert [len(g) for g in groups] == [4, 4]
    assert groups[0] == list(eight_devices[:4])
    with pytest.raises(ValueError):
        slice_groups(eight_devices, num_slices=3)  # 8 % 3 != 0
    # Without num_slices on an undifferentiated host: one slice.
    assert len(slice_groups(eight_devices)) == 1


def test_slice_groups_mixed_slice_index(eight_devices):
    """Heterogeneous sets: some devices expose slice_index=int, others None.

    The group keys must stay sortable (None maps to -1) instead of
    sorted() raising TypeError on None < int."""

    class _Dev:
        def __init__(self, dev, slice_index):
            self._dev = dev
            self.process_index = dev.process_index
            if slice_index is not None:
                self.slice_index = slice_index

        # slice_index intentionally absent when None: getattr default path.

    mixed = [_Dev(d, 1 if i < 4 else None) for i, d in enumerate(eight_devices)]
    groups = slice_groups(mixed)
    assert [len(g) for g in groups] == [4, 4]
    # The sentinel -1 sorts the index-less group first.
    assert all(not hasattr(d, "slice_index") for d in groups[0])
    assert all(getattr(d, "slice_index", None) == 1 for d in groups[1])


def test_hybrid_mesh_data_axis_slice_major(eight_devices):
    mesh = build_hybrid_mesh(MeshConfig(data=-1), num_slices=2,
                             devices=eight_devices)
    assert mesh.shape["data"] == 8
    groups = slice_groups(eight_devices, num_slices=2)
    flat = mesh.devices.reshape(8)
    # Positions 0-3 are slice 0, 4-7 slice 1 (slice-major).
    assert [_slice_of(d, groups) for d in flat] == [0] * 4 + [1] * 4


def test_hybrid_mesh_model_axis_stays_intra_slice(eight_devices):
    mesh = build_hybrid_mesh(MeshConfig(data=4, model=2), num_slices=2,
                             devices=eight_devices)
    groups = slice_groups(eight_devices, num_slices=2)
    arr = mesh.devices.reshape(4, 2)  # (data, model)
    for row in arr:
        # Both tensor-parallel partners share a slice: their all-reduces
        # ride ICI, never DCN.
        assert _slice_of(row[0], groups) == _slice_of(row[1], groups)
    # Data axis still slice-major at the granularity of per-slice share.
    assert [_slice_of(r[0], groups) for r in arr] == [0, 0, 1, 1]


def test_hybrid_mesh_rejects_oversized_intra_slice_axis(eight_devices):
    # model=8 over 2 slices would have to cross DCN; it surfaces as the
    # data axis (1) not being divisible by the slice count.
    with pytest.raises(ValueError, match="not divisible"):
        build_hybrid_mesh(MeshConfig(data=1, model=8), num_slices=2,
                          devices=eight_devices)
    with pytest.raises(ValueError, match="not divisible"):
        # data=2 cannot span 4 slices (2 % 4 != 0).
        build_hybrid_mesh(MeshConfig(data=2, model=4), num_slices=4,
                          devices=eight_devices)


def test_hybrid_mesh_single_slice_degenerates(eight_devices):
    from pddl_tpu.core.mesh import build_mesh

    hybrid = build_hybrid_mesh(MeshConfig(data=-1), devices=eight_devices)
    plain = build_mesh(MeshConfig(data=-1), devices=eight_devices)
    assert (hybrid.devices == plain.devices).all()
    assert hybrid.axis_names == plain.axis_names == CANONICAL_AXES


def test_training_on_hybrid_mesh(eight_devices):
    """One compiled DP x TP train step over a faked 2-slice mesh."""
    from pddl_tpu.data.synthetic import SyntheticImageClassification
    from pddl_tpu.models.vit import ViT
    from pddl_tpu.parallel.tensor_parallel import TensorParallelStrategy
    from pddl_tpu.train.loop import Trainer

    strategy = TensorParallelStrategy(model_parallel=2)
    strategy._mesh = build_hybrid_mesh(
        MeshConfig(data=4, model=2), num_slices=2, devices=eight_devices
    )
    vit = ViT(patch_size=4, embed_dim=32, depth=1, num_heads=4,
              num_classes=10, attention="reference")
    trainer = Trainer(vit, optimizer="adamw", learning_rate=1e-3,
                      strategy=strategy)
    data = SyntheticImageClassification(
        batch_size=strategy.scale_batch_size(2), image_size=32,
        num_classes=10, seed=0,
    )
    trainer.fit(data, epochs=1, steps_per_epoch=2, verbose=0)
    assert np.isfinite(trainer.history.history["loss"][-1])
