"""Data-pipeline tests: source formats + the reference's three sharding schemes.

The reference's shard semantics under test (SURVEY.md §0, §2a C7):
- auto-shard DATA: per-example sharding (`imagenet-resnet50-multiworkers.py:66-69`)
- Horovod: per-*batch* sharding after batching (`imagenet-resnet50-hvd.py:77-81`)
- single/mirrored: no sharding
"""

import os

import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")

from pddl_tpu.data.imagenet import ImageNetConfig, ImageNetDataset, load_imagenet


def _write_image_folder(root, split="train", classes=4, per_class=6, size=10):
    """Tiny image-folder tree; pixel values encode the class id."""
    rng = np.random.default_rng(0)
    for c in range(classes):
        d = os.path.join(root, split, f"class_{c:02d}")
        os.makedirs(d, exist_ok=True)
        for i in range(per_class):
            img = np.full((size, size, 3), c * 10, np.uint8)
            img[0, 0] = rng.integers(0, 255, 3)  # break exact duplicates
            png = tf.io.encode_png(tf.constant(img)).numpy()
            with open(os.path.join(d, f"img_{i}.png"), "wb") as f:
                f.write(png)


def _write_tfrecords(root, split="train", n=24, size=10, shards=3):
    os.makedirs(root, exist_ok=True)
    idx = 0
    for s in range(shards):
        path = os.path.join(root, f"{split}-{s:05d}-of-{shards:05d}")
        with tf.io.TFRecordWriter(path) as w:
            for _ in range(n // shards):
                label = idx % 7
                img = np.full((size, size, 3), label, np.uint8)
                png = tf.io.encode_png(tf.constant(img)).numpy()
                ex = tf.train.Example(features=tf.train.Features(feature={
                    "image/encoded": tf.train.Feature(
                        bytes_list=tf.train.BytesList(value=[png])),
                    "image/class/label": tf.train.Feature(
                        int64_list=tf.train.Int64List(value=[label])),
                }))
                w.write(ex.SerializeToString())
                idx += 1


def test_image_folder_pipeline(tmp_path):
    _write_image_folder(tmp_path, classes=3, per_class=4, size=10)
    ds = ImageNetDataset(ImageNetConfig(
        data_dir=str(tmp_path), split="train", global_batch_size=4,
        image_size=8, shuffle=False,
    ))
    batches = list(ds)
    assert len(batches) == 3  # 12 images / 4
    b = batches[0]
    assert b["image"].shape == (4, 8, 8, 3)
    assert b["image"].dtype == np.float32
    assert b["label"].dtype == np.int32
    # Labels are class-dir indices; pixel value 10*c must match label c
    # (center pixel survives the central crop).
    for img, lbl in zip(b["image"], b["label"]):
        assert img[4, 4, 0] == pytest.approx(10.0 * lbl)


def test_tfrecord_pipeline(tmp_path):
    _write_tfrecords(tmp_path, n=24, shards=3)
    ds = ImageNetDataset(ImageNetConfig(
        data_dir=str(tmp_path), split="train", global_batch_size=6,
        image_size=8, shuffle=False,
    ))
    batches = list(ds)
    assert len(batches) == 4
    for b in batches:
        assert b["image"].shape == (6, 8, 8, 3)
        # pixel encodes label
        np.testing.assert_allclose(b["image"][:, 4, 4, 0], b["label"])


def test_data_sharding_disjoint_and_complete(tmp_path):
    """DATA auto-shard analogue: per-example, disjoint, smaller local batch."""
    _write_image_folder(tmp_path, classes=4, per_class=4, size=10)

    def labels_for(proc):
        ds = ImageNetDataset(ImageNetConfig(
            data_dir=str(tmp_path), global_batch_size=8, image_size=8,
            shuffle=False, shard="data", process_index=proc, process_count=2,
        ))
        out = []
        for b in ds:
            assert b["label"].shape == (4,)  # local = global/2
            out.extend(b["image"][:, 4, 4, 0].tolist())
        return out

    a, b = labels_for(0), labels_for(1)
    assert len(a) == len(b) == 8
    # Round-robin example sharding: together they cover all 16 images.
    assert sorted(a + b) == sorted(
        [10.0 * c for c in range(4) for _ in range(4)]
    )


def test_batch_sharding_keeps_full_batches(tmp_path):
    """Horovod scheme: shard after batch — full-size batches, every n-th."""
    _write_tfrecords(tmp_path, n=24, shards=3)

    def batches_for(proc):
        ds = ImageNetDataset(ImageNetConfig(
            data_dir=str(tmp_path), global_batch_size=6, image_size=8,
            shuffle=False, shard="batch", process_index=proc, process_count=2,
        ))
        return list(ds)

    a, b = batches_for(0), batches_for(1)
    assert len(a) == 2 and len(b) == 2  # 4 batches split 2/2
    for batch in a + b:
        assert batch["image"].shape[0] == 6  # full batch per rank
    # Ranks see different batches.
    assert not np.array_equal(a[0]["label"], b[0]["label"])


def test_validation_split_deterministic(tmp_path):
    _write_image_folder(tmp_path, split="validation", classes=2, per_class=4)
    train_dir = tmp_path  # train absent; only build val
    train, val = load_imagenet(str(train_dir), train_batch_size=4,
                               image_size=8, shard="none")
    v1 = [b["label"] for b in val]
    v2 = [b["label"] for b in val]
    for x, y in zip(v1, v2):
        np.testing.assert_array_equal(x, y)


def test_missing_source_raises(tmp_path):
    ds = ImageNetDataset(ImageNetConfig(data_dir=str(tmp_path / "nope")))
    with pytest.raises(FileNotFoundError):
        ds.build()


def test_repeat_stream(tmp_path):
    """PS-style .repeat()ed stream (`imagenet-resnet50-ps.py:118-119`)."""
    _write_tfrecords(tmp_path, n=12, shards=2)
    ds = ImageNetDataset(ImageNetConfig(
        data_dir=str(tmp_path), global_batch_size=4, image_size=8,
        shuffle=False, repeat=True,
    ))
    it = iter(ds)
    got = [next(it) for _ in range(10)]  # > one epoch (3 batches)
    assert len(got) == 10
