"""Data-pipeline tests: source formats + the reference's three sharding schemes.

The reference's shard semantics under test (SURVEY.md §0, §2a C7):
- auto-shard DATA: per-example sharding (`imagenet-resnet50-multiworkers.py:66-69`)
- Horovod: per-*batch* sharding after batching (`imagenet-resnet50-hvd.py:77-81`)
- single/mirrored: no sharding
"""

import os

import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")

from pddl_tpu.data.imagenet import ImageNetConfig, ImageNetDataset, load_imagenet


def _write_image_folder(root, split="train", classes=4, per_class=6, size=10):
    """Tiny image-folder tree; pixel values encode the class id."""
    rng = np.random.default_rng(0)
    for c in range(classes):
        d = os.path.join(root, split, f"class_{c:02d}")
        os.makedirs(d, exist_ok=True)
        for i in range(per_class):
            img = np.full((size, size, 3), c * 10, np.uint8)
            img[0, 0] = rng.integers(0, 255, 3)  # break exact duplicates
            png = tf.io.encode_png(tf.constant(img)).numpy()
            with open(os.path.join(d, f"img_{i}.png"), "wb") as f:
                f.write(png)


def _write_tfrecords(root, split="train", n=24, size=10, shards=3):
    os.makedirs(root, exist_ok=True)
    idx = 0
    for s in range(shards):
        path = os.path.join(root, f"{split}-{s:05d}-of-{shards:05d}")
        with tf.io.TFRecordWriter(path) as w:
            for _ in range(n // shards):
                label = idx % 7
                img = np.full((size, size, 3), label, np.uint8)
                png = tf.io.encode_png(tf.constant(img)).numpy()
                ex = tf.train.Example(features=tf.train.Features(feature={
                    "image/encoded": tf.train.Feature(
                        bytes_list=tf.train.BytesList(value=[png])),
                    "image/class/label": tf.train.Feature(
                        int64_list=tf.train.Int64List(value=[label])),
                }))
                w.write(ex.SerializeToString())
                idx += 1


def test_image_folder_pipeline(tmp_path):
    _write_image_folder(tmp_path, classes=3, per_class=4, size=10)
    ds = ImageNetDataset(ImageNetConfig(
        data_dir=str(tmp_path), split="train", global_batch_size=4,
        image_size=8, shuffle=False,
    ))
    batches = list(ds)
    assert len(batches) == 3  # 12 images / 4
    b = batches[0]
    assert b["image"].shape == (4, 8, 8, 3)
    assert b["image"].dtype == np.float32
    assert b["label"].dtype == np.int32
    # Labels are class-dir indices; pixel value 10*c must match label c
    # (center pixel survives the central crop).
    for img, lbl in zip(b["image"], b["label"]):
        assert img[4, 4, 0] == pytest.approx(10.0 * lbl)


def test_tfrecord_pipeline(tmp_path):
    _write_tfrecords(tmp_path, n=24, shards=3)
    ds = ImageNetDataset(ImageNetConfig(
        data_dir=str(tmp_path), split="train", global_batch_size=6,
        image_size=8, shuffle=False,
    ))
    batches = list(ds)
    assert len(batches) == 4
    for b in batches:
        assert b["image"].shape == (6, 8, 8, 3)
        # pixel encodes label
        np.testing.assert_allclose(b["image"][:, 4, 4, 0], b["label"])


def test_data_sharding_disjoint_and_complete(tmp_path):
    """DATA auto-shard analogue: per-example, disjoint, smaller local batch."""
    _write_image_folder(tmp_path, classes=4, per_class=4, size=10)

    def labels_for(proc):
        ds = ImageNetDataset(ImageNetConfig(
            data_dir=str(tmp_path), global_batch_size=8, image_size=8,
            shuffle=False, shard="data", process_index=proc, process_count=2,
        ))
        out = []
        for b in ds:
            assert b["label"].shape == (4,)  # local = global/2
            out.extend(b["image"][:, 4, 4, 0].tolist())
        return out

    a, b = labels_for(0), labels_for(1)
    assert len(a) == len(b) == 8
    # Round-robin example sharding: together they cover all 16 images.
    assert sorted(a + b) == sorted(
        [10.0 * c for c in range(4) for _ in range(4)]
    )


def test_batch_sharding_keeps_full_batches(tmp_path):
    """Horovod scheme: shard after batch — full-size batches, every n-th."""
    _write_tfrecords(tmp_path, n=24, shards=3)

    def batches_for(proc):
        ds = ImageNetDataset(ImageNetConfig(
            data_dir=str(tmp_path), global_batch_size=6, image_size=8,
            shuffle=False, shard="batch", process_index=proc, process_count=2,
        ))
        return list(ds)

    a, b = batches_for(0), batches_for(1)
    assert len(a) == 2 and len(b) == 2  # 4 batches split 2/2
    for batch in a + b:
        assert batch["image"].shape[0] == 6  # full batch per rank
    # Ranks see different batches.
    assert not np.array_equal(a[0]["label"], b[0]["label"])


def test_validation_split_deterministic(tmp_path):
    _write_image_folder(tmp_path, split="validation", classes=2, per_class=4)
    train_dir = tmp_path  # train absent; only build val
    train, val = load_imagenet(str(train_dir), train_batch_size=4,
                               image_size=8, shard="none")
    v1 = [b["label"] for b in val]
    v2 = [b["label"] for b in val]
    for x, y in zip(v1, v2):
        np.testing.assert_array_equal(x, y)


def test_missing_source_raises(tmp_path):
    ds = ImageNetDataset(ImageNetConfig(data_dir=str(tmp_path / "nope")))
    with pytest.raises(FileNotFoundError):
        ds.build()


def test_repeat_stream(tmp_path):
    """PS-style .repeat()ed stream (`imagenet-resnet50-ps.py:118-119`)."""
    _write_tfrecords(tmp_path, n=12, shards=2)
    ds = ImageNetDataset(ImageNetConfig(
        data_dir=str(tmp_path), global_batch_size=4, image_size=8,
        shuffle=False, repeat=True,
    ))
    it = iter(ds)
    got = [next(it) for _ in range(10)]  # > one epoch (3 batches)
    assert len(got) == 10


# ------------------------------------------------------------------ TFDS
# tensorflow_datasets is not installed in this environment, so the
# reference's literal ingest (`tfds.load('imagenet2012')`,
# /root/reference/imagenet-resnet50.py:16-34) is exercised through a
# faithful stub module injected via sys.modules: same call surface
# (load kwargs + ReadConfig), returning a REAL tf.data.Dataset of
# already-decoded (image, label) tuples — so everything downstream of
# the tfds.load call (source selection, DATA auto-shard, preprocess,
# batching) is the repo's genuine code path.

def _make_fake_tfds(n_examples=12, img_size=10):
    import types

    mod = types.ModuleType("tensorflow_datasets")
    mod.calls = []

    class ReadConfig:
        def __init__(self, shuffle_seed=None):
            self.shuffle_seed = shuffle_seed

    def load(name, *, split, data_dir, shuffle_files, as_supervised,
             read_config):
        mod.calls.append({
            "name": name, "split": split, "data_dir": data_dir,
            "shuffle_files": shuffle_files, "as_supervised": as_supervised,
            "read_config": read_config,
        })
        assert as_supervised, "the pipeline expects (image, label) tuples"
        # Pixel value == example index == label, so downstream tests can
        # recover exactly which examples each process saw.
        images = np.stack([
            np.full((img_size, img_size, 3), i, np.uint8)
            for i in range(n_examples)
        ])
        labels = np.arange(n_examples, dtype=np.int64)
        return tf.data.Dataset.from_tensor_slices((images, labels))

    mod.load = load
    mod.ReadConfig = ReadConfig
    return mod


def _tfds_env(tmp_path, monkeypatch, **kwargs):
    import sys

    (tmp_path / "imagenet2012").mkdir(exist_ok=True)
    fake = _make_fake_tfds(**kwargs)
    monkeypatch.setitem(sys.modules, "tensorflow_datasets", fake)
    return fake


def test_tfds_pipeline_end_to_end(tmp_path, monkeypatch):
    """Source #1 selected when <data_dir>/imagenet2012 exists; batches come
    out preprocessed (f32, crop/pad to size, int32 labels) from the
    already-decoded TFDS images."""
    fake = _tfds_env(tmp_path, monkeypatch)
    cfg = ImageNetConfig(data_dir=str(tmp_path), split="train",
                         global_batch_size=4, image_size=8, shuffle=False)
    batches = list(ImageNetDataset(cfg))

    [call] = fake.calls
    assert call["name"] == "imagenet2012"
    assert call["split"] == "train"
    assert call["data_dir"] == str(tmp_path)
    assert call["shuffle_files"] is False

    assert len(batches) == 3  # 12 examples / batch 4
    for b in batches:
        assert b["image"].shape == (4, 8, 8, 3)
        assert b["image"].dtype == np.float32
        assert b["label"].dtype == np.int32
        # 10px stub images center-crop to 8px; constant fill == label.
        np.testing.assert_array_equal(
            b["image"][:, 0, 0, 0].astype(np.int64), b["label"])
    seen = sorted(int(l) for b in batches for l in b["label"])
    assert seen == list(range(12))


def test_tfds_shuffle_seed_passthrough(tmp_path, monkeypatch):
    """cfg.shuffle/seed reach tfds.load as shuffle_files + the ReadConfig
    shuffle_seed (every process must see the same file order or per-example
    ds.shard() drops/duplicates examples across hosts)."""
    fake = _tfds_env(tmp_path, monkeypatch)
    cfg = ImageNetConfig(data_dir=str(tmp_path), global_batch_size=4,
                         image_size=8, shuffle=True, seed=7)
    next(iter(ImageNetDataset(cfg)))
    [call] = fake.calls
    assert call["shuffle_files"] is True
    assert call["read_config"].shuffle_seed == 7


def test_tfds_data_autoshard_disjoint_and_complete(tmp_path, monkeypatch):
    """DATA auto-shard through the TFDS branch: per-example striding
    BEFORE shuffle/batch — the two processes' examples are disjoint and
    their union is the whole dataset
    (imagenet-resnet50-multiworkers.py:66-69 semantics)."""
    per_process = []
    for proc in range(2):
        fake = _tfds_env(tmp_path, monkeypatch)
        cfg = ImageNetConfig(
            data_dir=str(tmp_path), global_batch_size=4, image_size=8,
            shuffle=False, shard="data", process_index=proc,
            process_count=2,
        )
        labels = [int(l) for b in ImageNetDataset(cfg) for l in b["label"]]
        del fake
        per_process.append(labels)

    # Each host batches global/process_count = 2 examples per batch and
    # keeps every 2nd example, starting at its own index.
    assert per_process[0] == [0, 2, 4, 6, 8, 10]
    assert per_process[1] == [1, 3, 5, 7, 9, 11]
