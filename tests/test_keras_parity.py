"""Golden numerics parity vs tf.keras.applications.ResNet50.

The reference's model IS ``keras.applications.ResNet50``
(``/root/reference/imagenet-resnet50.py:56``); our Flax ResNet claims
exact-architecture parity so Keras ``.h5`` weights import 1:1
(``weights='imagenet'`` mode, ``imagenet-pretrained-resnet50.py:56``).
This test proves it end to end: random-init Keras model → save ``.h5`` →
import through :func:`pddl_tpu.ckpt.load_keras_resnet50_h5` → logits on
the same input must match Keras to float32 round-off (~1e-7 observed;
any architecture mismatch — BN epsilon, stride placement, padding — blows
this up by orders of magnitude).
"""

import numpy as np
import pytest

tf_keras = pytest.importorskip("tf_keras")


def test_resnet50_logits_match_keras_exactly(tmp_path):
    import jax
    import jax.numpy as jnp

    from pddl_tpu.ckpt.keras_import import load_keras_resnet50_h5
    from pddl_tpu.models.resnet import ResNet50

    keras_model = tf_keras.applications.ResNet50(
        weights=None, include_top=True, classes=1000,
        classifier_activation=None,
    )
    h5 = str(tmp_path / "keras_resnet50.h5")
    keras_model.save_weights(h5)

    x = np.random.RandomState(0).rand(1, 224, 224, 3).astype(np.float32)
    ref = np.asarray(keras_model(x, training=False))

    model = ResNet50(num_classes=1000, dtype=jnp.float32)
    variables = model.init(jax.random.key(0), jnp.asarray(x), train=False)
    variables = load_keras_resnet50_h5(h5, variables, require_head=True)
    ours = np.asarray(model.apply(variables, jnp.asarray(x), train=False))

    np.testing.assert_allclose(ours, ref, atol=1e-5, rtol=1e-5)
