"""Golden numerics parity vs tf.keras.applications.ResNet50.

The reference's model IS ``keras.applications.ResNet50``
(``/root/reference/imagenet-resnet50.py:56``); our Flax ResNet claims
exact-architecture parity so Keras ``.h5`` weights import 1:1
(``weights='imagenet'`` mode, ``imagenet-pretrained-resnet50.py:56``).
This test proves it end to end: random-init Keras model → save ``.h5`` →
import through :func:`pddl_tpu.ckpt.load_keras_resnet50_h5` → logits on
the same input must match Keras to float32 round-off (~1e-7 observed;
any architecture mismatch — BN epsilon, stride placement, padding — blows
this up by orders of magnitude).
"""

import numpy as np
import pytest

tf_keras = pytest.importorskip("tf_keras")


def test_resnet50_logits_match_keras_exactly(tmp_path):
    import jax
    import jax.numpy as jnp

    from pddl_tpu.ckpt.keras_import import load_keras_resnet50_h5
    from pddl_tpu.models.resnet import ResNet50

    keras_model = tf_keras.applications.ResNet50(
        weights=None, include_top=True, classes=1000,
        classifier_activation=None,
    )
    h5 = str(tmp_path / "keras_resnet50.h5")
    keras_model.save_weights(h5)

    x = np.random.RandomState(0).rand(1, 224, 224, 3).astype(np.float32)
    ref = np.asarray(keras_model(x, training=False))

    model = ResNet50(num_classes=1000, dtype=jnp.float32)
    variables = model.init(jax.random.key(0), jnp.asarray(x), train=False)
    variables = load_keras_resnet50_h5(h5, variables, require_head=True)
    ours = np.asarray(model.apply(variables, jnp.asarray(x), train=False))

    np.testing.assert_allclose(ours, ref, atol=1e-5, rtol=1e-5)


def test_h5_export_loads_into_genuine_keras(tmp_path):
    """The reference's ``model.save('...-reuse.h5')`` promise in reverse
    (``/root/reference/imagenet-resnet50.py:69-72``): our exported weight
    file must load into a real keras.applications.ResNet50 via
    ``load_weights(by_name=True)`` and reproduce our logits (up to conv
    float-reordering noise between backends)."""
    import jax
    import jax.numpy as jnp

    from pddl_tpu.ckpt.keras_import import export_keras_style_h5
    from pddl_tpu.models.resnet import ResNet50

    model = ResNet50(num_classes=1000, dtype=jnp.float32)
    x = np.random.RandomState(1).rand(1, 224, 224, 3).astype(np.float32)
    variables = model.init(jax.random.key(0), jnp.asarray(x), train=False)
    ours = np.asarray(model.apply(variables, jnp.asarray(x), train=False))

    h5 = str(tmp_path / "export.h5")
    export_keras_style_h5(h5, variables)
    km = tf_keras.applications.ResNet50(
        weights=None, include_top=True, classes=1000,
        classifier_activation=None,
    )
    km.load_weights(h5, by_name=True)
    theirs = np.asarray(km(x, training=False))
    # Random-init logits are O(1e3); agreement is relative (backend conv
    # summation order), so rtol does the work.
    np.testing.assert_allclose(ours, theirs, rtol=5e-3, atol=5e-3)
    # Guard against the silent-skip failure mode (load_weights(by_name)
    # ignoring every layer): loaded output must differ wildly from
    # random-init Keras.
    km2 = tf_keras.applications.ResNet50(
        weights=None, include_top=True, classes=1000,
        classifier_activation=None,
    )
    assert np.abs(np.asarray(km2(x, training=False)) - ours).max() > 1.0
