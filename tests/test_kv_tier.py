"""Tiered KV cache (ISSUE 13): host-RAM spill tier + cross-replica
prefix transfer (`serve/kvcache/hosttier.py`, `ServeEngine(host_tier=)`,
the fleet's chain pull).

The contracts under test:

- **Tier mechanics**: byte-budgeted store/match/pin/evict with
  radix-style refcounts; structural holes end promotable chains; the
  leaf spec refuses malformed payloads.
- **Eviction is demotion**: the radix LRU reclaim offers victims to the
  host tier; ``flush_unpinned`` (the OOM response) BYPASSES demotion —
  pinned discriminatively, at the radix hook level and through a real
  injected OOM.
- **Token-exactness**: a chain that round-trips the host tier (or
  crosses replicas over the chain wire format) yields bit-identical
  streams to ``generate()`` — row and paged engines, GPT and Llama.
- **Cold path unchanged**: byte budget 0 compiles the exact untiered
  program set and emits identical tokens.
- **Budget charge**: promotions price ``promote_tokens_per_block`` per
  block through the cost_fn (the adapter_load_tokens precedent).
- **Resilience**: a 3-seed chaos matrix with faults at the
  ``host_promote`` site, a kill mid-promotion with drain/restore while
  the tier is populated — every survivor token-exact, zero recompiles,
  no leaked host pins.
- **Fleet**: second-tier shadow routing (``routed_host_tier``) and the
  replica-to-replica chain pull eliminating duplicate prefill.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from conftest import ref_greedy
from pddl_tpu.models.gpt import tiny_gpt
from pddl_tpu.models.llama import tiny_llama
from pddl_tpu.obs.export import (
    fleet_exposition,
    parse_prometheus_text,
    serve_exposition,
)
from pddl_tpu.serve import ServeEngine
from pddl_tpu.serve.drain import kv_chain_from_wire, kv_chain_to_wire
from pddl_tpu.serve.faults import FaultKind, FaultPlan, FaultSpec, KillPoint
from pddl_tpu.serve.fleet.replica import LocalReplica
from pddl_tpu.serve.fleet.router import FleetRouter, _ShadowIndex
from pddl_tpu.serve.kvcache import (
    HostTierCache,
    HostTierConfig,
    RadixPrefixCache,
)
from pddl_tpu.serve.request import (
    Priority,
    Request,
    RequestHandle,
    RequestState,
)

pytestmark = pytest.mark.kvtier

_no_sleep = lambda s: None  # noqa: E731

BS = 8  # prefix block size every engine below uses


@pytest.fixture(scope="module")
def gpt_setup():
    model = tiny_gpt(vocab_size=32, max_len=64)
    prompt = jnp.ones((1, 8), jnp.int32)
    params = model.init(jax.random.key(0), prompt, train=False)["params"]
    return model, {"params": params}


@pytest.fixture(scope="module")
def llama_setup():
    model = tiny_llama(vocab_size=32, max_len=64)
    prompt = jnp.ones((1, 8), jnp.int32)
    params = model.init(jax.random.key(1), prompt, train=False)["params"]
    return model, {"params": params}


def _prompts(n=4, length=24, seed=0):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, 32, size=length).astype(np.int32)
            for _ in range(n)]


def _engine(model, variables, *, paged=False, host=1 << 24, **kw):
    """A tier-testable engine: the device pool is deliberately TINY
    (row: 7 allocatable blocks; paged: floor + 1) so cycling a few
    3-block prompts forces LRU eviction — the demotion trigger."""
    kw.setdefault("max_slots", 2)
    kw.setdefault("prefill_len", 32)
    kw.setdefault("prefix_block_size", BS)
    kw.setdefault("prefix_chunk", BS)
    if paged:
        kw.setdefault("prefix_cache_blocks", 2 * (64 // BS) + 1 + 1)
    else:
        kw.setdefault("prefix_cache_blocks", 8)
    return ServeEngine(model, variables, paged=paged, host_tier=host,
                       **kw)


def _serve_all(eng, prompts, n_new=4):
    outs = []
    for p in prompts:
        h = eng.submit(p, n_new)
        eng.run(max_steps=5000)
        assert h.done, h.state
        outs.append(list(h.tokens))
    return outs


# ------------------------------------------------------------ tier unit
def _payload(val=1.0, shape=(1, 2, BS, 4)):
    return {"k": np.full(shape, val, np.float32),
            "v": np.full(shape, -val, np.float32)}


def _spec():
    return {"k": ((1, 2, BS, 4), np.dtype(np.float32)),
            "v": ((1, 2, BS, 4), np.dtype(np.float32))}


def test_hosttier_store_match_pin_evict():
    block_bytes = sum(a.nbytes for a in _payload().values())
    tier = HostTierCache(BS, 3 * block_bytes, leaf_spec=_spec())
    toks = list(range(4 * BS))
    # Store blocks 1..3 tip-first-ish: depth 3 first (structural 1-2),
    # then backfill — the device-evicts-leaf-first arrival order.
    assert tier.store(toks[:3 * BS], _payload(3.0))
    assert tier.store(toks[:2 * BS], _payload(2.0))
    assert tier.store(toks[:1 * BS], _payload(1.0))
    assert tier.blocks_resident == 3
    assert tier.bytes_resident == 3 * block_bytes
    # Re-store of a populated node is refused (no double accounting).
    assert not tier.store(toks[:2 * BS], _payload(9.0))
    # Full-chain match from depth 0; payloads come back root-first.
    tip = tier.match_from(toks, 0, 4)
    assert tip is not None and tip.depth == 3
    data = tier.chain_data(tip, 3)
    assert [d["k"][0, 0, 0, 0] for d in data] == [1.0, 2.0, 3.0]
    # A match from a device depth only needs structural coverage there.
    assert tier.match_depth(toks, 1, 4) == 2
    # Pin the chain, then overflow the budget: everything resident is
    # pinned, so the newcomer is REFUSED (never evict under a pin).
    tip = tier.pin_chain(toks, 0, 3)
    assert tip is not None and tier.pins_outstanding == 1
    other = [100 + t for t in range(BS)]
    assert not tier.store(other, _payload(7.0))
    assert tier.match_depth(toks, 0, 3) == 3
    tier.unpin(tip)
    assert tier.pins_outstanding == 0
    # Unpinned now: the same store evicts the LRU victim and lands.
    assert tier.store(other, _payload(7.0))
    assert tier.blocks_resident == 3
    assert tier.evictions >= 1
    # Spec validation refuses malformed payloads.
    bad = {"k": np.zeros((1, 2, BS, 4), np.float32)}  # missing "v"
    assert not tier.store([300 + t for t in range(BS)], bad)
    wrong = _payload()
    wrong["k"] = wrong["k"].astype(np.float64)
    assert not tier.store([300 + t for t in range(BS)], wrong)


def test_hosttier_full_budget_backfill_stays_reachable():
    """Discriminative for the detached-node leak: at a FULL budget,
    storing a chain's parent block evicts that chain's own deeper
    block (leaf-first demotion order, oldest LRU stamp) — the evictor's
    prune walk must not delete the store's target node out of the tree
    before the payload attaches. On the unfixed cache the backfilled
    block is tracked but unreachable: match misses it and the budget
    bytes can never be evicted again."""
    block_bytes = sum(a.nbytes for a in _payload().values())
    tier = HostTierCache(BS, block_bytes, leaf_spec=_spec())
    toks = list(range(2 * BS))
    assert tier.store(toks[:2 * BS], _payload(2.0))  # leaf first
    assert tier.store(toks[:1 * BS], _payload(1.0))  # backfill evicts it
    assert tier.bytes_resident == block_bytes
    assert tier.blocks_resident == 1
    # The backfilled block is REACHABLE: matchable from the root...
    tip = tier.match_from(toks, 0, 2)
    assert tip is not None and tip.depth == 1
    assert tip.data["k"][0, 0, 0, 0] == 1.0
    # ...and evictable: an unrelated store can reclaim its bytes (the
    # leaked node was invisible to the eviction DFS, so this store was
    # refused and the accounting stuck at a phantom block forever).
    other = [100 + t for t in range(BS)]
    assert tier.store(other, _payload(7.0))
    assert tier.bytes_resident == block_bytes
    assert tier.blocks_resident == 1
    assert tier.match_depth(toks, 0, 2) == 0


def test_hosttier_hole_ends_promotable_chain():
    tier = HostTierCache(BS, 1 << 20, leaf_spec=_spec())
    toks = list(range(3 * BS))
    assert tier.store(toks[:1 * BS], _payload(1.0))
    assert tier.store(toks[:3 * BS], _payload(3.0))  # depth 2 is a hole
    tip = tier.match_from(toks, 0, 3)
    assert tip is not None and tip.depth == 1  # stops at the hole


def test_radix_flush_bypasses_demotion_hook():
    """Discriminative at the radix level: allocation-pressure eviction
    calls ``on_evict``; the degraded flush (``flush_unpinned``) must
    NOT — spilling during an OOM response defeats the shedding."""
    idx = RadixPrefixCache(BS, 4)  # 3 allocatable
    seen = []
    idx.on_evict = lambda victims: seen.extend(
        idx.chain_tokens(v) for v in victims)
    toks = list(range(3 * BS))
    ids = idx.allocate(3)
    idx.extend(idx.match(toks).node, toks, ids)
    # Allocation pressure: the LRU victim is offered to the hook.
    idx.allocate(1)
    assert len(seen) == 1
    # The flush frees BOTH stored unpinned blocks WITHOUT offering
    # anything — a partial flush or a demoting flush both fail here.
    # (blocks_live is 3: the id allocate() just handed out is live but
    # caller-held, not the index's to free.)
    seen.clear()
    freed = idx.flush_unpinned()
    assert freed == 2 and idx.blocks_live == 1
    assert seen == []


# ------------------------------------------------- engine token-exact
@pytest.mark.parametrize("paged", [False, True], ids=["row", "paged"])
def test_demote_promote_token_exact(gpt_setup, paged,
                                    pin_zero_recompiles):
    """Cycling more chains than the device pool holds forces demotion;
    revisiting them forces promotion — and every stream, cold or
    promoted, matches the one-shot ``generate()`` oracle exactly."""
    model, variables = gpt_setup
    eng = pin_zero_recompiles(_engine(model, variables, paged=paged))
    # 6 distinct 3-block chains: more than either mode's pool can keep
    # (row: 7 allocatable; paged: floor 17 minus live usage).
    prompts = _prompts(6)
    refs = [ref_greedy(model, variables, p, 4) for p in prompts]
    for _ in range(3):
        outs = _serve_all(eng, prompts)
        assert outs == refs
    snap = eng.metrics.snapshot()
    assert snap["host_tier_spills"] > 0, "pool never demoted — tighten it"
    assert snap["host_tier_hits"] > 0
    assert snap["host_tier_promotions"] > 0
    assert snap["host_tier_promote_tokens_charged"] > 0
    assert eng.host_tier_bytes_resident > 0
    assert eng._host.pins_outstanding == 0
    assert eng.compile_counts()["host_promote"] == 1


def test_llama_promotion_token_exact(llama_setup, pin_zero_recompiles):
    model, variables = llama_setup
    eng = pin_zero_recompiles(_engine(model, variables))
    prompts = _prompts(4, seed=5)
    refs = [ref_greedy(model, variables, p, 4) for p in prompts]
    for _ in range(2):
        assert _serve_all(eng, prompts) == refs
    assert eng.metrics.host_tier_promotions > 0


def test_budget_zero_is_bit_identical_to_untiered(gpt_setup):
    """The cold-path contract: byte budget 0 (or host_tier=None) is
    the untiered engine — same compiled-program SET (no host_promote
    key), same tokens, in both engine modes."""
    model, variables = gpt_setup
    prompts = _prompts(4)
    for paged in (False, True):
        plain = _engine(model, variables, paged=paged, host=None)
        zero = _engine(model, variables, paged=paged,
                       host=HostTierConfig(byte_budget=0))
        plain.warmup(), zero.warmup()
        assert plain.compile_counts() == zero.compile_counts()
        assert "host_promote" not in zero.compile_counts()
        assert not zero.host_tier_enabled
        outs_p = [_serve_all(plain, prompts) for _ in range(2)]
        outs_z = [_serve_all(zero, prompts) for _ in range(2)]
        assert outs_p == outs_z


def test_host_tier_requires_prefix_machinery(gpt_setup):
    model, variables = gpt_setup
    with pytest.raises(ValueError, match="prefix-cache machinery"):
        ServeEngine(model, variables, max_slots=2, prefill_len=32,
                    prefix_cache_blocks=0, host_tier=1 << 20)


def test_degraded_mode_touches_the_tier_in_neither_direction(gpt_setup):
    """A real injected OOM flips the engine degraded: the flush must
    hard-free (no spills), and admissions during the cool-down must
    not promote — the discriminative ISSUE 13 satellite pin."""
    model, variables = gpt_setup
    clock = __import__("conftest").FakeClock()
    eng = _engine(model, variables, clock=clock,
                  backoff_sleep=_no_sleep, degraded_cooldown_s=100.0)
    eng.warmup()
    prompts = _prompts(6)
    _serve_all(eng, prompts)          # populate pool + host tier
    _serve_all(eng, prompts)          # revisit: spills + promotions
    spills_before = eng.metrics.host_tier_spills
    bytes_before = eng.host_tier_bytes_resident
    assert spills_before > 0
    # Inject a REAL OOM on the very next tick: the live stream dies
    # into replay, degraded flushes every unpinned block — hard-frees.
    eng._faults = FaultPlan(scheduled=[
        FaultSpec(step=eng._step_idx, site="tick", kind=FaultKind.OOM)])
    h = eng.submit(prompts[0], 4)
    for _ in range(5):
        eng.step()
        if eng.degraded:
            break
    assert eng.degraded
    assert eng.metrics.host_tier_spills == spills_before, \
        "degraded flush demoted into the host tier"
    # Admissions while degraded promote nothing (cold path).
    hits_before = eng.metrics.host_tier_hits
    h2 = eng.submit(prompts[1], 4)
    eng.run(max_steps=2000)
    assert h.done and h2.done
    assert eng.metrics.host_tier_hits == hits_before
    assert eng.host_tier_bytes_resident == bytes_before


def test_promotion_budget_charge(gpt_setup):
    """The scheduler-facing price: a host-tier chain charges
    promote_tokens_per_block per block instead of block_size prefill
    tokens, and the charge lands on the counter."""
    model, variables = gpt_setup
    eng = _engine(model, variables, host=HostTierConfig(
        byte_budget=1 << 24, promote_tokens_per_block=3),
        prefill_token_budget=64)
    eng.warmup()
    prompts = _prompts(4)
    _serve_all(eng, prompts)   # A's chain ends up demoted by the cycle
    target = prompts[0]

    def cost_of(p):
        h = RequestHandle(Request(prompt=list(p), max_new_tokens=4),
                          arrival_s=0.0)
        return eng._prefill_cost(h)

    cold = cost_of(np.asarray(
        np.random.default_rng(9).integers(0, 32, 24), np.int32))
    assert cold == 24  # never seen: full prompt
    # `target`'s chain is split across tiers (LRU evicts leaf-first):
    # the cost composes the device match m with the host extension h —
    # promoted blocks price 3 tokens each instead of 8 prefill tokens.
    cap = (24 - 1) // BS
    m = eng._prefix.match(target, max_blocks=cap).n_blocks
    h = eng._host.match_depth(target, m, cap - m)
    assert h > 0, "the cycle never demoted target's chain"
    assert cost_of(target) == 24 - (m + h) * BS + h * 3
    charged_before = eng.metrics.host_tier_promote_tokens_charged
    handle = eng.submit(target, 4)
    eng.run(max_steps=2000)
    assert handle.done
    assert eng.metrics.host_tier_promote_tokens_charged \
        == charged_before + h * 3


def test_min_chain_blocks_policy(gpt_setup):
    """Spill-worthiness: chains shorter than min_chain_blocks are
    freed, not demoted."""
    model, variables = gpt_setup
    eng = _engine(model, variables, host=HostTierConfig(
        byte_budget=1 << 24, min_chain_blocks=3))
    eng.warmup()
    # 2-block prompts (16 tokens): every chain is below the floor.
    prompts = _prompts(4, length=16, seed=3)
    for _ in range(3):
        _serve_all(eng, prompts)
    assert eng.metrics.prefix_evictions > 0
    assert eng.metrics.host_tier_spills == 0


# ------------------------------------------------------------ resilience
def test_fault_storm_at_host_promote_replays_token_exact(
        gpt_setup, pin_zero_recompiles):
    model, variables = gpt_setup
    plan = FaultPlan(seed=7, transient_rate=1.0, sites=["host_promote"],
                     max_random_injections=4, sleep_fn=_no_sleep)
    eng = pin_zero_recompiles(_engine(model, variables, fault_plan=plan,
                                      backoff_sleep=_no_sleep))
    prompts = _prompts(4)
    refs = [ref_greedy(model, variables, p, 4) for p in prompts]
    for _ in range(3):
        assert _serve_all(eng, prompts) == refs
    assert eng.metrics.retries + eng.metrics.replays > 0
    assert eng._host.pins_outstanding == 0, "fault-unwind leaked a host pin"


@pytest.mark.chaos
@pytest.mark.parametrize("seed", [0, 1, 2])
@pytest.mark.parametrize("paged", [False, True], ids=["row", "paged"])
def test_chaos_matrix_faults_at_host_promote(gpt_setup, seed, paged,
                                             pin_zero_recompiles):
    """The ISSUE 13 chaos matrix: seeded transient storms aimed at the
    promotion site while chains cycle through the tier — every request
    terminal, every stream token-exact, zero recompiles, zero leaked
    host pins."""
    model, variables = gpt_setup
    plan = FaultPlan(seed=seed, transient_rate=0.5,
                     sites=["host_promote"], max_random_injections=6,
                     sleep_fn=_no_sleep)
    eng = pin_zero_recompiles(_engine(model, variables, paged=paged,
                                      fault_plan=plan,
                                      backoff_sleep=_no_sleep))
    prompts = _prompts(4, seed=seed)
    refs = [ref_greedy(model, variables, p, 4) for p in prompts]
    for _ in range(3):
        assert _serve_all(eng, prompts) == refs
    assert eng._host.pins_outstanding == 0


@pytest.mark.chaos
@pytest.mark.parametrize("paged", [False, True], ids=["row", "paged"])
def test_kill_mid_promotion_drain_restores_token_exact(gpt_setup, paged):
    """A KILL at the host_promote site unwinds out of step() like a
    real crash while the tier is populated; the drain snapshot (taken
    on the dying engine) restores into a FRESH tiered engine
    token-exactly — the tier's contents die with the process and that
    must not matter."""
    model, variables = gpt_setup
    prompts = _prompts(6)  # enough chains to overflow either pool
    refs = [ref_greedy(model, variables, p, 6) for p in prompts]
    plan = FaultPlan(scheduled=[
        FaultSpec(step=s, site="host_promote", kind=FaultKind.KILL)
        for s in range(200)])
    eng = _engine(model, variables, paged=paged, fault_plan=plan,
                  backoff_sleep=_no_sleep)
    eng.warmup()
    _serve_all(eng, prompts, n_new=6)  # cold pass: no promotions yet
    assert eng.metrics.host_tier_spills > 0
    handles = [eng.submit(p, 6) for p in prompts]  # hits → promotion
    killed = False
    for _ in range(2000):
        if all(h.done for h in handles):
            break
        try:
            eng.step()
        except KillPoint:
            killed = True
            break
    assert killed, "no promotion happened — the kill never fired"
    snapshot = eng.drain()
    fresh = _engine(model, variables, paged=paged)
    fresh.warmup()
    restored = fresh.restore(snapshot)
    fresh.run(max_steps=5000)
    assert all(h.done for h in restored)
    by_prompt = {tuple(h.request.prompt): list(h.tokens)
                 for h in restored}
    for p, ref in zip(prompts, refs):
        assert by_prompt[tuple(int(t) for t in p)] == ref


def test_drain_restore_with_tier_populated(gpt_setup):
    """A graceful drain while the tier holds chains restores into a
    fresh tiered engine token-exactly (KV is a pure function of the
    tokens; the tier is an optimization, never restore state)."""
    model, variables = gpt_setup
    prompts = _prompts(4)
    refs = [ref_greedy(model, variables, p, 8) for p in prompts]
    eng = _engine(model, variables)
    eng.warmup()
    _serve_all(eng, prompts)
    assert eng.metrics.host_tier_spills > 0
    handles = [eng.submit(p, 8) for p in prompts]
    for _ in range(3):
        eng.step()
    snapshot = eng.drain()
    fresh = _engine(model, variables)
    fresh.warmup()
    restored = fresh.restore(snapshot)
    fresh.run(max_steps=5000)
    assert [list(h.tokens) for h in restored] \
        == [refs[[tuple(int(t) for t in p) for p in prompts].index(
            tuple(h.request.prompt))] for h in restored]
    assert all(h.state is RequestState.FINISHED for h in restored)


# ---------------------------------------------------------- exposition
def test_exposition_round_trips_host_tier_series(gpt_setup):
    model, variables = gpt_setup
    eng = _engine(model, variables)
    eng.warmup()
    prompts = _prompts(4)
    _serve_all(eng, prompts)
    _serve_all(eng, prompts)
    text = serve_exposition(eng.metrics, eng)
    samples, types = parse_prometheus_text(text)
    for name in ("pddl_serve_host_tier_spills_total",
                 "pddl_serve_host_tier_hits_total",
                 "pddl_serve_host_tier_promotions_total",
                 "pddl_serve_host_tier_promote_tokens_charged_total"):
        assert (name, ()) in samples, name
        assert types[name] == "counter"
    assert samples[("pddl_serve_host_tier_bytes_resident", ())] \
        == eng.metrics.host_tier_bytes_resident
    assert types["pddl_serve_host_tier_bytes_resident"] == "gauge"
    assert samples[("pddl_serve_engine_host_tier", ())] == 1.0
    assert samples[("pddl_serve_engine_host_tier_bytes_resident", ())] \
        == eng.host_tier_bytes_resident
    assert ("pddl_serve_engine_compile_counts",
            (("key", "host_promote"),)) in samples


# ------------------------------------------------------------- transfer
def test_chain_wire_roundtrip_and_cross_engine_import(gpt_setup):
    """export → JSON → import on a sibling engine: the pulled chain
    promotes there and the stream stays token-exact (token identity is
    bit identity under the position-absolute cache contract)."""
    model, variables = gpt_setup
    prompts = _prompts(2)
    ref = ref_greedy(model, variables, prompts[0], 4)
    src = _engine(model, variables)
    src.warmup()
    _serve_all(src, prompts)
    entry = src.export_prefix_chain(prompts[0])
    assert entry is not None
    entry = json.loads(json.dumps(entry))  # the pipe's JSON round trip
    toks, blocks = kv_chain_from_wire(entry)
    assert toks == [int(t) for t in prompts[0][:len(blocks) * BS]]
    assert kv_chain_from_wire(kv_chain_to_wire(toks, blocks))[0] == toks
    dst = _engine(model, variables)
    dst.warmup()
    assert dst.import_prefix_chain(entry) == len(blocks)
    h = dst.submit(prompts[0], 4)
    dst.run(max_steps=2000)
    assert list(h.tokens) == ref
    assert dst.metrics.host_tier_hits == 1
    assert dst.metrics.prefill_tokens_saved >= len(blocks) * BS
    # An untiered sibling refuses gracefully — BOTH directions: import
    # is a counted no-op, and export answers None instead of reaching
    # for the tier's jitted gather (a TypeError here used to kill the
    # whole worker process when a pull-armed router met a tier-less
    # replica).
    plain = _engine(model, variables, host=None)
    plain.warmup()
    assert plain.import_prefix_chain(entry) == 0
    _serve_all(plain, prompts)
    assert plain.export_prefix_chain(prompts[0]) is None


def test_shadow_models_the_second_tier():
    shadow = _ShadowIndex(BS, capacity_blocks=3, host_capacity_blocks=64)
    p1 = list(range(4 * BS))
    shadow.observe(p1, max_blocks=4)      # capacity 3: stores 3 blocks
    assert shadow.match_blocks(p1, 4) == 3
    p2 = [500 + t for t in range(4 * BS)]
    shadow.observe(p2, max_blocks=4)      # evicts p1 into the host shadow
    assert shadow.match_blocks_host(p1, 4) > 0
    blind = _ShadowIndex(BS, capacity_blocks=3)
    blind.observe(p1, max_blocks=4)
    blind.observe(p2, max_blocks=4)
    assert blind.match_blocks_host(p1, 4) == 0


def _fleet_factory(model, variables):
    def factory():
        return ServeEngine(model, variables, max_slots=2, prefill_len=32,
                           prefix_cache_blocks=24, prefix_block_size=BS,
                           prefix_chunk=BS, host_tier=1 << 24)
    return factory


def test_fleet_chain_pull_eliminates_duplicate_prefill(gpt_setup):
    """The 2-replica leg: replica A holds the warm shared prefix, load
    pressure escapes an interactive request to cold replica B. Shadow-
    blind, B re-prefills the prefix (duplicate work); with the pull, B
    imports A's chain and PROMOTES instead — and the stream is
    identical either way."""
    model, variables = gpt_setup
    rng = np.random.default_rng(11)
    shared = rng.integers(0, 32, size=24).astype(np.int32)
    probe = np.concatenate([shared[:16],
                            rng.integers(0, 32, 8).astype(np.int32)])

    def run(pull):
        fleet = FleetRouter(
            [LocalReplica(0, _fleet_factory(model, variables)),
             LocalReplica(1, _fleet_factory(model, variables))],
            affinity_block_size=BS, interactive_reroute_load=1,
            shadow_host_capacity_blocks=1024,
            chain_pull_blocks=(2 if pull else None))
        fleet.warmup()
        h1 = fleet.submit(list(shared), 4, priority=Priority.BATCH)
        while not h1.done:
            fleet.step()
        warm = h1.replica_id
        busy = [fleet.submit(list(shared), 24, priority=Priority.BATCH)
                for _ in range(2)]
        h2 = fleet.submit(list(probe), 4,
                          priority=Priority.INTERACTIVE)
        while not (h2.done and all(b.done for b in busy)):
            fleet.step()
        cold_slot = next(s for s in fleet.replicas
                         if s.replica_id != warm)
        assert h2.replica_id == cold_slot.replica_id  # load escape fired
        saved = cold_slot.driver.engine.metrics.prefill_tokens_saved
        pulls = fleet.metrics.chain_pulls
        fleet.close()
        return list(h2.tokens), saved, pulls

    t_blind, saved_blind, pulls_blind = run(False)
    t_pull, saved_pull, pulls_pull = run(True)
    assert t_blind == t_pull
    assert pulls_blind == 0 and pulls_pull >= 1
    assert saved_blind == 0          # duplicate prefill paid in full
    assert saved_pull >= 2 * BS      # the pulled chain was promoted


def test_fleet_exposition_carries_tier_counters(gpt_setup):
    model, variables = gpt_setup
    fleet = FleetRouter(
        [LocalReplica(0, _fleet_factory(model, variables))],
        affinity_block_size=BS, shadow_host_capacity_blocks=64,
        chain_pull_blocks=2)
    samples, types = parse_prometheus_text(fleet_exposition(fleet))
    for name in ("pddl_fleet_routed_host_tier_total",
                 "pddl_fleet_chain_pulls_total",
                 "pddl_fleet_chain_pull_tokens_total"):
        assert (name, ()) in samples, name
        assert types[name] == "counter"
    fleet.close()


def test_router_routes_to_host_tier_holder(gpt_setup):
    """No replica holds the prefix in HBM, one holds it in host RAM:
    the route label is host_tier and the counter moves."""
    model, variables = gpt_setup
    fleet = FleetRouter(
        [LocalReplica(0, _fleet_factory(model, variables)),
         LocalReplica(1, _fleet_factory(model, variables))],
        affinity_block_size=BS, shadow_host_capacity_blocks=1024)
    fleet.warmup()
    prompt = list(range(24))
    # White-box shadow state: replica 1 once held the chain, its
    # device shadow evicted it to the host shadow.
    fleet.replicas[1].shadow.observe_host(prompt, max_blocks=3)
    slot, how, _, _ = fleet._route(
        prompt, None, [s for s in fleet.replicas if s.available])
    assert how == "host_tier" and slot.replica_id == 1
    h = fleet.submit(prompt, 2)
    while not h.done:
        fleet.step()
    assert h.replica_id == 1
    assert fleet.metrics.routed_host_tier == 1
    fleet.close()
