"""Chunked large-vocab cross-entropy: exactness vs the naive logits path
(loss AND all three gradients), padding, shapes, and the end-to-end
headless-GPT training integration."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from pddl_tpu.ops.large_vocab import chunked_cross_entropy


def _naive(features, kernel, labels, bias):
    logits = (features.astype(jnp.float32) @ kernel.astype(jnp.float32)
              + bias.astype(jnp.float32))
    return optax.softmax_cross_entropy_with_integer_labels(
        logits, labels).mean()


@pytest.mark.parametrize("v,chunk", [(64, 64), (100, 32), (257, 64)])
def test_matches_naive_loss_and_grads(v, chunk):
    """Including non-dividing vocab sizes (padding path)."""
    rng = np.random.default_rng(0)
    n, e = 24, 16
    features = jnp.asarray(rng.normal(size=(n, e)), jnp.float32)
    kernel = jnp.asarray(rng.normal(size=(e, v)) * 0.1, jnp.float32)
    bias = jnp.asarray(rng.normal(size=(v,)) * 0.1, jnp.float32)
    labels = jnp.asarray(rng.integers(0, v, n), jnp.int32)

    ref = _naive(features, kernel, labels, bias)
    got = chunked_cross_entropy(features, kernel, labels, bias,
                                chunk_size=chunk)
    np.testing.assert_allclose(float(got), float(ref), rtol=1e-5)

    ref_grads = jax.grad(_naive, argnums=(0, 1, 3))(
        features, kernel, labels, bias)
    got_grads = jax.grad(
        lambda f, k, b: chunked_cross_entropy(f, k, labels, b,
                                              chunk_size=chunk),
        argnums=(0, 1, 2),
    )(features, kernel, bias)
    for g_ref, g_got in zip(ref_grads, got_grads):
        np.testing.assert_allclose(np.asarray(g_got), np.asarray(g_ref),
                                   rtol=2e-4, atol=1e-6)


def test_batched_shape_and_no_bias():
    rng = np.random.default_rng(1)
    b, s, e, v = 2, 8, 16, 96
    features = jnp.asarray(rng.normal(size=(b, s, e)), jnp.float32)
    kernel = jnp.asarray(rng.normal(size=(e, v)) * 0.1, jnp.float32)
    labels = jnp.asarray(rng.integers(0, v, (b, s)), jnp.int32)
    got = chunked_cross_entropy(features, kernel, labels, chunk_size=32)
    ref = _naive(features.reshape(-1, e), kernel, labels.reshape(-1),
                 jnp.zeros((v,)))
    np.testing.assert_allclose(float(got), float(ref), rtol=1e-5)


def test_jit_and_bf16_features():
    rng = np.random.default_rng(2)
    n, e, v = 16, 8, 40
    features = jnp.asarray(rng.normal(size=(n, e)), jnp.bfloat16)
    kernel = jnp.asarray(rng.normal(size=(e, v)) * 0.1, jnp.bfloat16)
    labels = jnp.asarray(rng.integers(0, v, n), jnp.int32)
    f = jax.jit(lambda ff, kk: chunked_cross_entropy(ff, kk, labels,
                                                     chunk_size=16))
    loss = f(features, kernel)
    assert loss.dtype == jnp.float32 and np.isfinite(float(loss))
    g = jax.jit(jax.grad(lambda ff: chunked_cross_entropy(
        ff, kernel, labels, chunk_size=16)))(features)
    assert g.dtype == jnp.bfloat16


def test_headless_gpt_trains_with_chunked_loss():
    """The integration pattern: transformer features + own head params +
    chunked CE as the loss — converges on the deterministic task just
    like the logits path."""
    from pddl_tpu.data.synthetic import SyntheticLanguageModeling
    from pddl_tpu.models.gpt import tiny_gpt

    vocab = 32
    ds = SyntheticLanguageModeling(batch_size=16, seq_len=16,
                                   vocab_size=vocab, seed=0)
    model = tiny_gpt(vocab_size=vocab, max_len=32)
    batch0 = ds.batch(0)
    tokens0 = jnp.asarray(batch0["tokens"])
    variables = model.init(jax.random.key(0), tokens0, train=False)
    params = variables["params"]
    tx = optax.adamw(3e-3)
    opt_state = tx.init(params)

    def loss_fn(params, tokens, targets):
        # Features = ln_final's output (what feeds the lm_head Dense),
        # captured via capture_intermediates; the head's own kernel/bias
        # then enter the loss through the chunked op instead of a
        # [B,S,V] logits matmul. (XLA drops the unused lm_head forward
        # as dead code.)
        out, state = model.apply(
            {"params": params}, tokens, train=True,
            capture_intermediates=lambda mdl, _: mdl.name == "ln_final",
        )
        feats = jax.tree.leaves(
            state["intermediates"]["ln_final"]["__call__"])[0]
        head = params["lm_head"]
        return chunked_cross_entropy(
            feats, head["kernel"], targets, head["bias"], chunk_size=16)

    @jax.jit
    def step(params, opt_state, tokens, targets):
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens, targets)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    losses = []
    for i in range(30):
        b = ds.batch(i)
        params, opt_state, loss = step(
            params, opt_state, jnp.asarray(b["tokens"]),
            jnp.asarray(b["targets"]))
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.5, losses[:3] + losses[-3:]
    # And the head's gradient actually flowed (kernel moved).
    moved = np.abs(np.asarray(params["lm_head"]["kernel"]
                              - variables["params"]["lm_head"]["kernel"]))
    assert moved.max() > 1e-4
