"""Llama family: RoPE/RMSNorm/SwiGLU/GQA correctness.

The modern-decoder analogue of test_gpt.py (the reference repo has no
transformer at all — SURVEY.md §5 "Long-context: absent"): golden logits
vs a genuine ``transformers`` Llama (random-init, no network), GQA
semantics, KV-cache decode parity, the shared generate()/fused-CE
machinery, and Megatron TP under ``LLAMA_TP_RULES``.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from jax.sharding import PartitionSpec as P

from pddl_tpu.core.mesh import MODEL_AXIS
from pddl_tpu.models.llama import Llama, tiny_llama
from pddl_tpu.models.gpt import fused_lm_loss, generate

V, S, E, L, H = 61, 24, 32, 2, 4


def _tokens(batch=2, seq=S, vocab=V, seed=3):
    return jnp.asarray(
        jax.random.randint(jax.random.key(seed), (batch, seq), 0, vocab),
        jnp.int32,
    )


def _model(**kw):
    kw.setdefault("vocab_size", V)
    kw.setdefault("max_len", 64)
    kw.setdefault("embed_dim", E)
    kw.setdefault("depth", L)
    kw.setdefault("num_heads", H)
    kw.setdefault("num_kv_heads", 2)
    kw.setdefault("attention", "reference")
    return Llama(**kw)


def test_llama_shapes_and_param_tree():
    model = _model()
    tokens = _tokens()
    v = model.init(jax.random.key(0), tokens, train=False)
    logits = model.apply(v, tokens, train=False)
    assert logits.shape == (2, S, V) and logits.dtype == jnp.float32
    blk = v["params"]["block0"]
    # GQA: K/V carry num_kv_heads=2 vs 4 query heads; SwiGLU three mats;
    # no biases anywhere in the block.
    assert blk["attn"]["query"]["kernel"].shape == (E, 4, E // 4)
    assert blk["attn"]["key"]["kernel"].shape == (E, 2, E // 4)
    assert "bias" not in blk["attn"]["query"]
    assert set(blk) == {"ln1", "ln2", "attn", "mlp_gate", "mlp_up",
                        "mlp_down"}
    assert "bias" not in v["params"]["lm_head"]


def test_llama_causality():
    """Changing a future token must not change earlier logits."""
    model = _model()
    tokens = _tokens()
    v = model.init(jax.random.key(0), tokens, train=False)
    base = model.apply(v, tokens, train=False)
    mutated = tokens.at[:, -1].set((tokens[:, -1] + 1) % V)
    got = model.apply(v, mutated, train=False)
    np.testing.assert_allclose(np.asarray(base[:, :-1]),
                               np.asarray(got[:, :-1]), atol=1e-6)
    assert not np.allclose(np.asarray(base[:, -1]), np.asarray(got[:, -1]))


def test_gqa_matches_mha_with_tiled_kv():
    """GQA is definitionally MHA with each KV head repeated: tiling the
    2-head K/V weights into a 4-head model must reproduce the logits."""
    tokens = _tokens()
    gqa = _model(num_kv_heads=2)
    mha = _model(num_kv_heads=4)
    v_gqa = gqa.init(jax.random.key(0), tokens, train=False)
    params = jax.tree.map(np.asarray, v_gqa["params"])
    for i in range(L):
        attn = params[f"block{i}"]["attn"]
        for name in ("key", "value"):
            attn[name] = {"kernel": np.repeat(attn[name]["kernel"], 2, axis=1)}
    ref = gqa.apply(v_gqa, tokens, train=False)
    got = mha.apply({"params": params}, tokens, train=False)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


def test_flash_matches_reference_attention():
    model_ref = _model()
    model_fl = _model(attention="flash")
    tokens = _tokens()
    v = model_ref.init(jax.random.key(0), tokens, train=False)
    ref = model_ref.apply(v, tokens, train=False)
    got = model_fl.apply(v, tokens, train=False)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_decode_matches_full_forward():
    """Prefill + single-token KV-cache steps reproduce the full forward's
    next-token logits at every position."""
    model = _model()
    tokens = _tokens(batch=2, seq=12)
    v = model.init(jax.random.key(0), tokens, train=False)
    full = model.apply(v, tokens, train=False)

    dec = model.clone(decode=True)
    cache = jax.eval_shape(
        lambda: dec.init(jax.random.key(0), tokens[:, :1], train=False)
    )["cache"]
    cache = jax.tree.map(lambda sd: jnp.zeros(sd.shape, sd.dtype), cache)

    # Prefill the first 4 tokens in one call, then step one at a time.
    logits, mut = dec.apply({"params": v["params"], "cache": cache},
                            tokens[:, :4], train=False, mutable=["cache"])
    np.testing.assert_allclose(np.asarray(logits), np.asarray(full[:, :4]),
                               atol=1e-5, rtol=1e-5)
    cache = mut["cache"]
    for t in range(4, 12):
        logits, mut = dec.apply({"params": v["params"], "cache": cache},
                                tokens[:, t:t + 1], train=False,
                                mutable=["cache"])
        cache = mut["cache"]
        np.testing.assert_allclose(
            np.asarray(logits[:, 0]), np.asarray(full[:, t]),
            atol=1e-5, rtol=1e-5)


def test_generate_works_on_llama():
    """gpt.generate() is duck-typed over the Llama family (same decode
    interface); greedy decoding is deterministic and respects shapes."""
    model = _model()
    v = model.init(jax.random.key(0), _tokens(), train=False)
    prompt = _tokens(batch=2, seq=5, seed=11)
    out1 = generate(model, {"params": v["params"]}, prompt, max_new_tokens=6)
    out2 = generate(model, {"params": v["params"]}, prompt, max_new_tokens=6)
    assert out1.shape == (2, 11)
    np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))
    np.testing.assert_array_equal(np.asarray(out1[:, :5]), np.asarray(prompt))
    # Greedy continuation must equal argmax over the full forward.
    full = model.apply(v, out1[:, :-1], train=False)
    np.testing.assert_array_equal(
        np.asarray(out1[:, 5:]),
        np.asarray(jnp.argmax(full[:, 4:], axis=-1)))


def test_fused_lm_loss_matches_materialized_biasless():
    """The fused-CE path handles the Llama family's bias-free head."""
    model = _model()
    tokens = _tokens()
    targets = jnp.roll(tokens, -1, axis=1)
    v = model.init(jax.random.key(0), tokens, train=False)

    def materialized(params):
        logits = model.apply({"params": params}, tokens, train=False)
        logp = jax.nn.log_softmax(logits, axis=-1)
        return -jnp.mean(jnp.take_along_axis(
            logp, targets[..., None], axis=-1))

    def fused(params):
        return fused_lm_loss(model, {"params": params}, tokens, targets,
                             train=False)

    l1, g1 = jax.value_and_grad(materialized)(v["params"])
    l2, g2 = jax.value_and_grad(fused)(v["params"])
    np.testing.assert_allclose(float(l1), float(l2), rtol=1e-5)
    a = np.asarray(g1["block0"]["mlp_gate"]["kernel"])
    b = np.asarray(g2["block0"]["mlp_gate"]["kernel"])
    np.testing.assert_allclose(a, b, atol=1e-5, rtol=1e-4)


def test_init_through_features_only_still_creates_lm_head():
    """An init traced through the fused-CE path (features_only=True) must
    still create lm_head params — like gpt._GPTHead's init fall-through —
    or fused_lm_loss KeyErrors on its own init tree."""
    model = _model()
    tokens = _tokens()
    v = model.init(jax.random.key(0), tokens, train=False,
                   features_only=True)
    assert "lm_head" in v["params"]
    loss = fused_lm_loss(model, v, tokens, jnp.roll(tokens, -1, axis=1),
                         train=False)
    assert np.isfinite(float(loss))


def test_llama_under_tensor_parallel():
    from pddl_tpu.data.synthetic import SyntheticLanguageModeling
    from pddl_tpu.parallel.tensor_parallel import (
        LLAMA_TP_RULES, TensorParallelStrategy)
    from pddl_tpu.train.loop import Trainer

    strategy = TensorParallelStrategy(model_parallel=2, rules=LLAMA_TP_RULES)
    ds = SyntheticLanguageModeling(batch_size=8, seq_len=32, vocab_size=16,
                                   seed=0)
    tr = Trainer(tiny_llama(vocab_size=16), optimizer="adamw",
                 learning_rate=3e-3, strategy=strategy, seed=0,
                 input_key="tokens", target_key="targets")
    hist = tr.fit(ds, epochs=1, steps_per_epoch=4, verbose=0)
    assert np.isfinite(hist.history["loss"][-1])
    params = tr.state.params
    blk = params["block0"]
    assert blk["attn"]["query"]["kernel"].sharding.spec == P(None, MODEL_AXIS)
    # GQA K/V: 2 kv heads over model_parallel=2 still shard cleanly.
    assert blk["attn"]["key"]["kernel"].sharding.spec == P(None, MODEL_AXIS)
    assert blk["mlp_gate"]["kernel"].sharding.spec == P(None, MODEL_AXIS)
    assert blk["mlp_down"]["kernel"].sharding.spec == P(MODEL_AXIS)
    assert params["embed"]["embedding"].sharding.spec == P(MODEL_AXIS)


# ------------------------------------------------------------ HF golden
torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")


def _hf_llama(vocab=V, kv_heads=2, cls=None, **extra):
    """Tiny random HF model sharing one hyperparameter set across all the
    golden tests; ``cls``/``extra`` cover the Mistral variant."""
    if cls is None:
        cls = transformers.LlamaForCausalLM
    cfg = cls.config_class(
        vocab_size=vocab, hidden_size=E, intermediate_size=64,
        num_hidden_layers=L, num_attention_heads=H,
        num_key_value_heads=kv_heads, max_position_embeddings=64,
        rms_norm_eps=1e-6, rope_theta=10000.0, attention_dropout=0.0,
        tie_word_embeddings=False, **extra,
    )
    torch.manual_seed(0)
    return cls(cfg).eval()


def test_hf_llama_logits_match():
    from pddl_tpu.ckpt.hf_import import load_hf_llama

    hf = _hf_llama()
    ours = _model(intermediate_dim=64, rms_eps=1e-6)
    tokens = _tokens()
    v = ours.init(jax.random.key(0), tokens, train=False)
    v = load_hf_llama(hf, v, model=ours)

    with torch.no_grad():
        ref = hf(torch.from_numpy(
            np.asarray(tokens, np.int64))).logits.numpy()
    got = np.asarray(ours.apply(v, tokens, train=False))
    np.testing.assert_allclose(got, ref, atol=3e-4, rtol=3e-4)


def test_hf_llama_rejects_mismatched_eps():
    from pddl_tpu.ckpt.hf_import import load_hf_llama

    hf = _hf_llama()  # rms_norm_eps=1e-6
    ours = _model(intermediate_dim=64, rms_eps=1e-5)
    v = ours.init(jax.random.key(0), _tokens(), train=False)
    with pytest.raises(ValueError, match="rms_eps"):
        load_hf_llama(hf, v, model=ours)


def test_hf_llama_import_into_padded_vocab():
    from pddl_tpu.ckpt.hf_import import load_hf_llama

    hf = _hf_llama()
    ours = _model(intermediate_dim=64, rms_eps=1e-6, vocab_multiple=32)
    tokens = _tokens()
    v = ours.init(jax.random.key(0), tokens, train=False)
    v = load_hf_llama(hf, v, model=ours)
    with torch.no_grad():
        ref = hf(torch.from_numpy(
            np.asarray(tokens, np.int64))).logits.numpy()
    got = np.asarray(ours.apply(v, tokens, train=False))
    np.testing.assert_allclose(got, ref, atol=3e-4, rtol=3e-4)


def test_sliding_window_flash_matches_reference():
    """Mistral-style SWA through the family: flash and reference agree,
    and the window genuinely changes the function vs plain causal."""
    tokens = _tokens()
    plain = _model()
    swa_ref = _model(sliding_window=8)
    swa_fl = _model(sliding_window=8, attention="flash")
    v = plain.init(jax.random.key(0), tokens, train=False)
    out_plain = plain.apply(v, tokens, train=False)
    out_ref = swa_ref.apply(v, tokens, train=False)
    out_fl = swa_fl.apply(v, tokens, train=False)
    np.testing.assert_allclose(np.asarray(out_fl), np.asarray(out_ref),
                               atol=2e-5, rtol=2e-5)
    assert not np.allclose(np.asarray(out_plain), np.asarray(out_ref))


def test_sliding_window_decode_matches_full_forward():
    model = _model(sliding_window=4)
    tokens = _tokens(batch=2, seq=12)
    v = model.init(jax.random.key(0), tokens, train=False)
    full = model.apply(v, tokens, train=False)

    dec = model.clone(decode=True)
    cache = jax.eval_shape(
        lambda: dec.init(jax.random.key(0), tokens[:, :1], train=False)
    )["cache"]
    cache = jax.tree.map(lambda sd: jnp.zeros(sd.shape, sd.dtype), cache)
    logits, mut = dec.apply({"params": v["params"], "cache": cache},
                            tokens[:, :6], train=False, mutable=["cache"])
    np.testing.assert_allclose(np.asarray(logits), np.asarray(full[:, :6]),
                               atol=1e-5, rtol=1e-5)
    cache = mut["cache"]
    for t in range(6, 12):
        logits, mut = dec.apply({"params": v["params"], "cache": cache},
                                tokens[:, t:t + 1], train=False,
                                mutable=["cache"])
        cache = mut["cache"]
        np.testing.assert_allclose(
            np.asarray(logits[:, 0]), np.asarray(full[:, t]),
            atol=1e-5, rtol=1e-5)


@pytest.mark.parametrize("attention", ["ring", "ring_flash"])
def test_sliding_window_supported_on_ring_path(attention):
    """SWA × sequence parallelism (VERDICT r3 task 4): the ring paths
    accept sliding_window and reproduce the windowed reference logits —
    long-context Mistral's two levers compose."""
    from pddl_tpu.core.mesh import MeshConfig, build_mesh

    mesh = build_mesh(MeshConfig(data=1, seq=8))
    tokens = _tokens(batch=1, seq=64)
    ref_model = _model(sliding_window=10, max_len=64)
    ring_model = _model(sliding_window=10, max_len=64,
                        attention=attention, mesh=mesh)
    v = ref_model.init(jax.random.key(0), tokens, train=False)
    ref = ref_model.apply(v, tokens, train=False)
    got = jax.jit(lambda t: ring_model.apply(v, t, train=False))(tokens)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=2e-4, rtol=2e-4)


def test_generate_respects_sliding_window():
    """Greedy generate on an SWA model equals full-forward argmax (the
    decode cache applies the same window as the train-path mask)."""
    model = _model(sliding_window=4, max_len=32)
    v = model.init(jax.random.key(0), _tokens(seq=16), train=False)
    prompt = _tokens(batch=2, seq=5, seed=11)
    out = generate(model, {"params": v["params"]}, prompt, max_new_tokens=6)
    full = model.apply(v, out[:, :-1], train=False)
    np.testing.assert_array_equal(
        np.asarray(out[:, 5:]),
        np.asarray(jnp.argmax(full[:, 4:], axis=-1)))


@pytest.mark.slow  # multi-hop pallas-interpret loop: tier-2 wall-clock
def test_rolling_ring_cache_wraps_and_matches_full_forward():
    """Mistral's rolling KV cache: with window < max_len the decode cache
    is a ring of ~window slots (not max_len), and logits stay exact at
    every position even after the ring has WRAPPED (oldest keys
    overwritten are precisely the out-of-window ones)."""
    model = _model(sliding_window=100, max_len=192)
    seq = 160  # > ring length 128: wraps
    tokens = _tokens(batch=1, seq=seq, seed=9)
    v = model.init(jax.random.key(0), tokens, train=False)
    full = model.apply(v, tokens, train=False)

    dec = model.clone(decode=True)
    cache = jax.eval_shape(
        lambda: dec.init(jax.random.key(0), tokens[:, :1], train=False)
    )["cache"]
    # The ring is window-sized (rounded to 128), NOT max_len-sized.
    k_shape = cache["block0"]["attn"]["cached_key"].shape
    assert k_shape[2] == 128, k_shape
    cache = jax.tree.map(lambda sd: jnp.zeros(sd.shape, sd.dtype), cache)

    prefill = 8
    logits, mut = dec.apply({"params": v["params"], "cache": cache},
                            tokens[:, :prefill], train=False,
                            mutable=["cache"])
    np.testing.assert_allclose(np.asarray(logits),
                               np.asarray(full[:, :prefill]),
                               atol=1e-5, rtol=1e-5)
    cache = mut["cache"]
    step = jax.jit(lambda cache, tok: dec.apply(
        {"params": v["params"], "cache": cache}, tok,
        train=False, mutable=["cache"]))
    for t in range(prefill, seq):
        logits, mut = step(cache, tokens[:, t:t + 1])
        cache = mut["cache"]
        np.testing.assert_allclose(
            np.asarray(logits[:, 0]), np.asarray(full[:, t]),
            atol=1e-5, rtol=1e-5, err_msg=f"position {t}")


@pytest.mark.slow  # multi-hop pallas-interpret loop: tier-2 wall-clock
def test_ring_prefill_longer_than_ring():
    """A prompt longer than the ring: prefill writes only the last
    `ring` keys; subsequent single-token steps stay exact."""
    model = _model(sliding_window=100, max_len=256)
    seq, prefill = 150, 140  # prefill 140 > ring 128
    tokens = _tokens(batch=1, seq=seq, seed=13)
    v = model.init(jax.random.key(0), tokens, train=False)
    full = model.apply(v, tokens, train=False)

    dec = model.clone(decode=True)
    cache = jax.eval_shape(
        lambda: dec.init(jax.random.key(0), tokens[:, :1], train=False)
    )["cache"]
    cache = jax.tree.map(lambda sd: jnp.zeros(sd.shape, sd.dtype), cache)
    logits, mut = dec.apply({"params": v["params"], "cache": cache},
                            tokens[:, :prefill], train=False,
                            mutable=["cache"])
    np.testing.assert_allclose(np.asarray(logits),
                               np.asarray(full[:, :prefill]),
                               atol=1e-5, rtol=1e-5)
    cache = mut["cache"]
    for t in range(prefill, seq):
        logits, mut = dec.apply({"params": v["params"], "cache": cache},
                                tokens[:, t:t + 1], train=False,
                                mutable=["cache"])
        cache = mut["cache"]
        np.testing.assert_allclose(
            np.asarray(logits[:, 0]), np.asarray(full[:, t]),
            atol=1e-5, rtol=1e-5, err_msg=f"position {t}")


@pytest.mark.slow  # multi-hop pallas-interpret loop: tier-2 wall-clock
def test_ring_chunked_prefill_at_nonzero_index():
    """Chunked prefill on the SWA ring path: a SECOND multi-token call at
    i > 0 (after the ring has content, including post-wrap) must merge
    in-window HISTORY keys with the block's own — exact vs full forward."""
    model = _model(sliding_window=100, max_len=256)
    tokens = _tokens(batch=1, seq=200, seed=17)
    v = model.init(jax.random.key(0), tokens, train=False)
    full = model.apply(v, tokens, train=False)

    dec = model.clone(decode=True)
    cache = jax.eval_shape(
        lambda: dec.init(jax.random.key(0), tokens[:, :1], train=False)
    )["cache"]
    cache = jax.tree.map(lambda sd: jnp.zeros(sd.shape, sd.dtype), cache)
    # Three multi-token chunks: 0..80 (no wrap), 80..150 (wraps the
    # 128-ring), 150..200 (fully wrapped history).
    for lo, hi in ((0, 80), (80, 150), (150, 200)):
        logits, mut = dec.apply({"params": v["params"], "cache": cache},
                                tokens[:, lo:hi], train=False,
                                mutable=["cache"])
        cache = mut["cache"]
        np.testing.assert_allclose(
            np.asarray(logits), np.asarray(full[:, lo:hi]),
            atol=1e-5, rtol=1e-5, err_msg=f"chunk {lo}:{hi}")


def test_decode_attention_rolling_validates_statics():
    from pddl_tpu.ops.attention import decode_attention

    q = jnp.zeros((1, 2, 1, 8))
    c = jnp.zeros((1, 2, 64, 8))
    with pytest.raises(ValueError, match="sliding window"):
        decode_attention(q, c, c, jnp.int32(0), rolling=True)
    with pytest.raises(ValueError, match="overwritten"):
        decode_attention(q, c, c, jnp.int32(0), rolling=True, window=100)


def test_sliding_window_below_one_rejected_everywhere():
    from pddl_tpu.ops.attention import attention_reference, flash_attention

    q = jnp.zeros((1, 1, 16, 8))
    for fn in (flash_attention, attention_reference):
        with pytest.raises(ValueError, match=">= 1"):
            fn(q, q, q, causal=True, window=0)
    model = _model(sliding_window=0)
    with pytest.raises(ValueError, match=">= 1"):
        model.init(jax.random.key(0), _tokens(), train=False)
    dec = _model(sliding_window=-1).clone(decode=True)
    with pytest.raises(ValueError, match=">= 1"):
        dec.init(jax.random.key(0), _tokens(seq=1), train=False)


def test_hf_mistral_checkpoint_loads_with_sliding_window():
    """A Mistral checkpoint is a Llama-layout state dict + SWA config:
    load_hf_llama imports it, and with sliding_window set from the config
    our logits match transformers' (S=24 > window=8, so the window
    genuinely shapes the compared logits)."""
    from pddl_tpu.ckpt.hf_import import load_hf_llama

    hf = _hf_llama(cls=transformers.MistralForCausalLM, sliding_window=8)
    ours = _model(intermediate_dim=64, rms_eps=1e-6, sliding_window=8)
    tokens = _tokens()
    v = ours.init(jax.random.key(0), tokens, train=False)
    v = load_hf_llama(hf, v, model=ours)
    with torch.no_grad():
        ref = hf(torch.from_numpy(
            np.asarray(tokens, np.int64))).logits.numpy()
    got = np.asarray(ours.apply(v, tokens, train=False))
    np.testing.assert_allclose(got, ref, atol=3e-4, rtol=3e-4)


def test_hf_mistral_rejects_sliding_window_mismatch():
    from pddl_tpu.ckpt.hf_import import load_hf_llama

    hf = _hf_llama(cls=transformers.MistralForCausalLM, sliding_window=8)
    ours = _model(intermediate_dim=64, rms_eps=1e-6)  # window left unset
    v = ours.init(jax.random.key(0), _tokens(), train=False)
    with pytest.raises(ValueError, match="sliding_window"):
        load_hf_llama(hf, v, model=ours)


def test_hf_qwen2_checkpoint_loads_with_qkv_bias():
    """Qwen2's structural delta is q/k/v projection biases: build with
    qkv_bias=True and the imported logits match transformers'."""
    from pddl_tpu.ckpt.hf_import import load_hf_llama

    hf = _hf_llama(cls=transformers.Qwen2ForCausalLM)
    ours = _model(intermediate_dim=64, rms_eps=1e-6, qkv_bias=True)
    tokens = _tokens()
    v = ours.init(jax.random.key(0), tokens, train=False)
    blk = v["params"]["block0"]["attn"]
    assert "bias" in blk["query"] and "bias" not in blk["out"]
    v = load_hf_llama(hf, v, model=ours)
    with torch.no_grad():
        ref = hf(torch.from_numpy(
            np.asarray(tokens, np.int64))).logits.numpy()
    got = np.asarray(ours.apply(v, tokens, train=False))
    np.testing.assert_allclose(got, ref, atol=3e-4, rtol=3e-4)


def test_hf_qwen2_bias_mismatch_raises_descriptively():
    from pddl_tpu.ckpt.hf_import import load_hf_llama

    hf = _hf_llama(cls=transformers.Qwen2ForCausalLM)
    ours = _model(intermediate_dim=64, rms_eps=1e-6)  # qkv_bias left False
    v = ours.init(jax.random.key(0), _tokens(), train=False)
    with pytest.raises(ValueError, match="qkv_bias=True"):
        load_hf_llama(hf, v, model=ours)


def test_hf_mixed_layer_types_rejected():
    """A checkpoint windowing only SOME layers (Qwen2 max_window_layers)
    is unrepresentable by the global sliding_window attribute."""
    from pddl_tpu.ckpt.hf_import import load_hf_llama

    hf = _hf_llama(cls=transformers.Qwen2ForCausalLM,
                   use_sliding_window=True, sliding_window=8,
                   max_window_layers=1)  # layer 0 full, layer 1 sliding
    ours = _model(intermediate_dim=64, rms_eps=1e-6, qkv_bias=True)
    v = ours.init(jax.random.key(0), _tokens(), train=False)
    with pytest.raises(ValueError, match="per-layer attention types"):
        load_hf_llama(hf, v, model=ours)


def test_hf_export_roundtrips_into_transformers():
    """export_hf_llama produces a state dict transformers loads strictly,
    and the served logits match ours — TPU-train, serve-anywhere."""
    from pddl_tpu.ckpt.hf_export import export_hf_llama

    ours = _model(intermediate_dim=64, rms_eps=1e-6, qkv_bias=True)
    tokens = _tokens()
    v = ours.init(jax.random.key(7), tokens, train=False)
    sd = {k: torch.from_numpy(x) for k, x in export_hf_llama(
        v, model=ours).items()}

    hf = _hf_llama(cls=transformers.Qwen2ForCausalLM)
    missing, unexpected = hf.load_state_dict(sd, strict=True)
    assert not missing and not unexpected
    hf = hf.eval()
    with torch.no_grad():
        ref = hf(torch.from_numpy(
            np.asarray(tokens, np.int64))).logits.numpy()
    got = np.asarray(ours.apply(v, tokens, train=False))
    np.testing.assert_allclose(got, ref, atol=3e-4, rtol=3e-4)


def _mixtral_pair():
    """Matching (HF MixtralForCausalLM, our MoE Llama) at tiny shape.

    moe_capacity_factor is generous because Mixtral routing is DROPLESS;
    with capacity >= routed tokens the dense-dispatch formulation is
    exactly transformers' gather/scatter one."""
    hf = _hf_llama(cls=transformers.MixtralForCausalLM,
                   num_local_experts=4, num_experts_per_tok=2,
                   sliding_window=None, router_aux_loss_coef=0.0)
    ours = _model(intermediate_dim=64, rms_eps=1e-6, moe_experts=4,
                  moe_top_k=2, moe_capacity_factor=16.0)
    return hf, ours


def test_hf_mixtral_logits_match():
    """Mixtral = Llama layout + routed SwiGLU experts: import through
    load_hf_mixtral and the logits match transformers' (VERDICT r3
    task 5 done-criterion)."""
    from pddl_tpu.ckpt.hf_import import load_hf_mixtral

    hf, ours = _mixtral_pair()
    tokens = _tokens()
    v = ours.init(jax.random.key(0), tokens, train=False)
    v = load_hf_mixtral(hf, v, model=ours)
    with torch.no_grad():
        ref = hf(torch.from_numpy(
            np.asarray(tokens, np.int64))).logits.numpy()
    got = np.asarray(ours.apply(v, tokens, train=False))
    np.testing.assert_allclose(got, ref, atol=3e-4, rtol=3e-4)


def test_hf_mixtral_rejects_wrong_expert_config():
    from pddl_tpu.ckpt.hf_import import load_hf_mixtral

    hf, _ = _mixtral_pair()
    tokens = _tokens()
    dense = _model(intermediate_dim=64, rms_eps=1e-6)  # no MoE
    v = dense.init(jax.random.key(0), tokens, train=False)
    with pytest.raises(ValueError, match="moe_experts"):
        load_hf_mixtral(hf, v, model=dense)

    wrong_k = _model(intermediate_dim=64, rms_eps=1e-6, moe_experts=4,
                     moe_top_k=1)
    v = wrong_k.init(jax.random.key(0), tokens, train=False)
    with pytest.raises(ValueError, match="num_experts_per_tok"):
        load_hf_mixtral(hf, v, model=wrong_k)

    wrong_n = _model(intermediate_dim=64, rms_eps=1e-6, moe_experts=8,
                     moe_top_k=2)
    v = wrong_n.init(jax.random.key(0), tokens, train=False)
    with pytest.raises(ValueError, match="experts"):
        load_hf_mixtral(hf, v, model=wrong_n)

    # Undersized TRAIN capacity is fine for serving since round 5: eval
    # runs dropless by construction (ops/moe.py eval_dropless), so the
    # import succeeds...
    droppy = _model(intermediate_dim=64, rms_eps=1e-6, moe_experts=4,
                    moe_top_k=2, moe_capacity_factor=1.0)
    v = droppy.init(jax.random.key(0), tokens, train=False)
    load_hf_mixtral(hf, v, model=droppy)
    # ...but a model that turned dropless eval OFF would silently drop
    # routed tokens transformers' dropless Mixtral keeps — still
    # rejected up front.
    droppy_off = _model(intermediate_dim=64, rms_eps=1e-6, moe_experts=4,
                        moe_top_k=2, moe_capacity_factor=1.0,
                        moe_eval_dropless=False)
    v = droppy_off.init(jax.random.key(0), tokens, train=False)
    with pytest.raises(ValueError, match="capacity"):
        load_hf_mixtral(hf, v, model=droppy_off)


def test_hf_mixtral_dropless_eval_parity_under_imbalance():
    """The round-5 dropless-eval guarantee, proven against transformers:
    force PATHOLOGICAL routing (router biased so every token's top-2 is
    experts 0 and 1 — 4x over a capacity_factor=1 budget) and the
    imported model's eval logits must STILL match HF's dropless Mixtral.
    Before eval_dropless this configuration silently zeroed most routed
    tokens' expert outputs."""
    import torch as _torch

    from pddl_tpu.ckpt.hf_import import load_hf_mixtral

    hf, _ = _mixtral_pair()
    # Bias every layer's router hard toward experts 0 and 1.
    with _torch.no_grad():
        for layer in hf.model.layers:
            gate = layer.block_sparse_moe.gate
            gate.weight.zero_()
            gate.weight[0, :] = 5.0
            gate.weight[1, :] = 4.0
    ours = _model(intermediate_dim=64, rms_eps=1e-6, moe_experts=4,
                  moe_top_k=2, moe_capacity_factor=1.0)
    tokens = _tokens()
    v = ours.init(jax.random.key(0), tokens, train=False)
    v = load_hf_mixtral(hf, v, model=ours)
    with _torch.no_grad():
        ref = hf(_torch.from_numpy(
            np.asarray(tokens, np.int64))).logits.numpy()
    got = np.asarray(ours.apply(v, tokens, train=False))
    np.testing.assert_allclose(got, ref, atol=3e-4, rtol=3e-4)
    # The TRAIN path at this capacity genuinely drops (the scenario is
    # real): its output must differ from the dropless eval one.
    got_train, _ = ours.apply(v, tokens, train=True,
                              mutable=["losses", "metrics"])
    assert not np.allclose(np.asarray(got_train), got, atol=1e-3)


def test_hf_mixtral_export_roundtrips_into_transformers():
    """export_hf_llama emits block_sparse_moe keys for MoE blocks;
    transformers loads them strictly and serves our logits."""
    from pddl_tpu.ckpt.hf_export import export_hf_llama

    hf, ours = _mixtral_pair()
    tokens = _tokens()
    v = ours.init(jax.random.key(7), tokens, train=False)
    sd = {k: torch.from_numpy(x) for k, x in export_hf_llama(
        v, model=ours).items()}
    missing, unexpected = hf.load_state_dict(sd, strict=True)
    assert not missing and not unexpected
    hf = hf.eval()
    with torch.no_grad():
        ref = hf(torch.from_numpy(
            np.asarray(tokens, np.int64))).logits.numpy()
    got = np.asarray(ours.apply(v, tokens, train=False))
    np.testing.assert_allclose(got, ref, atol=3e-4, rtol=3e-4)


def test_hf_export_import_is_identity_with_padded_vocab():
    """export -> import lands bit-exactly back on the original params,
    including slicing vocab_multiple padding off and refilling it."""
    from pddl_tpu.ckpt.hf_export import export_hf_llama
    from pddl_tpu.ckpt.hf_import import load_hf_llama

    ours = _model(intermediate_dim=64, rms_eps=1e-6, vocab_multiple=32)
    tokens = _tokens()
    v = ours.init(jax.random.key(7), tokens, train=False)
    sd = export_hf_llama(v, model=ours)

    class _Holder:
        def state_dict(self):
            return sd

    v2 = load_hf_llama(_Holder(), v, model=ours)
    before = ours.apply(v, tokens, train=False)
    after = ours.apply(v2, tokens, train=False)
    np.testing.assert_array_equal(np.asarray(before), np.asarray(after))


def test_hf_biasless_checkpoint_into_biased_model_raises():
    from pddl_tpu.ckpt.hf_import import load_hf_llama

    hf = _hf_llama()  # plain Llama: no qkv biases
    ours = _model(intermediate_dim=64, rms_eps=1e-6, qkv_bias=True)
    v = ours.init(jax.random.key(0), _tokens(), train=False)
    with pytest.raises(ValueError, match="qkv_bias=False"):
        load_hf_llama(hf, v, model=ours)


@pytest.mark.slow  # multi-hop pallas-interpret loop: tier-2 wall-clock
def test_ring_flash_gqa_matches_reference():
    """Sequence-parallel ring attention through the Llama family: GQA
    K/V expand before the ring, so the sharded result must equal the
    single-device reference (softmax reduction reordered -> tolerance)."""
    from pddl_tpu.core.mesh import MeshConfig, build_mesh

    # seq=8: eight ring rotations, real multi-hop cross-shard causality
    # (matching the GPT family's ring test), not a trivial 2-hop ring.
    mesh = build_mesh(MeshConfig(data=1, seq=8))
    tokens = _tokens(batch=2, seq=32)
    ref_model = _model(max_len=32)
    ring_model = _model(max_len=32, attention="ring_flash", mesh=mesh)
    v = ref_model.init(jax.random.key(0), tokens, train=False)
    ref = ref_model.apply(v, tokens, train=False)
    got = ring_model.apply(v, tokens, train=False)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=1e-4, rtol=1e-4)


def test_gpipe_llama_matches_sequential_and_trains():
    """PP x the modern-decoder family: the pipelined Llama is exactly the
    sequential model, and it trains under PipelineStrategy (DP x PP)."""
    from pddl_tpu.data.synthetic import SyntheticLanguageModeling
    from pddl_tpu.models.llama import GPipeLlama
    from pddl_tpu.parallel import PipelineStrategy
    from pddl_tpu.train.loop import Trainer

    strategy = PipelineStrategy(n_stages=4)  # data=2 x stage=4
    mesh = strategy.setup()
    model = GPipeLlama(vocab_size=16, n_stages=4, blocks_per_stage=1,
                       n_microbatches=2, mesh=mesh, embed_dim=32,
                       num_heads=4, num_kv_heads=2)
    x = _tokens(batch=4, seq=32, vocab=16)
    variables = model.init(jax.random.key(1), x)
    piped = np.asarray(jax.jit(lambda v, xx: model.apply(v, xx))(variables, x))
    seq = np.asarray(model.apply_sequential(variables, x))
    np.testing.assert_allclose(piped, seq, atol=1e-4, rtol=1e-4)

    # Causality (and RoPE position handling) survive the pipeline.
    x2 = x.at[:, -8:].set((x[:, -8:] + 5) % 16)
    out2 = np.asarray(model.apply(variables, x2, train=False))
    np.testing.assert_allclose(out2[:, :-8], piped[:, :-8],
                               atol=1e-4, rtol=1e-4)

    ds = SyntheticLanguageModeling(batch_size=8, seq_len=32, vocab_size=16,
                                   seed=0)
    tr = Trainer(model, optimizer="adamw", learning_rate=3e-3,
                 strategy=strategy, input_key="tokens",
                 target_key="targets", seed=0)
    hist = tr.fit(ds, epochs=2, steps_per_epoch=4, verbose=0)
    assert hist.history["loss"][-1] < hist.history["loss"][0]
    leaf = jax.tree.leaves(tr.state.params["stages"])[0]
    assert leaf.sharding.spec[0] == "stage"
