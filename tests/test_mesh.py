"""Mesh construction + collectives on the fake 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from pddl_tpu.core import collectives
from pddl_tpu.core.mesh import (
    MeshConfig,
    build_mesh,
    mesh_num_replicas,
    shard_map,
    validate_divisible,
)


def test_mesh_default_all_data(eight_devices):
    mesh = build_mesh()
    assert mesh.shape["data"] == 8
    assert mesh.shape["model"] == 1
    assert mesh_num_replicas(mesh) == 8


def test_mesh_wildcard_and_fixed(eight_devices):
    mesh = build_mesh(MeshConfig(data=-1, model=2))
    assert mesh.shape["data"] == 4 and mesh.shape["model"] == 2


def test_mesh_bad_shapes(eight_devices):
    with pytest.raises(ValueError):
        build_mesh(MeshConfig(data=3))  # 8 % 3 != 0
    with pytest.raises(ValueError):
        MeshConfig(data=-1, model=-1).axis_sizes(8)


def test_validate_divisible(mesh8):
    validate_divisible(32, mesh8)
    with pytest.raises(ValueError):
        validate_divisible(31, mesh8)


def test_psum_pmean_over_mesh(mesh8):
    def f(x):
        return collectives.psum(x, "data"), collectives.pmean(x, "data")

    g = shard_map(f, mesh=mesh8, in_specs=P("data"), out_specs=P())
    s, m = g(jnp.arange(8.0))
    assert s[0] == 28.0
    assert m[0] == 3.5


def test_broadcast_from_root(mesh8):
    def f(x):
        return collectives.broadcast(x, "data", root=3)

    g = shard_map(f, mesh=mesh8, in_specs=P("data"), out_specs=P("data"))
    out = g(jnp.arange(8.0))
    np.testing.assert_array_equal(np.asarray(out), np.full(8, 3.0))


def test_ppermute_ring(mesh8):
    def f(x):
        return collectives.ppermute_ring(x, "data", shift=1)

    g = shard_map(f, mesh=mesh8, in_specs=P("data"), out_specs=P("data"))
    out = np.asarray(g(jnp.arange(8.0)))
    # member i sends to i+1: position j holds value j-1 (mod 8)
    np.testing.assert_array_equal(out, np.roll(np.arange(8.0), 1))


def test_reduce_scatter(mesh8):
    def f(x):
        return collectives.reduce_scatter(x, "data")

    # Each member holds a length-8 vector of ones; psum_scatter sums across
    # members then scatters: each member ends with 8/8=1 element == 8.0.
    g = shard_map(f, mesh=mesh8, in_specs=P(None), out_specs=P("data"))
    out = np.asarray(g(jnp.ones(8)))
    np.testing.assert_array_equal(out, np.full(8, 8.0))
