"""Low-precision parameter-update rules (train/mixed_precision.py).

The plain bf16 recipe rounds most sub-ulp updates to zero (the measured
+2.4% val-loss cost, docs/CONVERGENCE.md); these tests pin the two
fixes' defining properties: stochastic rounding is *unbiased* and lets
sub-ulp updates accumulate, the f32 master is *exact*, and both compose
with the injected-hyperparam chain (LR callbacks), MultiSteps, and the
Trainer.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from pddl_tpu.train.mixed_precision import (
    _sr_to_bf16,
    f32_master_update,
    stabilize_moment_dtype,
    stochastic_round_update,
)
from pddl_tpu.train.state import (
    get_learning_rate,
    make_optimizer,
    set_learning_rate,
)


def _state_dtypes(state):
    return [l.dtype for l in jax.tree.leaves(state) if hasattr(l, "dtype")]


def test_sr_is_unbiased_and_lands_on_neighbors():
    """SR of x must yield only the two bracketing bf16 values, with mean
    converging to x (unbiasedness is the whole point)."""
    lo = jnp.float32(1.0)
    ulp = jnp.float32(np.spacing(np.float32(1.0)) * 2**16)  # bf16 ulp at 1.0
    frac = 0.3
    x = jnp.full((4096,), lo + frac * ulp, jnp.float32)
    out = _sr_to_bf16(x, jax.random.PRNGKey(0)).astype(jnp.float32)
    vals = np.unique(np.asarray(out))
    np.testing.assert_array_equal(vals, [1.0, 1.0 + float(ulp)])
    p_up = float((out > lo).mean())
    assert abs(p_up - frac) < 0.03, p_up  # 4096 samples: ~0.007 stderr


def test_sr_exact_values_round_trip():
    """Values already representable in bf16 must never move."""
    x = jnp.array([0.0, 1.0, -2.5, 0.00390625], jnp.float32)
    out = _sr_to_bf16(x, jax.random.PRNGKey(1))
    np.testing.assert_array_equal(np.asarray(out, np.float32),
                                  np.asarray(x, np.float32))


def test_sub_ulp_updates_accumulate_under_sr_not_plain():
    """1000 SGD steps of -1e-4 on a bf16 param at 1.0 (ulp 0.0078): plain
    rounding drops every step (param frozen); SR accumulates them to
    ~0.9 in expectation."""
    p = {"w": jnp.ones((256,), jnp.bfloat16)}
    g = {"w": jnp.full((256,), 1e-4, jnp.float32)}
    sgd = optax.sgd(1.0)

    def run(tx):
        state = tx.init(p)

        def step(carry, _):
            params, s = carry
            u, s = tx.update({"w": g["w"].astype(params["w"].dtype)}, s,
                             params)
            return (optax.apply_updates(params, u), s), None

        (pf, _), _ = jax.lax.scan(step, (p, state), None, length=1000)
        return float(pf["w"].astype(jnp.float32).mean())

    frozen = run(sgd)
    assert frozen == 1.0, frozen  # every update lost to round-to-nearest
    moved = run(stochastic_round_update(sgd, seed=0))
    assert abs(moved - 0.9) < 0.01, moved
    exact = run(f32_master_update(sgd))
    # master accumulates exactly; stored bf16 is the cast of 0.9
    assert abs(exact - 0.9) < 0.004, exact


def test_f32_master_matches_f32_reference_exactly():
    """With identical external grads, the master trajectory must be
    bit-identical to running the same optimizer on f32 params."""
    tx = optax.adam(1e-2)
    wrapped = f32_master_update(tx)
    p16 = {"w": jnp.linspace(-1, 1, 64).astype(jnp.bfloat16)}
    p32 = jax.tree.map(lambda x: x.astype(jnp.float32), p16)
    s16, s32 = wrapped.init(p16), tx.init(p32)
    key = jax.random.PRNGKey(7)
    for i in range(20):
        g = {"w": jax.random.normal(jax.random.fold_in(key, i), (64,))}
        u16, s16 = wrapped.update(g, s16, p16)
        p16 = optax.apply_updates(p16, u16)
        u32, s32 = tx.update(g, s32, p32)
        p32 = optax.apply_updates(p32, u32)
    np.testing.assert_array_equal(np.asarray(s16.master["w"]),
                                  np.asarray(p32["w"]))
    # and the stored bf16 params are exactly the cast of the master
    np.testing.assert_array_equal(
        np.asarray(p16["w"], np.float32),
        np.asarray(p32["w"].astype(jnp.bfloat16), np.float32))


def test_f32_leaves_pass_through_unchanged():
    """Mixed trees: f32 leaves get the inner update exactly; only bf16
    leaves are rounded."""
    tx = stochastic_round_update(optax.sgd(0.5), seed=3)
    p = {"a": jnp.ones((8,), jnp.float32), "b": jnp.ones((8,), jnp.bfloat16)}
    g = {"a": jnp.full((8,), 0.25, jnp.float32),
         "b": jnp.full((8,), 0.25, jnp.bfloat16)}
    s = tx.init(p)
    u, _ = tx.update(g, s, p)
    np.testing.assert_array_equal(np.asarray(u["a"]), -0.125)
    new_b = optax.apply_updates(p, u)["b"]
    assert new_b.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(new_b, np.float32), 0.875)


def test_stabilized_moments_are_f32_from_init():
    """make_optimizer must pin bf16-param moments to f32 at init so the
    state signature never changes across updates (the hidden step-2
    retrace found in round 5)."""
    tx = make_optimizer("adam", 1e-3)
    p = {"w": jnp.ones((4,), jnp.bfloat16)}
    s0 = tx.init(p)
    assert jnp.bfloat16 not in _state_dtypes(s0)
    u, s1 = tx.update({"w": jnp.ones((4,), jnp.bfloat16)}, s0, p)
    assert _state_dtypes(s1) == _state_dtypes(s0)


@pytest.mark.parametrize("mode", ["stochastic_round", "f32_master"])
def test_state_signature_stable_and_lr_callbacks_work(mode):
    """The wrappers' NamedTuple states must keep the whole chain's
    signature stable across updates AND stay transparent to the
    get/set_learning_rate recursion (ReduceLROnPlateau's path)."""
    from pddl_tpu.train.state import TrainState

    tx = make_optimizer("adam", 1e-3, grad_clip_norm=1.0,
                        accumulate_steps=2, param_update=mode)
    p = {"w": jnp.ones((4,), jnp.bfloat16)}
    s0 = tx.init(p)
    u, s1 = tx.update({"w": jnp.ones((4,), jnp.bfloat16)}, s0, p)
    assert _state_dtypes(s1) == _state_dtypes(s0)
    state = TrainState(step=jnp.zeros((), jnp.int32), params=p,
                       batch_stats={}, opt_state=s0)
    assert get_learning_rate(state) == pytest.approx(1e-3)
    state = set_learning_rate(state, 5e-4)
    assert get_learning_rate(state) == pytest.approx(5e-4)


@pytest.mark.parametrize("mode", ["plain", "stochastic_round", "f32_master"])
def test_trainer_trains_bf16_model_under_each_mode(mode):
    """End to end: a tiny bf16-param GPT fits under each update rule —
    loss finite and decreasing, params still bf16."""
    from pddl_tpu.data.synthetic import SyntheticLanguageModeling
    from pddl_tpu.models.gpt import tiny_gpt
    from pddl_tpu.train.loop import Trainer

    model = tiny_gpt(vocab_size=32, param_dtype=jnp.bfloat16)
    data = SyntheticLanguageModeling(batch_size=8, seq_len=32,
                                     vocab_size=32, seed=0)
    tr = Trainer(model, optimizer="adam", learning_rate=1e-2, seed=0,
                 input_key="tokens", target_key="targets",
                 param_update=mode)
    hist = tr.fit(data, epochs=2, steps_per_epoch=8, verbose=0)
    losses = hist.history["loss"]
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]
    leaf = jax.tree.leaves(tr.state.params)[0]
    assert leaf.dtype == jnp.bfloat16


def test_checkpoint_roundtrip_with_wrapper_state(tmp_path):
    """The wrapper state (master copy / PRNG key) must survive an orbax
    save/restore — it is optimizer state like any other."""
    from pddl_tpu.ckpt.checkpoint import Checkpointer
    from pddl_tpu.data.synthetic import SyntheticLanguageModeling
    from pddl_tpu.models.gpt import tiny_gpt
    from pddl_tpu.train.loop import Trainer

    def build():
        model = tiny_gpt(vocab_size=32, param_dtype=jnp.bfloat16)
        return Trainer(model, optimizer="adam", learning_rate=1e-2, seed=0,
                       input_key="tokens", target_key="targets",
                       param_update="f32_master")

    data = SyntheticLanguageModeling(batch_size=8, seq_len=32,
                                     vocab_size=32, seed=0)
    tr = build()
    tr.fit(data, epochs=1, steps_per_epoch=3, verbose=0)
    mgr = Checkpointer(str(tmp_path))
    mgr.save(tr.state)
    mgr.wait()

    tr2 = build()
    tr2.init_state(next(iter(data)))
    restored = Checkpointer(str(tmp_path), read_only=True).restore(tr2.state)
    for a, b in zip(jax.tree.leaves(tr.state.opt_state),
                    jax.tree.leaves(restored.opt_state)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_prebuilt_transformation_composes_with_param_update():
    """A prebuilt optax chain passed to make_optimizer must still get the
    requested update rule — silently training with the biased plain rule
    while config claims stochastic_round would be a lie."""
    tx = make_optimizer(optax.sgd(1.0), param_update="stochastic_round")
    p = {"w": jnp.ones((256,), jnp.bfloat16)}
    s = tx.init(p)
    # sub-ulp update: plain rounding would freeze the param at 1.0
    g = {"w": jnp.full((256,), 1e-4, jnp.bfloat16)}
    for _ in range(200):
        u, s = tx.update(g, s, p)
        p = optax.apply_updates(p, u)
    moved = float(p["w"].astype(jnp.float32).mean())
    assert moved < 0.995, moved  # updates accumulated => SR was applied


def test_f32_master_is_literal_noop_for_f32_params():
    """No bf16 leaves: no master copy may be stored (it would duplicate
    every parameter in optimizer state for zero behavioral change)."""
    from pddl_tpu.train.mixed_precision import F32MasterState

    tx = f32_master_update(optax.adam(1e-3))
    p = {"w": jnp.ones((8,), jnp.float32)}
    s = tx.init(p)
    assert s.master is None
    ref = optax.adam(1e-3)
    sr = ref.init(p)
    g = {"w": jnp.full((8,), 0.5, jnp.float32)}
    u, s = tx.update(g, s, p)
    ur, sr = ref.update(g, sr, p)
    np.testing.assert_array_equal(np.asarray(u["w"]), np.asarray(ur["w"]))
    assert s.master is None
