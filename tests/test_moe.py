"""Mixture-of-Experts: Switch FFN routing numerics + expert parallelism.

Beyond-parity capability (reference has no MoE; SURVEY.md §2c). Checks:
the dense one-hot dispatch math routes every under-capacity token to its
argmax expert, the load-balancing aux loss flows into training via the
"losses" collection, expert-major weights shard over the ``expert`` mesh
axis, and a MoE ViT trains under DP x EP on the fake 8-device mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from pddl_tpu.core.mesh import EXPERT_AXIS
from pddl_tpu.data.synthetic import SyntheticImageClassification
from pddl_tpu.models.vit import ViT
from pddl_tpu.ops.moe import SwitchFFN
from pddl_tpu.parallel import ExpertParallelStrategy
from pddl_tpu.train.loop import Trainer


def test_switch_ffn_routes_to_argmax_expert():
    """With capacity >= tokens, output == the argmax expert's FFN * gate."""
    moe = SwitchFFN(num_experts=4, mlp_ratio=2, capacity_factor=8.0)
    x = jax.random.normal(jax.random.key(0), (2, 8, 16))
    variables = moe.init(jax.random.key(1), x)
    out, state = moe.apply(variables, x, mutable=["losses"])
    assert out.shape == x.shape

    p = variables["params"]
    xt = np.asarray(x.reshape(16, 16))
    logits = xt.astype(np.float32) @ np.asarray(p["router"]["kernel"]) + np.asarray(p["router"]["bias"])
    probs = np.asarray(jax.nn.softmax(jnp.asarray(logits), axis=-1))
    idx = probs.argmax(-1)
    gate = probs.max(-1)

    def gelu(a):
        return np.asarray(jax.nn.gelu(jnp.asarray(a)))

    expected = np.stack([
        (gelu(xt[t] @ np.asarray(p["w1"][e]) + np.asarray(p["b1"][e]))
         @ np.asarray(p["w2"][e]) + np.asarray(p["b2"][e])) * gate[t]
        for t, e in enumerate(idx)
    ]).reshape(2, 8, 16)
    np.testing.assert_allclose(np.asarray(out), expected, atol=1e-5, rtol=1e-4)


def test_switch_ffn_sows_aux_loss():
    moe = SwitchFFN(num_experts=4, mlp_ratio=2)
    x = jax.random.normal(jax.random.key(0), (2, 8, 16))
    variables = moe.init(jax.random.key(1), x)
    # init() itself sows into "losses"; pass only params so the fresh
    # apply's collection holds exactly this call's value.
    _, state = moe.apply({"params": variables["params"]}, x,
                         mutable=["losses"])
    (aux,) = jax.tree.leaves(state["losses"])
    # Switch loss is n*sum(f*P) scaled by aux_loss_weight; perfectly
    # balanced routing gives exactly aux_loss_weight, worst case n times it.
    assert 0.0 < float(aux) <= moe.aux_loss_weight * moe.num_experts + 1e-6


def test_capacity_drops_overflow_tokens():
    """capacity_factor -> tiny: overflow tokens produce zero output rows."""
    moe = SwitchFFN(num_experts=2, mlp_ratio=1, capacity_factor=0.125)
    x = jax.random.normal(jax.random.key(0), (1, 16, 8))
    variables = moe.init(jax.random.key(1), x)
    out, _ = moe.apply(variables, x, mutable=["losses"])
    # capacity = 16 * 0.125 / 2 = 1 token per expert => at most 2 non-zero
    # output rows out of 16.
    nonzero = np.abs(np.asarray(out).reshape(16, 8)).sum(-1) > 1e-7
    assert nonzero.sum() <= 2


def test_expert_parallel_training_and_sharding():
    strategy = ExpertParallelStrategy(expert_parallel=4)  # data=2 x expert=4
    model = ViT(patch_size=8, embed_dim=32, depth=2, num_heads=4,
                num_classes=8, attention="reference", moe_experts=4,
                moe_every=2)
    tr = Trainer(model, optimizer="adamw", learning_rate=1e-3,
                 strategy=strategy, seed=0)
    ds = SyntheticImageClassification(
        batch_size=strategy.scale_batch_size(8), image_size=32,
        num_classes=8, seed=0, signal_strength=3.0)
    hist = tr.fit(ds, epochs=2, steps_per_epoch=4, verbose=0)
    assert hist.history["loss"][-1] < hist.history["loss"][0]

    # Expert weights sharded one-expert-group-per-position on `expert`;
    # router and dense-MLP blocks untouched.
    moe_params = tr.state.params["block1"]["moe"]
    assert moe_params["w1"].sharding.spec == P(EXPERT_AXIS)
    assert moe_params["w2"].sharding.spec == P(EXPERT_AXIS)
    assert moe_params["b1"].sharding.spec == P(EXPERT_AXIS)
    assert moe_params["router"]["kernel"].sharding.spec == P()
    assert tr.state.params["block0"]["mlp1"]["kernel"].sharding.spec == P()


def test_top2_routes_to_two_best_experts():
    """With capacity >= all assignments, top-2 output equals the sum of the
    two best experts' FFNs weighted by renormalized gates (GShard)."""
    moe = SwitchFFN(num_experts=4, mlp_ratio=2, top_k=2, capacity_factor=8.0)
    x = jax.random.normal(jax.random.key(0), (2, 8, 16))
    variables = moe.init(jax.random.key(1), x)
    out, _ = moe.apply(variables, x, mutable=["losses"])

    p = variables["params"]
    xt = np.asarray(x.reshape(16, 16))
    logits = xt.astype(np.float32) @ np.asarray(p["router"]["kernel"]) \
        + np.asarray(p["router"]["bias"])
    probs = np.asarray(jax.nn.softmax(jnp.asarray(logits), axis=-1))

    def gelu(a):
        return np.asarray(jax.nn.gelu(jnp.asarray(a)))

    def ffn(t, e):
        return gelu(xt[t] @ np.asarray(p["w1"][e]) + np.asarray(p["b1"][e])) \
            @ np.asarray(p["w2"][e]) + np.asarray(p["b2"][e])

    expected = np.zeros_like(xt)
    for t in range(16):
        order = probs[t].argsort()[::-1]
        e1, e2 = order[0], order[1]
        g1, g2 = probs[t, e1], probs[t, e2]
        denom = g1 + g2
        expected[t] = (g1 / denom) * ffn(t, e1) + (g2 / denom) * ffn(t, e2)
    np.testing.assert_allclose(np.asarray(out).reshape(16, 16), expected,
                               atol=1e-5, rtol=1e-4)


def test_top2_unnormalized_gates():
    """normalize_gates=False keeps the raw router probabilities as gates."""
    common = dict(num_experts=4, mlp_ratio=2, top_k=2, capacity_factor=8.0)
    x = jax.random.normal(jax.random.key(0), (1, 8, 16))
    moe_n = SwitchFFN(**common)
    variables = moe_n.init(jax.random.key(1), x)
    out_norm, _ = moe_n.apply(variables, x, mutable=["losses"])
    out_raw, _ = SwitchFFN(**common, normalize_gates=False).apply(
        variables, x, mutable=["losses"])
    # Raw top-2 gates sum below 1, so the un-normalized output is strictly
    # smaller in magnitude wherever the output is non-zero.
    a = np.abs(np.asarray(out_raw)).sum()
    b = np.abs(np.asarray(out_norm)).sum()
    assert a < b


def test_top2_capacity_ordering_matches_two_phase_oracle():
    """Under capacity pressure the implementation's documented semantics —
    ALL first choices claim slots (in token order), then second choices
    queue behind the group's kept first-choice count — must match an
    explicit two-phase oracle exactly, including which tokens drop."""
    moe = SwitchFFN(num_experts=2, mlp_ratio=1, top_k=2, capacity_factor=0.25)
    # capacity = int(0.25 * 2 * 8 / 2) = 2 slots per expert, 8 tokens:
    # guaranteed contention on both experts.
    x = jax.random.normal(jax.random.key(2), (1, 8, 8))
    variables = moe.init(jax.random.key(1), x)
    out, _ = moe.apply(variables, x, mutable=["losses"])

    p = variables["params"]
    xt = np.asarray(x[0])
    probs = np.asarray(jax.nn.softmax(
        x[0].astype(jnp.float32) @ jnp.asarray(p["router"]["kernel"])
        + jnp.asarray(p["router"]["bias"]), axis=-1))
    capacity = 2

    def ffn(t, e):
        h = np.asarray(jax.nn.gelu(jnp.asarray(
            xt[t] @ np.asarray(p["w1"][e]) + np.asarray(p["b1"][e]))))
        return h @ np.asarray(p["w2"][e]) + np.asarray(p["b2"][e])

    expected = np.zeros_like(xt)
    e1 = probs.argmax(-1)
    # Phase 1: first choices in token order.
    fill = {0: 0, 1: 0}
    kept1 = []
    for t in range(8):
        if fill[e1[t]] < capacity:
            fill[e1[t]] += 1
            kept1.append(t)
    # Phase 2: second choices queue behind the KEPT first-choice counts.
    for t in range(8):
        e2 = probs[t].argsort()[::-1][1]
        g1, g2 = probs[t, e1[t]], probs[t, e2]
        denom = g1 + g2
        if t in kept1:
            expected[t] += (g1 / denom) * ffn(t, e1[t])
        if fill[e2] < capacity:
            fill[e2] += 1
            expected[t] += (g2 / denom) * ffn(t, e2)
    np.testing.assert_allclose(np.asarray(out)[0], expected,
                               atol=1e-5, rtol=1e-4)
    # The scenario actually exercised drops (otherwise weaken nothing).
    assert len(kept1) < 8 or any(
        np.abs(expected[t]).sum() == 0 for t in range(8))


def test_top1_behavior_unchanged_by_generalization():
    """top_k=1 (the default) must reproduce the pre-top-k Switch output
    byte-for-byte: same capacity formula, same gates, same dispatch."""
    moe = SwitchFFN(num_experts=4, mlp_ratio=2, capacity_factor=1.25)
    x = jax.random.normal(jax.random.key(0), (2, 16, 16))
    variables = moe.init(jax.random.key(1), x)
    out, _ = moe.apply(variables, x, mutable=["losses"])
    # Re-derive with the documented top-1 semantics directly.
    p = variables["params"]
    probs = np.asarray(jax.nn.softmax(
        x.astype(jnp.float32) @ jnp.asarray(p["router"]["kernel"])
        + jnp.asarray(p["router"]["bias"]), axis=-1))
    capacity = max(1, int(1.25 * 16 / 4))
    expected = np.zeros((2, 16, 16), np.float32)
    for b in range(2):
        fill = {e: 0 for e in range(4)}
        for t in range(16):
            e = probs[b, t].argmax()
            if fill[e] < capacity:
                fill[e] += 1
                xt = np.asarray(x[b, t])
                h = np.asarray(jax.nn.gelu(jnp.asarray(
                    xt @ np.asarray(p["w1"][e]) + np.asarray(p["b1"][e]))))
                expected[b, t] = (h @ np.asarray(p["w2"][e])
                                  + np.asarray(p["b2"][e])) * probs[b, t, e]
    np.testing.assert_allclose(np.asarray(out), expected, atol=1e-5,
                               rtol=1e-4)


def test_top2_expert_parallel_training():
    strategy = ExpertParallelStrategy(expert_parallel=4)
    model = ViT(patch_size=8, embed_dim=32, depth=2, num_heads=4,
                num_classes=8, attention="reference", moe_experts=4,
                moe_top_k=2, moe_every=2)
    tr = Trainer(model, optimizer="adamw", learning_rate=1e-3,
                 strategy=strategy, seed=0)
    ds = SyntheticImageClassification(
        batch_size=strategy.scale_batch_size(8), image_size=32,
        num_classes=8, seed=0, signal_strength=3.0)
    hist = tr.fit(ds, epochs=2, steps_per_epoch=4, verbose=0)
    assert hist.history["loss"][-1] < hist.history["loss"][0]
    moe_params = tr.state.params["block1"]["moe"]
    assert moe_params["w1"].sharding.spec == P(EXPERT_AXIS)


def test_drop_rate_observable_matches_capacity_math():
    """The sown moe_drop_rate must equal the exact dropped-slot fraction:
    force every token to expert 0 (router bias) and check against the
    closed form 1 - capacity/(S*top_k) per batch row."""
    moe = SwitchFFN(num_experts=4, mlp_ratio=2, capacity_factor=1.0,
                    eval_dropless=False)
    x = jax.random.normal(jax.random.key(0), (2, 16, 8))
    variables = moe.init(jax.random.key(1), x)
    p = jax.tree.map(jnp.copy, variables["params"])
    p["router"]["kernel"] = jnp.zeros_like(p["router"]["kernel"])
    p["router"]["bias"] = jnp.array([10.0, 0.0, 0.0, 0.0])
    _, state = moe.apply({"params": p}, x, mutable=["losses", "metrics"])
    (rate,) = jax.tree.leaves(state["metrics"])
    # capacity = int(1.0 * 1 * 16 / 4) = 4 kept of 16 slots per row
    np.testing.assert_allclose(float(rate), 1.0 - 4 / 16, atol=1e-6)

    # Balanced router at high capacity: (near-)zero drops.
    moe2 = SwitchFFN(num_experts=4, mlp_ratio=2, capacity_factor=8.0)
    _, state2 = moe2.apply({"params": variables["params"]}, x,
                           mutable=["losses", "metrics"])
    (rate2,) = jax.tree.leaves(state2["metrics"])
    assert float(rate2) == 0.0


def test_eval_dropless_capacity_ignores_capacity_factor():
    """train=False + eval_dropless: even a capacity_factor that drops
    hard in training keeps EVERY routed token at eval — worst case all
    tokens on one expert — and the sown drop rate is exactly 0."""
    moe = SwitchFFN(num_experts=4, mlp_ratio=2, capacity_factor=0.25,
                    top_k=2)
    x = jax.random.normal(jax.random.key(0), (2, 16, 8))
    variables = moe.init(jax.random.key(1), x)
    p = jax.tree.map(jnp.copy, variables["params"])
    # Worst case: every token's top-2 is experts 0 and 1.
    p["router"]["kernel"] = jnp.zeros_like(p["router"]["kernel"])
    p["router"]["bias"] = jnp.array([10.0, 8.0, 0.0, 0.0])

    out_tr, st_tr = moe.apply({"params": p}, x, True,
                              mutable=["losses", "metrics"])
    out_ev, st_ev = moe.apply({"params": p}, x, False,
                              mutable=["losses", "metrics"])
    (rate_tr,) = jax.tree.leaves(st_tr["metrics"])
    (rate_ev,) = jax.tree.leaves(st_ev["metrics"])
    assert float(rate_tr) > 0.8  # training capacity drops almost all
    assert float(rate_ev) == 0.0  # eval is dropless by construction
    # and the dropped-token rows actually differ (drops zero their slots)
    assert not np.allclose(np.asarray(out_tr), np.asarray(out_ev))


def test_trainer_logs_moe_drop_rate():
    """End to end: the drop-rate observable surfaces in History under
    its sown name, averaged across routed blocks."""
    model = ViT(patch_size=8, embed_dim=32, depth=2, num_heads=4,
                num_classes=8, moe_experts=4, moe_top_k=1, moe_every=1,
                attention="reference")
    ds = SyntheticImageClassification(batch_size=8, image_size=32,
                                      num_classes=8, seed=0)
    tr = Trainer(model, optimizer="adamw", learning_rate=1e-3, seed=0)
    hist = tr.fit(ds, epochs=1, steps_per_epoch=2, verbose=0,
                  validation_data=ds, validation_steps=1)
    assert "moe_drop_rate" in hist.history
    assert "val_moe_drop_rate" in hist.history
    rate = hist.history["moe_drop_rate"][-1]
    assert 0.0 <= rate <= 1.0
    # eval path is dropless
    assert hist.history["val_moe_drop_rate"][-1] == 0.0
