"""Mixture-of-Experts: Switch FFN routing numerics + expert parallelism.

Beyond-parity capability (reference has no MoE; SURVEY.md §2c). Checks:
the dense one-hot dispatch math routes every under-capacity token to its
argmax expert, the load-balancing aux loss flows into training via the
"losses" collection, expert-major weights shard over the ``expert`` mesh
axis, and a MoE ViT trains under DP x EP on the fake 8-device mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from pddl_tpu.core.mesh import EXPERT_AXIS
from pddl_tpu.data.synthetic import SyntheticImageClassification
from pddl_tpu.models.vit import ViT
from pddl_tpu.ops.moe import SwitchFFN
from pddl_tpu.parallel import ExpertParallelStrategy
from pddl_tpu.train.loop import Trainer


def test_switch_ffn_routes_to_argmax_expert():
    """With capacity >= tokens, output == the argmax expert's FFN * gate."""
    moe = SwitchFFN(num_experts=4, mlp_ratio=2, capacity_factor=8.0)
    x = jax.random.normal(jax.random.key(0), (2, 8, 16))
    variables = moe.init(jax.random.key(1), x)
    out, state = moe.apply(variables, x, mutable=["losses"])
    assert out.shape == x.shape

    p = variables["params"]
    xt = np.asarray(x.reshape(16, 16))
    logits = xt.astype(np.float32) @ np.asarray(p["router"]["kernel"]) + np.asarray(p["router"]["bias"])
    probs = np.asarray(jax.nn.softmax(jnp.asarray(logits), axis=-1))
    idx = probs.argmax(-1)
    gate = probs.max(-1)

    def gelu(a):
        return np.asarray(jax.nn.gelu(jnp.asarray(a)))

    expected = np.stack([
        (gelu(xt[t] @ np.asarray(p["w1"][e]) + np.asarray(p["b1"][e]))
         @ np.asarray(p["w2"][e]) + np.asarray(p["b2"][e])) * gate[t]
        for t, e in enumerate(idx)
    ]).reshape(2, 8, 16)
    np.testing.assert_allclose(np.asarray(out), expected, atol=1e-5, rtol=1e-4)


def test_switch_ffn_sows_aux_loss():
    moe = SwitchFFN(num_experts=4, mlp_ratio=2)
    x = jax.random.normal(jax.random.key(0), (2, 8, 16))
    variables = moe.init(jax.random.key(1), x)
    # init() itself sows into "losses"; pass only params so the fresh
    # apply's collection holds exactly this call's value.
    _, state = moe.apply({"params": variables["params"]}, x,
                         mutable=["losses"])
    (aux,) = jax.tree.leaves(state["losses"])
    # Switch loss is n*sum(f*P) scaled by aux_loss_weight; perfectly
    # balanced routing gives exactly aux_loss_weight, worst case n times it.
    assert 0.0 < float(aux) <= moe.aux_loss_weight * moe.num_experts + 1e-6


def test_capacity_drops_overflow_tokens():
    """capacity_factor -> tiny: overflow tokens produce zero output rows."""
    moe = SwitchFFN(num_experts=2, mlp_ratio=1, capacity_factor=0.125)
    x = jax.random.normal(jax.random.key(0), (1, 16, 8))
    variables = moe.init(jax.random.key(1), x)
    out, _ = moe.apply(variables, x, mutable=["losses"])
    # capacity = 16 * 0.125 / 2 = 1 token per expert => at most 2 non-zero
    # output rows out of 16.
    nonzero = np.abs(np.asarray(out).reshape(16, 8)).sum(-1) > 1e-7
    assert nonzero.sum() <= 2


def test_expert_parallel_training_and_sharding():
    strategy = ExpertParallelStrategy(expert_parallel=4)  # data=2 x expert=4
    model = ViT(patch_size=8, embed_dim=32, depth=2, num_heads=4,
                num_classes=8, attention="reference", moe_experts=4,
                moe_every=2)
    tr = Trainer(model, optimizer="adamw", learning_rate=1e-3,
                 strategy=strategy, seed=0)
    ds = SyntheticImageClassification(
        batch_size=strategy.scale_batch_size(8), image_size=32,
        num_classes=8, seed=0, signal_strength=3.0)
    hist = tr.fit(ds, epochs=2, steps_per_epoch=4, verbose=0)
    assert hist.history["loss"][-1] < hist.history["loss"][0]

    # Expert weights sharded one-expert-group-per-position on `expert`;
    # router and dense-MLP blocks untouched.
    moe_params = tr.state.params["block1"]["moe"]
    assert moe_params["w1"].sharding.spec == P(EXPERT_AXIS)
    assert moe_params["w2"].sharding.spec == P(EXPERT_AXIS)
    assert moe_params["b1"].sharding.spec == P(EXPERT_AXIS)
    assert moe_params["router"]["kernel"].sharding.spec == P()
    assert tr.state.params["block0"]["mlp1"]["kernel"].sharding.spec == P()
