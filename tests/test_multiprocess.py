"""Multi-process bootstrap + collectives over a local TCP coordinator.

The reference "tests" multi-node by spinning up an in-process gRPC cluster
(``/root/reference/imagenet-resnet50-ps.py:31-65``). The JAX equivalent is
two real OS processes joined through ``jax.distributed.initialize`` (the
coordinator is plain TCP on localhost), each owning 2 fake CPU devices —
exercising the actual multi-host code path: PDDL_* env discovery, global
mesh construction, ``make_array_from_process_local_data`` feeding, and a
cross-process collective (gloo stands in for ICI/DCN on CPU).
"""

import contextlib
import os
import socket
import subprocess
import sys
import time

import numpy as np
import pytest

from pddl_tpu.core.mesh import has_vma_checking

# The container's older jaxlib cannot compile cross-process collectives
# on the CPU backend at all (children die with "INVALID_ARGUMENT:
# Multiprocess computations aren't implemented on the CPU backend"), so
# the whole real-2-process topology is unreachable there. The in-process
# 8-device mesh covers the sharding/collective paths in tier-1; these
# tests add the genuine multi-host bootstrap on a modern jax.
pytestmark = pytest.mark.skipif(
    not has_vma_checking(),
    reason="container jaxlib lacks cross-process CPU collectives "
           "(gloo multiprocess backend); covered on modern jax only")

_CHILD = os.path.join(os.path.dirname(__file__), "_multiworker_child.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _clean_env() -> dict:
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {
        k: v for k, v in os.environ.items()
        # Children resolve their own platform/devices; don't leak ours.
        if k not in ("XLA_FLAGS", "JAX_PLATFORMS")
    }
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    return env


@contextlib.contextmanager
def _cluster(cmd, n_procs, port, env_base, **extra_env):
    """Launch the workers; on ANY exit path kill every survivor — a hung
    rendezvous must not leak orphans holding the coordinator port."""
    procs = []
    try:
        for pid in range(n_procs):
            env = dict(
                env_base,
                PDDL_COORDINATOR=f"127.0.0.1:{port}",
                PDDL_NUM_PROCESSES=str(n_procs),
                PDDL_PROCESS_ID=str(pid),
                **{k: str(v) for k, v in extra_env.items()},
            )
            procs.append(subprocess.Popen(
                cmd, env=env,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            ))
        yield procs
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()


def _reap(procs, timeout=570):
    """Collect outputs under ONE shared deadline; hung processes are
    SIGKILLed (a worker blocked in a collective against a dead peer
    ignores SIGTERM — it is inside C++), never raises. The first timeout
    kills the whole cluster: the caller's returncode assertions decide
    what that means."""
    deadline = time.monotonic() + timeout
    outputs = []
    for p in procs:
        try:
            out, _ = p.communicate(
                timeout=max(0.0, deadline - time.monotonic()))
        except subprocess.TimeoutExpired:
            for q in procs:
                if q.poll() is None:
                    q.kill()
            out, _ = p.communicate()
        outputs.append(out)
    return outputs


def _run_bootstrap_cluster(n_procs, **extra_env):
    with _cluster([sys.executable, _CHILD], n_procs, _free_port(),
                  _clean_env(), **extra_env) as procs:
        outputs = _reap(procs)
    for pid, (p, out) in enumerate(zip(procs, outputs)):
        assert p.returncode == 0, f"child {pid} failed:\n{out}"
        assert f"child {pid} OK" in out, out


def test_two_process_bootstrap_and_training(tmp_path):
    # PDDL_HEARTBEAT_DIR additionally exercises worker-failure
    # detection over the real 2-process topology: every worker beats
    # the shared directory, a never-beating phantom worker is detected
    # as lost, and the coordinated-restart marker propagates from the
    # last rank to every process (_multiworker_child.py).
    _run_bootstrap_cluster(2, PDDL_HEARTBEAT_DIR=str(tmp_path / "hb"))


def test_four_process_bootstrap_and_training():
    """4 real OS processes x 1 fake device each = a 4-device world: the
    discovery/mesh/collective/training path at the reference's multi-node
    scale (`imagenet-resnet50-multiworkers.py` under srun with 4 tasks),
    with the per-host device count at a non-default value."""
    _run_bootstrap_cluster(4, PDDL_TEST_LOCAL_DEVICES=1)


def _run_cluster_vs_oracle(child_name, tag, *, cluster_local_devices,
                           oracle_devices):
    """Shared LM multi-process harness: run ``child_name`` as TWO real OS
    processes x ``cluster_local_devices`` fake devices, assert both
    workers print the same ``{tag} OK loss=...``, then run the SAME child
    as one process x ``oracle_devices`` fake devices and assert the
    multi-process loss matches that single-process fake-mesh oracle."""
    import re

    child = os.path.join(os.path.dirname(__file__), child_name)

    def parse(out):
        m = re.search(tag + r" OK loss=([0-9.]+)", out)
        assert m, out
        return float(m.group(1))

    with _cluster([sys.executable, child], 2, _free_port(), _clean_env(),
                  PDDL_TEST_LOCAL_DEVICES=cluster_local_devices) as procs:
        outputs = _reap(procs)
    losses = []
    for pid, (p, out) in enumerate(zip(procs, outputs)):
        assert p.returncode == 0, \
            f"{tag} worker {pid} failed:\n{out[-3000:]}"
        losses.append(parse(out))
    assert losses[0] == losses[1], losses  # replicated loss, same value

    env = dict(_clean_env(), PDDL_TEST_LOCAL_DEVICES=str(oracle_devices))
    single = subprocess.run([sys.executable, child], env=env,
                            capture_output=True, text=True, timeout=570)
    assert single.returncode == 0, single.stdout + single.stderr
    oracle = parse(single.stdout)
    # Same math, different device/process layout: f32 reduction-order
    # noise only.
    np.testing.assert_allclose(losses[0], oracle, rtol=2e-6)


def test_lm_tensor_parallel_across_processes():
    """The flagship LM family through REAL process boundaries (VERDICT r3
    task 7): a tiny GQA Llama trains two steps under DP x TP
    (LLAMA_TP_RULES, data=2 x model=2) as TWO OS processes x 2 fake
    devices — Megatron all-reduces and the grad all-reduce riding gloo —
    and the loss must match the SAME config run as one process x 4 fake
    devices (the single-process fake-mesh oracle)."""
    _run_cluster_vs_oracle("_lm_tp_child.py", "LMTP",
                           cluster_local_devices=2, oracle_devices=4)


def test_lm_pipeline_parallel_across_processes():
    """GPipe through REAL process boundaries (VERDICT r4 task 6): a tiny
    GQA GPipeLlama trains two steps over a ``data=1 x stage=2`` mesh as
    TWO OS processes x 1 fake device — one pipeline stage per process, so
    every ``ppermute`` activation hop of the schedule (forward and the
    AD-derived backward pipeline) rides gloo across the boundary — and
    the loss must match the SAME config run as one process x 2 fake
    devices (the single-process fake-mesh oracle)."""
    _run_cluster_vs_oracle("_lm_pp_child.py", "LMPP",
                           cluster_local_devices=1, oracle_devices=2)


def _cli_env() -> dict:
    env = _clean_env()
    # Each "host" owns 2 fake CPU devices; gloo stands in for ICI/DCN.
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    return env


_CLI_CMD = [sys.executable, "-m", "pddl_tpu", "--preset", "multiworker",
            "--synthetic", "--model", "tiny_resnet", "--num-classes", "8",
            "--image-size", "32", "--batch", "2", "--verbose", "0"]


def test_two_process_cli_multiworker_preset():
    """The multiworker preset end to end as TWO real CLI processes: the
    reference's `srun python imagenet-resnet50-multiworkers.py` moment
    (one command per host, SLURM-style env discovery), but through
    `python -m pddl_tpu` with PDDL_* bootstrap vars."""
    cmd = _CLI_CMD + ["--epochs", "1", "--steps-per-epoch", "3"]
    with _cluster(cmd, 2, _free_port(), _cli_env()) as procs:
        outputs = _reap(procs)
    for pid, (p, out) in enumerate(zip(procs, outputs)):
        assert p.returncode == 0, f"CLI worker {pid} failed:\n{out[-3000:]}"


def test_kill_one_worker_then_cluster_resumes(tmp_path):
    """Fault injection across real processes (VERDICT r1 #8): SIGKILL one
    worker mid-run, tear the job down, relaunch with --resume, and the
    cluster continues from the last consistent checkpoint to completion —
    the TPU-preemption story (job-level restart) with genuine OS processes.
    """
    from pddl_tpu.ckpt import latest_epoch

    ckpt_dir = str(tmp_path / "ckpt")

    def cmd(epochs):
        return _CLI_CMD + ["--epochs", str(epochs), "--steps-per-epoch", "2",
                           "--checkpoint-dir", ckpt_dir, "--resume"]

    def finalized_steps():
        """Completed checkpoints by FILESYSTEM scan only. The poller must
        not construct a Checkpointer against the live directory: a
        single-process orbax CheckpointManager believes it is the primary
        host and garbage-collects the workers' in-flight tmp dirs.
        Orbax finalizes a step by atomically renaming
        '<step>.orbax-checkpoint-tmp' to '<step>', so a digits-only dir
        name means the checkpoint is complete."""
        if not os.path.isdir(ckpt_dir):
            return []
        return sorted(int(d) for d in os.listdir(ckpt_dir) if d.isdigit())

    # Phase 1: an effectively unbounded run (cannot finish inside the
    # test); wait for the first completed epoch checkpoint, then SIGKILL
    # worker 1 (no cleanup chance) mid-training.
    with _cluster(cmd(100000), 2, _free_port(), _cli_env()) as procs:
        deadline = time.monotonic() + 240
        while not finalized_steps():
            assert time.monotonic() < deadline, "no checkpoint appeared"
            for pid, p in enumerate(procs):
                assert p.poll() is None, (
                    f"worker {pid} died before first checkpoint:\n"
                    f"{p.communicate()[0][-3000:]}"
                )
            time.sleep(0.1)
        procs[1].kill()
        # The survivor is blocked in a collective against a dead peer; a
        # real launcher tears the job down — give it a grace period, then
        # escalate (the _cluster exit kills whatever remains).
        try:
            procs[0].communicate(timeout=30)
        except subprocess.TimeoutExpired:
            procs[0].terminate()
        _reap(procs, timeout=30)
    resumed_from = latest_epoch(ckpt_dir)
    assert resumed_from is not None

    # Phase 2: full relaunch (fresh coordinator port); --resume restores
    # the epoch-`resumed_from` state and trains two more epochs to the new
    # target. Both workers must finish cleanly and the checkpoint advance
    # past the crash point — training continued, not restarted.
    target_epochs = resumed_from + 3
    with _cluster(cmd(target_epochs), 2, _free_port(), _cli_env()) as procs:
        outputs = _reap(procs)
    for pid, (p, out) in enumerate(zip(procs, outputs)):
        assert p.returncode == 0, f"resumed worker {pid} failed:\n{out[-3000:]}"
    assert latest_epoch(ckpt_dir) == target_epochs - 1
