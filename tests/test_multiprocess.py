"""Multi-process bootstrap + collectives over a local TCP coordinator.

The reference "tests" multi-node by spinning up an in-process gRPC cluster
(``/root/reference/imagenet-resnet50-ps.py:31-65``). The JAX equivalent is
two real OS processes joined through ``jax.distributed.initialize`` (the
coordinator is plain TCP on localhost), each owning 2 fake CPU devices —
exercising the actual multi-host code path: PDDL_* env discovery, global
mesh construction, ``make_array_from_process_local_data`` feeding, and a
cross-process collective (gloo stands in for ICI/DCN on CPU).
"""

import os
import socket
import subprocess
import sys

_CHILD = os.path.join(os.path.dirname(__file__), "_multiworker_child.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_bootstrap_and_training():
    port = _free_port()
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env_base = {
        k: v for k, v in os.environ.items()
        # Children resolve their own platform/devices; don't leak ours.
        if k not in ("XLA_FLAGS", "JAX_PLATFORMS")
    }
    env_base["PYTHONPATH"] = repo_root + os.pathsep + env_base.get("PYTHONPATH", "")
    procs = []
    try:
        for pid in range(2):
            env = dict(
                env_base,
                PDDL_COORDINATOR=f"127.0.0.1:{port}",
                PDDL_NUM_PROCESSES="2",
                PDDL_PROCESS_ID=str(pid),
            )
            procs.append(subprocess.Popen(
                [sys.executable, _CHILD], env=env,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            ))
        outputs = []
        for p in procs:
            out, _ = p.communicate(timeout=570)
            outputs.append(out)
    finally:
        # A hung rendezvous (one child dead, the other blocked in
        # initialize) must not leak orphans holding the coordinator port.
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
    for pid, (p, out) in enumerate(zip(procs, outputs)):
        assert p.returncode == 0, f"child {pid} failed:\n{out}"
        assert f"child {pid} OK" in out, out


def test_two_process_cli_multiworker_preset():
    """The multiworker preset end to end as TWO real CLI processes: the
    reference's `srun python imagenet-resnet50-multiworkers.py` moment
    (one command per host, SLURM-style env discovery), but through
    `python -m pddl_tpu` with PDDL_* bootstrap vars."""
    port = _free_port()
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env_base = {
        k: v for k, v in os.environ.items()
        if k not in ("XLA_FLAGS", "JAX_PLATFORMS")
    }
    env_base["PYTHONPATH"] = repo_root + os.pathsep + env_base.get(
        "PYTHONPATH", "")
    # Each "host" owns 2 fake CPU devices; gloo stands in for ICI/DCN.
    env_base["JAX_PLATFORMS"] = "cpu"
    env_base["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    cmd = [sys.executable, "-m", "pddl_tpu", "--preset", "multiworker",
           "--synthetic", "--model", "tiny_resnet", "--num-classes", "8",
           "--image-size", "32", "--batch", "2", "--epochs", "1",
           "--steps-per-epoch", "3", "--verbose", "0"]
    procs = []
    try:
        for pid in range(2):
            env = dict(
                env_base,
                PDDL_COORDINATOR=f"127.0.0.1:{port}",
                PDDL_NUM_PROCESSES="2",
                PDDL_PROCESS_ID=str(pid),
            )
            procs.append(subprocess.Popen(
                cmd, env=env, cwd=repo_root,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            ))
        outputs = [p.communicate(timeout=570)[0] for p in procs]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
    for pid, (p, out) in enumerate(zip(procs, outputs)):
        assert p.returncode == 0, f"CLI worker {pid} failed:\n{out[-3000:]}"
