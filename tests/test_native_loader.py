"""Native C++ loader tests: correctness, sharding, determinism, Trainer
integration (the in-repo replacement for tf.data's C++ runtime, SURVEY.md
§2b C15)."""

import numpy as np
import pytest

from conftest import native_build_error
from pddl_tpu.data.native_loader import (
    NativeLoader,
    write_packed,
)

_BUILD_ERROR = native_build_error()
pytestmark = pytest.mark.skipif(
    bool(_BUILD_ERROR), reason=f"native library unbuildable: {_BUILD_ERROR}"
)


def _make_packed(tmp_path, n=32, h=8, w=8, c=3, files=2, seed=0):
    rng = np.random.default_rng(seed)
    paths = []
    per = n // files
    for fi in range(files):
        images = rng.integers(0, 255, (per, h, w, c), np.uint8)
        # label encodes (file, index) so we can detect duplicates/omissions
        labels = np.arange(fi * per, (fi + 1) * per, dtype=np.int32)
        # make pixel [0,0,0] equal the label for content checks
        images[:, 0, 0, 0] = (labels % 256).astype(np.uint8)
        p = str(tmp_path / f"shard{fi}.pdl")
        write_packed(p, images, labels)
        paths.append(p)
    return paths


def test_roundtrip_content(tmp_path):
    paths = _make_packed(tmp_path, n=16, files=1)
    loader = NativeLoader(paths, batch_size=4, shuffle=False, num_workers=1)
    assert loader.num_samples == 16
    assert loader.batches_per_epoch == 4
    seen = []
    for b in loader:
        assert b["image"].shape == (4, 8, 8, 3)
        assert b["image"].dtype == np.uint8  # device-side cast is the default
        np.testing.assert_array_equal(b["image"][:, 0, 0, 0],
                                      b["label"] % 256)
        seen.extend(b["label"].tolist())
    assert seen == list(range(16))  # unshuffled order preserved
    loader.close()


def test_shuffle_deterministic_and_complete(tmp_path):
    paths = _make_packed(tmp_path, n=32, files=2)

    def epoch_labels(seed):
        loader = NativeLoader(paths, batch_size=8, shuffle=True, seed=seed,
                              num_workers=1)
        out = [l for b in loader for l in b["label"].tolist()]
        loader.close()
        return out

    a, b, c = epoch_labels(7), epoch_labels(7), epoch_labels(8)
    assert a == b                      # same seed → same order
    assert a != c                      # different seed → different order
    assert sorted(a) == list(range(32))  # permutation, no dup/loss


def test_reshuffles_between_epochs(tmp_path):
    paths = _make_packed(tmp_path, n=32, files=1)
    loader = NativeLoader(paths, batch_size=8, shuffle=True, seed=1,
                          num_workers=1)
    e1 = [l for b in loader for l in b["label"].tolist()]
    e2 = [l for b in loader for l in b["label"].tolist()]
    assert sorted(e1) == sorted(e2) == list(range(32))
    assert e1 != e2
    loader.close()


def test_sharding_disjoint_complete(tmp_path):
    paths = _make_packed(tmp_path, n=32, files=2)
    got = []
    for idx in range(4):
        loader = NativeLoader(paths, batch_size=4, shuffle=False,
                              shard_index=idx, shard_count=4, num_workers=1)
        assert loader.num_samples == 8
        got.append([l for b in loader for l in b["label"].tolist()])
        loader.close()
    flat = [l for shard in got for l in shard]
    assert sorted(flat) == list(range(32))
    for i in range(4):
        for j in range(i + 1, 4):
            assert not set(got[i]) & set(got[j])


def test_drop_remainder_and_partial(tmp_path):
    paths = _make_packed(tmp_path, n=16, files=1)
    full = NativeLoader(paths, batch_size=5, shuffle=False,
                        drop_remainder=True, num_workers=1)
    assert full.batches_per_epoch == 3
    assert sum(len(b["label"]) for b in full) == 15
    full.close()
    part = NativeLoader(paths, batch_size=5, shuffle=False,
                        drop_remainder=False, num_workers=1)
    counts = [len(b["label"]) for b in part]
    assert counts == [5, 5, 5, 1]
    part.close()


def test_many_workers_no_loss(tmp_path):
    paths = _make_packed(tmp_path, n=64, files=2)
    loader = NativeLoader(paths, batch_size=8, shuffle=True, seed=3,
                          num_workers=4, prefetch_depth=8)
    labels = [l for b in loader for l in b["label"].tolist()]
    assert sorted(labels) == list(range(64))
    loader.close()


def test_missing_file_raises(tmp_path):
    with pytest.raises(FileNotFoundError):
        NativeLoader([str(tmp_path / "nope.pdl")], batch_size=4)


def test_trainer_integration(tmp_path):
    from pddl_tpu.models.resnet import tiny_resnet
    from pddl_tpu.parallel.single import SingleDeviceStrategy
    from pddl_tpu.train.loop import Trainer

    rng = np.random.default_rng(0)
    n, classes = 64, 4
    labels = rng.integers(0, classes, n).astype(np.int32)
    # Class-dependent mean so the model can fit.
    images = (rng.normal(64, 8, (n, 16, 16, 3)) + labels[:, None, None, None]
              * 40).clip(0, 255).astype(np.uint8)
    path = str(tmp_path / "train.pdl")
    write_packed(path, images, labels)

    loader = NativeLoader([path], batch_size=16, shuffle=True, seed=0,
                          num_workers=2)
    tr = Trainer(tiny_resnet(num_classes=classes), learning_rate=3e-3,
                 strategy=SingleDeviceStrategy())
    hist = tr.fit(loader, epochs=3, verbose=0)
    assert hist.history["loss"][-1] < hist.history["loss"][0]
    loader.close()
