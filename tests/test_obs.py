"""Observability layer (`pddl_tpu/obs/`), CPU.

The contracts under test:

- **Zero-cost disabled**: with the default no-op tracer, a full engine
  run allocates NOTHING attributable to `obs/trace.py` (tracemalloc
  pin) — tracing off must be indistinguishable from the pre-obs
  engine.
- **Span timelines**: a traced request's span reconstructs the whole
  lifecycle — queued → admitted (queue wait) → prefix match → prefill
  chunks → first token → per-tick decode events → finish — with
  monotone timestamps, and the JSONL sink round-trips it.
- **Ring buffer**: capacity is respected under arbitrary load (oldest
  overwritten, newest kept), records carry per-site dispatch wall
  time, and the summary aggregates the window.
- **Exporters**: the Prometheus text exposition round-trips through a
  STRICT parser; every `ServeMetrics.snapshot()` key appears in both
  the snapshot and the exposition (the drift guard — a new counter
  cannot silently skip export); the stdlib `/metrics` endpoint serves
  the same body over HTTP.
- **Reservoirs**: `ServeMetrics` memory is bounded under sustained
  load while snapshot percentiles stay stable (capped uniform
  sampling), and zero-recompile holds with tracing enabled.
"""

import json
import tracemalloc
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pddl_tpu.models.gpt import generate, tiny_gpt
from pddl_tpu.obs import (
    SERVE_COUNTER_KEYS,
    JsonlEventLog,
    MetricsHTTPServer,
    NullTracer,
    RequestTracer,
    TelemetryRing,
    engine_gauges,
    parse_prometheus_text,
    read_jsonl,
    render_prometheus,
    serve_exposition,
)
from pddl_tpu.serve import ServeEngine
from pddl_tpu.serve.metrics import Reservoir, ServeMetrics
from pddl_tpu.utils.profiling import StepTimer
from conftest import ref_greedy as _ref_greedy

pytestmark = pytest.mark.obs


@pytest.fixture(scope="module")
def gpt_setup():
    model = tiny_gpt(vocab_size=32, max_len=64)
    prompt = jnp.ones((1, 8), jnp.int32)
    params = model.init(jax.random.key(0), prompt, train=False)["params"]
    return model, {"params": params}


# ---------------------------------------------------------------- tracer
def test_disabled_tracer_allocates_nothing(gpt_setup):
    """The zero-cost-when-disabled pin: run a real workload through an
    engine with the default no-op tracer and assert tracemalloc saw
    ZERO net allocations attributed to obs/trace.py."""
    from pddl_tpu.obs import trace as trace_mod

    model, variables = gpt_setup
    eng = ServeEngine(model, variables, max_slots=2, prefill_len=16)
    eng.warmup()
    assert eng.tracer is trace_mod.NULL_TRACER
    handles = [eng.submit((np.arange(5) + i) % 32, 4) for i in range(3)]
    eng.run(max_steps=5)  # warm every code path before measuring
    tracemalloc.start()
    try:
        snap_before = tracemalloc.take_snapshot()
        eng.run(max_steps=200)
        snap_after = tracemalloc.take_snapshot()
    finally:
        tracemalloc.stop()
    assert all(h.done for h in handles)
    trace_file = trace_mod.__file__
    diff = snap_after.filter_traces(
        [tracemalloc.Filter(True, trace_file)]).compare_to(
        snap_before.filter_traces(
            [tracemalloc.Filter(True, trace_file)]), "lineno")
    grew = [d for d in diff if d.size_diff > 0]
    assert not grew, f"disabled tracer allocated: {grew}"


def test_disabled_tracer_dtrace_hooks_allocate_nothing():
    """The ISSUE 19 extension of the zero-cost pin: the distributed-
    tracing hook surface (trace context stamping, restore, chain
    transfer, span shipping, flight-recorder rotation) must be no-op
    AND allocation-free on the NullTracer — these hooks sit on the
    fleet hot paths of every UNtraced fleet too."""
    from pddl_tpu.obs import trace as trace_mod

    tracer = trace_mod.NULL_TRACER

    def drive():
        for i in range(200):
            tracer.on_trace_context(i, "0" * 16, "router")
            tracer.on_restored(None, i)
            tracer.on_chain_export(3, 0.001)
            tracer.on_chain_import(3, 0.001)
            tracer.on_span_shipped(4, 0)
            tracer.on_flight_rotate(2, 4096)

    drive()  # warm the code paths before measuring
    tracemalloc.start()
    try:
        snap_before = tracemalloc.take_snapshot()
        drive()
        snap_after = tracemalloc.take_snapshot()
    finally:
        tracemalloc.stop()
    trace_file = trace_mod.__file__
    diff = snap_after.filter_traces(
        [tracemalloc.Filter(True, trace_file)]).compare_to(
        snap_before.filter_traces(
            [tracemalloc.Filter(True, trace_file)]), "lineno")
    grew = [d for d in diff if d.size_diff > 0]
    assert not grew, f"disabled dtrace hooks allocated: {grew}"


def test_span_timeline_reconstructs_request(gpt_setup, tmp_path,
                                            pin_zero_recompiles):
    """One traced request: the span carries the full queue → admission
    → prefix match → prefill chunks → first token → decode → finish
    timeline with monotone timestamps, and the JSONL sink holds the
    identical record. Zero recompiles with tracing ON."""
    model, variables = gpt_setup
    path = str(tmp_path / "trace.jsonl")
    log = JsonlEventLog(path)
    tracer = RequestTracer(sink=log)
    eng = pin_zero_recompiles(ServeEngine(
        model, variables, max_slots=2, prefill_len=16, tracer=tracer))
    p, n = (np.arange(10) * 3 + 1) % 32, 5
    h = eng.submit(p, n)
    eng.run(max_steps=50)
    log.close()
    assert h.tokens == _ref_greedy(model, variables, p, n)
    assert tracer.spans_finished == 1
    (record,) = list(tracer.finished)
    assert record["kind"] == "span"
    assert record["schema"] == 1
    assert record["finish_reason"] == "length"
    assert record["attrs"]["prompt_len"] == 10
    assert record["attrs"]["tokens_emitted"] == n
    assert record["attrs"]["ttft_s"] >= 0
    names = [e["name"] for e in record["events"]]
    assert names[0] == "queued"
    assert "admitted" in names
    assert "prefix_match" in names  # prefix cache is on by default
    assert "prefill_chunk" in names
    assert "first_token" in names
    assert names.count("decode") == n - 1  # first token isn't a tick
    ts = [e["t_s"] for e in record["events"]]
    assert ts == sorted(ts), "span events out of order"
    assert record["end_s"] >= record["start_s"]
    admitted = next(e for e in record["events"] if e["name"] == "admitted")
    assert admitted["queue_wait_s"] >= 0
    chunks = [e for e in record["events"] if e["name"] == "prefill_chunk"]
    assert all(c["wall_s"] > 0 for c in chunks)
    # The sink's line is the same record, schema-stamped.
    (from_disk,) = [r for r in read_jsonl(path) if r["kind"] == "span"]
    assert from_disk == json.loads(json.dumps(record))


def test_broken_sink_never_crashes_the_engine(gpt_setup, tmp_path):
    """Observability must never be a fault source: a sink that closes
    (or throws) mid-run degrades to counted no-export — the engine
    keeps serving, drains cleanly, and the in-process deques still
    hold the records."""
    model, variables = gpt_setup
    log = JsonlEventLog(str(tmp_path / "t.jsonl"))
    tracer = RequestTracer(sink=log)
    eng = ServeEngine(model, variables, max_slots=1, prefill_len=16,
                      tracer=tracer)
    h1 = eng.submit(np.arange(5) % 32, 3)
    eng.run(max_steps=30)
    assert h1.done
    log.close()  # the sink dies under the engine
    h2 = eng.submit((np.arange(6) + 1) % 32, 3)
    eng.run(max_steps=30)
    assert h2.done
    assert eng.drain()["telemetry"]["ticks"] > 0  # drain event eats it
    assert tracer.sink_errors > 0
    assert tracer.spans_finished == 2  # records survive in-process


def test_drain_flushes_inflight_spans(gpt_setup, tmp_path):
    """SIGTERM-drain is exactly when a postmortem needs the spans:
    every in-flight request's span must be flushed to the sink with
    finish_reason 'drained' (the requests resume in a FRESH engine —
    these records would otherwise never land)."""
    model, variables = gpt_setup
    path = str(tmp_path / "drain_trace.jsonl")
    log = JsonlEventLog(path)
    tracer = RequestTracer(sink=log)
    eng = ServeEngine(model, variables, max_slots=1, prefill_len=16,
                      tracer=tracer)
    running = eng.submit(np.arange(5) % 32, 20)
    queued = eng.submit((np.arange(6) + 1) % 32, 4)
    for _ in range(3):
        eng.step()
    assert not running.done and not queued.done
    eng.drain()
    log.close()
    assert not tracer.active
    spans = [r for r in read_jsonl(path) if r["kind"] == "span"]
    assert len(spans) == 2
    assert all(s["finish_reason"] == "drained" for s in spans)
    assert all(s["attrs"]["drained"] for s in spans)
    # The running request's history survived into the flushed span.
    by_id = {s["request_id"]: s for s in spans}
    run_span = by_id[running.request.request_id]
    names = [e["name"] for e in run_span["events"]]
    assert "admitted" in names and "decode" in names


def test_span_event_cap_drops_and_counts(gpt_setup):
    model, variables = gpt_setup
    tracer = RequestTracer(max_events_per_span=4)
    eng = ServeEngine(model, variables, max_slots=1, prefill_len=16,
                      tracer=tracer)
    h = eng.submit(np.arange(6) % 32, 10)
    eng.run(max_steps=50)
    assert h.done
    (record,) = list(tracer.finished)
    assert len(record["events"]) == 4
    assert record["events_dropped"] > 0


def test_decode_events_have_their_own_budget(gpt_setup):
    """A long stream must not crowd rare lifecycle events out of the
    span: decode events stop at their own cap while later non-decode
    events still land."""
    model, variables = gpt_setup
    tracer = RequestTracer(max_decode_events_per_span=2)
    eng = ServeEngine(model, variables, max_slots=1, prefill_len=16,
                      tracer=tracer)
    h = eng.submit(np.arange(6) % 32, 10)
    eng.run(max_steps=50)
    assert h.done
    (record,) = list(tracer.finished)
    names = [e["name"] for e in record["events"]]
    assert names.count("decode") == 2
    assert record["events_dropped"] == 10 - 1 - 2  # the overflow
    assert record["finish_reason"] == "length"  # finish still settled


# ------------------------------------------------------------------ ring
def test_ring_respects_capacity_and_order():
    ring = TelemetryRing(capacity=4)
    assert len(ring) == 0 and ring.last() is None
    for i in range(11):
        ring.append({"step": i, "tick_wall_s": 0.001 * (i + 1),
                     "queue_depth": i, "live_slots": 1, "tokens": 2,
                     "retries": 0, "degraded": False,
                     "site_wall_s": {"tick": 0.001}})
    assert len(ring) == 4
    assert ring.total_appended == 11
    steps = [r["step"] for r in ring.snapshot()]
    assert steps == [7, 8, 9, 10]  # oldest evicted, order kept
    assert ring.last()["step"] == 10
    summary = ring.summary()
    assert summary["ticks"] == 4
    assert summary["tokens_emitted"] == 8
    assert summary["site_wall_s"] == {"tick": 0.004}
    with pytest.raises(ValueError, match="capacity"):
        TelemetryRing(capacity=0)


def test_engine_ring_records_per_site_wall(gpt_setup):
    model, variables = gpt_setup
    eng = ServeEngine(model, variables, max_slots=2, prefill_len=16,
                      telemetry_capacity=8)
    handles = [eng.submit((np.arange(6) + i) % 32, 3) for i in range(3)]
    eng.run(max_steps=50)
    assert all(h.done for h in handles)
    assert len(eng.telemetry) <= 8
    window = eng.telemetry.snapshot()
    assert [r["step"] for r in window] == sorted(r["step"] for r in window)
    # An admission step saw admission sites; every live step saw a tick.
    sites = set()
    for r in window:
        sites.update(r["site_wall_s"])
        assert r["tick_wall_s"] >= 0
    assert "tick" in sites
    total_tokens = sum(r["tokens"] for r in eng.telemetry.snapshot())
    assert total_tokens <= 9  # window may have dropped early steps


# ------------------------------------------------------------- exporters
def test_jsonl_log_appends_whole_lines(tmp_path):
    path = str(tmp_path / "events.jsonl")
    with JsonlEventLog(path) as log:
        log.write({"kind": "tick", "step": 0, "np": np.int32(3)})
        log.write({"kind": "tick", "step": 1, "schema": 99})
    # Reopening appends, never truncates.
    with JsonlEventLog(path) as log:
        log.write({"kind": "span", "step": 2})
    records = read_jsonl(path)
    assert [r["kind"] for r in records] == ["tick", "tick", "span"]
    assert records[0]["schema"] == 1   # stamped
    assert records[0]["np"] == 3       # numpy scalars serialize
    assert records[1]["schema"] == 99  # caller's schema respected
    with pytest.raises(ValueError, match="closed"):
        log.write({"kind": "tick"})


def test_prometheus_render_parses_strict():
    snap = {"requests_finished": 3, "ttft_p50_s": 0.125,
            "maybe_none": None, "flag": True,
            "compile_counts": {"tick": 1, "insert": 1}}
    text = render_prometheus(snap, prefix="pddl_serve",
                             counters=frozenset({"requests_finished"}))
    samples, types = parse_prometheus_text(text)
    assert types["pddl_serve_requests_finished_total"] == "counter"
    assert types["pddl_serve_ttft_p50_s"] == "gauge"
    assert samples[("pddl_serve_requests_finished_total", ())] == 3.0
    assert samples[("pddl_serve_ttft_p50_s", ())] == 0.125
    assert np.isnan(samples[("pddl_serve_maybe_none", ())])
    assert samples[("pddl_serve_flag", ())] == 1.0
    assert samples[("pddl_serve_compile_counts",
                    (("key", "tick"),))] == 1.0
    # The parser is a real referee: malformed input is loud.
    for bad in ("pddl metric 1", "name{unclosed 1", "name 1 2 3",
                "# TYPE name bogus"):
        with pytest.raises(ValueError):
            parse_prometheus_text(bad)
    with pytest.raises(ValueError, match="not exposition-legal"):
        render_prometheus({"bad-key": 1})


def test_snapshot_drift_guard_every_metric_exported(gpt_setup):
    """THE drift guard: every counter/gauge in `ServeMetrics.snapshot()`
    must appear in the Prometheus exposition (and every declared
    counter key must still exist in the snapshot), so a new metric
    cannot ship half-exported."""
    model, variables = gpt_setup
    eng = ServeEngine(model, variables, max_slots=2, prefill_len=16)
    h = eng.submit(np.arange(6) % 32, 3)
    eng.run(max_steps=30)
    assert h.done
    snap = eng.metrics.snapshot()
    text = serve_exposition(eng.metrics, eng)
    samples, types = parse_prometheus_text(text)
    exported = {name for name, _ in samples}
    for key in snap:
        name = f"pddl_serve_{key}"
        if key in SERVE_COUNTER_KEYS:
            name += "_total"
        assert name in exported, \
            f"snapshot key {key!r} missing from the exposition"
        expect = "counter" if key in SERVE_COUNTER_KEYS else "gauge"
        assert types[name] == expect
    # Stale declarations are drift too: every declared counter must
    # still be a snapshot key.
    assert SERVE_COUNTER_KEYS <= set(snap), \
        "SERVE_COUNTER_KEYS declares a metric snapshot() no longer has"
    # Engine gauges ride along (the ISSUE's dashboard set).
    for gauge in ("pddl_serve_engine_live_slots",
                  "pddl_serve_engine_degraded",
                  "pddl_serve_engine_prefix_pool_nbytes",
                  "pddl_serve_engine_compile_counts",
                  "pddl_serve_ring_tick_wall_p50_s"):
        assert any(name == gauge for name, _ in samples), gauge
    for key in engine_gauges(eng):
        assert f"pddl_serve_engine_{key}" in {n for n, _ in samples}


def test_latency_histograms_round_trip_strict():
    """The ISSUE 19 exposition satellite: TTFT and token-latency
    render as conventional CUMULATIVE ``_bucket`` histograms —
    ascending ``le``, ``le="+Inf"`` equal to ``_count``, ``_sum``
    over the same samples — and the whole body round-trips through
    the strict parser in both directions (each histogram verified
    sample-exact from the parsed side)."""
    from pddl_tpu.obs import (TOKEN_LATENCY_BUCKETS_S, TTFT_BUCKETS_S,
                              reservoir_histogram)

    metrics = ServeMetrics()
    ttfts = [0.004, 0.03, 0.03, 0.2, 3.0, 30.0]  # incl. one > max edge
    toklats = [0.0005, 0.002, 0.02, 0.02, 0.3]
    for v in ttfts:
        metrics.ttft_s.append(v)
    metrics.token_latency_s.extend(toklats)
    text = serve_exposition(metrics)
    samples, types = parse_prometheus_text(text)
    for name, buckets, values in (
            ("pddl_serve_ttft_seconds", TTFT_BUCKETS_S, ttfts),
            ("pddl_serve_token_latency_seconds",
             TOKEN_LATENCY_BUCKETS_S, toklats)):
        assert types[name] == "histogram"
        # Cumulative and ascending, each bucket counting v <= le.
        prev = 0
        for edge in sorted(buckets):
            got = samples[(f"{name}_bucket",
                           (("le", format(edge, "g")),))]
            assert got == sum(1 for v in values if v <= edge)
            assert got >= prev
            prev = got
        inf = samples[(f"{name}_bucket", (("le", "+Inf"),))]
        assert inf == len(values) == samples[(f"{name}_count", ())]
        assert samples[(f"{name}_sum", ())] == pytest.approx(
            sum(values))
    # The other direction: a hand-built spec renders, parses, and
    # reproduces itself bucket-for-bucket.
    spec = reservoir_histogram([0.01, 0.5], (0.1, 1.0))
    assert spec["buckets"] == {"0.1": 1, "1": 2, "+Inf": 2}
    body = render_prometheus({}, prefix="pddl_x",
                             histograms={"lat_seconds": spec})
    parsed, ptypes = parse_prometheus_text(body)
    assert ptypes["pddl_x_lat_seconds"] == "histogram"
    assert {le: parsed[("pddl_x_lat_seconds_bucket", (("le", le),))]
            for le in spec["buckets"]} == {
                le: float(c) for le, c in spec["buckets"].items()}
    assert parsed[("pddl_x_lat_seconds_count", ())] == 2.0
    # An empty reservoir still exports the full (all-zero) ladder.
    empty = reservoir_histogram(Reservoir(4), TTFT_BUCKETS_S)
    assert empty["count"] == 0 and empty["sum"] == 0.0
    assert set(empty["buckets"].values()) == {0}
    parse_prometheus_text(render_prometheus(
        {}, prefix="pddl_y", histograms={"e_seconds": empty}))


def test_metrics_http_endpoint_scrapes(gpt_setup):
    model, variables = gpt_setup
    eng = ServeEngine(model, variables, max_slots=1, prefill_len=16)
    h = eng.submit(np.arange(4) % 32, 2)
    eng.run(max_steps=20)
    assert h.done
    with MetricsHTTPServer(lambda: serve_exposition(eng.metrics, eng)) \
            as server:
        with urllib.request.urlopen(server.url, timeout=10) as resp:
            assert resp.status == 200
            assert resp.headers["Content-Type"].startswith("text/plain")
            body = resp.read().decode()
        samples, _ = parse_prometheus_text(body)
        assert samples[("pddl_serve_requests_finished_total", ())] == 1.0
        # Anything but /metrics is a 404, and a scrape survives it.
        bad = urllib.request.Request(
            f"http://{server.host}:{server.port}/other")
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(bad, timeout=10)
        assert exc.value.code == 404
        with urllib.request.urlopen(server.url, timeout=10) as resp:
            assert resp.status == 200


def test_step_timer_routes_through_renderer():
    """The training-side satellite: StepTimer emits the ServeMetrics
    snapshot-dict shape (stable keys, None before data, p99 included)
    and renders through the same Prometheus path."""
    timer = StepTimer(global_batch_size=8, verbose=0)
    cold = timer.snapshot()
    assert cold["step_time_p99_s"] is None
    assert cold["steps_timed"] == 0.0
    timer.step_times = [0.01 * (i + 1) for i in range(100)]
    snap = timer.snapshot()
    assert snap["step_time_p99_s"] >= snap["step_time_p90_s"] \
        >= snap["step_time_p50_s"]
    assert snap["steps_timed"] == 100.0
    assert snap["images_per_sec"] > 0
    text = render_prometheus(snap, prefix="pddl_train_step")
    samples, _ = parse_prometheus_text(text)
    assert samples[("pddl_train_step_step_time_p99_s", ())] == \
        pytest.approx(snap["step_time_p99_s"])
    assert samples[("pddl_train_step_steps_timed", ())] == 100.0


# ------------------------------------------------------------ reservoirs
def test_reservoir_caps_memory_keeps_percentiles():
    """The unbounded-growth fix: 200k samples through an 8k reservoir
    hold 8k floats, and p50/p99 stay within a tight tolerance of the
    true stream percentiles (uniform reservoir sampling)."""
    rng = np.random.default_rng(0)
    stream = rng.lognormal(mean=-3.0, sigma=0.5, size=200_000)
    res = Reservoir(cap=8192, seed=1)
    res.extend(stream.tolist())
    assert len(res) == 8192
    assert res.count == 200_000
    sampled_p50 = np.percentile(list(res), 50)
    sampled_p99 = np.percentile(list(res), 99)
    true_p50 = np.percentile(stream, 50)
    true_p99 = np.percentile(stream, 99)
    assert abs(sampled_p50 - true_p50) / true_p50 < 0.05
    assert abs(sampled_p99 - true_p99) / true_p99 < 0.05
    with pytest.raises(ValueError, match="cap"):
        Reservoir(cap=0)


def test_serve_metrics_bounded_under_sustained_load():
    """Drive ServeMetrics far past its cap straight through the real
    recording paths: every reservoir stays at cap, counters stay exact,
    and snapshot() still answers with sane percentiles."""
    m = ServeMetrics(reservoir_cap=64)
    for i in range(10_000):
        m.record_tick(float(i), queue_depth=i % 7, live_slots=i % 4,
                      total_slots=4, new_tokens=2, tick_seconds=0.001)
        m.record_first_token(0.05)
    assert len(m.ttft_s) == 64 and m.ttft_s.count == 10_000
    assert len(m.token_latency_s) == 64
    assert len(m.queue_depth) == 64
    assert len(m.occupancy) == 64
    snap = m.snapshot()
    assert snap["tokens_emitted"] == 30_000  # counters stay exact
    assert snap["ttft_p50_s"] == pytest.approx(0.05)
    assert snap["token_latency_p99_s"] == pytest.approx(0.001)
    assert 0.0 <= snap["mean_slot_occupancy"] <= 1.0


def test_tracer_hook_surface_matches_null():
    """RequestTracer must override only methods NullTracer declares —
    the engine calls exactly the NullTracer surface, so a hook added on
    the real tracer alone would never fire."""
    null_hooks = {n for n in vars(NullTracer)
                  if n.startswith("on_")}
    real_hooks = {n for n in vars(RequestTracer)
                  if n.startswith("on_")}
    assert real_hooks <= null_hooks, \
        f"RequestTracer hooks unknown to the engine: " \
        f"{real_hooks - null_hooks}"
