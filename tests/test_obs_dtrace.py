"""Fleet-wide distributed tracing (ISSUE 19), CPU.

The contracts under test:

- **Clock alignment** (`obs/propagate.py`): NTP-style offset samples
  off scripted ping/pong times; the minimal-RTT sample wins (its
  asymmetry error is bounded by the RTT), negative-RTT samples are
  discarded.
- **Span shipping**: the worker-side buffer is bounded, drops are
  counted (never silent), drain is FIFO and batch-limited.
- **Collector identity**: hedge aliases and the r20 hand-off rebind
  fold every secondary rid into the PRIMARY trace; ``context_for`` is
  pure (a failed routing attempt opens no phantom trace); the record
  ledger is bounded with terminal records evicted first.
- **Stitch across the hand-off** (`obs/assemble.py`): a split-fleet
  request's trace spans the prefill replica, the chain-wire transfer,
  and the decode replica with ZERO gaps — streams token-exact vs the
  greedy oracle, TTFT critical path resolvable with segments summing
  to TTFT.
- **Flight recorder** (`obs/flightrec.py`): CRC-framed rotation +
  prune round-trips through ``harvest``; a torn tail yields the
  readable prefix (the WAL's discipline); an injected storage storm
  degrades it to counted drops — appends never raise.
- **SIGKILL postmortem**: a hard-killed ProcessReplica's flight
  segments reassemble its final ticks (per-rid token prefixes of the
  canonical streams), the router writes the postmortem bundle, and
  every migrated stream's trace still stitches gap-free.
- **Chaos campaigns**: 3 seeded multi-plane campaigns with tracing
  armed hold the conductor's ``trace_complete`` invariant green.
"""

import json
import os
import struct
import zlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pddl_tpu.chaos import ChaosConductor, ReplicaChaos, local_kill
from pddl_tpu.models.gpt import tiny_gpt
from pddl_tpu.obs import assemble as assemble_mod
from pddl_tpu.obs import flightrec as flightrec_mod
from pddl_tpu.obs.assemble import TRACE_SEGMENTS, aggregate, stitch
from pddl_tpu.obs.propagate import (
    ClockAligner,
    SpanShipper,
    TraceCollector,
    estimate_offset,
    trace_id_for_rid,
)
from pddl_tpu.serve import FaultPlan, ServeEngine
from pddl_tpu.serve.fleet import FleetRouter, LocalReplica
from pddl_tpu.utils.faults import StorageFaultPlan
from conftest import ref_greedy as _ref_greedy, FakeClock

pytestmark = pytest.mark.dtrace

BS = 8


@pytest.fixture(scope="module")
def gpt_setup():
    model = tiny_gpt(vocab_size=32, max_len=64)
    prompt = jnp.ones((1, 8), jnp.int32)
    params = model.init(jax.random.key(0), prompt, train=False)["params"]
    return model, {"params": params}


def _no_sleep(_):
    pass


def _engine_factory(model, variables, *, host=1 << 24, plan=None):
    """Hand-off-capable engine (prefix cache + host tier) — the same
    shape test_serve_disagg pins token-exact."""
    def make():
        return ServeEngine(model, variables, max_slots=2, prefill_len=32,
                           prefix_cache_blocks=24, prefix_block_size=BS,
                           prefix_chunk=BS, host_tier=host,
                           fault_plan=plan, max_queue_depth=64,
                           backoff_sleep=_no_sleep)
    return make


def _split_fleet(model, variables, n_prefill, n_decode, **router_kw):
    pf = _engine_factory(model, variables)
    df = _engine_factory(model, variables)
    replicas = [LocalReplica(i, pf, role="prefill")
                for i in range(n_prefill)]
    replicas += [LocalReplica(n_prefill + i, df, role="decode")
                 for i in range(n_decode)]
    return FleetRouter(replicas, affinity_block_size=BS,
                       affinity_blocks=1, respawn=False, **router_kw)


def _workload(n_requests, seed=0):
    """Cold prompts >= 1 full block (the exportable chain) with short
    greedy continuations."""
    rng = np.random.default_rng(seed)
    reqs = []
    for _ in range(n_requests):
        plen = int(rng.integers(12, 25))
        reqs.append((rng.integers(0, 32, size=plen).astype(np.int32),
                     int(rng.integers(3, 8))))
    return reqs


# ------------------------------------------------------- clock alignment
def test_estimate_offset_scripted_skew():
    """A remote clock running local+5s: symmetric samples recover the
    skew exactly; the midpoint assumption bounds the error of an
    asymmetric sample by half its RTT."""
    skew = 5.0
    # Remote reads its clock exactly mid-flight: offset is exact.
    off, rtt = estimate_offset(10.0, 10.2, 10.1 + skew)
    assert off == pytest.approx(skew)
    assert rtt == pytest.approx(0.2)
    # Fully asymmetric sample (remote read at the START of the round
    # trip): the error is rtt/2, never more.
    off_bad, rtt_bad = estimate_offset(30.0, 30.5, 30.0 + skew)
    assert abs(off_bad - skew) == pytest.approx(rtt_bad / 2.0)


def test_clock_aligner_min_rtt_wins():
    aligner = ClockAligner()
    skew = 5.0
    # High-RTT asymmetric sample first (offset error 0.25s)...
    aligner.observe(30.0, 30.5, 30.0 + skew)
    first = aligner.offset_s
    assert first is not None and abs(first - skew) > 0.2
    # ...then a tight sample: smaller RTT replaces it outright.
    aligner.observe(40.0, 40.01, 40.005 + skew)
    assert aligner.offset_s == pytest.approx(skew, abs=1e-9)
    assert aligner.best_rtt_s == pytest.approx(0.01)
    # A worse-RTT sample never overwrites the best one.
    aligner.observe(50.0, 50.3, 50.0 + skew)
    assert aligner.best_rtt_s == pytest.approx(0.01)
    # Negative RTT (clock stepped backwards mid-sample): discarded.
    aligner.observe(60.0, 59.9, 60.0 + skew)
    assert aligner.samples == 3
    assert aligner.best_rtt_s == pytest.approx(0.01)


# --------------------------------------------------------- span shipping
def test_span_shipper_bounds_and_drop_counting():
    shipper = SpanShipper(capacity=4)
    assert all(shipper.add({"i": i}) for i in range(4))
    assert not shipper.add({"i": 4})  # full: counted drop, no raise
    assert not shipper.add({"i": 5})
    assert shipper.dropped == 2
    assert len(shipper) == 4
    batch = shipper.drain(3)
    assert [r["i"] for r in batch] == [0, 1, 2]  # FIFO, batch-limited
    assert [r["i"] for r in shipper.drain(None)] == [3]
    assert shipper.shipped == 4
    assert len(shipper) == 0


# ---------------------------------------------------- collector identity
def test_collector_alias_rebind_and_purity():
    clock = FakeClock(100.0)
    col = TraceCollector(clock=clock)
    # context_for is PURE: probing a rid opens no phantom record.
    assert col.context_for(7) == (trace_id_for_rid(7), "router")
    assert col.records() == []
    col.on_submit(7, prompt_len=12, priority="batch")
    col.on_route(7, 0, how="affinity")
    # Hedge copy 8 and the hand-off's fresh rid 9 both alias to 7.
    col.on_hedge(8, 7, replica_id=1)
    col.rebind(8, 9)  # rebind chains THROUGH an alias to the primary
    assert col.primary_rid(9) == 7
    assert col.context_for(9)[0] == trace_id_for_rid(7)
    col.on_finish(9, "finished", "length", 5)
    recs = [r for r in col.records() if r["kind"] == "fleet_span"]
    assert len(recs) == 1  # one trace, not three
    assert recs[0]["trace_id"] == trace_id_for_rid(7)
    assert recs[0]["state"] == "finished"
    assert recs[0]["n_tokens"] == 5
    names = [e["name"] for e in recs[0]["events"]]
    assert names == ["submit", "route", "hedge", "finish"]


def test_collector_eviction_prefers_terminal_records():
    col = TraceCollector(clock=FakeClock(0.0), max_traces=2)
    col.on_submit(1, prompt_len=4, priority="batch")
    col.on_finish(1, "finished", "length", 3)
    col.on_submit(2, prompt_len=4, priority="batch")  # live
    col.on_submit(3, prompt_len=4, priority="batch")  # overflows
    assert col.records_dropped == 1
    kept = {r["rid"] for r in col.records()
            if r["kind"] == "fleet_span"}
    assert kept == {2, 3}  # the TERMINAL record retired first


# ------------------------------------------- stitch across the hand-off
def test_stitch_across_handoff_token_exact(gpt_setup):
    """One prefill + one decode replica: every stream token-exact vs
    the oracle, every trace gap-free spanning BOTH replicas with the
    chain-wire transfer spans and the hand-off on the router record."""
    model, variables = gpt_setup
    fleet = _split_fleet(model, variables, 1, 1, dtrace=True)
    assert fleet.disagg_armed and fleet.dtrace is not None
    reqs = _workload(6, seed=1)
    refs = [_ref_greedy(model, variables, p, n) for p, n in reqs]
    handles = [fleet.submit(p, n) for p, n in reqs]
    fleet.run(max_steps=1200)
    for _ in range(3):  # let the last finish's spans ship
        fleet.step()
    for h, ref in zip(handles, refs):
        assert list(h.tokens) == ref
    traces = stitch(fleet.dtrace.records())
    assert len(traces) == len(reqs)
    handed_off = 0
    for trace in traces.values():
        assert trace.gaps() == []
        events = [e["name"] for e in trace.router["events"]]
        if "handoff" in events:
            handed_off += 1
            # The trace spans prefill replica -> wire -> decode replica.
            assert set(trace.replicas()) == {0, 1}
            assert {s["name"] for s in trace.chain_spans()} == {
                "chain_export", "chain_import"}
            assert "handoff_export" in events
            assert "handoff_import" in events
        cp = trace.critical_path()
        assert cp is not None
        # Segments sum exactly to TTFT (first_tick is the residual).
        total = sum(cp[name] for name in TRACE_SEGMENTS)
        assert total == pytest.approx(cp["ttft_s"], abs=1e-9)
    assert handed_off == fleet.metrics.handoffs_completed > 0
    fleet.close()


def test_aggregate_and_cli_report(gpt_setup, tmp_path, capsys):
    """The fleet-level attribution surface: aggregate() percentiles
    over a traced unified fleet, the collector dump, and the
    ``python -m pddl_tpu.obs.assemble`` CLI over it."""
    model, variables = gpt_setup
    factory = _engine_factory(model, variables)
    fleet = FleetRouter(
        [LocalReplica(0, factory), LocalReplica(1, factory)],
        affinity_block_size=BS, affinity_blocks=1, respawn=False,
        dtrace=True)
    reqs = _workload(5, seed=2)
    handles = [fleet.submit(p, n) for p, n in reqs]
    fleet.run(max_steps=600)
    for _ in range(3):
        fleet.step()
    assert all(h.done for h in handles)
    traces = stitch(fleet.dtrace.records())
    agg = aggregate(traces.values())
    assert agg["traces"] == len(reqs)
    assert agg["attributed"] == len(reqs)
    assert agg["gappy"] == 0
    assert agg["segments"]["ttft_s"]["p50_s"] > 0.0
    assert "prefill" in agg["segments"]
    dump = tmp_path / "trace.jsonl"
    n = fleet.dtrace.dump(str(dump))
    assert n == len(fleet.dtrace.records())
    fleet.close()
    assert assemble_mod.main([str(dump)]) == 0
    report = capsys.readouterr().out
    assert f"traces={len(reqs)} attributed={len(reqs)} gappy=0" in report
    assert "first_tick" in report
    assert assemble_mod.main([str(dump), "--json"]) == 0
    parsed = json.loads(capsys.readouterr().out)
    assert parsed["gappy"] == 0


# -------------------------------------------------------- flight recorder
def test_flightrec_rotation_prune_and_harvest(tmp_path):
    d = str(tmp_path / "frec")
    rec = flightrec_mod.FlightRecorder(d, max_segment_bytes=256,
                                       max_segments=2)
    for i in range(40):
        assert rec.append({"kind": "flight_tick", "i": i})
    rec.close()
    assert rec.rotations > 2  # rotation happened, prune engaged
    segs = [n for n in os.listdir(d) if n.startswith("seg-")]
    assert 0 < len(segs) <= 2
    got = flightrec_mod.harvest(d)
    # Oldest segments were pruned: harvest returns a contiguous TAIL
    # of the append stream, in order, ending at the last record.
    idx = [r["i"] for r in got]
    assert idx == list(range(idx[0], 40))
    assert rec.counts()["records_written"] == 40


def test_flightrec_torn_tail_yields_readable_prefix(tmp_path):
    d = str(tmp_path / "frec")
    rec = flightrec_mod.FlightRecorder(d, max_segment_bytes=1 << 20)
    for i in range(5):
        rec.append({"i": i})
    rec.close()
    path = os.path.join(d, flightrec_mod.CURRENT_NAME)
    with open(path, "rb") as f:
        data = f.read()
    # A SIGKILL mid-write: append half a frame, then garbage.
    payload = json.dumps({"i": 99}).encode()
    frame = struct.pack(">4sII", b"PFR1", len(payload),
                        zlib.crc32(payload)) + payload
    with open(path, "ab") as f:
        f.write(frame[:len(frame) // 2])
    assert [r["i"] for r in flightrec_mod.readable_records(
        data + frame[:len(frame) // 2])] == list(range(5))
    # CRC mismatch stops the read at the corrupt frame too.
    bad = bytearray(data)
    bad[-1] ^= 0xFF
    assert len(flightrec_mod.readable_records(bytes(bad))) == 4
    # harvest() over the directory applies the same prefix rule.
    assert [r["i"] for r in flightrec_mod.harvest(d)] == list(range(5))


def test_flightrec_storage_faults_degrade_counted(tmp_path):
    """A dying disk degrades the recorder to counted no-export —
    appends keep returning (False), nothing raises, serving notices
    nothing."""
    plan = StorageFaultPlan(seed=3, eio_rate=1.0)
    rec = flightrec_mod.FlightRecorder(str(tmp_path / "frec"),
                                       storage_plan=plan,
                                       error_limit=3)
    results = [rec.append({"i": i}) for i in range(10)]
    assert not any(results)
    assert rec.disabled
    assert rec.records_dropped == 10
    assert rec.errors >= 1
    rec.close()


# --------------------------------------------------- SIGKILL postmortem
_WORKER_CFG = dict(vocab=32, max_len=64, embed_dim=32, depth=1, heads=2,
                   slots=4, prefill_len=16, max_queue_depth=64,
                   param_seed=0, prefix_cache_blocks=0)


def test_sigkill_flight_harvest_and_postmortem(tmp_path):
    """Hard-kill a traced ProcessReplica mid-stream: the router
    harvests its flight segments (final ticks reassembled as per-rid
    token prefixes of the canonical streams), writes the postmortem
    bundle, and every migrated stream finishes with a gap-free trace."""
    import subprocess
    import sys
    import time

    from pddl_tpu.serve.fleet import ProcessReplica

    frdirs = [str(tmp_path / f"frec-{i}") for i in range(2)]
    reps = [ProcessReplica(
        i, {**_WORKER_CFG, "replica_id": i, "dtrace": True,
            "flightrec_dir": frdirs[i]},
        python=sys.executable, stderr=subprocess.DEVNULL,
        ping_interval_s=0.01, wait_ready=False) for i in range(2)]
    for r in reps:
        r.wait_ready()
    fleet = FleetRouter(reps, respawn=False, dtrace=True)
    try:
        rng = np.random.default_rng(4)
        prompts = [rng.integers(0, 32, size=10).tolist()
                   for _ in range(6)]
        handles = [fleet.submit(p, 24) for p in prompts]
        rids = dict(fleet._by_rid)  # rid -> handle, before migration
        deadline = time.monotonic() + 60.0
        while (any(len(h.tokens) < 2 for h in handles)
               and time.monotonic() < deadline):
            fleet.step()
        assert all(len(h.tokens) >= 2 for h in handles)
        victim = fleet.replicas[0]
        served = list(victim.assigned)  # rids on the doomed replica
        assert served  # the kill must actually orphan streams
        victim.driver.kill()
        deadline = time.monotonic() + 120.0
        while (any(not h.done for h in handles)
               and time.monotonic() < deadline):
            fleet.step()
        assert all(h.state.value == "finished" for h in handles)
        drain = time.monotonic() + 1.0
        while time.monotonic() < drain:
            fleet.step()
            time.sleep(0.01)
        # The postmortem bundle landed next to the dead worker's
        # segments, quoting what the harvest recovered.
        bundles = [n for n in os.listdir(frdirs[0])
                   if n.startswith("postmortem-")]
        assert len(bundles) == 1
        with open(os.path.join(frdirs[0], bundles[0])) as f:
            bundle = json.load(f)
        assert bundle["replica"] == 0
        assert bundle["harvested_records"] > 0
        assert {int(rid) for rid, _ in bundle["mirrors"]} == set(served)
        # The flight segments reassemble the dead worker's final
        # ticks: concatenated per-rid tokens are prefixes of the
        # canonical streams the router finished elsewhere.
        flight = flightrec_mod.harvest(frdirs[0])
        assert any(r.get("kind") == "flight_tick" for r in flight)
        flight_toks = {}
        for r in flight:
            if r.get("kind") == "flight_tokens":
                for rid, toks in r["toks"]:
                    flight_toks.setdefault(int(rid), []).extend(
                        int(t) for t in toks)
        assert flight_toks  # the final ticks ARE in the file
        for rid, toks in flight_toks.items():
            full = list(rids[rid].tokens)
            assert toks == full[:len(toks)]
        # Every stream's trace still stitches gap-free ACROSS the
        # migration, and both replicas shipped pipe spans.
        traces = stitch(fleet.dtrace.records())
        assert len(traces) == len(handles)
        for trace in traces.values():
            assert trace.gaps() == []
        shipped = {r.get("replica") for r in fleet.dtrace.records()
                   if r.get("kind") == "span"
                   and r.get("source") == "pipe"}
        assert 1 in shipped  # the survivor kept shipping
    finally:
        fleet.close()


# ------------------------------------------------------ chaos campaigns
@pytest.mark.parametrize("seed", [0, 1, 2])
def test_conductor_campaign_trace_complete(gpt_setup, tmp_path, seed):
    """The composed-plane campaign with tracing armed: the referee's
    ``trace_complete`` invariant (every stitched trace gap-free after
    storms, kills and a router crash) holds across 3 seeds — and is
    CHECKED, not auto-skipped."""
    model, variables = gpt_setup
    plans = {}
    state = {"base": 0}

    def make_replicas():
        base, state["base"] = state["base"], state["base"] + 10
        reps = []
        for k in range(2):
            plan = FaultPlan(sleep_fn=_no_sleep)
            plans[base + k] = plan
            reps.append(LocalReplica(
                base + k,
                _engine_factory(model, variables, host=None, plan=plan)))
        return reps

    def make_chaos(fleet):
        return [ReplicaChaos(
                    replica_id=int(s.replica_id),
                    plan=plans[int(s.replica_id)],
                    kill_fn=(lambda p=plans[int(s.replica_id)]:
                             local_kill(p)))
                for s in fleet.replicas]

    sp = StorageFaultPlan(seed=seed)
    cond = ChaosConductor(
        make_replicas, make_chaos,
        lambda p, n: _ref_greedy(model, variables, p, n),
        journal_dir=str(tmp_path / "wal"), storage_plan=sp,
        router_kw=dict(affinity_block_size=BS, affinity_blocks=1,
                       respawn=False, dtrace=True),
        journal_kw=dict(fsync_batch_records=2, retry_limit=1,
                        retry_backoff_s=0.0, rearm_interval_s=0.0,
                        sleep_fn=_no_sleep),
        recovery_bound_s=30.0, seed=seed)
    report = cond.run(
        [(p, n) for p, n in _workload(5, seed=200 + seed)],
        planes=("device", "storage", "kill", "router"),
        horizon=30, kills=1, max_wall_s=90.0)
    assert report.ok, report.violations
    assert report.invariants["trace_complete"] is True
    assert not any(s.startswith("trace_complete")
                   for s in report.skipped)
    kinds = [a.kind for a in report.actions]
    assert {"kill", "router_crash"} <= set(kinds)
