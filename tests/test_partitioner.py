"""MinSizePartitioner parity with the reference's PS variable sharding
(`/root/reference/imagenet-resnet50-ps.py:75-78`)."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from pddl_tpu.core.sharding import MinSizePartitioner, shard_tree


def test_small_tensor_replicated():
    part = MinSizePartitioner(min_shard_bytes=256 << 10)
    # 64 floats = 256B << 256KB: stays whole (one "shard"), like TF's
    # MinSizePartitioner returning 1 partition.
    assert part.spec((64,), np.float32, axis_size=8) == P()
    assert part.num_shards((64,), np.float32, 8) == 1


def test_large_tensor_sharded_on_largest_dim():
    part = MinSizePartitioner(min_shard_bytes=256 << 10)
    # 2048x1024 f32 = 8MB >= 256KB * 8 -> shard over the axis, largest dim.
    spec = part.spec((2048, 1024), np.float32, axis_size=8)
    assert spec == P("data")
    assert part.num_shards((2048, 1024), np.float32, 8) == 8


def test_max_shards_cap():
    part = MinSizePartitioner(min_shard_bytes=1, max_shards=2)
    assert part.num_shards((1024, 1024), np.float32, 8) == 2
    # XLA tiles over the whole axis or not at all: a 2-shard cap on an
    # 8-wide axis means the tensor stays replicated (never over-sharded).
    assert part.spec((1024, 1024), np.float32, axis_size=8) == P()


def test_min_shard_bytes_floor_respected():
    # 512 KiB tensor, 256 KiB floor, 8-wide axis: TF would make 2 shards;
    # tiling 8 ways would give 64 KiB shards (< floor) -> replicate.
    part = MinSizePartitioner(min_shard_bytes=256 << 10)
    assert part.spec((512 << 8, 512), np.float32, axis_size=2) == P("data")
    assert part.spec((1024, 128), np.float32, axis_size=8) == P()


def test_indivisible_dim_falls_back_replicated():
    part = MinSizePartitioner(min_shard_bytes=1)
    # 1001 and 3 not divisible by 8 on any dim -> replicate rather than pad.
    assert part.spec((1001, 3), np.float32, axis_size=8) == P()


def test_tree_shardings_place_params(mesh8):
    part = MinSizePartitioner(min_shard_bytes=1 << 10)
    tree = {
        "big": jnp.zeros((1024, 64)),  # 256KB -> sharded
        "tiny": jnp.zeros((16,)),  # 64B -> replicated
    }
    shardings = part.tree_shardings(mesh8, tree)
    placed = shard_tree(tree, shardings)
    assert placed["big"].sharding.spec == P("data")
    assert placed["tiny"].sharding.spec == P()
    # The big leaf is physically split 8 ways.
    shard_shapes = {s.data.shape for s in placed["big"].addressable_shards}
    assert shard_shapes == {(128, 64)}
