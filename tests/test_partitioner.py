"""MinSizePartitioner parity with the reference's PS variable sharding
(`/root/reference/imagenet-resnet50-ps.py:75-78`).

The reference partitioner returns a free shard COUNT in 1..max_shards; the
XLA mapping realizes that count exactly when it divides the mesh axis
(full-axis tiling at N, a factored shard×replicate layout for 2..N-1),
rounding down to the nearest feasible divisor otherwise."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from pddl_tpu.core.sharding import MinSizePartitioner, shard_tree


def test_small_tensor_replicated():
    part = MinSizePartitioner(min_shard_bytes=256 << 10)
    # 64 floats = 256B << 256KB: stays whole (one "shard"), like TF's
    # MinSizePartitioner returning 1 partition.
    assert part.spec((64,), np.float32, axis_size=8) == P()
    assert part.num_shards((64,), np.float32, 8) == 1
    assert part.feasible_shards((64,), np.float32, 8) == (1, None)


def test_large_tensor_sharded_on_largest_dim():
    part = MinSizePartitioner(min_shard_bytes=256 << 10)
    # 2048x1024 f32 = 8MB >= 256KB * 8 -> shard over the axis, largest dim.
    spec = part.spec((2048, 1024), np.float32, axis_size=8)
    assert spec == P("data")
    assert part.num_shards((2048, 1024), np.float32, 8) == 8
    assert part.feasible_shards((2048, 1024), np.float32, 8) == (8, 0)


def test_max_shards_cap_shards_subaxis(mesh8):
    # The reference's max_shards is a free count (:78): a 2-shard cap on an
    # 8-wide axis must yield a 2-way split (each shard replicated over 4
    # devices), not replication.
    part = MinSizePartitioner(min_shard_bytes=1, max_shards=2)
    assert part.num_shards((1024, 1024), np.float32, 8) == 2
    assert part.feasible_shards((1024, 1024), np.float32, 8) == (2, 0)
    sh = part.sharding(mesh8, (1024, 1024), np.float32)
    placed = jax.device_put(jnp.zeros((1024, 1024)), sh)
    shard_shapes = {s.data.shape for s in placed.addressable_shards}
    assert shard_shapes == {(512, 1024)}
    # Each half lives on a contiguous 4-device run: 8 addressable shards,
    # 2 distinct halves.
    starts = {s.index[0].start or 0 for s in placed.addressable_shards}
    assert starts == {0, 512}


def test_min_shard_bytes_floor_respected(mesh8):
    part = MinSizePartitioner(min_shard_bytes=256 << 10)
    assert part.spec((512 << 8, 512), np.float32, axis_size=2) == P("data")
    # 512 KiB tensor, 256 KiB floor, 8-wide axis: TF makes 2 shards; the
    # XLA mapping now realizes exactly that (2-way sub-axis split) instead
    # of replicating.
    assert part.feasible_shards((1024, 128), np.float32, 8) == (2, 0)
    sh = part.sharding(mesh8, (1024, 128), np.float32)
    placed = jax.device_put(jnp.zeros((1024, 128)), sh)
    assert {s.data.shape for s in placed.addressable_shards} == {(512, 128)}
    # The full-axis PartitionSpec projection still can't express it.
    assert part.spec((1024, 128), np.float32, axis_size=8) == P()


def test_intermediate_count_rounds_to_divisor(mesh8):
    # TF count 6 on an 8-wide axis: 6 doesn't divide 8 -> round down to 4.
    part = MinSizePartitioner(min_shard_bytes=1, max_shards=6)
    assert part.num_shards((64, 64), np.float32, 8) == 6
    assert part.feasible_shards((64, 64), np.float32, 8) == (4, 0)
    sh = part.sharding(mesh8, (64, 64), np.float32)
    placed = jax.device_put(jnp.zeros((64, 64)), sh)
    assert {s.data.shape for s in placed.addressable_shards} == {(16, 64)}


def test_indivisible_dim_falls_back_replicated(mesh8):
    part = MinSizePartitioner(min_shard_bytes=1)
    # 1001 and 3 share no factor with 8 on any dim -> replicate, not pad.
    assert part.spec((1001, 3), np.float32, axis_size=8) == P()
    assert part.feasible_shards((1001, 3), np.float32, 8) == (1, None)
    assert part.sharding(mesh8, (1001, 3), np.float32).is_fully_replicated


def test_odd_dim_picks_divisible_smaller_dim(mesh8):
    # Largest dim 1000 is not divisible by 8 but is by 4... 1000 = 8*125,
    # actually divisible; use 999 (27*37): falls through to dim 1 (64).
    part = MinSizePartitioner(min_shard_bytes=1)
    n, d = part.feasible_shards((999, 64), np.float32, 8)
    assert (n, d) == (8, 1)
    sh = part.sharding(mesh8, (999, 64), np.float32)
    placed = jax.device_put(jnp.zeros((999, 64)), sh)
    assert {s.data.shape for s in placed.addressable_shards} == {(999, 8)}


def test_subaxis_disabled_on_mixed_mesh(mesh4x2):
    # A mesh with a live model axis: factoring the whole device set would
    # fold the model axis into replica groups -> intermediate counts stay
    # replicated (full-axis tiling still fine).
    part = MinSizePartitioner(min_shard_bytes=1, max_shards=2)
    sh = part.sharding(mesh4x2, (64, 64), np.float32)
    assert sh.is_fully_replicated
    full = MinSizePartitioner(min_shard_bytes=1)
    assert part.spec((64, 64), np.float32, 4) == P()
    assert full.sharding(mesh4x2, (64, 64), np.float32).spec == P("data")


def test_tree_shardings_place_params(mesh8):
    part = MinSizePartitioner(min_shard_bytes=1 << 10)
    tree = {
        "big": jnp.zeros((1024, 64)),  # 256KB -> sharded 8-ways
        "mid": jnp.zeros((512,)),      # 2KB -> TF count 2 -> 2-way split
        "tiny": jnp.zeros((16,)),      # 64B -> replicated
    }
    shardings = part.tree_shardings(mesh8, tree)
    placed = shard_tree(tree, shardings)
    assert placed["big"].sharding.spec == P("data")
    assert placed["tiny"].sharding.spec == P()
    assert {s.data.shape for s in placed["big"].addressable_shards} == {(128, 64)}
    assert {s.data.shape for s in placed["mid"].addressable_shards} == {(256,)}


def test_ps_training_converges_with_subaxis_shards(mesh8):
    """VERDICT r1 #5 done-criterion: a PS config where middle-ground
    tensors shard 2..N-1 ways on an 8-device mesh and training converges."""
    from pddl_tpu.data.synthetic import SyntheticImageClassification
    from pddl_tpu.models.resnet import tiny_resnet
    from pddl_tpu.parallel.ps import ParameterServerStrategy
    from pddl_tpu.train.loop import Trainer

    # At 1 KiB the tiny model's params spread over the whole range:
    # replicated, 2-way, 4-way (sub-axis), and 8-way (full-axis).
    strategy = ParameterServerStrategy(min_shard_bytes=1 << 10)
    strategy._mesh = mesh8
    trainer = Trainer(
        tiny_resnet(num_classes=10), learning_rate=1e-2, strategy=strategy,
    )
    ds = SyntheticImageClassification(
        batch_size=strategy.scale_batch_size(2), image_size=32,
        num_classes=10, signal_strength=3.0,
    )
    h = trainer.fit(ds, epochs=2, steps_per_epoch=4, verbose=0)
    losses = h.history["loss"]
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]  # learning under the mixed layout

    # The layout actually contains intermediate shard counts: at least one
    # parameter leaf is neither replicated nor full-axis (its sharding
    # mesh carries the factored _data_shard axis).
    subaxis = [
        leaf for leaf in jax.tree.leaves(trainer.state.params)
        if "_data_shard" in leaf.sharding.mesh.axis_names
        and not leaf.sharding.is_fully_replicated
    ]
    assert subaxis, "expected some 2..N-1-way sharded parameters"
    full = [
        leaf for leaf in jax.tree.leaves(trainer.state.params)
        if "data" in jax.tree.leaves(tuple(leaf.sharding.spec))
    ]
    assert full, "expected some full-axis sharded parameters"
