"""Pipeline parallelism: the GPipe schedule must be a numerical no-op.

The pipelined forward (scan over ticks + ppermute hops, stage weights
sharded over ``stage``) computes exactly the same function as applying the
stages sequentially — forward AND gradients (the backward pipeline is
AD-derived). Plus: stage sharding placement and DP x PP end-to-end
training on the fake 8-device mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from pddl_tpu.core.mesh import MeshConfig, STAGE_AXIS, build_mesh
from pddl_tpu.data.synthetic import SyntheticImageClassification
from pddl_tpu.models.vit import GPipeViT
from pddl_tpu.parallel import PipelineStrategy
from pddl_tpu.train.loop import Trainer


def _model(mesh, n_stages=4, n_micro=4):
    return GPipeViT(
        n_stages=n_stages, blocks_per_stage=1, n_microbatches=n_micro,
        mesh=mesh, patch_size=8, embed_dim=32, num_heads=4, num_classes=8,
    )


def test_pipeline_forward_matches_sequential():
    mesh = build_mesh(MeshConfig(data=2, stage=4))
    model = _model(mesh)
    x = jax.random.normal(jax.random.key(0), (8, 32, 32, 3))
    variables = model.init(jax.random.key(1), x)

    piped = jax.jit(lambda v, xx: model.apply(v, xx))(variables, x)
    seq = model.apply_sequential(variables, x)
    np.testing.assert_allclose(np.asarray(piped), np.asarray(seq),
                               atol=1e-4, rtol=1e-4)


def test_pipeline_gradients_match_sequential():
    """jax.grad through scan+ppermute IS the backward pipeline."""
    mesh = build_mesh(MeshConfig(data=2, stage=4))
    model = _model(mesh)
    x = jax.random.normal(jax.random.key(0), (8, 32, 32, 3))
    variables = model.init(jax.random.key(1), x)

    def loss_piped(v):
        out = model.apply(v, x)
        return jnp.sum(out * jnp.cos(jnp.arange(out.size).reshape(out.shape)))

    def loss_seq(v):
        out = model.apply_sequential(v, x)
        return jnp.sum(out * jnp.cos(jnp.arange(out.size).reshape(out.shape)))

    gp = jax.jit(jax.grad(loss_piped))(variables)
    gs = jax.grad(loss_seq)(variables)
    for a, b in zip(jax.tree.leaves(gp), jax.tree.leaves(gs)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-4, rtol=1e-3)


def test_pipeline_strategy_shards_stages_and_trains():
    strategy = PipelineStrategy(n_stages=4)  # data=2 x stage=4
    mesh = strategy.setup()
    model = _model(mesh)
    tr = Trainer(model, optimizer="adamw", learning_rate=1e-3,
                 strategy=strategy, seed=0)
    ds = SyntheticImageClassification(
        batch_size=8, image_size=32, num_classes=8, seed=0,
        signal_strength=3.0)
    # 4 epochs: adamw needs a few warmup steps before the loss moves
    # decisively on this tiny config (2 epochs is within seed-noise of
    # flat).
    hist = tr.fit(ds, epochs=4, steps_per_epoch=4, verbose=0)
    assert hist.history["loss"][-1] < hist.history["loss"][0]

    # One stage's weights per mesh position; embed/head replicated.
    stages = tr.state.params["stages"]
    leaf = jax.tree.leaves(stages)[0]
    assert leaf.sharding.spec[0] == STAGE_AXIS
    assert tr.state.params["embed"]["patch_embed"]["kernel"].sharding.spec == P()
    # Optimizer moments inherit the stage layout.
    flat = jax.tree_util.tree_flatten_with_path(tr.state.opt_state)[0]
    moment = [leaf for path, leaf in flat
              if "stages" in str(path) and hasattr(leaf, "sharding")
              and leaf.ndim > 0]
    assert moment and all(m.sharding.spec[0] == STAGE_AXIS for m in moment)


def test_pipeline_with_flash_attention_stages():
    """Flash-attention stages inside the GPipe shard_map: same function as
    the sequential oracle (vma check relaxed only on interpret backends)."""
    mesh = build_mesh(MeshConfig(data=2, stage=4))
    model = GPipeViT(n_stages=4, blocks_per_stage=1, n_microbatches=2,
                     mesh=mesh, patch_size=8, embed_dim=32, num_heads=4,
                     num_classes=8, attention="flash")
    x = jax.random.normal(jax.random.key(0), (8, 32, 32, 3))
    variables = model.init(jax.random.key(1), x)
    piped = np.asarray(jax.jit(lambda v, xx: model.apply(v, xx))(variables, x))
    seq = np.asarray(model.apply_sequential(variables, x))
    np.testing.assert_allclose(piped, seq, atol=1e-4, rtol=1e-4)


def test_3d_parallelism_dp_pp_tp():
    """data=2 x stage=2 x model=2: staged block weights shard over BOTH
    stage and model; the full 3D train step compiles and trains."""
    from pddl_tpu.core.mesh import MODEL_AXIS

    strategy = PipelineStrategy(n_stages=2, model_parallel=2)
    mesh = strategy.setup()
    assert mesh.shape == {"data": 2, "model": 2, "seq": 1, "expert": 1,
                          "stage": 2}
    model = GPipeViT(n_stages=2, blocks_per_stage=1, n_microbatches=2,
                     mesh=mesh, patch_size=8, embed_dim=32, num_heads=4,
                     num_classes=8)
    tr = Trainer(model, optimizer="adamw", learning_rate=1e-3,
                 strategy=strategy, seed=0)
    ds = SyntheticImageClassification(
        batch_size=8, image_size=32, num_classes=8, seed=0,
        signal_strength=3.0)
    hist = tr.fit(ds, epochs=2, steps_per_epoch=4, verbose=0)
    assert hist.history["loss"][-1] < hist.history["loss"][0]

    stages = tr.state.params["stages"]
    # q/k/v kernels: [n_stages, E, H, D] -> P(stage, None, model)
    qk = stages["block0"]["attn"]["query"]["kernel"]
    assert qk.sharding.spec == P(STAGE_AXIS, None, MODEL_AXIS)
    # MLP up: [n_stages, E, 4E] -> P(stage, None, model)
    m1 = stages["block0"]["mlp1"]["kernel"]
    assert m1.sharding.spec == P(STAGE_AXIS, None, MODEL_AXIS)
    # LayerNorm scale: [n_stages, E] -> stage only
    ln = stages["block0"]["ln1"]["scale"]
    assert ln.sharding.spec == P(STAGE_AXIS)


@pytest.mark.slow  # multi-hop pallas-interpret loop: tier-2 wall-clock
def test_pipeline_bubble_arithmetic():
    """Every microbatch count yields the same math (bubble only wastes
    compute, never correctness)."""
    mesh = build_mesh(MeshConfig(data=4, stage=2))
    # Microbatches split the per-data-shard batch: 16/4 = 4 local.
    x = jax.random.normal(jax.random.key(0), (16, 32, 32, 3))
    outs = []
    for n_micro in (1, 2, 4):
        model = _model(mesh, n_stages=2, n_micro=n_micro)
        variables = model.init(jax.random.key(1), x)
        outs.append(np.asarray(model.apply(variables, x)))
    for o in outs[1:]:
        np.testing.assert_allclose(o, outs[0], atol=1e-4, rtol=1e-4)


@pytest.mark.slow  # multi-hop pallas-interpret loop: tier-2 wall-clock
def test_remat_stages_changes_memory_never_numbers():
    """remat_stages (per-tick jax.checkpoint of the stage call — the
    GPipe activation-memory mitigation, benchmarks/gpipe_memory_bench.py)
    must reproduce the plain pipeline's loss AND gradients exactly."""
    import optax

    from pddl_tpu.models.llama import GPipeLlama

    mesh = build_mesh(MeshConfig(data=2, stage=4))
    tokens = jax.random.randint(jax.random.key(3), (8, 33), 0, 64)

    def loss_and_grads(remat):
        model = GPipeLlama(vocab_size=64, n_stages=4, blocks_per_stage=1,
                           n_microbatches=2, mesh=mesh, embed_dim=32,
                           num_heads=4, num_kv_heads=2,
                           remat_stages=remat)
        variables = model.init(jax.random.key(1), tokens[:, :-1])

        def loss_fn(params):
            logits = model.apply({"params": params}, tokens[:, :-1])
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, tokens[:, 1:]).mean()

        return jax.value_and_grad(loss_fn)(variables["params"])

    loss_plain, g_plain = loss_and_grads(False)
    loss_remat, g_remat = loss_and_grads(True)
    np.testing.assert_allclose(float(loss_remat), float(loss_plain),
                               rtol=1e-6)
    for a, b in zip(jax.tree.leaves(g_remat), jax.tree.leaves(g_plain)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-5, rtol=1e-5)
