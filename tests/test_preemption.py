"""Preemption handling: SIGTERM mid-training → consistent checkpoint + stop.

The failure-detection capability the reference lacks (SURVEY.md §5). A
real SIGTERM is delivered to this process mid-epoch; the handler must save
at the next batch boundary, stop training cleanly, and the save must
restore into a resumed run.
"""

import os
import signal

import jax
import numpy as np

from pddl_tpu.data.synthetic import SyntheticImageClassification
from pddl_tpu.models.resnet import tiny_resnet
from pddl_tpu.parallel import SingleDeviceStrategy
from pddl_tpu.train.callbacks import Callback
from pddl_tpu.train.loop import Trainer
from pddl_tpu.utils.preemption import PreemptionCheckpoint


class _SendSigterm(Callback):
    """Delivers a real SIGTERM to our own process at a chosen step."""

    def __init__(self, at_step: int):
        self.at_step = at_step

    def on_train_batch_end(self, step, state, logs):
        if step == self.at_step:
            os.kill(os.getpid(), signal.SIGTERM)
        return None


def test_sigterm_checkpoints_and_stops(tmp_path):
    ckpt_dir = str(tmp_path / "preempt")
    tr = Trainer(tiny_resnet(num_classes=8), learning_rate=1e-2,
                 strategy=SingleDeviceStrategy(), seed=0)
    ds = SyntheticImageClassification(batch_size=8, image_size=16,
                                      num_classes=8, seed=0)
    # SIGTERM lands during epoch 0 (after step 2 of 50 planned).
    hist = tr.fit(ds, epochs=5, steps_per_epoch=10, verbose=0,
                  callbacks=[_SendSigterm(at_step=2),
                             PreemptionCheckpoint(ckpt_dir)])
    # Mid-epoch stop exits immediately: no validation, no epoch-end hooks,
    # and the partial epoch is not recorded in History.
    assert len(hist.epoch) == 0
    saved_step = int(jax.device_get(tr.state.step))

    # The checkpoint restores into a fresh trainer with matching state.
    from pddl_tpu.ckpt.checkpoint import Checkpointer

    tr2 = Trainer(tiny_resnet(num_classes=8), learning_rate=1e-2,
                  strategy=SingleDeviceStrategy(), seed=0)
    tr2.init_state(next(iter(ds)))
    ckpt = Checkpointer(ckpt_dir, async_save=False)
    try:
        restored = ckpt.restore(tr2.state)
        # The interrupted epoch (0) restarts on resume: saved epoch
        # metadata is -1 so initial_epoch = saved+1 = 0.
        assert ckpt.metadata().get("epoch") == -1
    finally:
        ckpt.close()
    # Saved at the batch boundary right after the signal (step 3 = index 2
    # + 1 completed steps), and params round-trip exactly.
    assert int(jax.device_get(restored.step)) == saved_step == 3
    for a, b in zip(jax.tree.leaves(jax.device_get(tr.state.params)),
                    jax.tree.leaves(jax.device_get(restored.params))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_reused_callback_resets_preempted_flag(tmp_path):
    """In-process retry: the same callback instance must not stop the next
    fit() after one step just because the previous run was preempted."""
    cb = PreemptionCheckpoint(str(tmp_path / "re"))
    tr = Trainer(tiny_resnet(num_classes=8), learning_rate=1e-2,
                 strategy=SingleDeviceStrategy(), seed=0)
    ds = SyntheticImageClassification(batch_size=8, image_size=16,
                                      num_classes=8, seed=0)
    tr.fit(ds, epochs=2, steps_per_epoch=3, verbose=0,
           callbacks=[_SendSigterm(at_step=1), cb])
    assert cb.preempted
    # Second run with the SAME callback completes normally.
    hist = tr.fit(ds, epochs=2, steps_per_epoch=3, verbose=0, callbacks=[cb])
    assert len(hist.epoch) == 2


def test_handlers_restored_even_when_fit_raises(tmp_path):
    """on_train_end cleanup (handler restore) must survive a training
    error — otherwise the process is left ignoring SIGTERM."""
    prev = signal.getsignal(signal.SIGTERM)

    class Boom(Callback):
        def on_train_batch_end(self, step, state, logs):
            raise RuntimeError("boom")

    tr = Trainer(tiny_resnet(num_classes=8), learning_rate=1e-2,
                 strategy=SingleDeviceStrategy(), seed=0)
    ds = SyntheticImageClassification(batch_size=8, image_size=16,
                                      num_classes=8, seed=0)
    try:
        tr.fit(ds, epochs=1, steps_per_epoch=2, verbose=0,
               callbacks=[PreemptionCheckpoint(str(tmp_path / "x")), Boom()])
    except RuntimeError:
        pass
    assert signal.getsignal(signal.SIGTERM) is prev


def test_handlers_restored_after_train(tmp_path):
    prev = signal.getsignal(signal.SIGTERM)
    tr = Trainer(tiny_resnet(num_classes=8), learning_rate=1e-2,
                 strategy=SingleDeviceStrategy(), seed=0)
    ds = SyntheticImageClassification(batch_size=8, image_size=16,
                                      num_classes=8, seed=0)
    tr.fit(ds, epochs=1, steps_per_epoch=2, verbose=0,
           callbacks=[PreemptionCheckpoint(str(tmp_path / "c"))])
    assert signal.getsignal(signal.SIGTERM) is prev
