"""Preemption handling: SIGTERM mid-training → consistent checkpoint + stop.

The failure-detection capability the reference lacks (SURVEY.md §5). A
real SIGTERM is delivered to this process mid-epoch; the handler must save
at the next batch boundary, stop training cleanly, and the save must
restore into a resumed run.
"""

import os
import signal

import jax
import numpy as np

from pddl_tpu.data.synthetic import SyntheticImageClassification
from pddl_tpu.models.resnet import tiny_resnet
from pddl_tpu.parallel import SingleDeviceStrategy
from pddl_tpu.train.callbacks import Callback
from pddl_tpu.train.loop import Trainer
from pddl_tpu.utils.preemption import PreemptionCheckpoint


class _SendSigterm(Callback):
    """Delivers a real SIGTERM to our own process at a chosen step."""

    def __init__(self, at_step: int):
        self.at_step = at_step

    def on_train_batch_end(self, step, state, logs):
        if step == self.at_step:
            os.kill(os.getpid(), signal.SIGTERM)
        return None


def test_sigterm_checkpoints_and_stops(tmp_path):
    ckpt_dir = str(tmp_path / "preempt")
    tr = Trainer(tiny_resnet(num_classes=8), learning_rate=1e-2,
                 strategy=SingleDeviceStrategy(), seed=0)
    ds = SyntheticImageClassification(batch_size=8, image_size=16,
                                      num_classes=8, seed=0)
    # SIGTERM lands during epoch 0 (after step 2 of 50 planned).
    hist = tr.fit(ds, epochs=5, steps_per_epoch=10, verbose=0,
                  callbacks=[_SendSigterm(at_step=2),
                             PreemptionCheckpoint(ckpt_dir)])
    # Mid-epoch stop exits immediately: no validation, no epoch-end hooks,
    # and the partial epoch is not recorded in History.
    assert len(hist.epoch) == 0
    saved_step = int(jax.device_get(tr.state.step))

    # The checkpoint restores into a fresh trainer with matching state.
    from pddl_tpu.ckpt.checkpoint import Checkpointer

    tr2 = Trainer(tiny_resnet(num_classes=8), learning_rate=1e-2,
                  strategy=SingleDeviceStrategy(), seed=0)
    tr2.init_state(next(iter(ds)))
    ckpt = Checkpointer(ckpt_dir, async_save=False)
    try:
        restored = ckpt.restore(tr2.state)
        meta = ckpt.metadata()
        # Legacy field: the interrupted epoch (0) restarts on a legacy
        # resume (initial_epoch = saved+1 = 0)...
        assert meta.get("epoch") == -1
        # ...and the STEP-granular loader position rides alongside, so
        # fit(resume=...) re-enters mid-epoch instead of replaying it.
        assert meta["loader"] == {"epoch": 0, "step_in_epoch": 3,
                                  "batches_consumed": 3}
        assert meta["checksums"]  # grace save is verified too
    finally:
        ckpt.close()
    # Saved at the batch boundary right after the signal (step 3 = index 2
    # + 1 completed steps), and params round-trip exactly.
    assert int(jax.device_get(restored.step)) == saved_step == 3
    for a, b in zip(jax.tree.leaves(jax.device_get(tr.state.params)),
                    jax.tree.leaves(jax.device_get(restored.params))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_preempted_run_resumes_mid_epoch_bit_exact(tmp_path):
    """The full preemption story, step-granular: SIGTERM mid-epoch →
    grace save with loader position → fit(resume=...) continues from
    the INTERRUPTED step and the final params match an uninterrupted
    run bit-exactly."""
    ds = SyntheticImageClassification(batch_size=8, image_size=16,
                                      num_classes=8, seed=0)

    clean = Trainer(tiny_resnet(num_classes=8), learning_rate=1e-2,
                    strategy=SingleDeviceStrategy(), seed=0)
    clean.fit(ds, epochs=2, steps_per_epoch=5, verbose=0)

    ckpt_dir = str(tmp_path / "pre")
    tr = Trainer(tiny_resnet(num_classes=8), learning_rate=1e-2,
                 strategy=SingleDeviceStrategy(), seed=0)
    tr.fit(ds, epochs=2, steps_per_epoch=5, verbose=0,
           callbacks=[_SendSigterm(at_step=6),
                      PreemptionCheckpoint(ckpt_dir)])
    assert int(jax.device_get(tr.state.step)) == 7  # stopped mid-epoch 1

    tr2 = Trainer(tiny_resnet(num_classes=8), learning_rate=1e-2,
                  strategy=SingleDeviceStrategy(), seed=0)
    hist = tr2.fit(ds, epochs=2, steps_per_epoch=5, verbose=0,
                   resume=ckpt_dir)
    assert hist.epoch == [1]  # re-entered the interrupted epoch
    assert int(jax.device_get(tr2.state.step)) == 10
    for a, b in zip(jax.tree.leaves(jax.device_get(clean.state.params)),
                    jax.tree.leaves(jax.device_get(tr2.state.params))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_reused_callback_resets_preempted_flag(tmp_path):
    """In-process retry: the same callback instance must not stop the next
    fit() after one step just because the previous run was preempted."""
    cb = PreemptionCheckpoint(str(tmp_path / "re"))
    tr = Trainer(tiny_resnet(num_classes=8), learning_rate=1e-2,
                 strategy=SingleDeviceStrategy(), seed=0)
    ds = SyntheticImageClassification(batch_size=8, image_size=16,
                                      num_classes=8, seed=0)
    tr.fit(ds, epochs=2, steps_per_epoch=3, verbose=0,
           callbacks=[_SendSigterm(at_step=1), cb])
    assert cb.preempted
    # Second run with the SAME callback completes normally.
    hist = tr.fit(ds, epochs=2, steps_per_epoch=3, verbose=0, callbacks=[cb])
    assert len(hist.epoch) == 2


def test_handlers_restored_even_when_fit_raises(tmp_path):
    """on_train_end cleanup (handler restore) must survive a training
    error — otherwise the process is left ignoring SIGTERM."""
    prev = signal.getsignal(signal.SIGTERM)

    class Boom(Callback):
        def on_train_batch_end(self, step, state, logs):
            raise RuntimeError("boom")

    tr = Trainer(tiny_resnet(num_classes=8), learning_rate=1e-2,
                 strategy=SingleDeviceStrategy(), seed=0)
    ds = SyntheticImageClassification(batch_size=8, image_size=16,
                                      num_classes=8, seed=0)
    try:
        tr.fit(ds, epochs=1, steps_per_epoch=2, verbose=0,
               callbacks=[PreemptionCheckpoint(str(tmp_path / "x")), Boom()])
    except RuntimeError:
        pass
    assert signal.getsignal(signal.SIGTERM) is prev


def test_handlers_restored_after_train(tmp_path):
    prev = signal.getsignal(signal.SIGTERM)
    tr = Trainer(tiny_resnet(num_classes=8), learning_rate=1e-2,
                 strategy=SingleDeviceStrategy(), seed=0)
    ds = SyntheticImageClassification(batch_size=8, image_size=16,
                                      num_classes=8, seed=0)
    tr.fit(ds, epochs=1, steps_per_epoch=2, verbose=0,
           callbacks=[PreemptionCheckpoint(str(tmp_path / "c"))])
    assert signal.getsignal(signal.SIGTERM) is prev


def test_cli_process_kill_and_resume(tmp_path):
    """The full operational story as real processes: a CLI training run is
    SIGTERMed mid-flight (Cloud-TPU eviction), exits cleanly after a
    consistent save, and a second --resume invocation picks up from it."""
    import subprocess
    import sys
    import time

    ckpt_dir = str(tmp_path / "run")
    env = dict(os.environ)
    env.update(JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=1")
    cmd = [sys.executable, "-m", "pddl_tpu", "--preset", "single",
           "--synthetic", "--model", "tiny_resnet", "--num-classes", "8",
           "--image-size", "32", "--batch", "4", "--steps-per-epoch", "5",
           "--verbose", "0", "--checkpoint-dir", ckpt_dir, "--resume",
           "--epochs", "500"]
    child = subprocess.Popen(cmd, env=env, cwd=os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))),
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    try:
        # Wait until at least one epoch checkpoint landed on disk.
        deadline = time.time() + 120
        from pddl_tpu.ckpt.checkpoint import latest_epoch

        while time.time() < deadline:
            if child.poll() is not None:
                out = child.stdout.read().decode()
                raise AssertionError(f"child exited early:\n{out[-2000:]}")
            if latest_epoch(ckpt_dir) is not None:
                break
            time.sleep(1.0)
        else:
            raise AssertionError("no checkpoint appeared within 120s")

        child.send_signal(signal.SIGTERM)
        out, _ = child.communicate(timeout=120)
        assert child.returncode == 0, out.decode()[-2000:]
    finally:
        if child.poll() is None:
            child.kill()
            child.wait()
        if child.stdout is not None:
            child.stdout.close()

    stopped_at = latest_epoch(ckpt_dir)
    assert stopped_at is not None

    # Second invocation resumes and completes the (short) remaining run.
    resume_epochs = max(stopped_at + 2, 2)
    cmd[cmd.index("--epochs") + 1] = str(resume_epochs)
    done = subprocess.run(cmd, env=env, cwd=os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))),
        capture_output=True, timeout=240)
    assert done.returncode == 0, done.stdout.decode()[-2000:]
    assert latest_epoch(ckpt_dir) >= resume_epochs - 1
