"""Prefix-aware KV reuse (`pddl_tpu/serve/kvcache/`), CPU.

The contracts under test:

- **Token-exactness**: a prefix-HIT admission (gathered blocks + chunked
  suffix prefill) emits exactly what a cold prefill emits, which itself
  equals single-request ``generate()`` — for the GPT (scalar-MHA cache)
  and Llama (GQA + RoPE) families, and composed with int8
  ``param_transform``. Every exactness test also asserts the hit
  actually happened (``prefix_hits``/``prefill_tokens_saved``), so a
  silently-dead cache cannot pass vacuously.
- **Suffix-priced admission**: the prefill-token budget charges the
  UNCACHED suffix, so shared-prefix requests co-admit where cold ones
  serialize.
- **Refcount/eviction invariants**: property-tested over randomized op
  sequences on the radix index — block accounting exact, pinned chains
  never evicted, interior nodes outlive children, LRU order respected.
- **Fixed-shape discipline**: the prefix-cache engine (seven resident
  programs: insert/tick/sample plus gather, narrow+wide chunk-prefill,
  donate) compiles nothing new after warmup across a hit/miss/evict
  workload (`pin_zero_recompiles` fixture from conftest).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pddl_tpu.models.gpt import generate, tiny_gpt
from pddl_tpu.models.llama import tiny_llama
from pddl_tpu.ops.attention import cache_blocks_gather, cache_blocks_scatter
from pddl_tpu.serve import RadixPrefixCache, ServeEngine
from pddl_tpu.serve.kvcache.radix import SCRATCH_BLOCK
from conftest import ref_greedy as _ref_greedy


@pytest.fixture(scope="module")
def gpt_setup():
    model = tiny_gpt(vocab_size=32, max_len=64)
    prompt = jnp.ones((1, 8), jnp.int32)
    params = model.init(jax.random.key(0), prompt, train=False)["params"]
    return model, {"params": params}


@pytest.fixture(scope="module")
def llama_setup():
    model = tiny_llama(vocab_size=32, max_len=64)
    prompt = jnp.ones((1, 8), jnp.int32)
    params = model.init(jax.random.key(1), prompt, train=False)["params"]
    return model, {"params": params}


def _exactness_workload(model, variables, ref_variables=None, **engine_kw):
    """Cold admit, full-prefix re-hit, and partial-prefix hit — all
    pinned token-exact against generate(); returns the engine so the
    caller can inspect telemetry."""
    ref_variables = ref_variables or variables
    eng = ServeEngine(model, variables, max_slots=2, prefill_len=16,
                      **engine_kw)
    base = (np.arange(12) * 5 + 1) % 32
    sibling = np.concatenate([base[:8], (np.arange(6) + 17) % 32])
    h_cold = eng.submit(base, 6)
    eng.run(max_steps=100)
    h_hit = eng.submit(base, 6)          # full-chain hit
    h_part = eng.submit(sibling, 6)      # shares base's first block
    eng.run(max_steps=100)
    assert h_cold.tokens == _ref_greedy(model, ref_variables, base, 6)
    assert h_hit.tokens == _ref_greedy(model, ref_variables, base, 6)
    assert h_part.tokens == _ref_greedy(model, ref_variables, sibling, 6)
    # Not vacuous: the hits really took the gather path.
    assert eng.metrics.prefix_hits >= 2
    assert eng.metrics.prefill_tokens_saved >= 2 * eng.prefix_block_size
    return eng


def test_prefix_hit_token_exact_gpt(gpt_setup):
    model, variables = gpt_setup
    eng = _exactness_workload(model, variables)
    assert eng.prefix_cache_enabled


def test_prefix_hit_token_exact_llama(llama_setup):
    """The GQA + RoPE family: post-RoPE cached keys are position-
    absolute, so gathered prefix blocks must be bit-valid in a new
    request's row cache."""
    model, variables = llama_setup
    _exactness_workload(model, variables)


@pytest.mark.parametrize("family", ["gpt", "llama"])
def test_int8_prefix_hit_token_exact(family, gpt_setup, llama_setup):
    """int8 param_transform composes: the pool stores K/V (which int8
    weight storage never touches), dequant runs inside the chunked
    suffix prefill like every other compiled program."""
    from pddl_tpu.ops.quant import dequantize, quantize_int8

    model, variables = gpt_setup if family == "gpt" else llama_setup
    qparams = quantize_int8(variables["params"], min_elems=128)
    dense = {"params": dequantize(qparams)}
    _exactness_workload(model, {"params": qparams}, ref_variables=dense,
                        param_transform=dequantize)


def test_zero_recompiles_across_hit_miss_evict(gpt_setup,
                                               pin_zero_recompiles):
    """Every resident program (seven with the prefix cache on) stays at
    one executable through cold admissions, full and partial hits, and
    pool-pressure evictions (a pool too small for the workload's
    distinct prefixes)."""
    model, variables = gpt_setup
    eng = pin_zero_recompiles(
        ServeEngine(model, variables, max_slots=2, prefill_len=16,
                    prefix_cache_blocks=4))  # 3 usable blocks + scratch
    for i in range(6):  # distinct prompts force eviction churn
        p = (np.arange(14) * 7 + 11 * i) % 32
        h = eng.submit(p, 4)
        eng.run(max_steps=100)
        assert h.tokens == _ref_greedy(model, variables, p, 4)
    assert eng.metrics.prefix_lookups == 6
    assert eng.metrics.prefix_evictions > 0  # pressure actually happened


def test_block_aligned_repeat_never_thrashes_a_full_pool(gpt_setup):
    """Donation dedup: a block-aligned prompt's tail block can never be
    GATHERED (the match cap leaves one suffix token) but it IS stored —
    re-admitting the same prompt must descend the stored chain instead
    of allocating a fresh block, or a full pool would LRU-evict a
    useful block to supply an id the index hands straight back."""
    model, variables = gpt_setup
    eng = ServeEngine(model, variables, max_slots=1, prefill_len=16,
                      prefix_block_size=8, prefix_cache_blocks=3)
    p = (np.arange(16) * 3 + 5) % 32  # 2 blocks, exactly fills the pool
    for _ in range(3):
        h = eng.submit(p, 3)
        eng.run(max_steps=50)
        assert h.tokens == _ref_greedy(model, variables, p, 3)
    assert eng.metrics.prefix_evictions == 0  # repeats allocate nothing
    assert eng.metrics.prefix_blocks_live == 2
    assert eng.metrics.prefix_hits == 2


def test_suffix_priced_admission_budget(gpt_setup):
    """The budget charges the uncached suffix: two shared-prefix
    requests co-admit under a budget that would serialize them cold
    (the prefix-off control engine proves the discrimination)."""
    model, variables = gpt_setup
    shared = (np.arange(8) * 3 + 2) % 32

    def prompts():
        return (np.concatenate([shared, [5, 9]]),
                np.concatenate([shared, [21, 4]]))

    # Prefix engine: seed the cache, then both suffix-2 requests fit a
    # 6-token budget in ONE admission burst.
    eng = ServeEngine(model, variables, max_slots=2, prefill_len=16,
                      prefill_token_budget=6)
    seed = eng.submit(np.concatenate([shared, [1, 2]]), 2)
    eng.run(max_steps=50)
    assert seed.done
    a, b = (eng.submit(p, 4) for p in prompts())
    eng.step()
    assert len(a.tokens) >= 1 and len(b.tokens) >= 1  # both admitted

    # Control: identical budget, prefix caching off — the second
    # request's full 10-token prompt exceeds the burst budget and waits.
    ctl = ServeEngine(model, variables, max_slots=2, prefill_len=16,
                      prefill_token_budget=6, prefix_cache_blocks=0)
    c, d = (ctl.submit(p, 4) for p in prompts())
    ctl.step()
    assert len(c.tokens) >= 1
    assert d.tokens == []  # still queued behind the budget


# ------------------------------------------------------------- primitives
def test_gather_scatter_roundtrip():
    """cache_blocks_scatter then cache_blocks_gather reproduces the row
    tokens bit-exactly at block granularity (the device copy contract
    both halves of the prefix cache rest on)."""
    rng = np.random.default_rng(0)
    pool = jnp.zeros((6, 2, 4, 3), jnp.float32)  # [N, H, bs, D]
    row = jnp.asarray(rng.normal(size=(1, 2, 32, 3)), jnp.float32)
    ids = jnp.asarray([2, 5, 1], jnp.int32)
    pool = cache_blocks_scatter(pool, row, ids, 1)  # tokens [4, 16)
    got = cache_blocks_gather(pool, ids)
    np.testing.assert_array_equal(np.asarray(got),
                                  np.asarray(row[:, :, 4:16]))
    # Scratch-padded scatter must not disturb real blocks.
    pool2 = cache_blocks_scatter(pool, row,
                                 jnp.asarray([0, 0, 0], jnp.int32), 0)
    np.testing.assert_array_equal(
        np.asarray(cache_blocks_gather(pool2, ids)),
        np.asarray(row[:, :, 4:16]))


def test_gather_scatter_validation():
    pool = jnp.zeros((4, 2, 4, 3))
    with pytest.raises(ValueError, match="block_ids"):
        cache_blocks_gather(pool, jnp.zeros((2, 2), jnp.int32))
    with pytest.raises(ValueError, match="batch-1"):
        cache_blocks_scatter(pool, jnp.zeros((2, 2, 8, 3)),
                             jnp.zeros(1, jnp.int32), 0)


# ------------------------------------------------------------ radix index
def _chain_tokens(rng, n_blocks, bs):
    return rng.integers(0, 8, size=n_blocks * bs).tolist()


def test_radix_refcount_eviction_invariants_property():
    """Randomized op sequences (match / extend / pin / unpin /
    allocate-with-eviction) against the invariants the engine relies
    on. Seeded — failures reproduce."""
    rng = np.random.default_rng(1234)
    bs, num_blocks = 4, 12
    idx = RadixPrefixCache(bs, num_blocks)
    pinned = []      # nodes we hold pins on

    def protected_ids():
        """Block ids on any pinned chain's root path — never evictable."""
        out = set()
        for node in pinned:
            walk = node
            while walk is not idx._root:
                out.add(walk.block_id)
                walk = walk.parent
        return out

    def live_ids():
        out, stack = [], [idx._root]
        while stack:
            n = stack.pop()
            stack.extend(n.children.values())
            if n is not idx._root:
                out.append(n.block_id)
        return out

    prompts = [_chain_tokens(rng, rng.integers(1, 4), bs)
               for _ in range(8)]
    for _ in range(300):
        op = rng.integers(0, 4)
        if op == 0:  # match + maybe extend with fresh blocks
            toks = prompts[rng.integers(len(prompts))]
            m = idx.match(toks)
            want = len(toks) // bs - m.n_blocks
            if want > 0:
                ids = idx.allocate(want)
                for bid in ids:
                    assert bid != SCRATCH_BLOCK
                    assert bid not in live_ids(), "double-issued block"
                if ids:
                    idx.extend(m.node, toks[m.n_blocks * bs:
                                            (m.n_blocks + len(ids)) * bs],
                               ids)
        elif op == 1:  # pin a matched chain
            toks = prompts[rng.integers(len(prompts))]
            m = idx.match(toks)
            if m.node is not idx._root:
                idx.pin(m.node)
                pinned.append(m.node)
        elif op == 2 and pinned:  # unpin
            idx.unpin(pinned.pop(rng.integers(len(pinned))))
        else:  # allocation pressure → forced LRU eviction of unpinned
            before = set(live_ids())
            safe = protected_ids()
            ids = idx.allocate(rng.integers(1, 4))
            idx._free.extend(ids)  # give them straight back
            evicted = before - set(live_ids())
            # eviction must never reach a pinned chain's blocks
            assert not (evicted & safe), (evicted, safe)
        # -------- invariants, after every op --------
        ids_now = live_ids()
        assert len(ids_now) == len(set(ids_now)), "block owned twice"
        assert SCRATCH_BLOCK not in ids_now
        assert idx.blocks_live + idx.blocks_free == num_blocks - 1
        assert idx.blocks_live == len(ids_now)
        # pinned chains fully alive: every pinned node's root path holds
        # ref > 0 and is still attached
        for node in pinned:
            walk = node
            while walk is not idx._root:
                assert walk.ref > 0
                assert walk.parent.children[walk.key] is walk
                walk = walk.parent
    # draining every pin leaves the whole tree evictable: allocation
    # pressure empties it without losing a single block
    while pinned:
        idx.unpin(pinned.pop())
    freed = idx.allocate(num_blocks - 1)
    assert len(freed) == num_blocks - 1  # everything evicted, none lost
    assert not idx._root.children  # tree fully drained


def test_radix_lru_order_and_pin_protection():
    bs = 2
    idx = RadixPrefixCache(bs, 4)  # 3 usable blocks
    a = idx.match([1, 1]); ids_a = idx.allocate(1)
    na = idx.extend(a.node, [1, 1], ids_a)
    b = idx.match([2, 2]); ids_b = idx.allocate(1)
    idx.extend(b.node, [2, 2], ids_b)
    c = idx.match([3, 3]); ids_c = idx.allocate(1)
    idx.extend(c.node, [3, 3], ids_c)
    idx.pin(na)
    idx.match([2, 2])  # refresh b — chain [1,1] is pinned, [3,3] is LRU
    got = idx.allocate(1)
    assert got == ids_c  # LRU unpinned leaf evicted first
    assert idx.match([1, 1]).n_blocks == 1  # pinned chain survived
    assert idx.match([3, 3]).n_blocks == 0
    # with every surviving chain pinned, allocation degrades gracefully
    # to empty (the engine then donates nothing) instead of failing
    idx.pin(idx.match([2, 2]).node)
    assert idx.allocate(3) == []
    with pytest.raises(RuntimeError, match="underflow"):
        idx.unpin(na); idx.unpin(na)


def test_radix_validation():
    with pytest.raises(ValueError, match="num_blocks"):
        RadixPrefixCache(4, 1)
    idx = RadixPrefixCache(4, 4)
    with pytest.raises(ValueError, match="scratch"):
        idx.extend(idx._root, [1, 2, 3, 4], [SCRATCH_BLOCK])
    with pytest.raises(ValueError, match="full"):
        idx.extend(idx._root, [1, 2], idx.allocate(1))


def test_engine_validation():
    """Loud config errors: unusable block size, chunk/positions clash."""
    model = tiny_gpt(vocab_size=32, max_len=64)
    prompt = jnp.ones((1, 8), jnp.int32)
    variables = {"params": model.init(jax.random.key(2), prompt,
                                      train=False)["params"]}
    with pytest.raises(ValueError, match="cacheable block"):
        ServeEngine(model, variables, max_slots=1, prefill_len=8,
                    prefix_block_size=8, prefix_cache_blocks=8)
    with pytest.raises(ValueError, match="prefix_chunk"):
        ServeEngine(model, variables, max_slots=1, prefill_len=32,
                    prefix_block_size=8, prefix_chunk=48,
                    prefix_cache_blocks=8)
