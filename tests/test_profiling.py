"""Profiling subsystem tests (SURVEY.md §5 tracing gap)."""

import glob
import os

import numpy as np

from pddl_tpu.data.synthetic import SyntheticImageClassification
from pddl_tpu.models.resnet import tiny_resnet
from pddl_tpu.parallel.single import SingleDeviceStrategy
from pddl_tpu.train.loop import Trainer
from pddl_tpu.utils.profiling import (
    Profiler,
    StepTimer,
    capture,
    device_memory_stats,
    trace,
)


def _fit(callbacks, steps=6, batch=8):
    tr = Trainer(tiny_resnet(num_classes=10), strategy=SingleDeviceStrategy())
    ds = SyntheticImageClassification(batch_size=batch, image_size=32,
                                      num_classes=10, seed=0)
    tr.fit(ds, epochs=1, steps_per_epoch=steps, verbose=0, callbacks=callbacks)
    return tr


def test_trace_annotation_no_crash():
    with trace("host_region"):
        pass
    with trace("step_region", step=3):
        pass


def test_capture_writes_trace(tmp_path):
    import jax
    import jax.numpy as jnp

    logdir = str(tmp_path / "prof")
    with capture(logdir):
        jax.jit(lambda x: x * 2)(jnp.ones(8)).block_until_ready()
    assert glob.glob(os.path.join(logdir, "**", "*.xplane.pb"),
                     recursive=True)


def test_profiler_callback_produces_trace(tmp_path):
    logdir = str(tmp_path / "prof")
    _fit([Profiler(logdir, epoch=0, start_step=1, num_steps=2)])
    assert glob.glob(os.path.join(logdir, "**", "*.xplane.pb"),
                     recursive=True)


def test_step_timer_stats():
    timer = StepTimer(global_batch_size=8, verbose=0)
    _fit([timer], steps=6)
    stats = timer.stats
    assert stats["steps_timed"] == 5  # compile step skipped
    assert stats["step_time_mean_s"] > 0
    assert stats["step_time_p99_s"] >= stats["step_time_p50_s"]
    assert stats["images_per_sec"] > 0
    # The serving-schema snapshot carries the same numbers (the shared
    # Prometheus export path is pinned in tests/test_obs.py).
    assert timer.snapshot()["step_time_p99_s"] == stats["step_time_p99_s"]
    # per-chip normalization divides by the 8 fake devices
    np.testing.assert_allclose(
        stats["images_per_sec_per_chip"] * 8, stats["images_per_sec"]
    )


def test_device_memory_stats_shape():
    stats = device_memory_stats()
    assert len(stats) == 8
    for v in stats.values():
        assert set(v) == {"bytes_in_use", "peak_bytes_in_use", "bytes_limit"}
