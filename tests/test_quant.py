"""Weight-only int8 serving: quantize/dequantize + the decode hook.

Quality on real text is the bench's job (`specdecode_bench.py --int8`);
here we pin the mechanics: which leaves quantize, the error bound per
output channel, the storage halving, and that the ``param_transform``
hook in both decode paths reproduces exactly what running on the
dequantized weights produces (the hook moves WHERE dequant happens, not
what is computed).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pddl_tpu.models.gpt import generate, tiny_gpt
from pddl_tpu.models.llama import tiny_llama
from pddl_tpu.models.speculative import generate_speculative
from pddl_tpu.ops.quant import dequantize, quantize_int8, quantized_bytes


def _params(model, prompt):
    return model.init(jax.random.key(0), prompt, train=False)["params"]


def test_roundtrip_error_bounded_per_channel():
    w = jax.random.normal(jax.random.key(1), (256, 512)) * jnp.linspace(
        0.01, 10.0, 512)[None, :]  # wildly different channel ranges
    tree = {"dense": {"kernel": w}}
    q = quantize_int8(tree, min_elems=1)
    back = dequantize(q)["dense"]["kernel"]
    # Symmetric 127-level: per-element error <= scale/2 = amax/254.
    # The relative slack covers a w/scale landing exactly on a rounding
    # tie (x.5), where the f32 error sits epsilon past the bound.
    bound = jnp.max(jnp.abs(w), axis=0) / 254.0
    assert jnp.all(jnp.abs(back - w) <= bound * (1 + 1e-5) + 1e-7)
    # Per-channel matters: the smallest channel's error obeys its OWN
    # amax bound, orders of magnitude below what the global (per-tensor)
    # amax would allow.
    small_err = jnp.max(jnp.abs((back - w)[:, 0]))
    assert small_err <= jnp.max(jnp.abs(w[:, 0])) / 254.0 + 1e-7
    assert small_err < jnp.max(jnp.abs(w)) / 254.0 / 50.0


def test_eligibility_rules():
    model = tiny_gpt(vocab_size=32, max_len=64)
    params = _params(model, jnp.zeros((1, 8), jnp.int32))
    q = quantize_int8(params, min_elems=128)
    flat = jax.tree_util.tree_flatten_with_path(
        q, is_leaf=lambda n: isinstance(n, dict)
        and set(n) == {"qvalue", "scale", "like"})[0]
    quantized = {"/".join(str(getattr(p, "key", p)) for p in path)
                 for path, node in flat
                 if isinstance(node, dict) and "qvalue" in node}
    # Embeddings never quantize (gathered, not streamed); biases and
    # norm scales are 1-D.
    assert not any("embed" in k.lower() for k in quantized)
    assert any("lm_head" in k for k in quantized)
    assert any("block" in k for k in quantized)
    stats = quantized_bytes(q)
    dense = quantized_bytes(params)
    assert stats["quantized_leaves"] > 0
    # f32 params: int8 storage cuts the quantized share ~4x; overall
    # strictly smaller.
    assert stats["bytes"] < dense["bytes"]
    # Original dtype round-trips through the "like" carrier.
    leaves = jax.tree.leaves(dequantize(q))
    assert all(l.dtype == jnp.float32 for l in leaves)


def test_amax_zero_channel_is_finite():
    w = jnp.zeros((64, 8)).at[:, :4].set(1.0)
    q = quantize_int8({"k": w}, min_elems=1)
    back = dequantize(q)["k"]
    assert jnp.all(jnp.isfinite(back))
    np.testing.assert_array_equal(np.asarray(back), np.asarray(w))


@pytest.mark.parametrize("factory", [tiny_gpt, tiny_llama],
                         ids=["gpt", "llama"])
def test_generate_param_transform_hook(factory):
    """generate(qparams, param_transform=dequantize) must equal
    generate(dequantize(qparams)) — identical weights, identical f32
    elementwise dequant math, only the jit boundary moves."""
    model = factory(vocab_size=32, max_len=64)
    prompt = jnp.tile(jnp.arange(6, dtype=jnp.int32), (2, 2))
    params = _params(model, prompt)
    qparams = quantize_int8(params, min_elems=128)
    ref = generate(model, {"params": dequantize(qparams)}, prompt,
                   max_new_tokens=16)
    out = generate(model, {"params": qparams}, prompt, max_new_tokens=16,
                   param_transform=dequantize)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_speculative_param_transform_hook():
    model = tiny_gpt(vocab_size=32, max_len=96)
    prompt = jnp.tile(jnp.arange(7, dtype=jnp.int32), (1, 3))[:, :18]
    params = _params(model, prompt)
    qparams = quantize_int8(params, min_elems=128)
    ref = generate(model, {"params": dequantize(qparams)}, prompt,
                   max_new_tokens=24)
    out = generate_speculative(model, {"params": qparams}, prompt, 24,
                               param_transform=dequantize)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_sharded_generate_rejects_param_transform(mesh4x2):
    from pddl_tpu.parallel.tensor_parallel import TensorParallelStrategy

    model = tiny_gpt(vocab_size=32, max_len=64)
    prompt = jnp.zeros((1, 4), jnp.int32)
    params = _params(model, prompt)
    strategy = TensorParallelStrategy(model_parallel=2)
    strategy.setup()
    with pytest.raises(NotImplementedError, match="unsharded"):
        generate(model, {"params": quantize_int8(params, min_elems=128)},
                 prompt, max_new_tokens=4, strategy=strategy,
                 param_transform=dequantize)
