"""ResNet family: shapes, BN-mode semantics, dtype policy."""

import jax
import jax.numpy as jnp
import numpy as np

from pddl_tpu.models import resnet


def _init(model, shape=(2, 32, 32, 3), train=True):
    variables = model.init(jax.random.key(0), jnp.zeros(shape), train=train)
    return variables


def test_tiny_resnet_shapes():
    model = resnet.tiny_resnet(num_classes=10)
    variables = _init(model)
    out, updates = model.apply(
        variables, jnp.zeros((2, 32, 32, 3)), train=True, mutable=["batch_stats"]
    )
    assert out.shape == (2, 10)
    assert "batch_stats" in updates


def test_resnet50_structure_matches_keras_counts():
    """ResNet-50 must have Keras's layer counts: 53 convs (1 stem + 16*3
    bottleneck + 4 shortcut), 53 BNs, 1 dense — the arch the reference uses
    (imagenet-resnet50.py:56)."""
    model = resnet.ResNet50(num_classes=1000)
    variables = model.init(jax.random.key(0), jnp.zeros((1, 64, 64, 3)), train=False)
    flat = jax.tree_util.tree_flatten_with_path(variables["params"])[0]
    conv_kernels = [p for p, _ in flat if any("conv" in str(k).lower() for k in p)
                    and str(p[-1])
                    == str(jax.tree_util.DictKey("kernel"))]
    assert len(conv_kernels) == 53
    bn_scales = [p for p, _ in flat if str(p[-1]) == str(jax.tree_util.DictKey("scale"))]
    assert len(bn_scales) == 53
    # Param count parity with keras ResNet50 (weights incl. head): ~25.6M.
    n_params = sum(np.prod(v.shape) for _, v in flat)
    assert 25_500_000 < n_params < 25_700_000


def test_num_classes_zero_returns_pooled_features():
    model = resnet.tiny_resnet(num_classes=0)
    variables = _init(model)
    out = model.apply(variables, jnp.zeros((2, 32, 32, 3)), train=False)
    assert out.ndim == 2 and out.shape[0] == 2  # (batch, features)


def test_frozen_bn_mode_no_stats_update():
    """bn_mode='frozen' reproduces the reference's base_model(training=False)
    behavior (imagenet-resnet50.py:57): batch_stats never change."""
    model = resnet.tiny_resnet(num_classes=10, bn_mode="frozen")
    variables = _init(model)
    x = jax.random.normal(jax.random.key(1), (4, 32, 32, 3))
    _, updates = model.apply(variables, x, train=True, mutable=["batch_stats"])
    before = jax.tree.leaves(variables["batch_stats"])
    after = jax.tree.leaves(updates["batch_stats"])
    for b, a in zip(before, after):
        np.testing.assert_array_equal(np.asarray(b), np.asarray(a))


def test_train_bn_mode_updates_stats():
    model = resnet.tiny_resnet(num_classes=10, bn_mode="train")
    variables = _init(model)
    x = jax.random.normal(jax.random.key(1), (4, 32, 32, 3)) + 3.0
    _, updates = model.apply(variables, x, train=True, mutable=["batch_stats"])
    before = np.concatenate([np.ravel(v) for v in jax.tree.leaves(variables["batch_stats"])])
    after = np.concatenate([np.ravel(v) for v in jax.tree.leaves(updates["batch_stats"])])
    assert not np.allclose(before, after)


def test_bfloat16_compute_f32_logits():
    model = resnet.tiny_resnet(num_classes=10, dtype=jnp.bfloat16)
    variables = _init(model)
    out = model.apply(variables, jnp.zeros((2, 32, 32, 3)), train=False)
    assert out.dtype == jnp.float32
    # params stay f32
    assert all(v.dtype == jnp.float32 for v in jax.tree.leaves(variables["params"]))


def test_space_to_depth_stem_exact_equivalence():
    """The s2d stem + exact kernel transform computes the SAME function as
    the Keras 7x7/s2 stem (models/resnet.py derivation): full-model logits
    agree up to conv-reassociation noise."""
    kw = dict(stage_sizes=(2, 2), num_classes=10, width_multiplier=0.25)
    m_ref = resnet.ResNet(**kw)
    m_s2d = resnet.ResNet(**kw, stem="space_to_depth")
    x = jax.random.normal(jax.random.key(0), (2, 64, 64, 3))
    v = m_ref.init(jax.random.key(1), x, train=False)

    p2 = jax.tree.map(lambda a: a, v["params"])
    p2 = dict(p2)
    p2["stem_conv"] = dict(p2["stem_conv"])
    p2["stem_conv"]["kernel"] = resnet.s2d_stem_kernel(
        v["params"]["stem_conv"]["kernel"])
    assert p2["stem_conv"]["kernel"].shape == (4, 4, 12, 16)

    y_ref = m_ref.apply(v, x, train=False)
    y_s2d = m_s2d.apply({"params": p2, "batch_stats": v["batch_stats"]},
                        x, train=False)
    np.testing.assert_allclose(np.asarray(y_s2d), np.asarray(y_ref),
                               atol=1e-4, rtol=2e-3)


def test_space_to_depth_stem_rejects_odd_input():
    m = resnet.ResNet(stage_sizes=(1,), num_classes=4,
                      width_multiplier=0.125, stem="space_to_depth")
    import pytest

    with pytest.raises(ValueError, match="even padded"):
        m.init(jax.random.key(0), jnp.zeros((1, 65, 65, 3)), train=False)
