"""Preset smoke tests: every reference script's configuration runs a few
steps end-to-end on the fake 8-device mesh (the in-process-cluster testing
idea from `imagenet-resnet50-ps.py:31-65`, done the JAX way — SURVEY.md §4).
"""

import dataclasses
import os

import jax
import numpy as np
import pytest

from pddl_tpu.config import PRESETS, get_preset
from pddl_tpu.run import build_data, build_trainer, run_experiment


def _smoke(cfg, **fit_kw):
    cfg = cfg.replace(
        model="tiny_resnet", num_classes=8, image_size=32, crop=32,
        per_replica_batch=2, val_per_replica_batch=2, epochs=2,
        compute_dtype="float32", verbose=0, data_dir=None,
    )
    return run_experiment(cfg, steps_per_epoch=2, validation_steps=1)


@pytest.mark.parametrize("preset", sorted(PRESETS))
def test_preset_smoke(preset):
    cfg = get_preset(preset)
    if cfg.weights or cfg.pretrained_h5:
        # Weight acquisition/import is covered by test_fetch.py and
        # test_keras_parity.py; smoke the training path itself.
        cfg = cfg.replace(weights=None, pretrained_h5=None)
    hist = _smoke(cfg)
    losses = hist.history["loss"]
    assert len(losses) == 2
    assert np.isfinite(losses).all()
    assert "val_loss" in hist.history


def test_preset_table_matches_reference_arithmetic():
    """Batch/LR arithmetic per script (SURVEY.md §6)."""
    assert PRESETS["single"].per_replica_batch == 32
    assert PRESETS["multiworker"].per_replica_batch == 128
    assert PRESETS["multiworker"].val_per_replica_batch == 256
    assert PRESETS["multiworker-pretrained"].per_replica_batch == 32
    assert PRESETS["hvd"].learning_rate == 0.1 and PRESETS["hvd"].scale_lr
    assert PRESETS["hvd"].warmup_epochs == 3
    assert PRESETS["hvd"].crop == 160  # imagenet-resnet50-hvd.py:89
    assert PRESETS["hvd"].data_shard == "batch"
    for name in ("single-pretrained", "mirrored-pretrained",
                 "multiworker-pretrained"):
        assert PRESETS[name].bn_mode == "frozen"  # training=False quirk
    assert PRESETS["single"].bn_mode == "train"  # deliberate fix (SURVEY §0)


def test_mirrored_batch_scaling():
    """Global batch = 32 x replicas (imagenet-resnet50-mirror.py:54)."""
    cfg = get_preset("mirrored").replace(
        model="tiny_resnet", num_classes=8, image_size=32, crop=32,
        compute_dtype="float32", verbose=0,
    )
    trainer, _ = build_trainer(cfg)
    strategy = trainer.strategy
    strategy.setup()
    train, _ = build_data(cfg, strategy)
    assert train.batch_size == 32 * 8


def test_hvd_preset_scales_lr():
    cfg = get_preset("hvd").replace(
        model="tiny_resnet", num_classes=8, image_size=32, crop=32,
        compute_dtype="float32", verbose=0,
    )
    trainer, _ = build_trainer(cfg)
    from pddl_tpu.train.state import get_learning_rate  # after warmup target

    # LR injected into the optimizer = 0.1 * 8 replicas.
    ds = build_data(cfg, trainer.strategy)[0]
    trainer.init_state(next(iter(ds)))
    assert get_learning_rate(trainer.state) == pytest.approx(0.8)


def test_pretrained_h5_flow(tmp_path):
    """--pretrained-h5 path: weights land in the live (sharded) state."""
    from pddl_tpu.ckpt.keras_import import export_keras_style_h5

    from pddl_tpu.models.resnet import ResNet

    # Tiny ResNet-50-topology donor checkpoint.
    donor = ResNet(stage_sizes=(3, 4, 6, 3), num_classes=8,
                   width_multiplier=0.0625)
    v = donor.init(jax.random.key(5), np.zeros((1, 32, 32, 3), np.float32),
                   train=False)
    path = str(tmp_path / "pre.h5")
    export_keras_style_h5(path, v)

    cfg = get_preset("single-pretrained").replace(
        model="resnet50", num_classes=8, image_size=32, crop=32,
        per_replica_batch=2, epochs=1, compute_dtype="float32", verbose=0,
        pretrained_h5=path,
    )
    # resnet50 factory must be narrowed to match the donor
    from pddl_tpu.models import registry
    registry.register_model(
        "resnet50_test_narrow",
        lambda **kw: ResNet(stage_sizes=(3, 4, 6, 3),
                            width_multiplier=0.0625, **kw),
    )
    cfg = cfg.replace(model="resnet50_test_narrow")
    hist = run_experiment(cfg, steps_per_epoch=1, validation_steps=1)
    assert np.isfinite(hist.history["loss"][-1])


def test_cli_parses_and_runs():
    from pddl_tpu.run import main

    rc = main([
        "--preset", "mirrored", "--synthetic", "--model", "tiny_resnet",
        "--num-classes", "8", "--image-size", "32", "--batch", "2",
        "--epochs", "1", "--steps-per-epoch", "2", "--verbose", "0",
    ])
    assert rc == 0


def test_cli_runs_llama_family():
    """The Llama family rides the same LM plumbing as gpt* names
    (token batches, synthetic-text data, no augmentation)."""
    from pddl_tpu.run import main

    rc = main([
        "--preset", "single", "--model", "tiny_llama", "--batch", "8",
        "--seq-len", "32", "--epochs", "1", "--steps-per-epoch", "2",
        "--verbose", "0",
    ])
    assert rc == 0


def test_strategy_options_pick_llama_tp_rules():
    """A tensor-parallel Llama run must get LLAMA_TP_RULES (the default
    VIT table matches none of the SwiGLU/embed leaf names and would
    silently replicate most of each block); explicit rules still win."""
    from pddl_tpu.config import get_preset
    from pddl_tpu.parallel.tensor_parallel import LLAMA_TP_RULES
    from pddl_tpu.run import _strategy_options

    cfg = get_preset("single", model="tiny_llama",
                     strategy="tensor_parallel",
                     strategy_options={"model_parallel": 2})
    assert _strategy_options(cfg)["rules"] is LLAMA_TP_RULES

    cfg = get_preset("single", model="tiny_gpt",
                     strategy="tensor_parallel",
                     strategy_options={"model_parallel": 2})
    assert "rules" not in _strategy_options(cfg)

    sentinel = ()
    cfg = get_preset("single", model="tiny_llama",
                     strategy="tensor_parallel",
                     strategy_options={"model_parallel": 2,
                                       "rules": sentinel})
    assert _strategy_options(cfg)["rules"] is sentinel


def test_cli_tensor_parallel_llama_trains():
    from pddl_tpu.run import main

    rc = main([
        "--preset", "single", "--model", "tiny_llama", "--batch", "8",
        "--seq-len", "32", "--epochs", "1", "--steps-per-epoch", "2",
        "--strategy", "tensor_parallel", "--model-parallel", "2",
        "--verbose", "0",
    ])
    assert rc == 0


def test_unknown_preset_raises():
    with pytest.raises(ValueError, match="unknown preset"):
        get_preset("nope")


def test_cli_profile_and_stablehlo_export(tmp_path):
    from pddl_tpu.run import main

    shlo = str(tmp_path / "model.shlo")
    prof = str(tmp_path / "prof")
    rc = main([
        "--preset", "single", "--synthetic", "--model", "tiny_resnet",
        "--num-classes", "8", "--image-size", "32", "--batch", "4",
        "--epochs", "1", "--steps-per-epoch", "8", "--verbose", "0",
        "--save", shlo, "--profile-dir", prof,
    ])
    assert rc == 0
    # Profiler wrote trace artifacts under the plugin layout.
    import glob as _glob

    assert os.path.isdir(prof), "profiler never created its log dir"
    traces = _glob.glob(os.path.join(prof, "**", "*.trace.json*"),
                        recursive=True) + _glob.glob(
        os.path.join(prof, "**", "*.xplane.pb"), recursive=True)
    assert traces, f"no trace files under {prof}: {os.listdir(prof)}"
    from pddl_tpu.ckpt.export import load_inference_artifact

    call, exported = load_inference_artifact(shlo)
    assert exported.in_avals[0].shape == (1, 32, 32, 3)
    out = call(np.zeros((1, 32, 32, 3), np.float32))
    assert np.asarray(out).shape == (1, 8)
