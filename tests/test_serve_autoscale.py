"""Elastic autoscaling fleet (`pddl_tpu/serve/fleet/autoscaler.py`), CPU.

The contracts under test:

- **Flapping-load chaos matrix** (3 seeds, ``@pytest.mark.autoscale`` +
  ``chaos``): load storms and calms while the autoscaler runs; the
  fleet scales up under pressure and scales down by LIVE-MIGRATING the
  victim's streams — and a DIFFERENT replica is killed while that
  scale-down migration is in flight. Every request reaches FINISHED,
  every stream is token-identical to the unkilled oracle, zero
  recompiles hold on every surviving replica.
- **Control-loop policy**: scale-up engages at pressure BELOW the
  brownout ladder's high-water mark (capacity ahead of shedding); a
  wedged spawn raises the typed ``ReplicaSpawnTimeout`` and is retried
  behind a doubling backoff; the scale-down projection guard vetoes a
  shrink the survivors could not absorb.
- **Router mechanics**: ``scale_up`` joins a ready replica (and
  revives parked orphans); ``scale_down`` migrates via the drain
  snapshot, refuses to orphan work when no survivor exists.
- **Trace generator** (`fleet/tracegen.py`): seeded determinism, the
  diurnal peak:trough shape, the heavy-tail output mix, priority
  split, Zipf adapter popularity.
- **Replay client** (`fleet/replay.py`): rejected events re-enter at
  ``now + retry_after_s`` (the satellite fix — the r12 harness dropped
  them), and replica-hours are metered for goodput-per-replica-hour.
- **Observability**: autoscale counters/gauges render through
  ``fleet_exposition`` and re-parse through the strict referee.
"""

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pddl_tpu.models.gpt import tiny_gpt
from pddl_tpu.obs import RequestTracer, fleet_exposition, parse_prometheus_text
from pddl_tpu.serve import QueueFull, ServeEngine
from pddl_tpu.serve.fleet import (
    AdmissionControl,
    FleetAutoscaler,
    FleetRouter,
    LocalReplica,
    ProcessReplica,
    ReplicaDied,
    ReplicaSpawnTimeout,
    ScaleDecision,
    diurnal_trace,
    replay_trace,
)
from pddl_tpu.serve.request import Priority, RequestState
from conftest import ref_greedy as _ref_greedy, FakeClock as _FakeClock

pytestmark = pytest.mark.autoscale


@pytest.fixture(scope="module")
def gpt_setup():
    model = tiny_gpt(vocab_size=32, max_len=64)
    prompt = jnp.ones((1, 8), jnp.int32)
    params = model.init(jax.random.key(0), prompt, train=False)["params"]
    return model, {"params": params}


def _no_sleep(_):
    pass


def _engine_factory(model, variables, *, max_queue_depth=3):
    def make():
        return ServeEngine(model, variables, max_slots=2, prefill_len=16,
                           max_queue_depth=max_queue_depth,
                           prefix_cache_blocks=0,
                           backoff_sleep=_no_sleep)
    return make


# ---------------------------------------------------------- chaos matrix
@pytest.mark.chaos
@pytest.mark.parametrize("seed", [0, 1, 2])
def test_autoscale_flap_chaos_matrix(gpt_setup, pin_zero_recompiles, seed):
    """Flapping load with a kill mid-scale-down: storm -> scale-up,
    calm-with-live-streams -> migration scale-down, and the FIRST
    migration target dies while the scale-down restore is in flight
    (cascade onto the remaining survivors), then a second storm flaps
    capacity back up. Every admitted request FINISHES token-exact vs
    the oracle; zero recompiles on every surviving replica."""
    model, variables = gpt_setup
    clock = _FakeClock(50.0)
    tracer = RequestTracer()
    armed = {}
    factory = _engine_factory(model, variables)

    class DiesMidRestore(LocalReplica):
        def restore(self, pairs):
            if armed.pop("on", None):
                raise ReplicaDied(self.replica_id,
                                  "killed during someone else's "
                                  "scale-down migration")
            super().restore(pairs)

    def make_replica(rid):
        return DiesMidRestore(rid, factory)

    fleet = FleetRouter(
        [make_replica(0), make_replica(1)],
        affinity_block_size=8, affinity_blocks=1, respawn=False,
        clock=clock, tracer=tracer,
        admission=AdmissionControl(
            detector_kw=dict(window_s=1.0, min_samples=4),
            # The ladder armed but parked far above the autoscaler's
            # band: rung 2 would CAP max_new_tokens and break the
            # oracle comparison this matrix pins.
            brownout_kw=dict(high=0.9, low=0.05)))
    # up_load high enough that the projection guard does not veto the
    # calm-phase shrink (the survivors CAN absorb ~8 requests here);
    # the guard has its own discriminative test below.
    FleetAutoscaler(fleet, make_replica, min_replicas=2, max_replicas=4,
                    up_pressure=0.15, down_pressure=0.02,
                    up_load=8.0, down_load=6.0,
                    up_hold_s=0.1, down_hold_s=0.3, cooldown_s=0.2)
    fleet = pin_zero_recompiles(fleet)
    rng = np.random.default_rng(seed)
    handles = []

    def submit_burst(n, lo, hi):
        for _ in range(n):
            p = rng.integers(0, 32,
                             size=int(rng.integers(6, 14))).astype(np.int32)
            n_new = int(rng.integers(lo, hi))
            try:
                h = fleet.submit(p, n_new)
            except QueueFull:
                continue
            handles.append((h, _ref_greedy(model, variables, p, n_new)))

    # Phase 1 — storm: 16 submits against 2x(2 slots + 3 queue): the
    # overflow sheds feed the detector, and capacity scales up.
    submit_burst(16, 3, 7)
    for _ in range(60):
        fleet.step()
        clock.now += 0.05
        if not fleet.has_work:
            break
    assert fleet.metrics.scale_up_events >= 1
    assert not fleet.has_work
    n_after_storm = len(fleet.replicas)
    assert n_after_storm >= 3

    # Phase 2 — calm with LIVE streams. First age the storm out of the
    # detector's 1 s window in one jump (a single tick arms the
    # down-hold but cannot satisfy it), so no late scale-up can seat an
    # EMPTY replica as the future scale-down victim; then load every
    # replica with long decodes. The down-hold expires a few ticks in,
    # mid-stream, and the scale-down live-migrates running work — and
    # the armed death takes out the first migration TARGET while that
    # migration is in flight.
    # Streams of 40+ tokens: long enough to outlive the worst-case
    # scale-down arming (a load-up shed can hold pressure in the dead
    # band for a full detector window before the down-hold even starts).
    clock.now += 1.2
    fleet.step()
    for _ in range(40):
        if all(s.load >= 2 for s in fleet.replicas if s.available):
            break
        submit_burst(1, 40, 48)
    assert all(s.load >= 2 for s in fleet.replicas if s.available)
    armed["on"] = True
    for _ in range(500):
        fleet.step()
        clock.now += 0.05
        if not fleet.has_work:
            break
    # A post-kill scale-up is legitimate (the cascade concentrates load
    # on the survivor and the load trigger replaces the loss); what the
    # matrix pins is that the scale-down MIGRATED live work.
    assert fleet.metrics.scale_down_events >= 1, \
        "the calm phase never scaled down"
    assert fleet.metrics.scale_down_migrated >= 2
    assert fleet.metrics.migrated_via_drain >= 1  # live migration path
    assert not armed, "the mid-migration kill never fired"
    assert fleet.metrics.replica_down_events >= 1  # the killed target
    assert not fleet.has_work

    # Phase 3 — the flap: storm again on the shrunken fleet.
    submit_burst(16, 3, 7)
    for _ in range(120):
        fleet.step()
        clock.now += 0.05
        if not fleet.has_work:
            break
    assert not fleet.has_work
    assert fleet.metrics.scale_up_events >= 2  # both storms grew it

    finished = 0
    for h, ref in handles:
        assert h.done, f"request {h} never reached a terminal state"
        assert h.state == RequestState.FINISHED
        assert h.tokens == ref, \
            f"stream diverged (seed {seed}): {h}"
        finished += 1
    assert finished == len(handles)
    assert fleet.metrics.requests_failed == 0
    assert fleet.metrics.requests_orphaned == 0
    # The whole episode is visible: scale events traced, exposition
    # (autoscale series included) re-parses through the strict referee.
    assert tracer.events_named("scale_up")
    assert tracer.events_named("scale_down")
    assert tracer.events_named("replica_down")
    samples, types = parse_prometheus_text(fleet_exposition(fleet))
    assert samples[("pddl_fleet_scale_up_events_total", ())] >= 2.0
    assert samples[("pddl_fleet_scale_down_events_total", ())] >= 1.0
    assert types["pddl_fleet_scale_down_migrated_total"] == "counter"
    assert samples[("pddl_fleet_autoscale_scale_up_completed_total",
                    ())] >= 2.0
    assert ("pddl_fleet_autoscale_replicas", ()) in samples


# ------------------------------------------------------- control policy
def test_scale_up_engages_before_brownout_ladder(gpt_setup):
    """The capacity-first contract: at pressure between the
    autoscaler's up_pressure and the ladder's high mark, a replica is
    spawned while the rung stays NORMAL — brownout is the last resort,
    not the first response."""
    model, variables = gpt_setup
    clock = _FakeClock(10.0)
    factory = _engine_factory(model, variables)
    admission = AdmissionControl(
        detector_kw=dict(window_s=10.0, min_samples=4),
        brownout_kw=dict(high=0.5, low=0.05, escalate_hold_s=0.0))
    fleet = FleetRouter([LocalReplica(0, factory)], respawn=False,
                        clock=clock, admission=admission)
    scaler = FleetAutoscaler(fleet, lambda rid: LocalReplica(rid, factory),
                             min_replicas=1, max_replicas=2,
                             up_pressure=0.2, down_pressure=0.02,
                             up_hold_s=0.2, down_hold_s=5.0,
                             cooldown_s=0.1)
    # One third rejected: pressure ~0.33 — above up_pressure (0.2),
    # below the ladder's high (0.5).
    for i in range(12):
        admission.observe(clock.now, rejected=(i % 3 == 0))
    assert scaler.step(clock.now) is ScaleDecision.HOLD  # hold arming
    clock.now += 0.25
    assert scaler.step(clock.now) is ScaleDecision.SCALE_UP
    assert len(fleet.replicas) == 2
    assert int(admission.rung) == 0  # ladder never engaged
    assert scaler.metrics.scale_up_completed == 1


def test_spawn_timeout_fails_fast_with_breaker_backoff(gpt_setup):
    """A wedged spawn raises the typed ReplicaSpawnTimeout out of the
    poll; the attempt fails WITHOUT blocking the loop, and retries are
    gated by a doubling backoff that resets on success."""
    model, variables = gpt_setup
    clock = _FakeClock(0.0)
    factory = _engine_factory(model, variables)

    class WedgedDriver:
        def __init__(self, rid):
            self.replica_id = rid

        def poll_ready(self):
            raise ReplicaSpawnTimeout(self.replica_id, 1.0)

    spawned = []

    def make(rid):
        spawned.append(rid)
        if len(spawned) < 3:
            return WedgedDriver(rid)
        return LocalReplica(rid, factory)

    fleet = FleetRouter([LocalReplica(0, factory)], respawn=False,
                        clock=clock)
    scaler = FleetAutoscaler(fleet, make, min_replicas=1, max_replicas=2,
                             up_pressure=0.9, down_pressure=0.02,
                             up_load=1.0, down_load=0.0,
                             up_hold_s=0.0, down_hold_s=99.0,
                             cooldown_s=0.0,
                             spawn_backoff_base_s=1.0,
                             spawn_backoff_max_s=8.0)
    fleet.submit(list(range(1, 9)), 4)  # load >= up_load arms want_up
    scaler.step(clock.now)  # attempt 1: wedged -> typed failure
    assert scaler.metrics.spawn_timeouts == 1
    assert scaler.metrics.scale_up_failed == 1
    assert len(spawned) == 1
    # Inside the backoff window: no new spawn, however hot the signal.
    clock.now += 0.5
    for _ in range(3):
        scaler.step(clock.now)
    assert len(spawned) == 1
    # Past the first backoff (1 s): attempt 2 fails too, backoff
    # doubles; attempt 3 only fires after ~2 s more.
    clock.now += 1.0
    scaler.step(clock.now)       # re-arm the hold at the new now
    scaler.step(clock.now)       # attempt 2 (hold 0): wedged again
    assert len(spawned) == 2
    clock.now += 1.0
    scaler.step(clock.now)
    assert len(spawned) == 2     # doubled backoff still gating
    clock.now += 1.5
    scaler.step(clock.now)
    assert len(spawned) == 3     # attempt 3: a real replica joins
    assert scaler.metrics.scale_up_completed == 1
    assert len(fleet.replicas) == 2
    # Success reset the backoff for the NEXT incident.
    assert scaler.gauges()["spawn_backoff_s"] == 1.0
    fleet.close()


def test_scale_down_projection_guard_vetoes_unabsorbable_shrink(
        gpt_setup):
    """The survivors-must-absorb rule: with total load that would push
    the remaining replicas back over the scale-up band, the controller
    refuses to shrink (a scale-down that causes the next scale-up is
    flapping with extra steps)."""
    model, variables = gpt_setup
    clock = _FakeClock(0.0)
    factory = _engine_factory(model, variables, max_queue_depth=16)
    fleet = FleetRouter([LocalReplica(0, factory),
                         LocalReplica(1, factory)],
                        respawn=False, clock=clock)
    scaler = FleetAutoscaler(fleet, lambda rid: LocalReplica(rid, factory),
                             min_replicas=1, max_replicas=2,
                             up_pressure=0.9, down_pressure=0.5,
                             up_load=4.0, down_load=4.0,
                             up_hold_s=0.0, down_hold_s=0.1,
                             cooldown_s=0.0)
    # 7 requests over 2 replicas: mean 3.5 <= down_load arms the
    # shrink, but 7 / 1 survivor = 7 >= up_load vetoes it.
    for i in range(7):
        fleet.submit(list(range(1, 8)), 3)
    clock.now += 0.2
    scaler.step(clock.now)
    clock.now += 0.2
    assert scaler.step(clock.now) is ScaleDecision.HOLD
    assert scaler.metrics.scale_down_vetoed >= 1
    assert len(fleet.replicas) == 2
    fleet.run(max_steps=400)
    fleet.close()


# ------------------------------------------------------ router mechanics
def test_router_scale_down_live_migrates_token_exact(gpt_setup):
    """The mechanism alone: scale_down drains the victim and restores
    its queued+running streams on the survivor, token-exact, counted
    as drain-path migration; the last replica refuses to retire."""
    model, variables = gpt_setup
    factory = _engine_factory(model, variables, max_queue_depth=16)
    fleet = FleetRouter([LocalReplica(0, factory),
                         LocalReplica(1, factory)],
                        affinity_block_size=8, affinity_blocks=1,
                        respawn=False)
    reqs = [(list(range(1, 9)), 6), (list(range(3, 10)), 5),
            ((np.arange(8) * 3 + 1) % 32, 7)]
    refs = [_ref_greedy(model, variables, p, n) for p, n in reqs]
    handles = [fleet.submit(p, n) for p, n in reqs]
    for _ in range(2):
        fleet.step()
    victim = max(fleet.replicas, key=lambda s: s.load)
    moved = fleet.scale_down(victim.replica_id)
    assert moved == victim.load or moved >= 1
    assert len(fleet.replicas) == 1
    assert fleet.metrics.scale_down_events == 1
    assert fleet.metrics.migrated_via_drain >= 1
    assert fleet.metrics.migrated_via_replay == 0
    fleet.run(max_steps=400)
    for h, ref in zip(handles, refs):
        assert h.state == RequestState.FINISHED
        assert h.tokens == ref, "stream diverged across scale-down"
    with pytest.raises(ValueError, match="no other available"):
        fleet.scale_down(fleet.replicas[0].replica_id)
    fleet.close()


def test_router_scale_up_revives_orphans(gpt_setup):
    """A scale-up during a total outage is also a recovery: parked
    orphans re-enter on the new replica and finish token-exact."""
    from pddl_tpu.serve import FaultKind, FaultPlan

    model, variables = gpt_setup
    clock = _FakeClock()
    plan = FaultPlan(sleep_fn=_no_sleep)

    def make():
        return ServeEngine(model, variables, max_slots=2, prefill_len=16,
                           max_queue_depth=8, prefix_cache_blocks=0,
                           fault_plan=plan, backoff_sleep=_no_sleep)

    fleet = FleetRouter([LocalReplica(0, make)], respawn=True,
                        clock=clock)
    p, n = list(range(1, 9)), 6
    ref = _ref_greedy(model, variables, p, n)
    h = fleet.submit(p, n)
    plan._sched[(2, "tick")] = [FaultKind.KILL]
    fleet.run(max_steps=20)
    assert fleet.metrics.requests_orphaned == 1
    assert not h.done
    factory = _engine_factory(model, variables)
    fleet.scale_up(LocalReplica(7, factory))
    assert fleet.metrics.scale_up_events == 1
    fleet.run(max_steps=200)
    assert h.state == RequestState.FINISHED
    assert h.tokens == ref
    assert h.replica_id == 7
    fleet.close()


def test_process_replica_wait_ready_timeout_is_typed():
    """A worker that never acks ready: wait_ready(timeout_s=...) and
    poll_ready() both raise the typed ReplicaSpawnTimeout (a
    ReplicaDied subclass, so every existing handler still catches it)
    and put the wedged process down."""

    class SleeperReplica(ProcessReplica):
        def _worker_argv(self):
            return [sys.executable, "-c", "import time; time.sleep(60)"]

    rep = SleeperReplica(0, {}, wait_ready=False, ready_timeout_s=0.2)
    try:
        with pytest.raises(ReplicaSpawnTimeout) as exc:
            rep.wait_ready(timeout_s=0.2)
        assert isinstance(exc.value, ReplicaDied)
        assert exc.value.waited_s >= 0.2
        deadline = time.monotonic() + 10
        while rep._proc.poll() is None and time.monotonic() < deadline:
            time.sleep(0.01)
        assert rep._proc.poll() is not None  # wedged spawn put down
    finally:
        if rep._proc.poll() is None:
            rep._proc.kill()
    rep2 = SleeperReplica(1, {}, wait_ready=False, ready_timeout_s=0.15)
    try:
        assert rep2.poll_ready() is False  # non-blocking while in budget
        deadline = time.monotonic() + 10
        with pytest.raises(ReplicaSpawnTimeout):
            while time.monotonic() < deadline:
                rep2.poll_ready()
                time.sleep(0.02)
    finally:
        if rep2._proc.poll() is None:
            rep2._proc.kill()


# ------------------------------------------------------- trace generator
def test_tracegen_is_seeded_and_diurnal():
    adapters = [f"a{i}" for i in range(6)]
    ev1, mean1 = diurnal_trace(3000, 64, seed=5, duration_s=100.0,
                               periods=1.0, peak_to_trough=6.0,
                               adapters=adapters)
    ev2, mean2 = diurnal_trace(3000, 64, seed=5, duration_s=100.0,
                               periods=1.0, peak_to_trough=6.0,
                               adapters=adapters)
    assert mean1 == mean2
    assert [(e["t"], e["session"], tuple(e["prompt"])) for e in ev1] \
        == [(e["t"], e["session"], tuple(e["prompt"])) for e in ev2]
    assert len(ev1) == 3000
    ts = np.array([e["t"] for e in ev1])
    # Sessions STARTING near the end spill their later turns past the
    # nominal day (think time is real time); the spill is bounded.
    assert (np.diff(ts) >= 0).all() and ts[0] >= 0 and ts[-1] <= 110.0
    # Diurnal shape (phase starts at the trough, peaks mid-trace): the
    # peak decile carries several times the trough deciles' arrivals.
    peak = ((ts >= 45) & (ts <= 55)).sum()
    trough = ((ts <= 5).sum() + (ts >= 95).sum())
    assert peak / max(trough, 1) > 2.5
    # Priority mix ~ 35/15/50 (sessions weight it by their turns).
    fracs = {p: np.mean([e["priority"] is p for e in ev1])
             for p in Priority}
    assert 0.2 < fracs[Priority.INTERACTIVE] < 0.5
    assert 0.05 < fracs[Priority.BATCH] < 0.3
    assert 0.35 < fracs[Priority.BEST_EFFORT] < 0.65
    for e in ev1:
        if e["priority"] is Priority.INTERACTIVE:
            assert e["deadline_s"] is not None
        else:
            assert e["deadline_s"] is None
    # Heavy-tail outputs: most replies short, a real tail, hard cap.
    news = np.array([e["new_tokens"] for e in ev1])
    assert np.percentile(news, 50) <= 12
    assert news.max() <= 48 and (news > 24).sum() >= 10
    # Zipf adapter popularity: the head adapter dominates, a no-adapter
    # slice survives, sessions keep their tenant across turns.
    counts = {}
    for e in ev1:
        counts[e["adapter"]] = counts.get(e["adapter"], 0) + 1
    named = {a: n for a, n in counts.items() if a is not None}
    assert max(named, key=named.get) == "a0"
    assert named["a0"] > 1.5 * named[min(named, key=named.get)]
    assert counts.get(None, 0) > 0
    by_session = {}
    for e in ev1:
        by_session.setdefault(e["session"], set()).add(e["adapter"])
    assert all(len(a) == 1 for a in by_session.values())


# --------------------------------------------------------- replay client
def test_replay_client_honors_retry_after_hints(gpt_setup):
    """The satellite fix: a rate-limited submit re-enters at
    ``now + retry_after_s`` and eventually lands — with hints off, the
    same events are terminally shed. Replica-hours are metered."""
    model, variables = gpt_setup
    factory = _engine_factory(model, variables, max_queue_depth=16)

    def fresh_fleet():
        fleet = FleetRouter(
            [LocalReplica(0, factory)], respawn=False,
            admission=AdmissionControl(
                rates={Priority.INTERACTIVE: 4.0}, burst=1.0))
        fleet.warmup()  # compile outside the replay's real-time window
        return fleet

    schedule = [dict(t=0.01 * i, session=f"s{i}",
                     prompt=list(range(1, 7)), new_tokens=2,
                     priority=Priority.INTERACTIVE, deadline_s=None,
                     adapter=None) for i in range(3)]
    fleet = fresh_fleet()
    rep = replay_trace(fleet, schedule, honor_hints=True, hang_s=30.0)
    fleet.close()
    assert rep.all_terminal
    assert len(rep.handles) == 3          # every event landed...
    assert rep.retried_after_hint >= 2    # ...two after their hints
    assert rep.hinted_rejects >= 2
    assert sum(rep.rejects.values()) == 0
    assert rep.wall_s >= 0.3              # the hints were real waits
    # One replica the whole run: replica-hours ~ wall clock.
    assert rep.replica_seconds == pytest.approx(rep.wall_s, rel=0.2)
    assert rep.goodput_tokens == 6
    assert rep.goodput_per_replica_hour > 0
    fleet = fresh_fleet()
    rep_blind = replay_trace(fleet, schedule, honor_hints=False,
                             hang_s=30.0)
    fleet.close()
    assert sum(rep_blind.rejects.values()) == 2  # dropped, the old way


def test_replay_meters_rung_time_and_scaled_fleet(gpt_setup):
    """An autoscaled fleet under a compressed diurnal burst: the
    replay meters replica-seconds through the scale events and the
    report's handles all settle; scale events show up in the
    exposition-facing counters."""
    model, variables = gpt_setup
    factory = _engine_factory(model, variables)
    fleet = FleetRouter(
        [LocalReplica(0, factory)], respawn=False,
        admission=AdmissionControl(
            detector_kw=dict(window_s=1.0, min_samples=4),
            brownout_kw=dict(high=0.6, low=0.05)))
    FleetAutoscaler(fleet, lambda rid: LocalReplica(rid, factory),
                    min_replicas=1, max_replicas=3,
                    up_pressure=0.1, down_pressure=0.02,
                    up_load=4.0, down_load=1.0,
                    up_hold_s=0.02, down_hold_s=0.4, cooldown_s=0.05)
    # prompt_cap must fit the engines' prefill_len (16): an oversize
    # prompt is a ValueError out of submit, and the replay client
    # deliberately lets that CRASH rather than count it as a shed.
    events, _ = diurnal_trace(60, 32, seed=3, duration_s=2.0,
                              periods=1.0, peak_to_trough=8.0,
                              prompt_base=6, prompt_cap=14,
                              max_turns=2, think_time_s=0.05,
                              new_tokens_base=2, new_tokens_scale=2.0,
                              new_tokens_cap=8)
    rep = replay_trace(fleet, events, honor_hints=True, hang_s=60.0)
    snap = fleet.metrics.snapshot()
    fleet.close()
    assert rep.all_terminal
    assert rep.replica_seconds > 0
    assert snap["scale_up_events"] >= 1
    assert len(rep.handles) + sum(rep.rejects.values()) == len(events)
