"""Storage-fault tolerance + multi-plane chaos campaigns (ISSUE 18),
CPU.

The contracts under test:

- **StorageFaultPlan**: seeded EIO/ENOSPC/torn-write/slow-fsync
  injection at exact ``(op, seq)`` coordinates through the journal's
  VFS shim — schedule validation, per-op call counters, the rate
  cascade, the injection cap, observer coordinates, ``quiesce()``.
- **WAL degradation**: transient storage errors retry with bounded
  backoff and never surface; persistent failure degrades the journal
  to NON_DURABLE (acks keep flowing, backlog retained in memory,
  alarmed through metrics/exposition/tracer) with rate-limited re-arm
  probes; ENOSPC skips the blind retry and forces an emergency
  checkpoint+rotate; a mid-checkpoint failure aborts with the
  checkpoint/prev pair still readable (the r10 newest-VERIFIED rule);
  a torn write's tail is repaired before any retry so replay stays
  exact; ``wal_bytes`` reports the last KNOWN size on fstat failure
  instead of lying "empty".
- **Seeded respawn jitter**: a same-instant mass-kill no longer
  schedules every breaker probe (or autoscaler spawn retry) at the
  same instant — subtractive jitter, so no probe ever fires LATER
  than the deterministic schedule.
- **3-seed storage-chaos matrix**: EIO storm over live token-delta
  fsyncs / ENOSPC at the checkpoint rotate / replica kill while
  NON_DURABLE — each followed by a router crash and
  ``FleetRouter.recover``, every stream token-exact vs the greedy
  oracle, zero recompiles on the recovered fleet, ``read_state``
  bit-stable across reads.
- **ChaosConductor campaigns** (marker ``chaosd``): seeded randomized
  multi-plane schedules (storage storm + hard kill + router crash)
  against unified and disaggregated+tiered fleets, judged by the
  invariant referee.
"""

import math
import os
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pddl_tpu.chaos import ChaosConductor, ReplicaChaos, local_kill
from pddl_tpu.models.gpt import tiny_gpt
from pddl_tpu.obs import RequestTracer, fleet_exposition, parse_prometheus_text
from pddl_tpu.serve import FaultPlan, ServeEngine
from pddl_tpu.serve.fleet import (
    BreakerState,
    CircuitBreaker,
    FleetAutoscaler,
    FleetRouter,
    LocalReplica,
    ReplicaSpawnTimeout,
    RouterJournal,
)
from pddl_tpu.serve.fleet import journal as journal_io
from pddl_tpu.serve.request import RequestState
from pddl_tpu.utils.faults import (
    StorageFaultKind,
    StorageFaultPlan,
    StorageFaultSpec,
)
from conftest import ref_greedy as _ref_greedy, FakeClock

pytestmark = pytest.mark.storage

BS = 8


@pytest.fixture(scope="module")
def gpt_setup():
    model = tiny_gpt(vocab_size=32, max_len=64)
    prompt = jnp.ones((1, 8), jnp.int32)
    params = model.init(jax.random.key(0), prompt, train=False)["params"]
    return model, {"params": params}


def _no_sleep(_):
    pass


def _engine_factory(model, variables, plan=None):
    def make():
        return ServeEngine(model, variables, max_slots=2, prefill_len=16,
                           fault_plan=plan, max_queue_depth=64,
                           prefix_cache_blocks=0,
                           backoff_sleep=_no_sleep)
    return make


def _workload(seed, n_requests=4, *, min_len=8, max_len=13, n_new=8):
    """Unique seeded prompts (uniqueness keys the token-exact check
    across a crash) with one fixed continuation length, so the oracle
    compiles a handful of shapes, not one per stream."""
    rng = np.random.default_rng(seed)
    reqs, seen = [], set()
    while len(reqs) < n_requests:
        plen = int(rng.integers(min_len, max_len))
        p = rng.integers(0, 32, size=plen).astype(np.int32)
        key = tuple(int(t) for t in p)
        if key in seen:
            continue
        seen.add(key)
        reqs.append((p, n_new))
    return reqs


# ------------------------------------------------------ StorageFaultPlan
def test_storage_plan_validation():
    with pytest.raises(ValueError, match="eio_rate"):
        StorageFaultPlan(eio_rate=1.2)
    with pytest.raises(ValueError, match="sum"):
        StorageFaultPlan(eio_rate=0.6, torn_rate=0.6)
    with pytest.raises(ValueError, match="unknown storage op"):
        StorageFaultPlan(ops=("scribble",))
    with pytest.raises(ValueError, match="unknown scheduled op"):
        StorageFaultPlan(scheduled=(
            StorageFaultSpec("scribble", 0, StorageFaultKind.EIO),))
    with pytest.raises(ValueError, match="seq"):
        StorageFaultPlan(scheduled=(
            StorageFaultSpec("write", -1, StorageFaultKind.EIO),))
    with pytest.raises(ValueError, match="count"):
        StorageFaultPlan(scheduled=(
            StorageFaultSpec("write", 0, StorageFaultKind.EIO, count=0),))
    with pytest.raises(ValueError, match="unknown storage op"):
        StorageFaultPlan().check("scribble")


def test_storage_plan_scheduled_coordinates_fire_exactly():
    plan = StorageFaultPlan(scheduled=(
        StorageFaultSpec("write", 1, StorageFaultKind.EIO, count=2),))
    coords = []
    plan.on_inject = lambda seq, op, kind: coords.append((seq, op, kind))
    assert plan.check("write") is None            # seq 0: clean
    for _ in range(2):                            # seqs 1-2: the spec
        with pytest.raises(OSError):
            plan.check("write")
    assert plan.check("write") is None            # seq 3: spent
    assert plan.check("fsync") is None            # other ops untouched
    assert coords == [(1, "write", "eio"), (2, "write", "eio")]
    assert plan.calls["write"] == 4 and plan.calls["fsync"] == 1
    assert plan.injected[StorageFaultKind.EIO] == 2
    assert plan.total_injected == 2


def test_storage_plan_rate_cascade_cap_and_quiesce():
    plan = StorageFaultPlan(seed=3, eio_rate=1.0,
                            max_random_injections=2)
    for _ in range(2):
        with pytest.raises(OSError):
            plan.check("fsync")
    assert plan.check("fsync") is None  # cap: chaos runs terminate
    assert plan.total_injected == 2

    slept = []
    slow = StorageFaultPlan(seed=0, slow_rate=1.0, slow_s=0.123,
                            sleep_fn=slept.append)
    assert slow.check("fsync") is None  # SLOW returns normally...
    assert slept == [0.123]             # ...after the injected stall
    slow.quiesce()
    slept.clear()
    assert slow.check("fsync") is None
    assert slept == []                  # repaired disk: rates cleared


# ------------------------------------------------- journal degradation
def _journal(d, sp=None, **kw):
    kw.setdefault("retry_backoff_s", 0.0)
    kw.setdefault("sleep_fn", _no_sleep)
    return RouterJournal(str(d), storage_plan=sp, **kw)


def test_transient_write_error_retries_without_degrading(tmp_path):
    sp = StorageFaultPlan(scheduled=(
        StorageFaultSpec("write", 0, StorageFaultKind.EIO),))
    j = _journal(tmp_path / "wal", sp)
    j.append({"k": 1}, durable=True)
    assert not j.non_durable
    assert j.storage_errors == 1       # counted, then retried past
    assert j.degraded_events == 0
    assert len(list(journal_io.iter_wal_records(j.wal_path))) == 1
    j.close()


def test_persistent_fsync_failure_degrades_then_rearms(tmp_path):
    clock = FakeClock(0.0)
    sp = StorageFaultPlan(eio_rate=1.0, ops=("fsync",))
    events = []
    j = _journal(tmp_path / "wal", sp, retry_limit=2,
                 rearm_interval_s=1.0, clock=clock)
    j.on_storage_event = lambda ev, detail: events.append(ev)
    j.append({"k": 1}, durable=True)   # NEVER raises: degrades instead
    assert j.non_durable and j.degraded_events == 1
    assert j.storage_errors == 3       # retry_limit + 1 attempts
    assert "journal_degraded" in events
    j.append({"k": 2})                 # acks keep flowing
    j.append({"k": 3})
    # Probes are rate-limited: ticks inside the interval do not hammer
    # the dead disk.
    errs = j.storage_errors
    for _ in range(5):
        j.tick()
    assert j.storage_errors == errs
    clock.now = 1.5
    j.tick()                           # due probe, disk still dead
    assert j.storage_errors == errs + 1 and j.non_durable
    sp.quiesce()                       # the disk comes back
    clock.now = 3.0
    j.tick()                           # due probe -> full flush+fsync
    assert not j.non_durable and j.rearms == 1
    assert "journal_rearmed" in events
    # The retained backlog became durable at re-arm: nothing was lost.
    assert len(list(journal_io.iter_wal_records(j.wal_path))) == 3
    j.close()


def test_enospc_forces_emergency_checkpoint_that_reclaims(tmp_path):
    sp = StorageFaultPlan(scheduled=(
        StorageFaultSpec("write", 1, StorageFaultKind.ENOSPC),))
    j = _journal(tmp_path / "wal", sp)
    j.append({"k": 1}, durable=True)
    j.append({"k": 2}, durable=True)   # write seq 1: disk full
    assert j.emergency_checkpoint_due  # no blind retry on a full disk
    assert j.non_durable
    assert j.storage_errors == 1       # ENOSPC broke out of the retries
    assert j.checkpoint([(1, {"prompt": [1], "tokens": []})], next_rid=2)
    assert not j.emergency_checkpoint_due
    assert not j.non_durable and j.rearms == 1
    assert os.path.exists(j.wal_prev_path)  # the rotate reclaimed space
    cp = journal_io.load_checkpoint(str(tmp_path / "wal"))
    assert cp is not None and cp["next_rid"] == 2
    assert j.records_since_checkpoint == 0
    j.close()


def test_checkpoint_failure_keeps_newest_verified_pair(tmp_path):
    # Replace seqs: cp1 consumes 0 (promote) + 1 (rotate); cp2 demotes
    # at 2, then EIO at 3 kills the promotion — the worst interleaving.
    sp = StorageFaultPlan(scheduled=(
        StorageFaultSpec("replace", 3, StorageFaultKind.EIO),))
    j = _journal(tmp_path / "wal", sp)
    d = str(tmp_path / "wal")
    j.append({"k": 1}, durable=True)
    assert j.checkpoint([(1, {"a": 1})], next_rid=2)
    j.append({"k": 2}, durable=True)
    events = []
    j.on_storage_event = lambda ev, detail: events.append(ev)
    assert not j.checkpoint([(1, {"a": 1}), (2, {"b": 2})], next_rid=3)
    assert "journal_checkpoint_failed" in events
    assert j.non_durable
    # The r10 rule: the pair still holds a VERIFIED checkpoint (cp1,
    # demoted to the prev slot) and the WAL records since it — the
    # failed cycle lost nothing.
    cp = journal_io.load_checkpoint(d)
    assert cp is not None and cp["next_rid"] == 2
    assert [rec["k"] for _, rec in
            journal_io.iter_wal_records(j.wal_path)] == [2]
    # The disk recovers: the next cycle completes and re-arms.
    assert j.checkpoint([(1, {"a": 1}), (2, {"b": 2})], next_rid=3)
    assert not j.non_durable and j.rearms == 1
    assert journal_io.load_checkpoint(d)["next_rid"] == 3
    j.close()


def test_torn_write_tail_repaired_before_retry(tmp_path):
    sp = StorageFaultPlan(scheduled=(
        StorageFaultSpec("write", 0, StorageFaultKind.TORN),))
    j = _journal(tmp_path / "wal", sp)
    j.append({"k": 1}, durable=True)
    assert not j.non_durable
    assert sp.injected[StorageFaultKind.TORN] == 1
    # The half-written garbage was truncated before the retry: the
    # file holds exactly one readable frame, no buried tail.
    assert [rec["k"] for _, rec in
            journal_io.iter_wal_records(j.wal_path)] == [1]
    assert os.path.getsize(j.wal_path) == j.wal_bytes
    j.close()


def test_wal_bytes_returns_last_known_on_fstat_failure(tmp_path):
    sp = StorageFaultPlan(scheduled=(
        StorageFaultSpec("fstat", 1, StorageFaultKind.EIO),))
    j = _journal(tmp_path / "wal", sp)
    j.append({"k": 1}, durable=True)
    wb = j.wal_bytes
    assert wb > 0
    errs = j.storage_errors
    assert j.wal_bytes == wb           # last KNOWN size, not 0
    assert j.storage_errors == errs + 1  # ...and the error is counted
    assert j.wal_bytes == wb           # fstat healthy again
    assert j.storage_errors == errs + 1
    j.close()


# ------------------------------------------------- router integration
def test_router_surfaces_degradation_and_rearm(gpt_setup, tmp_path):
    model, variables = gpt_setup
    sp = StorageFaultPlan(eio_rate=1.0, ops=("fsync",))
    j = _journal(tmp_path / "wal", sp, retry_limit=1,
                 rearm_interval_s=0.0)
    tracer = RequestTracer()
    fleet = FleetRouter(
        [LocalReplica(i, _engine_factory(model, variables))
         for i in range(2)],
        journal=j, tracer=tracer, affinity_block_size=BS,
        affinity_blocks=1, respawn=False)
    reqs = _workload(11, n_requests=2, n_new=4)
    refs = {tuple(int(t) for t in p): _ref_greedy(model, variables, p, n)
            for p, n in reqs}
    handles = [fleet.submit(p, n) for p, n in reqs]
    fleet.step()
    m = fleet.metrics
    assert j.non_durable
    assert m.journal_degraded_events == 1
    assert m.journal_storage_errors >= 1
    assert tracer.events_named("journal_degraded")
    samples, types = parse_prometheus_text(fleet_exposition(fleet))
    assert samples[("pddl_fleet_journal_non_durable", ())] == 1.0
    assert types["pddl_fleet_journal_non_durable"] == "gauge"
    for key in ("journal_storage_errors", "journal_degraded_events",
                "journal_rearms"):
        name = f"pddl_fleet_{key}_total"
        assert types[name] == "counter"
        assert samples[(name, ())] == float(getattr(m, key))
    # The disk comes back: the next tick's probe re-arms, and the
    # degraded window cost the streams nothing.
    sp.quiesce()
    fleet.run(max_steps=500)
    assert not j.non_durable
    assert m.journal_rearms >= 1
    assert tracer.events_named("journal_rearmed")
    samples, _ = parse_prometheus_text(fleet_exposition(fleet))
    assert samples[("pddl_fleet_journal_non_durable", ())] == 0.0
    for h in handles:
        assert h.state == RequestState.FINISHED
        assert h.tokens == refs[tuple(int(t) for t in h.request.prompt)]
    fleet.close()
    # Unarmed fleet: present-but-unobserved, NaN.
    bare = FleetRouter(
        [LocalReplica(0, _engine_factory(model, variables))],
        respawn=False)
    samples, _ = parse_prometheus_text(fleet_exposition(bare))
    assert math.isnan(samples[("pddl_fleet_journal_non_durable", ())])
    bare.close()


# --------------------------------------------- seeded respawn jitter
def test_breaker_jitter_is_subtractive_seeded_and_validated():
    def opened(seed, frac):
        b = CircuitBreaker(failure_threshold=1, backoff_base_s=2.0,
                           backoff_max_s=30.0, jitter_frac=frac,
                           seed=seed)
        b.record_failure(100.0)
        assert b.state is BreakerState.OPEN
        return b.open_until_s
    # Subtractive: never LATER than the deterministic schedule.
    assert opened(None, 0.0) == 102.0
    a, b = opened(0, 0.25), opened(1, 0.25)
    assert 100.0 < a <= 102.0 and 100.0 < b <= 102.0
    assert a != b                      # per-seed desynchronization
    assert opened(7, 0.25) == opened(7, 0.25)  # deterministic per seed
    with pytest.raises(ValueError, match="jitter_frac"):
        CircuitBreaker(jitter_frac=1.0)


def test_same_instant_double_kill_respawn_probes_diverge(gpt_setup):
    """The respawn-herd pin: both replicas die in the SAME router step
    (same clock instant), yet their HALF_OPEN probes land at different
    instants — the router arms per-replica seeded jitter fleet-wide.
    The orphaned streams still revive token-exact."""
    model, variables = gpt_setup
    clock = FakeClock(0.0)
    plans = [FaultPlan(sleep_fn=_no_sleep) for _ in range(2)]
    fleet = FleetRouter(
        [LocalReplica(i, _engine_factory(model, variables, plans[i]))
         for i in range(2)],
        affinity_block_size=BS, affinity_blocks=1, respawn=True,
        clock=clock)
    reqs = _workload(21, n_requests=2, n_new=6)
    refs = {tuple(int(t) for t in p): _ref_greedy(model, variables, p, n)
            for p, n in reqs}
    handles = [fleet.submit(p, n) for p, n in reqs]
    for _ in range(2):
        fleet.step()
    for plan in plans:
        local_kill(plan)
    fleet.step()                       # both die at the same instant
    slots = list(fleet.replicas)
    assert all(s.breaker.state is BreakerState.OPEN for s in slots)
    assert all(s.breaker.jitter_frac > 0.0 for s in slots)
    opens = [s.breaker.open_until_s for s in slots]
    assert opens[0] != opens[1]        # the herd is desynchronized
    assert all(clock.now < o <= clock.now + 0.5 for o in opens)
    clock.now += 1.0                   # past both (jittered) probes
    fleet.run(max_steps=800)
    for h in handles:
        assert h.state == RequestState.FINISHED
        assert h.tokens == refs[tuple(int(t) for t in h.request.prompt)]
    fleet.close()


def test_autoscaler_spawn_retry_jitter_diverges(gpt_setup):
    model, variables = gpt_setup
    fleet = FleetRouter(
        [LocalReplica(0, _engine_factory(model, variables))],
        respawn=False)
    mk = lambda rid: LocalReplica(rid, _engine_factory(model, variables))

    def failed_retry_at(seed, frac):
        s = FleetAutoscaler(fleet, mk, min_replicas=1, max_replicas=2,
                            spawn_backoff_base_s=4.0,
                            spawn_backoff_max_s=16.0,
                            spawn_jitter_frac=frac,
                            spawn_jitter_seed=seed)
        s._spawn_failed(100.0, 9, ReplicaSpawnTimeout(9, 1.0))
        return s._spawn_retry_at

    assert failed_retry_at(None, 0.0) == 104.0  # exact schedule default
    a, b = failed_retry_at(0, 0.5), failed_retry_at(1, 0.5)
    assert 100.0 < a <= 104.0 and 100.0 < b <= 104.0
    assert a != b
    with pytest.raises(ValueError, match="spawn_jitter_frac"):
        FleetAutoscaler(fleet, mk, min_replicas=1, max_replicas=2,
                        spawn_jitter_frac=1.0)
    fleet.close()


# ------------------------------------- 3-seed storage-chaos matrix
def _chaos_fleet(model, variables, d, sp, **journal_kw):
    journal_kw.setdefault("fsync_batch_records", 2)
    plans = [FaultPlan(sleep_fn=_no_sleep) for _ in range(2)]
    j = _journal(d, sp, retry_limit=1, rearm_interval_s=0.0,
                 **journal_kw)
    fleet = FleetRouter(
        [LocalReplica(i, _engine_factory(model, variables, plans[i]))
         for i in range(2)],
        journal=j, affinity_block_size=BS, affinity_blocks=1,
        respawn=False)
    return fleet, plans, j


@pytest.mark.parametrize("seed,scenario", [
    (0, "eio_storm"),          # every disk op EIOs while tokens flow
    (1, "enospc_rotate"),      # disk full exactly at the WAL rotate
    (2, "kill_non_durable"),   # replica hard-death inside the window
])
def test_storage_chaos_recovery_token_exact(gpt_setup, tmp_path, seed,
                                            scenario):
    model, variables = gpt_setup
    d = tmp_path / "wal"
    if scenario == "enospc_rotate":
        sp = StorageFaultPlan(seed=seed, scheduled=(
            StorageFaultSpec("replace", 1, StorageFaultKind.ENOSPC),))
        fleet, plans, j = _chaos_fleet(model, variables, d, sp,
                                       checkpoint_every_records=6)
    else:
        sp = StorageFaultPlan(seed=seed)
        fleet, plans, j = _chaos_fleet(model, variables, d, sp)
    reqs = _workload(seed)
    refs = {tuple(int(t) for t in p): _ref_greedy(model, variables, p, n)
            for p, n in reqs}
    handles = [fleet.submit(p, n) for p, n in reqs]
    for _ in range(2):
        fleet.step()                   # admissions are durable
    if scenario == "eio_storm":
        sp._rates = (1.0, 0.0, 0.0, 0.0)
        for _ in range(4):
            fleet.step()
        assert j.non_durable
        assert fleet.metrics.journal_degraded_events >= 1
    elif scenario == "enospc_rotate":
        for _ in range(6):
            fleet.step()               # checkpoint_due fires in here
        assert sp.injected[StorageFaultKind.ENOSPC] == 1
        assert not j.non_durable       # rotate failure is non-fatal
        assert journal_io.load_checkpoint(str(d)) is not None
    else:                              # kill while NON_DURABLE
        sp._rates = (1.0, 0.0, 0.0, 0.0)
        for _ in range(3):
            fleet.step()
        assert j.non_durable
        local_kill(plans[1])
        for _ in range(2):
            fleet.step()               # replica 1 dies mid-degradation
    finished_pre = [(tuple(int(t) for t in p), list(h.tokens))
                    for (p, _), h in zip(reqs, handles)
                    if h.done and h.state == RequestState.FINISHED]
    # The router crash: abandon it un-closed (what SIGKILL leaves) and
    # recover over the same WAL directory with FRESH replicas. The fold
    # must be bit-stable across reads first (pure function of the dir).
    sp.quiesce()
    assert journal_io.read_state(str(d)) == journal_io.read_state(str(d))
    recovered, revived = FleetRouter.recover(
        str(d),
        [LocalReplica(10 + i, _engine_factory(model, variables))
         for i in range(2)],
        affinity_block_size=BS, affinity_blocks=1, respawn=False)
    for _ in range(600):
        recovered.step()
        if all(fh.done for fh in revived.values()):
            break
    # Token-exact: revived streams continue from the durable mirror and
    # land on the oracle; the NON_DURABLE loss window (fsync-batched
    # token deltas) only shortens the mirror, never corrupts it.
    for fh in revived.values():
        assert fh.state == RequestState.FINISHED
        assert fh.tokens == refs[tuple(int(t) for t in fh.request.prompt)]
    for key, toks in finished_pre:
        assert toks == refs[key]
    counts = recovered.compile_counts()
    assert counts and all(v == 1 for v in counts.values())
    recovered.close()


# --------------------------------------------- conductor campaigns
@pytest.mark.chaosd
@pytest.mark.parametrize("seed", [0, 1])
def test_conductor_campaign_unified_fleet(gpt_setup, tmp_path, seed):
    """Composed planes over a unified 2-replica fleet: a storage storm
    + a seeded hard kill + a router crash in one campaign, all seven
    referee invariants green."""
    model, variables = gpt_setup
    plans = {}
    state = {"base": 0}

    def make_replicas():
        base, state["base"] = state["base"], state["base"] + 10
        reps = []
        for k in range(2):
            plan = FaultPlan(sleep_fn=_no_sleep)
            plans[base + k] = plan
            reps.append(LocalReplica(
                base + k, _engine_factory(model, variables, plan)))
        return reps

    def make_chaos(fleet):
        return [ReplicaChaos(
                    replica_id=int(s.replica_id),
                    plan=plans[int(s.replica_id)],
                    kill_fn=(lambda p=plans[int(s.replica_id)]:
                             local_kill(p)))
                for s in fleet.replicas]

    sp = StorageFaultPlan(seed=seed)
    cond = ChaosConductor(
        make_replicas, make_chaos,
        lambda p, n: _ref_greedy(model, variables, p, n),
        journal_dir=str(tmp_path / "wal"), storage_plan=sp,
        router_kw=dict(affinity_block_size=BS, affinity_blocks=1,
                       respawn=False),
        journal_kw=dict(fsync_batch_records=2, retry_limit=1,
                        retry_backoff_s=0.0, rearm_interval_s=0.0,
                        sleep_fn=_no_sleep),
        recovery_bound_s=30.0, seed=seed)
    report = cond.run(_workload(100 + seed, n_requests=5),
                      planes=("device", "storage", "kill", "router"),
                      horizon=30, kills=1, max_wall_s=90.0)
    assert report.ok, report.violations
    kinds = [a.kind for a in report.actions]
    assert {"storm_on", "kill", "router_crash"} <= set(kinds)
    assert report.recovery_s is not None and report.recovery_s <= 30.0
    assert report.injected.get("storage", 0) >= 1  # the storm landed


@pytest.mark.chaosd
def test_conductor_campaign_disagg_tier_fleet(gpt_setup, tmp_path):
    """The campaign over a role-split fleet with the host tier armed:
    a storage storm degrades the WAL while prefill->decode hand-offs
    run, then the router crashes — recovery re-admits through the
    prefill pool and every invariant (pins balanced across the radix
    trees included) holds."""
    model, variables = gpt_setup
    state = {"base": 0}

    def _factory(host):
        def make():
            return ServeEngine(model, variables, max_slots=2,
                               prefill_len=32, prefix_cache_blocks=24,
                               prefix_block_size=BS, prefix_chunk=BS,
                               host_tier=host, max_queue_depth=64,
                               backoff_sleep=_no_sleep)
        return make

    def make_replicas():
        base, state["base"] = state["base"], state["base"] + 10
        return [LocalReplica(base, _factory(1 << 24), role="prefill"),
                LocalReplica(base + 1, _factory(1 << 24), role="decode")]

    def make_chaos(fleet):
        # No per-replica kill plane: killing the only replica of a
        # role starves its pool. The router-crash plane abandons the
        # whole fleet instead — the mass-failure this fleet shape
        # actually fears.
        return [ReplicaChaos(replica_id=int(s.replica_id))
                for s in fleet.replicas]

    sp = StorageFaultPlan(seed=5)
    cond = ChaosConductor(
        make_replicas, make_chaos,
        lambda p, n: _ref_greedy(model, variables, p, n),
        journal_dir=str(tmp_path / "wal"), storage_plan=sp,
        router_kw=dict(affinity_block_size=BS, affinity_blocks=1,
                       respawn=False),
        journal_kw=dict(fsync_batch_records=2, retry_limit=1,
                        retry_backoff_s=0.0, rearm_interval_s=0.0,
                        sleep_fn=_no_sleep),
        recovery_bound_s=30.0, seed=5)
    report = cond.run(
        _workload(7, n_requests=4, min_len=12, max_len=20, n_new=5),
        planes=("storage", "router"), horizon=30, kills=0,
        max_wall_s=90.0)
    assert report.ok, report.violations
    assert report.invariants["pins_balanced"]
    assert "router_crash" in [a.kind for a in report.actions]
    assert report.recovery_s is not None


# The worker-subprocess model config (mirrors the ctrlplane process
# fleet): the oracle is the worker's OWN engine built from the same
# cfg, so parent and child provably share params.
_WORKER_CFG = dict(vocab=32, max_len=64, embed_dim=32, depth=1, heads=2,
                   slots=4, prefill_len=16, max_queue_depth=64,
                   param_seed=0, prefix_cache_blocks=0)


@pytest.mark.chaosd
@pytest.mark.parametrize("seed", [0, 1, 2])
def test_conductor_campaign_seven_planes_process_fleet(tmp_path, seed):
    """ISSUE 20 acceptance: 3-seed campaigns drawing all SEVEN planes
    — device, wire, storage, gray, kill, router, partition — over a
    fleet of REAL worker processes, every referee invariant green
    including ``single_writer`` (no two routers' commands accepted in
    the same epoch interval: 100% of the deposed primary's
    post-partition probes come back typed EpochFenced rejects).

    The partition plane fires strictly before the router-crash window,
    so the promoted standby is the router the crash plane then
    SIGKILLs — hot failover and cold recovery compose in one campaign.
    The device plane rides along declared-but-inert: its injection
    surface is an in-process engine FaultPlan, which does not exist
    behind the worker pipe (the unified local-fleet campaign above
    owns that coverage)."""
    import subprocess
    import sys

    from pddl_tpu.serve.fleet import ProcessReplica, WireFaultPlan
    from pddl_tpu.serve.fleet.worker import build_engine

    wire_plans = {}
    state = {"base": 0}

    def make_replicas():
        base, state["base"] = state["base"], state["base"] + 10
        reps = []
        for k in range(2):
            rid = base + k
            wp = WireFaultPlan(1000 * seed + rid, corrupt_rate=0.01,
                               duplicate_rate=0.01, drop_rate=0.005)
            wire_plans[rid] = wp
            reps.append(ProcessReplica(
                rid, {**_WORKER_CFG, "replica_id": rid},
                python=sys.executable, stderr=subprocess.DEVNULL,
                wire_fault_plan=wp))
        return reps

    def make_chaos(fleet):
        return [ReplicaChaos(replica_id=int(s.replica_id),
                             wire_plan=wire_plans.get(int(s.replica_id)),
                             slow_fn=s.driver.set_tick_delay,
                             kill_fn=s.driver.kill)
                for s in fleet.replicas]

    eng = build_engine(_WORKER_CFG)
    sp = StorageFaultPlan(seed=seed)
    cond = ChaosConductor(
        make_replicas, make_chaos,
        lambda p, n: _ref_greedy(eng.model, {"params": eng._params},
                                 p, n),
        journal_dir=str(tmp_path / "wal"), storage_plan=sp,
        router_kw=dict(affinity_block_size=BS, affinity_blocks=1,
                       respawn=False),
        journal_kw=dict(fsync_batch_records=2, retry_limit=1,
                        retry_backoff_s=0.0, rearm_interval_s=0.0,
                        sleep_fn=_no_sleep),
        recovery_bound_s=60.0, seed=seed)
    report = cond.run(
        _workload(300 + seed, n_requests=4),
        planes=("device", "wire", "storage", "gray", "kill", "router",
                "partition"),
        horizon=30, kills=1, pace_s=0.01, max_wall_s=240.0)
    assert report.ok, report.violations
    assert report.invariants["single_writer"]
    assert "single_writer" not in " ".join(report.skipped)
    kinds = [a.kind for a in report.actions]
    assert {"partition", "router_crash", "kill", "storm_on",
            "slow_on"} <= set(kinds)
    assert report.failover_s is not None and report.failover_s < 10.0
    assert report.recovery_s is not None
    assert report.injected.get("wire", 0) >= 1    # the storm was real
