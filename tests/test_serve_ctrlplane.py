"""Control-plane durability (`fleet/journal.py`, `fleet/transport.py`,
gray-failure machinery in `fleet/health.py`/`fleet/router.py`), CPU.

The contracts under test (ISSUE 14):

- **Router WAL + crash-exact recovery**: a 3-seed matrix of router
  "SIGKILLs" at seeded WAL-record coordinates (mid-admission,
  mid-migration, mid-stream, mid-chain-pull) — every acked in-flight
  stream revives through ``FleetRouter.recover`` and finishes
  token-identical to the unkilled oracle, with zero recompiles on the
  recovered replicas. Torn WAL tails and corrupted checkpoints restore
  from the newest VERIFIED state (the r10 discipline).
- **Framed transport**: length+CRC+seq framing rejects every corrupt/
  truncated frame (zero corrupt frames accepted is a codec property),
  dedups duplicates, heals gaps through bounded resend — and a seeded
  :class:`WireFaultPlan` storm over real worker processes leaves every
  stream terminal and token-exact. Oversized frames are TYPED rejects
  on both pipe ends, never a crash or an unbounded buffer.
- **Gray failure**: the latency-quantile detector suspects a replica
  whose per-tick p95 drifts from its own baseline; interactive
  submissions hedge to a healthy sibling with first-result-wins
  cancellation, and ``gray_drain`` retires the suspect through the
  r16 ``scale_down`` live-migration path before it hard-fails.
- **Observability**: the new counters/gauges render through
  ``fleet_exposition`` and re-parse through the strict Prometheus
  referee, in both armed and unarmed fleets.
"""

import json
import math
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pddl_tpu.models.gpt import tiny_gpt
from pddl_tpu.obs import RequestTracer, fleet_exposition, parse_prometheus_text
from pddl_tpu.serve import FaultKind, FaultPlan, ServeEngine
from pddl_tpu.serve.fleet import (
    FleetRouter,
    FrameReceiver,
    FrameSender,
    GrayDetector,
    LocalReplica,
    RouterJournal,
    WireFaultKind,
    WireFaultPlan,
    WireFaultSpec,
)
from pddl_tpu.serve.fleet import journal as journal_io
from pddl_tpu.serve.fleet.transport import (
    FrameError,
    decode_frame,
    encode_frame,
)
from pddl_tpu.serve.request import Priority, RequestState
from pddl_tpu.utils.faults import KillPoint
from conftest import ref_greedy as _ref_greedy

pytestmark = pytest.mark.ctrlplane


@pytest.fixture(scope="module")
def gpt_setup():
    model = tiny_gpt(vocab_size=32, max_len=64)
    prompt = jnp.ones((1, 8), jnp.int32)
    params = model.init(jax.random.key(0), prompt, train=False)["params"]
    return model, {"params": params}


def _no_sleep(_):
    pass


def _local_fleet(model, variables, n, *, with_plans=False,
                 max_queue_depth=64, **router_kw):
    plans = [FaultPlan(sleep_fn=_no_sleep) if with_plans else None
             for _ in range(n)]

    def factory(plan):
        def make():
            return ServeEngine(model, variables, max_slots=2,
                               prefill_len=16, fault_plan=plan,
                               max_queue_depth=max_queue_depth,
                               prefix_cache_blocks=0,
                               backoff_sleep=_no_sleep)
        return make

    replicas = [LocalReplica(i, factory(plans[i])) for i in range(n)]
    fleet = FleetRouter(replicas, affinity_block_size=8,
                        affinity_blocks=1, respawn=False, **router_kw)
    return fleet, plans


def _fresh_replicas(model, variables, n):
    def factory():
        return ServeEngine(model, variables, max_slots=2,
                           prefill_len=16, max_queue_depth=64,
                           prefix_cache_blocks=0,
                           backoff_sleep=_no_sleep)
    return [LocalReplica(i, factory) for i in range(n)]


def _workload(n_requests, seed=0, vocab=32):
    rng = np.random.default_rng(seed)
    reqs = []
    for _ in range(n_requests):
        plen = int(rng.integers(6, 15))
        reqs.append((rng.integers(0, vocab, size=plen).astype(np.int32),
                     int(rng.integers(3, 8))))
    return reqs


# ------------------------------------------------------ framed transport
def test_frame_codec_roundtrip_and_typed_rejects():
    payload = json.dumps({"ev": "tokens", "toks": [[3, [1, 2]]]}).encode()
    frame = encode_frame(7, payload)
    assert frame.endswith(b"\n")
    seq, got = decode_frame(frame.rstrip(b"\n"))
    assert (seq, got) == (7, payload)
    # Corruption anywhere fails validation — never a mis-parse.
    for idx in (1, 10, len(frame) - 3):
        mangled = bytearray(frame.rstrip(b"\n"))
        mangled[idx] ^= 0x40
        with pytest.raises(FrameError):
            decode_frame(bytes(mangled))
    with pytest.raises(FrameError):
        decode_frame(frame.rstrip(b"\n")[: len(frame) // 2])  # truncated
    with pytest.raises(FrameError):
        decode_frame(b'{"ev": "raw json line"}')  # unframed


def test_receiver_orders_dedups_and_reports_gaps():
    sender = FrameSender()
    frames = [sender.encode(json.dumps({"n": i}).encode())
              for i in range(1, 6)]
    rx = FrameReceiver()
    assert [json.loads(p)["n"] for p in rx.feed(frames[0].rstrip(b"\n"))] \
        == [1]
    # A duplicate of a delivered frame drops silently.
    assert rx.feed(frames[0].rstrip(b"\n")) == []
    assert rx.stats["dups"] == 1
    # Out-of-order arrival buffers until the gap fills, then releases
    # everything in order.
    assert rx.feed(frames[2].rstrip(b"\n")) == []
    assert rx.has_gap and rx.expected_seq == 2
    out = rx.feed(frames[1].rstrip(b"\n"))
    assert [json.loads(p)["n"] for p in out] == [2, 3]
    assert not rx.has_gap
    # A corrupt frame is refused (CRC) and the sender's replay buffer
    # can answer the resend request for it.
    bad = bytearray(frames[3].rstrip(b"\n"))
    bad[-2] ^= 0x5A
    assert rx.feed(bytes(bad)) == []
    assert rx.stats["crc_rejects"] == 1
    resent = sender.resend_from(rx.expected_seq)
    assert len(resent) == 2  # frames 4 and 5 still buffered
    for f in resent:
        rx.feed(f.rstrip(b"\n"))
    assert rx.expected_seq == 6 and not rx.has_gap


def test_receiver_oversize_is_typed_and_consumes_the_seq_slot():
    sender = FrameSender()
    small = sender.encode(b'{"n": 1}')
    big = sender.encode(b'{"blob": "' + b"x" * 4096 + b'"}')
    after = sender.encode(b'{"n": 3}')
    rx = FrameReceiver(max_frame_bytes=1024)
    assert len(rx.feed(small.rstrip(b"\n"))) == 1
    # The oversized frame is REFUSED by policy but its sequence slot
    # is consumed — resending the same bytes could never heal it, so
    # it must not wedge the gap machinery.
    assert rx.feed(big.rstrip(b"\n")) == []
    assert rx.stats["too_large"] == 1
    assert not rx.has_gap
    assert len(rx.feed(after.rstrip(b"\n"))) == 1
    assert rx.expected_seq == 4


def test_wire_fault_plan_seeded_and_scheduled():
    def run(seed):
        plan = WireFaultPlan(seed, corrupt_rate=0.2, drop_rate=0.1,
                             duplicate_rate=0.1, sleep_fn=_no_sleep)
        out = []
        for i in range(1, 41):
            frame = encode_frame(i, b'{"n": %d}' % i)
            out.append(tuple(plan.apply("ev", i, frame)))
        return out, dict(plan.injected)

    a, inj_a = run(3)
    b, inj_b = run(3)
    c, _ = run(4)
    assert a == b, "same seed must mangle the same frames"
    assert a != c
    assert sum(inj_a.values()) > 0
    # Scheduled coordinates fire exactly once at (step, site).
    plan = WireFaultPlan(0, scheduled=[
        WireFaultSpec(2, "cmd", WireFaultKind.DROP)])
    f1, f2 = encode_frame(1, b"{}"), encode_frame(2, b"{}")
    assert plan.apply("cmd", 1, f1) == [f1]
    assert plan.apply("ev", 2, f2) == [f2]  # wrong site: no fire
    assert plan.apply("cmd", 2, f2) == []   # dropped
    assert plan.injected[WireFaultKind.DROP] == 1
    with pytest.raises(ValueError, match="unknown scheduled wire site"):
        WireFaultPlan(0, scheduled=[
            WireFaultSpec(1, "typo", WireFaultKind.DROP)])


# ------------------------------------------------------------ router WAL
class _Handle:
    """Minimal handle for journal encoder tests."""

    def __init__(self, prompt, n):
        from pddl_tpu.serve.request import Request, SamplingParams

        self.request = Request(prompt=list(prompt), max_new_tokens=n,
                               sampling=SamplingParams())
        self.tokens = []
        self.arrival_s = 0.0
        self.ttft_s = None


def test_journal_append_read_and_state_fold(tmp_path):
    d = str(tmp_path / "j")
    j = RouterJournal(d, fsync_batch_records=2)
    h = _Handle([1, 2, 3], 5)
    j.append(journal_io.encode_admit(0, h.request, "sess-a"),
             durable=True)
    j.append(journal_io.encode_route(0, 1, "hash"))
    j.append(journal_io.encode_admit(1, _Handle([4, 5], 3).request,
                                     None), durable=True)
    j.append(journal_io.encode_tokens(0, [9, 8]))
    j.append(journal_io.encode_tokens(0, [7]))
    j.append(journal_io.encode_finish(1, "finished", "stop"))
    j.commit()
    entries, next_rid = journal_io.read_state(d)
    assert next_rid == 2
    assert sorted(entries) == [0]  # rid 1 finished
    assert entries[0]["prompt"] == [1, 2, 3]
    assert entries[0]["tokens"] == [9, 8, 7]  # deltas folded in order
    assert entries[0]["session"] == "sess-a"
    j.close()


def test_journal_torn_tail_recovers_readable_prefix(tmp_path):
    d = str(tmp_path / "j")
    j = RouterJournal(d)
    for rid in range(4):
        j.append(journal_io.encode_admit(
            rid, _Handle([rid + 1], 2).request, None), durable=True)
    j.close()
    wal = os.path.join(d, "wal.log")
    size = os.path.getsize(wal)
    # A SIGKILL mid-write tears the last record: cut it mid-payload.
    with open(wal, "r+b") as f:
        f.truncate(size - 7)
    entries, next_rid = journal_io.read_state(d)
    assert sorted(entries) == [0, 1, 2]  # exactly the readable prefix
    assert next_rid == 3
    # Bit-rot mid-file: everything from the corrupt record on is
    # untrusted, the prefix before it still reads. Find the third
    # record's payload via the frame headers and flip bytes in it.
    header = journal_io._HEADER
    with open(wal, "rb") as f:
        data = f.read()
    offsets, off = [], 0
    while off + header.size <= len(data):
        _, _, length, _ = header.unpack_from(data, off)
        offsets.append(off)
        off += header.size + length
    with open(wal, "r+b") as f:
        f.seek(offsets[2] + header.size + 2)
        f.write(b"\xff\xff")
    entries, _ = journal_io.read_state(d)
    assert sorted(entries) == [0, 1]
    # A fresh journal over the same dir (the recovery path) scans the
    # same readable prefix, TRUNCATES the torn tail, and continues the
    # seq line past it — appends after unreadable bytes would put
    # every later durable record beyond what recovery can read.
    j2 = RouterJournal(d)
    assert j2._next_seq == 3
    j2.append(journal_io.encode_admit(
        9, _Handle([7], 2).request, None), durable=True)
    j2.close()
    entries, next_rid = journal_io.read_state(d)
    assert sorted(entries) == [0, 1, 9]
    assert next_rid == 10


def test_checkpoint_cycle_and_corrupt_checkpoint_fallback(tmp_path):
    d = str(tmp_path / "j")
    j = RouterJournal(d, checkpoint_every_records=4)
    for rid in range(3):
        j.append(journal_io.encode_admit(
            rid, _Handle([rid + 1, rid + 2], 3).request, None),
            durable=True)
    # First checkpoint: rid 0 finished, 1..2 in flight.
    j.append(journal_io.encode_finish(0, "finished", "stop"))
    assert j.checkpoint_due
    entries, _ = journal_io.read_state(d)
    cp1 = [(rid, e) for rid, e in sorted(entries.items()) if rid != 0]
    j.checkpoint(cp1, next_rid=3)
    assert not j.checkpoint_due
    assert j.records_since_checkpoint == 0
    # Post-checkpoint traffic, then a second cycle.
    j.append(journal_io.encode_admit(
        3, _Handle([9, 9], 2).request, None), durable=True)
    j.append(journal_io.encode_tokens(1, [5]))
    j.commit()
    entries, next_rid = journal_io.read_state(d)
    assert sorted(entries) == [1, 2, 3]
    assert entries[1]["tokens"] == [5]
    assert next_rid == 4
    cp2 = [(rid, e) for rid, e in sorted(entries.items())]
    j.checkpoint(cp2, next_rid=4)
    j.append(journal_io.encode_tokens(2, [6]))
    j.commit()
    # The current checkpoint fails its CRC (torn/bit-rotted): recovery
    # falls back to the PREVIOUS verified checkpoint plus the rotated
    # WAL segment — nothing acked is lost (r10: newest VERIFIED).
    cp_path = os.path.join(d, "checkpoint.json")
    with open(cp_path) as f:
        wrapped = json.load(f)
    wrapped["crc"] ^= 0xDEAD
    with open(cp_path, "w") as f:
        json.dump(wrapped, f)
    entries, next_rid = journal_io.read_state(d)
    assert sorted(entries) == [1, 2, 3]
    assert entries[1]["tokens"] == [5]
    assert entries[2]["tokens"] == [6]
    assert next_rid == 4
    j.close()


class CrashingJournal(RouterJournal):
    """The router-SIGKILL injector at WAL-record granularity: raises
    :class:`KillPoint` INSTEAD of appending the first record matching
    ``kill_when`` — the crash coordinate is "this control-plane event
    was about to be journaled", which is exactly where a real SIGKILL
    lands mid-admission / mid-migration / mid-stream."""

    def __init__(self, *args, **kwargs):
        self.kill_when = None
        super().__init__(*args, **kwargs)

    def append(self, record, *, durable=False):
        if self.kill_when is not None and self.kill_when(record):
            self.kill_when = None
            raise KillPoint("journal", self.records_appended)
        return super().append(record, durable=durable)


def _drive_until_crash(fleet, reqs):
    """Submit + pump, letting a KillPoint unwind like a real SIGKILL
    (the router object is then abandoned). Returns acked handles."""
    handles = []
    try:
        for p, n in reqs:
            handles.append(fleet.submit(p, n))
        for _ in range(600):
            fleet.step()
            if not fleet.has_work:
                break
    except KillPoint:
        pass
    return handles


@pytest.mark.chaos
@pytest.mark.parametrize("seed", [0, 1, 2])
@pytest.mark.parametrize("coord", ["mid_admission", "mid_stream",
                                   "mid_migration"])
def test_router_sigkill_matrix_recovers_token_exact(
        gpt_setup, pin_zero_recompiles, tmp_path, seed, coord):
    """The 3-seed x 3-coordinate crash matrix: kill the router at a
    seeded WAL-record coordinate, recover into a FRESH fleet, and
    every acked stream that had not durably finished revives and
    completes token-identical to the unkilled oracle — with zero
    recompiles on the recovered replicas."""
    model, variables = gpt_setup
    d = str(tmp_path / "wal")
    journal = CrashingJournal(d, fsync_batch_records=4)
    fleet, plans = _local_fleet(model, variables, 2,
                                with_plans=(coord == "mid_migration"),
                                journal=journal)
    reqs = _workload(8, seed=seed)
    refs = {tuple(int(t) for t in p): _ref_greedy(model, variables, p, n)
            for p, n in reqs}
    counters = {"admit": 0, "tokens": 0}

    if coord == "mid_admission":
        k = 3 + seed

        def kill_when(rec):
            if rec.get("rec") == "admit":
                counters["admit"] += 1
                return counters["admit"] == k
            return False
    elif coord == "mid_stream":
        k = 4 + 2 * seed

        def kill_when(rec):
            if rec.get("rec") == "tokens":
                counters["tokens"] += 1
                return counters["tokens"] == k
            return False
    else:  # mid_migration: a replica dies, the router crashes while
        #    journaling the migration re-binds.
        def kill_when(rec):
            return rec.get("rec") == "route" \
                and rec.get("via") == "migration"

    journal.kill_when = kill_when
    if coord == "mid_migration":
        # Arm the replica death that forces the migration: submit
        # first so a victim has load, then kill its next tick.
        handles = []
        try:
            for p, n in reqs:
                handles.append(fleet.submit(p, n))
            for _ in range(2):
                fleet.step()
            victim = max(fleet.replicas, key=lambda s: s.load)
            assert victim.load > 0
            eng = victim.driver.engine
            plans[victim.replica_id]._sched[
                (eng._step_idx + 1, "tick")] = [FaultKind.KILL]
            for _ in range(600):
                fleet.step()
                if not fleet.has_work:
                    break
        except KillPoint:
            pass
        assert journal.kill_when is None, \
            "the migration coordinate never fired"
    else:
        handles = _drive_until_crash(fleet, reqs)
        assert journal.kill_when is None, \
            f"the {coord} coordinate never fired"

    # --- the router process is gone; recover from the WAL alone.
    recovered, revived = FleetRouter.recover(
        d, _fresh_replicas(model, variables, 2),
        affinity_block_size=8, affinity_blocks=1, respawn=False)
    recovered = pin_zero_recompiles(recovered)
    assert revived, "nothing revived"
    recovered.run(max_steps=2000)
    for rid, fh in revived.items():
        assert fh.state == RequestState.FINISHED, f"rid {rid}: {fh}"
        key = tuple(int(t) for t in fh.request.prompt)
        assert fh.tokens == refs[key], \
            f"stream diverged after {coord} crash (seed {seed})"
    # Every acked request that had NOT settled at crash time must be
    # among the revived (its finish record cannot have been durable).
    revived_prompts = {tuple(int(t) for t in fh.request.prompt)
                      for fh in revived.values()}
    for h in handles:
        if not h.done:
            assert tuple(int(t) for t in h.request.prompt) \
                in revived_prompts
    # Recovery is the snapshot path's second normal case: the first
    # act of the recovered router was a fresh verified checkpoint.
    assert journal_io.load_checkpoint(d) is not None
    recovered.close()


def test_recover_mid_chain_pull(gpt_setup, tmp_path):
    """The chain-pull coordinate: the router dies INSIDE a
    replica-to-replica prefix transfer (import side, the r18 load-
    escape recipe). Acked in-flight streams still recover token-exact
    — the half-pulled chain is cache contents, never request state, so
    nothing depends on it — and the un-acked puller was never
    journaled, so it is (correctly) not revived."""
    model, variables = gpt_setup
    armed = {}

    def factory():
        return ServeEngine(model, variables, max_slots=2,
                           prefill_len=32, prefix_cache_blocks=24,
                           prefix_block_size=8, prefix_chunk=8,
                           host_tier=1 << 24, backoff_sleep=_no_sleep)

    class DiesMidImport(LocalReplica):
        def import_chain(self, entry):
            if armed.pop("on", None):
                raise KillPoint("import_chain", 0)
            return super().import_chain(entry)

    d = str(tmp_path / "wal")
    fleet = FleetRouter(
        [DiesMidImport(i, factory) for i in range(2)],
        affinity_block_size=8, respawn=False,
        interactive_reroute_load=1,
        shadow_host_capacity_blocks=1024, chain_pull_blocks=2,
        journal=RouterJournal(d))
    rng = np.random.default_rng(11)
    shared = rng.integers(0, 32, size=24).astype(np.int32)
    probe = np.concatenate([shared[:16],
                            rng.integers(0, 32, 8).astype(np.int32)])
    h1 = fleet.submit(list(shared), 4, priority=Priority.BATCH)
    fleet.run(max_steps=400)
    assert h1.state == RequestState.FINISHED
    # Two busy batch streams keep the warm replica loaded: the
    # interactive probe load-escapes to the cold sibling, which pulls
    # the chain — and the router dies inside the import.
    busy = [fleet.submit(list(shared), 24, priority=Priority.BATCH)
            for _ in range(2)]
    fleet.step()
    armed["on"] = True
    with pytest.raises(KillPoint):
        fleet.submit(list(probe), 4, priority=Priority.INTERACTIVE)
    ref_busy = _ref_greedy(model, variables, list(shared), 24)

    def plain_factory():
        # Recovery replicas need no tier and no prefix pool — replay
        # rebuilds KV — but DO need a prefill window that admits the
        # 24-token prompts.
        return ServeEngine(model, variables, max_slots=2,
                           prefill_len=32, max_queue_depth=64,
                           prefix_cache_blocks=0,
                           backoff_sleep=_no_sleep)

    recovered, revived = FleetRouter.recover(
        d, [LocalReplica(i, plain_factory) for i in range(2)],
        affinity_block_size=8, affinity_blocks=1, respawn=False)
    recovered.run(max_steps=2000)
    prompts = [tuple(int(t) for t in fh.request.prompt)
               for fh in revived.values()]
    assert tuple(int(t) for t in probe) not in prompts  # never acked
    live = [fh for fh in revived.values()
            if fh.request.max_new_tokens == 24]
    assert len(live) == 2  # both busy streams revived
    for fh in live:
        assert fh.state == RequestState.FINISHED
        assert fh.tokens == ref_busy
    recovered.close()


def test_recover_unjournaled_router_is_empty(gpt_setup, tmp_path):
    model, variables = gpt_setup
    recovered, revived = FleetRouter.recover(
        str(tmp_path / "empty"), _fresh_replicas(model, variables, 1),
        respawn=False)
    assert revived == {}
    # The recovered (empty) router serves normally.
    h = recovered.submit(list(range(1, 8)), 3)
    recovered.run(max_steps=200)
    assert h.tokens == _ref_greedy(model, variables,
                                   list(range(1, 8)), 3)
    recovered.close()


# ---------------------------------------------------------- gray failure
def test_gray_detector_suspects_drift_and_recovers():
    det = GrayDetector(window=4, baseline=8, z_threshold=4.0,
                       min_excess_s=0.001, consecutive=2)
    rng = np.random.default_rng(0)
    for _ in range(12):
        det.observe(0, 0.001 + 1e-5 * rng.random())
        det.observe(1, 0.001 + 1e-5 * rng.random())
    assert det.suspected == set()
    # Replica 0 drifts; replica 1 stays in band.
    for _ in range(6):
        det.observe(0, 0.030)
        det.observe(1, 0.001 + 1e-5 * rng.random())
    assert det.suspected == {0}
    assert det.is_suspected(0) and not det.is_suspected(1)
    # While suspected, the baseline is FROZEN: staying slow does not
    # launder the drift away.
    for _ in range(20):
        det.observe(0, 0.030)
    assert det.suspected == {0}
    # Returning to the old band `consecutive` times clears it.
    det.observe(0, 0.001)
    det.observe(0, 0.001)
    assert det.suspected == set()
    det.forget(1)
    assert det.suspected == set()


def _make_gray(fleet, plans, victim_id, *, latency_s):
    """Drive the fleet until the detector suspects ``victim_id``: a
    long-running stream keeps each engine ticking; after a clean
    baseline window, the victim's every device call gains a real
    latency injection, which the router's per-step wall sampling
    sees."""
    det = fleet.gray
    # The median-of-``smooth`` prefilter (ISSUE 18 de-flake) consumes
    # ``smooth`` raw samples per window entry — scale the drive counts
    # so the baseline actually fills.
    need = (det.window + det.baseline) * det.smooth
    for _ in range(need + 2):
        fleet.step()
    plans[victim_id]._rates = (0.0, 0.0, 1.0)  # latency on every call
    plans[victim_id].latency_s = latency_s
    plans[victim_id]._sleep = time.sleep
    for _ in range(200 * det.smooth):
        fleet.step()
        # A gray_drain fleet acts on the suspicion INSIDE the same
        # step (and forgets the retired replica) — the executed drain
        # is the observable then, not the transient suspicion.
        if victim_id in det.suspected or fleet.metrics.gray_drains:
            return
    raise AssertionError(
        f"detector never suspected replica {victim_id}")


def test_gray_hedge_first_result_wins_token_exact(gpt_setup, tmp_path):
    model, variables = gpt_setup
    tracer = RequestTracer()
    fleet, plans = _local_fleet(
        model, variables, 2, with_plans=True, tracer=tracer,
        journal=RouterJournal(str(tmp_path / "wal")),
        # smooth=3 (ISSUE 18 de-flake): median-of-3 prefilter kills
        # single-sample wall outliers; baseline=4 medians keeps the
        # same 12 RAW samples of baseline coverage as before.
        gray=GrayDetector(window=4, baseline=4, z_threshold=4.0,
                          min_excess_s=0.002, consecutive=2, smooth=3),
        gray_hedge=True, gray_drain=False)
    # Pin a session to replica 0, and keep BOTH of its engine slots
    # busy so a later hedged request must queue there — which is what
    # lets the healthy sibling win by rounds, deterministically.
    pin = fleet.submit(list(range(1, 9)), 56, session="s0")
    victim_id = pin.replica_id
    busy = fleet.submit(list(range(2, 10)), 56, session="s0")
    assert busy.replica_id == victim_id
    _make_gray(fleet, plans, victim_id, latency_s=0.002)
    assert fleet.gray.suspected == {victim_id}
    # An INTERACTIVE submission stuck to the suspect hedges to the
    # healthy sibling...
    prompt = ((np.arange(7) * 5 + 3) % 32).astype(np.int32)
    ref = _ref_greedy(model, variables, prompt, 4)
    h = fleet.submit(prompt, 4, session="s0")
    assert fleet.metrics.hedges_launched == 1
    assert tracer.events_named("hedge")
    # ...a BATCH submission with the same routing does not.
    hb = fleet.submit(((np.arange(6) + 11) % 32).astype(np.int32), 3,
                      session="s0", priority=Priority.BATCH)
    assert fleet.metrics.hedges_launched == 1
    fleet.run(max_steps=3000)
    assert h.state == RequestState.FINISHED
    assert h.tokens == ref  # greedy determinism: either copy, one stream
    assert hb.state == RequestState.FINISHED
    # The pair settled exactly once: the healthy sibling won (the
    # suspect's copy was queued behind two busy slots), the loser was
    # cancelled.
    assert fleet.metrics.hedge_wins == 1
    assert fleet.metrics.hedge_cancelled == 1
    assert h.replica_id != victim_id
    assert not fleet._hedge_peer and not fleet._hedge_rids
    fleet.close()
    # The journal filed the WON hedge's tokens/finish under the
    # PRIMARY rid its admit used: every finished stream folds away —
    # a mismatch would leave the hedged stream resurrectable.
    entries, _ = journal_io.read_state(str(tmp_path / "wal"))
    assert entries == {}


def test_hedge_copy_failure_does_not_kill_the_stream(gpt_setup):
    """A hedge copy that fails with nothing emitted must be quietly
    abandoned — the healthy (if slow) primary keeps the stream, so
    hedging can never turn one admission into a failure it would not
    otherwise have."""
    model, variables = gpt_setup

    class FailsWhenArmed(LocalReplica):
        def __init__(self, rid, factory):
            super().__init__(rid, factory)
            self.fail_next = False
            self._fake = []

        def submit(self, rid, *a, **kw):
            if self.fail_next:
                self.fail_next = False
                self._fake.append({"ev": "finish", "rid": rid,
                                   "state": "failed", "reason": "error",
                                   "ttft_s": None, "n_tokens": 0})
                return
            super().submit(rid, *a, **kw)

        def step(self):
            events = super().step() + self._fake
            self._fake = []
            return events

    plans = [FaultPlan(sleep_fn=_no_sleep) for _ in range(2)]

    def factory(plan):
        def make():
            return ServeEngine(model, variables, max_slots=2,
                               prefill_len=16, fault_plan=plan,
                               prefix_cache_blocks=0,
                               backoff_sleep=_no_sleep)
        return make

    fleet = FleetRouter(
        [FailsWhenArmed(i, factory(plans[i])) for i in range(2)],
        affinity_block_size=8, affinity_blocks=1, respawn=False,
        # smooth=3 (ISSUE 18 de-flake): median-of-3 prefilter kills
        # single-sample wall outliers; baseline=4 medians keeps the
        # same 12 RAW samples of baseline coverage as before.
        gray=GrayDetector(window=4, baseline=4, z_threshold=4.0,
                          min_excess_s=0.002, consecutive=2, smooth=3),
        gray_hedge=True, gray_drain=False)
    pin = fleet.submit(list(range(1, 9)), 56, session="s0")
    victim_id = pin.replica_id
    fleet.submit(list(range(2, 10)), 56, session="s0")
    _make_gray(fleet, plans, victim_id, latency_s=0.002)
    sibling = next(s for s in fleet.replicas
                   if s.replica_id != victim_id)
    sibling.driver.fail_next = True  # the hedge copy dies on arrival
    prompt = ((np.arange(7) * 5 + 3) % 32).astype(np.int32)
    ref = _ref_greedy(model, variables, prompt, 4)
    h = fleet.submit(prompt, 4, session="s0")
    assert fleet.metrics.hedges_launched == 1
    fleet.run(max_steps=3000)
    assert h.state == RequestState.FINISHED  # the primary carried it
    assert h.tokens == ref
    assert fleet.metrics.hedge_wins == 0
    assert fleet.metrics.hedge_cancelled == 0
    assert fleet.metrics.requests_failed == 0
    assert not fleet._hedge_peer and not fleet._hedge_rids
    fleet.close()


def test_gray_drain_retires_suspect_via_live_migration(gpt_setup):
    model, variables = gpt_setup
    tracer = RequestTracer()
    fleet, plans = _local_fleet(
        model, variables, 2, with_plans=True, tracer=tracer,
        # smooth=3 (ISSUE 18 de-flake): median-of-3 prefilter kills
        # single-sample wall outliers; baseline=4 medians keeps the
        # same 12 RAW samples of baseline coverage as before.
        gray=GrayDetector(window=4, baseline=4, z_threshold=4.0,
                          min_excess_s=0.002, consecutive=2, smooth=3),
        gray_hedge=False, gray_drain=True)
    pin = fleet.submit(list(range(1, 9)), 56, session="s0")
    victim_id = pin.replica_id
    busy = fleet.submit(list(range(2, 10)), 56, session="s0")
    assert busy.replica_id == victim_id
    refs = {tuple(range(1, 9)): _ref_greedy(model, variables,
                                            list(range(1, 9)), 56),
            tuple(range(2, 10)): _ref_greedy(model, variables,
                                             list(range(2, 10)), 56)}
    _make_gray(fleet, plans, victim_id, latency_s=0.002)
    # The suspect was retired through scale_down (live migration): its
    # in-flight streams moved and still finish token-exact.
    assert fleet.metrics.gray_drains == 1
    assert len(fleet.replicas) == 1
    assert fleet.replicas[0].replica_id != victim_id
    assert tracer.events_named("gray_drain")
    assert fleet.metrics.scale_down_events == 1
    fleet.run(max_steps=3000)
    for h in (pin, busy):
        assert h.state == RequestState.FINISHED
        assert h.tokens == refs[tuple(int(t) for t in h.request.prompt)]
        assert h.migrations >= 1
    fleet.close()


# --------------------------------------------------------- process fleet
_WORKER_CFG = dict(vocab=32, max_len=64, embed_dim=32, depth=1, heads=2,
                   slots=4, prefill_len=16, max_queue_depth=64,
                   param_seed=0, prefix_cache_blocks=0)


@pytest.mark.chaos
def test_process_fleet_wire_storm_token_exact(pin_zero_recompiles):
    """Seeded transport-fault storm over two REAL worker processes:
    corrupt/truncate/duplicate/reorder/drop frames in both directions.
    Every stream terminal and token-exact, every corrupt frame refused
    (counted, never parsed), retries healed the gaps, zero recompiles
    on both replicas."""
    import subprocess

    from pddl_tpu.serve.fleet import ProcessReplica
    from pddl_tpu.serve.fleet.worker import build_engine

    plans = [WireFaultPlan(
        seed=100 + i, corrupt_rate=0.01, truncate_rate=0.005,
        duplicate_rate=0.01, reorder_rate=0.005, drop_rate=0.005,
        scheduled=[WireFaultSpec(5, "ev", WireFaultKind.CORRUPT),
                   WireFaultSpec(4, "cmd", WireFaultKind.DROP)])
        for i in range(2)]
    reps = [ProcessReplica(i, {**_WORKER_CFG, "replica_id": i},
                           python=sys.executable,
                           stderr=subprocess.DEVNULL,
                           wire_fault_plan=plans[i]) for i in range(2)]
    fleet = FleetRouter(reps, affinity_block_size=8, affinity_blocks=1,
                        respawn=False)
    fleet = pin_zero_recompiles(fleet)
    try:
        rng = np.random.default_rng(1)
        prompts = [rng.integers(0, 32, size=10).tolist()
                   for _ in range(8)]
        handles = [fleet.submit(p, 12) for p in prompts]
        deadline = time.monotonic() + 120
        while any(not h.done for h in handles) \
                and time.monotonic() < deadline:
            fleet.step()
        eng = build_engine(_WORKER_CFG)
        for p, h in zip(prompts, handles):
            assert h.state == RequestState.FINISHED, f"stranded: {h}"
            assert h.tokens == _ref_greedy(
                eng.model, {"params": eng._params}, p, 12), \
                "stream diverged under the wire storm"
        # The storm actually fired, every corrupt frame was refused
        # (CRC), and the resend machinery healed the gaps.
        assert sum(p.total_injected for p in plans) > 0
        assert fleet.metrics.wire_crc_rejects > 0
        assert fleet.metrics.wire_retries > 0
        assert fleet.metrics.replica_down_events == 0
        assert fleet.metrics.requests_failed == 0
    finally:
        fleet.close()


def test_worker_self_reports_tick_walls_and_delay_knob():
    """Gray detection across a pipe rests on the worker self-reporting
    its engine-tick wall on pongs (the parent's pump wall cannot see a
    slow self-driving worker): samples flow through
    ``take_latency_samples``, and the ``set_tick_delay`` chaos knob
    visibly shifts them."""
    import subprocess

    from pddl_tpu.serve.fleet import ProcessReplica
    from pddl_tpu.serve.request import SamplingParams

    cfg = {**_WORKER_CFG, "replica_id": 0}
    rep = ProcessReplica(0, cfg, python=sys.executable,
                         stderr=subprocess.DEVNULL,
                         ping_interval_s=0.02)
    try:
        rep.submit(1, list(range(1, 9)), 50, SamplingParams(), None)
        deadline = time.monotonic() + 30
        clean: list = []
        while len(clean) < 5 and time.monotonic() < deadline:
            rep.step()
            clean.extend(s for s in rep.take_latency_samples()
                         if s is not None)
        assert clean, "no self-reported tick walls arrived"
        # The knob only shows on ticks, and ticks only happen with
        # work: slow the worker, then give it a second stream.
        rep.set_tick_delay(0.05)
        rep.submit(2, list(range(2, 10)), 50, SamplingParams(), None)
        slow: list = []
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            rep.step()
            slow.extend(s for s in rep.take_latency_samples()
                        if s >= 0.05)
            if len(slow) >= 3:
                break
        assert len(slow) >= 3, "delay knob never surfaced in samples"
        # The clean stream finishes in ~100ms, after which every pong
        # RE-REPORTS the final tick's wall — so one noise-inflated
        # last tick can dominate the clean median on a loaded host
        # (duplicated samples are not independent evidence). The
        # fastest clean tick is the only sample the duplication
        # artifact cannot poison: the knob's typical tick must clear
        # it by most of the injected 50ms.
        assert float(np.median(slow)) > float(min(clean)) + 0.04
    finally:
        rep.close()


def test_worker_oversized_frame_typed_reject_stays_alive():
    """The unbounded single-line pipe read, closed: a frame past the
    worker's max_frame_bytes is a TYPED reject (wire_error event, seq
    slot consumed) — the worker neither crashes nor wedges, and serves
    the next request normally."""
    import subprocess

    from pddl_tpu.serve.fleet import ProcessReplica
    from pddl_tpu.serve.request import SamplingParams

    cfg = {**_WORKER_CFG, "replica_id": 0, "slots": 2,
           "max_frame_bytes": 4096}
    rep = ProcessReplica(0, cfg, python=sys.executable,
                         stderr=subprocess.DEVNULL)
    try:
        rep._send({"cmd": "restore",
                   "requests": [[99, {"prompt": [1] * 6000,
                                      "max_new_tokens": 1}]]})
        deadline = time.monotonic() + 30
        rejected = False
        while not rejected and time.monotonic() < deadline:
            for ev in rep.step():
                if ev.get("ev") == "wire_error" \
                        and ev.get("kind") == "frame_too_large":
                    rejected = True
        assert rejected, "no typed oversize reject"
        # The worker survived AND its receive stream did not wedge: a
        # fresh request serves end-to-end.
        rep.submit(1, list(range(1, 7)), 3, SamplingParams(), None)
        deadline = time.monotonic() + 30
        ok = False
        while not ok and time.monotonic() < deadline:
            for ev in rep.step():
                if ev.get("ev") == "finish" and ev.get("rid") == 1:
                    assert ev["state"] == RequestState.FINISHED.value
                    ok = True
        assert ok, "worker did not serve after the oversize reject"
    finally:
        rep.close()


@pytest.mark.chaos
def test_process_fleet_router_crash_under_storm_recovers(tmp_path):
    """Router SIGKILL x transport-fault storm, process replicas: the
    journaled router dies mid-service under an injected wire storm;
    recovery spawns FRESH workers and every acked stream finishes
    token-exact, with zero recompiles on the recovered workers."""
    import subprocess

    from pddl_tpu.serve.fleet import ProcessReplica
    from pddl_tpu.serve.fleet.worker import build_engine

    d = str(tmp_path / "wal")

    def spawn(i, seed):
        return ProcessReplica(
            i, {**_WORKER_CFG, "replica_id": i}, python=sys.executable,
            stderr=subprocess.DEVNULL,
            wire_fault_plan=WireFaultPlan(seed, corrupt_rate=0.01,
                                          duplicate_rate=0.01,
                                          drop_rate=0.005))

    journal = CrashingJournal(d, fsync_batch_records=4)
    counters = {"tokens": 0}

    def kill_when(rec):
        if rec.get("rec") == "tokens":
            counters["tokens"] += 1
            return counters["tokens"] == 6
        return False

    journal.kill_when = kill_when
    reps = [spawn(i, 7 + i) for i in range(2)]
    fleet = FleetRouter(reps, affinity_block_size=8, affinity_blocks=1,
                        respawn=False, journal=journal)
    rng = np.random.default_rng(2)
    prompts = [rng.integers(0, 32, size=10).tolist() for _ in range(6)]
    handles = []
    try:
        for p in prompts:
            handles.append(fleet.submit(p, 10))
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            fleet.step()
    except KillPoint:
        pass
    assert journal.kill_when is None, "the crash coordinate never fired"
    # The dead router's workers are orphans; the machine reaps them.
    for rep in reps:
        rep.kill()
    recovered, revived = FleetRouter.recover(
        d, [spawn(10 + i, 70 + i) for i in range(2)],
        affinity_block_size=8, affinity_blocks=1, respawn=False)
    try:
        assert revived
        deadline = time.monotonic() + 120
        while any(not fh.done for fh in revived.values()) \
                and time.monotonic() < deadline:
            recovered.step()
        eng = build_engine(_WORKER_CFG)
        by_prompt = {tuple(p): _ref_greedy(
            eng.model, {"params": eng._params}, p, 10) for p in prompts}
        for fh in revived.values():
            assert fh.state == RequestState.FINISHED
            assert fh.tokens == by_prompt[
                tuple(int(t) for t in fh.request.prompt)]
        counts = recovered.compile_counts()
        assert counts and all(v == 1 for v in counts.values()), \
            f"recovered workers recompiled: {counts}"
    finally:
        recovered.close()


# -------------------------------------------------------- observability
def test_exposition_ctrlplane_series_both_directions(gpt_setup,
                                                     tmp_path):
    model, variables = gpt_setup
    fleet, plans = _local_fleet(
        model, variables, 2, with_plans=True,
        journal=RouterJournal(str(tmp_path / "wal")),
        gray=GrayDetector(window=4, baseline=4, min_excess_s=0.002,
                          consecutive=2, smooth=3))
    h = fleet.submit(list(range(1, 9)), 4, session="s0")
    victim_id = h.replica_id
    fleet.submit(list(range(2, 10)), 56, session="s0")
    _make_gray(fleet, plans, victim_id, latency_s=0.002)
    fleet.submit(list(range(3, 9)), 3, session="s0")  # hedges
    fleet.run(max_steps=2000)
    text = fleet_exposition(fleet)
    samples, types = parse_prometheus_text(text)  # strict referee in
    m = fleet.metrics                             # the render direction
    # ...and the parse direction: values round-trip exactly.
    for key, want in [("hedges_launched", m.hedges_launched),
                      ("hedge_wins", m.hedge_wins),
                      ("hedge_cancelled", m.hedge_cancelled),
                      ("gray_drains", m.gray_drains),
                      ("wire_retries", m.wire_retries),
                      ("wire_crc_rejects", m.wire_crc_rejects)]:
        name = f"pddl_fleet_{key}_total"
        assert types[name] == "counter"
        assert samples[(name, ())] == float(want)
    assert m.hedges_launched >= 1
    assert samples[("pddl_fleet_journal_bytes", ())] \
        == float(fleet.journal.wal_bytes)
    assert samples[("pddl_fleet_journal_lag_records", ())] \
        == float(fleet.journal.records_since_checkpoint)
    assert samples[("pddl_fleet_replicas_suspected_gray", ())] \
        == float(len(fleet.gray.suspected))
    assert types["pddl_fleet_journal_bytes"] == "gauge"
    fleet.close()
    # Unarmed fleet: the gauges still export, as NaN (present but
    # unobserved — a scrape can tell "off" from "vanished").
    bare, _ = _local_fleet(model, variables, 1)
    samples, _ = parse_prometheus_text(fleet_exposition(bare))
    assert math.isnan(samples[("pddl_fleet_journal_bytes", ())])
    assert math.isnan(
        samples[("pddl_fleet_replicas_suspected_gray", ())])
    bare.close()
