"""Disaggregated prefill/decode serving (`pddl_tpu/serve/fleet/
disagg.py` + role plumbing), CPU.

The contracts under test:

- **Backward compatibility**: an all-unified fleet never arms — zero
  hand-offs, zero prefill routes, r19 behavior bit-for-bit.
- **The hand-off** (``@pytest.mark.disagg``): on a split fleet every
  cold prompt routes to the prefill pool, chunk-prefills there, and
  the finished KV chain ships to a decode replica — every stream
  token-identical to the one-shot oracle, every hand-off journaled
  under the original rid, zero recompiles on the decode replicas
  after warmup (the per-replica ``pin_zero_recompiles``).
- **Chaos**: the prefill replica dies mid-KV-hand-off (seeded
  3-coordinate matrix): the in-flight chain unwinds on the source,
  the stream re-prefills elsewhere token-exact, and no host-tier pins
  leak. A REFUSED transfer (tier-less decode target) keeps the stream
  decoding where it prefilled — slow beats wrong.
- **Stall accounting**: with no decode replica available the move
  waits and ``decode_long_prompt_stalls`` counts ONCE per stream.
- **Recovery**: a router crash mid-split-fleet recovers from the WAL
  (handoff records in the log are audit-only) and every stream
  finishes token-exact on a fresh split fleet.
- **Per-role autoscaling**: the prefill pool scales up on its own
  load signal while the decode pool holds; one shared replica-id
  line; role gauges as labeled series.
- **Observability**: role counts, hand-off counters, and the
  stall gauge (NaN while unarmed) render and re-parse through the
  strict Prometheus referee.
"""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pddl_tpu.models.gpt import tiny_gpt
from pddl_tpu.obs import RequestTracer, fleet_exposition, parse_prometheus_text
from pddl_tpu.serve import ServeEngine
from pddl_tpu.serve.fleet import (
    FleetRouter,
    LocalReplica,
    ReplicaDied,
    RoleAutoscaler,
    RouterJournal,
    ScaleDecision,
    validate_role,
)
from pddl_tpu.serve.fleet import disagg as disagg_mod
from pddl_tpu.serve.fleet import journal as journal_io
from pddl_tpu.serve.fleet import router as router_mod
from pddl_tpu.serve.fleet import worker as worker_mod
from pddl_tpu.serve.request import RequestState
from conftest import ref_greedy as _ref_greedy, FakeClock as _FakeClock

pytestmark = pytest.mark.disagg

BS = 8  # prefix/affinity block size, shared router <-> engines


@pytest.fixture(scope="module")
def gpt_setup():
    model = tiny_gpt(vocab_size=32, max_len=64)
    prompt = jnp.ones((1, 8), jnp.int32)
    params = model.init(jax.random.key(0), prompt, train=False)["params"]
    return model, {"params": params}


def _no_sleep(_):
    pass


def _engine_factory(model, variables, *, host=1 << 24):
    """Hand-off-capable engine: prefix cache ON (the chain to export)
    and host tier ON (the landing zone) — ``host=None`` builds the
    tier-less twin the refusal leg needs."""
    def make():
        return ServeEngine(model, variables, max_slots=2, prefill_len=32,
                           prefix_cache_blocks=24, prefix_block_size=BS,
                           prefix_chunk=BS, host_tier=host,
                           max_queue_depth=64, backoff_sleep=_no_sleep)
    return make


def _split_fleet(model, variables, n_prefill, n_decode, *,
                 decode_host=1 << 24, tracer=None, clock=None,
                 replica_cls=LocalReplica, **router_kw):
    """n_prefill prefill-role + n_decode decode-role LocalReplicas
    (prefill ids first) over hand-off-capable engines."""
    pf = _engine_factory(model, variables)
    df = _engine_factory(model, variables, host=decode_host)
    replicas = [replica_cls(i, pf, role="prefill")
                for i in range(n_prefill)]
    replicas += [replica_cls(n_prefill + i, df, role="decode")
                 for i in range(n_decode)]
    import time
    return FleetRouter(
        replicas, affinity_block_size=BS, affinity_blocks=1,
        respawn=False, tracer=tracer,
        clock=clock if clock is not None else time.monotonic,
        **router_kw)


def _workload(n_requests, seed=0):
    """Cold prompts >= 1 full block (the exportable chain) with short
    greedy continuations — every stream oracle-comparable."""
    rng = np.random.default_rng(seed)
    reqs = []
    for _ in range(n_requests):
        plen = int(rng.integers(12, 25))
        reqs.append((rng.integers(0, 32, size=plen).astype(np.int32),
                     int(rng.integers(3, 8))))
    return reqs


# ------------------------------------------------------------ vocabulary
def test_role_vocabulary_parity():
    """The cross-module agreements graftlint `role-vocab` pins, as a
    runtime smoke test: worker mirrors disagg's ROLES, the router's
    route labels are journal-classifiable, handoff is a record kind."""
    assert worker_mod.ROLES == disagg_mod.ROLES
    assert set(router_mod.ROUTE_LABELS) <= set(journal_io.VIA_LABELS)
    assert "handoff" in journal_io.RECORD_KINDS
    assert "from_replica" in journal_io.RECORD_KEYS_V3


def test_validate_role():
    assert validate_role(None) == "unified"
    for role in disagg_mod.ROLES:
        assert validate_role(role) == role
    with pytest.raises(ValueError, match="replica role"):
        validate_role("prefil")
    with pytest.raises(ValueError, match="replica role"):
        LocalReplica(0, lambda: None, role="both")


# ------------------------------------------------- backward compatibility
def test_unified_fleet_never_arms(gpt_setup):
    """No strict roles -> not armed: zero prefill routes, zero
    hand-offs, streams finish exactly as an r19 fleet would."""
    model, variables = gpt_setup
    factory = _engine_factory(model, variables)
    fleet = FleetRouter(
        [LocalReplica(0, factory), LocalReplica(1, factory)],
        affinity_block_size=BS, affinity_blocks=1, respawn=False)
    assert not fleet.disagg_armed
    reqs = _workload(4, seed=3)
    refs = [_ref_greedy(model, variables, p, n) for p, n in reqs]
    handles = [fleet.submit(p, n) for p, n in reqs]
    fleet.run(max_steps=600)
    assert [list(h.tokens) for h in handles] == refs
    assert fleet.metrics.routed_prefill == 0
    assert fleet.metrics.handoffs_completed == 0
    assert fleet.metrics.handoffs_failed == 0
    fleet.close()


# ------------------------------------------------------- the hand-off
def test_split_fleet_hands_off_and_stays_token_exact(
        gpt_setup, pin_zero_recompiles):
    """The tentpole: every cold prompt routes prefill, ships its chain,
    and decodes on a decode replica — token-exact vs the unified
    oracle, journaled, counted, with zero recompiles on every replica
    after warmup."""
    model, variables = gpt_setup
    tracer = RequestTracer()
    fleet = _split_fleet(model, variables, 1, 2, tracer=tracer)
    assert fleet.disagg_armed
    fleet = pin_zero_recompiles(fleet)
    reqs = _workload(6, seed=1)
    refs = [_ref_greedy(model, variables, p, n) for p, n in reqs]
    handles = [fleet.submit(p, n) for p, n in reqs]
    fleet.run(max_steps=1200)
    decode_ids = {1, 2}
    for h, ref in zip(handles, refs):
        assert h.state == RequestState.FINISHED
        assert list(h.tokens) == ref
        assert h.replica_id in decode_ids, \
            "stream finished on the prefill replica despite a hand-off"
        assert h.migrations >= 1
    m = fleet.metrics
    assert m.routed_prefill == len(reqs)
    assert m.handoffs_completed == len(reqs)
    assert m.handoffs_failed == 0
    assert m.handoff_bytes > 0
    assert m.handoff_tokens >= len(reqs) * BS
    events = tracer.events_named("handoff")
    assert len(events) == len(reqs)
    for ev in events:
        assert ev["from_replica"] == 0 and ev["to_replica"] in decode_ids
        assert ev["blocks"] >= 1 and ev["ms"] >= 0.0
    # The decode replicas' host tiers hold the shipped chains, pins
    # all released.
    for slot in fleet.replicas:
        host = slot.driver.engine._host
        assert host.pins_outstanding == 0
    assert any(fleet.replicas[i].driver.engine.host_tier_bytes_resident
               > 0 for i in decode_ids)
    fleet.close()


def test_handoff_journal_records_under_original_rid(gpt_setup, tmp_path):
    """The WAL leg: one handoff record per stream, stamped with the
    prefill source and filed under the ORIGINAL rid (the alias
    discipline — tokens/finish keep keying to the admit)."""
    model, variables = gpt_setup
    fleet = _split_fleet(
        model, variables, 1, 1,
        journal=RouterJournal(str(tmp_path / "wal"),
                              fsync_batch_records=1))
    reqs = _workload(2, seed=5)
    handles = [fleet.submit(p, n) for p, n in reqs]
    fleet.run(max_steps=600)
    assert all(h.state == RequestState.FINISHED for h in handles)
    assert fleet.metrics.handoffs_completed == len(reqs)
    fleet.close()
    records = [rec for _, rec in journal_io.iter_wal_records(
        str(tmp_path / "wal" / "wal.log"))]
    admits = {r["rid"] for r in records if r["rec"] == "admit"}
    handoffs = [r for r in records if r["rec"] == "handoff"]
    finishes = {r["rid"] for r in records if r["rec"] == "finish"}
    assert len(handoffs) == len(reqs)
    for rec in handoffs:
        assert rec["from_replica"] == 0 and rec["replica"] == 1
        assert rec["rid"] in admits, \
            "handoff journaled under a fresh rid the admit never saw"
    assert finishes == admits, \
        "post-handoff finish records lost the admit's rid alias"
    # Audit-only on recovery: everything finished, nothing to replay.
    entries, _ = journal_io.read_state(str(tmp_path / "wal"))
    assert entries == {}


# ------------------------------------------------------------------ chaos
@pytest.mark.chaos
@pytest.mark.parametrize("seed", [0, 1, 2])
def test_prefill_dies_mid_handoff_unwinds_and_reprefills(gpt_setup,
                                                         seed):
    """The seeded mid-KV-hand-off kill coordinate: the prefill source
    dies inside the chain export of the (seed+1)-th hand-off. The
    in-flight chain unwinds with the replica, every stream re-enters
    elsewhere and finishes token-exact, and no host-tier pin leaks on
    the survivor."""
    model, variables = gpt_setup
    arm = {"countdown": seed + 1}

    class DiesMidExport(LocalReplica):
        def export_chain(self, prompt, max_blocks=None):
            arm["countdown"] -= 1
            if arm["countdown"] == 0:
                raise ReplicaDied(self.replica_id,
                                  "killed mid-KV-hand-off")
            return super().export_chain(prompt, max_blocks)

    tracer = RequestTracer()
    fleet = _split_fleet(model, variables, 1, 1, tracer=tracer,
                         replica_cls=DiesMidExport)
    reqs = _workload(4, seed=seed)
    refs = [_ref_greedy(model, variables, p, n) for p, n in reqs]
    handles = [fleet.submit(p, n) for p, n in reqs]
    fleet.run(max_steps=1200)
    assert not fleet.has_work
    for h, ref in zip(handles, refs):
        assert h.state == RequestState.FINISHED
        assert list(h.tokens) == ref, \
            f"stream diverged across the mid-hand-off kill (seed {seed})"
    assert fleet.metrics.handoffs_failed >= 1
    assert fleet.metrics.replica_down_events == 1
    downs = tracer.events_named("replica_down")
    assert len(downs) == 1 and downs[0]["replica"] == 0
    # The decode survivor leaked no pins across the unwind + replay.
    survivor = fleet.replicas[1].driver.engine
    assert survivor._host.pins_outstanding == 0
    fleet.close()


def test_refused_transfer_keeps_stream_on_prefill(gpt_setup):
    """A tier-less decode target refuses the chain: moving the stream
    would re-prefill the long prompt there, so it STAYS on the prefill
    replica (slow beats wrong), finishes token-exact, and the refusal
    is counted + traced exactly once per stream."""
    model, variables = gpt_setup
    tracer = RequestTracer()
    fleet = _split_fleet(model, variables, 1, 1, decode_host=None,
                         tracer=tracer)
    reqs = _workload(2, seed=9)
    refs = [_ref_greedy(model, variables, p, n) for p, n in reqs]
    handles = [fleet.submit(p, n) for p, n in reqs]
    fleet.run(max_steps=600)
    for h, ref in zip(handles, refs):
        assert h.state == RequestState.FINISHED
        assert list(h.tokens) == ref
        assert h.replica_id == 0  # never moved
        assert h.migrations == 0
    assert fleet.metrics.handoffs_completed == 0
    assert fleet.metrics.handoffs_failed == len(reqs)
    refusals = tracer.events_named("handoff_refused")
    assert len(refusals) == len(reqs)  # no per-round retry storm
    fleet.close()


def test_decode_stall_counts_once_per_stream(gpt_setup):
    """Every decode replica down: the hand-off waits (re-noted each
    tokens event) and the stall counter moves ONCE per stream, however
    many rounds the stall lasts."""
    model, variables = gpt_setup

    class DiesOnFirstStep(LocalReplica):
        def step(self):
            raise ReplicaDied(self.replica_id, "decode pool outage")

    pf = _engine_factory(model, variables)
    df = _engine_factory(model, variables)
    fleet = FleetRouter(
        [LocalReplica(0, pf, role="prefill"),
         DiesOnFirstStep(1, df, role="decode")],
        affinity_block_size=BS, affinity_blocks=1, respawn=False)
    assert fleet.disagg_armed  # armed is fleet SHAPE, not health
    reqs = _workload(2, seed=4)
    refs = [_ref_greedy(model, variables, p, n) for p, n in reqs]
    handles = [fleet.submit(p, n) for p, n in reqs]
    fleet.run(max_steps=600)
    for h, ref in zip(handles, refs):
        assert h.state == RequestState.FINISHED
        assert list(h.tokens) == ref
        assert h.replica_id == 0  # decoded where it prefilled
    assert fleet.metrics.decode_long_prompt_stalls == len(reqs)
    assert fleet.metrics.handoffs_completed == 0
    fleet.close()


# --------------------------------------------------------------- recovery
def test_router_crash_recovers_split_fleet_token_exact(gpt_setup,
                                                       tmp_path):
    """Router SIGKILL mid-hand-off-era traffic: the WAL (admits,
    tokens, handoff records) folds back into in-flight streams, a
    FRESH split fleet re-enters them through mirror replay, and every
    stream finishes token-exact — handoff records are audit-only."""
    model, variables = gpt_setup
    d = str(tmp_path / "wal")
    fleet = _split_fleet(
        model, variables, 1, 1,
        journal=RouterJournal(d, fsync_batch_records=1))
    rng = np.random.default_rng(6)
    # Long enough generations that the kill lands mid-stream.
    reqs = [(rng.integers(0, 32, size=int(rng.integers(12, 25)))
             .astype(np.int32), 14) for _ in range(3)]
    refs = [_ref_greedy(model, variables, p, n) for p, n in reqs]
    handles = [fleet.submit(p, n) for p, n in reqs]
    for _ in range(6):  # tokens flowing, at least one hand-off stamped
        fleet.step()
    assert any(h.tokens for h in handles)
    assert not any(h.done for h in handles)
    assert fleet.metrics.handoffs_completed >= 1
    # SIGKILL: the router object is abandoned, no drain, no close.
    records = [rec for _, rec in journal_io.iter_wal_records(
        str(tmp_path / "wal" / "wal.log"))]
    assert any(r["rec"] == "handoff" for r in records)
    pf = _engine_factory(model, variables)
    df = _engine_factory(model, variables)
    recovered, revived = FleetRouter.recover(
        d, [LocalReplica(10, pf, role="prefill"),
            LocalReplica(11, df, role="decode")],
        affinity_block_size=BS, affinity_blocks=1, respawn=False)
    assert recovered.disagg_armed
    assert len(revived) == len(reqs)
    recovered.run(max_steps=1200)
    by_prompt = {tuple(int(t) for t in p): ref
                 for (p, _n), ref in zip(reqs, refs)}
    for fh in revived.values():
        assert fh.state == RequestState.FINISHED
        assert list(fh.tokens) == by_prompt[
            tuple(int(t) for t in fh.request.prompt)]
    recovered.close()


# ------------------------------------------------------ per-role scaling
def test_role_autoscaler_scales_prefill_pool_independently(gpt_setup):
    """Cold-prompt load lands on the prefill pool only; its controller
    scales up on its own load band while the idle decode pool HOLDs —
    one shared replica-id line, role gauges as labeled series."""
    model, variables = gpt_setup
    clock = _FakeClock(100.0)
    fleet = _split_fleet(model, variables, 1, 1, clock=clock)
    pf = _engine_factory(model, variables)
    df = _engine_factory(model, variables)
    ras = RoleAutoscaler(
        fleet,
        {"prefill": lambda rid: LocalReplica(rid, pf, role="prefill"),
         "decode": lambda rid: LocalReplica(rid, df, role="decode")},
        per_role={"prefill": dict(up_load=1.0)},
        min_replicas=1, max_replicas=3, up_load=50.0, up_hold_s=0.0)
    assert fleet.autoscaler is ras
    for p, n in _workload(3, seed=2):
        fleet.submit(p, n)  # armed routing: all three land on prefill
    decisions = ras.step(clock.now)
    assert decisions["prefill"] == ScaleDecision.SCALE_UP
    assert decisions["decode"] == ScaleDecision.HOLD
    assert len(fleet.replicas) == 3
    new = next(s for s in fleet.replicas if s.replica_id == 2)
    assert new.driver.role == "prefill"  # shared id line: 0,1 taken
    gauges = ras.gauges()
    assert gauges["role_replicas"] == {"prefill": 2, "decode": 1}
    assert gauges["pending_spawns"] == 0
    assert ras.metrics.snapshot()["scale_up_completed"] == 1
    fleet.run(max_steps=1200)
    assert not fleet.has_work
    fleet.close()


# ---------------------------------------------------------- observability
def test_exposition_disagg_series_both_directions(gpt_setup):
    model, variables = gpt_setup
    fleet = _split_fleet(model, variables, 1, 2)
    reqs = _workload(3, seed=8)
    handles = [fleet.submit(p, n) for p, n in reqs]
    fleet.run(max_steps=900)
    assert all(h.done for h in handles)
    m = fleet.metrics
    samples, types = parse_prometheus_text(fleet_exposition(fleet))
    by_role = {role: samples[("pddl_fleet_replicas_by_role",
                              (("key", role),))]
               for role in ("prefill", "decode", "unified")}
    assert by_role == {"prefill": 1.0, "decode": 2.0, "unified": 0.0}
    for key, want in [("routed_prefill", m.routed_prefill),
                      ("handoffs_completed", m.handoffs_completed),
                      ("handoffs_failed", m.handoffs_failed),
                      ("handoff_bytes", m.handoff_bytes),
                      ("handoff_tokens", m.handoff_tokens)]:
        name = f"pddl_fleet_{key}_total"
        assert types[name] == "counter"
        assert samples[(name, ())] == float(want)
    assert m.handoffs_completed >= 1
    # Armed: the stall gauge observes (0 here — no decode outage).
    assert types["pddl_fleet_decode_long_prompt_stalls"] == "gauge"
    assert samples[("pddl_fleet_decode_long_prompt_stalls", ())] == 0.0
    fleet.close()
    # Unarmed fleet: role series still complete, stall gauge NaN
    # (present but unobserved — "off" is distinguishable from
    # "vanished").
    factory = _engine_factory(model, variables)
    bare = FleetRouter([LocalReplica(0, factory)],
                       affinity_block_size=BS, affinity_blocks=1)
    samples, _ = parse_prometheus_text(fleet_exposition(bare))
    assert samples[("pddl_fleet_replicas_by_role",
                    (("key", "unified"),))] == 1.0
    assert samples[("pddl_fleet_replicas_by_role",
                    (("key", "prefill"),))] == 0.0
    assert math.isnan(
        samples[("pddl_fleet_decode_long_prompt_stalls", ())])
    bare.close()


def test_exposition_carries_role_autoscaler_gauges(gpt_setup):
    model, variables = gpt_setup
    clock = _FakeClock(10.0)
    fleet = _split_fleet(model, variables, 1, 1, clock=clock)
    pf = _engine_factory(model, variables)
    RoleAutoscaler(
        fleet,
        {"prefill": lambda rid: LocalReplica(rid, pf, role="prefill")},
        min_replicas=1, max_replicas=2, up_load=50.0)
    samples, types = parse_prometheus_text(fleet_exposition(fleet))
    assert samples[("pddl_fleet_autoscale_role_replicas",
                    (("key", "prefill"),))] == 1.0
    assert samples[("pddl_fleet_autoscale_role_max_replicas",
                    (("key", "prefill"),))] == 2.0
    assert samples[("pddl_fleet_autoscale_replicas", ())] == 2.0
    assert types["pddl_fleet_autoscale_scale_up_started_total"] \
        == "counter"
    fleet.close()
