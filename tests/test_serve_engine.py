"""Continuous-batching serving engine (`pddl_tpu/serve/`), CPU.

The contracts under test:

- **Exactness**: a greedy request served through the slot-pooled engine
  emits exactly what single-request ``generate()`` emits — admit order,
  slot reuse, and neighbors in the batch must not change anyone's
  tokens (both families: GPT scalar-MHA cache, Llama GQA + RoPE).
- **Isolation**: per-slot sampling parameters are runtime arrays; one
  tick serves a greedy request next to a hot-temperature one without
  either leaking into the other.
- **Lifecycle**: admit → stream → evict for length/eos; cancellation
  and deadlines evict mid-decode with tokens-so-far intact; a full
  queue sheds load with the typed ``QueueFull``.
- **Fixed-shape discipline**: after ``warmup()`` a mixed workload
  (different prompt lengths, sampling params, request sizes) compiles
  NOTHING new — all four resident programs stay at exactly one
  executable.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from conftest import ref_greedy as _ref_greedy, FakeClock as _FakeClock
from pddl_tpu.models.gpt import (
    batched_filtered_logits,
    filtered_logits,
    generate,
    tiny_gpt,
)
from pddl_tpu.models.llama import tiny_llama
from pddl_tpu.serve import (
    FinishReason,
    QueueFull,
    RequestState,
    SamplingParams,
    ServeEngine,
)


@pytest.fixture(scope="module")
def gpt_setup():
    model = tiny_gpt(vocab_size=32, max_len=64)
    prompt = jnp.ones((1, 8), jnp.int32)
    params = model.init(jax.random.key(0), prompt, train=False)["params"]
    return model, {"params": params}


def test_admit_evict_slot_reuse_matches_generate(gpt_setup):
    """More requests than slots: every slot is reused, every request's
    greedy stream equals its single-request generate() — the whole
    point of iteration-level scheduling is that batching is invisible
    to each stream."""
    model, variables = gpt_setup
    eng = ServeEngine(model, variables, max_slots=2, prefill_len=16)
    eng.warmup()
    prompts = [np.arange(1 + 2 * i, dtype=np.int32)[:9] % 32
               for i in range(5)]
    lengths = [4, 7, 3, 6, 5]
    handles = [eng.submit(p, n) for p, n in zip(prompts, lengths)]
    eng.run(max_steps=100)
    for h, p, n in zip(handles, prompts, lengths):
        assert h.state == RequestState.FINISHED
        assert h.finish_reason == FinishReason.LENGTH
        assert h.tokens == _ref_greedy(model, variables, p, n)
    # 5 requests through 2 slots: reuse is structural, and occupancy
    # telemetry saw the pool actually multiplexed.
    snap = eng.metrics.snapshot()
    assert snap["requests_finished"] == 5
    assert snap["tokens_emitted"] == sum(lengths)
    assert snap["mean_slot_occupancy"] > 0.5


def test_llama_family_through_engine():
    """The GQA + RoPE family (per-row rotary positions, grouped cache)
    through the same engine, exact vs generate()."""
    model = tiny_llama(vocab_size=32, max_len=64)
    prompt = jnp.ones((1, 8), jnp.int32)
    variables = {"params": model.init(jax.random.key(1), prompt,
                                      train=False)["params"]}
    eng = ServeEngine(model, variables, max_slots=2, prefill_len=16)
    prompts = [(np.arange(6) * 5 + i) % 32 for i in range(3)]
    handles = [eng.submit(p, 5) for p in prompts]
    eng.run(max_steps=100)
    for h, p in zip(handles, prompts):
        assert h.tokens == _ref_greedy(model, variables, p, 5)


def test_per_slot_sampling_isolation(gpt_setup):
    """Three requests in one tick with different sampling params. The
    discriminative pair: greedy and (temperature=1, top_k=1) must BOTH
    reproduce their solo greedy streams (top-1 sampling is argmax), so
    a hot-temperature neighbor in the same fused tick proves per-slot
    parameters don't leak across rows."""
    model, variables = gpt_setup
    eng = ServeEngine(model, variables, max_slots=3, prefill_len=16,
                      rng=jax.random.key(7))
    pa = (np.arange(5) * 3) % 32
    pb = (np.arange(7) * 2 + 1) % 32
    pc = (np.arange(4) + 11) % 32
    ha = eng.submit(pa, 6)  # greedy
    hb = eng.submit(pb, 6, sampling=SamplingParams(temperature=1.0, top_k=1))
    hc = eng.submit(pc, 6, sampling=SamplingParams(temperature=8.0))
    eng.run(max_steps=50)
    assert ha.tokens == _ref_greedy(model, variables, pa, 6)
    assert hb.tokens == _ref_greedy(model, variables, pb, 6)
    assert all(0 <= t < 32 for t in hc.tokens) and len(hc.tokens) == 6


def test_batched_filter_matches_static_per_row():
    """The per-slot sampler's filter pipeline must equal the compiled
    single-request one row by row (same top-k tie rule, same nucleus
    CDF rule) — the engine's sampling is generate()'s, just batched."""
    logits = jax.random.normal(jax.random.key(3), (4, 33)) * 3.0
    cfgs = [(1.0, 5, 0.9), (0.7, 0, 2.0), (2.0, 1, 2.0), (0.5, 0, 0.3)]
    t = jnp.array([c[0] for c in cfgs])
    k = jnp.array([c[1] for c in cfgs], jnp.int32)
    p = jnp.array([c[2] for c in cfgs])
    batched = batched_filtered_logits(logits, temperature=t, top_k=k,
                                      top_p=p)
    for i, (ti, ki, pi) in enumerate(cfgs):
        ref = filtered_logits(logits[i:i + 1], temperature=ti,
                              top_k=ki or None,
                              top_p=pi if pi <= 1.0 else None)
        np.testing.assert_allclose(np.asarray(batched[i:i + 1]),
                                   np.asarray(ref), rtol=1e-6,
                                   err_msg=f"row {i} cfg {cfgs[i]}")


def test_cancellation_mid_decode_frees_the_slot(gpt_setup):
    """Cancel a running request: evicted at the next step with its
    tokens-so-far intact, and a queued request takes over the slot."""
    model, variables = gpt_setup
    eng = ServeEngine(model, variables, max_slots=1, prefill_len=16)
    long_h = eng.submit(np.arange(4) % 32, 40)
    queued_p = (np.arange(5) + 2) % 32
    queued_h = eng.submit(queued_p, 4)
    for _ in range(3):
        eng.step()
    assert long_h.state == RequestState.RUNNING
    assert queued_h.state == RequestState.QUEUED
    emitted_at_cancel = len(long_h.tokens)
    assert emitted_at_cancel >= 1
    long_h.cancel()
    eng.run(max_steps=50)
    assert long_h.state == RequestState.CANCELLED
    assert long_h.finish_reason == FinishReason.CANCELLED
    assert len(long_h.tokens) == emitted_at_cancel  # stream stopped
    assert queued_h.state == RequestState.FINISHED
    assert queued_h.tokens == _ref_greedy(model, variables, queued_p, 4)


def test_cancelling_a_queued_request_never_runs(gpt_setup):
    model, variables = gpt_setup
    eng = ServeEngine(model, variables, max_slots=1, prefill_len=16)
    running = eng.submit(np.arange(4) % 32, 3)
    queued = eng.submit(np.arange(5) % 32, 3)
    queued.cancel()
    eng.run(max_steps=50)
    assert running.state == RequestState.FINISHED
    assert queued.state == RequestState.CANCELLED
    assert queued.tokens == []


def test_deadline_timeout_evicts(gpt_setup):
    """An injectable clock drives the deadline: the request times out
    mid-decode, keeps its partial stream, and is counted."""
    model, variables = gpt_setup
    clock = _FakeClock()
    eng = ServeEngine(model, variables, max_slots=1, prefill_len=16,
                      clock=clock)
    h = eng.submit(np.arange(4) % 32, 40, deadline_s=10.0)
    eng.step()
    assert h.state == RequestState.RUNNING
    partial = len(h.tokens)
    assert partial >= 1
    clock.now = 11.0  # past the deadline
    eng.step()
    assert h.state == RequestState.TIMED_OUT
    assert h.finish_reason == FinishReason.TIMED_OUT
    assert len(h.tokens) == partial
    snap = eng.metrics.snapshot()
    assert snap["requests_timed_out"] == 1
    assert snap["requests_finished"] == 0  # counters are disjoint


def test_deadline_expired_in_queue_never_pays_prefill(gpt_setup):
    """A request whose deadline passes while QUEUED is timed out at
    admission — no prefill dispatch, no post-deadline token, the slot
    goes to the next admissible request."""
    model, variables = gpt_setup
    clock = _FakeClock()
    eng = ServeEngine(model, variables, max_slots=1, prefill_len=16,
                      clock=clock)
    running = eng.submit(np.arange(4) % 32, 30)
    eng.step()  # admit `running` first: EDF would otherwise pop the
    #             deadlined request ahead of the deadline-less one
    doomed = eng.submit(np.arange(5) % 32, 4, deadline_s=5.0)
    fine = eng.submit((np.arange(6) + 1) % 32, 3)
    for _ in range(3):
        eng.step()
    clock.now = 6.0  # doomed expires in the queue; running keeps going
    running.cancel()
    eng.run(max_steps=100)
    assert doomed.state == RequestState.TIMED_OUT
    assert doomed.tokens == []  # never ran
    assert fine.state == RequestState.FINISHED
    assert fine.tokens == _ref_greedy(model, variables, (np.arange(6) + 1) % 32, 3)


def test_queue_full_sheds_load_typed(gpt_setup):
    model, variables = gpt_setup
    eng = ServeEngine(model, variables, max_slots=1, prefill_len=16,
                      max_queue_depth=2)
    for _ in range(2):
        eng.submit(np.arange(4) % 32, 2)
    with pytest.raises(QueueFull) as exc:
        eng.submit(np.arange(4) % 32, 2)
    assert exc.value.queue_depth == 2
    assert exc.value.max_queue_depth == 2
    assert eng.metrics.snapshot()["requests_rejected"] == 1
    eng.run(max_steps=50)  # the accepted two still complete
    assert eng.metrics.snapshot()["requests_finished"] == 2


def test_zero_recompiles_after_warmup(gpt_setup, pin_zero_recompiles):
    """THE fixed-shape contract: one warmup, then a deliberately mixed
    workload — different prompt lengths, temperatures, top-k/top-p,
    request sizes, slot churn — and every resident program still has
    exactly ONE compiled executable (the `pin_zero_recompiles` fixture
    asserts the counts at warmup and again at teardown)."""
    model, variables = gpt_setup
    eng = pin_zero_recompiles(
        ServeEngine(model, variables, max_slots=2, prefill_len=16,
                    rng=jax.random.key(9)))
    mixed = [
        (np.arange(3) % 32, 2, SamplingParams()),
        (np.arange(9) % 32, 7, SamplingParams(temperature=0.8, top_k=4)),
        (np.arange(14) % 32, 1, SamplingParams(temperature=1.5, top_p=0.7)),
        (np.arange(5) % 32, 9,
         SamplingParams(temperature=0.3, top_k=2, top_p=0.95)),
        (np.arange(16) % 32, 3, SamplingParams()),
    ]
    handles = [eng.submit(p, n, sampling=s) for p, n, s in mixed]
    eng.run(max_steps=200)
    assert all(h.state == RequestState.FINISHED for h in handles)


def test_int8_serving_composes_through_engine(gpt_setup):
    """The generate() int8 hook through the engine: int8 params +
    param_transform reproduce the dequantized model's greedy streams
    exactly (same weights, same math — only the HBM representation and
    the jit boundary move)."""
    from pddl_tpu.ops.quant import dequantize, quantize_int8

    model, variables = gpt_setup
    qparams = quantize_int8(variables["params"], min_elems=128)
    dense = {"params": dequantize(qparams)}
    eng = ServeEngine(model, {"params": qparams}, max_slots=2,
                      prefill_len=16, param_transform=dequantize)
    prompts = [(np.arange(6) + i) % 32 for i in range(3)]
    handles = [eng.submit(p, 5) for p in prompts]
    eng.run(max_steps=50)
    for h, p in zip(handles, prompts):
        assert h.tokens == _ref_greedy(model, dense, p, 5)


def test_eos_finishes_early(gpt_setup):
    """Whatever greedy emits 2 tokens in, declaring that token eos must
    stop the stream right there with reason EOS (token included)."""
    model, variables = gpt_setup
    p = np.arange(6) % 32
    ref = _ref_greedy(model, variables, p, 3)
    eos = ref[1]
    eng = ServeEngine(model, variables, max_slots=1, prefill_len=16,
                      eos_token=eos)
    h = eng.submit(p, 20)
    eng.run(max_steps=50)
    assert h.state == RequestState.FINISHED
    assert h.finish_reason == FinishReason.EOS
    # The stream stops at the FIRST occurrence of the eos token (which
    # may be earlier than index 1 if greedy repeats it), eos included.
    assert h.tokens == ref[:ref.index(eos) + 1]


def test_submit_validation_and_ring_refusal(gpt_setup):
    model, variables = gpt_setup
    eng = ServeEngine(model, variables, max_slots=1, prefill_len=8)
    with pytest.raises(ValueError, match="prefill_len"):
        eng.submit(np.zeros(9, np.int32), 4)
    with pytest.raises(ValueError, match="max_len"):
        eng.submit(np.zeros(8, np.int32), 64)
    with pytest.raises(ValueError, match="at least one token"):
        eng.submit(np.zeros(0, np.int32), 4)
    with pytest.raises(ValueError, match="top_k/top_p"):
        SamplingParams(top_k=4)
    swa = tiny_llama(vocab_size=32, max_len=1024, sliding_window=64)
    with pytest.raises(NotImplementedError, match="ring"):
        ServeEngine(swa, variables, max_slots=1)
