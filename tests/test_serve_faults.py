"""Fault tolerance of the serving engine (`pddl_tpu/serve/faults.py`,
engine retry/replay/degraded/drain paths), CPU.

The contracts under test:

- **Chaos matrix** (seeds x fault kinds, `@pytest.mark.chaos`): under
  seeded injection of transient errors, RESOURCE_EXHAUSTED, and latency
  spikes, the engine never crashes, every admitted request reaches a
  terminal state, every SURVIVING (FINISHED) request's stream is
  token-identical to the fault-free run, and zero recompiles after
  warmup still holds across retry/replay/degraded transitions.
- **Retry**: a transient burst within the retry budget recovers in
  place — same tokens, no replay.
- **Replay**: a burst past the budget declares the slot KV lost; the
  request is rebuilt token-exactly from prompt + emitted tokens via the
  normal admission path plus re-fed ticks (no new compiled program).
- **Failure isolation**: a request that outlives ``max_replays`` ends
  FAILED/``FinishReason.ERROR``; the engine itself keeps serving.
- **Degraded mode**: an OOM flushes unpinned prefix blocks, turns
  donations off, keeps serving on the cold path, and re-arms after the
  cool-down — all token-exact.
- **Drain/restore**: SIGTERM (and even a hard kill-point mid-step)
  snapshots queued + running requests; a fresh engine restores and
  resumes each stream token-exactly.
- **Refcount hygiene**: storms of cancelled/faulted/deadline admissions
  leave the radix index at its refcount baseline (no pinned-chain
  leak).
"""

import signal
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pddl_tpu.models.gpt import generate, tiny_gpt
from pddl_tpu.obs import RequestTracer
from pddl_tpu.serve import (
    FaultKind,
    FaultPlan,
    FaultSpec,
    FinishReason,
    KillPoint,
    Priority,
    QueueFull,
    RequestState,
    ServeEngine,
)
from pddl_tpu.serve.scheduler import FCFSScheduler
from pddl_tpu.serve.request import Request, RequestHandle
from conftest import ref_greedy as _ref_greedy, FakeClock as _FakeClock


@pytest.fixture(scope="module")
def gpt_setup():
    model = tiny_gpt(vocab_size=32, max_len=64)
    prompt = jnp.ones((1, 8), jnp.int32)
    params = model.init(jax.random.key(0), prompt, train=False)["params"]
    return model, {"params": params}


def _no_sleep(_):
    pass


_WORKLOAD = [((np.arange(9) * 5 + 1) % 32, 6),
             ((np.arange(12) * 3 + 7) % 32, 5),
             ((np.arange(9) * 5 + 1) % 32, 4),   # shared prefix with #0
             ((np.arange(6) + 17) % 32, 7),
             ((np.arange(14) * 7 + 2) % 32, 3)]


@pytest.fixture(scope="module")
def workload_refs(gpt_setup):
    model, variables = gpt_setup
    return [_ref_greedy(model, variables, p, n) for p, n in _WORKLOAD]


def _next_step(eng):
    """The (step, site) coordinate the engine's NEXT step() will use."""
    return eng._step_idx


# ------------------------------------------------------------ chaos matrix
_PROFILES = {
    "transient": dict(transient_rate=0.08, max_random_injections=12),
    "oom": dict(oom_rate=0.05, max_random_injections=6),
    "latency": dict(latency_rate=0.25, latency_s=1e-4,
                    max_random_injections=30),
    "mixed": dict(transient_rate=0.05, oom_rate=0.02, latency_rate=0.1,
                  latency_s=1e-4, max_random_injections=20),
}


@pytest.mark.chaos
@pytest.mark.parametrize("seed", [0, 1, 2])
@pytest.mark.parametrize("profile", sorted(_PROFILES))
def test_chaos_matrix(gpt_setup, workload_refs, pin_zero_recompiles,
                      seed, profile):
    """Seeded chaos: no crash, every request terminal, survivors
    token-identical to the fault-free run, zero recompiles throughout —
    with per-request tracing ON across the whole matrix, and every
    injected fault (LATENCY included, which raises nothing) surfacing
    as a trace event whose (step, site) coordinates the retry events
    then match. (The seed-0 column doubles as the tier-1 smoke; the
    whole matrix is fast enough to stay un-`slow`.)"""
    model, variables = gpt_setup
    plan = FaultPlan(seed=seed, sleep_fn=_no_sleep, **_PROFILES[profile])
    tracer = RequestTracer()
    eng = pin_zero_recompiles(ServeEngine(
        model, variables, max_slots=2, prefill_len=16,
        fault_plan=plan, backoff_sleep=_no_sleep, tracer=tracer))
    handles = [eng.submit(p, n) for p, n in _WORKLOAD]
    eng.run(max_steps=600)
    assert not eng.has_work, "engine failed to drain under chaos"
    for h, ref in zip(handles, workload_refs):
        assert h.done, f"request {h} never reached a terminal state"
        if h.state == RequestState.FINISHED:
            assert h.tokens == ref, \
                f"surviving stream diverged under {profile}/seed {seed}"
    # Observability contract under chaos: injections and recoveries
    # land in the trace with coordinates that line up.
    injected_evs = tracer.events_named("fault_injected")
    assert len(injected_evs) == plan.total_injected
    by_kind = {}
    for ev in injected_evs:
        by_kind[ev["kind"]] = by_kind.get(ev["kind"], 0) + 1
    assert by_kind == {k.value: v for k, v in plan.injected.items() if v}
    injected_coords = {(e["step"], e["site"]) for e in injected_evs}
    retry_evs = tracer.events_named("retry")
    assert len(retry_evs) == eng.metrics.retries
    for ev in retry_evs:
        assert (ev["step"], ev["site"]) in injected_coords, \
            f"retry at uninjected coordinate {(ev['step'], ev['site'])}"
    assert len(tracer.events_named("replay")) \
        == eng.metrics.replays + eng.metrics.requests_failed
    assert len(tracer.events_named("degraded_entry")) \
        == eng.metrics.degraded_entries
    # Every request's span settled with its terminal reason.
    assert tracer.spans_finished >= len(handles)
    assert not tracer.active
    # The engine is still serviceable after the storm (plan exhausted
    # its injection cap, so this completes clean).
    p, n = _WORKLOAD[0]
    again = eng.submit(p, n)
    eng.run(max_steps=100)
    assert again.tokens == workload_refs[0]


# -------------------------------------------------------- targeted faults
def test_transient_tick_retry_recovers_in_place(gpt_setup,
                                                pin_zero_recompiles):
    """A transient burst within max_retries recovers inside the retry
    loop: same stream, retries counted, no replay charged."""
    model, variables = gpt_setup
    p, n = (np.arange(7) * 4 + 3) % 32, 6
    ref = _ref_greedy(model, variables, p, n)
    plan = FaultPlan(scheduled=[FaultSpec(step=2, site="tick",
                                          kind=FaultKind.TRANSIENT,
                                          count=2)])
    eng = pin_zero_recompiles(ServeEngine(
        model, variables, max_slots=2, prefill_len=16, fault_plan=plan,
        max_retries=3, backoff_sleep=_no_sleep))
    h = eng.submit(p, n)
    eng.run(max_steps=100)
    assert h.state == RequestState.FINISHED
    assert h.tokens == ref
    assert eng.metrics.retries == 2
    assert eng.metrics.retry_sites == {"tick": 2}
    assert eng.metrics.replays == 0


def test_scheduled_fault_surfaces_in_trace_at_exact_coordinates(
        gpt_setup):
    """A surgical FaultSpec at (step=2, site="tick", count=2): the
    trace must carry exactly two fault_injected and two retry events at
    that coordinate — the span-event/(step, site) contract the runbook's
    replay-storm diagnosis relies on — and the recovering request's
    span must record its replay-free finish."""
    model, variables = gpt_setup
    p, n = (np.arange(7) * 4 + 3) % 32, 6
    plan = FaultPlan(scheduled=[FaultSpec(step=2, site="tick",
                                          kind=FaultKind.TRANSIENT,
                                          count=2)])
    tracer = RequestTracer()
    eng = ServeEngine(model, variables, max_slots=2, prefill_len=16,
                      fault_plan=plan, max_retries=3,
                      backoff_sleep=_no_sleep, tracer=tracer)
    h = eng.submit(p, n)
    eng.run(max_steps=100)
    assert h.state == RequestState.FINISHED
    injected = tracer.events_named("fault_injected")
    assert [(e["step"], e["site"], e["kind"]) for e in injected] \
        == [(2, "tick", "transient")] * 2
    retries = tracer.events_named("retry")
    assert [(e["step"], e["site"]) for e in retries] == [(2, "tick")] * 2
    assert [e["attempt"] for e in retries] == [1, 2]
    (span,) = list(tracer.finished)
    assert span["finish_reason"] == "length"
    assert span["attrs"]["replays"] == 0
    # The ring saw the same step's retries (telemetry agreement).
    rec = next(r for r in eng.telemetry.snapshot() if r["step"] == 2)
    assert rec["retries"] == 2


def test_tick_retries_exhausted_replays_token_exact(gpt_setup,
                                                    pin_zero_recompiles):
    """Past the retry budget the live slots' KV is declared lost: both
    running requests replay (prompt re-prefilled, emitted tokens re-fed
    through the fused tick) and still finish token-exact."""
    model, variables = gpt_setup
    reqs = [((np.arange(8) * 3 + 1) % 32, 7), ((np.arange(5) + 9) % 32, 6)]
    refs = [_ref_greedy(model, variables, p, n) for p, n in reqs]
    plan = FaultPlan(scheduled=[FaultSpec(step=3, site="tick",
                                          kind=FaultKind.TRANSIENT,
                                          count=8)])
    eng = pin_zero_recompiles(ServeEngine(
        model, variables, max_slots=2, prefill_len=16, fault_plan=plan,
        max_retries=2, backoff_sleep=_no_sleep))
    handles = [eng.submit(p, n) for p, n in reqs]
    eng.run(max_steps=100)
    for h, ref in zip(handles, refs):
        assert h.state == RequestState.FINISHED
        assert h.tokens == ref
        assert h.replays == 1
    assert eng.metrics.replays == 2
    assert eng.metrics.retries == 2  # the budget's two actual retries


def test_replay_admission_queue_wait_counts_from_requeue(gpt_setup):
    """The replay 'admitted' event's queue_wait_s measures time since
    the REQUEUE, not since the original submit — otherwise the first
    service attempt reads as scheduler backlog in the timeline."""
    model, variables = gpt_setup
    clock = _FakeClock()
    plan = FaultPlan(scheduled=[FaultSpec(step=3, site="tick",
                                          kind=FaultKind.TRANSIENT,
                                          count=8)])
    tracer = RequestTracer(clock=clock)
    eng = ServeEngine(model, variables, max_slots=1, prefill_len=16,
                      clock=clock, fault_plan=plan, max_retries=2,
                      backoff_sleep=_no_sleep, tracer=tracer)
    h = eng.submit((np.arange(8) * 3 + 1) % 32, 7)
    for _ in range(100):
        if h.done:
            break
        eng.step()
        clock.now += 1.0
    assert h.state == RequestState.FINISHED
    assert h.replays == 1
    (span,) = list(tracer.finished)
    admits = [e for e in span["events"] if e["name"] == "admitted"]
    assert [a["replay"] for a in admits] == [False, True]
    # One fake-clock second passed between the requeue (mid-step 3)
    # and the replay admission (step 4); the original admission was
    # four seconds before that.
    assert admits[1]["queue_wait_s"] == 1.0
    assert span["duration_s"] > admits[1]["queue_wait_s"]


def test_replay_budget_exhausted_fails_request_not_engine(gpt_setup):
    """Every tick failing forever: requests settle FAILED/ERROR after
    max_replays instead of crash-looping; the engine survives and keeps
    answering."""
    model, variables = gpt_setup
    plan = FaultPlan(sites=("tick",), transient_rate=1.0)
    eng = ServeEngine(model, variables, max_slots=2, prefill_len=16,
                      fault_plan=plan, max_retries=1, max_replays=2,
                      backoff_sleep=_no_sleep)
    handles = [eng.submit((np.arange(4) + i) % 32, 5) for i in range(2)]
    eng.run(max_steps=60)
    assert not eng.has_work
    for h in handles:
        assert h.state == RequestState.FAILED
        assert h.finish_reason == FinishReason.ERROR
        assert h.replays == 3  # budget + the final straw
        assert len(h.tokens) == 1  # the admission-time first token
    snap = eng.metrics.snapshot()
    assert snap["requests_failed"] == 2
    assert snap["requests_finished"] == 0
    assert eng.step() == 0  # still alive, just idle


def test_oom_degrades_flushes_and_rearms(gpt_setup):
    """RESOURCE_EXHAUSTED on the gather path: unpinned pool blocks are
    flushed, donations stop, serving continues cold and token-exact,
    and the prefix cache re-arms (hits resume) after the cool-down."""
    model, variables = gpt_setup
    clock = _FakeClock()
    p = (np.arange(12) * 5 + 1) % 32
    ref = _ref_greedy(model, variables, p, 4)
    plan = FaultPlan()
    eng = ServeEngine(model, variables, max_slots=1, prefill_len=16,
                      clock=clock, fault_plan=plan,
                      degraded_cooldown_s=5.0, backoff_sleep=_no_sleep)
    assert eng.prefix_cache_enabled
    h0 = eng.submit(p, 4)
    eng.run(max_steps=50)
    assert h0.tokens == ref
    assert eng._prefix.blocks_live > 0
    assert eng.prefix_pool_nbytes > 0  # the sheddable-HBM gauge
    # The NEXT admission's gather (a prefix hit on p's chain) OOMs.
    plan._sched[(_next_step(eng), "gather")] = [FaultKind.OOM]
    h1 = eng.submit(p, 4)
    eng.run(max_steps=50)
    assert h1.state == RequestState.FINISHED
    assert h1.tokens == ref  # replayed cold, still exact
    assert h1.replays == 1
    assert eng.degraded
    assert eng._prefix.blocks_live == 0  # flushed (nothing was pinned)
    assert eng.metrics.degraded_entries == 1
    # While degraded: no lookups, no donations, still exact.
    lookups_during = eng.metrics.prefix_lookups
    h2 = eng.submit(p, 4)
    eng.run(max_steps=50)
    assert h2.tokens == ref
    assert eng.metrics.prefix_lookups == lookups_during
    assert eng._prefix.blocks_live == 0
    # Past the cool-down the cache re-arms: donation resumes, then hits.
    clock.now += 6.0
    h3 = eng.submit(p, 4)
    eng.run(max_steps=50)
    assert not eng.degraded
    assert eng.metrics.degraded_time_s > 0
    assert h3.tokens == ref
    assert eng._prefix.blocks_live > 0  # donated again
    hits_before = eng.metrics.prefix_hits
    h4 = eng.submit(p, 4)
    eng.run(max_steps=50)
    assert h4.tokens == ref
    assert eng.metrics.prefix_hits == hits_before + 1  # cache is back


def test_real_error_on_donated_program_never_redispatches(gpt_setup):
    """A REAL device error (not an injected pre-dispatch fault) from a
    donated-buffer program may have consumed its input, so the engine
    must escalate immediately — rebuild the slot pool and replay —
    instead of retrying into a deleted array. Simulated with a fake
    XlaRuntimeError from the insert program."""
    model, variables = gpt_setup
    FakeXla = type("XlaRuntimeError", (RuntimeError,), {})
    reqs = [((np.arange(6) * 3 + 2) % 32, 6), ((np.arange(9) + 5) % 32, 5)]
    refs = [_ref_greedy(model, variables, p, n) for p, n in reqs]
    eng = ServeEngine(model, variables, max_slots=2, prefill_len=16,
                      backoff_sleep=_no_sleep)
    eng.warmup()
    h0 = eng.submit(*reqs[0])
    eng.step()
    assert h0.state == RequestState.RUNNING
    real_insert, calls = eng._insert_p, {"n": 0}

    def flaky_insert(*args):
        calls["n"] += 1
        if calls["n"] == 1:
            raise FakeXla("INTERNAL: interconnect hiccup mid-dispatch")
        return real_insert(*args)

    eng._insert_p = flaky_insert
    try:
        h1 = eng.submit(*reqs[1])
        eng.run(max_steps=100)
    finally:
        eng._insert_p = real_insert
    for h, ref in zip((h0, h1), refs):
        assert h.state == RequestState.FINISHED
        assert h.tokens == ref
    # Escalated, not retried: the failing dispatch was never re-issued
    # (call 2 is the replay admission's fresh insert), the mid-stream
    # neighbor was replayed off the rebuilt pool cache too.
    assert eng.metrics.retries == 0
    assert h0.replays == 1 and h1.replays == 1


# -------------------------------------------------------- drain / restore
def _drain_restore_roundtrip(model, variables, eng_a, snapshot_source):
    """Restore ``snapshot_source`` into a fresh engine and pin every
    stream token-exact against the fault-free reference."""
    eng_b = ServeEngine(model, variables, max_slots=2, prefill_len=16)
    restored = eng_b.restore(snapshot_source)
    eng_b.run(max_steps=200)
    return eng_b, restored


def test_sigterm_drain_restore_roundtrip(gpt_setup, tmp_path):
    """The acceptance round-trip: SIGTERM → flag → drain at the next
    step boundary (snapshot on disk, admission stopped) → fresh engine
    restores → every in-flight request resumes token-exactly."""
    model, variables = gpt_setup
    reqs = [((np.arange(6) * 3 + 2) % 32, 8), ((np.arange(9) + 4) % 32, 7),
            ((np.arange(5) * 7 + 1) % 32, 6), ((np.arange(7) + 11) % 32, 5)]
    refs = [_ref_greedy(model, variables, p, n) for p, n in reqs]
    path = str(tmp_path / "serve_drain.json")
    eng_a = ServeEngine(model, variables, max_slots=2, prefill_len=16)
    eng_a.install_drain_handler(path)
    try:
        handles_a = [eng_a.submit(p, n) for p, n in reqs]
        for _ in range(3):
            eng_a.step()
        # Two running mid-stream, two still queued.
        assert sum(h.state == RequestState.RUNNING for h in handles_a) == 2
        partial = [list(h.tokens) for h in handles_a]
        assert any(partial)
        signal.raise_signal(signal.SIGTERM)
        assert eng_a.step() == 0  # the drain step emits nothing
    finally:
        eng_a.uninstall_drain_handler()
    assert eng_a.drained and not eng_a.has_work
    with pytest.raises(RuntimeError, match="drained"):
        eng_a.submit(reqs[0][0], 2)
    eng_b, restored = _drain_restore_roundtrip(model, variables, eng_a, path)
    assert len(restored) == 4
    # Drain order is running-first; match each restored handle to its
    # original by prompt.
    by_prompt = {tuple(h.request.prompt): h for h in restored}
    for (p, n), ref, part in zip(reqs, refs, partial):
        h = by_prompt[tuple(int(t) for t in p)]
        assert h.state == RequestState.FINISHED
        assert h.tokens == ref          # full stream, token-exact
        assert h.tokens[:len(part)] == part  # resumed, not re-sampled
    # Previously-running requests keep their measured TTFT.
    assert by_prompt[tuple(int(t) for t in reqs[0][0])].ttft_s is not None


def test_kill_point_mid_step_state_still_drainable(gpt_setup):
    """A hard kill-point (BaseException) aborts step() like a real
    SIGKILL would; the host-side request state survives, drains, and
    restores token-exactly — the harshest recovery path."""
    model, variables = gpt_setup
    reqs = [((np.arange(8) * 5 + 3) % 32, 7), ((np.arange(6) + 1) % 32, 6),
            ((np.arange(10) * 3 + 9) % 32, 5)]
    refs = [_ref_greedy(model, variables, p, n) for p, n in reqs]
    plan = FaultPlan(scheduled=[FaultSpec(step=2, site="tick",
                                          kind=FaultKind.KILL)])
    eng_a = ServeEngine(model, variables, max_slots=2, prefill_len=16,
                        fault_plan=plan, backoff_sleep=_no_sleep)
    handles = [eng_a.submit(p, n) for p, n in reqs]
    with pytest.raises(KillPoint):
        eng_a.run(max_steps=100)
    assert any(h.tokens for h in handles)  # it died mid-flight
    snapshot = eng_a.drain()
    assert len(snapshot["requests"]) == 3
    eng_b, restored = _drain_restore_roundtrip(model, variables, eng_a,
                                               snapshot)
    by_prompt = {tuple(h.request.prompt): h for h in restored}
    for (p, n), ref in zip(reqs, refs):
        h = by_prompt[tuple(int(t) for t in p)]
        assert h.state == RequestState.FINISHED
        assert h.tokens == ref


def test_drain_preserves_remaining_deadline_budget(gpt_setup):
    """Deadline semantics survive the round trip: wall budget consumed
    before the drain stays consumed in the restoring engine."""
    model, variables = gpt_setup
    clock_a = _FakeClock()
    eng_a = ServeEngine(model, variables, max_slots=1, prefill_len=16,
                        clock=clock_a)
    h = eng_a.submit(np.arange(4) % 32, 30, deadline_s=10.0)
    eng_a.step()
    clock_a.now = 7.0  # 7s of the 10s budget burned
    snapshot = eng_a.drain()
    clock_b = _FakeClock()
    clock_b.now = 100.0  # a different epoch entirely
    eng_b = ServeEngine(model, variables, max_slots=1, prefill_len=16,
                        clock=clock_b)
    (restored,) = eng_b.restore(snapshot)
    eng_b.step()
    assert restored.state == RequestState.RUNNING
    clock_b.now += 4.0  # 7 + 4 > 10: the budget is spent
    eng_b.step()
    assert restored.state == RequestState.TIMED_OUT


def test_cross_process_drain_restore_roundtrip(tmp_path):
    """The snapshot is a real WIRE format, not an in-process artifact:
    written by one interpreter (`tests/_serve_drain_child.py` — builds
    the deterministic fleet-worker engine, serves, drains on disk),
    restored token-exactly in THIS interpreter. Pins what the in-process
    round-trip cannot: JSON serialization fidelity, version checking,
    and param-derivation determinism across processes (the fleet's
    migration path crosses exactly this boundary)."""
    import json
    import os
    import subprocess

    from pddl_tpu.serve.fleet.worker import build_engine

    workload = [
        {"prompt": ((np.arange(10) * 5 + 1) % 64).tolist(),
         "max_new_tokens": 8},
        {"prompt": ((np.arange(13) * 3 + 7) % 64).tolist(),
         "max_new_tokens": 7},
        {"prompt": ((np.arange(7) + 17) % 64).tolist(),
         "max_new_tokens": 6},
        {"prompt": ((np.arange(11) * 7 + 2) % 64).tolist(),
         "max_new_tokens": 5},
    ]
    cfg = dict(vocab=64, max_len=128, embed_dim=64, depth=2, heads=2,
               slots=2, prefill_len=32, max_queue_depth=64, param_seed=3,
               steps_before_drain=3, workload=workload)
    child = os.path.join(os.path.dirname(__file__), "_serve_drain_child.py")
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["PYTHONPATH"] = root + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, child, str(tmp_path), json.dumps(cfg)],
        env=env, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, f"drain child failed:\n{proc.stderr[-3000:]}"
    with open(tmp_path / "state.json") as f:
        child_state = json.load(f)
    assert any(child_state["partial_tokens"]), "child drained nothing live"
    assert "running" in child_state["states"]

    engine = build_engine(cfg)  # fresh engine, THIS interpreter
    restored = engine.restore(str(tmp_path / "snapshot.json"))
    assert len(restored) == len(workload)
    engine.run(max_steps=500)
    refs = [_ref_greedy(engine.model, {"params": engine._params},
                        req["prompt"], req["max_new_tokens"])
            for req in workload]
    by_prompt = {tuple(h.request.prompt): h for h in restored}
    for req, ref, part in zip(workload, refs,
                              child_state["partial_tokens"]):
        h = by_prompt[tuple(req["prompt"])]
        assert h.state == RequestState.FINISHED
        assert h.tokens == ref                 # full stream, token-exact
        assert h.tokens[:len(part)] == part    # resumed, not re-sampled


# ---------------------------------------------------- backpressure hints
def test_retry_after_hint_monotone_nonnegative():
    """Property (seeded sweep): whatever admission history the engine
    has seen, ``estimate_retry_after_s`` is non-negative and monotone
    non-decreasing in queue depth — a deeper queue never promises a
    SHORTER wait (that inversion is what turns polite backoff into a
    retry storm)."""
    from pddl_tpu.serve.metrics import ServeMetrics

    rng = np.random.default_rng(0)
    warm_trials = 0
    for _ in range(25):
        m = ServeMetrics()
        t = 0.0
        for _ in range(int(rng.integers(0, 40))):
            t += float(rng.exponential(rng.uniform(0.01, 2.0)))
            m.record_admission(t)
        depths = sorted(int(rng.integers(0, 64)) for _ in range(10))
        hints = [m.estimate_retry_after_s(d) for d in depths]
        if m.recent_admission_interval_s() is None:
            assert all(h is None for h in hints)  # honest cold answer
            continue
        warm_trials += 1
        assert all(h is not None and h >= 0.0 for h in hints)
        assert all(a <= b for a, b in zip(hints, hints[1:])), \
            f"hint not monotone over depths {depths}: {hints}"
    assert warm_trials >= 10  # the sweep exercised the warm estimator


@pytest.mark.parametrize("priority", list(Priority))
def test_polite_client_never_sees_consecutive_queue_fulls(gpt_setup,
                                                          priority):
    """Property (seeded runs, ALL THREE priority classes): a client
    that HONORS ``retry_after_s`` (waits the hinted interval while the
    engine keeps draining) never gets rejected twice in a row — the
    hint really does estimate when a queue slot frees, for
    ``best_effort`` (whose hint prices the whole queue ahead of it)
    just as for ``interactive``. Un-hinted rejections (cold estimator)
    are exempt: there was nothing to honor."""
    model, variables = gpt_setup
    for seed in (0, 1, 2):
        rng = np.random.default_rng(seed)
        clock = _FakeClock()
        eng = ServeEngine(model, variables, max_slots=1, prefill_len=16,
                          max_queue_depth=3, clock=clock)

        def pump(dt, *, eng=eng, clock=clock):
            # The draining engine: steps keep happening as time passes.
            for _ in range(max(1, int(dt / 0.25))):
                eng.step()
                clock.now += 0.25

        submitted, last_full_hinted = 0, False
        while submitted < 25:
            prompt = (np.arange(int(rng.integers(4, 10)))
                      + submitted) % 32
            try:
                eng.submit(prompt, int(rng.integers(2, 5)),
                           priority=priority)
                submitted += 1
                last_full_hinted = False
            except QueueFull as e:
                if e.retry_after_s is not None:
                    assert not last_full_hinted, \
                        (f"seed {seed}: consecutive QueueFulls for a "
                         f"client honoring retry_after_s")
                    assert e.retry_after_s >= 0.0
                    last_full_hinted = True
                    pump(e.retry_after_s + 0.25)
                else:
                    last_full_hinted = False
                    pump(0.25)
            if rng.random() < 0.5:
                pump(0.25)
        eng.run(max_steps=1000)


def test_queue_full_carries_retry_after_hint(gpt_setup):
    model, variables = gpt_setup
    clock = _FakeClock()
    eng = ServeEngine(model, variables, max_slots=1, prefill_len=16,
                      max_queue_depth=2, clock=clock)
    # Cold engine: no admission history yet, the hint is honestly None.
    cold = ServeEngine(model, variables, max_slots=1, prefill_len=16,
                       max_queue_depth=1)
    cold.submit(np.arange(4) % 32, 2)
    with pytest.raises(QueueFull) as exc:
        cold.submit(np.arange(4) % 32, 2)
    assert exc.value.retry_after_s is None
    # Build admission history at ~1 admission/s.
    for i in range(4):
        eng.submit((np.arange(4) + i) % 32, 2)
        eng.run(max_steps=10)
        clock.now += 1.0
    # Now saturate: one long request holds the slot, two fill the queue.
    eng.submit(np.arange(5) % 32, 30)
    eng.step()
    eng.submit((np.arange(5) + 1) % 32, 2)
    eng.submit((np.arange(5) + 2) % 32, 2)
    with pytest.raises(QueueFull) as exc:
        eng.submit((np.arange(5) + 3) % 32, 2)
    hint = exc.value.retry_after_s
    assert hint is not None
    # depth 2 x ~1s/admission: the hint scales with the queue ahead.
    assert 1.0 <= hint <= 4.0
    assert "retry after" in str(exc.value)


def test_deadline_shed_at_pop_time(gpt_setup):
    """Scheduler-level shedding: a queued handle whose deadline expired
    is failed at pop time with FinishReason.DEADLINE — before it can
    burn prefill budget or a slot."""
    sched = FCFSScheduler(max_queue_depth=8)
    fresh = RequestHandle(Request(prompt=[1, 2], max_new_tokens=2),
                          arrival_s=0.0)
    doomed = RequestHandle(Request(prompt=[3, 4], max_new_tokens=2,
                                   deadline_s=5.0), arrival_s=0.0)
    sched.submit(doomed)
    sched.submit(fresh)
    shed = []
    admitted = sched.admit(2, on_expired=shed.append, now_fn=lambda: 9.0)
    assert admitted == [fresh]
    assert shed == [doomed]
    assert doomed.state == RequestState.TIMED_OUT
    assert doomed.finish_reason == FinishReason.DEADLINE
    # Engine-level accounting: the shed lands in its own counter.
    model, variables = gpt_setup
    clock = _FakeClock()
    eng = ServeEngine(model, variables, max_slots=1, prefill_len=16,
                      clock=clock)
    running = eng.submit(np.arange(4) % 32, 30)
    eng.step()  # admit `running` before the deadlined request exists
    #             (EDF pops real deadlines ahead of deadline-less work)
    dead = eng.submit(np.arange(5) % 32, 4, deadline_s=5.0)
    eng.step()
    clock.now = 6.0
    running.cancel()
    eng.run(max_steps=50)
    assert dead.state == RequestState.TIMED_OUT
    assert dead.finish_reason == FinishReason.DEADLINE
    assert dead.tokens == []
    snap = eng.metrics.snapshot()
    assert snap["requests_deadline_shed"] == 1
    assert snap["requests_timed_out"] == 0  # disjoint counters


# -------------------------------------------------------- refcount hygiene
def _refcount_baseline(prefix):
    """(all refs zero, accounting exact) over the whole radix tree."""
    stack = [prefix._root]
    while stack:
        node = stack.pop()
        stack.extend(node.children.values())
        if node is not prefix._root and node.ref != 0:
            return False
    return (prefix.blocks_live + prefix.blocks_free
            == prefix.num_blocks - 1)


@pytest.mark.chaos
def test_cancel_storm_refcounts_return_to_baseline(gpt_setup,
                                                   pin_zero_recompiles):
    """A seeded storm of shared-prefix admissions — half cancelled at
    random moments, deadlines expiring in the queue, faults injected
    throughout — must leave every radix refcount at zero and the block
    accounting exact once the engine drains: no unwind path may leak a
    pinned chain."""
    model, variables = gpt_setup
    rng = np.random.default_rng(42)
    clock = _FakeClock()
    plan = FaultPlan(seed=7, transient_rate=0.05, oom_rate=0.02,
                     max_random_injections=25, sleep_fn=_no_sleep)
    eng = pin_zero_recompiles(ServeEngine(
        model, variables, max_slots=2, prefill_len=16, clock=clock,
        prefix_cache_blocks=6, max_queue_depth=64, fault_plan=plan,
        degraded_cooldown_s=3.0, backoff_sleep=_no_sleep))
    shared = (np.arange(8) * 3 + 2) % 32
    handles = []
    for round_i in range(6):
        for j in range(4):
            tail = rng.integers(0, 32, size=int(rng.integers(1, 7)))
            prompt = np.concatenate([shared, tail]).astype(np.int32)[:15]
            deadline = 4.0 if rng.random() < 0.3 else None
            handles.append(eng.submit(prompt, int(rng.integers(2, 6)),
                                      deadline_s=deadline))
        for _ in range(int(rng.integers(1, 4))):
            eng.step()
            clock.now += 0.5
            for h in handles:
                if not h.done and rng.random() < 0.25:
                    h.cancel()
    eng.run(max_steps=400)
    assert not eng.has_work
    assert all(h.done for h in handles)
    assert _refcount_baseline(eng._prefix), \
        "cancel/fault storm leaked a pinned prefix chain"
    # The engine is healthy: one more request completes exact.
    clock.now += 10.0  # clear any degraded window
    p = (np.arange(10) * 5 + 3) % 32
    h = eng.submit(p, 4)
    eng.run(max_steps=50)
    assert h.tokens == _ref_greedy(model, variables, p, 4)
    assert _refcount_baseline(eng._prefix)


# ------------------------------------------------------------- fault plan
def test_fault_plan_determinism_and_validation():
    """Same seed + same call sequence = same injections; bad configs
    are loud."""
    def drive(plan):
        fired = []
        plan.on_step(0)
        for i in range(200):
            try:
                plan.check("tick")
            except Exception as e:
                fired.append((i, type(e).__name__))
        return fired

    a = drive(FaultPlan(seed=3, transient_rate=0.1, oom_rate=0.05,
                        sleep_fn=_no_sleep))
    b = drive(FaultPlan(seed=3, transient_rate=0.1, oom_rate=0.05,
                        sleep_fn=_no_sleep))
    c = drive(FaultPlan(seed=4, transient_rate=0.1, oom_rate=0.05,
                        sleep_fn=_no_sleep))
    assert a and a == b
    assert a != c
    with pytest.raises(ValueError, match="sum to <= 1"):
        FaultPlan(transient_rate=0.8, oom_rate=0.4)
    with pytest.raises(ValueError, match="unknown fault site"):
        FaultPlan(sites=("warp_core",))
    with pytest.raises(ValueError, match="unknown scheduled site"):
        FaultPlan(scheduled=[FaultSpec(0, "nope", FaultKind.KILL)])
    plan = FaultPlan(seed=0, latency_rate=1.0, latency_s=2.5,
                     max_random_injections=3, sleep_fn=_no_sleep)
    slept = []
    plan._sleep = slept.append
    plan.on_step(0)
    for _ in range(10):
        plan.check("tick")
    assert slept == [2.5] * 3  # latency fires, then the cap holds
    assert plan.injected[FaultKind.LATENCY] == 3
