"""Multi-replica serving fleet (`pddl_tpu/serve/fleet/`), CPU.

The contracts under test:

- **Chaos matrix** (3 seeds x N in {2, 4}, ``@pytest.mark.fleet`` +
  ``chaos``): a seeded kill-point takes one replica down mid-stream;
  every in-flight request reaches a terminal state, every FINISHED
  stream is token-identical to an unkilled oracle run (live migration
  via the drain wire format), and zero recompiles hold on every
  surviving replica (the per-replica ``pin_zero_recompiles``).
- **Routing**: prefix affinity lands shared-prefix prompts on the
  replica whose (shadow) radix cache holds them; sticky sessions keep
  multi-turn traffic in place; rendezvous hashing is deterministic;
  QueueFull sheds to the least-loaded healthy replica and only a
  fleet-wide full rejects, with the smallest retry_after hint.
- **Circuit breaker**: CLOSED→OPEN on consecutive failures, HALF_OPEN
  probe after bounded exponential backoff, probe success respawns the
  replica and returns orphaned requests to service.
- **Hard-kill fallback**: a replica that cannot drain (SIGKILL'd
  worker process) migrates via the router's prompt+token mirrors and
  still finishes token-exact.
- **Observability**: fleet events (replica_down, migration, circuit)
  flow through the tracer; ``fleet_exposition`` renders and re-parses
  through the strict Prometheus referee.
"""

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pddl_tpu.models.gpt import generate, tiny_gpt
from pddl_tpu.obs import RequestTracer, fleet_exposition, parse_prometheus_text
from pddl_tpu.serve import FaultKind, FaultPlan, QueueFull, ServeEngine
from pddl_tpu.serve.fleet import (
    BreakerState,
    CircuitBreaker,
    FleetRouter,
    LocalReplica,
    NoHealthyReplica,
    ReplicaDied,
)
from pddl_tpu.serve.request import Priority, RequestState
from conftest import ref_greedy as _ref_greedy, FakeClock as _FakeClock

pytestmark = pytest.mark.fleet


@pytest.fixture(scope="module")
def gpt_setup():
    model = tiny_gpt(vocab_size=32, max_len=64)
    prompt = jnp.ones((1, 8), jnp.int32)
    params = model.init(jax.random.key(0), prompt, train=False)["params"]
    return model, {"params": params}


def _no_sleep(_):
    pass


def _local_fleet(model, variables, n, *, with_plans=False, clock=None,
                 respawn=True, tracer=None, max_queue_depth=64,
                 breaker=None, **router_kw):
    """N LocalReplica fleet over one shared tiny model; each replica
    gets its own (initially empty) fault plan so tests can schedule
    surgical kills after routing settles."""
    plans = [FaultPlan(sleep_fn=_no_sleep) if with_plans else None
             for _ in range(n)]

    def factory(plan):
        def make():
            # Engine prefix cache OFF: routing affinity lives in the
            # ROUTER's shadow index, and migration replay is prefix-
            # agnostic — the 4-program engine keeps the matrix fast
            # while the zero-recompile pin still covers every replica.
            return ServeEngine(model, variables, max_slots=2,
                               prefill_len=16, fault_plan=plan,
                               max_queue_depth=max_queue_depth,
                               prefix_cache_blocks=0,
                               backoff_sleep=_no_sleep)
        return make

    replicas = [LocalReplica(i, factory(plans[i])) for i in range(n)]
    fleet = FleetRouter(replicas, affinity_block_size=8, affinity_blocks=1,
                        respawn=respawn, tracer=tracer,
                        breaker=breaker,
                        clock=clock if clock is not None else time.monotonic,
                        **router_kw)
    return fleet, plans


def _workload(n_requests, seed=0):
    """Distinct prompt heads (spread over the hash ring) plus a shared-
    prefix pair (the affinity case); greedy, so streams are oracle-
    comparable."""
    rng = np.random.default_rng(seed)
    reqs = []
    for i in range(n_requests):
        if i % 4 == 3 and reqs:  # every 4th shares the previous prompt
            p, _ = reqs[-1]
            reqs.append((p, int(rng.integers(3, 7))))
        else:
            plen = int(rng.integers(6, 15))
            reqs.append((rng.integers(0, 32, size=plen).astype(np.int32),
                         int(rng.integers(3, 8))))
    return reqs


# ---------------------------------------------------------- chaos matrix
@pytest.mark.chaos
@pytest.mark.parametrize("seed", [0, 1, 2])
@pytest.mark.parametrize("n_replicas", [2, 4])
def test_fleet_kill_matrix(gpt_setup, pin_zero_recompiles, seed,
                           n_replicas):
    """Kill one of N replicas mid-stream (seeded kill-point at its next
    tick): every request terminal, survivors token-exact vs the
    unkilled oracle, zero recompiles on every surviving replica, and
    the death/migration visible in the fleet trace."""
    model, variables = gpt_setup
    tracer = RequestTracer()
    fleet, plans = _local_fleet(model, variables, n_replicas,
                                with_plans=True, respawn=False,
                                tracer=tracer)
    fleet = pin_zero_recompiles(fleet)
    reqs = _workload(3 * n_replicas, seed=seed)
    refs = [_ref_greedy(model, variables, p, n) for p, n in reqs]
    handles = [fleet.submit(p, n) for p, n in reqs]
    # Let streams start, then schedule a kill on the busiest replica's
    # NEXT tick — guaranteed mid-stream, whatever the routing chose.
    for _ in range(2):
        fleet.step()
    victim = max((s for s in fleet.replicas), key=lambda s: s.load)
    assert victim.load > 0
    eng = victim.driver.engine
    plans[victim.replica_id]._sched[(eng._step_idx + seed % 2, "tick")] = \
        [FaultKind.KILL]
    fleet.run(max_steps=600)
    assert not fleet.has_work, "fleet failed to drain after the kill"
    finished = 0
    for h, ref in zip(handles, refs):
        assert h.done, f"request {h} never reached a terminal state"
        if h.state == RequestState.FINISHED:
            finished += 1
            assert h.tokens == ref, \
                f"stream diverged (seed {seed}, N={n_replicas}): {h}"
    assert finished == len(handles)  # kills lose no requests at all
    assert fleet.metrics.replica_down_events == 1
    assert fleet.metrics.requests_migrated >= 1
    assert fleet.metrics.migrated_via_drain >= 1  # live migration path
    downs = tracer.events_named("replica_down")
    assert len(downs) == 1 and downs[0]["replica"] == victim.replica_id
    assert tracer.events_named("migration")
    # The fleet still serves after the loss.
    p, n = reqs[0]
    again = fleet.submit(p, n)
    fleet.run(max_steps=200)
    assert again.tokens == refs[0]


def test_cascading_death_mid_restore_stays_token_exact(gpt_setup):
    """The restore TARGET dies mid-migration, after streaming one more
    token for a request it partially restored. The retry pass must
    rebuild wire entries from the router's freshened mirrors — reusing
    the original snapshot would re-emit that token and break stream
    exactness."""
    model, variables = gpt_setup
    armed = {}

    def factory():
        return ServeEngine(model, variables, max_slots=2, prefill_len=16,
                           max_queue_depth=64, prefix_cache_blocks=0,
                           backoff_sleep=_no_sleep)

    class DiesMidRestore(LocalReplica):
        def __init__(self, rid):
            super().__init__(rid, factory)
            self.die_on_step = False
            self._late = []

        def step(self):
            if self.die_on_step:
                self.die_on_step = False
                raise ReplicaDied(self.replica_id, "injected death")
            return super().step()

        def restore(self, pairs):
            if armed.pop("on", None):
                rid, entry = pairs[0]
                sofar = [int(t) for t in entry["tokens"]]
                nxt = _ref_greedy(model, variables, entry["prompt"],
                                  len(sofar) + 1)[-1]
                self._late.append({"ev": "tokens", "toks": [(rid, [nxt])]})
                raise ReplicaDied(self.replica_id, "died mid-restore")
            super().restore(pairs)

        def take_pending(self):
            events = super().take_pending()
            events += self._late
            self._late = []
            return events

    fleet = FleetRouter([DiesMidRestore(i) for i in range(3)],
                        affinity_block_size=8, affinity_blocks=1,
                        respawn=False)
    reqs = _workload(9, seed=5)
    refs = [_ref_greedy(model, variables, p, n) for p, n in reqs]
    handles = [fleet.submit(p, n) for p, n in reqs]
    for _ in range(2):
        fleet.step()
    victim = max(fleet.replicas, key=lambda s: s.load)
    assert victim.load > 0
    victim.driver.die_on_step = True
    armed["on"] = True  # first restore target dies mid-restore
    fleet.run(max_steps=600)
    assert fleet.metrics.replica_down_events == 2
    for h, ref in zip(handles, refs):
        assert h.done
        assert h.state == RequestState.FINISHED
        assert h.tokens == ref, "stream diverged across cascaded deaths"


# -------------------------------------------------------------- routing
def test_prefix_affinity_routes_to_cache_holder(gpt_setup):
    model, variables = gpt_setup
    fleet, _ = _local_fleet(model, variables, 2)
    shared = ((np.arange(12) * 3 + 5) % 32).astype(np.int32)
    h0 = fleet.submit(shared, 3)
    first_replica = h0.replica_id
    fleet.run(max_steps=100)
    # Same leading blocks, different tail: must land where the cache is.
    tail = np.concatenate([shared[:8], (np.arange(5) + 2) % 32]) \
        .astype(np.int32)
    h1 = fleet.submit(tail, 3)
    assert h1.replica_id == first_replica
    assert fleet.metrics.routed_affinity >= 1
    fleet.run(max_steps=100)
    assert h1.tokens == _ref_greedy(model, variables, tail, 3)


def test_priority_aware_routing_sheds_interactive_off_hot_affinity(
        gpt_setup):
    """ROADMAP item 5's unclaimed follow-on, made discriminative: with
    the affinity replica under load-pressure, an INTERACTIVE request
    abandons the warm cache for the least-loaded healthy replica
    (labeled ``load``), while a BATCH request with the SAME warm
    prefix keeps pure affinity — the cache is worth a queue wait only
    to traffic without an interactive SLO."""
    model, variables = gpt_setup
    fleet, _ = _local_fleet(model, variables, 2,
                            interactive_reroute_load=2)
    shared = ((np.arange(12) * 3 + 5) % 32).astype(np.int32)
    h0 = fleet.submit(shared, 3)
    hot = h0.replica_id
    fleet.run(max_steps=100)

    def _variant(t):
        return np.concatenate([shared[:8], [t]]).astype(np.int32)

    # Pile un-stepped load onto the warm replica (affinity routes the
    # shared head straight back to it).
    pressure = [fleet.submit(_variant(2 + i), 4) for i in range(2)]
    assert all(h.replica_id == hot for h in pressure)
    # Batch priority, same warm prefix, same pressure: stays put.
    hb = fleet.submit(_variant(20), 3, priority=Priority.BATCH)
    assert hb.replica_id == hot
    assert fleet.metrics.routed_load_balanced == 0
    # Interactive under the same pressure: least-loaded replica wins.
    hi = fleet.submit(_variant(21), 3)
    assert hi.replica_id != hot
    assert fleet.metrics.routed_load_balanced == 1
    fleet.run(max_steps=300)
    for h, t in [(hb, 20), (hi, 21)]:
        assert h.tokens == _ref_greedy(model, variables, _variant(t), 3)


def test_sticky_sessions_and_rendezvous_determinism(gpt_setup):
    model, variables = gpt_setup
    fleet, _ = _local_fleet(model, variables, 4)
    p = (np.arange(9) * 5 + 1) % 32
    a = fleet.submit(p, 2, session="alice")
    b = fleet.submit((np.arange(7) + 3) % 32, 2, session="alice")
    assert b.replica_id == a.replica_id  # sticky beats hash
    assert fleet.metrics.routed_sticky >= 1
    fleet.run(max_steps=100)
    # Rendezvous: identical cold prompt heads route identically (fresh
    # fleet — no shadow state).
    fleet2, _ = _local_fleet(model, variables, 4)
    q = (np.arange(10) * 7 + 2) % 32
    picks = {fleet2.submit(np.concatenate([q[:8], [i]]).astype(np.int32),
                           2).replica_id
             for i in range(3)}
    # Hmm-free determinism: the 8-token head dominates affinity_blocks=1
    # (one 8-token block), so all three share a hash key.
    assert len(picks) == 1
    fleet2.run(max_steps=100)


def test_queue_full_sheds_to_least_loaded_then_rejects(gpt_setup):
    model, variables = gpt_setup
    fleet, _ = _local_fleet(model, variables, 2, max_queue_depth=2)
    # Fill replica chosen by the hash for this head, then keep going:
    # overflow must shed to the sibling, and only a fleet-wide full
    # queue rejects the caller.
    p = (np.arange(9) * 5 + 1) % 32
    handles = []
    shed_before = fleet.metrics.shed_rerouted
    with pytest.raises(QueueFull) as exc:
        for i in range(12):
            handles.append(fleet.submit(p, 30))
    assert fleet.metrics.shed_rerouted > shed_before
    assert fleet.metrics.shed_rejected == 1
    assert exc.value.queue_depth > 0
    by_replica = {}
    for h in handles:
        by_replica[h.replica_id] = by_replica.get(h.replica_id, 0) + 1
    assert len(by_replica) == 2  # both replicas took load
    for h in handles:
        h.cancel()
    fleet.run(max_steps=300)


def test_no_healthy_replica_raises(gpt_setup):
    model, variables = gpt_setup
    clock = _FakeClock()
    fleet, plans = _local_fleet(model, variables, 1, with_plans=True,
                                clock=clock, respawn=False)
    h = fleet.submit((np.arange(6) + 1) % 32, 8)
    plans[0]._sched[(2, "tick")] = [FaultKind.KILL]
    fleet.run(max_steps=50)
    assert fleet.healthy_replicas == 0
    with pytest.raises(NoHealthyReplica):
        fleet.submit((np.arange(6) + 1) % 32, 2)
    # With no possible recovery the in-flight request failed terminally
    # rather than hanging forever.
    assert h.done


# ------------------------------------------------------ circuit breaker
def test_circuit_breaker_transitions_and_backoff():
    transitions = {}

    def count(old, new):
        key = f"{old.value}->{new.value}"
        transitions[key] = transitions.get(key, 0) + 1

    br = CircuitBreaker(failure_threshold=2, backoff_base_s=1.0,
                        backoff_max_s=4.0, on_transition=count)
    assert br.state is BreakerState.CLOSED and br.allows_traffic
    br.record_failure(0.0)
    assert br.state is BreakerState.CLOSED  # below threshold
    br.record_failure(0.0)
    assert br.state is BreakerState.OPEN and not br.allows_traffic
    assert not br.probe_due(0.5) and br.probe_due(1.0)
    br.begin_probe(1.0)
    assert br.state is BreakerState.HALF_OPEN
    br.record_failure(1.0)  # probe failed: re-open, backoff doubled
    assert br.state is BreakerState.OPEN
    assert not br.probe_due(2.9) and br.probe_due(3.0)
    br.begin_probe(3.0)
    br.record_failure(3.0)  # doubled again (4.0, at the cap)
    br.begin_probe(7.0)
    br.record_success(7.0)  # recovery: CLOSED, backoff reset
    assert br.state is BreakerState.CLOSED
    br.record_failure(8.0)
    br.record_failure(8.0)
    assert br.probe_due(9.0)  # back at the base interval
    assert transitions["closed->open"] == 2
    assert transitions["half_open->open"] == 2
    with pytest.raises(RuntimeError, match="must be open"):
        CircuitBreaker().begin_probe(0.0)  # probing a closed circuit


def test_replica_respawn_revives_orphans_token_exact(gpt_setup):
    """Single-replica fleet: the kill orphans the in-flight requests;
    past the breaker backoff a HALF_OPEN probe respawns the engine and
    the orphans replay to token-exact completion."""
    model, variables = gpt_setup
    clock = _FakeClock()
    tracer = RequestTracer()
    fleet, plans = _local_fleet(
        model, variables, 1, with_plans=True, clock=clock, respawn=True,
        tracer=tracer, breaker={"backoff_base_s": 2.0})
    reqs = [((np.arange(8) * 3 + 1) % 32, 6), ((np.arange(5) + 9) % 32, 5)]
    refs = [_ref_greedy(model, variables, p, n) for p, n in reqs]
    handles = [fleet.submit(p, n) for p, n in reqs]
    plans[0]._sched[(2, "tick")] = [FaultKind.KILL]
    fleet.run(max_steps=20)
    assert fleet.healthy_replicas == 0
    assert fleet.metrics.requests_orphaned == 2
    assert all(not h.done for h in handles)  # parked, not failed
    clock.now += 5.0  # past the backoff: the next step probes
    fleet.run(max_steps=300)
    for h, ref in zip(handles, refs):
        assert h.state == RequestState.FINISHED
        assert h.tokens == ref
        assert h.migrations >= 1
    assert fleet.metrics.replica_up_events == 1
    assert fleet.metrics.probes == 1
    assert tracer.events_named("replica_up")
    assert any(e["transition"] == "open->half_open"
               for e in tracer.events_named("circuit"))


# --------------------------------------------------------- process fleet
def test_process_fleet_sigkill_migration_token_exact():
    """Two real worker processes; SIGKILL one mid-stream. The router
    cannot drain a SIGKILL'd worker, so migration runs off its own
    prompt+token mirrors — and every stream still finishes token-exact
    vs an oracle engine with the same param seed."""
    from pddl_tpu.serve.fleet import ProcessReplica
    from pddl_tpu.serve.fleet.worker import build_engine

    cfg = dict(vocab=64, max_len=128, embed_dim=64, depth=2, heads=2,
               slots=4, prefill_len=32, max_queue_depth=64, param_seed=0,
               prefix_cache_blocks=0)  # 4-program engine: exact pin set
    reps = [ProcessReplica(i, {**cfg, "replica_id": i},
                           python=sys.executable) for i in range(2)]
    fleet = FleetRouter(reps, affinity_block_size=8, affinity_blocks=1,
                        respawn=False)
    try:
        rng = np.random.default_rng(0)
        prompts = [rng.integers(0, 64, size=12).tolist()
                   for _ in range(8)]
        handles = [fleet.submit(p, 16) for p in prompts]
        assert len({h.replica_id for h in handles}) == 2
        deadline = time.monotonic() + 60
        while sum(len(h.tokens) for h in handles) < 20 \
                and time.monotonic() < deadline:
            fleet.step()
        victim_id = handles[0].replica_id
        victim = next(s for s in fleet.replicas
                      if s.replica_id == victim_id)
        assert victim.load > 0
        victim.driver.kill()  # SIGKILL: no drain possible
        fleet.run(max_steps=400000, idle_sleep_s=0.002)
        assert all(h.done for h in handles)
        eng = build_engine(cfg)
        for p, h in zip(prompts, handles):
            assert h.state == RequestState.FINISHED
            assert h.tokens == _ref_greedy(eng.model,
                                           {"params": eng._params}, p, 16)
        assert fleet.metrics.replica_down_events == 1
        assert fleet.metrics.migrated_via_replay >= 1
        assert fleet.metrics.migrated_via_drain == 0
        # Zero recompiles on the surviving worker.
        counts = fleet.compile_counts()
        survivor = 1 - victim_id
        assert counts and all(
            v == 1 for k, v in counts.items()
            if k.startswith(f"r{survivor}/"))
    finally:
        fleet.close()


def test_sigkill_after_finish_settles_from_pipe_buffer():
    """A SIGKILL'd worker's stdout stays readable until EOF: finish
    events it wrote before dying must settle their handles from the
    residual OS pipe buffer, not replay-migrate (here: fail, no
    survivors) an already-complete stream."""
    import select

    from pddl_tpu.serve.fleet import ProcessReplica
    from pddl_tpu.serve.fleet.worker import build_engine

    cfg = dict(vocab=32, max_len=64, embed_dim=32, depth=1, heads=2,
               slots=2, prefill_len=16, max_queue_depth=8, param_seed=0,
               prefix_cache_blocks=0, replica_id=0)
    rep = ProcessReplica(0, cfg, python=sys.executable)
    fleet = FleetRouter([rep], affinity_block_size=8, affinity_blocks=1,
                        respawn=False)
    try:
        prompt = [3, 1, 4, 1, 5]
        h = fleet.submit(prompt, 4)
        # Let the worker finish and write its events WITHOUT the router
        # reading the pipe; first readable byte, then a settle window
        # for the rest of the batch (4 tokens on a warm engine: ~ms).
        fd = rep._proc.stdout.fileno()
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            readable, _, _ = select.select([fd], [], [], 0.1)
            if readable:
                time.sleep(0.5)
                break
        rep.kill()
        rep._proc.wait(timeout=10)
        fleet.run(max_steps=1000)  # death surfaces; capture runs
        assert h.state == RequestState.FINISHED
        eng = build_engine(cfg)
        assert h.tokens == _ref_greedy(eng.model,
                                       {"params": eng._params}, prompt, 4)
        assert fleet.metrics.requests_failed == 0
        assert fleet.metrics.requests_migrated == 0
        assert fleet.metrics.requests_orphaned == 0
    finally:
        fleet.close()


def test_worker_rejects_bad_restore_entry_and_stays_alive():
    """One corrupt migrated entry (undecodable wire dict) must fail
    THAT request terminally — never crash the healthy survivor it was
    being restored onto (which would cascade one bad mirror into a
    second replica loss)."""
    from pddl_tpu.serve.fleet import ProcessReplica
    from pddl_tpu.serve.request import SamplingParams

    cfg = dict(vocab=32, max_len=64, embed_dim=32, depth=1, heads=2,
               slots=2, prefill_len=16, max_queue_depth=8, param_seed=0,
               prefix_cache_blocks=0, replica_id=0)
    rep = ProcessReplica(0, cfg, python=sys.executable)
    try:
        rep.restore([(7, {"tokens": [1, 2]})])  # no prompt: undecodable
        deadline = time.monotonic() + 30
        finish = None
        while finish is None and time.monotonic() < deadline:
            for ev in rep.step():
                if ev.get("ev") == "finish" and ev.get("rid") == 7:
                    finish = ev
        assert finish is not None, "bad entry never settled"
        assert finish["state"] == RequestState.FAILED.value
        # The worker survived: a fresh request still serves end-to-end.
        rep.submit(8, list(range(1, 7)), 3, SamplingParams(), None)
        deadline = time.monotonic() + 30
        ok = False
        while not ok and time.monotonic() < deadline:
            for ev in rep.step():
                if ev.get("ev") == "finish" and ev.get("rid") == 8:
                    assert ev["state"] == RequestState.FINISHED.value
                    ok = True
        assert ok, "worker did not serve after rejecting the bad entry"
    finally:
        rep.close()


def test_cancelled_orphans_settle_during_total_outage(gpt_setup):
    """cancel() must lead to a terminal state even for ORPHANS — parked
    requests no live replica holds. Without the step()-time sweep, an
    unbounded run() spins on has_work through an outage whose probes
    never succeed."""
    model, variables = gpt_setup
    clock = _FakeClock()
    fleet, plans = _local_fleet(model, variables, 1, with_plans=True,
                                clock=clock, respawn=True)
    handles = [fleet.submit((np.arange(6) + i) % 32, 6) for i in range(2)]
    plans[0]._sched[(2, "tick")] = [FaultKind.KILL]
    fleet.run(max_steps=20)
    assert fleet.metrics.requests_orphaned == 2
    for h in handles:
        h.cancel()
    fleet.run(max_steps=10)  # clock frozen: no probe fires
    for h in handles:
        assert h.state == RequestState.CANCELLED
    assert not fleet.has_work
    assert not fleet._by_rid


def test_router_idle_gap_is_not_heartbeat_silence():
    """beat_age_s is the age of the oldest UNANSWERED ping, never time
    since the last read: a router that idles between bursts must not
    wake up, see a stale read-timestamp on every healthy worker, and
    breaker-kill them before a single pong could round-trip."""
    from pddl_tpu.serve.fleet import ProcessReplica

    cfg = dict(vocab=32, max_len=64, embed_dim=32, depth=1, heads=2,
               slots=2, prefill_len=16, max_queue_depth=8, param_seed=0,
               prefix_cache_blocks=0, replica_id=0)
    clock = _FakeClock(1000.0)
    rep = ProcessReplica(0, cfg, python=sys.executable, clock=clock)
    try:
        deadline = time.monotonic() + 30
        rep.step()  # sends a ping: outstanding until the pong reads
        # Frozen fake clock: an outstanding ping also reads age 0, so
        # wait on the marker itself for the pong to actually land.
        while rep._unanswered_ping_s is not None \
                and time.monotonic() < deadline:
            time.sleep(0.01)
            rep.step()
        assert rep._unanswered_ping_s is None
        assert rep.beat_age_s() == 0.0
        clock.now += 100.0  # long idle gap, nothing in flight
        assert rep.beat_age_s() == 0.0  # the gap is OUR silence, not its
        rep.step()  # fresh ping: age anchors to this send, not the gap
        assert rep.beat_age_s() <= 1.0
    finally:
        rep.close()


def test_fleet_drain_includes_snapshot_absent_assigned(gpt_setup):
    """A request assigned to a replica but missing from its drain
    snapshot (e.g. a migration restore still buffered unread in a
    worker's stdin pipe) must enter the fleet-wide drain from the
    router's mirrors — the leftovers rule death handling applies — and
    restore token-exactly, never vanish from a drain that reported
    success."""
    model, variables = gpt_setup

    class Forgetful(LocalReplica):
        def drain_entries(self, now_s):
            return super().drain_entries(now_s)[1:]  # "unread" request

    def factory():
        return ServeEngine(model, variables, max_slots=2, prefill_len=16,
                           prefix_cache_blocks=0)

    fleet = FleetRouter([Forgetful(0, factory)], respawn=False)
    reqs = [(list(range(1, 9)), 5), (list(range(3, 10)), 4)]
    refs = [_ref_greedy(model, variables, p, n) for p, n in reqs]
    handles = [fleet.submit(p, n) for p, n in reqs]
    for _ in range(2):
        fleet.step()
    snapshot = fleet.drain()
    assert len(snapshot["requests"]) == 2  # nothing vanished
    fresh = factory()
    restored = fresh.restore(snapshot)
    while any(not h.done for h in restored):
        fresh.step()
    by_prompt = {tuple(h.request.prompt): h.tokens for h in restored}
    for (p, _n), ref, fh in zip(reqs, refs, handles):
        assert by_prompt[tuple(p)] == ref
        del fh  # fleet handles stay QUEUED/RUNNING post-drain by design


def test_local_drain_entries_encode_on_engine_clock(gpt_setup):
    """``elapsed_s`` (consumed deadline budget) is a same-epoch
    difference: the capture must encode against the ENGINE's clock the
    handles' ``arrival_s`` was stamped on, not the router's — a chaos
    router driving a fake clock over real-clock engines would
    otherwise snapshot a zero (or garbage) budget."""
    from pddl_tpu.serve import ServeEngine

    eng_clock = _FakeClock(100.0)
    rep = LocalReplica(0, lambda: ServeEngine(
        gpt_setup[0], gpt_setup[1], max_slots=2, prefill_len=16,
        prefix_cache_blocks=0, clock=eng_clock))
    rep.submit(3, list(range(1, 9)), 4, None, None)
    eng_clock.now = 103.0
    (rid, entry), = rep.drain_entries(5.0)  # router epoch: meaningless
    assert rid == 3
    assert entry["elapsed_s"] == pytest.approx(3.0)


# -------------------------------------------------------- observability
def test_fleet_exposition_renders_and_reparses(gpt_setup):
    model, variables = gpt_setup
    clock = _FakeClock()
    fleet, plans = _local_fleet(model, variables, 2, with_plans=True,
                                clock=clock, respawn=False)
    reqs = _workload(4, seed=7)
    handles = [fleet.submit(p, n) for p, n in reqs]
    for _ in range(2):
        fleet.step()
    victim = max(fleet.replicas, key=lambda s: s.load)
    plans[victim.replica_id]._sched[
        (victim.driver.engine._step_idx, "tick")] = [FaultKind.KILL]
    fleet.run(max_steps=300)
    assert all(h.done for h in handles)
    text = fleet_exposition(fleet)
    samples, types = parse_prometheus_text(text)  # the strict referee
    assert samples[("pddl_fleet_replicas", ())] == 2.0
    assert samples[("pddl_fleet_replicas_healthy", ())] == 1.0
    assert samples[("pddl_fleet_replica_down_events_total", ())] == 1.0
    assert samples[("pddl_fleet_requests_migrated_total", ())] >= 1.0
    assert types["pddl_fleet_requests_migrated_total"] == "counter"
    dead = (("key", f"r{victim.replica_id}"),)
    assert samples[("pddl_fleet_replica_state", dead)] == 0.0
    assert samples[("pddl_fleet_replica_breaker_open", dead)] == 1.0
    # Circuit transitions surfaced as flattened counters.
    assert any(name.startswith("pddl_fleet_circuit_")
               for name, _ in samples)
