"""Router high availability (`fleet/standby.py` + the fencing-epoch
plumbing through journal/router/transport/worker/replica), CPU.

The contracts under test (ISSUE 20):

- **Lease = single-writer token**: the file-backed lease's epoch
  increments exactly on holder change; acquisition against a live
  foreign lease is a typed :class:`LeaseHeld`; renewal by a deposed
  holder reports False. The keeper's renewal jitter is SUBTRACTIVE
  and seeded (the r21 breaker/spawn discipline) — a jittered renewal
  can only land EARLY, so jitter can never push a renewal past the
  lease's safety margin.
- **WAL shipping + tail fold**: every journal append (NON_DURABLE
  backlog included) ships as one CRC-framed line; the standby's fold
  matches ``journal.read_state`` exactly, dedups by journal seq, and
  heals wire gaps with a disk catch-up (counted).
- **Fenced hot takeover**: promotion fences every worker at the new
  epoch FIRST, then rebuilds a router over the SAME live drivers and
  mirror-replays (r11 contract) — token-exact, zero recompiles. The
  deposed-but-alive primary's every subsequent command is a typed
  :class:`EpochFenced` reject on every worker — and the negative
  control shows an UNFENCED (epoch-free) command still passes, so the
  refusal is provably the epoch's doing.
- **Loss window under r21 storage faults**: promoting off a
  NON_DURABLE primary with the wire also dead loses exactly the
  fsync-batched token deltas — whose replay regenerates identical
  tokens.
- **Observability**: ``takeovers`` / ``fenced_commands_refused`` /
  ``standby_catchups`` counters and ``router_epoch`` / ``lease_age_s``
  / ``standby_lag_records`` gauges round-trip through the strict
  Prometheus referee in both directions, NaN when unarmed.
"""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pddl_tpu.models.gpt import tiny_gpt
from pddl_tpu.obs import fleet_exposition, parse_prometheus_text
from pddl_tpu.serve import ServeEngine
from pddl_tpu.serve.fleet import (
    EpochFenced,
    FleetRouter,
    HotStandby,
    Lease,
    LeaseHeld,
    LeaseKeeper,
    LocalReplica,
    RouterJournal,
    WalShipper,
    WalTail,
)
from pddl_tpu.serve.fleet import journal as journal_io
from pddl_tpu.serve.request import Request, RequestState, SamplingParams
from pddl_tpu.utils.faults import StorageFaultPlan
from conftest import FakeClock, ref_greedy as _ref_greedy

pytestmark = pytest.mark.ha


@pytest.fixture(scope="module")
def gpt_setup():
    model = tiny_gpt(vocab_size=32, max_len=64)
    prompt = jnp.ones((1, 8), jnp.int32)
    params = model.init(jax.random.key(0), prompt, train=False)["params"]
    return model, {"params": params}


def _no_sleep(_):
    pass


def _local_fleet(model, variables, n, **router_kw):
    def factory():
        return ServeEngine(model, variables, max_slots=2,
                           prefill_len=16, max_queue_depth=64,
                           prefix_cache_blocks=0,
                           backoff_sleep=_no_sleep)
    replicas = [LocalReplica(i, factory) for i in range(n)]
    return FleetRouter(replicas, affinity_block_size=8,
                       affinity_blocks=1, respawn=False, **router_kw)


def _workload(n_requests, seed=0, vocab=32):
    rng = np.random.default_rng(seed)
    reqs = []
    for _ in range(n_requests):
        plen = int(rng.integers(6, 15))
        reqs.append((rng.integers(0, vocab, size=plen).astype(np.int32),
                     int(rng.integers(3, 8))))
    return reqs


_ROUTER_KW = dict(affinity_block_size=8, affinity_blocks=1,
                  respawn=False)


def _armed_pair(tmp_path, fleet, journal, *, ttl_s=1.0, clock=None):
    """The deployment shape the runbook documents: a lease-armed
    primary (``set_epoch(keeper.acquire())`` — without this the
    primary's commands are epoch-free and fencing has nothing to
    refuse) plus a hot standby attached to its WAL shipper."""
    clock = clock or FakeClock(0.0)
    lease = Lease(str(tmp_path / "ha_lease.json"), ttl_s=ttl_s,
                  clock=clock)
    keeper = LeaseKeeper(lease, "primary", seed=0)
    fleet.set_epoch(keeper.acquire())
    fleet.ha = keeper
    standby = HotStandby(str(tmp_path / "wal"),
                         [s.driver for s in fleet.replicas],
                         lease=lease, holder="standby", seed=1,
                         router_kw=dict(_ROUTER_KW))
    shipper = WalShipper(journal, standby.feed)
    standby.attach(shipper)
    return clock, lease, keeper, standby, shipper


# ----------------------------------------------------------- the lease
def test_lease_single_writer_epoch_semantics(tmp_path):
    clock = FakeClock(0.0)
    lease = Lease(str(tmp_path / "lease.json"), ttl_s=1.0, clock=clock)
    assert lease.read() is None and lease.age_s() is None
    assert lease.expired()                    # never held = expired
    assert lease.acquire("a") == 1            # first holder arms epoch 1
    assert lease.acquire("a") == 1            # re-acquire: same holder,
    assert lease.renew("a")                   # same epoch; renew extends
    with pytest.raises(LeaseHeld) as ei:      # a live foreign lease is
        lease.acquire("b")                    # a typed refusal
    assert ei.value.other == "a" and ei.value.remaining_s > 0
    clock.now = 0.5
    assert lease.age_s() == pytest.approx(0.5)
    assert lease.acquire("b", steal=True) == 2  # forced failover bumps
    assert not lease.renew("a")               # deposed: must stop
    clock.now = 2.0                           # b's lease lapses
    assert lease.expired()
    assert lease.acquire("a") == 3            # every holder change bumps
    with pytest.raises(ValueError, match="ttl_s"):
        Lease(str(tmp_path / "x.json"), ttl_s=0.0)


def test_lease_keeper_validation_and_subtractive_jitter(tmp_path):
    clock = FakeClock(0.0)
    lease = Lease(str(tmp_path / "lease.json"), ttl_s=0.9, clock=clock)
    with pytest.raises(ValueError, match="jitter_frac"):
        LeaseKeeper(lease, "a", jitter_frac=1.0)
    with pytest.raises(ValueError, match="jitter_frac"):
        LeaseKeeper(lease, "a", jitter_frac=-0.1)
    with pytest.raises(ValueError, match="renew_every_s"):
        LeaseKeeper(lease, "a", renew_every_s=0.9)   # == ttl: no margin
    with pytest.raises(ValueError, match="renew_every_s"):
        LeaseKeeper(lease, "a", renew_every_s=0.0)
    # The jitter property: every drawn interval sits in
    # ((1 - frac) * renew_every_s, renew_every_s] — SUBTRACTIVE, so a
    # jittered renewal always lands no later than the unjittered one
    # and can never eat the (ttl - renew_every_s) safety margin.
    k = LeaseKeeper(lease, "a", renew_every_s=0.3, jitter_frac=0.9,
                    seed=42)
    draws = [k._interval_s() for _ in range(500)]
    assert all(0.3 * (1.0 - 0.9) < d <= 0.3 for d in draws)
    assert len(set(draws)) > 400              # it actually jitters
    twin = LeaseKeeper(lease, "a", renew_every_s=0.3, jitter_frac=0.9,
                       seed=42)
    assert draws == [twin._interval_s() for _ in range(500)]  # seeded
    other = LeaseKeeper(lease, "a", renew_every_s=0.3,
                        jitter_frac=0.9, seed=43)
    assert draws != [other._interval_s() for _ in range(500)]


def test_lease_keeper_never_expires_while_stepped_then_deposes(tmp_path):
    # Drive a keeper with maximal jitter across many renewals under a
    # fake clock: as long as step() runs at all, the lease NEVER
    # expires — the operational meaning of "jitter cannot delay
    # renewal past the safety margin".
    clock = FakeClock(0.0)
    lease = Lease(str(tmp_path / "lease.json"), ttl_s=0.9, clock=clock)
    keeper = LeaseKeeper(lease, "primary", renew_every_s=0.3,
                         jitter_frac=0.9, seed=7)
    keeper.acquire()
    for _ in range(2000):
        clock.now += 0.05
        assert not lease.expired(), "renewal landed past the margin"
        assert keeper.step()
    assert keeper.renewals >= 300
    # Depose it: a standby steals; the keeper's next due renewal
    # reports False and latches.
    assert lease.acquire("standby", steal=True) == 2
    clock.now += 0.9
    assert keeper.step() is False and keeper.deposed
    assert keeper.step() is False             # latched
    assert keeper.lag_records() is None       # a primary has no lag


# ------------------------------------------------- shipper + tail fold
def test_wal_shipper_tail_fold_matches_read_state(tmp_path):
    d = str(tmp_path / "wal")
    j = RouterJournal(d, fsync_batch_records=2)
    tail = WalTail(d)
    shipper = WalShipper(j, tail.feed)
    r0 = Request(prompt=[1, 2, 3], max_new_tokens=5,
                 sampling=SamplingParams())
    r1 = Request(prompt=[4, 5], max_new_tokens=3,
                 sampling=SamplingParams())
    j.append(journal_io.encode_admit(0, r0, "sess-a"), durable=True)
    j.append(journal_io.encode_route(0, 1, "hash"))
    j.append(journal_io.encode_fence_epoch(7), durable=True)
    j.append(journal_io.encode_tokens(0, [9, 8]))
    j.append(journal_io.encode_admit(1, r1, None), durable=True)
    j.append(journal_io.encode_tokens(1, [4]))
    j.append(journal_io.encode_finish(1, "finished", "stop"))
    assert shipper.shipped == 7 and shipper.ship_errors == 0
    assert tail.records_folded == 7 and tail.lag_records() == 0
    assert sorted(tail.entries) == [0]        # rid 1 finished
    assert tail.entries[0]["prompt"] == [1, 2, 3]
    assert tail.entries[0]["tokens"] == [9, 8]
    assert tail.entries[0]["session"] == "sess-a"
    assert tail.bindings == {0: 1}
    assert tail.primary_epoch == 7
    assert tail.next_rid == 2
    # The live fold IS the recovery fold: commit and compare against
    # read_state (tokens/session/prompt of the one open stream).
    j.commit()
    entries, next_rid = journal_io.read_state(d)
    assert next_rid == tail.next_rid
    assert sorted(entries) == sorted(tail.entries)
    assert entries[0]["tokens"] == tail.entries[0]["tokens"]
    j.close()


def test_wal_tail_wire_gap_heals_via_disk_catchup(tmp_path):
    d = str(tmp_path / "wal")
    j = RouterJournal(d, fsync_batch_records=1)
    tail = WalTail(d, gap_feeds=3)
    dropped = {"n": 0}

    def lossy_sink(line):
        dropped["n"] += 1
        if dropped["n"] == 3:
            return                            # one frame lost forever
        tail.feed(line)

    shipper = WalShipper(j, lossy_sink)
    r = Request(prompt=[1, 2, 3], max_new_tokens=9,
                sampling=SamplingParams())
    j.append(journal_io.encode_admit(0, r, None), durable=True)
    j.append(journal_io.encode_route(0, 0, "hash"))
    j.append(journal_io.encode_tokens(0, [5]))          # the lost frame
    assert tail.covered_seq == 2
    # Three more feeds arrive behind the unhealable gap; the third
    # trips the catch-up, which refolds from disk and then drains the
    # frames the gap left buffered — nothing is lost, nothing doubled.
    j.append(journal_io.encode_tokens(0, [6]))
    j.append(journal_io.encode_tokens(0, [7]))
    assert tail.covered_seq == 2 and tail.lag_records() == 3
    j.append(journal_io.encode_tokens(0, [8]))
    assert tail.catchups == 1
    assert tail.covered_seq == 6 and tail.lag_records() == 0
    assert tail.entries[0]["tokens"] == [5, 6, 7, 8]
    assert shipper.shipped == 6
    j.close()


def test_standby_join_and_midstream_attach(tmp_path):
    d = str(tmp_path / "wal")
    j = RouterJournal(d, fsync_batch_records=1)
    shipper = WalShipper(j, lambda line: None)   # nobody listening yet
    r = Request(prompt=[2, 2], max_new_tokens=4,
                sampling=SamplingParams())
    j.append(journal_io.encode_admit(0, r, None), durable=True)
    j.append(journal_io.encode_tokens(0, [3]))
    lease = Lease(str(tmp_path / "lease.json"), ttl_s=1.0,
                  clock=FakeClock(0.0))
    standby = HotStandby(d, [], lease=lease)
    # Join = the constructor's disk catch-up: history folded without
    # ever having seen a frame.
    assert standby.tail.catchups == 1
    assert standby.tail.entries[0]["tokens"] == [3]
    standby.attach(shipper)                      # mid-stream: frame seq
    j.append(journal_io.encode_tokens(0, [9]))   # space re-aligned
    assert standby.lag_records() == 0
    assert standby.tail.entries[0]["tokens"] == [3, 9]
    assert standby.tail.catchups == 1            # no gap, no catch-up
    j.close()


# ----------------------------------------------------- fenced takeover
def test_hot_takeover_token_exact_zero_recompiles(
        gpt_setup, pin_zero_recompiles, tmp_path):
    """The tentpole path: primary serves halfway, its lease lapses,
    the standby promotes over the SAME live replicas — every stream
    finishes token-identical to the unkilled oracle with zero
    recompiles, under a bumped fencing epoch."""
    model, variables = gpt_setup
    d = str(tmp_path / "wal")
    journal = RouterJournal(d, fsync_batch_records=4)
    fleet = _local_fleet(model, variables, 2, journal=journal)
    clock, lease, keeper, standby, shipper = _armed_pair(
        tmp_path, fleet, journal)
    assert fleet.epoch == 1
    reqs = _workload(6, seed=3)
    refs = {tuple(int(t) for t in p): _ref_greedy(model, variables, p, n)
            for p, n in reqs}
    handles = [fleet.submit(p, n) for p, n in reqs]
    for _ in range(4):
        fleet.step()                  # partial progress, then the
        keeper.step()                 # primary silently dies
    fleet = pin_zero_recompiles(fleet)  # same engines survive takeover
    acked = {tuple(int(t) for t in h.request.prompt): list(h.tokens)
             for h in handles}
    clock.now = 5.0                   # the lease lapses un-renewed
    out = standby.step()
    assert out is not None and standby.promoted
    router, revived = out
    assert standby.step() is None     # the pair is returned exactly once
    assert router.epoch == 2          # holder change bumped the epoch
    assert lease.read()["holder"] == "standby"
    assert keeper.step() is False     # the deposed primary learns it
    router.run(max_steps=4000)
    assert router.metrics.takeovers == 1
    assert router.metrics.standby_catchups >= 1
    # Every acked-unfinished stream revived and landed on the oracle;
    # already-finished ones keep their (also oracle-exact) tokens.
    open_keys = {k for k, t in acked.items()
                 if len(t) < len(refs[k])}
    revived_keys = set()
    for old_rid, fh in revived.items():
        key = tuple(int(t) for t in fh.request.prompt)
        revived_keys.add(key)
        assert fh.state == RequestState.FINISHED, f"rid {old_rid}: {fh}"
        assert fh.tokens == refs[key], "stream diverged over takeover"
    assert open_keys <= revived_keys, "an acked open stream was lost"
    # Takeover's first act after replay was a fresh verified checkpoint.
    assert journal_io.load_checkpoint(d) is not None
    router.close()


def test_deposed_primary_fenced_on_every_worker(gpt_setup, tmp_path):
    """The split-brain discriminant. A partitioned-but-alive primary
    keeps commanding after the standby promoted: 100% of its commands
    are typed :class:`EpochFenced` rejects, counted, on EVERY worker.
    The negative control — an epoch-FREE command still passes — proves
    the refusal is the fencing epoch's doing: this test fails against
    an unfenced router."""
    model, variables = gpt_setup
    d = str(tmp_path / "wal")
    journal = RouterJournal(d, fsync_batch_records=4)
    fleet = _local_fleet(model, variables, 2, journal=journal)
    clock, lease, keeper, standby, shipper = _armed_pair(
        tmp_path, fleet, journal)
    handles = [fleet.submit(p, n) for p, n in _workload(4, seed=1)]
    for _ in range(3):
        fleet.step()
    # Full bidirectional silence: the primary neither renews nor hears
    # the standby; it stays alive and keeps trying to command.
    clock.now = 5.0
    out = standby.step()
    assert out is not None and standby.promoted
    router, revived = out
    assert router.epoch == 2
    # The deposed primary's next commands: refused, typed, counted.
    probes = [([1 + (k % 30)] * (6 + k), 4) for k in range(3)]
    refused_before = fleet.metrics.fenced_commands_refused
    for p, n in probes:
        with pytest.raises(EpochFenced) as ei:
            fleet.submit(p, n)
        assert ei.value.epoch == 1 and ei.value.highest == 2
    assert fleet.metrics.fenced_commands_refused - refused_before == 3
    # ...and not just whichever replica routing picked: EVERY worker
    # holds the fence floor against the stale epoch.
    for slot in fleet.replicas:
        with pytest.raises(EpochFenced):
            slot.driver.cancel(0, epoch=1)
    # Negative control (the unfenced-router shape): an epoch-free
    # command sails through on every worker — exactly why arming the
    # primary's epoch is mandatory, and what this discriminant would
    # MISS if the router under test never stamped epochs.
    for slot in fleet.replicas:
        slot.driver.cancel(424242)            # no raise: accepted
    router.run(max_steps=4000)
    for fh in revived.values():
        assert fh.state == RequestState.FINISHED
    router.close()


def test_takeover_off_non_durable_primary_loss_window(
        gpt_setup, tmp_path):
    """Takeover x r21 storage faults, wire ALSO dead (the partition
    case): the standby inherits the in-memory backlog semantics — the
    loss window is exactly the fsync-batched token deltas — and the
    r11 replay regenerates identical tokens, so every stream still
    lands on the oracle."""
    model, variables = gpt_setup
    d = str(tmp_path / "wal")
    sp = StorageFaultPlan(seed=0)
    journal = RouterJournal(d, storage_plan=sp, fsync_batch_records=2,
                            retry_limit=1, retry_backoff_s=0.0,
                            rearm_interval_s=1e9, sleep_fn=_no_sleep)
    fleet = _local_fleet(model, variables, 2, journal=journal)
    clock = FakeClock(0.0)
    lease = Lease(str(tmp_path / "ha_lease.json"), ttl_s=1.0,
                  clock=clock)
    keeper = LeaseKeeper(lease, "primary", seed=0)
    fleet.set_epoch(keeper.acquire())
    # The standby joined from disk but the replication wire is DOWN —
    # the shipper's frames go nowhere (its sink predates the standby).
    standby = HotStandby(d, [s.driver for s in fleet.replicas],
                         lease=lease, holder="standby", seed=1,
                         router_kw=dict(_ROUTER_KW),
                         journal_kw=dict(fsync_batch_records=2))
    WalShipper(journal, lambda line: None)
    reqs = _workload(5, seed=9)
    refs = {tuple(int(t) for t in p): _ref_greedy(model, variables, p, n)
            for p, n in reqs}
    handles = [fleet.submit(p, n) for p, n in reqs]
    for _ in range(2):
        fleet.step()                       # admissions durable on disk
    sp._rates = (1.0, 0.0, 0.0, 0.0)       # then the disk dies
    for _ in range(4):
        fleet.step()
    assert journal.non_durable
    assert fleet.metrics.journal_degraded_events >= 1
    acked = {tuple(int(t) for t in h.request.prompt): list(h.tokens)
             for h in handles}
    # The primary dies partitioned; the standby's disk catch-up sees
    # only the durable prefix: the backlog token deltas are the loss
    # window (strictly behind at least one acked stream).
    sp.quiesce()                           # the standby's own I/O path
    clock.now = 5.0
    out = standby.step()
    assert out is not None
    router, revived = out
    behind = [
        rid for rid, fh in revived.items()
        if len(standby.tail.entries.get(rid, {}).get("tokens", []))
        < len(acked.get(tuple(int(t) for t in fh.request.prompt), []))]
    assert behind, "no loss window: the NON_DURABLE backlog leaked " \
                   "to disk, or the primary never streamed"
    router.run(max_steps=4000)
    for fh in revived.values():
        key = tuple(int(t) for t in fh.request.prompt)
        assert fh.state == RequestState.FINISHED
        assert fh.tokens == refs[key], \
            "replayed loss-window deltas diverged from the oracle"
    assert router.metrics.takeovers == 1
    router.close()


# -------------------------------------------------------- observability
def test_ha_exposition_series_both_directions(gpt_setup, tmp_path):
    model, variables = gpt_setup
    d = str(tmp_path / "wal")
    journal = RouterJournal(d, fsync_batch_records=4)
    fleet = _local_fleet(model, variables, 2, journal=journal)
    clock, lease, keeper, standby, shipper = _armed_pair(
        tmp_path, fleet, journal)
    handles = [fleet.submit(p, n) for p, n in _workload(3, seed=5)]
    for _ in range(3):
        fleet.step()
    # Primary-side gauges: epoch armed, lease fresh, no lag (a primary
    # has none: NaN).
    clock.now = 0.25
    samples, types = parse_prometheus_text(fleet_exposition(fleet))
    assert samples[("pddl_fleet_router_epoch", ())] == 1.0
    assert samples[("pddl_fleet_lease_age_s", ())] \
        == pytest.approx(0.25)
    assert math.isnan(samples[("pddl_fleet_standby_lag_records", ())])
    assert types["pddl_fleet_router_epoch"] == "gauge"
    # Promote; probe the deposed primary once so the refusal counter
    # moves; then scrape the PROMOTED router.
    clock.now = 5.0
    router, _ = standby.step()
    with pytest.raises(EpochFenced):
        fleet.submit([3, 3, 3, 3, 3, 3], 4)
    router.run(max_steps=4000)
    samples, types = parse_prometheus_text(fleet_exposition(router))
    m = router.metrics
    for key, want in [("takeovers", m.takeovers),
                      ("fenced_commands_refused",
                       m.fenced_commands_refused),
                      ("standby_catchups", m.standby_catchups)]:
        name = f"pddl_fleet_{key}_total"
        assert types[name] == "counter"
        assert samples[(name, ())] == float(want)
    assert m.takeovers == 1 and m.standby_catchups >= 1
    assert samples[("pddl_fleet_router_epoch", ())] == 2.0
    assert samples[("pddl_fleet_standby_lag_records", ())] == 0.0
    assert samples[("pddl_fleet_lease_age_s", ())] >= 0.0
    # The deposed primary's own scrape shows ITS refusal count.
    psamples, _ = parse_prometheus_text(fleet_exposition(fleet))
    assert psamples[(("pddl_fleet_fenced_commands_refused_total"),
                     ())] == float(fleet.metrics.fenced_commands_refused)
    router.close()
    # Unarmed fleet: all three gauges present, NaN — "HA off" is
    # distinguishable from "metric vanished"; counters render 0.
    bare = _local_fleet(model, variables, 1)
    samples, _ = parse_prometheus_text(fleet_exposition(bare))
    assert math.isnan(samples[("pddl_fleet_router_epoch", ())])
    assert math.isnan(samples[("pddl_fleet_lease_age_s", ())])
    assert math.isnan(samples[("pddl_fleet_standby_lag_records", ())])
    assert samples[("pddl_fleet_takeovers_total", ())] == 0.0
    bare.close()
