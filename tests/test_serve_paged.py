"""True paged attention (`ops/attention.paged_*` + ``ServeEngine(paged=True)``).

The contracts under test:

- **Op-level numerics**: `paged_decode_attention`'s jnp reference path
  equals dense `decode_attention` over the equivalent contiguous cache
  (GQA, per-row depths, sliding window, multi-token chunks), and the
  Pallas kernel (interpret mode on CPU) equals the reference — the
  tier-1 oracle chain the TPU hot path hangs off.
- **Write discipline**: `paged_cache_insert` lands each token in its
  table-mapped block; padding junk beyond the table deflects to the
  scratch sink and can never corrupt a real block.
- **Engine token-exactness**: the paged engine — no resident slot
  cache, prefix hits PINNED in place, suffix blocks appended in place,
  donation a pure refcount hand-off — emits exactly what the
  resident-row engine and one-shot ``generate()`` emit, across
  GPT/Llama/int8 and across cold, prefix-hit, preempted, and replayed
  streams.
- **Sharing with zero copies**: concurrent shared-prefix streams
  reference the SAME pool blocks (``blocks_shared`` > 0), admission
  records the gather bytes it no longer pays (``copy_bytes_avoided``),
  and a block-aligned repeat dedups onto the stored chain instead of
  growing the pool.
- **Resilience parity**: the 3-seed chaos matrix, drain/restore (v3
  snapshots carry block tables; v2 snapshots restore through the same
  replay path), and the zero-recompile pin all hold in paged mode.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from conftest import ref_greedy as _ref_greedy
from pddl_tpu.models.gpt import tiny_gpt
from pddl_tpu.models.llama import tiny_llama
from pddl_tpu.obs.export import parse_prometheus_text, serve_exposition
from pddl_tpu.ops.attention import (
    decode_attention,
    paged_cache_insert,
    paged_decode_attention,
    paged_decode_attention_kernel,
)
from pddl_tpu.serve import ServeEngine
from pddl_tpu.serve.faults import FaultPlan
from pddl_tpu.serve.request import Priority, RequestState

pytestmark = pytest.mark.paged

_no_sleep = lambda s: None  # noqa: E731


@pytest.fixture(scope="module")
def gpt_setup():
    model = tiny_gpt(vocab_size=32, max_len=64)
    prompt = jnp.ones((1, 8), jnp.int32)
    params = model.init(jax.random.key(0), prompt, train=False)["params"]
    return model, {"params": params}


@pytest.fixture(scope="module")
def llama_setup():
    model = tiny_llama(vocab_size=32, max_len=64)
    prompt = jnp.ones((1, 8), jnp.int32)
    params = model.init(jax.random.key(1), prompt, train=False)["params"]
    return model, {"params": params}


# ------------------------------------------------------------- op level
def _random_paged(rng, b, hkv, bs, t, d):
    """A pool + disjoint per-row linear tables + the DENSE cache they
    spell (the oracle's view)."""
    n = 1 + b * t
    kp = jnp.asarray(rng.randn(n, hkv, bs, d), jnp.float32)
    vp = jnp.asarray(rng.randn(n, hkv, bs, d), jnp.float32)
    table = np.zeros((b, t), np.int32)
    for i in range(b):
        table[i] = 1 + i * t + np.arange(t)
    kc = np.asarray(kp)[table].transpose(0, 2, 1, 3, 4).reshape(
        b, hkv, t * bs, d)
    vc = np.asarray(vp)[table].transpose(0, 2, 1, 3, 4).reshape(
        b, hkv, t * bs, d)
    return kp, vp, table, jnp.asarray(kc), jnp.asarray(vc)


@pytest.mark.parametrize("hq,hkv", [(4, 4), (4, 2)])
def test_paged_reference_matches_dense_decode(hq, hkv):
    """Per-row depths (the serving tick's shape), MHA and GQA: the
    paged jnp path == decode_attention over the equivalent contiguous
    cache."""
    rng = np.random.RandomState(0)
    b, bs, t, d = 3, 4, 6, 8
    kp, vp, table, kc, vc = _random_paged(rng, b, hkv, bs, t, d)
    q = jnp.asarray(rng.randn(b, hq, 1, d), jnp.float32)
    index = np.array([5, 17, 0], np.int32)
    ref = decode_attention(q, kc, vc, index)
    got = paged_decode_attention(q, kp, vp, table, index, kernel=False,
                                 blocks_per_chunk=2)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_paged_reference_multi_token_and_window():
    """The chunk-prefill shape (batch-1, s>1 at a scalar offset) and
    sliding-window masking both match the dense oracle."""
    rng = np.random.RandomState(1)
    b, hkv, bs, t, d, s = 1, 2, 4, 6, 8, 5
    kp, vp, table, kc, vc = _random_paged(rng, b, hkv, bs, t, d)
    q = jnp.asarray(rng.randn(b, 4, s, d), jnp.float32)
    ref = decode_attention(q, kc, vc, np.int32(7))
    got = paged_decode_attention(q, kp, vp, table, np.int32(7),
                                 kernel=False, blocks_per_chunk=3)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)
    q1 = jnp.asarray(rng.randn(b, 4, 1, d), jnp.float32)
    ref_w = decode_attention(q1, kc, vc, np.int32(13), window=6)
    got_w = paged_decode_attention(q1, kp, vp, table, np.int32(13),
                                   window=6, kernel=False)
    np.testing.assert_allclose(np.asarray(got_w), np.asarray(ref_w),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("hq,hkv", [(4, 4), (4, 2)])
def test_paged_kernel_matches_reference(hq, hkv):
    """The Pallas kernel (scalar-prefetched block table driving the
    K/V index maps), interpret mode on CPU, == the jnp oracle — per-row
    depths including a zero-depth (freshly admitted) row."""
    rng = np.random.RandomState(2)
    b, bs, t, d = 3, 4, 6, 8
    kp, vp, table, kc, vc = _random_paged(rng, b, hkv, bs, t, d)
    q = jnp.asarray(rng.randn(b, hq, 1, d), jnp.float32)
    index = np.array([23, 0, 8], np.int32)
    ref = paged_decode_attention(q, kp, vp, table, index, kernel=False)
    got = paged_decode_attention_kernel(q, kp, vp, table, index,
                                        interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_paged_cache_insert_and_scratch_deflection():
    """Each slot's token lands at (table[pos//bs], pos%bs); positions
    past the table land in the scratch sink, and no real block outside
    the write set changes."""
    rng = np.random.RandomState(3)
    b, hkv, bs, t, d = 3, 2, 4, 6, 8
    kp, vp, table, _, _ = _random_paged(rng, b, hkv, bs, t, d)
    index = np.array([5, 17, 0], np.int32)
    kv = jnp.asarray(rng.randn(b, hkv, 1, d), jnp.float32)
    out = paged_cache_insert(kp, kv, table, index)
    for i in range(b):
        got = np.asarray(out[table[i, index[i] // bs], :, index[i] % bs])
        np.testing.assert_array_equal(got, np.asarray(kv[i, :, 0]))
    # Batch-1 multi-token chunk write (the block-granular RMW path):
    # tokens land contiguously at their (block, offset) homes...
    kv2 = jnp.asarray(rng.randn(1, hkv, 10, d), jnp.float32)
    start = 9  # mid-block start, spans blocks 2..4
    out2 = paged_cache_insert(kp, kv2, table[:1], np.int32(start))
    for j in range(10):
        pos = start + j
        got = np.asarray(out2[table[0, pos // bs], :, pos % bs])
        np.testing.assert_array_equal(got, np.asarray(kv2[0, :, j]))
    # ...earlier tokens in the first span block survive the RMW...
    np.testing.assert_array_equal(
        np.asarray(out2[table[0, start // bs], :, : start % bs]),
        np.asarray(kp[table[0, start // bs], :, : start % bs]))
    # ...and a write running off the table's end deflects to scratch:
    # no real block outside row 0's own table changes.
    out3 = paged_cache_insert(kp, kv2, table[:1], np.int32(t * bs - 3))
    np.testing.assert_array_equal(np.asarray(out3[1 + t:]),
                                  np.asarray(kp[1 + t:]))
    # The in-table tail tokens still landed.
    for j in range(3):
        pos = t * bs - 3 + j
        got = np.asarray(out3[table[0, pos // bs], :, pos % bs])
        np.testing.assert_array_equal(got, np.asarray(kv2[0, :, j]))


# --------------------------------------------------------- engine level
def _paged_engine(model, variables, **kw):
    kw.setdefault("max_slots", 2)
    kw.setdefault("prefill_len", 16)
    return ServeEngine(model, variables, paged=True, **kw)


def _exactness_workload(model, variables, ref_variables=None, **engine_kw):
    """Cold admit, full-chain re-hit, partial hit — the paged twin of
    `test_prefix_cache._exactness_workload`, pinned against the same
    generate() oracle."""
    ref_variables = ref_variables or variables
    eng = _paged_engine(model, variables, **engine_kw)
    base = (np.arange(12) * 5 + 1) % 32
    sibling = np.concatenate([base[:8], (np.arange(6) + 17) % 32])
    h_cold = eng.submit(base, 6)
    eng.run(max_steps=100)
    h_hit = eng.submit(base, 6)
    h_part = eng.submit(sibling, 6)
    eng.run(max_steps=100)
    assert h_cold.tokens == _ref_greedy(model, ref_variables, base, 6)
    assert h_hit.tokens == _ref_greedy(model, ref_variables, base, 6)
    assert h_part.tokens == _ref_greedy(model, ref_variables, sibling, 6)
    # Not vacuous: the hits referenced cached blocks in place.
    assert eng.metrics.prefix_hits >= 2
    assert eng.metrics.copy_bytes_avoided > 0
    return eng


@pytest.fixture(scope="module")
def exact_gpt(gpt_setup):
    """One warmed paged GPT engine, driven through the exactness
    workload — shared by the pins that only READ its end state
    (program set, metrics exposition), so the suite compiles one
    engine for the three of them."""
    model, variables = gpt_setup
    return _exactness_workload(model, variables)


def test_paged_token_exact_gpt(exact_gpt, pin_zero_recompiles):
    eng = pin_zero_recompiles(exact_gpt)
    assert eng.paged
    # The paged program set: no gather, no insert, no donate scatter.
    assert set(eng.compile_counts()) <= {
        "tick", "sample_first", "chunk_prefill", "chunk_prefill_wide"}


def test_paged_token_exact_llama(llama_setup):
    """GQA + RoPE: post-RoPE keys are position-absolute, so a SHARED
    pool block read through two different slots' tables is bit-valid
    for both."""
    model, variables = llama_setup
    _exactness_workload(model, variables)


@pytest.mark.parametrize("family", ["gpt", "llama"])
def test_paged_int8_token_exact(family, gpt_setup, llama_setup):
    """int8 param_transform composes: what the pool stores is K/V,
    which int8 weight storage never touches; dequant runs inside the
    paged chunk/tick programs."""
    from pddl_tpu.ops.quant import dequantize, quantize_int8

    model, variables = gpt_setup if family == "gpt" else llama_setup
    qparams = quantize_int8(variables["params"], min_elems=128)
    dense = {"params": dequantize(qparams)}
    _exactness_workload(model, {"params": qparams}, ref_variables=dense,
                        param_transform=dequantize)


def test_paged_equals_resident_row_engine(gpt_setup):
    """THE oracle pin the ISSUE names: the same mixed workload through
    a paged and a resident-row engine, stream-for-stream identical."""
    model, variables = gpt_setup
    prompts = [((np.arange(9 + i) * 3 + 5 * i + 1) % 32) for i in range(5)]
    prompts.append(prompts[0].copy())  # a full-chain re-hit
    streams = {}
    for mode in ("paged", "row"):
        eng = ServeEngine(model, variables, max_slots=2, prefill_len=16,
                          paged=(mode == "paged"))
        hs = [eng.submit(p, 5) for p in prompts]
        eng.run(max_steps=300)
        streams[mode] = [h.tokens for h in hs]
    assert streams["paged"] == streams["row"]


def test_concurrent_shared_prefix_blocks_shared_in_place(gpt_setup):
    """Many live slots on one warm prefix: the matched blocks exist
    ONCE (blocks_shared counts them), table occupancy is reported, and
    every stream is token-exact — the capacity story of paged mode as
    an observable, not a slogan."""
    model, variables = gpt_setup
    eng = _paged_engine(model, variables, max_slots=4)
    base = (np.arange(12) * 5 + 1) % 32
    warm = eng.submit(base, 3)
    eng.run(max_steps=60)
    assert warm.tokens == _ref_greedy(model, variables, base, 3)
    variants = [np.concatenate([base[:8], [(i * 7 + 3) % 32]])
                for i in range(4)]
    hs = [eng.submit(v, 6) for v in variants]
    shared_seen, fill_seen = 0, 0.0
    while eng.has_work:
        eng.step()
        shared_seen = max(shared_seen, eng.blocks_shared)
        fill_seen = max(fill_seen, eng.block_table_fill)
    for h, v in zip(hs, variants):
        assert h.tokens == _ref_greedy(model, variables, v, 6)
    assert shared_seen >= 1          # the warm block was referenced >1x
    assert 0.0 < fill_seen <= 1.0
    assert eng.metrics.blocks_shared >= 0  # gauge stamped per tick
    assert eng.metrics.copy_bytes_avoided > 0


def test_block_aligned_repeat_never_grows_a_paged_pool(gpt_setup):
    """The paged twin of the donation-dedup pin: re-admitting a
    block-aligned prompt swaps the slot's table onto the stored chain
    and RELEASES the duplicate private blocks, so repeats hold the
    pool at its deduplicated size (no eviction churn, live == 2)."""
    model, variables = gpt_setup
    eng = _paged_engine(model, variables, max_slots=1)
    p = (np.arange(16) * 3 + 5) % 32  # 2 full blocks at bs=8
    for _ in range(3):
        h = eng.submit(p, 3)
        eng.run(max_steps=50)
        assert h.tokens == _ref_greedy(model, variables, p, 3)
    assert eng.metrics.prefix_evictions == 0
    assert eng.metrics.prefix_blocks_live == 2
    assert eng.metrics.prefix_hits == 2


def test_paged_preemption_resumes_token_exact(gpt_setup):
    """A parked (preempted) best_effort stream resumes token-exactly
    through replay admission — its freed private blocks went back to
    the pool and were fully rewritten on re-admission."""
    model, variables = gpt_setup
    eng = _paged_engine(model, variables, max_slots=1)
    pb = (np.arange(8) * 5 + 4) % 32
    hbe = eng.submit(pb, 10, priority=Priority.BEST_EFFORT)
    for _ in range(3):
        eng.step()
    pi = (np.arange(8) * 11 + 6) % 32
    hint = eng.submit(pi, 4, priority=Priority.INTERACTIVE)
    eng.run(max_steps=300)
    assert eng.metrics.preemptions >= 1
    assert hbe.tokens == _ref_greedy(model, variables, pb, 10)
    assert hint.tokens == _ref_greedy(model, variables, pi, 4)


def test_paged_sliced_admission_token_exact(gpt_setup, pin_zero_recompiles):
    """Chunked-prefill fairness composes: slices write straight into
    the slot's pool blocks across interleaved ticks, pin held from
    slice start (flush spares pinned chains)."""
    model, variables = gpt_setup
    eng = pin_zero_recompiles(_paged_engine(
        model, variables, prefill_slice_tokens=4, prefix_chunk=4))
    p = (np.arange(15) * 3 + 1) % 32
    ha = eng.submit(p, 6)
    hb = eng.submit(p, 6)
    eng.run(max_steps=300)
    assert ha.tokens == _ref_greedy(model, variables, p, 6)
    assert hb.tokens == _ref_greedy(model, variables, p, 6)


def test_paged_pool_size_validation(gpt_setup):
    """paged without the pool machinery, or with a pool the live
    streams could starve, fails LOUDLY at construction."""
    model, variables = gpt_setup
    with pytest.raises(ValueError, match="paged=True needs"):
        ServeEngine(model, variables, max_slots=2, prefill_len=16,
                    paged=True, prefix_cache_blocks=0)
    with pytest.raises(ValueError, match="starve"):
        ServeEngine(model, variables, max_slots=2, prefill_len=16,
                    paged=True, prefix_cache_blocks=4)


# ----------------------------------------------------------- resilience
@pytest.mark.chaos
@pytest.mark.parametrize("seed", [0, 1, 2])
def test_paged_chaos_matrix(gpt_setup, pin_zero_recompiles, seed):
    """The mixed chaos profile in paged mode: every request terminal,
    survivors token-exact, zero recompiles across retry / replay /
    degraded / pool-rebuild transitions."""
    model, variables = gpt_setup
    plan = FaultPlan(seed=seed, sleep_fn=_no_sleep, transient_rate=0.05,
                     oom_rate=0.02, latency_rate=0.1, latency_s=1e-4,
                     max_random_injections=20)
    eng = pin_zero_recompiles(_paged_engine(
        model, variables, fault_plan=plan, backoff_sleep=_no_sleep))
    jobs = []
    for i in range(5):
        p = (np.arange(10) * 3 + i * 7 + 1) % 32
        jobs.append((p, eng.submit(p, 5)))
    eng.run(max_steps=600)
    assert not eng.has_work, "engine failed to drain under chaos"
    for p, h in jobs:
        assert h.done, f"request {h} never reached a terminal state"
        if h.state == RequestState.FINISHED:
            assert h.tokens == _ref_greedy(model, variables, p, 5)


def test_parked_slice_survives_paged_pool_reset(gpt_setup,
                                                pin_zero_recompiles):
    """A mid-prefill slice parked across steps must NOT leak its
    (retired) block ids or radix node into the rebuilt paged world
    when a tick fault forces the full pool reset: the slice is
    dropped pre-reset and its handle re-admits from scratch against
    the fresh pool — every stream still terminal and token-exact, no
    double-owned blocks (the refcount invariants would trip on a
    re-allocated duplicate)."""
    from pddl_tpu.serve.faults import FaultKind

    model, variables = gpt_setup
    plan = FaultPlan(sleep_fn=_no_sleep)
    eng = pin_zero_recompiles(_paged_engine(
        model, variables, prefill_slice_tokens=4, prefix_chunk=4,
        fault_plan=plan, backoff_sleep=_no_sleep, max_retries=0))
    p_live = (np.arange(8) * 5 + 4) % 32
    p_sliced = (np.arange(15) * 3 + 1) % 32
    h_live = eng.submit(p_live, 8)
    while eng.live_slots < 1:  # h_live fully admitted, now decoding
        eng.step()
    h_sliced = eng.submit(p_sliced, 4)
    eng.step()
    # White-box arm: the second admission must be PARKED mid-prefill
    # (15 tokens at 4/step), holding private ids + a table row; now a
    # single un-retryable transient at the NEXT tick forces the
    # live-slot replay and the full paged-world rebuild underneath it.
    assert eng._slice is not None
    plan._sched[(eng._step_idx, "tick")] = [FaultKind.TRANSIENT]
    eng.run(max_steps=400)
    assert h_live.done and h_sliced.done
    assert h_live.tokens == _ref_greedy(model, variables, p_live, 8)
    assert h_sliced.tokens == _ref_greedy(model, variables, p_sliced, 4)
    assert eng.metrics.replays >= 1  # the reset really happened


def test_paged_drain_restore_round_trip(gpt_setup):
    """v3 snapshot: carries ``paged`` + each running slot's block
    table (postmortem context); restore into a fresh paged engine
    resumes token-exactly via replay. A v2-shaped snapshot (no
    tables — the copy engine's format) restores through the SAME
    path."""
    model, variables = gpt_setup
    eng1 = _paged_engine(model, variables)
    p1 = (np.arange(11) * 5 + 2) % 32
    p2 = (np.arange(9) * 7 + 3) % 32
    eng1.submit(p1, 8)
    eng1.submit(p2, 8)
    for _ in range(3):
        eng1.step()
    snap = eng1.drain()
    assert snap["version"] == 5  # spec accounting rides v5; tables still here
    assert snap["paged"] is True
    running = [e for e in snap["requests"] if e.get("tokens")]
    assert running and all("block_table" in e for e in running)
    assert all(0 not in e["block_table"] for e in running)

    eng2 = _paged_engine(model, variables)
    rh = eng2.restore(snap)
    eng2.run(max_steps=300)
    assert rh[0].tokens == _ref_greedy(model, variables, p1, 8)
    assert rh[1].tokens == _ref_greedy(model, variables, p2, 8)

    # v2 copy-path snapshot into a paged engine: same replay restore.
    snap_v2 = dict(snap)
    snap_v2["version"] = 2
    snap_v2.pop("paged")
    snap_v2["requests"] = [
        {k: v for k, v in e.items() if k != "block_table"}
        for e in snap["requests"]]
    eng3 = _paged_engine(model, variables)
    rh3 = eng3.restore(snap_v2)
    eng3.run(max_steps=300)
    assert rh3[0].tokens == _ref_greedy(model, variables, p1, 8)
    assert rh3[1].tokens == _ref_greedy(model, variables, p2, 8)


# -------------------------------------------------------- observability
def test_paged_metrics_reach_the_exposition(exact_gpt):
    """blocks_shared / copy_bytes_avoided / block_table_fill flow
    through ServeMetrics AND the engine gauges into the Prometheus
    body, round-tripped through the strict referee parser (over the
    shared exactness engine's end state — its workload recorded hits
    and sharing)."""
    eng = exact_gpt
    text = serve_exposition(eng.metrics, eng)
    samples, types = parse_prometheus_text(text)
    flat = {name: v for (name, labels), v in samples.items() if not labels}
    assert flat["pddl_serve_copy_bytes_avoided_total"] > 0
    assert types["pddl_serve_copy_bytes_avoided_total"] == "counter"
    assert "pddl_serve_blocks_shared" in flat
    assert "pddl_serve_block_table_fill" in flat
    assert flat["pddl_serve_engine_paged"] == 1
    assert "pddl_serve_engine_blocks_shared" in flat
    assert "pddl_serve_engine_block_table_fill" in flat
