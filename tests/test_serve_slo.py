"""SLO-aware scheduling + fleet admission control under overload
(ISSUE 7), CPU.

The contracts under test:

- **Priority pop order**: ``interactive`` > ``batch`` > ``best_effort``
  at the scheduler, EDF within a class, and the PRIORITY-AWARE
  ``retry_after_s`` hint (a lower class prices the deeper queue it
  actually waits behind).
- **Anti-starvation aging** (discriminative): a sustained interactive
  flood with one queued batch request still finishes the batch request
  within the aging bound — and the same schedule STARVES it with aging
  disabled, so plain EDF cannot pass by accident.
- **Chunked-prefill fairness**: with ``prefill_slice_tokens`` set, a
  long cold prompt's admission spreads over multiple steps with decode
  ticks in between (running streams keep emitting), token-exact, zero
  recompiles — and cancel/deadline land mid-slice without wedging the
  engine.
- **Versioned drain snapshots**: v2 round-trips priority + deadline; a
  pre-ISSUE-7 v1 snapshot (no priority field) restores with
  ``interactive`` defaults instead of raising.
- **Fleet admission control**: per-priority token buckets reject with
  the bucket's own refill hint; the brownout ladder escalates one rung
  per hold under pressure, sheds ``best_effort`` first with the
  longest honest hint, caps output tokens, rejects cold prompts, and
  recovers HYSTERETICALLY; per-priority metrics flow through the
  strict Prometheus referee.
- **Chaos under overload** (3 seeds, fault injection while 2x
  saturated): every request reaches a terminal state (finished /
  DEADLINE / shed-with-hint), every FINISHED stream is token-exact,
  zero recompiles throughout.
"""

import json
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pddl_tpu.models.gpt import tiny_gpt
from pddl_tpu.obs import fleet_exposition, parse_prometheus_text, serve_exposition
from pddl_tpu.serve import (
    AdmissionRejected,
    FaultPlan,
    FinishReason,
    Priority,
    QueueFull,
    RequestState,
    SLOScheduler,
    ServeEngine,
)
from pddl_tpu.serve import drain as drain_io
from pddl_tpu.serve.fleet import (
    AdmissionControl,
    BrownoutController,
    BrownoutRung,
    FleetRouter,
    LocalReplica,
    OverloadDetector,
    TokenBucket,
)
from pddl_tpu.serve.request import Request, RequestHandle
from conftest import ref_greedy as _ref_greedy, FakeClock as _FakeClock

pytestmark = pytest.mark.overload


@pytest.fixture(scope="module")
def gpt_setup():
    model = tiny_gpt(vocab_size=32, max_len=64)
    prompt = jnp.ones((1, 8), jnp.int32)
    params = model.init(jax.random.key(0), prompt, train=False)["params"]
    return model, {"params": params}


def _no_sleep(_):
    pass


def _handle(priority=Priority.INTERACTIVE, deadline_s=None, arrival_s=0.0,
            prompt=(1, 2, 3)):
    return RequestHandle(
        Request(prompt=list(prompt), max_new_tokens=2,
                deadline_s=deadline_s, priority=priority),
        arrival_s=arrival_s)


# ------------------------------------------------------------ pop order
def test_priority_classes_pop_before_lower_ones():
    sched = SLOScheduler(max_queue_depth=16)
    be = _handle(Priority.BEST_EFFORT)
    ba = _handle(Priority.BATCH)
    ia = _handle(Priority.INTERACTIVE)
    for h in (be, ba, ia):  # worst class submitted FIRST
        sched.submit(h)
    assert sched.admit(3, now_fn=lambda: 0.0) == [ia, ba, be]


def test_edf_within_class_and_deadline_less_last():
    sched = SLOScheduler(max_queue_depth=16)
    loose = _handle(deadline_s=10.0)
    tight = _handle(deadline_s=5.0)
    none = _handle()  # deadline-less: synthetic horizon, pops last
    for h in (none, loose, tight):
        sched.submit(h)
    assert sched.admit(3, now_fn=lambda: 0.0) == [tight, loose, none]


def test_depth_at_or_above_counts_the_queue_a_class_waits_behind():
    sched = SLOScheduler(max_queue_depth=16)
    for p in (Priority.INTERACTIVE, Priority.INTERACTIVE, Priority.BATCH,
              Priority.BEST_EFFORT):
        sched.submit(_handle(p))
    assert sched.depth_at_or_above(Priority.INTERACTIVE) == 2
    assert sched.depth_at_or_above(Priority.BATCH) == 3
    assert sched.depth_at_or_above(Priority.BEST_EFFORT) == 4


def test_aging_bound_is_discriminative_vs_plain_edf():
    """A sustained interactive flood (the queue never lacks fresh
    interactive work) with ONE queued batch request: with aging the
    batch request is admitted within the aging bound; the SAME
    schedule with aging disabled starves it indefinitely — so plain
    EDF without aging fails this test."""
    def flood_rounds(aging_s, rounds):
        clock = _FakeClock()
        sched = SLOScheduler(max_queue_depth=4096, aging_s=aging_s)
        batch = _handle(Priority.BATCH, arrival_s=0.0)
        sched.submit(batch)
        admitted_at = None
        for r in range(rounds):
            # Two fresh interactive arrivals, one admission slot per
            # round: interactive pressure never drains.
            for _ in range(2):
                sched.submit(_handle(Priority.INTERACTIVE,
                                     arrival_s=clock.now))
            for h in sched.admit(1, now_fn=clock):
                if h is batch and admitted_at is None:
                    admitted_at = clock.now
            clock.now += 1.0
        return admitted_at

    aging_s = 10.0
    admitted_at = flood_rounds(aging_s, rounds=40)
    assert admitted_at is not None, "batch request starved WITH aging"
    assert admitted_at <= aging_s + 1.0, \
        f"batch admitted at {admitted_at}s, past the {aging_s}s bound"
    assert flood_rounds(None, rounds=40) is None, \
        "plain EDF admitted the batch request — the test is not " \
        "discriminative"


def test_over_budget_head_stays_in_place_not_promoted():
    """Review-driven pin: a head blocked by the prefill budget must
    stay IN the queue at its own rank — parking it in the replay
    bypass lane would let a big best_effort prompt jump ahead of
    interactive work arriving the very next tick."""
    sched = SLOScheduler(max_queue_depth=8, prefill_token_budget=4)
    small = _handle(prompt=(1, 2))
    big = _handle(Priority.BEST_EFFORT, prompt=tuple(range(10)))
    sched.submit(small)
    sched.submit(big)
    assert sched.admit(2, now_fn=lambda: 0.0) == [small]  # big: over budget
    late_ia = _handle(Priority.INTERACTIVE, arrival_s=1.0)
    sched.submit(late_ia)
    assert sched.admit(1, now_fn=lambda: 1.0) == [late_ia], \
        "budget-parked best_effort outranked a later interactive"
    assert sched.admit(1, now_fn=lambda: 1.0) == [big]


def test_router_chains_caller_brownout_callback(gpt_setup):
    """Review-driven pin: FleetRouter's metrics observer must CHAIN
    the on_transition hook the caller gave AdmissionControl, not
    clobber it — a user's paging hook keeps firing."""
    model, variables = gpt_setup
    seen = []
    admission = AdmissionControl(
        on_transition=lambda a, b: seen.append((a, b)),
        brownout_kw=dict(high=0.2, low=0.05, escalate_hold_s=0.0,
                         recover_hold_s=0.2))
    clock = _FakeClock(10.0)
    fleet = _slo_fleet(model, variables, 1, clock=clock,
                       admission=admission, max_queue_depth=2)
    for i in range(12):
        try:
            fleet.submit([(i + j) % 32 for j in range(1, 6)], 3)
        except QueueFull:
            pass
        clock.now += 0.01
    assert seen, "caller's brownout hook never fired"
    assert fleet.metrics.brownout_escalations == \
        sum(1 for a, b in seen if b > a)
    fleet.run(max_steps=500)


def test_requeue_front_outranks_every_class():
    """Replayed handles bypass the SLO order entirely: a best_effort
    replay pops before a fresh interactive submit (it was admitted
    once already — shedding or demoting it would turn a device fault
    into visible starvation)."""
    sched = SLOScheduler(max_queue_depth=16)
    replayed = _handle(Priority.BEST_EFFORT)
    sched.submit(_handle(Priority.INTERACTIVE))
    sched.requeue_front([replayed])
    out = sched.admit(1, now_fn=lambda: 0.0)
    assert out == [replayed]


# -------------------------------------------------- priority-aware hints
def test_queue_full_hint_is_rank_monotone(gpt_setup):
    """At one queue state, the retry_after_s hint never SHRINKS as the
    class gets less urgent: best_effort >= batch >= interactive — the
    lower class really does wait behind more work."""
    model, variables = gpt_setup
    clock = _FakeClock()
    eng = ServeEngine(model, variables, max_slots=1, prefill_len=16,
                      max_queue_depth=4, clock=clock)
    # Warm the admission-interval estimator at ~1 admission/s.
    for i in range(4):
        eng.submit((np.arange(4) + i) % 32, 2)
        eng.run(max_steps=10)
        clock.now += 1.0
    # Saturate with a mixed-class queue: 1 running + 4 queued.
    eng.submit(np.arange(5) % 32, 30)
    eng.step()
    eng.submit((np.arange(5) + 1) % 32, 2, priority=Priority.INTERACTIVE)
    eng.submit((np.arange(5) + 2) % 32, 2, priority=Priority.INTERACTIVE)
    eng.submit((np.arange(5) + 3) % 32, 2, priority=Priority.BATCH)
    eng.submit((np.arange(5) + 4) % 32, 2, priority=Priority.BEST_EFFORT)
    hints = {}
    for p in Priority:
        with pytest.raises(QueueFull) as exc:
            eng.submit((np.arange(5) + 5) % 32, 2, priority=p)
        assert exc.value.priority is p
        hints[p] = exc.value.retry_after_s
        assert hints[p] is not None and hints[p] >= 0.0
    assert hints[Priority.INTERACTIVE] <= hints[Priority.BATCH] \
        <= hints[Priority.BEST_EFFORT]
    assert hints[Priority.INTERACTIVE] < hints[Priority.BEST_EFFORT]


# --------------------------------------------------- versioned snapshots
def test_drain_snapshot_roundtrips_priority_and_deadline(gpt_setup):
    """Priority + deadline fields (the v2 additions) survive the
    drain→restore round trip at the CURRENT snapshot version (v3 since
    the paged engine — the fleet migration path inherits this for free:
    `serve/drain.py` IS its wire format)."""
    model, variables = gpt_setup
    clock_a = _FakeClock()
    eng_a = ServeEngine(model, variables, max_slots=1, prefill_len=16,
                        clock=clock_a)
    h_batch = eng_a.submit(np.arange(6) % 32, 4, priority=Priority.BATCH,
                           deadline_s=30.0)
    h_be = eng_a.submit((np.arange(7) + 2) % 32, 3,
                        priority=Priority.BEST_EFFORT)
    eng_a.step()
    clock_a.now = 4.0
    snapshot = eng_a.drain()
    assert snapshot["version"] == drain_io.SNAPSHOT_VERSION == 5
    by_len = {len(e["prompt"]): e for e in snapshot["requests"]}
    assert by_len[6]["priority"] == "batch"
    assert by_len[6]["deadline_s"] == 30.0
    assert by_len[7]["priority"] == "best_effort"
    eng_b = ServeEngine(model, variables, max_slots=1, prefill_len=16)
    restored = eng_b.restore(snapshot)
    by_prompt = {tuple(h.request.prompt): h for h in restored}
    assert by_prompt[tuple(h_batch.request.prompt)].request.priority \
        is Priority.BATCH
    assert by_prompt[tuple(h_be.request.prompt)].request.priority \
        is Priority.BEST_EFFORT
    eng_b.run(max_steps=100)
    assert all(h.state == RequestState.FINISHED for h in restored)


def test_pre_issue7_v1_snapshot_restores_with_interactive_default(
        gpt_setup, tmp_path):
    """A version-1 snapshot — written by a pre-priority engine, no
    ``priority`` key anywhere — must restore (NOT raise) with every
    request defaulting to ``interactive``, and still resume
    token-exactly. Pinned next to the cross-process drain child: this
    is the compatibility face of the same wire format."""
    model, variables = gpt_setup
    p, n = ((np.arange(9) * 5 + 1) % 32).tolist(), 6
    ref = _ref_greedy(model, variables, p, n)
    v1 = {
        "version": 1,
        "drained_unix_s": 0.0,
        "requests": [{
            "prompt": p, "max_new_tokens": n,
            "sampling": {"temperature": 0.0, "top_k": None, "top_p": None},
            "deadline_s": None, "elapsed_s": 1.5,
            "tokens": ref[:2],  # mid-stream: exercises replay too
            "ttft_s": 0.1,
        }],
    }
    path = tmp_path / "v1_snapshot.json"
    path.write_text(json.dumps(v1))
    eng = ServeEngine(model, variables, max_slots=1, prefill_len=16)
    (restored,) = eng.restore(str(path))
    assert restored.request.priority is Priority.INTERACTIVE
    eng.run(max_steps=100)
    assert restored.state == RequestState.FINISHED
    assert restored.tokens == ref  # resumed, not re-sampled
    # Unknown future versions still refuse loudly.
    bad = tmp_path / "v99.json"
    bad.write_text(json.dumps({"version": 99, "requests": []}))
    with pytest.raises(ValueError, match="version"):
        drain_io.load_snapshot(str(bad))


# ------------------------------------------------ chunked-prefill slices
def test_sliced_prefill_interleaves_decode_ticks_token_exact(
        gpt_setup, pin_zero_recompiles):
    """The fairness mechanism itself: with ``prefill_slice_tokens``, a
    long cold prompt's admission spans multiple steps and the RUNNING
    stream keeps emitting between slices (without slicing it gets
    exactly one tick's token while the whole prefill lands in one
    step). Both requests finish token-exact; zero recompiles."""
    model, variables = gpt_setup

    def run(slice_tokens):
        eng = ServeEngine(model, variables, max_slots=2, prefill_len=32,
                          prefix_chunk=8,
                          prefill_slice_tokens=slice_tokens)
        eng.warmup()
        short_p, long_p = (np.arange(6) + 1) % 32, (np.arange(31) * 3) % 32
        a = eng.submit(short_p, 12)
        eng.step()  # A is running
        b = eng.submit(long_p, 3)
        a_before = len(a.tokens)
        steps_until_b = 0
        while not b.tokens:
            eng.step()
            steps_until_b += 1
            assert steps_until_b < 50
        a_during = len(a.tokens) - a_before
        eng.run(max_steps=200)
        return a, b, short_p, long_p, a_during, steps_until_b, eng

    a, b, short_p, long_p, a_during, steps, eng = run(8)
    pin_zero_recompiles(eng)
    # 31 cold tokens at 8 tokens/step: the admission spans >= 4 steps
    # and A emitted a token in each — the discriminative fairness claim.
    assert steps >= 4
    assert a_during >= 3
    assert a.tokens == _ref_greedy(model, variables, short_p, 12)
    assert b.tokens == _ref_greedy(model, variables, long_p, 3)
    # The whole-prompt engine admits B in ONE step: same outcome,
    # no interleaving (what slicing exists to fix).
    a2, b2, _, _, a2_during, steps2, _ = run(None)
    assert steps2 == 1 and a2_during <= 1
    assert b2.tokens == b.tokens


def test_cancel_and_deadline_land_mid_slice(gpt_setup,
                                            pin_zero_recompiles):
    """A parked slice must honor cancel() and deadline expiry between
    its steps — the request settles terminally, the engine keeps
    serving, nothing recompiles."""
    model, variables = gpt_setup
    clock = _FakeClock()
    eng = pin_zero_recompiles(ServeEngine(
        model, variables, max_slots=1, prefill_len=32, prefix_chunk=8,
        prefill_slice_tokens=8, clock=clock))
    long_p = (np.arange(31) * 5 + 2) % 32
    # Cancel mid-slice.
    h = eng.submit(long_p, 4)
    eng.step()  # slice started, not finished
    assert not h.done and not h.tokens
    h.cancel()
    eng.step()
    assert h.state == RequestState.CANCELLED
    # Deadline mid-slice.
    h2 = eng.submit(long_p, 4, deadline_s=1.0)
    eng.step()
    clock.now += 5.0
    eng.step()
    assert h2.state == RequestState.TIMED_OUT
    assert h2.finish_reason == FinishReason.TIMED_OUT
    # The engine is healthy: the same prompt now completes exact.
    h3 = eng.submit(long_p, 4)
    eng.run(max_steps=100)
    assert h3.tokens == _ref_greedy(model, variables, long_p, 4)
    snap = eng.metrics.snapshot()
    assert snap["requests_cancelled"] == 1
    assert snap["requests_timed_out"] == 1


# -------------------------------------------------------- preemption
def test_interactive_preempts_best_effort_token_exact(
        gpt_setup, pin_zero_recompiles):
    """Every slot busy with long best_effort streams, an interactive
    request arrives: one victim is PARKED (slot freed, requeued), the
    interactive request serves promptly, and the paused stream later
    resumes token-exactly through the replay machinery — the
    fault-recovery path doing scheduling duty, zero recompiles."""
    model, variables = gpt_setup
    eng = pin_zero_recompiles(ServeEngine(
        model, variables, max_slots=2, prefill_len=16,
        prefix_cache_blocks=0, preempt_cap=2))
    be_p = [(np.arange(7) + i) % 32 for i in range(2)]
    be = [eng.submit(p, 20, priority=Priority.BEST_EFFORT) for p in be_p]
    eng.step()
    assert eng.live_slots == 2
    ia_p = (np.arange(8) * 3 + 1) % 32
    ia = eng.submit(ia_p, 4, priority=Priority.INTERACTIVE)
    eng.step()  # preempts one best_effort, admits the interactive
    assert eng.metrics.preemptions == 1
    assert sum(1 for h in be if h.state == RequestState.QUEUED) == 1
    assert ia.state in (RequestState.RUNNING, RequestState.FINISHED)
    eng.run(max_steps=200)
    assert ia.tokens == _ref_greedy(model, variables, ia_p.tolist(), 4)
    for p, h in zip(be_p, be):
        assert h.state == RequestState.FINISHED
        assert h.tokens == _ref_greedy(model, variables, p.tolist(), 20)
    assert max(h.preemptions for h in be) == 1


def test_preempt_cap_zero_disables_preemption(gpt_setup):
    model, variables = gpt_setup
    eng = ServeEngine(model, variables, max_slots=1, prefill_len=16,
                      prefix_cache_blocks=0, preempt_cap=0)
    be = eng.submit(np.arange(6) % 32, 10, priority=Priority.BEST_EFFORT)
    eng.step()
    eng.submit((np.arange(5) + 2) % 32, 2,
               priority=Priority.INTERACTIVE)
    eng.step()
    assert be.state == RequestState.RUNNING  # never parked
    assert eng.metrics.preemptions == 0
    eng.run(max_steps=100)


# --------------------------------------------- per-priority observability
def test_per_priority_metrics_and_exposition_referee(gpt_setup):
    """ServeMetrics splits TTFT/finish/shed by class and the splits
    ride the Prometheus exposition as labeled series, verified through
    the strict parse_prometheus_text referee."""
    model, variables = gpt_setup
    clock = _FakeClock()
    eng = ServeEngine(model, variables, max_slots=1, prefill_len=16,
                      clock=clock)
    hi = eng.submit(np.arange(5) % 32, 2, priority=Priority.INTERACTIVE)
    hb = eng.submit((np.arange(6) + 1) % 32, 2, priority=Priority.BATCH)
    doomed = eng.submit((np.arange(7) + 2) % 32, 2,
                        priority=Priority.BEST_EFFORT, deadline_s=1.0)
    eng.step()
    clock.now = 5.0  # best_effort expires in the queue -> pop-time shed
    eng.run(max_steps=100)
    assert hi.state == hb.state == RequestState.FINISHED
    assert doomed.finish_reason == FinishReason.DEADLINE
    snap = eng.metrics.snapshot()
    assert snap["requests_finished_by_priority"] == {
        "interactive": 1, "batch": 1, "best_effort": 0}
    assert snap["requests_deadline_shed_by_priority"]["best_effort"] == 1
    assert snap["ttft_p99_s_by_priority"]["interactive"] is not None
    assert snap["ttft_p99_s_by_priority"]["best_effort"] is None
    samples, types = parse_prometheus_text(
        serve_exposition(eng.metrics, eng))
    key = "pddl_serve_requests_finished_by_priority"
    assert samples[(key, (("key", "interactive"),))] == 1.0
    assert samples[(key, (("key", "best_effort"),))] == 0.0
    assert types[key] == "gauge"
    shed_key = "pddl_serve_requests_deadline_shed_by_priority"
    assert samples[(shed_key, (("key", "best_effort"),))] == 1.0


# ------------------------------------------------- admission-control units
def test_token_bucket_rates_and_refill_hint():
    b = TokenBucket(2.0, burst=2)
    assert b.take(0.0) and b.take(0.0)
    assert not b.take(0.0)
    assert b.time_until_token(0.0) == pytest.approx(0.5)
    assert b.take(0.5)  # refilled at 2/s
    unlimited = TokenBucket(None, burst=1)
    assert all(unlimited.take(0.0) for _ in range(100))
    assert unlimited.time_until_token(0.0) == 0.0
    with pytest.raises(ValueError):
        TokenBucket(0.0, burst=1)


def test_overload_detector_pressure_and_degraded_floor():
    d = OverloadDetector(window_s=2.0, min_samples=4, degraded_floor=0.5)
    for i in range(2):
        d.observe(0.0, rejected=True)
    assert d.pressure(0.0) == 0.0  # below min_samples: not overloaded
    for i in range(2):
        d.observe(0.0, rejected=False)
    assert d.pressure(0.0) == pytest.approx(0.5)
    assert d.pressure(3.0) == 0.0  # the window slid past everything
    d.set_degraded(1)  # r08 OOM state: pressure floor even when calm
    assert d.pressure(3.0) == pytest.approx(0.5)
    d.set_degraded(0)
    assert d.pressure(3.0) == 0.0


def test_brownout_ladder_escalates_and_recovers_hysteretically():
    moves = []
    c = BrownoutController(high=0.3, low=0.1, escalate_hold_s=1.0,
                           recover_hold_s=2.0, output_cap=8,
                           on_transition=lambda a, b: moves.append((a, b)))
    assert c.update(0.0, 0.9) is BrownoutRung.NORMAL  # hold not met yet
    assert c.update(1.0, 0.9) is BrownoutRung.SHED_BEST_EFFORT
    assert c.update(1.5, 0.9) is BrownoutRung.SHED_BEST_EFFORT
    assert c.update(2.0, 0.9) is BrownoutRung.CAP_OUTPUT  # one rung/hold
    assert c.update(3.0, 0.9) is BrownoutRung.REJECT_COLD
    assert c.update(4.0, 0.9) is BrownoutRung.REJECT_COLD  # ceiling
    # The dead band (low < p < high) neither escalates nor recovers.
    assert c.update(5.0, 0.2) is BrownoutRung.REJECT_COLD
    assert c.update(50.0, 0.2) is BrownoutRung.REJECT_COLD
    # Recovery: one rung per recover_hold_s of calm — never a jump.
    assert c.update(60.0, 0.0) is BrownoutRung.REJECT_COLD
    assert c.update(62.0, 0.0) is BrownoutRung.CAP_OUTPUT
    assert c.update(63.0, 0.0) is BrownoutRung.CAP_OUTPUT
    assert c.update(64.0, 0.0) is BrownoutRung.SHED_BEST_EFFORT
    assert c.update(66.0, 0.0) is BrownoutRung.NORMAL
    assert c.escalations == 3 and c.deescalations == 3
    assert len(moves) == 6
    # Decisions per rung: best_effort sheds with the LONGEST hint (the
    # whole ladder must unwind before it re-enters).
    c.rung = BrownoutRung.REJECT_COLD
    ok, reason, hint = c.decide(Priority.BEST_EFFORT, cold=False)
    assert not ok and reason == "brownout_shed"
    assert hint == pytest.approx(3 * 2.0)
    ok, reason, cold_hint = c.decide(Priority.INTERACTIVE, cold=True)
    assert not ok and reason == "brownout_cold"
    assert cold_hint < hint  # cold re-enters one rung down: shorter
    ok, _, _ = c.decide(Priority.INTERACTIVE, cold=False)
    assert ok
    assert c.cap_new_tokens(100) == 8
    c.rung = BrownoutRung.NORMAL
    assert c.cap_new_tokens(100) == 100


# ----------------------------------------------------- fleet integration
def _slo_fleet(model, variables, n, *, clock, admission,
               max_queue_depth=4, slots=2):
    def factory():
        return ServeEngine(model, variables, max_slots=slots,
                           prefill_len=16, prefix_cache_blocks=0,
                           max_queue_depth=max_queue_depth,
                           backoff_sleep=_no_sleep)
    replicas = [LocalReplica(i, factory) for i in range(n)]
    return FleetRouter(replicas, affinity_block_size=8, affinity_blocks=1,
                       respawn=False, clock=clock, admission=admission)


def test_fleet_rate_limit_rejects_with_refill_hint(gpt_setup):
    model, variables = gpt_setup
    clock = _FakeClock(10.0)
    fleet = _slo_fleet(model, variables, 1, clock=clock,
                       admission=AdmissionControl(
                           rates={Priority.BEST_EFFORT: 1.0}, burst=1.0))
    p = (np.arange(6) + 1) % 32
    fleet.submit(p, 2, priority=Priority.BEST_EFFORT)  # takes the token
    with pytest.raises(AdmissionRejected) as exc:
        fleet.submit(p, 2, priority=Priority.BEST_EFFORT)
    assert exc.value.reason == "rate_limit"
    assert exc.value.retry_after_s == pytest.approx(1.0)
    assert exc.value.priority is Priority.BEST_EFFORT
    # Unlimited classes sail through; the limited class recovers after
    # its own refill interval — the hint was honest.
    fleet.submit(p, 2, priority=Priority.INTERACTIVE)
    clock.now += 1.0
    fleet.submit(p, 2, priority=Priority.BEST_EFFORT)
    assert fleet.metrics.admission_rate_limited == 1
    assert fleet.metrics.rejected_by_priority["best_effort"] == 1
    fleet.run(max_steps=300)


def test_fleet_brownout_sheds_best_effort_first_and_recovers(gpt_setup):
    """The acceptance shape in miniature: flood a small fleet far past
    capacity with a mixed-class workload. The ladder must escalate,
    the rejections must land overwhelmingly on best_effort, every
    accepted request must finish, and after the storm the ladder must
    unwind to NORMAL (hysteresis, not flapping)."""
    model, variables = gpt_setup
    clock = _FakeClock(100.0)
    admission = AdmissionControl(
        detector_kw=dict(window_s=5.0, min_samples=4),
        brownout_kw=dict(high=0.3, low=0.05, escalate_hold_s=0.0,
                         recover_hold_s=2.0, output_cap=2))
    fleet = _slo_fleet(model, variables, 2, clock=clock,
                       admission=admission, max_queue_depth=3)
    rng = np.random.default_rng(0)
    classes = [Priority.INTERACTIVE, Priority.BATCH, Priority.BEST_EFFORT]
    handles, rejects = [], {p.value: 0 for p in Priority}
    for i in range(60):  # ~4x what the queues can hold: a real storm
        p = classes[i % 3]
        prompt = rng.integers(0, 32, size=6)
        try:
            handles.append(fleet.submit(prompt, 2, priority=p))
        except (AdmissionRejected, QueueFull):
            rejects[p.value] += 1
        if i % 6 == 5:
            fleet.step()  # a little service between bursts
        clock.now += 0.01
    assert admission.rung > BrownoutRung.NORMAL
    assert fleet.metrics.brownout_escalations >= 1
    total = sum(rejects.values())
    assert total > 0
    # best_effort absorbs the bulk of the shedding: once the ladder is
    # up, EVERY best_effort submit is front-door shed, while
    # interactive is only ever queue-limited.
    assert rejects["best_effort"] >= max(rejects["interactive"],
                                         rejects["batch"])
    assert fleet.metrics.brownout_shed_best_effort >= 1
    # Output capping engaged at rung >= 2 for admitted requests.
    if admission.rung >= BrownoutRung.CAP_OUTPUT:
        assert fleet.metrics.brownout_capped_output >= 0
    # Drain the accepted work: everything terminal.
    while fleet.has_work:
        fleet.step()
        clock.now += 0.05
    assert all(h.done for h in handles)
    # Hysteretic recovery: calm steps unwind the ladder one rung per
    # recover_hold_s — and it reaches NORMAL, not a stuck brownout.
    for _ in range(200):
        fleet.step()
        clock.now += 0.1
        if admission.rung is BrownoutRung.NORMAL:
            break
    assert admission.rung is BrownoutRung.NORMAL
    assert fleet.metrics.brownout_deescalations \
        == fleet.metrics.brownout_escalations
    # The per-class rejects and the rung ride the fleet exposition.
    samples, types = parse_prometheus_text(fleet_exposition(fleet))
    assert samples[("pddl_fleet_brownout_rung", ())] == 0.0
    assert samples[("pddl_fleet_admission_rejected_best_effort_total",
                    ())] == float(rejects["best_effort"])
    assert types["pddl_fleet_admission_rejected_best_effort_total"] \
        == "counter"
    assert ("pddl_fleet_brownout_shed_best_effort_total", ()) in samples


def test_degraded_replica_raises_brownout_pressure(gpt_setup):
    """r08 composition: a replica in OOM-degraded mode feeds the
    overload detector's pressure floor, so sustained degradation
    browns the fleet out even when the queues look calm."""
    model, variables = gpt_setup
    clock = _FakeClock(50.0)
    admission = AdmissionControl(
        brownout_kw=dict(high=0.3, low=0.05, escalate_hold_s=0.0,
                         recover_hold_s=1.0))
    fleet = _slo_fleet(model, variables, 1, clock=clock,
                       admission=admission)
    slot = fleet.replicas[0]
    slot.driver.engine._degraded = True  # as an OOM would leave it
    fleet.step()
    clock.now += 0.1
    fleet.step()
    assert admission.rung > BrownoutRung.NORMAL
    with pytest.raises(AdmissionRejected):
        fleet.submit((np.arange(6) + 1) % 32, 2,
                     priority=Priority.BEST_EFFORT)


# --------------------------------------------------- chaos under overload
@pytest.mark.chaos
@pytest.mark.parametrize("seed", [0, 1, 2])
def test_chaos_under_overload_token_exact(gpt_setup, pin_zero_recompiles,
                                          seed):
    """Fault injection WHILE 2x saturated: a mixed-priority flood well
    past slot capacity, prefill slicing on, transient+OOM injection
    throughout. Every request must reach a terminal state (finished,
    DEADLINE, or shed-with-hint), every FINISHED stream must be
    token-identical to the fault-free oracle, zero recompiles across
    every retry/replay/degraded/sliced-admission transition."""
    model, variables = gpt_setup
    plan = FaultPlan(seed=seed, transient_rate=0.04, oom_rate=0.01,
                     max_random_injections=15, sleep_fn=_no_sleep)
    eng = pin_zero_recompiles(ServeEngine(
        model, variables, max_slots=2, prefill_len=16,
        max_queue_depth=6, fault_plan=plan, backoff_sleep=_no_sleep,
        prefill_slice_tokens=8, aging_s=0.5))
    rng = np.random.default_rng(seed)
    classes = [Priority.INTERACTIVE, Priority.BATCH, Priority.BEST_EFFORT]
    handles, refs, rejected = [], [], 0
    deadline = time.monotonic() + 120.0
    for i in range(24):  # ~2x what queue+slots hold at any moment
        plen = int(rng.integers(5, 15))
        prompt = rng.integers(0, 32, size=plen).astype(np.int32)
        n = int(rng.integers(2, 6))
        try:
            h = eng.submit(prompt, n, priority=classes[i % 3],
                           deadline_s=60.0 if i % 5 == 0 else None)
        except QueueFull as e:
            rejected += 1
            assert e.retry_after_s is None or e.retry_after_s >= 0.0
            continue
        handles.append(h)
        refs.append(_ref_greedy(model, variables, prompt.tolist(), n))
        if i % 3 == 2:
            eng.step()
        assert time.monotonic() < deadline
    eng.run(max_steps=800)
    assert not eng.has_work, "engine failed to drain the overload"
    finished = 0
    for h, ref in zip(handles, refs):
        assert h.done, f"request {h} never reached a terminal state"
        if h.state == RequestState.FINISHED:
            finished += 1
            assert h.tokens == ref, \
                f"surviving stream diverged under overload (seed {seed})"
    assert finished >= 1
    snap = eng.metrics.snapshot()
    # Nothing simply vanished: accepted = finished + terminal-others.
    assert (snap["requests_finished"] + snap["requests_timed_out"]
            + snap["requests_deadline_shed"] + snap["requests_cancelled"]
            + snap["requests_failed"]) == len(handles)
