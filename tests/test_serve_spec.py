"""Speculative serving (ISSUE 12): per-slot draft/verify in the fused
tick, CPU.

The contracts under test:

- **Token-exactness**: a ``spec_k > 0`` engine's greedy streams are
  IDENTICAL to the one-shot ``generate()`` oracle — for GPT, Llama,
  int8, both engine modes, cold and prefix-hit admissions, and with a
  draft model riding the paged pool. Acceptance changes only speed.
- **Zero recompiles over mixed accept counts**: speculative + sampled
  + grammar-constrained + multi-adapter slots in ONE tick, accepted
  lengths all over the map, and the compiled set never grows — the
  accepted-length ``[S]`` array is runtime data like the masks and
  adapter ids before it.
- **Chaos** (`@pytest.mark.chaos`): seeded faults at the new
  draft/verify/draft_prefill sites (and everywhere else) leave every
  request terminal and every survivor token-exact; a draft fault is
  NEVER fatal (fallback drafts); replay re-feeds ride the verify
  window ``spec_k+1`` known tokens at a time.
- **Drain v5 / migration**: snapshots carry per-stream speculative
  accounting, restore token-exactly into speculative AND classic
  engines (v1–v4 still restore), and a mid-speculation stream
  live-migrates across a fleet kill token-exactly.
- **Budget contract**: token-budget accounting charges ACCEPTED, not
  drafted, tokens (`scheduler.admit`).
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pddl_tpu.models.gpt import generate, tiny_gpt
from pddl_tpu.models.llama import tiny_llama
from pddl_tpu.models.speculative import ngram_drafts
from pddl_tpu.obs import RequestTracer
from pddl_tpu.obs.export import parse_prometheus_text, serve_exposition
from pddl_tpu.serve import (
    FaultKind,
    FaultPlan,
    FaultSpec,
    FinishReason,
    KillPoint,
    Priority,
    RequestState,
    ServeEngine,
)
from pddl_tpu.serve import drain as drain_io
from pddl_tpu.serve.fleet import FleetRouter, LocalReplica
from pddl_tpu.serve.request import Request, RequestHandle, SamplingParams
from pddl_tpu.serve.tenant import AdapterRegistry, TenantConfig
from conftest import ref_greedy as _ref_greedy

pytestmark = pytest.mark.spec

_no_sleep = lambda s: None  # noqa: E731

VOCAB32 = (list("0123456789") + list('{}[]":,.-') + ["true", "false"]
           + list("abcdefghijk"))


@pytest.fixture(scope="module")
def gpt_setup():
    model = tiny_gpt(vocab_size=32, max_len=64)
    prompt = jnp.ones((1, 8), jnp.int32)
    params = model.init(jax.random.key(0), prompt, train=False)["params"]
    return model, {"params": params}


@pytest.fixture(scope="module")
def llama_setup():
    model = tiny_llama(vocab_size=32, max_len=64)
    prompt = jnp.ones((1, 8), jnp.int32)
    params = model.init(jax.random.key(1), prompt, train=False)["params"]
    return model, {"params": params}


@pytest.fixture(scope="module")
def draft_setup():
    """A smaller, differently-seeded draft model over the same vocab —
    its guesses genuinely disagree with the target (acceptance is a
    property of the pair, exactness never is)."""
    model = tiny_gpt(vocab_size=32, max_len=64, depth=1)
    prompt = jnp.ones((1, 8), jnp.int32)
    params = model.init(jax.random.key(9), prompt, train=False)["params"]
    return model, {"params": params}


_WORKLOAD = [((np.arange(9) * 5 + 1) % 32, 9),
             ((np.arange(12) * 3 + 7) % 32, 6),
             ((np.arange(9) * 5 + 1) % 32, 5),   # shared prefix with #0
             ((np.arange(6) + 17) % 32, 8),
             ((np.arange(14) * 7 + 2) % 32, 4)]


@pytest.fixture(scope="module")
def workload_refs(gpt_setup):
    model, variables = gpt_setup
    return [_ref_greedy(model, variables, p, n) for p, n in _WORKLOAD]


def _spec_engine(model, variables, *, paged=False, **kw):
    kw.setdefault("max_slots", 2)
    kw.setdefault("prefill_len", 16)
    kw.setdefault("spec_k", 3)
    return ServeEngine(model, variables, paged=paged, **kw)


# ------------------------------------------------------- shared drafter
def test_ngram_drafts_one_definition_and_equivalence():
    """Satellite: the serving drafter IS the one-shot drafter — one
    imported definition — and the per-row vector form reproduces the
    historical scalar form bit-for-bit on identical token histories."""
    import pddl_tpu.models.speculative as spec_mod
    import pddl_tpu.serve.engine as engine_mod

    assert engine_mod.ngram_drafts is spec_mod.ngram_drafts
    assert spec_mod._ngram_drafts is spec_mod.ngram_drafts
    rng = np.random.default_rng(0)
    toks = jnp.asarray(rng.integers(0, 7, size=(3, 40)), jnp.int32)
    for cur_pos in (5, 17, 33):
        scalar = ngram_drafts(toks, jnp.int32(cur_pos), 3, 4)
        vector = ngram_drafts(
            toks, jnp.full((3,), cur_pos, jnp.int32), 3, 4)
        np.testing.assert_array_equal(np.asarray(scalar),
                                      np.asarray(vector))
    # Mixed per-row positions: each row matches its own scalar run.
    pos = jnp.asarray([5, 17, 33], jnp.int32)
    mixed = np.asarray(ngram_drafts(toks, pos, 3, 4))
    for r, p in enumerate((5, 17, 33)):
        solo = np.asarray(ngram_drafts(toks, jnp.int32(p), 3, 4))
        np.testing.assert_array_equal(mixed[r], solo[r])


# ----------------------------------------------------- token exactness
@pytest.mark.parametrize("paged", [False, True], ids=["row", "paged"])
def test_spec_token_exact_gpt(gpt_setup, workload_refs,
                              pin_zero_recompiles, paged):
    """Cold + shared-prefix admissions through the speculative engine:
    every greedy stream identical to generate(), more than one token
    per verify window actually accepted, zero recompiles over the
    mixed accept counts."""
    model, variables = gpt_setup
    eng = pin_zero_recompiles(
        _spec_engine(model, variables, paged=paged, max_slots=3))
    handles = [eng.submit(p, n) for p, n in _WORKLOAD]
    eng.run(max_steps=400)
    for h, ref in zip(handles, workload_refs):
        assert h.tokens == ref
    snap = eng.metrics.snapshot()
    assert snap["spec_ticks"] > 0
    assert snap["spec_drafted_tokens"] > 0
    total = sum(n for _, n in _WORKLOAD)
    # Speculation must have delivered: fewer verify windows than a
    # one-token tick would have needed is the whole point (loose bound
    # — acceptance on the untrained model is workload-dependent).
    assert snap["spec_accepted_tokens"] >= 1
    assert eng.metrics.tokens_emitted == total


@pytest.mark.parametrize("paged", [False, True], ids=["row", "paged"])
def test_spec_token_exact_llama(llama_setup, pin_zero_recompiles, paged):
    model, variables = llama_setup
    refs = [_ref_greedy(model, variables, p, n) for p, n in _WORKLOAD[:3]]
    eng = pin_zero_recompiles(
        _spec_engine(model, variables, paged=paged, max_slots=3))
    handles = [eng.submit(p, n) for p, n in _WORKLOAD[:3]]
    eng.run(max_steps=400)
    for h, ref in zip(handles, refs):
        assert h.tokens == ref


def test_spec_token_exact_int8(gpt_setup, pin_zero_recompiles):
    """int8 weight storage composes: the verify program dequantizes
    inside like every other compiled program."""
    from pddl_tpu.ops.quant import dequantize, quantize_int8

    model, variables = gpt_setup
    qparams = quantize_int8(variables["params"], min_elems=128)
    dense = {"params": dequantize(qparams)}
    p, n = _WORKLOAD[0]
    ref = _ref_greedy(model, dense, p, n)
    eng = pin_zero_recompiles(
        _spec_engine(model, {"params": qparams},
                     param_transform=dequantize))
    h = eng.submit(p, n)
    eng.run(max_steps=200)
    assert h.tokens == ref


def test_spec_draft_model_token_exact(gpt_setup, draft_setup,
                                      pin_zero_recompiles):
    """The draft model's KV rides the paged pool as a second cache tree
    (same blocks, same tables, same sharing): streams stay token-exact
    — including a repeat prompt whose blocks dedup-swap onto the stored
    chain — and the draft_prefill program compiles once."""
    model, variables = gpt_setup
    dmodel, dvars = draft_setup
    refs = [_ref_greedy(model, variables, p, n) for p, n in _WORKLOAD[:3]]
    eng = pin_zero_recompiles(
        _spec_engine(model, variables, paged=True, max_slots=3,
                     spec_draft_model=dmodel, spec_draft_variables=dvars))
    assert eng.spec_draft_model_enabled
    handles = [eng.submit(p, n) for p, n in _WORKLOAD[:3]]
    eng.run(max_steps=400)
    for h, ref in zip(handles, refs):
        assert h.tokens == ref
    assert "draft_prefill" in eng.compile_counts()
    # A repeat of the shared prompt hits the radix chain (whose blocks
    # now hold BOTH trees' K/V) and still reproduces the oracle.
    again = eng.submit(_WORKLOAD[0][0], _WORKLOAD[0][1])
    eng.run(max_steps=200)
    assert again.tokens == refs[0]


def test_eos_mid_window_truncates_exactly(gpt_setup):
    """An eos accepted mid-window ends the stream exactly where the
    one-token engine would have: everything past it is discarded."""
    model, variables = gpt_setup
    p, n = _WORKLOAD[0][0], 12
    ref = _ref_greedy(model, variables, p, n)
    eos = ref[len(ref) // 2]  # a token the greedy stream really emits
    plain = ServeEngine(model, variables, max_slots=1, prefill_len=16,
                        eos_token=eos)
    h0 = plain.submit(p, n)
    plain.run(max_steps=200)
    spec = _spec_engine(model, variables, eos_token=eos)
    h1 = spec.submit(p, n)
    spec.run(max_steps=200)
    assert h1.tokens == h0.tokens
    assert h1.finish_reason == h0.finish_reason == FinishReason.EOS


@pytest.mark.parametrize("paged", [False, True], ids=["row", "paged"])
def test_sampled_constrained_stream_stays_mask_legal(gpt_setup, paged):
    """A SAMPLED grammar-constrained stream on a speculative engine
    draws its one token per window under its FSM mask (review-found:
    an unmasked draw could emit an illegal token and crash the host
    FSM advance for every live stream). Every emitted token must be
    mask-legal and the stream must settle normally."""
    model, variables = gpt_setup
    from pddl_tpu.serve.tenant import compile_constraint

    tc = TenantConfig(registry=AdapterRegistry(model.embed_dim,
                                               model.vocab_size, rank=4),
                      token_strings=VOCAB32)
    eng = ServeEngine(model, variables, max_slots=2, prefill_len=16,
                      tenant=tc, spec_k=3, paged=paged)
    spec = {"kind": "regex", "pattern": r"-?\d+(\.\d+)?"}
    h = eng.submit(_WORKLOAD[0][0], 10, constraint=spec,
                   sampling=SamplingParams(temperature=1.0, top_k=8))
    greedy = eng.submit(_WORKLOAD[1][0], 10)  # a speculating neighbor
    eng.run(max_steps=300)
    assert h.done and greedy.done
    assert h.state == RequestState.FINISHED
    fsm = compile_constraint(spec, VOCAB32)
    state = fsm.start
    for tok in h.tokens:
        assert fsm.allow_row(state, None)[tok], \
            f"sampled constrained stream emitted illegal token {tok}"
        state = fsm.advance(state, tok)
        assert state >= 0


def test_sampled_rows_do_not_speculate(gpt_setup):
    """Sampled streams tick one exact token per window (cap 0): they
    finish, draw from the same batched sampler, and contribute nothing
    to the drafted/accepted series."""
    model, variables = gpt_setup
    eng = _spec_engine(model, variables, max_slots=2)
    hs = [eng.submit(p, n,
                     sampling=SamplingParams(temperature=1.0, top_k=8))
          for p, n in _WORKLOAD[:3]]
    eng.run(max_steps=400)
    assert all(h.done and len(h.tokens) == n
               for h, (_, n) in zip(hs, _WORKLOAD[:3]))
    assert eng.metrics.spec_drafted_tokens == 0
    assert eng.metrics.spec_ticks > 0


# -------------------------------------------- mixed batches, recompiles
@pytest.mark.parametrize("family", ["gpt", "llama"])
@pytest.mark.parametrize("paged", [False, True], ids=["row", "paged"])
def test_mixed_batch_zero_recompiles(gpt_setup, llama_setup,
                                     pin_zero_recompiles, paged, family):
    """The acceptance-criteria batch: speculative-greedy + sampled +
    grammar-constrained + two adapters live in ONE tick with mixed
    accept counts — zero recompiles in both engine modes for BOTH
    model families, and every deterministic stream equals its
    plain-engine twin."""
    model, variables = gpt_setup if family == "gpt" else llama_setup
    reg = AdapterRegistry(model.embed_dim, model.vocab_size, rank=4)
    reg.register_random("acme", seed=100, scale=0.1)
    reg.register_random("globex", seed=101, scale=0.1)
    constraint = {"kind": "regex", "pattern": r"-?\d+(\.\d+)?"}
    rng = np.random.default_rng(5)
    prompts = [rng.integers(0, 32, size=ln).astype(np.int32)
               for ln in (5, 7, 6, 4)]

    def run(spec_k):
        tc = TenantConfig(registry=reg, token_strings=VOCAB32)
        eng = ServeEngine(model, variables, max_slots=4, prefill_len=16,
                          tenant=tc, spec_k=spec_k, paged=paged)
        eng.warmup()
        hs = [eng.submit(prompts[0], 10, constraint=constraint),
              eng.submit(prompts[1], 10, adapter="acme"),
              eng.submit(prompts[2], 10, adapter="globex",
                         constraint=constraint),
              eng.submit(prompts[3], 10,
                         sampling=SamplingParams(temperature=0.8,
                                                 top_k=4))]
        eng.run(max_steps=400)
        return hs, eng

    base, _ = run(0)
    spec, eng = run(3)
    pin_zero_recompiles(eng)  # counts already 1; pinned through teardown
    for i, (b, s) in enumerate(zip(base, spec)):
        assert s.done
        if i != 3:  # the sampled stream is distribution-, not bit-, pinned
            assert s.tokens == b.tokens, f"slot {i} diverged"
            assert s.finish_reason == b.finish_reason
    assert eng.metrics.spec_drafted_tokens > 0


# ----------------------------------------------------------- resilience
@pytest.mark.chaos
@pytest.mark.parametrize("seed", [0, 1, 2])
@pytest.mark.parametrize("paged", [False, True], ids=["row", "paged"])
def test_spec_chaos_matrix(gpt_setup, workload_refs, pin_zero_recompiles,
                           seed, paged):
    """Seeded mixed chaos (transients, OOM, latency — the rate draws
    now also land on draft/verify/draft_prefill): no crash, every
    request terminal, survivors token-exact, zero recompiles across
    every recovery transition."""
    model, variables = gpt_setup
    plan = FaultPlan(seed=seed, sleep_fn=_no_sleep, transient_rate=0.05,
                     oom_rate=0.02, latency_rate=0.1, latency_s=1e-4,
                     max_random_injections=20)
    tracer = RequestTracer()
    eng = pin_zero_recompiles(
        _spec_engine(model, variables, paged=paged, fault_plan=plan,
                     backoff_sleep=_no_sleep, tracer=tracer))
    handles = [eng.submit(p, n) for p, n in _WORKLOAD]
    eng.run(max_steps=600)
    assert not eng.has_work, "engine failed to drain under chaos"
    for h, ref in zip(handles, workload_refs):
        assert h.done, f"request {h} never reached a terminal state"
        if h.state == RequestState.FINISHED:
            assert h.tokens == ref, \
                f"surviving stream diverged (seed {seed})"
    # Injections surfaced as trace events at matching coordinates.
    assert len(tracer.events_named("fault_injected")) \
        == plan.total_injected
    # Still serviceable after the storm.
    p, n = _WORKLOAD[0]
    again = eng.submit(p, n)
    eng.run(max_steps=100)
    assert again.tokens == workload_refs[0]


def test_verify_storm_replays_token_exact(gpt_setup,
                                          pin_zero_recompiles):
    """A transient burst at the VERIFY site past max_retries loses the
    live slots; replay rebuilds them token-exactly, re-feeding the
    emitted tokens through the verify window (multiple per tick)."""
    model, variables = gpt_setup
    p, n = _WORKLOAD[1]
    ref = _ref_greedy(model, variables, p, n)
    plan = FaultPlan(scheduled=[
        FaultSpec(step=3, site="verify", kind=FaultKind.TRANSIENT,
                  count=3)], sleep_fn=_no_sleep)
    eng = pin_zero_recompiles(
        _spec_engine(model, variables, fault_plan=plan, max_retries=1,
                     backoff_sleep=_no_sleep))
    h = eng.submit(p, n)
    eng.run(max_steps=300)
    assert h.tokens == ref
    assert eng.metrics.replays >= 1


def test_draft_fault_is_never_fatal(gpt_setup, pin_zero_recompiles):
    """A transient burst at the DRAFT site past max_retries falls back
    to repeat-last drafts: the stream neither replays nor diverges —
    drafting pays acceptance, never correctness."""
    model, variables = gpt_setup
    p, n = _WORKLOAD[0]
    ref = _ref_greedy(model, variables, p, n)
    plan = FaultPlan(scheduled=[
        FaultSpec(step=2, site="draft", kind=FaultKind.TRANSIENT,
                  count=4)], sleep_fn=_no_sleep)
    eng = pin_zero_recompiles(
        _spec_engine(model, variables, fault_plan=plan, max_retries=1,
                     backoff_sleep=_no_sleep))
    h = eng.submit(p, n)
    eng.run(max_steps=300)
    assert h.tokens == ref
    assert eng.metrics.replays == 0


def test_kill_mid_verify_drain_restore_token_exact(gpt_setup):
    """A hard kill-point at the verify site mid-stream, then
    drain/restore of the survivor state into a fresh speculative
    engine: streams resume token-exactly (the chaos matrix's
    preemption-mid-verify analogue at the hardest coordinate)."""
    model, variables = gpt_setup
    refs = [_ref_greedy(model, variables, p, n) for p, n in _WORKLOAD[:3]]
    plan = FaultPlan(scheduled=[
        FaultSpec(step=4, site="verify", kind=FaultKind.KILL)],
        sleep_fn=_no_sleep)
    eng = _spec_engine(model, variables, fault_plan=plan,
                       backoff_sleep=_no_sleep)
    handles = [eng.submit(p, n) for p, n in _WORKLOAD[:3]]
    with pytest.raises(KillPoint):
        eng.run(max_steps=300)
    snapshot = eng.drain()
    assert snapshot["version"] == 5
    eng2 = _spec_engine(model, variables)
    restored = eng2.restore(snapshot)
    eng2.run(max_steps=300)
    # Streams that FINISHED before the kill settled on the first
    # engine; everything else must finish token-exactly on the second.
    finished = {(tuple(h.request.prompt), h.request.max_new_tokens): h
                for h in [*handles, *restored] if h.done}
    for (p, n), ref in zip(_WORKLOAD[:3], refs):
        h = finished[(tuple(int(t) for t in p), n)]
        assert h.tokens == ref, "restored stream diverged"


def test_preempt_mid_speculation_token_exact(gpt_setup):
    """A best_effort stream parked mid-speculation for interactive
    work resumes token-exactly through the replay re-feed (spec_k+1
    known tokens per window)."""
    model, variables = gpt_setup
    p0, n0 = _WORKLOAD[1][0], 10
    p1, n1 = _WORKLOAD[3]
    ref0 = _ref_greedy(model, variables, p0, n0)
    ref1 = _ref_greedy(model, variables, p1, n1)
    eng = _spec_engine(model, variables, max_slots=1, preempt_cap=1)
    h0 = eng.submit(p0, n0, priority=Priority.BEST_EFFORT)
    for _ in range(2):
        eng.step()
    assert not h0.done
    h1 = eng.submit(p1, n1, priority=Priority.INTERACTIVE)
    eng.run(max_steps=300)
    assert eng.metrics.preemptions == 1
    assert h0.tokens == ref0 and h1.tokens == ref1


# ------------------------------------------------------ drain & compat
@pytest.mark.parametrize("paged", [False, True], ids=["row", "paged"])
def test_drain_restore_v5_round_trip(gpt_setup, paged):
    """Mid-flight drain: v5 snapshot carries the per-stream speculative
    accounting; restore is token-exact into a speculative engine of
    EITHER mode and into a classic (spec_k=0) engine."""
    model, variables = gpt_setup
    refs = [_ref_greedy(model, variables, p, n) for p, n in _WORKLOAD[:3]]
    eng = _spec_engine(model, variables, paged=paged)
    handles = [eng.submit(p, n) for p, n in _WORKLOAD[:3]]
    eng.step()  # one window each for the two slotted streams
    assert not any(h.done for h in handles)
    snapshot = eng.drain()
    assert snapshot["version"] == drain_io.SNAPSHOT_VERSION == 5
    assert snapshot["spec_k"] == 3
    entries = snapshot["requests"]
    assert len(entries) == 3
    assert all("spec" in e for e in entries)
    assert sum(e["spec"]["drafted"] for e in entries) \
        == eng.metrics.spec_drafted_tokens
    for spec_k in (3, 0):
        eng2 = ServeEngine(model, variables, max_slots=2,
                           prefill_len=16, spec_k=spec_k, paged=paged)
        restored = eng2.restore(snapshot)
        eng2.run(max_steps=300)
        done = {(tuple(h.request.prompt), h.request.max_new_tokens): h
                for h in restored}
        for (p, n), ref in zip(_WORKLOAD[:3], refs):
            h = done[(tuple(int(t) for t in p), n)]
            assert h.tokens == ref, f"diverged restoring into "\
                f"spec_k={spec_k}"
        if spec_k:
            # The migrated accounting continued, never reset.
            assert sum(h.spec_drafted for h in restored) \
                >= sum(e["spec"]["drafted"] for e in entries)


def test_v1_through_v4_snapshots_restore_into_spec_engine(gpt_setup,
                                                          tmp_path):
    """Back-compat both directions: pre-speculative snapshots (v1's
    bare entries through v4's tenant fields) restore token-exactly
    into a speculative engine — absent ``spec`` decodes to zeros — and
    future versions refuse loudly."""
    model, variables = gpt_setup
    p, n = _WORKLOAD[0]
    ref = _ref_greedy(model, variables, p, n)
    for version in (1, 4):
        entry = {"prompt": [int(t) for t in p], "max_new_tokens": n,
                 "tokens": ref[:2], "elapsed_s": 0.5}
        if version == 4:
            entry.update({"sampling": {"temperature": 0.0},
                          "priority": "interactive", "adapter": None,
                          "constraint": None, "ttft_s": 0.01,
                          "deadline_s": None})
        path = tmp_path / f"v{version}.json"
        path.write_text(json.dumps({"version": version,
                                    "requests": [entry]}))
        eng = _spec_engine(model, variables)
        restored = eng.restore(str(path))
        assert restored[0].spec_drafted == 0
        eng.run(max_steps=200)
        assert restored[0].tokens == ref, f"v{version} diverged"
    bad = tmp_path / "future.json"
    bad.write_text(json.dumps({"version": 99, "requests": []}))
    with pytest.raises(ValueError, match="unsupported"):
        drain_io.load_snapshot(str(bad))


@pytest.mark.chaos
@pytest.mark.fleet
def test_fleet_migration_mid_speculation_token_exact(gpt_setup,
                                                     pin_zero_recompiles):
    """Kill one of two SPECULATIVE replicas mid-stream (kill-point at
    its next verify): the dying replica's drain snapshot live-migrates
    its speculative streams onto the survivor, which resumes them
    token-exactly through the windowed replay re-feed."""
    model, variables = gpt_setup
    plans = [FaultPlan(sleep_fn=_no_sleep) for _ in range(2)]

    def factory(plan):
        def make():
            return _spec_engine(model, variables, fault_plan=plan,
                                prefix_cache_blocks=0,
                                backoff_sleep=_no_sleep)
        return make

    replicas = [LocalReplica(i, factory(plans[i])) for i in range(2)]
    fleet = pin_zero_recompiles(FleetRouter(
        replicas, affinity_block_size=8, affinity_blocks=1,
        respawn=False))
    reqs = [(p, n) for p, n in _WORKLOAD[:4]]
    refs = [_ref_greedy(model, variables, p, n) for p, n in reqs]
    handles = [fleet.submit(p, n) for p, n in reqs]
    for _ in range(2):
        fleet.step()
    victim = max(fleet.replicas, key=lambda s: s.load)
    assert victim.load > 0
    eng = victim.driver.engine
    plans[victim.replica_id]._sched[(eng._step_idx, "verify")] = \
        [FaultKind.KILL]
    fleet.run(max_steps=600)
    assert not fleet.has_work
    for h, ref in zip(handles, refs):
        assert h.done
        assert h.state == RequestState.FINISHED
        assert h.tokens == ref, "migrated speculative stream diverged"
    assert fleet.metrics.requests_migrated >= 1


# ------------------------------------------------------ budget contract
def test_budget_charges_accepted_not_drafted(gpt_setup):
    """`scheduler.admit`'s speculative contract: a fresh admission
    costs EXACTLY what the classic engine charges (drafting never
    inflates the price or shrinks the admitted batch), and a replay's
    catch-up charge is its emitted token count — accepted, not the
    (spec_k+1)-wide drafted compute."""
    model, variables = gpt_setup
    budget = 14  # two of the 9/12-token prompts never fit in one step

    def admitted_first_step(spec_k):
        eng = ServeEngine(model, variables, max_slots=4, prefill_len=16,
                          prefill_token_budget=budget, spec_k=spec_k)
        eng.warmup()
        for p, n in _WORKLOAD[:4]:
            eng.submit(p, n)
        eng.step()
        return eng.live_slots

    assert admitted_first_step(3) == admitted_first_step(0)
    # Replay catch-up: charged at the emitted (accepted) token count.
    eng = _spec_engine(model, variables,
                       prefill_token_budget=budget)
    eng.warmup()
    handle = RequestHandle(
        Request(prompt=[1, 2, 3], max_new_tokens=8), arrival_s=0.0)
    fresh = eng._prefill_cost(handle)
    handle.tokens = [4, 5, 6, 7]
    assert eng._prefill_cost(handle) == fresh + len(handle.tokens)


# -------------------------------------------------------- observability
def test_spec_metrics_and_exposition(gpt_setup):
    """The acceptance-rate series surfaces in the snapshot and renders
    through the strict Prometheus referee; the engine gauges carry the
    draft config."""
    model, variables = gpt_setup
    eng = _spec_engine(model, variables)
    hs = [eng.submit(p, n) for p, n in _WORKLOAD[:2]]
    eng.run(max_steps=300)
    assert all(h.done for h in hs)
    snap = eng.metrics.snapshot()
    assert snap["spec_ticks"] > 0
    assert snap["spec_drafted_tokens"] > 0
    assert snap["spec_acceptance_rate"] == pytest.approx(
        snap["spec_accepted_tokens"] / snap["spec_drafted_tokens"])
    samples, types = parse_prometheus_text(
        serve_exposition(eng.metrics, eng))
    assert types["pddl_serve_spec_ticks_total"] == "counter"
    assert types["pddl_serve_spec_acceptance_rate"] == "gauge"
    assert samples[("pddl_serve_engine_spec_k", ())] == 3.0
    assert ("pddl_serve_engine_compile_counts",
            (("key", "verify"),)) in samples


def test_spec_validation(gpt_setup, draft_setup):
    model, variables = gpt_setup
    dmodel, dvars = draft_setup
    with pytest.raises(ValueError, match="spec_k"):
        ServeEngine(model, variables, spec_k=-1)
    with pytest.raises(ValueError, match="paged"):
        ServeEngine(model, variables, spec_k=2,
                    spec_draft_model=dmodel, spec_draft_variables=dvars)
    with pytest.raises(ValueError, match="spec_k >= 1"):
        ServeEngine(model, variables, paged=True,
                    spec_draft_model=dmodel, spec_draft_variables=dvars)
    with pytest.raises(ValueError, match="spec_draft_variables"):
        ServeEngine(model, variables, paged=True, spec_k=2,
                    spec_draft_model=dmodel)
    big = tiny_gpt(vocab_size=64, max_len=64)
    with pytest.raises(ValueError, match="vocab"):
        ServeEngine(model, variables, paged=True, spec_k=2,
                    spec_draft_model=big, spec_draft_variables=dvars)
