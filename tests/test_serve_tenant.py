"""Multi-tenant serving (`serve/tenant/` + ``ServeEngine(tenant=...)``).

The contracts under test (ISSUE 9 acceptance criteria):

- **Grammar machinery**: regex subset → Brzozowski-derivative DFA →
  token FSM — acceptance semantics, class/quantifier parsing, the
  token-level trim (a mask can never steer a stream into a state no
  token tiling can complete from), JSON-schema lowering.
- **Adapter machinery**: registry shape/rank validation, pool LRU
  eviction under pin protection, exhaustion escalation.
- **Correctness oracles**: an adapter-off slot is token-exact vs the
  base model; a single-tenant batched LoRA apply is token-exact vs an
  unbatched MERGED-WEIGHTS ``generate()`` reference; every constrained
  stream's output is accepted by its grammar/schema.
- **Zero recompiles over a mixed batch**: ≥3 distinct adapters +
  constrained + unconstrained + no-adapter slots in ONE tick, in both
  ``paged=True`` and resident-row modes, GPT and Llama, int8 composing.
- **Resilience parity**: 3-seed chaos matrix with tenant requests
  (token-exact survivors, zero recompiles), preemption resume,
  drain/restore v4 + v1-v3 back-compat ("no adapter, unconstrained"
  defaults in both engine modes), future versions refused, plain
  engines refusing tenant snapshots, fleet migration of tenant streams.
- **Observability**: adapter/constraint counters and labeled series
  through ``serve_exposition`` and the strict referee parser.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from conftest import ref_greedy as _ref_greedy
from pddl_tpu.models.gpt import tiny_gpt
from pddl_tpu.models.llama import tiny_llama
from pddl_tpu.obs.export import parse_prometheus_text, serve_exposition
from pddl_tpu.ops.lora import merge_lora_into_head
from pddl_tpu.serve import ServeEngine
from pddl_tpu.serve.faults import FaultPlan
from pddl_tpu.serve.request import Priority, RequestState
from pddl_tpu.serve.tenant import (
    AdapterPool,
    AdapterPoolExhausted,
    AdapterRegistry,
    TenantConfig,
    compile_constraint,
    encode_text,
    json_schema_to_regex,
    token_fsm_from_regex,
)
from pddl_tpu.serve.tenant.grammar import RegexError

pytestmark = pytest.mark.tenant

_no_sleep = lambda s: None  # noqa: E731

# Token-id → string vocabulary for the 32-token test models: ids 0-9
# are the digit characters, then JSON punctuation and a few letters —
# enough to tile the schemas below; the rest are unmatched filler.
VOCAB32 = (list("0123456789") + list('{}[]":,.-') + ["true", "false"]
           + list("abcdefghijk"))
assert len(VOCAB32) == 32


@pytest.fixture(scope="module")
def gpt_setup():
    model = tiny_gpt(vocab_size=32, max_len=64)
    prompt = jnp.ones((1, 8), jnp.int32)
    params = model.init(jax.random.key(0), prompt, train=False)["params"]
    return model, {"params": params}


@pytest.fixture(scope="module")
def llama_setup():
    model = tiny_llama(vocab_size=32, max_len=64)
    prompt = jnp.ones((1, 8), jnp.int32)
    params = model.init(jax.random.key(1), prompt, train=False)["params"]
    return model, {"params": params}


def _registry(model, names=("acme", "globex", "initech"), scale=0.1):
    reg = AdapterRegistry(model.embed_dim, model.vocab_size, rank=4)
    for i, name in enumerate(names):
        reg.register_random(name, seed=100 + i, scale=scale)
    return reg


def _tenant_engine(model, variables, reg=None, **kw):
    reg = reg if reg is not None else _registry(model)
    kw.setdefault("max_slots", 2)
    kw.setdefault("prefill_len", 16)
    tc = TenantConfig(registry=reg, token_strings=VOCAB32,
                      adapter_pool_slots=kw.pop("adapter_pool_slots",
                                                None))
    return ServeEngine(model, variables, tenant=tc, **kw)


def _merged(model, variables, reg, name):
    ad = reg.get(name)
    return {"params": merge_lora_into_head(variables["params"], ad.a,
                                           ad.b)}


# ----------------------------------------------------------- grammar
def test_regex_token_fsm_basics():
    vocab = list("abc")
    fsm = token_fsm_from_regex("(ab|a)c*", vocab)
    a, b, c = 0, 1, 2
    assert fsm.accepts([a])
    assert fsm.accepts([a, b])
    assert fsm.accepts([a, b, c, c])
    assert fsm.accepts([a, c])
    assert not fsm.accepts([b])
    assert not fsm.accepts([a, b, a])
    # Start-state mask: only 'a' can begin a match.
    row = fsm.allow_row(fsm.start)
    assert row[a] and not row[b] and not row[c]


def test_regex_classes_escapes_quantifiers():
    vocab = list("0123456789ab\"\\-x.")
    fsm = token_fsm_from_regex(r"-?\d+(\.\d+)?", vocab)
    enc = lambda s: encode_text(s, vocab)  # noqa: E731
    assert fsm.accepts(enc("42"))
    assert fsm.accepts(enc("-7.25"))
    assert not fsm.accepts(enc("4."))
    assert not fsm.accepts(enc("x"))
    neg = token_fsm_from_regex(r'"[^"\\]*"', vocab)
    assert neg.accepts(enc('"ab0"'))
    assert not neg.accepts(enc('"a"b"'))
    rng = token_fsm_from_regex("[a-b]+", vocab)
    assert rng.accepts(enc("abba")) and not rng.accepts(enc("0"))
    with pytest.raises(RegexError):
        token_fsm_from_regex("*a", vocab)
    with pytest.raises(RegexError):
        token_fsm_from_regex("(a", vocab)


def test_multichar_tokens_and_token_level_trim():
    """Token lift handles multi-character tokens, and the TOKEN-level
    trim erases transitions into states no token tiling can complete —
    so a dead-end (grammar-complete) state is always ACCEPTING, the
    structural half of the "constrained output always validates"
    contract."""
    fsm = token_fsm_from_regex("abc+", ["ab", "c", "abc"])
    assert fsm.accepts([0, 1]) and fsm.accepts([2]) and fsm.accepts([2, 1])
    assert not fsm.accepts([1])
    # 'abx' needs an 'x' no token supplies: the trap branch is erased
    # from the masks, only 'ac' survives.
    vocab = list("abc")
    fsm2 = token_fsm_from_regex("(abx|ac)", vocab)
    s = fsm2.advance(fsm2.start, 0)
    assert not fsm2.allow_row(s)[1]  # 'b' would enter the dead branch
    assert fsm2.allow_row(s)[2]
    with pytest.raises(RegexError, match="tile"):
        token_fsm_from_regex("[ab]x[ab]", vocab)


def test_json_schema_lowering():
    # Property names drawn from the test vocabulary's letters (a-k):
    # the token-level trim LOUDLY rejects schemas the vocabulary
    # cannot tile (pinned at the end), so the happy path must tile.
    schema = {"type": "object", "properties": {
        "id": {"type": "integer"},
        "ab": {"type": "string"},
        "ed": {"type": "boolean"},
    }}
    pattern = json_schema_to_regex(schema)
    vocab = VOCAB32
    fsm = token_fsm_from_regex(pattern, vocab)
    enc = lambda s: encode_text(s, vocab)  # noqa: E731
    assert fsm.accepts(enc('{"id":42,"ab":"cig","ed":true}'))
    assert fsm.accepts(enc('{"id":-7,"ab":"","ed":false}'))
    # Property order is canonical (declared order), all required.
    assert not fsm.accepts(enc('{"ab":"cig","id":42,"ed":true}'))
    assert not fsm.accepts(enc('{"id":42,"ab":"cig"}'))
    # A schema the vocabulary cannot spell is refused loudly.
    with pytest.raises(RegexError, match="tile"):
        token_fsm_from_regex(json_schema_to_regex(
            {"type": "object",
             "properties": {"zz": {"type": "integer"}}}), vocab)
    arr = json_schema_to_regex({"type": "array",
                                "items": {"type": "integer"}})
    afsm = token_fsm_from_regex(arr, vocab)
    assert afsm.accepts(enc("[1,2,30]")) and afsm.accepts(enc("[]"))
    assert not afsm.accepts(enc("[1,]"))
    efsm = token_fsm_from_regex(
        json_schema_to_regex({"enum": ["ab", 7]}), vocab)
    assert efsm.accepts(enc('"ab"')) and efsm.accepts(enc("7"))
    with pytest.raises(ValueError, match="unsupported"):
        json_schema_to_regex({"type": "null"})
    with pytest.raises(ValueError):
        compile_constraint({"kind": "wat"}, vocab)
    with pytest.raises(ValueError):
        compile_constraint({"kind": "regex", "pattern": ""}, vocab)


# ----------------------------------------------------------- adapters
def test_registry_validation_and_rank_padding(gpt_setup):
    model, variables = gpt_setup
    reg = AdapterRegistry(model.embed_dim, model.vocab_size, rank=4)
    with pytest.raises(ValueError, match="must be"):
        reg.register("bad", np.zeros((7, 2)), np.zeros((2, 32)))
    with pytest.raises(ValueError, match="exceeds"):
        reg.register("big", np.zeros((model.embed_dim, 8)),
                     np.zeros((8, 32)))
    # A rank-2 adapter zero-pads to the pool rank — mathematically a
    # no-op: the padded merged head equals the unpadded one.
    rng = np.random.RandomState(0)
    a = rng.randn(model.embed_dim, 2).astype(np.float32)
    b = rng.randn(2, 32).astype(np.float32)
    ad = reg.register("small", a, b, scale=0.5)
    assert ad.a.shape == (model.embed_dim, 4)
    np.testing.assert_allclose(ad.a @ ad.b, 0.5 * (a @ b), rtol=1e-6)


def test_adapter_pool_lru_pins_and_exhaustion():
    pool = AdapterPool(3)  # identity + 2 usable rows
    r1 = pool.assign("a1")
    r2 = pool.assign("a2")
    assert {r1, r2} == {1, 2} and pool.resident == 2
    pool.pin(r1)
    # Full pool, a1 pinned: a3 must evict a2 (the only unpinned row).
    r3 = pool.assign("a3")
    assert r3 == r2 and pool.lookup("a2") is None
    assert pool.evictions == 1
    pool.pin(r3)
    with pytest.raises(AdapterPoolExhausted):
        pool.assign("a4")
    pool.unpin(r3)
    assert pool.assign("a4") == r3
    with pytest.raises(RuntimeError, match="underflow"):
        pool.unpin(r1) or pool.unpin(r1)
    # Identity row is never assignable/pinnable state.
    pool.pin(0), pool.unpin(0)  # no-ops
    with pytest.raises(ValueError, match="rows"):
        AdapterPool(1)


# ------------------------------------------------- correctness oracles
@pytest.mark.parametrize("paged", [False, True], ids=["row", "paged"])
def test_mixed_batch_token_exact_zero_recompiles_gpt(
        gpt_setup, pin_zero_recompiles, paged):
    """THE acceptance pin: one engine, ≥3 distinct adapters +
    constrained + unconstrained + no-adapter slots mixed through the
    same fused ticks — every stream token-exact against its own oracle
    (base model / merged weights / grammar referee), zero recompiles,
    both engine modes."""
    model, variables = gpt_setup
    reg = _registry(model)
    eng = pin_zero_recompiles(_tenant_engine(
        model, variables, reg=reg, max_slots=6, paged=paged))
    base = (np.arange(12) * 5 + 1) % 32
    spec = {"kind": "regex", "pattern": "[0-9][0-9][0-9][0-9]"}
    hs = {
        "plain": eng.submit(base, 6),
        "acme": eng.submit(base, 6, adapter="acme"),
        "globex": eng.submit((base + 3) % 32, 6, adapter="globex"),
        "initech": eng.submit((base + 7) % 32, 6, adapter="initech"),
        "constrained": eng.submit(base, 8, constraint=spec),
        "both": eng.submit(base, 8, adapter="acme", constraint=spec),
    }
    eng.step()
    # Not vacuous: all six flavors really do share ONE fused tick.
    assert eng.live_slots == 6
    eng.run(max_steps=400)
    assert hs["plain"].tokens == _ref_greedy(model, variables, base, 6)
    for name, prompt in (("acme", base), ("globex", (base + 3) % 32),
                         ("initech", (base + 7) % 32)):
        merged = _merged(model, variables, reg, name)
        assert hs[name].tokens == _ref_greedy(model, merged, prompt, 6), \
            f"adapter {name} diverged from the merged-weights reference"
    fsm = compile_constraint(spec, VOCAB32)
    for key in ("constrained", "both"):
        h = hs[key]
        assert h.finish_reason.value == "grammar"
        assert fsm.accepts(h.tokens), f"{key} output escaped its grammar"
    assert eng.metrics.adapter_loads == 3
    assert eng.metrics.constrained_requests == 2
    assert eng.metrics.requests_grammar_complete == 2


@pytest.mark.parametrize("paged", [False, True], ids=["row", "paged"])
def test_mixed_batch_token_exact_llama(llama_setup, pin_zero_recompiles,
                                       paged):
    """GQA + RoPE + bias-free head: the external-head tenant programs
    are token-exact on the Llama family too, both modes."""
    model, variables = llama_setup
    reg = _registry(model)
    eng = pin_zero_recompiles(_tenant_engine(
        model, variables, reg=reg, max_slots=3, paged=paged))
    base = (np.arange(11) * 3 + 2) % 32
    spec = {"kind": "regex", "pattern": "[0-9][0-9][0-9]"}
    h0 = eng.submit(base, 5)
    h1 = eng.submit(base, 5, adapter="acme")
    h2 = eng.submit(base, 6, adapter="globex", constraint=spec)
    eng.run(max_steps=300)
    assert h0.tokens == _ref_greedy(model, variables, base, 5)
    assert h1.tokens == _ref_greedy(
        model, _merged(model, variables, reg, "acme"), base, 5)
    assert compile_constraint(spec, VOCAB32).accepts(h2.tokens)


def test_int8_composes_with_adapters(gpt_setup):
    """int8 param_transform: dequant runs inside the tenant programs
    BEFORE the external head + LoRA delta, so the adapted stream
    matches a merged-weights reference over the dequantized params."""
    from pddl_tpu.ops.quant import dequantize, quantize_int8

    model, variables = gpt_setup
    qparams = quantize_int8(variables["params"], min_elems=128)
    dense = {"params": dequantize(qparams)}
    reg = _registry(model)
    eng = _tenant_engine(model, {"params": qparams}, reg=reg,
                         param_transform=dequantize)
    base = (np.arange(12) * 5 + 1) % 32
    h0 = eng.submit(base, 5)
    h1 = eng.submit(base, 5, adapter="acme")
    eng.run(max_steps=200)
    assert h0.tokens == _ref_greedy(model, dense, base, 5)
    assert h1.tokens == _ref_greedy(
        model, _merged(model, dense, reg, "acme"), base, 5)


def test_json_schema_constrained_stream_validates(gpt_setup):
    """A schema-constrained stream emits a parseable JSON document
    matching the schema — checked by json.loads, not just the FSM."""
    model, variables = gpt_setup
    eng = _tenant_engine(model, variables)
    schema = {"type": "object", "properties": {"id": {"type": "integer"}}}
    spec = {"kind": "json_schema", "schema": schema}
    base = (np.arange(10) * 7 + 3) % 32
    h = eng.submit(base, 20, constraint=spec)
    eng.run(max_steps=400)
    assert h.finish_reason.value == "grammar"
    text = "".join(VOCAB32[t] for t in h.tokens)
    doc = json.loads(text)
    assert isinstance(doc["id"], int)


def test_adapter_pool_churn_evicts_and_stays_exact(gpt_setup):
    """More adapters than pool rows: sequential single-slot traffic
    LRU-evicts cold factors and reloads on return — every stream still
    merged-exact, hit/load/eviction counters live."""
    model, variables = gpt_setup
    names = ["t0", "t1", "t2", "t3"]
    reg = AdapterRegistry(model.embed_dim, model.vocab_size, rank=4)
    for i, n in enumerate(names):
        reg.register_random(n, seed=40 + i, scale=0.1)
    eng = _tenant_engine(model, variables, reg=reg, max_slots=1,
                         adapter_pool_slots=3)  # identity + 2 rows
    base = (np.arange(10) * 3 + 1) % 32
    for name in names + [names[0]]:  # t0 returns after eviction
        h = eng.submit(base, 4, adapter=name)
        eng.run(max_steps=100)
        assert h.tokens == _ref_greedy(
            model, _merged(model, variables, reg, name), base, 4), name
    assert eng.metrics.adapter_evictions >= 3
    assert eng.metrics.adapter_loads == 5  # 4 cold + t0's reload
    snap = eng.metrics.snapshot()
    assert snap["requests_by_adapter"]["t0"] == 2


def test_cold_adapter_load_charges_the_budget(gpt_setup):
    """Tenancy-aware admission budget: a COLD adapter charges
    ``adapter_load_tokens`` on top of the (suffix-priced) prompt; a
    RESIDENT one charges nothing extra — the cached-prefix economics
    applied to weights."""
    model, variables = gpt_setup
    reg = _registry(model)
    eng = _tenant_engine(model, variables, reg=reg,
                         prefill_token_budget=64)
    base = (np.arange(12) * 5 + 1) % 32
    h = eng.submit(base, 3, adapter="acme")
    cold = eng._prefill_cost(h)
    plain = eng._prefill_cost(eng.submit(base, 3))
    assert cold == plain + eng._tenant.adapter_load_tokens
    eng.run(max_steps=100)  # acme now resident
    h2 = eng.submit(base, 3, adapter="acme")
    assert eng._prefill_cost(h2) <= plain  # warm adapter + warm prefix
    eng.run(max_steps=100)
    assert eng.metrics.adapter_hits >= 1


def test_submit_validation(gpt_setup):
    model, variables = gpt_setup
    plain = ServeEngine(model, variables, max_slots=1, prefill_len=16)
    with pytest.raises(ValueError, match="tenant"):
        plain.submit([1, 2, 3], 2, adapter="acme")
    with pytest.raises(ValueError, match="tenant"):
        plain.submit([1, 2, 3], 2,
                     constraint={"kind": "regex", "pattern": "a"})
    eng = _tenant_engine(model, variables)
    with pytest.raises(ValueError, match="not registered"):
        eng.submit([1, 2, 3], 2, adapter="nobody")
    with pytest.raises(ValueError, match="kind"):
        eng.submit([1, 2, 3], 2, constraint={"kind": "wat"})
    # Constraints need a grammar vocabulary.
    bare = ServeEngine(model, variables, max_slots=1, prefill_len=16,
                       tenant=TenantConfig(registry=_registry(model)))
    with pytest.raises(ValueError, match="token_strings"):
        bare.submit([1, 2, 3], 2,
                    constraint={"kind": "regex", "pattern": "[0-9]"})
    # Pool floor validation.
    with pytest.raises(ValueError, match="floor"):
        ServeEngine(model, variables, max_slots=4, prefill_len=16,
                    tenant=TenantConfig(registry=_registry(model),
                                        adapter_pool_slots=3))
    # An empty-language constraint over this vocabulary ("x*" with no
    # 'x' token: start state allows no token, no eos to escape) must
    # reject the REQUEST at submit — on the unfixed engine it sampled
    # an all--inf row and the FSM advance crashed the step for every
    # live stream.
    with pytest.raises(ValueError, match="no first token"):
        eng.submit([1, 2, 3], 2,
                   constraint={"kind": "regex", "pattern": "x*"})


def test_preempted_tenant_stream_resumes_exact(gpt_setup):
    """A preempted best_effort ADAPTED + CONSTRAINED stream resumes
    token-exactly through replay admission: the adapter re-acquires
    (pin released at park) and the FSM state re-derives from the
    emitted tokens."""
    model, variables = gpt_setup
    reg = _registry(model)
    eng = _tenant_engine(model, variables, reg=reg, max_slots=1)
    spec = {"kind": "regex", "pattern": "[0-9]" * 10}
    pb = (np.arange(8) * 5 + 4) % 32
    hbe = eng.submit(pb, 10, priority=Priority.BEST_EFFORT,
                     adapter="acme", constraint=spec)
    for _ in range(3):
        eng.step()
    pi = (np.arange(8) * 11 + 6) % 32
    hint = eng.submit(pi, 4, priority=Priority.INTERACTIVE)
    eng.run(max_steps=400)
    assert eng.metrics.preemptions >= 1
    assert hint.tokens == _ref_greedy(model, variables, pi, 4)
    assert hbe.done
    fsm = compile_constraint(spec, VOCAB32)
    assert fsm.accepts(hbe.tokens) or len(hbe.tokens) == 10


def test_install_fault_after_single_step_slice_releases_pin_once(
        gpt_setup):
    """A sliced admission that COMPLETES within its first step and then
    faults at install (sample_first): the install's failure path owns
    the adapter-pin release — the slice machinery must not release it
    a second time (refcount underflow crashed the step on the unfixed
    engine). The request replays and finishes merged-exact with every
    pin balanced."""
    from pddl_tpu.serve.faults import FaultKind

    model, variables = gpt_setup
    reg = _registry(model)
    eng = _tenant_engine(model, variables, reg=reg, max_slots=1,
                         prefill_slice_tokens=16, prefix_chunk=4,
                         fault_plan=FaultPlan(sleep_fn=_no_sleep),
                         backoff_sleep=_no_sleep, max_retries=0)
    p = (np.arange(8) * 5 + 1) % 32
    h = eng.submit(p, 4, adapter="acme")
    eng._faults._sched[(eng._step_idx, "sample_first")] = \
        [FaultKind.TRANSIENT]
    eng.run(max_steps=100)
    assert h.state == RequestState.FINISHED
    assert h.tokens == _ref_greedy(
        model, _merged(model, variables, reg, "acme"), p, 4)
    assert eng.metrics.replays >= 1
    assert eng._apool.pinned_rows() == []  # every pin balanced


# ----------------------------------------------------------- resilience
@pytest.mark.chaos
@pytest.mark.parametrize("seed", [0, 1, 2])
def test_tenant_chaos_matrix(gpt_setup, pin_zero_recompiles, seed):
    """The mixed chaos profile with tenant requests (paged engine):
    every request terminal, finished streams token-exact against their
    own oracles (merged weights / grammar referee), zero recompiles
    across retry / replay / degraded / pool-rebuild transitions — the
    adapter pins and FSM states unwind exactly through every recovery
    path."""
    model, variables = gpt_setup
    reg = _registry(model)
    plan = FaultPlan(seed=seed, sleep_fn=_no_sleep, transient_rate=0.05,
                     oom_rate=0.02, latency_rate=0.1, latency_s=1e-4,
                     max_random_injections=20)
    eng = pin_zero_recompiles(_tenant_engine(
        model, variables, reg=reg, max_slots=2, paged=True,
        fault_plan=plan, backoff_sleep=_no_sleep))
    spec = {"kind": "regex", "pattern": "[0-9][0-9][0-9][0-9]"}
    fsm = compile_constraint(spec, VOCAB32)
    jobs = []
    for i in range(6):
        p = (np.arange(10) * 3 + i * 7 + 1) % 32
        adapter = [None, "acme", "globex"][i % 3]
        constraint = spec if i % 2 else None
        jobs.append((p, adapter, constraint,
                     eng.submit(p, 5, adapter=adapter,
                                constraint=constraint)))
    eng.run(max_steps=800)
    assert not eng.has_work, "engine failed to drain under chaos"
    for p, adapter, constraint, h in jobs:
        assert h.done, f"request {h} never reached a terminal state"
        if h.state != RequestState.FINISHED:
            continue
        if constraint is not None:
            assert fsm.accepts(h.tokens) or len(h.tokens) == 5
        elif adapter is None:
            assert h.tokens == _ref_greedy(model, variables, p, 5)
        else:
            assert h.tokens == _ref_greedy(
                model, _merged(model, variables, reg, adapter), p, 5)


@pytest.mark.parametrize("paged", [False, True], ids=["row", "paged"])
def test_drain_restore_v4_round_trip(gpt_setup, paged):
    """v4 snapshot carries adapter + constraint; restore into a fresh
    tenant engine (same registry config) resumes adapted streams on the
    right weights and constrained streams under the same automaton,
    token-exactly."""
    model, variables = gpt_setup
    reg = _registry(model)
    spec = {"kind": "regex", "pattern": "[0-9]" * 8}
    eng1 = _tenant_engine(model, variables, reg=reg, max_slots=2,
                          paged=paged)
    p1 = (np.arange(11) * 5 + 2) % 32
    p2 = (np.arange(9) * 7 + 3) % 32
    eng1.submit(p1, 8, adapter="acme")
    eng1.submit(p2, 8, constraint=spec)
    for _ in range(3):
        eng1.step()
    snap = eng1.drain()
    assert snap["version"] == 5
    entries = {len(e["prompt"]): e for e in snap["requests"]}
    assert entries[11]["adapter"] == "acme"
    assert entries[9]["constraint"] == spec

    eng2 = _tenant_engine(model, variables, reg=reg, max_slots=2,
                          paged=paged)
    rh = eng2.restore(snap)
    eng2.run(max_steps=400)
    assert rh[0].tokens == _ref_greedy(
        model, _merged(model, variables, reg, "acme"), p1, 8)
    fsm = compile_constraint(spec, VOCAB32)
    assert fsm.accepts(rh[1].tokens) or len(rh[1].tokens) == 8


@pytest.mark.parametrize("paged", [False, True], ids=["row", "paged"])
def test_old_snapshots_restore_with_tenant_defaults(gpt_setup, tmp_path,
                                                    paged):
    """The back-compat pin: v1/v2/v3 snapshots — no adapter/constraint
    keys anywhere — restore into a tenant-capable engine in BOTH modes
    with "no adapter, unconstrained" defaults, token-exactly; future
    versions still refuse."""
    import pddl_tpu.serve.drain as drain_io

    model, variables = gpt_setup
    p, n = ((np.arange(9) * 5 + 1) % 32).tolist(), 6
    ref = _ref_greedy(model, variables, p, n)
    for version in (1, 2, 3):
        entry = {
            "prompt": p, "max_new_tokens": n,
            "sampling": {"temperature": 0.0, "top_k": None,
                         "top_p": None},
            "deadline_s": None, "elapsed_s": 1.5,
            "tokens": ref[:2],  # mid-stream: exercises replay
            "ttft_s": 0.1,
        }
        if version >= 2:
            entry["priority"] = "interactive"
        snap = {"version": version, "drained_unix_s": 0.0,
                "requests": [entry]}
        if version >= 3:
            snap["paged"] = False
        path = tmp_path / f"v{version}.json"
        path.write_text(json.dumps(snap))
        eng = _tenant_engine(model, variables, max_slots=1, paged=paged)
        (restored,) = eng.restore(str(path))
        assert restored.request.adapter is None
        assert restored.request.constraint is None
        eng.run(max_steps=200)
        assert restored.tokens == ref, (version, paged)
    bad = tmp_path / "v99.json"
    bad.write_text(json.dumps({"version": 99, "requests": []}))
    with pytest.raises(ValueError, match="version"):
        drain_io.load_snapshot(str(bad))


def test_plain_engine_refuses_tenant_snapshot(gpt_setup):
    """A tenant stream restored onto a plain engine would silently
    serve the BASE model — the restore refuses loudly instead."""
    model, variables = gpt_setup
    eng1 = _tenant_engine(model, variables, max_slots=1)
    eng1.submit((np.arange(8) * 3 + 1) % 32, 6, adapter="acme")
    eng1.step()
    snap = eng1.drain()
    plain = ServeEngine(model, variables, max_slots=1, prefill_len=16)
    with pytest.raises(ValueError, match="tenant"):
        plain.restore(snap)


@pytest.mark.fleet
@pytest.mark.chaos
def test_fleet_migrates_tenant_streams_token_exact(gpt_setup):
    """Fleet leg of the chaos matrix: a killed replica's ADAPTED +
    CONSTRAINED streams migrate to survivors and finish token-exactly
    (worker-config parity: every replica builds the same registry), and
    adapter-affinity routing re-homes after the death."""
    from conftest import FakeClock
    from pddl_tpu.serve.fleet.replica import LocalReplica
    from pddl_tpu.serve.fleet.router import FleetRouter
    from pddl_tpu.utils.faults import KillPoint

    model, variables = gpt_setup
    spec = {"kind": "regex", "pattern": "[0-9]" * 8}

    def factory():
        return _tenant_engine(model, variables, reg=_registry(model),
                              max_slots=2)

    clock = FakeClock()
    fleet = FleetRouter([LocalReplica(i, factory) for i in range(2)],
                        respawn=False, clock=clock)
    fleet.warmup()
    p1 = (np.arange(10) * 3 + 1) % 32
    p2 = (np.arange(10) * 7 + 2) % 32
    h1 = fleet.submit(p1, 8, adapter="acme")
    h2 = fleet.submit(p2, 8, constraint=spec)
    for _ in range(3):
        fleet.step()
    # Kill whichever replica holds h1 (mid-stream), hard.
    victim = next(s for s in fleet.replicas
                  if s.replica_id == h1.replica_id)
    original_step = victim.driver.engine.step
    victim.driver.engine.step = lambda: (_ for _ in ()).throw(
        KillPoint("chaos"))
    del original_step
    while fleet.has_work:
        fleet.step()
        clock.now += 0.05
    reg = _registry(model)
    assert h1.done and h2.done
    assert h1.tokens == _ref_greedy(
        model, _merged(model, variables, reg, "acme"), p1, 8)
    fsm = compile_constraint(spec, VOCAB32)
    assert fsm.accepts(h2.tokens) or len(h2.tokens) == 8
    assert fleet.metrics.requests_migrated >= 1
    # Affinity re-homes: the next acme submission lands on a survivor.
    h3 = fleet.submit(p1, 3, adapter="acme")
    assert h3.replica_id != victim.replica_id
    while fleet.has_work:
        fleet.step()
        clock.now += 0.05
    assert h3.tokens == _ref_greedy(
        model, _merged(model, variables, reg, "acme"), p1, 3)


@pytest.mark.fleet
def test_adapter_affinity_yields_to_interactive_load(gpt_setup):
    """The interactive pressure escape applies to ADAPTER affinity like
    prefix affinity: a popular adapter must not funnel interactive
    traffic onto its loaded home replica while a sibling idles (the
    unfixed router returned the home before the load check). The same
    pressure keeps BATCH traffic on the warm home."""
    from pddl_tpu.serve.fleet.replica import LocalReplica
    from pddl_tpu.serve.fleet.router import FleetRouter

    model, variables = gpt_setup

    def factory():
        return _tenant_engine(model, variables, reg=_registry(model),
                              max_slots=4, max_queue_depth=32)

    fleet = FleetRouter([LocalReplica(i, factory) for i in range(2)],
                        interactive_reroute_load=2)
    fleet.warmup()
    p = (np.arange(10) * 3 + 1) % 32
    h0 = fleet.submit(p, 32, adapter="acme")
    home = h0.replica_id
    # Load the home past the threshold (these stay assigned — long
    # streams, no stepping yet).
    fleet.submit((p + 1) % 32, 32, adapter="acme")
    assert fleet.submit((p + 2) % 32, 32, adapter="acme",
                        priority=Priority.BATCH).replica_id == home
    h_int = fleet.submit((p + 3) % 32, 32, adapter="acme",
                         priority=Priority.INTERACTIVE)
    assert h_int.replica_id != home
    assert fleet.metrics.routed_load_balanced >= 1
    while fleet.has_work:
        fleet.step()
    fleet.close()


# -------------------------------------------------------- observability
def test_tenant_metrics_reach_the_exposition(gpt_setup):
    """Adapter/constraint counters, the per-adapter labeled series and
    the engine tenant gauges flow through serve_exposition and the
    strict referee parser."""
    model, variables = gpt_setup
    eng = _tenant_engine(model, variables)
    base = (np.arange(10) * 5 + 1) % 32
    eng.submit(base, 4, adapter="acme")
    eng.submit(base, 4, adapter="acme")
    eng.submit(base, 5,
               constraint={"kind": "regex", "pattern": "[0-9][0-9]"})
    eng.run(max_steps=200)
    text = serve_exposition(eng.metrics, eng)
    samples, types = parse_prometheus_text(text)
    flat = {name: v for (name, labels), v in samples.items() if not labels}
    assert flat["pddl_serve_adapter_loads_total"] == 1
    assert flat["pddl_serve_adapter_hits_total"] == 1
    assert types["pddl_serve_adapter_loads_total"] == "counter"
    assert flat["pddl_serve_adapter_hit_rate"] == 0.5
    assert flat["pddl_serve_constrained_requests_total"] == 1
    assert flat["pddl_serve_requests_grammar_complete_total"] == 1
    assert flat["pddl_serve_engine_tenant"] == 1
    assert flat["pddl_serve_engine_adapter_pool_resident"] == 1
    labeled = {(n, dict(l).get("key")): v for (n, l), v in samples.items()
               if l}
    assert labeled[("pddl_serve_requests_by_adapter", "acme")] == 2
    # The empty-label placeholder convention on a PLAIN engine: the
    # open series still exports (NaN under key="").
    plain = ServeEngine(model, variables, max_slots=1, prefill_len=16)
    s2, _ = parse_prometheus_text(serve_exposition(plain.metrics, plain))
    assert ("pddl_serve_requests_by_adapter", (("key", ""),)) in s2
